"""Benchmark suite: gemm TFLOPS + model training throughput on one TPU chip.

BASELINE.json metrics (examples/sec/chip, gemm TFLOPS) measured against
the ≥30% MFU north star on v5e. The reference publishes no numbers of
its own (BASELINE.md), so the hardware ceiling is the bar.

Sub-benchmarks (each reported under "sub_benchmarks"):
  - gemm_bf16      — pure 8k^3 bf16 matmul chain (the ND4J Nd4j.gemm slot)
  - lenet_mnist    — config #1, MultiLayerNetwork fit_scan, bf16 compute
  - lstm_char      — config #4, GravesLSTM char-RNN-shaped stack, bf16
  - resnet50       — config #3, ComputationGraph fit_scan, bf16 compute
  - serving_inference — ParallelInference micro-batching engine vs the
    naive per-request serve loop (requests/sec, p50/p99 latency)
  - gpt_decode / lstm_decode — fused autoregressive generation (ONE
    scan dispatch for all of max_new_tokens, nn/generate.py) vs the
    eager per-token dispatch loop (tokens/sec/chip, per-token p50,
    steady-state jit-miss count, greedy identity)
  - router_slo — the horizontal serving tier under open-loop Poisson
    load: rps + p50/p99 healthy vs during a mid-load engine kill
    (failover, zero lost requests) and the shed rate under a deadline
    tighter than capacity (serving/router.py InferenceRouter)
  - multi_model — 8 models served from one chip through the
    ModelRegistry engine: aggregate rps + per-model p99, a hot-swap
    deploy under load (zero lost requests, bounded p99 impact), a
    corrupt-checkpoint deploy auto-rejected, and a NaN-poisoned canary
    auto-rolled-back — all while the prior versions keep serving
  - continuous_decode — iteration-level decode scheduling over the
    paged KV block pool (serving/continuous.py) vs the PR-5
    whole-burst submit_generate path, both under the SAME open-loop
    Poisson arrival trace with mixed prompt lengths and EOS-mixed
    generation lengths under a generous max_new cap: sustained USEFUL
    tokens/sec, time-to-first-token and per-token p50/p99, pool
    occupancy/preemptions, zero steady-state compiles and zero leaked
    blocks (pool free returns to total after drain)
  - prefix_cache — the cross-request prefix cache
    (serving/prefixcache.py radix index + refcounted/COW paged pool)
    on the shared-system-prompt workload: N users × one preamble ×
    distinct tails, cached vs uncached on the same seeded open-loop
    trace — TTFT p50/p99 (the ≥3x bar), prefill-token/FLOP reduction,
    hit rate, bitwise cached-vs-uncached token identity, zero
    steady-state compiles, zero leaked/double-freed blocks
  - quantized_serving — post-training quantized serving
    (nn/quantize.py int8/fp8 weights with fused on-the-fly dequant +
    the nn/kvpool.py quantized paged KV pool): fp32 vs int8-weights vs
    int8-weights+int8-KV on the continuous_decode open-loop workload
    at ONE fixed KV device-byte budget — sustained tokens/sec,
    concurrent decode rows (the pool-admission ceiling the quantized
    pool lifts 2-4x), TTFT p50/p99, the accuracy-gate numbers the perf
    claim ships with (teacher-forced greedy match rate, logit MSE,
    eval-metric delta vs fp32), zero steady-state compiles, zero
    leaked blocks — plus a chaos phase: a weights-quantized lane
    cohabiting the fp32 lane on ONE shared pool through a registry
    quality-gated deploy and kill-mid-burst faults (typed failures,
    exact survivors, pool drains clean)
  - mesh_train — the rebuilt mesh plane (parallel/mesh.py MeshPlane):
    dp/fsdp/tp one-step fit throughput on a forced-8-device CPU mesh
    vs the single-device step, steady-state jit-miss counts, and
    checkpoint save + restore-with-relayout (8→4, 8→1) latency — the
    MULTICHIP_r*.json trajectory feed
  - mesh_serving — mesh-sharded serving slices (ISSUE 12): tp=4 slice
    endpoints serving streams through the router on a forced-8-device
    mesh with one chip KILLED mid-run — zero lost requests/tokens
    (every stream token-for-token vs eager), elastic rebuild at half
    width, recovery time — plus the disaggregated prefill/decode
    phase: decode inter-token p99 under 1x/2x prefill-heavy load with
    and without a prefill endpoint, and the pinned offload semantics
    (the decode endpoint computes ZERO heavy-prompt tokens — on one
    physical core the p99s are semantics+overhead numbers, the
    mesh_train caveat; on real chips the offload IS the p99-flatness)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The headline metric is ResNet-50 MFU when available (the heaviest
reference config), with every sub-benchmark attached.
"""

import json
import time

import numpy as np


def _enable_compile_cache():
    """Persistent XLA executable cache: the suite compiles ~20 programs
    and first-compiles are 20-40s each on this box — cached across runs
    (same dir the test conftest uses)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", "/root/.cache/jax_comp_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


# v5e peaks: bf16 ~197 TFLOP/s per chip, f32 ~½ that.
PEAK_BF16 = 197e12
PEAK_F32 = 98.5e12

# Output-payload schema the trend gate (scripts/bench_trend.py) diffs
# against history: top-level {metric, value, unit, vs_baseline,
# schema_version, sub_benchmarks: {name: {metric, value, unit, ...}}}.
# Bump ONLY on breaking shape changes (renamed/retyped required keys);
# adding optional keys is compatible and needs no bump.
BENCH_SCHEMA_VERSION = 1


def _timeit(fn, warmup=1, iters=3):
    """Time a jitted fn that RETURNS A SCALAR; synchronization is by
    fetching the scalar (block_until_ready is a silent no-op on the
    tunneled axon platform, so fetch-to-host is the only honest sync)."""
    for _ in range(warmup):
        float(fn())
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    float(out)
    return (time.perf_counter() - t0) / iters


def _best_of_fit_scan(net, batch, epochs, staged, trials=2):
    """Best-of-N timed fit_scan dispatches (BASELINE.md contention
    note) — one timing policy for every fit_scan bench."""
    dt = float("inf")
    scores = None
    for _ in range(trials):
        t0 = time.perf_counter()
        scores = net.fit_scan(None, batch, epochs=epochs, staged=staged)
        dt = min(dt, time.perf_counter() - t0)
    return scores, dt


def bench_gemm():
    """Pure-gemm ceiling: chained bf16 matmuls (keeps the MXU busy,
    avoids an HBM-bound single-op measurement). The chain runs many
    times inside ONE program via the shared scan harness — a per-
    dispatch fetch paid the tunnel RTT (~0.1-0.25s) against ~45ms of
    device work and under-read the MXU by ~30% (r3: 59-65% 'MFU')."""
    import jax
    import jax.numpy as jnp

    n, chain = 8192, 8
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(jax.random.fold_in(key, 1), (n, n), jnp.bfloat16)

    def step(i, a, b):
        x = a + i.astype(a.dtype) * 0.001  # defeat CSE across scan steps
        for _ in range(chain):
            x = x @ b
        return jnp.sum(x.astype(jnp.float32))

    dt = _scan_reps_time(step, (a, b), reps=16)
    flops = chain * 2 * n**3 / dt
    return {"metric": "gemm_bf16_tflops", "value": round(flops / 1e12, 2),
            "unit": "TFLOP/s", "mfu": round(flops / PEAK_BF16, 4),
            "vs_baseline": round((flops / PEAK_BF16) / 0.30, 4)}


def _lenet():
    # single source of truth for the flagship architecture
    import __graft_entry__ as ge
    return ge._flagship(compute_dtype="bfloat16")


def lenet_train_flops_per_example() -> float:
    """Analytic FLOPs per training example (fwd = 2*MACs, train ~ 3x fwd):
    conv1 5x5x1x20 @24x24, conv2 5x5x20x50 @8x8, dense 800->500, out 500->10."""
    macs = (24 * 24 * 20 * 25
            + 8 * 8 * 50 * 25 * 20
            + 800 * 500
            + 500 * 10)
    return 3.0 * 2.0 * macs


def bench_lenet():
    import jax
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.mnist import load_mnist

    # epochs=120 -> 960 in-program steps (~1.2s device time): the whole
    # dataset lives on-device, so the only per-dispatch cost is the
    # tunnel RTT (~0.1-0.25s) — at 40 epochs it still inflated the
    # step time ~25%; marginal-step measurement puts the true device
    # throughput at ~1.6M ex/s (see BASELINE.md LeNet roofline note)
    batch, epoch_examples, epochs = 2048, 2048 * 8, 120
    net = _lenet()
    ds = load_mnist(train=True, num_examples=epoch_examples)
    data = DataSet(ds.features.reshape(-1, 28, 28, 1), ds.labels)

    staged = net.stage_scan(data, batch)  # one host→device transfer
    # warm up the SAME epochs-baked program the timed run uses; best of
    # 2 dispatches rides out pool contention (BASELINE.md note)
    net.fit_scan(None, batch, epochs=epochs, staged=staged)
    scores, dt = _best_of_fit_scan(net, batch, epochs, staged)

    n_examples = epochs * (epoch_examples // batch) * batch
    eps = n_examples / dt
    mfu = eps * lenet_train_flops_per_example() / PEAK_BF16
    assert np.isfinite(np.asarray(scores)).all()
    return {"metric": "lenet_mnist_train_examples_per_sec_per_chip",
            "value": round(eps, 1), "unit": "examples/sec/chip",
            "mfu": round(mfu, 4), "vs_baseline": round(mfu / 0.30, 4)}


def bench_lstm():
    """GravesLSTM char-RNN shape (config #4, LSTMHelpers.java:54,:212):
    vocab 64, hidden 512, seq 128 — hoisted input projections + per-step
    recurrent gemm [b,512]x[512,2048] inside lax.scan."""
    import jax
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    # batch 1024: the per-timestep recurrent gemm is [b,512]x[512,2048];
    # below ~1k batch the scan is latency-bound, not MXU-bound (256 ->
    # 3% MFU, 1024 -> 23% measured on v5e)
    vocab, hidden, seq, batch = 64, 512, 128, 1024
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.01).updater("adam").activation("tanh")
            .compute_dtype("bfloat16")
            .list()
            .layer(GravesLSTM(n_in=vocab, n_out=hidden))
            .layer(GravesLSTM(n_in=hidden, n_out=hidden))
            .layer(RnnOutputLayer(n_in=hidden, n_out=vocab, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch * 2, seq))
    x = np.eye(vocab, dtype=np.float32)[ids]
    y = np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    data = DataSet(x, y)

    staged = net.stage_scan(data, batch)  # one host→device transfer
    # 48 epochs x 2 steps: ~4.3s of device time per dispatch, so the
    # tunnel dispatch RTT (~0.1-0.25s) is <6% even at the slow end (the
    # same amortization note as bench_lenet / BASELINE.md; at 16 epochs
    # the RTT still shaved ~2pp of MFU)
    epochs = 48
    # warm up the SAME epochs-baked program the timed run uses; best
    # of 2 dispatches (BASELINE.md contention note)
    net.fit_scan(None, batch, epochs=epochs, staged=staged)
    scores, dt = _best_of_fit_scan(net, batch, epochs, staged)

    n_tokens = epochs * 2 * batch * seq
    tps = n_tokens / dt
    # per-token MACs: layer Wx [in,4h] + Wr [h,4h] per LSTM, + softmax head
    macs = (vocab * 4 * hidden + hidden * 4 * hidden
            + hidden * 4 * hidden + hidden * 4 * hidden
            + hidden * vocab)
    mfu = tps * 3 * 2 * macs / PEAK_BF16
    assert np.isfinite(np.asarray(scores)).all()
    return {"metric": "lstm_char_tokens_per_sec_per_chip",
            "value": round(tps, 1), "unit": "tokens/sec/chip",
            "mfu": round(mfu, 4), "vs_baseline": round(mfu / 0.30, 4)}



def _scan_reps_time(make_step, compile_args, reps, trials=5):
    """Time a per-step computation by scanning it ``reps`` times inside
    ONE program and taking the best of ``trials`` dispatches — the
    amortization recipe for ops whose single call is comparable to the
    tunnel dispatch RTT (BASELINE.md note). ``make_step(i)`` returns the
    scalar contribution for scan step i."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def rep(*args):
        def step(c, i):
            return c + make_step(i, *args), 0
        tot, _ = jax.lax.scan(step, jnp.float32(0), jnp.arange(reps))
        return tot

    float(rep(*compile_args))  # compile
    return min(_timeit(lambda: rep(*compile_args), warmup=0, iters=1)
               for _ in range(trials)) / reps


def bench_flash_attention():
    """Pallas flash-attention kernel, 16k causal bf16 (the long-context
    hot op; the XLA formulation OOMs past ~16k on the [b,h,t,t] scores).
    The kernel runs 16x inside ONE program (input varied per step to
    defeat CSE) and the best of 3 dispatches is taken — one bare kernel
    call is ~10ms, which the tunnel dispatch RTT would otherwise
    dominate (same amortization note as bench_lenet / BASELINE.md)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    b, t, h, d = 1, 16384, 8, 128
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d),
                                 jnp.bfloat16) for i in range(3))

    def step(i, q, k, v):  # perturb per step to defeat CSE
        o = flash_attention(q + i.astype(q.dtype) * 0.001, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32))

    # pinned protocol (VERDICT r4 #8): 10 trials instead of the default
    # 5 — the fwd kernel's documented clean-condition plateau is
    # 62.7 TF/s but pool contention drifts single runs 48-62; more
    # best-of trials tightens the read, and the JSON carries the
    # documented plateau + drift band explicitly so a contended run is
    # legible as such instead of under-reading the kernel
    dt = _scan_reps_time(step, (q, k, v), reps=16, trials=10)
    flops = 4 * b * h * t * t * d / 2 / dt  # causal halves the work
    return {"metric": "flash_attention_16k_causal_tflops",
            "value": round(flops / 1e12, 2), "unit": "TFLOP/s",
            "mfu": round(flops / PEAK_BF16, 4),
            "clean_plateau_tflops": 62.7,  # BASELINE.md flash fwd roofline
            "contention_drift_band_tflops": [48.0, 63.0],
            "vs_baseline": round((flops / PEAK_BF16) / 0.30, 4)}


def bench_flash_attention_train():
    """Pallas flash fwd+bwd TRAINING step at 32k causal — the config
    where the XLA formulation OOMs outright; both directions are Pallas
    kernels (ops/flash_attention.py), so the O(t²) weights never touch
    HBM. Flops: the mathematically required count — fwd 2 matmuls +
    bwd 5 matmuls (the standard 3.5x-forward convention) on the causal
    half; the implementation's duplicated s/dP matmuls are NOT credited."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    b, t, h, d = 1, 32768, 8, 128
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d),
                                 jnp.bfloat16) for i in range(3))
    loss = lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True).astype(jnp.float32) * 1e-3)
    grad_fn = jax.grad(loss, argnums=(0, 1, 2))

    def step(i, q, k, v):  # perturb per step to defeat CSE
        g = grad_fn(q + i.astype(q.dtype) * 0.001, k, v)
        return jnp.sum(g[0].astype(jnp.float32))

    dt = _scan_reps_time(step, (q, k, v), reps=16)  # ~0.9s per dispatch
    flops = (4 + 10) * b * h * t * t * d / 2 / dt
    return {"metric": "flash_attention_train_32k_causal_tflops",
            "value": round(flops / 1e12, 2), "unit": "TFLOP/s",
            "mfu": round(flops / PEAK_BF16, 4),
            "vs_baseline": round((flops / PEAK_BF16) / 0.30, 4)}


def bench_mlp_iris():
    """MLP-Iris (BASELINE config #2, 'DenseLayer only, ND4J gemm
    path'): the 4-feature/3-class shape at modern batch, fit_scan."""
    import time

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iris import load_iris_dataset
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    iris = load_iris_dataset()
    reps = 256  # 150 rows -> 38.4k examples so the chip sees real batches
    x = np.tile(iris.features, (reps, 1)).astype(np.float32)
    y = np.tile(iris.labels, (reps, 1)).astype(np.float32)
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.1).updater("adam").activation("relu")
            .compute_dtype("bfloat16")
            .list()
            .layer(DenseLayer(n_in=4, n_out=64))
            .layer(DenseLayer(n_in=64, n_out=64))
            .layer(OutputLayer(n_in=64, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    batch = 4096
    staged = net.stage_scan(DataSet(x, y), batch)
    epochs = 400  # tiny model: dispatch RTT swamps short programs
    # warm up the SAME epochs-baked program the timed run uses
    net.fit_scan(None, batch, epochs=epochs, staged=staged)
    scores, dt = _best_of_fit_scan(net, batch, epochs, staged)
    n = epochs * (x.shape[0] // batch) * batch
    assert np.isfinite(np.asarray(scores)).all()
    return {"metric": "mlp_iris_train_examples_per_sec_per_chip",
            "value": round(n / dt, 1), "unit": "examples/sec/chip",
            "vs_baseline": 1.0}  # reference publishes no number (BASELINE.md)


def bench_mlp_per_step_fit():
    """Per-step ``fit()`` path (NOT fit_scan) with the device-feed
    pipeline on vs off — the host-loop overhead benchmark. Pipeline on:
    prefetch-to-device staging thread, deferred score sync (no per-step
    device round-trip), and a shape-bucketed ragged tail (one compiled
    program across epochs). Pipeline off: the legacy loop with a
    blocking ``float(score)`` + h2d transfer on the critical path every
    iteration. Reports examples/sec both ways plus the feed-pipeline
    monitor counters so the JSON attributes the gap."""
    import time

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(0)
    batch = 4096
    n = batch * 10 + 1234  # ragged tail exercises the bucketing stage
    x = rng.standard_normal((n, 64)).astype(np.float32)
    y = np.eye(8, dtype=np.float32)[rng.integers(0, 8, n)]
    data = DataSet(x, y)

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.05).updater("adam").activation("relu")
                .compute_dtype("bfloat16")
                .list()
                .layer(DenseLayer(n_in=64, n_out=512))
                .layer(DenseLayer(n_in=512, n_out=512))
                .layer(OutputLayer(n_in=512, n_out=8, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    counter_names = (monitor.SCORE_SYNC_COUNTER, monitor.JIT_CACHE_MISS_COUNTER,
                     monitor.H2D_BYTES_COUNTER, monitor.FEED_PADDED_BATCHES_COUNTER)

    def run(pipeline):
        reg = monitor.get_registry()
        net = build()
        net.fit(ListDataSetIterator(data, batch), feed_pipeline=pipeline)  # warmup/compile
        before = {c: reg.family_total(c) for c in counter_names}
        epochs = 4
        t0 = time.perf_counter()
        for _ in range(epochs):
            net.fit(ListDataSetIterator(data, batch), feed_pipeline=pipeline)
        float(net.score())  # drain the dispatch queue before stopping the clock
        dt = time.perf_counter() - t0
        counters = {c: round(reg.family_total(c) - before[c], 1)
                    for c in counter_names}
        batches = n // batch + (1 if n % batch else 0)
        return epochs * batches * batch / dt, counters

    on_eps, on_counters = run(True)
    off_eps, off_counters = run(False)
    return {"metric": "mlp_per_step_fit_examples_per_sec_per_chip",
            "value": round(on_eps, 1), "unit": "examples/sec/chip",
            "pipeline_off_examples_per_sec": round(off_eps, 1),
            "pipeline_speedup": round(on_eps / off_eps, 3),
            "counters_pipeline_on": on_counters,
            "counters_pipeline_off": off_counters,
            # the comparable baseline is the legacy per-step loop itself
            "vs_baseline": round(on_eps / off_eps, 3)}


def bench_serving_inference():
    """Serving path: the ParallelInference micro-batching engine vs the
    naive per-request ``net.output`` loop, at several concurrency
    levels. The naive loop pays one dispatch (and on the tunneled
    platform one ~50-100ms host round-trip) per request; the engine
    coalesces concurrent requests into padded bucket batches across
    replicas. Reports requests/sec + per-request p50/p99 latency per
    level, the jit-cache-miss count during the post-warmup steady state
    (zero == the AOT warmup covered every dispatched program), and the
    batched-vs-unbatched numeric parity."""
    import threading
    import time

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    rng = np.random.default_rng(0)
    nin, nc = 64, 8
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.05).updater("adam").activation("relu")
            .list()
            .layer(DenseLayer(n_in=nin, n_out=256))
            .layer(OutputLayer(n_in=256, n_out=nc, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()

    levels = (1, 8, 16)
    n_each = 24  # requests per driver thread

    def drive(call, concurrency):
        xs = [rng.standard_normal((1, nin)).astype(np.float32)
              for _ in range(concurrency)]
        lats = [[] for _ in range(concurrency)]
        errors = []

        def worker(i):
            try:
                for _ in range(n_each):
                    t0 = time.perf_counter()
                    call(xs[i])
                    lats[i].append(time.perf_counter() - t0)
            except Exception as e:  # surfaced as a benched error
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise errors[0]
        flat = sorted(v for ls in lats for v in ls)
        n = len(flat)
        return {"requests_per_sec": round(n / wall, 1),
                "p50_ms": round(flat[n // 2] * 1e3, 3),
                "p99_ms": round(flat[min(n - 1, int(n * 0.99))] * 1e3, 3)}

    engine = ParallelInference(net, max_batch_size=32, max_latency_ms=3.0)
    engine.warmup([(nin,)])
    probe = rng.standard_normal((4, nin)).astype(np.float32)
    inline = np.asarray(net.output(probe))  # also warms the naive path
    net.output(probe[:1])
    batched = engine.output(probe)
    parity = float(np.abs(batched - inline).max())

    reg = monitor.get_registry()
    misses_before = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
    results = {}
    try:
        for c in levels:
            results[f"engine_c{c}"] = drive(engine.output, c)
            results[f"naive_c{c}"] = drive(
                lambda x: np.asarray(net.output(x)), c)
    finally:
        steady_misses = reg.family_total(
            monitor.JIT_CACHE_MISS_COUNTER) - misses_before
        stats = engine.stats()
        engine.shutdown()

    on = results["engine_c8"]["requests_per_sec"]
    off = results["naive_c8"]["requests_per_sec"]
    return {"metric": "serving_inference_requests_per_sec",
            "value": on, "unit": "requests/sec",
            "levels": results,
            "engine_speedup_c8": round(on / off, 3),
            "steady_state_jit_misses": steady_misses,
            "batched_vs_unbatched_max_abs_diff": parity,
            "batched_bitwise_equal": parity == 0.0,
            "engine_stats": stats,
            # the comparable baseline is the naive per-request loop
            "vs_baseline": round(on / off, 3)}


def bench_fault_recovery():
    """Fault-tolerance recovery-time benchmark, two fault domains:

    (1) training — inject one NaN batch into a supervised per-step fit;
    report the wall time of the rollback (detect → restore snapshot →
    LR backoff → recompile) and steps-to-resume (batches from the fault
    until the next healthy step lands — 1 means the very next batch
    trained);

    (2) serving — a closed-loop request driver against a 2-replica
    ParallelInference engine; report p50/p99 per-request latency
    healthy vs during a replica quarantine (poison hook trips one
    replica; the engine serves on at reduced capacity) and the
    recovery time from first injected fault to quarantine."""
    import time

    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.faultinject import (FailingDataSetIterator,
                                                poison_replica)
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.supervisor import TrainingSupervisor
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    rng = np.random.default_rng(0)
    nin, nc = 64, 8

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.05).updater("adam").activation("relu")
                .list()
                .layer(DenseLayer(n_in=nin, n_out=256))
                .layer(OutputLayer(n_in=256, n_out=nc, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    # ---- (1) NaN rollback recovery time
    n = 256 * 12
    data = DataSet(rng.standard_normal((n, nin)).astype(np.float32),
                   np.eye(nc, dtype=np.float32)[rng.integers(0, nc, n)])
    net = build()
    net.fit(ListDataSetIterator(data, 256))  # warm the train program
    sup = TrainingSupervisor(net, max_rollbacks=3)
    it = FailingDataSetIterator(ListDataSetIterator(data, 256), nan_at={5})
    steps_to_resume = None
    t_fault = t_recovered = None
    it.reset()
    while it.has_next():
        ds = it.next()
        t0 = time.perf_counter()
        ok = sup.step(ds)
        if not ok and t_fault is None:
            t_fault = t0  # the batch that tripped the rollback
        elif t_fault is not None and ok and t_recovered is None:
            t_recovered = time.perf_counter()
            steps_to_resume = sup.steps_done - 1 - sup.batches_skipped[-1]
    rollback_ms = (t_recovered - t_fault) * 1e3 if t_recovered else None

    # ---- (2) engine p99 during quarantine vs healthy
    snet = build()
    dev = jax.devices()[0]
    eng = ParallelInference(snet, max_batch_size=16, max_latency_ms=2.0,
                            devices=[dev, dev],
                            probe_interval_ms=3600_000.0)  # no self-heal mid-run
    try:
        eng.warmup([(nin,)])

        def drive(n_requests):
            lats = []
            for _ in range(n_requests):
                x = rng.standard_normal((2, nin)).astype(np.float32)
                t0 = time.perf_counter()
                eng.output(x, timeout=60)
                lats.append((time.perf_counter() - t0) * 1e3)
            return lats

        healthy = drive(200)
        t0 = time.perf_counter()
        poison_replica(eng, replica=0, failures=2)
        degraded = []
        for _ in range(100):  # bounded: ~1000 requests to trip the poison
            if eng.stats()["quarantined"]:
                break
            degraded.extend(drive(10))
        quarantine_ms = (time.perf_counter() - t0) * 1e3
        degraded.extend(drive(200))
        q = lambda xs, p: float(np.percentile(np.asarray(xs), p))
        result_serving = {
            "healthy_p50_ms": round(q(healthy, 50), 3),
            "healthy_p99_ms": round(q(healthy, 99), 3),
            "quarantined_p50_ms": round(q(degraded, 50), 3),
            "quarantined_p99_ms": round(q(degraded, 99), 3),
            "time_to_quarantine_ms": round(quarantine_ms, 3),
            "replicas": 2, "healthy_replicas_during_fault": 1,
        }
    finally:
        eng.shutdown()

    return {"metric": "fault_recovery_nan_rollback_ms",
            "value": round(rollback_ms, 3) if rollback_ms else -1.0,
            "unit": "ms",
            "steps_to_resume": steps_to_resume,
            "rollbacks": sup.rollbacks,
            "serving": result_serving,
            "vs_baseline": 1.0}


def _decode_bench(net, prompt, max_new, flops_per_token=None):
    """Shared fused-vs-eager decode measurement: warm both paths, pin
    greedy identity, time best-of-N, and report tokens/sec/chip +
    per-token p50 + the steady-state jit-miss count (the zero-compiles
    acceptance gate — the fused path must dispatch exactly its two
    warmed programs per run)."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.nn.generate import generate_eager

    b = prompt.shape[0]
    # warm/compile both paths (the timed runs then reuse executables)
    fused0 = net.generate(prompt, max_new)
    eager0 = generate_eager(net, prompt, max_new)
    greedy_equal = bool(np.array_equal(fused0, eager0))

    reg = monitor.get_registry()
    miss0 = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
    trials = 5
    fused_dts = []
    for _ in range(trials):
        t0 = time.perf_counter()
        net.generate(prompt, max_new)
        fused_dts.append(time.perf_counter() - t0)
    steady_misses = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) - miss0
    eager_dts = []
    for _ in range(2):
        t0 = time.perf_counter()
        generate_eager(net, prompt, max_new)
        eager_dts.append(time.perf_counter() - t0)

    tokens = b * max_new
    fused_tps = tokens / min(fused_dts)
    eager_tps = tokens / min(eager_dts)
    per_tok_ms = sorted(dt / max_new * 1e3 for dt in fused_dts)
    out = {
        "value": round(fused_tps, 1), "unit": "tokens/sec/chip",
        "eager_tokens_per_sec": round(eager_tps, 1),
        "fused_vs_eager": round(fused_tps / eager_tps, 3),
        "per_token_p50_ms": round(per_tok_ms[len(per_tok_ms) // 2], 4),
        "steady_state_jit_misses": float(steady_misses),
        "greedy_matches_eager": greedy_equal,
        "batch": b, "prompt_len": int(prompt.shape[1]),
        "max_new_tokens": max_new,
        # the comparable baseline is the eager per-token loop this
        # engine replaces (>= 5x is the acceptance bar)
        "vs_baseline": round(fused_tps / eager_tps, 3),
    }
    if flops_per_token is not None:
        out["mfu"] = round(fused_tps * flops_per_token / PEAK_BF16, 4)
    return out


def bench_gpt_decode():
    """Fused KV-cache decode (nn/generate.py: bucketed prefill + ALL of
    max_new_tokens as ONE lax.scan dispatch, on-device sampling) vs the
    eager per-token loop (one dispatch per token — the pre-PR serving
    status quo, which on the tunneled platform pays a host round-trip
    per token). Greedy output must be identical and the fused steady
    state must perform zero XLA compiles."""
    from deeplearning4j_tpu.models.zoo.transformer import gpt

    vocab, d, layers, heads, max_len = 8192, 512, 8, 8, 512
    b, t0, max_new = 8, 64, 128
    net = gpt(vocab_size=vocab, d_model=d, n_layers=layers,
              num_heads=heads, max_len=max_len,
              compute_dtype="bfloat16").init()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, vocab, (b, t0))
    # decode-step MACs/token: qkv+proj+mlp weights + O(t) attention reads
    per_layer = 3 * d * d + d * d + 2 * 4 * d * d + (t0 + max_new) * d
    flops = 2.0 * (layers * per_layer + d * vocab)
    return {"metric": "gpt_decode_tokens_per_sec_per_chip",
            **_decode_bench(net, prompt, max_new, flops_per_token=flops)}


def bench_lstm_decode():
    """Char-RNN generation through the scanned LSTM recurrence (config
    #4 shape family): same fused-vs-eager protocol as gpt_decode."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    vocab, hidden = 64, 512
    b, t0, max_new = 32, 32, 128
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.01).updater("adam").activation("tanh")
            .list()
            .layer(GravesLSTM(n_in=vocab, n_out=hidden))
            .layer(GravesLSTM(n_in=hidden, n_out=hidden))
            .layer(RnnOutputLayer(n_in=hidden, n_out=vocab,
                                  activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, vocab, (b, t0))
    macs = (vocab * 4 * hidden + hidden * 4 * hidden
            + hidden * 4 * hidden + hidden * 4 * hidden + hidden * vocab)
    return {"metric": "lstm_decode_tokens_per_sec_per_chip",
            **_decode_bench(net, prompt, max_new,
                            flops_per_token=2.0 * macs)}


def bench_continuous_decode():
    """Continuous batching vs whole-burst decode under the SAME seeded
    open-loop Poisson trace (arrivals don't wait for completions) with
    mixed prompt lengths and EOS-mixed GENERATION lengths — every
    request carries a generous max_new cap (the API max_tokens shape)
    but terminates at its own sampled EOS, typically far earlier. This
    is the traffic the whole-burst path structurally cannot serve
    well: a coalesced group computes until its SLOWEST row finishes
    (expected max of n geometric lengths grows with ln n while useful
    work stays at the mean), and every row pins a dense
    bucket+max_new cache for the group's whole lifetime. The
    iteration-level scheduler retires each row at ITS eos between
    K-token bursts, backfills the slot from the queue, and recycles
    the row's pool blocks immediately. Throughput counts USEFUL tokens
    (through each row's eos). Acceptance: >= 1.5x sustained tokens/sec
    and lower p99 time-to-first-token, with zero steady-state XLA
    compiles and zero leaked KV blocks."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    vocab, d, layers, heads, max_len = 32, 128, 4, 4, 256
    eos, max_new, temp = 0, 160, 2.0
    net = gpt(vocab_size=vocab, d_model=d, n_layers=layers,
              num_heads=heads, max_len=max_len,
              compute_dtype="float32", learning_rate=0.01).init()
    rng = np.random.default_rng(0)
    # saturating Poisson arrivals; mixed prompt buckets; generation
    # lengths ~ geometric via sampled EOS (mean ~vocab steps), capped
    # far above the mean by max_new — the realistic serving mix
    n_req = 96
    arrivals = np.cumsum(rng.exponential(0.0025, n_req))
    plens = rng.choice([6, 14, 30], n_req)
    prompts = [rng.integers(1, vocab, (1, int(t))) for t in plens]
    reg = monitor.get_registry()

    def useful(row, t_in):
        """Tokens through the row's own EOS (inclusive); the cap when
        no EOS was sampled."""
        gen = row[t_in:]
        idx = np.where(gen == eos)[0]
        return int(idx[0]) + 1 if len(idx) else len(gen)

    def drive(engine, scheduler=None):
        """One open-loop pass: submit on the trace clock, poll to
        completion, return per-request timings + pool peek."""
        done_t = {}

        def cb(i):
            return lambda f: done_t.__setitem__(i, time.perf_counter())

        t0 = time.perf_counter()
        subs, futs = [], []
        for i in range(n_req):
            target = t0 + arrivals[i]
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            subs.append(time.perf_counter())
            f = engine.submit_generate(prompts[i], max_new,
                                       temperature=temp, eos_token=eos,
                                       seed=i)
            f.add_done_callback(cb(i))
            futs.append(f)
        peak_occ = 0.0
        while len(done_t) < n_req:
            if scheduler is not None:
                peak_occ = max(peak_occ,
                               scheduler.stats()["pool"]["occupancy"])
            time.sleep(5e-3)
        tokens = [useful(f.result(0)[0], int(plens[i]))
                  for i, f in enumerate(futs)]
        t_end = max(done_t.values())
        total = int(np.sum(tokens))
        per_tok = sorted((done_t[i] - subs[i]) / tokens[i] * 1e3
                         for i in range(n_req))
        if scheduler is not None:
            ttfts = sorted((c["t_first"] - c["t_submit"]) * 1e3
                           for c in scheduler.completed)
        else:
            # whole-burst: the first token only exists when the whole
            # burst resolves — TTFT IS completion latency
            ttfts = sorted((done_t[i] - subs[i]) * 1e3
                           for i in range(n_req))
        q = lambda xs, p: xs[min(len(xs) - 1, int(len(xs) * p))]
        return {
            "tokens": total,
            "tokens_per_sec": total / (t_end - t0),
            "ttft_p50_ms": q(ttfts, 0.5), "ttft_p99_ms": q(ttfts, 0.99),
            "per_token_p50_ms": q(per_tok, 0.5),
            "per_token_p99_ms": q(per_tok, 0.99),
            "peak_pool_occupancy": peak_occ,
        }

    warm_lens = [6, 14, 30]
    # --- baseline: the PR-5 whole-burst coalescing path, OUT-OF-THE-BOX
    # knobs (max_batch_size=32, 5ms window — its designed operating
    # point; smaller batches would just trade its waste for latency)
    base_eng = ParallelInference(net, replicas=1)
    base_eng.warmup_generate(warm_lens, max_new, temperature=temp,
                             eos_token=eos)
    base = drive(base_eng)
    base_eng.shutdown()

    # --- continuous: iteration-level scheduler + paged KV pool sized
    # for the COMMON-case context (not slots x max cap: rare long
    # generations preempt instead of reserving worst-case memory)
    cont_eng = ParallelInference(net, replicas=1, continuous=True,
                                 decode_slots=16, decode_burst=8,
                                 kv_block_size=16, kv_blocks=97)
    cont_eng.warmup_generate(warm_lens, max_new)
    miss0 = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
    sched = cont_eng._continuous_scheduler()
    cont = drive(cont_eng, scheduler=sched)

    # --- tracing overhead (ISSUE 13): the SAME drive with request
    # tracing ON — the scheduler self-roots one trace per request
    # (queue_wait / prefill / decode_burst / chunk_deliver spans, all
    # from host timestamps the loop already takes). The acceptance bar
    # is ≤5% sustained tokens/sec, zero added device syncs, zero
    # steady-state compiles (the jit-miss window below spans BOTH
    # runs, so a tracing-induced compile would show up here).
    from deeplearning4j_tpu.monitor import reqtrace
    tracer = reqtrace.enable_request_tracing(completed_capacity=4096)
    traced = drive(cont_eng, scheduler=sched)
    reqtrace.disable_request_tracing()
    # decomposition FROM THE TRACES (tracer-scoped, so exactly this
    # run's spans — the process-global histogram would mix in earlier
    # sub-benchmarks' traced traffic)
    phase_ms = {}
    for entry in tracer.completed_traces():
        for s in entry["spans"]:
            phase_ms.setdefault(s["name"], []).append(s["dur_us"] / 1e3)
    ttft_phases = {
        k: {"count": len(v), "p50_ms": round(float(np.median(v)), 3),
            "p99_ms": round(float(np.percentile(v, 99)), 3)}
        for k, v in sorted(phase_ms.items())}

    # --- capacity observatory overhead (this PR): the SAME drive with
    # the windowed time-series layer DISABLED — the A/B behind the ≤2%
    # acceptance bar. Enabled is the default, so ``cont`` above IS the
    # enabled arm; every observatory sample is a host-side float
    # append, so the jit-miss window spanning all these runs also
    # proves it compiles nothing.
    prev_ts = monitor.set_timeseries_enabled(False)
    try:
        obs_off = drive(cont_eng, scheduler=sched)
    finally:
        monitor.set_timeseries_enabled(prev_ts)
    active_q = monitor.ts_query(monitor.TS_SCHED_ACTIVE, 60.0)

    steady_misses = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) - miss0
    cont_eng.drain(60)
    pool = sched.stats()["pool"]
    leaked = int(pool["blocks_total"] - pool["blocks_free"])
    sstats = sched.stats()
    cont_eng.shutdown()

    ratio = cont["tokens_per_sec"] / base["tokens_per_sec"]
    return {
        "metric": "continuous_decode_sustained_tokens_per_sec",
        "value": round(cont["tokens_per_sec"], 1), "unit": "tokens/sec",
        "whole_burst_tokens_per_sec": round(base["tokens_per_sec"], 1),
        # acceptance composite: the >= 1.5x sustained-throughput bar
        "vs_baseline": round(ratio, 3),
        "ttft_p50_ms": round(cont["ttft_p50_ms"], 2),
        "ttft_p99_ms": round(cont["ttft_p99_ms"], 2),
        "whole_burst_ttft_p50_ms": round(base["ttft_p50_ms"], 2),
        "whole_burst_ttft_p99_ms": round(base["ttft_p99_ms"], 2),
        "ttft_p99_improvement": round(
            base["ttft_p99_ms"] / max(1e-9, cont["ttft_p99_ms"]), 3),
        "per_token_p50_ms": round(cont["per_token_p50_ms"], 3),
        "per_token_p99_ms": round(cont["per_token_p99_ms"], 3),
        "whole_burst_per_token_p99_ms": round(base["per_token_p99_ms"], 3),
        "useful_tokens": cont["tokens"],
        "peak_pool_occupancy": round(cont["peak_pool_occupancy"], 3),
        "preemptions": int(sstats["preemptions"]),
        "bursts": int(sstats["bursts"]),
        "steady_state_jit_misses": float(steady_misses),
        "leaked_blocks": leaked,
        "requests": n_req,
        "max_new_cap": max_new,
        # ISSUE 13: per-request tracing cost + the TTFT decomposition
        # the traces yield (phase p50/p99 across the traced run)
        "tracing": {
            "tokens_per_sec_untraced": round(cont["tokens_per_sec"], 1),
            "tokens_per_sec_traced": round(traced["tokens_per_sec"], 1),
            "overhead_frac": round(
                max(0.0, 1.0 - traced["tokens_per_sec"]
                    / max(1e-9, cont["tokens_per_sec"])), 4),
            "spans_recorded": sum(len(e["spans"])
                                  for e in tracer.completed_traces()),
            "spans_dropped": int(tracer.dropped),
            "ttft_phase_ms": ttft_phases,
        },
        # capacity observatory cost: enabled (default) vs disabled on
        # the same engine/trace, plus one live window query as proof
        # the series actually populated during the enabled run
        "observatory": {
            "tokens_per_sec_enabled": round(cont["tokens_per_sec"], 1),
            "tokens_per_sec_disabled": round(obs_off["tokens_per_sec"], 1),
            "overhead_frac": round(
                max(0.0, 1.0 - cont["tokens_per_sec"]
                    / max(1e-9, obs_off["tokens_per_sec"])), 4),
            "active_rows_60s": (None if active_q is None else {
                "count": active_q["count"],
                "mean": round(active_q["mean"], 3),
                "p99": round(active_q["p99"], 3)}),
        },
    }


def bench_speculative_decode():
    """Speculative decoding (ISSUE 17): per-stream decode latency at
    small batch, where the engine is latency-bound — one target
    forward per token — and speculation is designed to win. A small
    draft proposes K tokens on its own paged-KV lane (one scanned
    program), the target verifies all K+1 positions in ONE forward,
    and exact rejection sampling keeps greedy output token-for-token
    equal to ``generate_eager``. Target and draft are both trained on
    the same near-deterministic synthetic language — the honest
    analogue of a production distilled draft: a draft only pays when
    it AGREES with the target on the serving distribution, so the
    bench earns its acceptance rate instead of staging one.
    Acceptance: >= 2x per-stream tokens/sec at batch 1-4 vs the
    non-speculative continuous path on the same net, NO regression at
    saturation (the spec_max_rows fallback engages — speculation is a
    latency tool, not a throughput tool), greedy parity vs the eager
    oracle, zero steady-state XLA compiles across the accept ladder,
    and zero leaked KV blocks on BOTH lanes."""
    import jax
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.serving.continuous import \
        ContinuousDecodeScheduler

    # K deeper than the plain burst: with near-1.0 agreement each spec
    # round yields K+1 tokens for ONE target verify, so the deeper K
    # amortizes the per-round host syncs; the plain arm keeps its own
    # tuned burst depth — the comparison is tuned-vs-tuned, not
    # handicapped
    vocab, max_new, k_spec, burst, slots = 32, 64, 12, 8, 8
    target = gpt(vocab_size=vocab, d_model=128, n_layers=4, num_heads=4,
                 max_len=128, compute_dtype="float32",
                 learning_rate=0.01).init()
    draft = gpt(vocab_size=vocab, d_model=32, n_layers=1, num_heads=2,
                max_len=128, compute_dtype="float32",
                learning_rate=0.01).init()
    rng = np.random.default_rng(0)

    def batch(b=16, t=33):
        start = rng.integers(0, vocab, (b, 1))
        ids = (start + np.arange(t)[None, :]) % vocab
        x = ids[:, :-1].astype(np.float32)
        y = np.eye(vocab, dtype=np.float32)[ids[:, 1:]]
        return DataSet(x, y)

    # cyclic counting: next = (prev + 1) % vocab — both nets learn it
    # to ~perfect greedy agreement in a few hundred tiny steps
    for _ in range(600):
        ds = batch()
        target.fit(ds)
        draft.fit(ds)
    reg = monitor.get_registry()
    prompts = [((np.arange(8) + 3 * i) % vocab)[None, :].astype(np.int64)
               for i in range(16)]

    def run(speculative, b):
        kw = ({"speculative": True, "spec_tokens": k_spec,
               "spec_max_rows": 4, "draft_net": draft}
              if speculative else {})
        sched = ContinuousDecodeScheduler(
            net=target, slots=slots, burst_tokens=burst, block_size=16,
            start=False, **kw)
        sched.warmup([8], max_new)
        miss0 = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        t0 = time.perf_counter()
        futs = [sched.submit(p, max_new) for p in prompts[:b]]
        steps = 0
        while not all(f.done() for f in futs):
            sched.step()
            steps += 1
            if steps > 20000:
                raise RuntimeError("speculative bench did not converge")
        dt = time.perf_counter() - t0
        outs = [f.result(0) for f in futs]
        st = sched.stats()
        dpool = st.get("draft_pool", {"blocks_total": 0, "blocks_free": 0})
        spec_st = st["speculative"]
        return {
            # every stream decodes max_new tokens over the same wall
            "per_stream_tokens_per_sec": max_new / dt,
            "steady_state_jit_misses": float(
                reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) - miss0),
            "leaked_blocks_target": int(st["pool"]["blocks_total"]
                                        - st["pool"]["blocks_free"]),
            "leaked_blocks_draft": int(dpool["blocks_total"]
                                       - dpool["blocks_free"]),
            "accept_rate": spec_st["accept_rate"],
            "rounds": spec_st["rounds"],
            "fallbacks": spec_st["fallbacks"],
        }, outs

    results = {}
    parity_ok = True
    for b in (1, 4, 16):
        plain, _ = run(False, b)
        spec, outs = run(True, b)
        if b <= 4:  # the greedy-parity oracle (eager is slow: spot-check)
            for p, out in list(zip(prompts, outs))[:2]:
                parity_ok &= bool(np.array_equal(
                    out, generate_eager(target, p, max_new)))
        results[b] = {
            "plain_tokens_per_sec": round(
                plain["per_stream_tokens_per_sec"], 1),
            "spec_tokens_per_sec": round(
                spec["per_stream_tokens_per_sec"], 1),
            "speedup": round(spec["per_stream_tokens_per_sec"]
                             / max(1e-9,
                                   plain["per_stream_tokens_per_sec"]), 3),
            "accept_rate": round(spec["accept_rate"], 4),
            "spec_rounds": spec["rounds"],
            "spec_fallbacks": spec["fallbacks"],
            "steady_state_jit_misses": spec["steady_state_jit_misses"]
            + plain["steady_state_jit_misses"],
            "leaked_blocks": spec["leaked_blocks_target"]
            + spec["leaked_blocks_draft"] + plain["leaked_blocks_target"],
        }
    # batch 16 over slots=8 with spec_max_rows=4: always saturated —
    # the fallback must engage and throughput must not regress
    sat = results[16]
    return {
        "metric": "speculative_decode_speedup_batch1",
        "value": results[1]["speedup"], "unit": "x",
        "batch1": results[1], "batch4": results[4], "saturated": sat,
        "speedup_batch4": results[4]["speedup"],
        "saturation_ratio": sat["speedup"],
        "fallback_engaged_at_saturation": sat["spec_fallbacks"] > 0,
        "greedy_matches_eager": parity_ok,
        "k_spec": k_spec, "max_new": max_new,
        "draft_params_frac": round(
            sum(x.size for x in jax.tree_util.tree_leaves(draft.params))
            / sum(x.size for x in jax.tree_util.tree_leaves(target.params)),
            4),
    }


def bench_quantized_serving():
    """Quantized serving end to end (ISSUE 14): the same model served
    fp32, int8-weights, and int8-weights + int8-KV under the SAME
    seeded open-loop trace and ONE fixed KV device-byte budget. The
    claims measured here, each with its gate:

    - **rows**: the paged pool is the admission ceiling (PR 8 preempts
      on exhaustion); int8 KV blocks cost ~3.6x fewer bytes, so the
      same budget holds ~3x the blocks → more CONCURRENT decode rows
      and fewer preemptions (peak active_sequences, polled live);
    - **tokens/sec**: sustained useful-token throughput per arm (on one
      CPU core the dequant adds compute, so the honest win here is the
      row/preemption headroom; on bandwidth-bound chips the byte
      reduction IS throughput);
    - **quality**: the nn/quantize.py accuracy gate (teacher-forced
      greedy match rate ≥99.5%, eval-metric delta <0.5% vs fp32 on the
      fixed seeded workload) — measured on a briefly-trained net, the
      regime quantization is specified for (random-init logits are
      near-ties everywhere and gate argmax flips meaninglessly);
    - **determinism**: zero steady-state XLA compiles on the warmed
      quantized ladders, zero leaked blocks after drain, and a chaos
      phase where a weights-quantized lane cohabits the fp32 lane on
      ONE shared pool (same KV spec — fp32 cache, int8 weights)
      through a quality-gated registry deploy and kill-mid-burst
      faults: killed bursts fail typed, survivors are exact, the pool
      drains back to fully free."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.nn.kvpool import PagedKVCachePool
    from deeplearning4j_tpu.nn.quantize import (accuracy_gate,
                                                make_quality_gate, quantize,
                                                quantized_param_bytes)
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving.registry import ModelRegistry

    vocab, d, layers, heads, max_len = 32, 128, 4, 4, 256
    eos, max_new, temp = 0, 160, 2.0
    bs_kv = 16
    net = gpt(vocab_size=vocab, d_model=d, n_layers=layers,
              num_heads=heads, max_len=max_len,
              compute_dtype="float32", learning_rate=0.01).init()
    # sharpen the logits with a short deterministic fit (the gate's
    # specified regime — post-TRAINING quantization): a simple modular
    # next-token structure, fixed seed
    rng_t = np.random.default_rng(7)
    T = 32

    def train_batch(n):
        starts = rng_t.integers(0, vocab, n)
        seq = (starts[:, None] + np.arange(T + 1)[None, :] * 3) % vocab
        x = seq[:, :T].astype(np.float32)
        y = np.zeros((n, T, vocab), np.float32)
        y[np.arange(n)[:, None], np.arange(T)[None, :], seq[:, 1:]] = 1.0
        return DataSet(x, y)

    for _ in range(30):
        net.fit(train_batch(16))
    qnet = quantize(net, "int8")
    gate = accuracy_gate(net, qnet, rows=8, length=24, seed=0)
    gate_fp8 = accuracy_gate(net, quantize(net, "fp8"), rows=8,
                             length=24, seed=0)

    # ONE fixed KV byte budget for every arm: sized so the fp32 pool is
    # the admission ceiling (the production shape — pool exhaustion is
    # what sheds/preempts), while the int8 pool fits ~3.6x the blocks
    hd = d // heads
    fp32_blocks = 17
    budget = fp32_blocks * PagedKVCachePool.bytes_per_block(
        layers, bs_kv, heads, hd, np.float32)

    rng = np.random.default_rng(0)
    n_req = 64
    arrivals = np.cumsum(rng.exponential(0.0035, n_req))
    plens = rng.choice([6, 14, 30], n_req)
    prompts = [rng.integers(1, vocab, (1, int(t))) for t in plens]
    reg = monitor.get_registry()

    def useful(row, t_in):
        gen = row[t_in:]
        idx = np.where(gen == eos)[0]
        return int(idx[0]) + 1 if len(idx) else len(gen)

    def drive(engine, scheduler):
        done_t = {}

        def cb(i):
            return lambda f: done_t.__setitem__(i, time.perf_counter())

        t0 = time.perf_counter()
        subs, futs = [], []
        row_samples = []
        for i in range(n_req):
            target = t0 + arrivals[i]
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            subs.append(time.perf_counter())
            f = engine.submit_generate(prompts[i], max_new,
                                       temperature=temp, eos_token=eos,
                                       seed=i)
            f.add_done_callback(cb(i))
            futs.append(f)
            row_samples.append(scheduler.stats()["active_sequences"])
        while len(done_t) < n_req:
            row_samples.append(scheduler.stats()["active_sequences"])
            time.sleep(5e-3)
        tokens = [useful(f.result(0)[0], int(plens[i]))
                  for i, f in enumerate(futs)]
        t_end = max(done_t.values())
        ttfts = sorted((c["t_first"] - c["t_submit"]) * 1e3
                       for c in scheduler.completed)
        q = lambda xs, p: xs[min(len(xs) - 1, int(len(xs) * p))]
        return {
            "tokens": int(np.sum(tokens)),
            "tokens_per_sec": float(np.sum(tokens)) / (t_end - t0),
            "ttft_p50_ms": q(ttfts, 0.5), "ttft_p99_ms": q(ttfts, 0.99),
            # sustained concurrency: mean active rows across the whole
            # drive (every 5ms poll) — the pool-admission ceiling as
            # the workload actually experienced it; peak is the
            # transient high-water mark
            "mean_rows": float(np.mean(row_samples)),
            "peak_rows": int(np.max(row_samples)),
            "preemptions": int(scheduler.stats()["preemptions"]),
        }

    warm_lens = [6, 14, 30]
    arms = {}
    jit_misses = {}
    leaked = {}
    for arm, (model, kv_quant) in (
            ("fp32", (net, None)),
            ("int8_weights", (qnet, None)),
            ("int8_weights_int8_kv", (qnet, "int8"))):
        eng = ParallelInference(model, replicas=1, continuous=True,
                                decode_slots=24, decode_burst=8,
                                kv_block_size=bs_kv, kv_quant=kv_quant,
                                kv_bytes_budget=budget)
        eng.warmup_generate(warm_lens, max_new)
        miss0 = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        sched = eng._continuous_scheduler()
        arms[arm] = drive(eng, sched)
        arms[arm]["kv_blocks"] = int(sched.stats()["pool"]["blocks_total"])
        jit_misses[arm] = float(
            reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) - miss0)
        eng.drain(60)
        pool = sched.stats()["pool"]
        leaked[arm] = int(pool["blocks_total"] - pool["blocks_free"])
        eng.shutdown()

    # --- chaos phase: quantized lane cohabiting the fp32 lane on ONE
    # shared pool. int8 WEIGHTS + fp32 KV shares the fp32 net's pool
    # spec, so stable (fp32) and the quality-gated quantized deploy
    # recycle one block budget; kill-mid-burst faults hit whichever
    # lane is dispatching — typed failures, exact survivors, clean pool
    from deeplearning4j_tpu.faultinject import BurstKill
    from deeplearning4j_tpu.serving.continuous import DecodeBurstError
    registry = ModelRegistry()
    registry.register("m", net=net, warm_shapes=[(8,)])
    bk = BurstKill(after=6, failures=2)
    ceng = ParallelInference(registry=registry, continuous=True,
                             decode_slots=8, decode_burst=8,
                             kv_block_size=bs_kv, kv_blocks=fp32_blocks,
                             decode_burst_hook=bk)
    v2 = registry.deploy("m", net=qnet,
                         quality_gate=make_quality_gate(seed=0))
    ceng.warmup_generate(warm_lens, 24, model="m", version=1)
    ceng.warmup_generate(warm_lens, 24, model="m", version=v2)
    csched = ceng._continuous_scheduler()
    futs = []
    for i in range(16):
        ver = 1 if i % 2 == 0 else v2
        futs.append((ver, i, ceng.submit_generate(
            prompts[i], 12, temperature=0.0, eos_token=None, seed=i,
            model="m", version=ver)))
    ceng.drain(120)
    killed = exact = 0
    for ver, i, f in futs:
        try:
            out = f.result(0)
        except DecodeBurstError:
            killed += 1
            continue
        ref = generate_eager(net if ver == 1 else qnet, prompts[i], 12,
                             seed=i)
        exact += int(np.array_equal(out, ref))
    cpool = csched.stats()["pool"]
    chaos = {
        "lanes": int(csched.stats()["lanes"]),
        "shared_pools": len(csched.stats()["pools"]),
        "killed_typed": killed,
        "survivors_exact": exact,
        "survivors": len(futs) - killed,
        "leaked_blocks": int(cpool["blocks_total"] - cpool["blocks_free"]),
        "quality_gated_deploy_version": int(v2),
    }
    ceng.shutdown()

    base, q8, qkv = (arms["fp32"], arms["int8_weights"],
                     arms["int8_weights_int8_kv"])
    rows_ratio = qkv["mean_rows"] / max(1e-9, base["mean_rows"])
    tps_ratio = qkv["tokens_per_sec"] / max(1e-9, base["tokens_per_sec"])
    return {
        "metric": "quantized_serving_concurrent_rows_vs_fp32",
        "value": round(rows_ratio, 3), "unit": "x",
        # acceptance composite: >=1.5x tokens/sec OR >=2x concurrent
        # rows at the fixed KV byte budget — rows is the pool-ceiling
        # claim and holds on any backend; report both ratios
        "vs_baseline": round(max(rows_ratio, tps_ratio), 3),
        "tokens_per_sec_ratio": round(tps_ratio, 3),
        "kv_bytes_budget": int(budget),
        "weight_bytes_fp32": quantized_param_bytes(net.params),
        "weight_bytes_int8": quantized_param_bytes(qnet.params),
        "arms": {k: {kk: (round(vv, 3) if isinstance(vv, float) else vv)
                     for kk, vv in v.items()} for k, v in arms.items()},
        "steady_state_jit_misses": jit_misses,
        "leaked_blocks": leaked,
        "accuracy_gate": gate,
        "accuracy_gate_fp8": {k: gate_fp8[k] for k in
                              ("passed", "greedy_match_rate",
                               "eval_metric_delta")},
        "chaos_cohabit": chaos,
        "requests": n_req,
        "max_new_cap": max_new,
    }


def bench_prefix_cache():
    """Cross-request prefix cache on the shared-system-prompt workload
    (ISSUE 11 acceptance): N users × ONE shared preamble × distinct
    short tails, open-loop arrivals, served cached vs uncached on the
    SAME seeded trace. The cached engine indexes retired sequences'
    KV blocks (serving/prefixcache.py) so every post-prime admission
    clones the preamble's block table and prefills only its tail.

    Reported: TTFT p50/p99 for both runs (the ≥3x bar is p50),
    prefill-token and estimated prefill-FLOP reduction, hit rate,
    bitwise token identity cached-vs-uncached (and vs the
    generate_eager oracle), zero steady-state jit misses, and
    chaos-drill-clean block accounting (zero leaked after the caches
    release, zero double-freed — the pool raises on double free)."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    vocab, d, layers, heads, max_len = 32, 128, 4, 4, 256
    preamble_len, max_new, n_req = 160, 16, 32
    tail_choices = [5, 9, 13]
    net = gpt(vocab_size=vocab, d_model=d, n_layers=layers,
              num_heads=heads, max_len=max_len,
              compute_dtype="float32", learning_rate=0.01).init()
    rng = np.random.default_rng(0)
    preamble = rng.integers(1, vocab, (1, preamble_len))
    prompts = [np.concatenate(
        [preamble, rng.integers(1, vocab, (1, int(t)))], axis=1)
        for t in rng.choice(tail_choices, n_req)]
    arrivals = np.cumsum(rng.exponential(0.012, n_req))
    plens = sorted({p.shape[1] for p in prompts})
    reg = monitor.get_registry()

    def run(prefix_cache):
        eng = ParallelInference(net, replicas=1, continuous=True,
                                decode_slots=8, decode_burst=8,
                                kv_block_size=16,
                                prefix_cache=prefix_cache)
        eng.warmup_generate(plens, max_new,
                            tail_lengths=tail_choices + [max(tail_choices)])
        # prime: request 0 retires BEFORE the open-loop load (both runs
        # pay it identically) — insert-on-retire seeds the cache, the
        # steady-state shape of a server that has been up for hours
        eng.generate(prompts[0], max_new, timeout=300)
        sched = eng._continuous_scheduler()
        done0 = len(sched.completed)
        pre0 = sched.stats()["prefill_tokens_computed"]
        miss0 = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        t0 = time.perf_counter()
        futs = []
        for i in range(1, n_req):
            target = t0 + arrivals[i]
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            futs.append(eng.submit_generate(prompts[i], max_new, seed=i))
        outs = [np.asarray(f.result(300)) for f in futs]
        t_end = time.perf_counter()
        misses = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) - miss0
        comp = list(sched.completed)[done0:]
        ttfts = sorted((c["t_first"] - c["t_submit"]) * 1e3 for c in comp)
        st = sched.stats()
        eng.drain(120)
        pool = sched.stats()["pool"]
        cached = sum(c.cached_blocks() for c in sched.prefix_caches())
        # conservation while the cache holds its pins, then full-free
        # once it releases them; a double free raises out of clear()
        leaked_held = int(pool["blocks_total"] - pool["blocks_free"]) \
            - cached
        double_freed = 0
        try:
            for c in sched.prefix_caches():
                c.clear()
        except RuntimeError:
            double_freed = 1
        pool = sched.stats()["pool"]
        leaked = int(pool["blocks_total"] - pool["blocks_free"])
        pc = st.get("prefix_cache") or {}
        eng.shutdown()
        q = lambda xs, p: xs[min(len(xs) - 1, int(len(xs) * p))]
        return {
            "outs": outs,
            "ttft_p50_ms": q(ttfts, 0.5), "ttft_p99_ms": q(ttfts, 0.99),
            "wall_s": t_end - t0,
            "prefill_tokens_computed": st["prefill_tokens_computed"] - pre0,
            "hit_rate": pc.get("hit_rate", 0.0),
            "saved_prefill_tokens": pc.get("saved_prefill_tokens", 0),
            "cow_copies": pc.get("cow_copies", 0),
            "jit_misses": float(misses),
            "leaked": leaked + leaked_held,
            "double_freed": double_freed,
        }

    base = run(False)
    cached = run(True)
    identical = all(np.array_equal(a, b)
                    for a, b in zip(base["outs"], cached["outs"]))
    eager_ok = np.array_equal(
        cached["outs"][0], generate_eager(net, prompts[1], max_new, seed=1))
    ratio = base["ttft_p50_ms"] / max(1e-9, cached["ttft_p50_ms"])
    token_red = 1.0 - (cached["prefill_tokens_computed"]
                       / max(1, base["prefill_tokens_computed"]))

    def prefill_flops(computed, total_ctx):
        # per layer: 12*d^2 linear MACs/token + qk^T/av context reads
        return 2.0 * layers * (12 * d * d * computed
                               + 2 * computed * total_ctx * d)

    ctx = float(np.mean(plens))
    flop_red = 1.0 - (prefill_flops(cached["prefill_tokens_computed"], ctx)
                      / max(1e-9,
                            prefill_flops(base["prefill_tokens_computed"],
                                          ctx)))
    clean = (identical and eager_ok and cached["jit_misses"] == 0
             and cached["leaked"] == 0 and base["leaked"] == 0
             and cached["double_freed"] == 0)
    return {
        "metric": "prefix_cache_ttft_p50_speedup",
        "value": round(ratio, 3), "unit": "x",
        # acceptance composite: >= 3x TTFT p50 with bitwise-identical
        # tokens, zero steady-state compiles, clean block accounting
        "vs_baseline": round(ratio, 3) if clean else 0.0,
        "ttft_p50_ms": round(cached["ttft_p50_ms"], 2),
        "ttft_p99_ms": round(cached["ttft_p99_ms"], 2),
        "uncached_ttft_p50_ms": round(base["ttft_p50_ms"], 2),
        "uncached_ttft_p99_ms": round(base["ttft_p99_ms"], 2),
        "ttft_p99_improvement": round(
            base["ttft_p99_ms"] / max(1e-9, cached["ttft_p99_ms"]), 3),
        "hit_rate": round(cached["hit_rate"], 4),
        "saved_prefill_tokens": int(cached["saved_prefill_tokens"]),
        "prefill_tokens_computed": int(cached["prefill_tokens_computed"]),
        "uncached_prefill_tokens": int(base["prefill_tokens_computed"]),
        "prefill_token_reduction": round(token_red, 4),
        "prefill_flop_reduction": round(flop_red, 4),
        "cow_copies": int(cached["cow_copies"]),
        "tokens_identical": bool(identical),
        "eager_identity": bool(eager_ok),
        "steady_state_jit_misses": cached["jit_misses"],
        "leaked_blocks": int(cached["leaked"] + base["leaked"]),
        "double_freed_blocks": int(cached["double_freed"]),
        "requests": n_req,
        "preamble_tokens": preamble_len,
    }


def bench_durable_decode():
    """Durable decode streams under open-loop Poisson load with an
    engine KILLED mid-run (ISSUE 10 acceptance): 3 continuous-decode
    endpoints serve token-streaming sessions through the router; one
    endpoint dies while its streams are mid-generation and every
    affected stream MIGRATES — re-pinned, resumed from the journaled
    prefix on a survivor — instead of failing or restarting.

    Reported: completion rate (the bar is 100%), the resume cost
    (prefix tokens re-prefilled instead of re-generated, migration
    count), migration latency p50/p99 (the longest token-gap a
    migrated stream observed — silence between the last pre-kill chunk
    and the first post-resume chunk), p99 inter-chunk token-gap for
    UNAFFECTED streams as the healthy baseline, zero duplicate/missing
    offsets across every stream seam, and zero leaked KV blocks after
    drain.

    ISSUE-11 satellite: the SAME drill runs twice — prefix cache OFF
    (the headline numbers, PR-10 comparable) and ON. Streams share one
    system preamble (each engine primes it at startup), so a migrated
    stream's resume re-prefill degrades to a table clone of the cached
    preamble plus its journaled suffix: ``resume_reprefill_tokens``
    (the prompt+prefix tokens the survivor actually COMPUTED) shrinks,
    pushing the migration token-gap toward the silence timeout alone."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.faultinject import kill_endpoint
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import InferenceRouter, LocalFleet

    vocab, d, layers, heads, max_len = 32, 64, 2, 4, 192
    max_new, n_req, preamble_len = 80, 24, 96
    tail_choices = [4, 12]
    net = gpt(vocab_size=vocab, d_model=d, n_layers=layers,
              num_heads=heads, max_len=max_len,
              compute_dtype="float32", learning_rate=0.01).init()
    rng = np.random.default_rng(0)
    # arrivals faster than per-endpoint service so streams overlap —
    # the kill must land on streams that are genuinely mid-generation.
    # Every stream shares ONE system preamble + a distinct tail (the
    # workload shape that makes a prefix cache matter); the load is
    # sized to the fleet's slot budget so the migration gap measures
    # detection + re-prefill, not unbounded queue wait.
    arrivals = np.cumsum(rng.exponential(0.025, n_req))
    preamble = rng.integers(1, vocab, (1, preamble_len))
    prompts = [np.concatenate(
        [preamble, rng.integers(1, vocab, (1, int(t)))], axis=1)
        for t in rng.choice(tail_choices, n_req)]
    warm_lens = sorted({p.shape[1] for p in prompts})
    reg = monitor.get_registry()

    class Coll:
        """Chunk audit + arrival clock per stream."""

        def __init__(self):
            self.tokens = []
            self.at = []          # arrival time per chunk
            self.dups = self.gaps = 0

        def __call__(self, off, toks):
            self.at.append(time.perf_counter())
            for i, t in enumerate(np.asarray(toks).reshape(-1).tolist()):
                idx = int(off) + i
                if idx < len(self.tokens):
                    self.dups += 1
                elif idx == len(self.tokens):
                    self.tokens.append(int(t))
                else:
                    self.gaps += 1

        def max_gap_ms(self):
            if len(self.at) < 2:
                return 0.0
            return max((b - a) for a, b in zip(self.at, self.at[1:])) * 1e3

    def run_once(prefix_cache):
        # ISSUE 13: the whole run is request-traced — each stream's
        # merged cross-process trace (router admission → wire →
        # worker → scheduler) is validated parent-complete by the
        # extended schema checker, and migrated streams additionally
        # prove their token-gap fully attributed (silence_wait /
        # repin / resume re-prefill / first resumed burst)
        import scripts.check_telemetry_schema as schema
        from deeplearning4j_tpu.monitor import reqtrace
        tracer = reqtrace.enable_request_tracing(completed_capacity=4096)
        engines = []

        def engine_factory():
            eng = ParallelInference(net, replicas=1, continuous=True,
                                    decode_slots=8, decode_burst=8,
                                    kv_block_size=16,
                                    prefix_cache=prefix_cache)
            eng.warmup_generate(warm_lens, max_new,
                                tail_lengths=tail_choices)
            if prefix_cache:
                # prime the shared preamble: one retired request seeds
                # the cache on every endpoint (incl. the post-kill
                # restart) — the steady-state shape of a long-lived
                # fleet serving one system prompt
                eng.generate(preamble, 1, timeout=120)
            engines.append(eng)
            return eng

        mig0 = reg.family_total(monitor.SESSION_MIGRATIONS_COUNTER)
        rp0 = reg.family_total(monitor.ROUTER_RESUME_PREFIX_COUNTER)
        # the shared-preamble prompts serve slower than PR 10's short
        # ones at the same concurrency: the silence budget must cover
        # an honest admission-queue wait, or healthy-but-queued streams
        # migrate in a cascade (a dead endpoint is still caught fast —
        # by heartbeat loss, not the per-chunk silence timer)
        router = InferenceRouter(per_try_timeout_s=5.0,
                                 eject_backoff_s=0.2, max_attempts=5)
        fleet = LocalFleet(engine_factory, router=router,
                           heartbeat_s=0.05, request_timeout_s=5.0,
                           heartbeat_timeout_s=0.3)
        for _ in range(3):
            fleet.add_endpoint()
        fleet.wait_ready(60)

        # kill once streams are genuinely mid-generation with
        # journaled chunks (an empty journal migrates as a restart)
        kill_at = n_req // 3
        victim = None
        victim_sessions = set()
        colls, futs = [], []
        t0 = time.perf_counter()
        for i in range(n_req):
            if i == kill_at:
                # kill the endpoint holding the most LIVE pinned streams
                pins = [(j, router.session_pin(f"s{j}")) for j in range(i)
                        if not futs[j].done()]
                owners = [p[0] for _, p in pins if p is not None]
                victim = max(set(owners), key=owners.count) if owners \
                    else fleet.names()[0]
                victim_sessions = {f"s{j}" for j, p in pins
                                   if p is not None and p[0] == victim}
                kill_endpoint(fleet, victim)
            target = t0 + arrivals[i]
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            c = Coll()
            colls.append(c)
            futs.append(router.submit_generate(prompts[i], max_new,
                                               session=f"s{i}",
                                               on_tokens=c))
        completed = 0
        for f in futs:
            try:
                f.result(timeout=120)
                completed += 1
            except BaseException:
                pass
        t_end = time.perf_counter()

        # ---- per-stream merged traces: ONE trace per stream, span
        # tree parent-complete; migrated-with-prefix streams get the
        # full gap-coverage audit (the ISSUE-13 acceptance bar)
        trace_violations = []
        migrated_validated = 0
        phase_ms = {}
        for i, f in enumerate(futs):
            tid = getattr(f, "trace_id", None)
            entry = tracer.completed_trace(tid) if tid else None
            if entry is None:
                trace_violations.append(f"s{i}: no completed trace")
                continue
            spans = entry["spans"]
            trace_violations.extend(
                schema.validate_trace_spans(spans, f"s{i}"))
            if any(s["name"] == "dispatch"
                   and (s.get("attrs") or {}).get("resume_prefix")
                   for s in spans):
                migrated_validated += 1
                trace_violations.extend(
                    schema.validate_migration_coverage(spans, f"s{i}"))
            for s in spans:
                phase_ms.setdefault(s["name"], []).append(
                    s["dur_us"] / 1e3)
        ttft_phases = {
            k: {"count": len(v),
                "p50_ms": round(float(np.median(v)), 3),
                "p99_ms": round(float(np.percentile(v, 99)), 3)}
            for k, v in sorted(phase_ms.items())}
        reqtrace.disable_request_tracing()

        migrations = int(reg.family_total(
            monitor.SESSION_MIGRATIONS_COUNTER) - mig0)
        resume_prefix = int(reg.family_total(
            monitor.ROUTER_RESUME_PREFIX_COUNTER) - rp0)
        dup = sum(c.dups for c in colls)
        gap = sum(c.gaps for c in colls)
        short = sum(1 for c in colls if len(c.tokens) != max_new)

        # token-gap tails: migrated (victim-pinned at kill) vs not
        mig_gaps = sorted(c.max_gap_ms() for i, c in enumerate(colls)
                          if f"s{i}" in victim_sessions)
        ok_gaps = sorted(c.max_gap_ms() for i, c in enumerate(colls)
                         if f"s{i}" not in victim_sessions and c.at)

        # drain every surviving engine; pools must return to fully
        # free once the prefix caches release their pins
        leaked = 0
        resume_reprefill = 0
        fleet.restart(victim)
        router.probe_now()
        for eng in engines:
            if not eng._closed:
                eng.drain(60)
            sched = eng._scheduler
            if sched is None:
                continue
            resume_reprefill += sched.stats()["resume_reprefill_tokens"]
            for c in sched.prefix_caches():
                c.clear()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                pool = sched.stats()["pool"]
                if pool["blocks_free"] >= pool["blocks_total"]:
                    break
                time.sleep(0.02)
            pool = sched.stats()["pool"]
            leaked += int(pool["blocks_total"] - pool["blocks_free"])
        snap = router.fleet_snapshot()
        fleet.shutdown(drain=False)
        router.close()
        q = lambda xs, p: (None if not xs else round(
            xs[min(len(xs) - 1, int(len(xs) * p))], 2))
        tokens = sum(len(c.tokens) for c in colls)
        return {
            "completed": completed, "short": short, "dup": dup,
            "gap": gap, "tokens": tokens, "wall_s": t_end - t0,
            "victim": victim, "victim_sessions": len(victim_sessions),
            "migrations": migrations,
            "resume_prefix_tokens": resume_prefix,
            "resume_reprefill_tokens": int(resume_reprefill),
            "mig_gap_p50": q(mig_gaps, 0.5), "mig_gap_p99": q(mig_gaps, 0.99),
            "ok_gap_p99": q(ok_gaps, 0.99),
            "leaked": leaked,
            "healthy_after": snap["healthy_endpoints"],
            "trace_violations": trace_violations,
            "migrated_traces_validated": migrated_validated,
            "ttft_phases": ttft_phases,
        }

    base = run_once(False)         # headline: PR-10-comparable numbers
    warm = run_once(True)          # satellite: warm-cache migration
    all_complete = (base["completed"] == n_req and base["short"] == 0
                    and base["dup"] == 0 and base["gap"] == 0)
    warm_complete = (warm["completed"] == n_req and warm["short"] == 0
                     and warm["dup"] == 0 and warm["gap"] == 0)
    traces_ok = (not base["trace_violations"]
                 and not warm["trace_violations"])
    return {
        "metric": "durable_decode_stream_completion",
        "value": round(base["completed"] / n_req, 4), "unit": "fraction",
        # acceptance composite: 100% of streams complete exactly,
        # append-only, despite the mid-run kill — BOTH runs, the warm
        # cache re-prefills fewer tokens than the cold resume, and
        # (ISSUE 13) every stream's merged trace is parent-complete
        # with migrated streams' token-gap fully span-attributed
        "vs_baseline": 1.0 if (all_complete and warm_complete
                               and base["leaked"] == 0
                               and warm["leaked"] == 0
                               and traces_ok) else 0.0,
        "streams": n_req,
        "streams_completed": base["completed"],
        "streams_short": base["short"],
        "tokens_streamed": base["tokens"],
        "tokens_per_sec": round(base["tokens"] / base["wall_s"], 1),
        "killed_endpoint": base["victim"],
        "streams_pinned_to_victim": base["victim_sessions"],
        "migrations": base["migrations"],
        "resume_prefix_tokens": base["resume_prefix_tokens"],
        "resume_reprefill_tokens": base["resume_reprefill_tokens"],
        "migration_gap_p50_ms": base["mig_gap_p50"],
        "migration_gap_p99_ms": base["mig_gap_p99"],
        "healthy_gap_p99_ms": base["ok_gap_p99"],
        "dup_offsets": base["dup"],
        "gap_events": base["gap"],
        "leaked_blocks": base["leaked"] + warm["leaked"],
        "healthy_endpoints_after": base["healthy_after"],
        # ISSUE 13: end-to-end trace audit + TTFT decomposition from
        # the merged per-stream traces (schema-checker validated)
        "trace_parent_complete": traces_ok,
        "trace_violations": (base["trace_violations"]
                             + warm["trace_violations"])[:8],
        "migrated_traces_validated": base["migrated_traces_validated"],
        "ttft_phase_ms": base["ttft_phases"],
        # warm-cache migration (prefix cache ON, same trace): the
        # resume re-prefills the cached preamble as a table clone
        "warm_cache": {
            "streams_completed": warm["completed"],
            "migrations": warm["migrations"],
            "resume_prefix_tokens": warm["resume_prefix_tokens"],
            "resume_reprefill_tokens": warm["resume_reprefill_tokens"],
            "migration_gap_p50_ms": warm["mig_gap_p50"],
            "migration_gap_p99_ms": warm["mig_gap_p99"],
            "healthy_gap_p99_ms": warm["ok_gap_p99"],
            "dup_offsets": warm["dup"], "gap_events": warm["gap"],
        },
        # the satellite's headline: tokens a migrated stream's resume
        # actually re-prefilled, per migration — the warm cache clones
        # the cached preamble instead of recomputing it
        "reprefill_per_migration": (
            None if not base["migrations"] else round(
                base["resume_reprefill_tokens"] / base["migrations"], 1)),
        "warm_reprefill_per_migration": (
            None if not warm["migrations"] else round(
                warm["resume_reprefill_tokens"] / warm["migrations"], 1)),
        "reprefill_reduction": (
            None if not (base["migrations"]
                         and base["resume_reprefill_tokens"]
                         and warm["migrations"]) else round(
                1.0 - (warm["resume_reprefill_tokens"] / warm["migrations"])
                / (base["resume_reprefill_tokens"] / base["migrations"]),
                4)),
    }


def bench_kv_tiering():
    """KV tiering + durable session hibernation (ISSUE 19 acceptance):
    a device pool sized for only a handful of LIVE sessions carries a
    whole fleet of idle conversations by demoting their KV to host RAM
    at end-of-turn (``hibernate=True``) and swapping it back on
    resume.

    Reported: resident sessions per device byte vs the device-only
    ceiling (the >=4x bar), resume TTFT p50 via swap-in vs the
    re-prefill resume on an identical tier-less engine plus the
    measured per-block H2D cost (the swap-vs-recompute crossover
    decomposition), an active stream's inter-token p99 while the full
    hibernate/resume churn runs beside it vs the same churn served by
    re-prefill (the <=1.2x bar), bitwise token identity of EVERY
    resumed turn vs the uninterrupted ``generate_eager`` oracle, zero
    steady-state jit misses, and a zero-leak drain of BOTH tiers."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    vocab, d, layers, heads, max_len = 32, 64, 2, 4, 160
    block, prompt_len, turn1, turn2 = 16, 48, 24, 16
    n_sessions, act_new = 12, 40
    total = turn1 + turn2
    # session KV footprint at end of turn 1; the device pool holds ~3
    # such sessions (plus slack for the active stream), the host tier
    # holds the whole roster — the capacity amplification under test
    sess_blocks = -(-(prompt_len + turn1) // block)
    kv_blocks = 1 + 3 * sess_blocks + 3
    cap_dev = (kv_blocks - 1) // sess_blocks
    net = gpt(vocab_size=vocab, d_model=d, n_layers=layers,
              num_heads=heads, max_len=max_len,
              compute_dtype="float32", learning_rate=0.01).init()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, vocab, (1, prompt_len))
               for _ in range(n_sessions)]
    oracles = [np.asarray(generate_eager(net, p, total, seed=i,
                                         temperature=0.8, top_k=5))
               for i, p in enumerate(prompts)]
    act_prompt = rng.integers(1, vocab, (1, prompt_len))
    reg = monitor.get_registry()

    class Gaps:
        """Inter-chunk arrival clock for the active stream."""

        def __init__(self):
            self.at = []

        def __call__(self, off, toks):
            self.at.append(time.perf_counter())

        def p99_ms(self):
            if len(self.at) < 2:
                return 0.0
            gaps = sorted(b - a for a, b in zip(self.at, self.at[1:]))
            return gaps[min(len(gaps) - 1, int(len(gaps) * 0.99))] * 1e3

    def run(tiered):
        eng = ParallelInference(net, replicas=1, continuous=True,
                                decode_slots=4, decode_burst=8,
                                kv_block_size=block, kv_blocks=kv_blocks,
                                kv_host_blocks=(n_sessions * sess_blocks + 8
                                                if tiered else None))
        sched = eng._continuous_scheduler()
        try:
            # warm every program shape once: turn-1, resume, active
            wp = rng.integers(1, vocab, (1, prompt_len))
            w1 = np.asarray(eng.submit_generate(
                wp, turn1, seed=97, temperature=0.8, top_k=5,
                session="warm", hibernate=tiered).result(600))
            eng.submit_generate(
                wp, total, seed=97, temperature=0.8, top_k=5,
                session="warm", prefix=w1[0, prompt_len:]).result(600)
            eng.submit_generate(act_prompt, act_new, seed=99).result(600)
            eng.drain(120)
            miss0 = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)

            # turn 1: every session generates, then parks. On the
            # tiered engine the KV demotes to host RAM and the session
            # stays resumable; the tier-less engine can only journal.
            for i, p in enumerate(prompts):
                out = np.asarray(eng.submit_generate(
                    p, turn1, seed=i, temperature=0.8, top_k=5,
                    session=f"s{i}", hibernate=tiered).result(600))
                np.testing.assert_array_equal(
                    out, oracles[i][:, :prompt_len + turn1])
            resident = eng.hibernated_count() if tiered else 0
            host_peak = sched.stats()["kvtier"]["host_blocks_used"]

            # resume churn beside one active stream: the stream's
            # inter-token p99 is the interference bar
            gaps = Gaps()
            act = eng.submit_generate(act_prompt, act_new, seed=99,
                                      on_tokens=gaps)
            mism = 0
            ttfts = []
            for i, p in enumerate(prompts):
                first = []
                t0 = time.perf_counter()
                got = np.asarray(eng.submit_generate(
                    p, total, seed=i, temperature=0.8, top_k=5,
                    session=f"s{i}",
                    prefix=oracles[i][0, prompt_len:prompt_len + turn1],
                    on_tokens=lambda off, toks: first.append(
                        time.perf_counter()) if not first else None,
                ).result(600))
                if not np.array_equal(got, oracles[i]):
                    mism += 1
                ttfts.append(((first[0] if first else time.perf_counter())
                              - t0) * 1e3)
            act.result(600)
            ttfts.sort()
            misses = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) \
                - miss0
            eng.drain(120)
            if tiered:
                eng.hibernate_release("warm")
            st = sched.stats()
            q = lambda xs, p: xs[min(len(xs) - 1, int(len(xs) * p))]
            return {
                "resident": resident,
                "host_peak": int(host_peak),
                "restores": st["kvtier"]["swap_restores"],
                "ttft_p50_ms": q(ttfts, 0.5),
                "ttft_p99_ms": q(ttfts, 0.99),
                "gap_p99_ms": gaps.p99_ms(),
                "mismatches": mism,
                "jit_misses": float(misses),
                "leaked": int(st["pool"]["blocks_total"]
                              - st["pool"]["blocks_free"]),
                "leaked_host": int(st["kvtier"]["host_blocks_used"]),
                "swap_in_ms_per_block": max(
                    [(p.swap_in_cost_ms() or 0.0)
                     for p in sched._pools.values()] or [0.0]),
            }
        finally:
            eng.shutdown()

    base = run(False)
    tier = run(True)
    # capacity amplification: sessions the SAME device pool keeps
    # resumable-without-recompute (device-only ceiling vs host roster)
    ratio = tier["resident"] / max(1, cap_dev)
    gap_ratio = tier["gap_p99_ms"] / max(1e-9, base["gap_p99_ms"])
    clean = (tier["mismatches"] == 0 and base["mismatches"] == 0
             and tier["resident"] == n_sessions
             and tier["restores"] >= n_sessions
             and tier["leaked"] == 0 and tier["leaked_host"] == 0
             and base["leaked"] == 0 and tier["jit_misses"] == 0)
    return {
        "metric": "kvtier_sessions_per_device_byte",
        "value": round(ratio, 3), "unit": "x",
        # acceptance composite: >=4x resident sessions per device byte
        # with every resume bitwise, zero steady-state compiles, both
        # tiers drained leak-free
        "vs_baseline": round(ratio, 3) if clean else 0.0,
        "device_session_capacity": cap_dev,
        "resident_sessions": tier["resident"],
        "session_blocks": sess_blocks,
        "host_blocks_peak": tier["host_peak"],
        "swap_restores": int(tier["restores"]),
        "resume_ttft_p50_ms": round(tier["ttft_p50_ms"], 2),
        "resume_ttft_p99_ms": round(tier["ttft_p99_ms"], 2),
        "reprefill_ttft_p50_ms": round(base["ttft_p50_ms"], 2),
        "reprefill_ttft_p99_ms": round(base["ttft_p99_ms"], 2),
        "swap_in_ms_per_block": round(tier["swap_in_ms_per_block"], 3),
        "intertoken_p99_ms": round(tier["gap_p99_ms"], 2),
        "baseline_intertoken_p99_ms": round(base["gap_p99_ms"], 2),
        "intertoken_p99_ratio": round(gap_ratio, 3),
        "token_mismatches": tier["mismatches"] + base["mismatches"],
        "steady_state_jit_misses": tier["jit_misses"],
        "leaked_blocks": tier["leaked"] + base["leaked"],
        "leaked_host_blocks": tier["leaked_host"],
        "sessions": n_sessions,
    }


def bench_router_slo():
    """Horizontal serving tier under open-loop Poisson load (the SLO
    protocol: arrivals don't wait for completions, so queueing shows up
    in the tail instead of silently throttling the driver).

    A 3-endpoint LocalFleet (thread-mode engine workers behind the
    broker wire protocol) serves through an InferenceRouter in three
    phases: (a) healthy steady state; (b) one endpoint KILLED mid-load
    (the faultinject process-kill seam) — every request must still
    resolve via failover and the p99 impact is the headline; (c) a
    deadline tighter than capacity at 2x the arrival rate — the
    admission controller must shed (RetryAfter) instead of queueing
    past the SLO, and the shed rate is reported."""
    import time

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.faultinject import kill_endpoint
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import (InferenceRouter, LocalFleet,
                                            RetryAfter)

    rng = np.random.default_rng(0)
    nin, nc = 64, 8
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.05).updater("adam").activation("relu")
            .list()
            .layer(DenseLayer(n_in=nin, n_out=256))
            .layer(OutputLayer(n_in=256, n_out=nc, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()

    def engine_factory():
        eng = ParallelInference(net, max_batch_size=16, max_latency_ms=2.0,
                                replicas=1)
        eng.warmup([(nin,)])
        return eng

    router = InferenceRouter(per_try_timeout_s=2.0, eject_backoff_s=0.2,
                             max_attempts=4)
    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=2.0, heartbeat_timeout_s=0.4)
    for _ in range(3):
        fleet.add_endpoint()
    fleet.wait_ready(30)
    x = rng.standard_normal((1, nin)).astype(np.float32)

    # capacity probe → open-loop rate at ~70% of closed-loop throughput
    t0 = time.perf_counter()
    for _ in range(50):
        router.output(x, timeout=30)
    svc_s = (time.perf_counter() - t0) / 50
    rate = 0.7 / svc_s

    def run_phase(duration_s, rate, deadline_ms=None,
                  priority="interactive"):
        lats, errors = [], []
        shed = 0
        sent = 0
        done_box = []

        def on_done(f, t_sub):
            err = f.exception()
            if err is not None:
                errors.append(err)
            else:
                done_box.append(time.perf_counter() - t_sub)

        end = time.perf_counter() + duration_s
        next_t = time.perf_counter()
        while time.perf_counter() < end:
            now = time.perf_counter()
            if now < next_t:
                time.sleep(min(next_t - now, 2e-3))
                continue
            next_t += rng.exponential(1.0 / rate)
            t_sub = time.perf_counter()
            try:
                fut = router.submit(x, deadline_ms=deadline_ms,
                                    priority=priority)
            except RetryAfter:
                shed += 1
                continue
            sent += 1
            fut.add_done_callback(lambda f, t=t_sub: on_done(f, t))
        # open loop ends: wait out the in-flight tail
        deadline = time.monotonic() + 60
        while len(done_box) + len(errors) < sent and \
                time.monotonic() < deadline:
            time.sleep(2e-3)
        lats = sorted(done_box)
        n = len(lats)
        return {"sent": sent, "completed": n, "errors": len(errors),
                "shed": shed,
                "requests_per_sec": round(n / duration_s, 1),
                "p50_ms": round(lats[n // 2] * 1e3, 3) if n else None,
                "p99_ms": round(lats[min(n - 1, int(n * 0.99))] * 1e3, 3)
                if n else None}

    try:
        healthy = run_phase(2.0, rate)
        victim = fleet.names()[0]
        kill_endpoint(fleet, victim)
        during_kill = run_phase(2.0, rate)
        fleet.restart(victim)
        router.probe_now()
        recovered = run_phase(1.0, rate)
        # deadline tighter than capacity at 2x the arrival rate:
        # admission admits while the latency estimate fits the
        # deadline's best_effort headroom and sheds as the backlog
        # estimate climbs — a PARTIAL shed rate, load-dependent, with
        # the admitted requests keeping a bounded tail
        tight = run_phase(1.0, rate * 2.0,
                          deadline_ms=max(1.0, svc_s * 1e3 * 8.0),
                          priority="best_effort")
        reg = monitor.get_registry()
        snap = router.fleet_snapshot()
    finally:
        fleet.shutdown(drain=False)
        router.close()

    lost = (during_kill["sent"] - during_kill["completed"]
            - during_kill["errors"])
    shed_rate = tight["shed"] / max(1, tight["shed"] + tight["sent"])
    return {
        "metric": "router_slo_requests_per_sec",
        "value": healthy["requests_per_sec"], "unit": "requests/sec",
        "healthy": healthy,
        "during_kill": during_kill,
        "recovered": recovered,
        "deadline_tight_2x": tight,
        "shed_rate_tight_deadline": round(shed_rate, 3),
        "during_kill_zero_lost": lost == 0
        and during_kill["errors"] == 0,
        "p99_impact_during_kill": (
            None if not (healthy["p99_ms"] and during_kill["p99_ms"])
            else round(during_kill["p99_ms"] / healthy["p99_ms"], 2)),
        "failovers": int(reg.family_total(monitor.ROUTER_FAILOVERS_COUNTER)),
        "hedges": int(reg.family_total(monitor.ROUTER_HEDGES_COUNTER)),
        "fleet": {k: snap[k] for k in ("healthy_endpoints",
                                       "total_endpoints", "shed",
                                       "failovers")},
        # the SLO story is relative: during-kill p99 over healthy p99
        "vs_baseline": (
            0.0 if not (healthy["p99_ms"] and during_kill["p99_ms"])
            else round(healthy["p99_ms"] / during_kill["p99_ms"], 3)),
    }


def bench_router_saturation():
    """The PR-18 data plane, measured at its three layers:

    (a) FRAMING — v3 (u32+JSON+npz, one frame per stream delta) vs v4
    (binary prologue + raw ``memoryview`` segments, one COALESCED frame
    per retiring burst): bytes and pack+unpack CPU per token delta, and
    MB/s through the shipped-KV tensor path;

    (b) TRANSPORT — the same token-delta workload over real TCP:
    thread-per-connection broker + per-stream legacy chunks vs the
    selectors reactor + coalesced v4 burst frames. The deltas/sec ratio
    is the headline (``vs_baseline``) — the whole point of the fleet's
    new wire;

    (c) ROUTER CORE — open-loop ramp against in-process echo endpoints
    (zero engine time, so the dispatch plane itself is the limit): the
    achieved-rps knee, submit-call admission p99 at the knee, and the
    journal-gauge walk cost with 10k registered streams."""
    import time
    from concurrent.futures import Future

    from deeplearning4j_tpu.serving import InferenceRouter
    from deeplearning4j_tpu.serving import wire
    from deeplearning4j_tpu.serving.endpoint import EngineEndpoint
    from deeplearning4j_tpu.streaming.broker import (TcpBroker,
                                                     TcpBrokerServer)

    rng = np.random.default_rng(0)
    burst = 32            # streams retiring per scheduler tick
    corrs = [f"c{i:04d}" for i in range(burst)]
    toks = [rng.integers(0, 32000, 2).astype(np.int64) for _ in corrs]

    # ---- (a) framing micro-bench: CPU + bytes per token delta
    def time_per_delta(fn, iters=400):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / (iters * burst)

    def legacy_burst():
        for c, t, off in zip(corrs, toks, range(burst)):
            hdr, body = wire.unpack_reply(wire.pack_chunk(c, off, t))
            assert wire.is_chunk(hdr)

    def v4_burst():
        evs = wire.decode_reply_events(wire.pack_chunks_v4(
            [(c, off, t) for c, t, off in zip(corrs, toks, range(burst))]))
        assert len(evs) == burst

    legacy_bytes = sum(len(wire.pack_chunk(c, 0, t))
                       for c, t in zip(corrs, toks)) / burst
    v4_bytes = len(wire.pack_chunks_v4(
        [(c, 0, t) for c, t in zip(corrs, toks)])) / burst
    legacy_us = time_per_delta(legacy_burst) * 1e6
    v4_us = time_per_delta(v4_burst) * 1e6

    kv = rng.standard_normal((2, 2, 4, 128, 64)).astype(np.float32)

    def time_kv(pack, unpack, iters=30):
        t0 = time.perf_counter()
        for _ in range(iters):
            unpack(pack("c", "kv", kv))
        return kv.nbytes * iters / (time.perf_counter() - t0) / 2**20

    kv_legacy_mbs = time_kv(wire.pack_tensor_chunk,
                            lambda p: wire.unpack_reply(p))
    kv_v4_mbs = time_kv(wire.pack_tensor_chunk_v4,
                        lambda p: wire.unpack_frame_v4(p))

    # ---- (b) transport chunk plane over real TCP
    def transport_deltas_per_sec(reactor, coalesce, n_deltas=4096):
        srv = TcpBrokerServer(reactor=reactor).start()
        try:
            host, port = srv.address
            pub = TcpBroker(host, port, max_retries=1)
            sub = TcpBroker(host, port, max_retries=1)
            frames = []
            if coalesce:
                for i in range(0, n_deltas, burst):
                    frames.append(wire.pack_chunks_v4(
                        [(corrs[j], i, toks[j]) for j in range(burst)]))
            else:
                frames = [wire.pack_chunk(corrs[i % burst], i,
                                          toks[i % burst])
                          for i in range(n_deltas)]
            got = 0
            t0 = time.perf_counter()
            for f in frames:
                pub.publish("chunks", f)
            while got < n_deltas:
                msg = sub.consume("chunks", timeout=5.0)
                if msg is None:
                    break
                for ev in wire.decode_reply_events(msg):
                    got += 1
            dt = time.perf_counter() - t0
            pub.close()
            sub.close()
            return got / dt, got
        finally:
            srv.stop()

    threaded_dps, threaded_got = transport_deltas_per_sec(
        reactor=False, coalesce=False)
    reactor_dps, reactor_got = transport_deltas_per_sec(
        reactor=True, coalesce=True)

    # ---- (c) router core: open-loop ramp on echo endpoints
    class _EchoEndpoint(EngineEndpoint):
        def __init__(self, name):
            self.name = name
            self.open = []

        def submit(self, x, timeout_s=None, model=None, version=None,
                   session=None):
            fut = Future()
            fut.set_result(x)
            return fut

        def submit_generate(self, prompt_ids, max_new_tokens,
                            timeout_s=None, model=None, version=None,
                            session=None, on_tokens=None, prefix=None,
                            **kwargs):
            fut = Future()
            if on_tokens is not None:
                on_tokens(0, np.arange(max_new_tokens, dtype=np.int64))
            full = np.concatenate(
                [np.asarray(prompt_ids, np.int64).reshape(1, -1),
                 np.arange(max_new_tokens, dtype=np.int64).reshape(1, -1)],
                axis=1)
            self.open.append((fut, full))
            return fut

        def stats(self):
            return {}

        def alive(self):
            return True

        @property
        def last_seen(self):
            return time.monotonic()

    router = InferenceRouter(per_try_timeout_s=5.0)
    eps = [_EchoEndpoint(f"echo-{i}") for i in range(4)]
    for ep in eps:
        router.add_endpoint(ep)
    x = np.zeros((1, 8), np.float32)
    try:
        for _ in range(200):                       # warm the hot path
            router.submit(x).result(5)
        knee = {"rps": 0.0, "p99_admit_us": None}
        levels = []
        rate = 2000.0
        while rate <= 128000.0:
            n = max(200, int(rate * 0.25))
            admits = []
            futs = []
            t0 = time.perf_counter()
            for _ in range(n):
                ta = time.perf_counter()
                futs.append(router.submit(x))
                admits.append(time.perf_counter() - ta)
            dt = time.perf_counter() - t0
            for f in futs:
                f.result(5)
            achieved = n / dt
            admits.sort()
            p99_us = admits[min(n - 1, int(n * 0.99))] * 1e6
            levels.append({"offered_rps": int(rate),
                           "achieved_rps": round(achieved, 0),
                           "p99_admit_us": round(p99_us, 1)})
            if achieved > knee["rps"]:
                knee = {"rps": round(achieved, 0),
                        "p99_admit_us": round(p99_us, 1)}
            if achieved < rate * 0.7:
                break                              # past the knee
            rate *= 2.0
        # journal overhead with 10k live journaled streams
        sfuts = []
        for i in range(10000):
            sfuts.append(router.submit_generate(
                np.array([[1, 2, 3]]), 4, session=f"s{i}",
                on_tokens=lambda off, t: None))
        t0 = time.perf_counter()
        router._journal_gauge()
        journal_walk_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        snap = router.fleet_snapshot()
        snapshot_ms = (time.perf_counter() - t0) * 1e3
        n_streams = len(router._streams)
        for ep in eps:
            for fut, full in ep.open:
                fut.set_result(full)
            ep.open.clear()
        for f in sfuts:
            f.result(30)
    finally:
        router.close()

    return {
        "metric": "router_saturation_chunk_plane_speedup",
        "value": round(reactor_dps / max(1e-9, threaded_dps), 2),
        "unit": "x (reactor+v4 coalesced vs threaded+legacy, deltas/sec)",
        "framing": {
            "legacy_us_per_delta": round(legacy_us, 3),
            "v4_us_per_delta": round(v4_us, 3),
            "cpu_speedup": round(legacy_us / max(1e-9, v4_us), 2),
            "legacy_bytes_per_delta": round(legacy_bytes, 1),
            "v4_bytes_per_delta": round(v4_bytes, 1),
            "kv_legacy_mb_s": round(kv_legacy_mbs, 1),
            "kv_v4_mb_s": round(kv_v4_mbs, 1),
            "kv_speedup": round(kv_v4_mbs / max(1e-9, kv_legacy_mbs), 2),
        },
        "transport": {
            "threaded_legacy_deltas_per_sec": round(threaded_dps, 0),
            "reactor_v4_deltas_per_sec": round(reactor_dps, 0),
            "threaded_delivered": threaded_got,
            "reactor_delivered": reactor_got,
        },
        "router_core": {
            "knee_rps": knee["rps"],
            "p99_admit_us_at_knee": knee["p99_admit_us"],
            "levels": levels,
            "journal_walk_ms_10k_streams": round(journal_walk_ms, 3),
            "fleet_snapshot_ms_10k_streams": round(snapshot_ms, 3),
            "journaled_streams": n_streams,
            "loop_lag_ms": snap.get("loop_lag_ms"),
        },
        "vs_baseline": round(reactor_dps / max(1e-9, threaded_dps), 2),
    }


def bench_multi_model():
    """Multi-model serving from ONE chip (serving/registry.py +
    registry-mode ParallelInference): 8 models behind one engine.

    Four phases, each pinning an acceptance criterion: (a) aggregate
    rps + per-model p99 under a concurrent cross-model mix; (b) a
    hot-swap deploy UNDER open-loop load — zero lost requests, bounded
    p99 impact, post-cutover traffic bitwise on the new version; (c) a
    corrupt-checkpoint deploy auto-rejected while the old version
    keeps serving; (d) a NaN-poisoned canary auto-rolled-back by the
    watch while the stable version keeps serving."""
    import os
    import tempfile
    import threading
    import time

    import jax
    import numpy as np

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.faultinject import corrupt_file
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import ModelRegistry
    from deeplearning4j_tpu.util.model_serializer import (
        CheckpointCorruptError, write_model)

    rng = np.random.default_rng(0)
    nin, nc, n_models = 32, 8, 8

    def make_net(seed, width):
        conf = (NeuralNetConfiguration.builder()
                .seed(seed).learning_rate(0.05).updater("adam")
                .activation("relu").list()
                .layer(DenseLayer(n_in=nin, n_out=width))
                .layer(OutputLayer(n_in=width, n_out=nc,
                                   activation="softmax",
                                   loss_function="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    names = [f"m{i}" for i in range(n_models)]
    nets = {n: make_net(i + 1, 64 + 32 * (i % 3))
            for i, n in enumerate(names)}
    registry = ModelRegistry()
    for name in names:
        registry.register(name, net=nets[name], warm_shapes=[(nin,)])
    engine = ParallelInference(registry=registry, max_batch_size=16,
                               max_latency_ms=2.0, replicas=1,
                               queue_capacity=4096)
    x = rng.standard_normal((1, nin)).astype(np.float32)
    results = {}
    try:
        t0 = time.perf_counter()
        compiled = engine.warmup([(nin,)])
        results["warmup_s"] = round(time.perf_counter() - t0, 2)
        results["warmup_programs"] = compiled

        def drive(duration_s, concurrency=8, on_submit=None):
            """Closed-loop cross-model drive; returns per-model
            latencies + error/lost accounting."""
            lats = {n: [] for n in names}
            errors = []
            stop = time.perf_counter() + duration_s

            def worker(widx):
                i = widx
                while time.perf_counter() < stop:
                    name = names[i % n_models]
                    i += 1
                    t_sub = time.perf_counter()
                    try:
                        fut = engine.submit(x, model=name)
                        fut.result(timeout=60)
                    except BaseException as e:
                        errors.append((name, type(e).__name__))
                        continue
                    lats[name].append(time.perf_counter() - t_sub)
                    if on_submit is not None:
                        on_submit()

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(concurrency)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return lats, errors

        def summarize(lats, duration_s):
            per_model = {}
            total = 0
            for name, ls in lats.items():
                total += len(ls)
                if ls:
                    s = sorted(ls)
                    per_model[name] = {
                        "requests": len(ls),
                        "p50_ms": round(s[len(s) // 2] * 1e3, 3),
                        "p99_ms": round(
                            s[min(len(s) - 1, int(len(s) * 0.99))] * 1e3, 3),
                    }
            return total / duration_s, per_model

        # (a) steady-state aggregate throughput + per-model p99
        lats, errors = drive(3.0)
        rps, per_model = summarize(lats, 3.0)
        results["aggregate_requests_per_sec"] = round(rps, 1)
        results["per_model"] = per_model
        results["steady_errors"] = len(errors)
        miss0 = monitor.get_registry().family_total(
            monitor.JIT_CACHE_MISS_COUNTER)

        # (b) hot-swap m0 under load: v2 trained to different params
        v2 = make_net(101, 64)
        y_v2 = np.asarray(v2.output(x))
        swap_done = {}

        def deploy_midway():
            time.sleep(0.8)
            t = time.perf_counter()
            registry.deploy("m0", net=v2)  # verify + warm + atomic cut
            swap_done["deploy_s"] = round(time.perf_counter() - t, 3)

        deployer = threading.Thread(target=deploy_midway)
        deployer.start()
        lats, errors = drive(2.5)
        deployer.join()
        rps_swap, per_model_swap = summarize(lats, 2.5)
        results["hot_swap"] = {
            "deploy_s": swap_done.get("deploy_s"),
            "requests_per_sec": round(rps_swap, 1),
            "lost_requests": len(errors),
            "zero_lost": len(errors) == 0,
            "m0_p99_ms_during_swap": per_model_swap.get("m0", {}).get("p99_ms"),
            "m0_p99_ms_healthy": per_model.get("m0", {}).get("p99_ms"),
            "post_swap_bitwise_v2": bool(np.array_equal(
                engine.output(x, model="m0", timeout=30), y_v2)),
            "active_version": registry.active_version("m0"),
        }

        # (c) corrupt-checkpoint deploy: rejected, old keeps serving
        with tempfile.TemporaryDirectory() as td:
            bad = os.path.join(td, "bad.zip")
            write_model(make_net(102, 64), bad)
            corrupt_file(bad, offset=-64)
            rejected = False
            try:
                registry.deploy("m1", path=bad)
            except CheckpointCorruptError:
                rejected = True
            still_serving = bool(np.array_equal(
                engine.output(x, model="m1", timeout=30),
                np.asarray(nets["m1"].output(x))))
            results["corrupt_deploy"] = {
                "rejected": rejected,
                "old_version_keeps_serving": still_serving,
                "active_version": registry.active_version("m1"),
            }

        # (d) NaN-poisoned canary: the watch rolls it back on its own
        poisoned = make_net(103, 64)
        poisoned.params["layer0"]["W"] = jax.numpy.asarray(
            np.full_like(np.asarray(poisoned.params["layer0"]["W"]),
                         np.nan))
        registry.deploy("m2", net=poisoned, canary_fraction=0.5,
                        warm=False)
        rolled_back = False
        for _ in range(32):
            engine.output(x, model="m2", timeout=30)
            if registry.entry("m2").canary is None:
                rolled_back = True
                break
        results["poisoned_canary"] = {
            "rolled_back": rolled_back,
            "stable_keeps_serving": bool(np.array_equal(
                engine.output(x, model="m2", timeout=30),
                np.asarray(nets["m2"].output(x)))),
            "active_version": registry.active_version("m2"),
        }
        results["steady_state_jit_misses"] = int(
            monitor.get_registry().family_total(
                monitor.JIT_CACHE_MISS_COUNTER) - miss0
            )  # hot-swap warms v2 off the hot path; steady mix adds 0
        stats = engine.stats()
        results["models_served"] = len(stats["models"])
    finally:
        engine.shutdown()

    return {
        "metric": "multi_model_aggregate_rps",
        "value": results["aggregate_requests_per_sec"],
        "unit": "requests/sec",
        # acceptance composite: hot-swap zero-lost + corrupt-deploy
        # rejected + canary rolled back, all while serving
        "vs_baseline": float(
            results["hot_swap"]["zero_lost"]
            and results["corrupt_deploy"]["rejected"]
            and results["corrupt_deploy"]["old_version_keeps_serving"]
            and results["poisoned_canary"]["rolled_back"]
            and results["poisoned_canary"]["stable_keeps_serving"]),
        **results,
    }


def _mesh_train_worker():
    """Worker half of ``bench_mesh_train`` — runs in a FRESH interpreter
    whose env forces an 8-device CPU mesh (the bench's main process may
    hold a 1-device/TPU backend; the mesh plane needs width). Prints ONE
    JSON line: per-layout one-step throughput, steady-state jit-miss
    counts, and the checkpoint save / restore-with-relayout latencies."""
    import os
    import tempfile

    import jax

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.monitor import JIT_CACHE_MISS_COUNTER
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import MeshPlane, make_mesh
    from deeplearning4j_tpu.parallel.tensor_parallel import (apply_shardings,
                                                             dense_tp_specs)
    from deeplearning4j_tpu.parallel.zero import apply_fsdp
    from deeplearning4j_tpu.util.sharded_checkpoint import (
        restore_checkpoint, save_checkpoint)

    assert len(jax.devices()) == 8, jax.devices()
    rng = np.random.default_rng(0)
    nin, width, nc, batch = 64, 256, 8, 512
    ds = DataSet(rng.standard_normal((batch, nin)).astype(np.float32),
                 np.eye(nc, dtype=np.float32)[rng.integers(0, nc, batch)])

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.05).updater("adam").activation("relu")
                .list()
                .layer(DenseLayer(n_in=nin, n_out=width))
                .layer(DenseLayer(n_in=width, n_out=width))
                .layer(OutputLayer(n_in=width, n_out=nc, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def setup_single(net):
        return None

    def setup_dp(net):
        # batch sharded over data, params replicated — GSPMD inserts the
        # gradient all-reduce inside the step (jit-with-shardings, no
        # hand-rolled collective)
        plane = MeshPlane.build({"data": 8})
        net.params = jax.device_put(net.params, plane.replicated())
        net.opt_state = jax.device_put(net.opt_state, plane.replicated())
        net.states = jax.device_put(net.states, plane.replicated())
        return plane

    def setup_fsdp(net):
        mesh = make_mesh({"data": 8})
        apply_fsdp(net, mesh)
        return net.mesh_plane

    def setup_tp(net):
        mesh = make_mesh({"tp": 8})
        apply_shardings(net, mesh, dense_tp_specs(
            ["layer0", "layer1"], axis="tp"))
        return net.mesh_plane

    steps = 30
    results = {}
    for name, setup in (("single", setup_single), ("dp", setup_dp),
                        ("fsdp", setup_fsdp), ("tp", setup_tp)):
        monitor.set_registry(monitor.MetricsRegistry())
        net = build()
        plane = setup(net)
        fit_ds = ds
        if plane is not None and name == "dp":
            x, y = plane.shard_batch(ds.features, ds.labels)
            fit_ds = DataSet(x, y)
        net.fit(fit_ds)  # compile
        miss0 = monitor.get_registry().counter(
            JIT_CACHE_MISS_COUNTER, "").value
        t0 = time.perf_counter()
        for _ in range(steps):
            net.fit(fit_ds)
        float(net.score())
        dt = time.perf_counter() - t0
        results[name] = {
            "examples_per_sec": round(steps * batch / dt, 1),
            "step_ms": round(dt / steps * 1e3, 3),
            "steady_state_jit_misses": int(monitor.get_registry().counter(
                JIT_CACHE_MISS_COUNTER, "").value - miss0),
        }

    # checkpoint save + restore-with-relayout latency (8 → 4 → 1): the
    # mesh-portability path an on-call actually pays during a shrink
    monitor.set_registry(monitor.MetricsRegistry())
    net = build()
    apply_fsdp(net, make_mesh({"data": 8}))
    net.fit(ds)
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "ckpt")
        t0 = time.perf_counter()
        save_checkpoint(net, ck)
        t_save = time.perf_counter() - t0
        t0 = time.perf_counter()
        restore_checkpoint(ck, mesh=make_mesh({"data": 4},
                                              devices=jax.devices()[:4]))
        t_r4 = time.perf_counter() - t0
        t0 = time.perf_counter()
        restore_checkpoint(ck, mesh=make_mesh({"data": 1},
                                              devices=jax.devices()[:1]))
        t_r1 = time.perf_counter() - t0
    results["checkpoint"] = {
        "save_ms": round(t_save * 1e3, 1),
        "restore_relayout_8to4_ms": round(t_r4 * 1e3, 1),
        "restore_relayout_8to1_ms": round(t_r1 * 1e3, 1),
        "relayouts": int(monitor.get_registry().counter(
            "dl4j_mesh_restore_relayouts_total", "").value),
    }
    print(json.dumps(results))


def bench_mesh_train():
    """Mesh-plane training benchmark (ISSUE 9): dp / fsdp / tp one-step
    throughput on the forced-8-device CPU mesh vs the single-device
    step, steady-state jit-miss counts (zero once the layout's program
    is compiled), and checkpoint save / restore-with-relayout latency
    (8 → 4 and 8 → 1 — the MeshShrink recovery path, timed).

    Runs in a subprocess with ``XLA_FLAGS`` forcing 8 CPU devices: the
    bench process itself may sit on a 1-device or TPU backend, and the
    mesh semantics under test need width. On one PHYSICAL core the
    8-way layouts cannot beat the single-device step (eight programs
    timeshare one core — ``vs_single`` is a semantics+overhead number
    there, not a scaling claim); on real chips the same harness reads
    out the scaling curve."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU plugin in the worker
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["DL4J_TPU_DISABLE_DEVICE_TRACE"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "_mesh_train_worker"],
        env=env, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(f"mesh_train worker failed:\n{proc.stderr[-3000:]}")
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    single = results["single"]["examples_per_sec"]
    for name in ("dp", "fsdp", "tp"):
        results[name]["vs_single"] = round(
            results[name]["examples_per_sec"] / max(single, 1e-9), 3)
    return {
        "metric": "mesh_train_dp_examples_per_sec",
        "value": results["dp"]["examples_per_sec"],
        "unit": "examples/sec",
        "vs_baseline": results["dp"]["vs_single"],
        **results,
    }


def _mesh_serving_worker():
    """Worker half of ``bench_mesh_serving`` — fresh interpreter, 8
    forced CPU devices. Prints ONE JSON line with the kill-a-chip and
    disaggregation phase results."""
    import os
    import tempfile
    import threading
    import time as _t

    import jax

    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import (InferenceRouter, LocalEndpoint,
                                            LocalFleet, RetryAfter)
    from deeplearning4j_tpu.util.model_serializer import (restore_model,
                                                          write_model)

    assert len(jax.devices()) == 8, jax.devices()
    vocab = 31
    lm = gpt(vocab_size=vocab, d_model=32, n_layers=2, num_heads=4,
             max_len=64, compute_dtype="float32", learning_rate=0.01,
             seed=0).init()
    td = tempfile.mkdtemp(prefix="dl4j-mesh-serving-")
    art = os.path.join(td, "lm.zip")
    write_model(lm, art)
    rng = np.random.default_rng(0)

    class Collector:
        def __init__(self):
            self.tokens = []
            self.at = []
            self.dups = 0
            self.gaps = 0

        def __call__(self, off, toks):
            now = _t.perf_counter()
            for i, t in enumerate(np.asarray(toks).reshape(-1).tolist()):
                idx = int(off) + i
                if idx < len(self.tokens):
                    self.dups += 1
                elif idx == len(self.tokens):
                    self.tokens.append(int(t))
                    self.at.append(now)
                else:
                    self.gaps += 1

    # ---- phase A: tp=4 slices, kill a chip mid-run ---------------------
    engines = []

    def slice_factory(plane):
        eng = ParallelInference(net=restore_model(art), slice_plane=plane,
                                continuous=True, decode_slots=4,
                                decode_burst=4, kv_block_size=8,
                                max_latency_ms=1.0)
        # warm the slice's program ladders BEFORE it takes traffic
        # (recovery_s therefore includes the rebuilt slice's warmup —
        # the honest restore-to-serving number)
        eng.warmup_generate([8], 12)
        engines.append(eng)
        return eng

    router = InferenceRouter(per_try_timeout_s=10.0, eject_backoff_s=0.1,
                             max_attempts=6, wedge_timeout_s=2.0)
    fleet = LocalFleet(slice_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=5.0, heartbeat_timeout_s=0.5,
                       slice_width=4, slice_devices=jax.devices())
    fleet.add_endpoint()
    fleet.add_endpoint()
    assert fleet.wait_ready(60)

    n_sessions, max_new = 24, 12
    kill_at = 8
    sessions = []
    t_kill = t_degraded = t_recovered = None
    killed_name = None
    t0 = _t.perf_counter()
    for i in range(n_sessions):
        t_in = int(rng.integers(3, 8))
        prompt = rng.integers(1, vocab, (1, t_in))
        temp = 0.6 if i % 3 == 0 else 0.0
        oracle = generate_eager(lm, prompt, max_new, temperature=temp,
                                seed=i)
        coll = Collector()
        fut = None
        for _ in range(400):
            try:
                fut = router.submit_generate(
                    prompt, max_new, temperature=temp, seed=i,
                    session=f"bench-{i}", on_tokens=coll)
                break
            except RetryAfter:
                _t.sleep(0.02)
        sessions.append((fut, oracle, coll))
        if i == kill_at:
            killed_name = fleet.names()[0]
            fleet.kill_chip(killed_name, seed=1)
            t_kill = _t.perf_counter()

            def _watch():
                nonlocal t_degraded, t_recovered
                while t_recovered is None:
                    snap = router.fleet_snapshot()
                    info = snap["endpoints"][killed_name]
                    sl = info.get("slice") or {}
                    if t_degraded is None and sl.get("degraded"):
                        t_degraded = _t.perf_counter()
                        fleet.rebuild_slice(killed_name)
                    elif t_degraded is not None and info["in_pool"]:
                        t_recovered = _t.perf_counter()
                        return
                    _t.sleep(0.02)
            threading.Thread(target=_watch, daemon=True).start()
        _t.sleep(0.03)

    lost = mismatches = dups = gaps = 0
    for fut, oracle, coll in sessions:
        try:
            out = fut.result(timeout=120)
        except BaseException:
            lost += 1
            continue
        if not np.array_equal(out, oracle):
            mismatches += 1
        if coll.tokens != [int(t) for t in oracle[0, -max_new:]]:
            mismatches += 1
        dups += coll.dups
        gaps += coll.gaps
    dt = _t.perf_counter() - t0
    deadline = _t.perf_counter() + 60
    while t_recovered is None and _t.perf_counter() < deadline:
        _t.sleep(0.05)
    # fleet convergence: collapse ejection backoffs and let probe
    # traffic reinstate half-open endpoints
    snap = router.fleet_snapshot()
    conv_deadline = _t.perf_counter() + 30
    while _t.perf_counter() < conv_deadline:
        router.probe_now()
        try:
            router.generate(rng.integers(1, vocab, (1, 4)), 1, timeout=30)
        except BaseException:
            pass
        snap = router.fleet_snapshot()
        if snap["healthy_endpoints"] >= 2:
            break
        _t.sleep(0.05)
    leaked = 0
    for eng in engines:
        sched = eng._scheduler
        if sched is None:
            continue
        pool = sched.stats()["pool"]
        leaked += int(pool["blocks_total"] - pool["blocks_free"])
    kill_phase = {
        "sessions": n_sessions,
        "lost_requests": lost,
        "token_mismatches": mismatches,
        "dup_offsets": dups,
        "gap_events": gaps,
        "leaked_blocks": leaked,
        "tokens_per_sec": round(n_sessions * max_new / dt, 1),
        "migrations": snap["migrations"],
        "rebuilt_width": fleet._members[killed_name].plane.axis_size("tp"),
        "recovery_s": (None if t_recovered is None or t_kill is None
                       else round(t_recovered - t_kill, 3)),
        "healthy_endpoints": snap["healthy_endpoints"],
    }
    fleet.shutdown(drain=False)
    router.close()

    # ---- phase B: disaggregated prefill/decode -------------------------
    dec_eng = ParallelInference(net=restore_model(art), continuous=True,
                                decode_slots=4, decode_burst=4,
                                kv_block_size=8, max_latency_ms=1.0)
    pre_eng = ParallelInference(net=restore_model(art), max_latency_ms=1.0)
    dec_eng.warmup_generate([4], 56)       # the steady decode streams
    dec_eng.warmup_generate([40], 1)       # the prefill-heavy requests
    pre_eng.warmup_prefill([4, 40])

    def run_phase(disagg: bool, n_heavy: int, rounds: int = 3):
        r = InferenceRouter(per_try_timeout_s=30.0)
        r.add_endpoint(LocalEndpoint(dec_eng, "dec"), role="decode")
        if disagg:
            r.add_endpoint(LocalEndpoint(pre_eng, "pre"), role="prefill")
        gaps_ms = []
        heavy_total = 0
        sched0 = dec_eng.stats()["scheduler"]
        prefill_tokens0 = sched0["prefill_tokens_computed"]
        handoffs0 = sched0["kv_handoffs"]
        for rnd in range(rounds):
            streams = []
            for i in range(3):
                prompt = rng.integers(1, vocab, (1, 4))
                coll = Collector()
                fut = r.submit_generate(prompt, 56, seed=100 + i,
                                        session=f"d-{disagg}-{rnd}-{i}",
                                        on_tokens=coll)
                streams.append((fut, coll))
            # prefill-heavy wave while the streams decode: each heavy
            # request's long prompt forward is the head-of-line block
            # the fused path pays between decode bursts; the disagg
            # path runs it on the prefill endpoint instead
            heavy = []
            for _ in range(n_heavy):
                prompt = rng.integers(1, vocab, (1, 40))
                try:
                    heavy.append(r.submit_generate(prompt, 1, seed=7))
                except RetryAfter:
                    pass
                _t.sleep(0.005)
            for f, _ in streams:
                f.result(timeout=120)
            for f in heavy:
                try:
                    f.result(timeout=120)
                except BaseException:
                    pass
            heavy_total += len(heavy)
            for _f, coll in streams:
                gaps_ms.extend((b - a) * 1e3
                               for a, b in zip(coll.at, coll.at[1:]))
        r.close()
        p99 = float(np.percentile(gaps_ms, 99)) if gaps_ms else 0.0
        sched1 = dec_eng.stats()["scheduler"]
        return {"heavy_per_round": n_heavy,
                "heavy_requests": heavy_total,
                "gap_samples": len(gaps_ms),
                "inter_token_p99_ms": round(p99, 2),
                # the offload semantics: prompt tokens the DECODE
                # endpoint computed itself (disagg: streams only —
                # every heavy prompt arrives as shipped KV)
                "decode_prefill_tokens":
                    sched1["prefill_tokens_computed"] - prefill_tokens0,
                "kv_handoffs": sched1["kv_handoffs"] - handoffs0}

    base_load = 6  # heavy prefills per round; 2x doubles the wave
    disagg_1x = run_phase(True, base_load)
    disagg_2x = run_phase(True, base_load * 2)
    fused_1x = run_phase(False, base_load)
    fused_2x = run_phase(False, base_load * 2)
    handoffs = dec_eng.stats()["scheduler"]["kv_handoffs"]
    dec_eng.shutdown()
    pre_eng.shutdown()

    def ratio(a, b):
        return round(b["inter_token_p99_ms"]
                     / max(a["inter_token_p99_ms"], 1e-9), 3)

    disagg_phase = {
        "kv_handoffs": handoffs,
        "disagg_1x": disagg_1x, "disagg_2x": disagg_2x,
        "fused_1x": fused_1x, "fused_2x": fused_2x,
        # the claim: decode p99 flat while prefill load doubles. NOTE
        # on this box every endpoint timeshares ONE physical core, so
        # wall-clock p99 is a semantics+overhead number (the mesh_train
        # caveat); the structural win the harness PINS is the offload —
        # the decode endpoint computes ZERO heavy-prompt tokens under
        # disaggregation (decode_prefill_tokens covers the streams
        # only), which on real chips is exactly the head-of-line work
        # that moves off the decode plane.
        "disagg_p99_ratio_2x_vs_1x": ratio(disagg_1x, disagg_2x),
        "fused_p99_ratio_2x_vs_1x": ratio(fused_1x, fused_2x),
        "heavy_prompt_tokens_offloaded_2x":
            fused_2x["decode_prefill_tokens"]
            - disagg_2x["decode_prefill_tokens"],
    }
    print(json.dumps({"kill_a_chip": kill_phase,
                      "disaggregation": disagg_phase}))


def bench_mesh_serving():
    """Mesh-sharded serving slices (ISSUE 12): two tp=4 slice endpoints
    on a forced-8-device mesh serving 24 decode streams through the
    router while one CHIP is killed mid-run — the poisoned slice
    declares itself degraded, its streams migrate token-for-token, the
    fleet rebuilds the slice at half width from the survivors; zero
    lost requests/tokens is the acceptance bar and recovery time is
    reported. Then the disaggregated prefill/decode phase: steady
    decode streams' inter-token p99 under 1x vs 2x prefill-heavy load,
    with and without a prefill-specialized endpoint, plus the PINNED
    offload semantics — under disaggregation the decode endpoint
    computes ZERO heavy-prompt tokens (every heavy prompt arrives as
    shipped KV). On this box every endpoint timeshares ONE physical
    core, so the wall-clock p99s are semantics+overhead numbers (the
    ``mesh_train`` caveat); on real chips the offloaded prompt forward
    is exactly the head-of-line block that keeps decode p99 flat while
    prefill load doubles."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU plugin in the worker
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["DL4J_TPU_DISABLE_DEVICE_TRACE"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "_mesh_serving_worker"],
        env=env, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"mesh_serving worker failed:\n{proc.stderr[-3000:]}")
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    kill = results["kill_a_chip"]
    dis = results["disaggregation"]
    ok = (kill["lost_requests"] == 0 and kill["token_mismatches"] == 0
          and kill["dup_offsets"] == 0 and kill["gap_events"] == 0
          and kill["leaked_blocks"] == 0
          # disaggregation offload semantics: under 2x prefill load the
          # decode endpoint recomputed NO heavy-prompt tokens (only the
          # streams' own short prompts) — the DistServe claim, pinned
          and dis["disagg_2x"]["decode_prefill_tokens"]
          < dis["fused_2x"]["decode_prefill_tokens"]
          and dis["disagg_2x"]["kv_handoffs"] > 0)
    return {
        "metric": "mesh_serving_kill_a_chip_completion",
        "value": kill["sessions"] - kill["lost_requests"],
        "unit": "sessions",
        "vs_baseline": 1.0 if ok else 0.0,
        **results,
    }


def bench_word2vec():
    """Word2Vec skip-gram (BASELINE config #5): the all-epochs-on-device
    SGNS scan engine (device pairgen + table negatives + capped MXU
    accumulation) over a synthetic zipf corpus, tokens/sec.

    ``vs_baseline`` is measured against a REAL external anchor: the
    tight-numpy host SGNS (``models/sequencevectors/host_baseline.py``,
    the ``SequenceVectors.java:1008`` Hogwild-engine role) run on the
    same corpus/params on this host — not the r3 self-referential 1.0."""
    import time

    from deeplearning4j_tpu.models.sequencevectors.host_baseline import (
        sgns_host_benchmark)
    from deeplearning4j_tpu.models.word2vec.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    vocab, n_sent, sent_len, bs = 2000, 8000, 20, 32768
    # zipf-ish frequencies like natural text
    probs = 1.0 / np.arange(1, vocab + 1)
    probs /= probs.sum()
    sents = [[f"w{t}" for t in rng.choice(vocab, sent_len, p=probs)]
             for _ in range(n_sent)]
    mk = lambda epochs: Word2Vec(layer_size=128, window_size=5,
                                 min_word_frequency=1, epochs=epochs,
                                 negative_sample=5, seed=1, batch_size=bs)
    mk(1).fit(sents)  # compile + warmup (same convention as the NN benches)
    epochs = 2
    w2v = mk(epochs)
    t0 = time.perf_counter()
    w2v.fit(sents)
    dt = time.perf_counter() - t0
    tokens = epochs * n_sent * sent_len
    hist = w2v._loss_history
    assert hist and np.isfinite(hist).all() and hist[-1] < hist[0], \
        f"word2vec loss not converging: {hist[:2]}..{hist[-2:]}"
    tps = tokens / dt
    # external anchor: numpy SGNS on this host, same corpus/params
    ids = [[int(t[1:]) for t in s] for s in sents]
    host = sgns_host_benchmark(ids, vocab, dim=128, window=5, K=5,
                               seed=1, max_seconds=10.0)
    return {"metric": "word2vec_sgns_tokens_per_sec_per_chip",
            "value": round(tps, 1), "unit": "tokens/sec/chip",
            "host_numpy_tokens_per_sec": round(host["tokens_per_sec"], 1),
            "vs_baseline": round(tps / host["tokens_per_sec"], 4)}


def bench_gpt():
    """GPT-style causal LM (zoo transformer, flash-attention blocks),
    synthetic token stream — the r2 small config (d512/L8/seq1024),
    kept for round-over-round comparability; small models structurally
    cap MFU (see gpt_large for the production shape)."""
    from deeplearning4j_tpu.models.zoo.transformer import gpt_benchmark
    return gpt_benchmark(PEAK_BF16)


def bench_gpt_large():
    """Production-shape GPT (d1024/L16/seq2048): the shape class real
    LM training runs at, where the framework must sustain >=30% MFU."""
    from deeplearning4j_tpu.models.zoo.transformer import gpt_benchmark
    r = gpt_benchmark(PEAK_BF16, d_model=1024, n_layers=16, seq_len=2048,
                      batch=8, steps=2)
    return {**r, "metric": "gpt_large_train_tokens_per_sec_per_chip"}


def bench_resnet50():
    """ResNet-50 (config #3, ComputationGraph.java:677) — requires the
    ComputationGraph fit_scan path; returns None until it exists."""
    try:
        from deeplearning4j_tpu.models.zoo.resnet import resnet50_benchmark
    except ImportError:
        return None
    return resnet50_benchmark(PEAK_BF16)


def main():
    _enable_compile_cache()
    from deeplearning4j_tpu import monitor

    subs = {}
    for name, fn in [("gemm_bf16", bench_gemm), ("lenet_mnist", bench_lenet),
                     ("mlp_iris", bench_mlp_iris),
                     ("mlp_per_step_fit", bench_mlp_per_step_fit),
                     ("lstm_char", bench_lstm),
                     ("resnet50", bench_resnet50),
                     ("flash_attention", bench_flash_attention),
                     ("flash_attention_train", bench_flash_attention_train),
                     ("gpt", bench_gpt), ("gpt_large", bench_gpt_large),
                     ("gpt_decode", bench_gpt_decode),
                     ("lstm_decode", bench_lstm_decode),
                     ("serving_inference", bench_serving_inference),
                     ("fault_recovery", bench_fault_recovery),
                     ("continuous_decode", bench_continuous_decode),
                     ("speculative_decode", bench_speculative_decode),
                     ("quantized_serving", bench_quantized_serving),
                     ("prefix_cache", bench_prefix_cache),
                     ("durable_decode", bench_durable_decode),
                     ("kv_tiering", bench_kv_tiering),
                     ("router_slo", bench_router_slo),
                     ("router_saturation", bench_router_saturation),
                     ("multi_model", bench_multi_model),
                     ("mesh_train", bench_mesh_train),
                     ("mesh_serving", bench_mesh_serving),
                     ("word2vec", bench_word2vec)]:
        # fresh registry per sub-bench: the monitor spans inside the
        # fit/stage paths give each result its own per-phase attribution
        # (data_load/compile/device_step/all_reduce), so BENCH rounds can
        # tell a staging regression from a device one
        prev_registry = monitor.set_registry(monitor.MetricsRegistry())
        r = None
        attempts = 3  # tunneled remote-compile can drop transiently
        last_err = None
        try:
            for attempt in range(attempts):
                try:
                    r = fn()
                    break
                except Exception as e:  # a broken sub-bench must not hide the rest
                    err = f"{type(e).__name__}: {e}"
                    r = {"error": err}
                    if err == last_err:  # deterministic failure: stop retrying
                        break
                    last_err = err
                    if attempt < attempts - 1:
                        time.sleep(5)
            phases = monitor.phase_breakdown()
            if r is not None and phases:
                r["phases"] = phases
        finally:
            monitor.set_registry(prev_registry)
        if r is not None:
            subs[name] = r

    headline = None
    for pref in ("resnet50", "gemm_bf16", "lenet_mnist", "lstm_char"):
        cand = subs.get(pref)
        if cand and "error" not in cand:
            headline = cand
            break
    if headline is None:  # everything failed: surface the first error
        headline = next(iter(subs.values()), {"metric": "none", "value": 0,
                                              "unit": "", "vs_baseline": 0})
    out = dict(headline)
    # machine-readable schema contract for scripts/bench_trend.py: the
    # trend gate refuses to diff payloads whose shape it doesn't know
    out["schema_version"] = BENCH_SCHEMA_VERSION
    out["sub_benchmarks"] = subs
    print(json.dumps(out))


if __name__ == "__main__":
    import sys as _sys

    if len(_sys.argv) > 1 and _sys.argv[1] == "_mesh_train_worker":
        _mesh_train_worker()
    elif len(_sys.argv) > 1 and _sys.argv[1] == "_mesh_serving_worker":
        _mesh_serving_worker()
    else:
        main()
