"""Benchmark: LeNet-MNIST training throughput on one TPU chip.

BASELINE.json config #1 (LeNet MNIST via MultiLayerNetwork) measured as
examples/sec/chip using the device-resident ``fit_scan`` path (whole
epoch = one XLA program; the host dispatches once per epoch).
``vs_baseline`` is achieved_MFU / 0.30 — the BASELINE.json north-star
target ("≥30% MFU on v5e"); >1.0 means the north star is met. The
reference publishes no numbers of its own (BASELINE.md), so the
hardware ceiling is the bar.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

BATCH = 2048
EPOCH_EXAMPLES = BATCH * 8
MEASURE_EPOCHS = 6

# v5e bf16 peak ~197 TFLOP/s; f32 ~half. Default compute dtype is f32.
PEAK_FLOPS = 98.5e12


def lenet_train_flops_per_example() -> float:
    """Analytic FLOPs per training example (fwd = 2*MACs, train ~ 3x fwd):
    conv1 5x5x1x20 @24x24, conv2 5x5x20x50 @8x8, dense 800->500, out 500->10."""
    macs = (24 * 24 * 20 * 25
            + 8 * 8 * 50 * 25 * 20
            + 800 * 500
            + 500 * 10)
    return 3.0 * 2.0 * macs


def main():
    import jax
    import __graft_entry__ as ge
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.mnist import load_mnist

    net = ge._flagship()
    ds = load_mnist(train=True, num_examples=EPOCH_EXAMPLES)
    data = DataSet(ds.features.reshape(-1, 28, 28, 1), ds.labels)

    net.fit_scan(data, BATCH, epochs=1)  # compile + warmup
    jax.block_until_ready(net.params)

    t0 = time.perf_counter()
    scores = net.fit_scan(data, BATCH, epochs=MEASURE_EPOCHS)
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0

    n_examples = MEASURE_EPOCHS * (EPOCH_EXAMPLES // BATCH) * BATCH
    examples_per_sec = n_examples / dt
    mfu = examples_per_sec * lenet_train_flops_per_example() / PEAK_FLOPS
    assert np.isfinite(scores).all()
    print(json.dumps({
        "metric": "lenet_mnist_train_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(mfu / 0.30, 6),
    }))


if __name__ == "__main__":
    main()
