#!/usr/bin/env python
"""Mesh-API lint — THIN SHIM over the ``mesh-api`` rule of the unified
static-analysis engine (``deeplearning4j_tpu/analysis/``; run
everything via ``scripts/analyze.py``).

The invariants, unchanged since PR 9/12 (the ``jax.shard_map``
AttributeError family was dead code for eight PRs before this lint):

1. **No dead API**: any ``jax.shard_map`` attribute access is an
   error, and ``jax.experimental.shard_map`` may be imported or
   referenced ONLY by ``parallel/mesh.py`` — per-device programs go
   through its one sanctioned ``device_collective`` wrapper.
2. **One mesh factory**: ``Mesh(...)`` construction outside
   ``parallel/mesh.py`` is an error — topology lives on the MeshPlane.
3. **Serving goes through the plane**: inside
   ``deeplearning4j_tpu/serving/`` even ``make_mesh`` /
   ``mesh_from_grid`` calls and ``Mesh`` imports are banned — a
   serving component is HANDED a ``MeshPlane``.

Importable (tier-1 runs :func:`check_repo`) and a CLI::

    python scripts/check_mesh_api.py [root]

Exit 0 when the repo is clean; 1 with one line per violation.
"""

from __future__ import annotations

import os
import sys
from typing import List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from deeplearning4j_tpu.analysis.engine import Project  # noqa: E402
from deeplearning4j_tpu.analysis.rules.mesh_api import \
    MeshApiRule  # noqa: E402

_RULE = MeshApiRule()


def check_file(path: str, rel: str = "") -> List[str]:
    """Violations ([] = clean) for one file."""
    rel = rel or path
    project = Project(os.path.dirname(path) or ".", paths=[path],
                      rels=[rel])
    m = project.modules[0]
    if m.parse_error is not None:
        return [f"{rel}: unparseable ({m.parse_error})"]
    return [f"{f.path}:{f.line}: {f.message}"
            for f in _RULE.check(project)
            if not m.suppressed(_RULE.name, f.line)]


def check_repo(root: str) -> List[str]:
    """Violations across every ``.py`` file under ``root``."""
    project = Project(root)
    out = []
    for f in sorted(_RULE.check(project),
                    key=lambda f: (f.path, f.line)):
        m = project.by_rel.get(f.path)
        if m is not None and m.suppressed(_RULE.name, f.line):
            continue
        out.append(f"{f.path}:{f.line}: {f.message}")
    return out


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else _ROOT
    problems = check_repo(root)
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        print(f"ok: no dead shard_map API and no rogue mesh construction "
              f"under {root}")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
