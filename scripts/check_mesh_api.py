#!/usr/bin/env python
"""Mesh-API lint: the dead ``jax.shard_map`` attribute can never come
back, and every mesh is built by ``parallel/mesh.py``.

The multi-chip plane was dead code for eight PRs because call sites
used ``jax.shard_map`` — an attribute that simply does not exist on
this jax (0.4.x); every ring-attention / pipeline / multihost /
seq-mesh test failed identically with AttributeError since the seed.
The rebuilt plane (``parallel/mesh.py`` MeshPlane/SpecLayout) holds two
disciplines this lint enforces STATICALLY, the way
``check_donation_gates.py`` pins the donation hazard:

1. **No dead API**: any ``jax.shard_map`` attribute access is an error,
   and the working ``jax.experimental.shard_map`` may be imported or
   referenced ONLY by ``parallel/mesh.py`` — everything per-device goes
   through its one sanctioned ``device_collective`` wrapper, so a jax
   upgrade/rename breaks exactly one file.
2. **One mesh factory**: ``Mesh(...)`` construction (bare or via
   ``jax.sharding.Mesh`` / ``sharding.Mesh``) outside ``parallel/mesh.py``
   is an error — topology decisions live on the MeshPlane, where the
   lint, the checkpoint layout recorder and /healthz can see them.

3. **Serving goes through the plane** (ISSUE 12, mesh-sharded serving
   slices): inside ``deeplearning4j_tpu/serving/`` even the sanctioned
   low-level factories (``make_mesh`` / ``mesh_from_grid``) and ``Mesh``
   imports are banned — a serving component is HANDED a ``MeshPlane``
   (or builds one via ``MeshPlane.build``, which records it on the
   active-plane seam /healthz reads); it never assembles raw mesh
   topology itself.

Importable (a tier-1 test runs :func:`check_repo`) and a CLI::

    python scripts/check_mesh_api.py [root]

Exit 0 when the repo is clean; 1 with one line per violation.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

#: the one file allowed to import/construct the raw primitives.
ALLOWED_FILES = ("parallel/mesh.py",)

#: directories where even the sanctioned low-level mesh factories are
#: banned: serving code takes a MeshPlane, it never builds topology.
SERVING_DIRS = ("deeplearning4j_tpu/serving/",)
SERVING_BANNED_CALLS = ("make_mesh", "mesh_from_grid")


def _in_serving(rel: str) -> bool:
    rel = rel.replace(os.sep, "/")
    return any(d in rel for d in SERVING_DIRS)


def _attr_chain(node) -> str:
    """Dotted name of an attribute chain ('jax.experimental.shard_map'),
    '' when the base is not a plain name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_mesh_ctor(node: ast.Call) -> bool:
    """Match ``Mesh(...)`` / ``jax.sharding.Mesh(...)`` /
    ``sharding.Mesh(...)`` — raw mesh construction."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "Mesh"
    if isinstance(f, ast.Attribute):
        return f.attr == "Mesh"
    return False


def check_file(path: str, rel: str = "") -> List[str]:
    """Violations ([] = clean) for one file."""
    rel = rel or path
    allowed = any(rel.endswith(a) for a in ALLOWED_FILES)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{rel}: unparseable ({e})"]
    problems: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            chain = _attr_chain(node)
            if chain == "jax.shard_map":
                problems.append(
                    f"{rel}:{node.lineno}: jax.shard_map does not exist on "
                    "this jax (the dead API that killed the multi-chip "
                    "plane) — use parallel.mesh.device_collective, or "
                    "jax.jit with shardings")
            elif "shard_map" in chain.split(".") and not allowed:
                problems.append(
                    f"{rel}:{node.lineno}: shard_map reference outside "
                    "parallel/mesh.py — per-device programs go through "
                    "parallel.mesh.device_collective")
        elif isinstance(node, (ast.Import, ast.ImportFrom)) and not allowed:
            mod = getattr(node, "module", "") or ""
            names = [a.name for a in node.names]
            if "shard_map" in mod or any("shard_map" in n for n in names):
                problems.append(
                    f"{rel}:{node.lineno}: shard_map import outside "
                    "parallel/mesh.py — per-device programs go through "
                    "parallel.mesh.device_collective")
            if _in_serving(rel) and (
                    any(n == "Mesh" or n.endswith(".Mesh") for n in names)
                    or any(n in SERVING_BANNED_CALLS for n in names)):
                problems.append(
                    f"{rel}:{node.lineno}: mesh-topology import inside "
                    "serving/ — serving components take a MeshPlane "
                    "(MeshPlane.build), they never assemble raw meshes")
        elif isinstance(node, ast.Call) and _is_mesh_ctor(node) \
                and not allowed:
            problems.append(
                f"{rel}:{node.lineno}: raw Mesh(...) construction outside "
                "parallel/mesh.py — build meshes via parallel.mesh "
                "(make_mesh / mesh_from_grid / MeshPlane)")
        elif isinstance(node, ast.Call) and _in_serving(rel):
            f = node.func
            callee = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if callee in SERVING_BANNED_CALLS:
                problems.append(
                    f"{rel}:{node.lineno}: {callee}() inside serving/ — "
                    "the sharded-serving code goes through MeshPlane "
                    "(MeshPlane.build / a plane handed in), never the "
                    "low-level mesh factories")
    return problems


def _tracked_py_files(root: str) -> List[Tuple[str, str]]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in (".git", "__pycache__", ".pytest_cache",
                                    "node_modules")]
        for name in filenames:
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                out.append((path, os.path.relpath(path, root)))
    return sorted(out)


def check_repo(root: str) -> List[str]:
    """Violations across every ``.py`` file under ``root``."""
    problems: List[str] = []
    for path, rel in _tracked_py_files(root):
        problems.extend(check_file(path, rel))
    return problems


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check_repo(root)
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        print(f"ok: no dead shard_map API and no rogue mesh construction "
              f"under {root}")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
