#!/usr/bin/env python
"""dl4j-analyze CLI — run the unified static-analysis engine repo-wide.

::

    python scripts/analyze.py                 # text report, exit != 0 on
                                              # any NEW finding
    python scripts/analyze.py --json          # machine-readable report
                                              # (what quick_check section
                                              # 0 consumes)
    python scripts/analyze.py --rules lock-order,prng-reuse
    python scripts/analyze.py --list-rules    # rule catalog
    python scripts/analyze.py --lock-graph    # the reconstructed lock
                                              # graph as JSON
    python scripts/analyze.py --write-baseline  # grandfather every
                                              # current NEW finding

Suppression: ``# dl4j-lint: disable=<rule>[,<rule>]`` on the flagged
line (or a comment-only line directly above). Baseline:
``scripts/analyze_baseline.json`` — (rule, path, message) keys,
line-free; entries carry a ``note`` saying why they are accepted.

Exit 0 iff zero unsuppressed, unbaselined findings. The legacy
``check_donation_gates.py`` / ``check_mesh_api.py`` /
``check_metric_names.py`` CLIs remain as shims over single rules.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from deeplearning4j_tpu.analysis import (  # noqa: E402
    all_rules,
    analyze,
    render_json,
    render_text,
    rule_by_name,
    write_baseline,
)
from deeplearning4j_tpu.analysis.engine import DEFAULT_BASELINE  # noqa: E402
from deeplearning4j_tpu.analysis.rules.lock_order import \
    build_lock_graph  # noqa: E402
from deeplearning4j_tpu.analysis.engine import Project  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("root", nargs="?", default=_ROOT)
    ap.add_argument("--json", action="store_true",
                    help="JSON report on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule names (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         f"<root>/{DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current NEW finding into "
                         "the baseline file and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the reconstructed lock-acquisition "
                         "graph (nodes/edges/cycles) as JSON and exit "
                         "0 iff acyclic")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also list suppressed/baselined findings")
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = [rule_by_name(r.strip())
                 for r in args.rules.split(",") if r.strip()]

    if args.list_rules:
        for r in all_rules():
            print(f"{r.name}: {r.description}")
        return 0

    if args.lock_graph:
        g = build_lock_graph(Project(args.root))
        print(json.dumps(g.as_dict(), indent=1, sort_keys=True))
        return 1 if g.cycles() else 0

    baseline = args.baseline or os.path.join(args.root, DEFAULT_BASELINE)
    report = analyze(args.root, rules=rules, baseline=baseline)

    if args.write_baseline:
        write_baseline(baseline, report.new)
        print(f"baseline: {len(report.new)} findings grandfathered "
              f"into {baseline} — fill in each entry's 'note' with why")
        return 0

    if args.json:
        print(render_json(report))
    else:
        print(render_text(report, verbose=args.verbose))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
