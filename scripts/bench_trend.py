#!/usr/bin/env python
"""Perf-regression trend gate over the committed bench history.

The repo commits one ``BENCH_r<NN>.json`` per growth round — the raw
driver record ``{"n", "cmd", "rc", "tail", "parsed"}`` where ``parsed``
is ``bench.py``'s stdout JSON (``schema_version`` + headline +
``sub_benchmarks``). This script turns that history into per-metric
trend series and GATES a candidate payload against them:

- **history** — every ``BENCH_r*.json`` in ``--history`` (default:
  repo root), ordered by round number; malformed rounds fail loudly
  (a gate that skips what it cannot read is not a gate);
- **candidate** — ``--fresh FILE`` (a saved ``bench.py`` stdout JSON),
  or by default the LATEST history round judged against the rounds
  before it — so the committed history itself must stay green;
- **noise band** — per metric, the trailing ``--window`` prior values
  give (mean, population stddev); the candidate regresses when it
  falls below ``mean - max(threshold·mean, nsigma·stddev)``. Every
  ``value`` here is a throughput (tokens/sec, TFLOP/s, examples/sec —
  higher is better); latencies ride inside sub-payloads and are not
  gated;
- **TREND.md** — the per-metric table (prior window, band floor,
  candidate, delta, verdict) is rewritten on every gating run;
- exit status: 0 green, 1 regression, 2 malformed history/candidate.

``--check`` is the schema-only mode ``stress_faultinject.quick_check``
wires in: it validates every committed round's shape AND replays a
deterministic synthetic fixture through the gate logic (an injected
regression must flag, a flat series must pass) — no bench run, no
TREND.md rewrite, seconds not minutes.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HISTORY_PATTERN = "BENCH_r*.json"
_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: schema_version values this gate knows how to diff (bench.py's
#: BENCH_SCHEMA_VERSION). Older committed rounds predate the field —
#: absent means "version 1 shape", which is what they are.
KNOWN_SCHEMA_VERSIONS = (1,)

DEFAULT_WINDOW = 4
DEFAULT_THRESHOLD = 0.10
DEFAULT_NSIGMA = 3.0


class TrendError(Exception):
    """Malformed history/candidate — exit 2, never a silent skip."""


# ----------------------------------------------------------- loading

def _validate_payload(payload: Any, where: str) -> Dict[str, Any]:
    """One bench.py stdout payload: required shape or TrendError."""
    if not isinstance(payload, dict):
        raise TrendError(f"{where}: payload is {type(payload).__name__}, "
                         "expected object")
    for key, kinds in (("metric", (str,)), ("value", (int, float)),
                       ("unit", (str,))):
        if key not in payload:
            raise TrendError(f"{where}: missing required key {key!r}")
        if not isinstance(payload[key], kinds):
            raise TrendError(
                f"{where}: key {key!r} is "
                f"{type(payload[key]).__name__}, expected "
                f"{'/'.join(k.__name__ for k in kinds)}")
    sv = payload.get("schema_version", 1)
    if sv not in KNOWN_SCHEMA_VERSIONS:
        raise TrendError(f"{where}: schema_version {sv!r} unknown to "
                         f"this gate (knows {KNOWN_SCHEMA_VERSIONS})")
    subs = payload.get("sub_benchmarks", {})
    if not isinstance(subs, dict):
        raise TrendError(f"{where}: sub_benchmarks is "
                         f"{type(subs).__name__}, expected object")
    for name, sub in subs.items():
        if not isinstance(sub, dict):
            raise TrendError(f"{where}: sub_benchmarks[{name!r}] is "
                             f"{type(sub).__name__}, expected object")
        if "error" in sub:
            continue  # a failed sub-bench carries its error, no value
        if not isinstance(sub.get("value"), (int, float)):
            raise TrendError(
                f"{where}: sub_benchmarks[{name!r}].value is "
                f"{type(sub.get('value')).__name__}, expected number")
    return payload


def load_history(history_dir: str) -> List[Tuple[int, Dict[str, Any]]]:
    """Every committed round as (round_number, validated payload),
    ascending. Rounds whose bench run itself failed (rc != 0 or no
    parsed payload) are malformed history — fail, don't skip."""
    rounds: List[Tuple[int, Dict[str, Any]]] = []
    for path in glob.glob(os.path.join(history_dir, HISTORY_PATTERN)):
        m = _ROUND_RE.search(path)
        if m is None:
            continue
        n = int(m.group(1))
        with open(path) as f:
            rec = json.load(f)
        if not isinstance(rec, dict) or "parsed" not in rec:
            raise TrendError(f"{path}: not a driver record "
                             "(missing 'parsed')")
        rounds.append((n, _validate_payload(rec["parsed"], path)))
    rounds.sort()
    return rounds


def extract_metrics(payload: Dict[str, Any]) -> Dict[str, float]:
    """Gated series from one payload: one entry per clean sub-benchmark
    (keyed by sub name — stable across rounds even when the headline
    metric rotates) plus the headline under ``headline``."""
    out: Dict[str, float] = {"headline": float(payload["value"])}
    for name, sub in sorted((payload.get("sub_benchmarks") or {}).items()):
        if isinstance(sub, dict) and "error" not in sub \
                and isinstance(sub.get("value"), (int, float)):
            out[name] = float(sub["value"])
    return out


# ------------------------------------------------------------- gating

def gate_metric(priors: List[float], fresh: float,
                threshold: float, nsigma: float) -> Dict[str, Any]:
    """One metric's verdict. The band floor is
    ``mean - max(threshold·mean, nsigma·stddev)``: the fractional
    threshold catches regressions on quiet series, the sigma term
    widens the band for series whose round-to-round history is noisy
    (each growth round changes the code — honest noise, not jitter)."""
    mean = sum(priors) / len(priors)
    var = sum((v - mean) ** 2 for v in priors) / len(priors)
    std = math.sqrt(var)
    band = max(threshold * abs(mean), nsigma * std)
    floor = mean - band
    delta = (fresh - mean) / mean if mean else 0.0
    return {"priors": list(priors), "mean": mean, "stddev": std,
            "floor": floor, "fresh": fresh, "delta_frac": delta,
            "regressed": fresh < floor}


def gate(history: List[Tuple[int, Dict[str, Any]]],
         fresh_payload: Dict[str, Any], window: int,
         threshold: float, nsigma: float) -> Dict[str, Dict[str, Any]]:
    """Every metric present in BOTH the candidate and ≥2 prior rounds
    gets a verdict; single-occurrence metrics (a brand-new sub-bench)
    have no trend yet and report ``new`` instead of a verdict."""
    series: Dict[str, List[float]] = {}
    for _, payload in history:
        for name, value in extract_metrics(payload).items():
            series.setdefault(name, []).append(value)
    fresh = extract_metrics(fresh_payload)
    report: Dict[str, Dict[str, Any]] = {}
    for name, value in sorted(fresh.items()):
        priors = series.get(name, [])[-window:]
        if len(priors) < 2:
            report[name] = {"fresh": value, "new": True,
                            "regressed": False}
            continue
        report[name] = gate_metric(priors, value, threshold, nsigma)
    return report


# ------------------------------------------------------------ TREND.md

def render_trend_md(report: Dict[str, Dict[str, Any]],
                    rounds: List[int], window: int, threshold: float,
                    nsigma: float, candidate_label: str) -> str:
    lines = [
        "# Bench trend",
        "",
        f"Candidate **{candidate_label}** gated against the trailing "
        f"{window}-round window of committed history "
        f"(rounds {', '.join(f'r{n:02d}' for n in rounds)}).",
        "",
        f"Noise band per metric: `mean - max({threshold:.0%}·mean, "
        f"{nsigma:g}σ)` over the prior window; a candidate below the "
        "floor is a regression (all gated values are throughputs — "
        "higher is better).",
        "",
        "| metric | prior mean | band floor | candidate | delta | "
        "verdict |",
        "|---|---|---|---|---|---|",
    ]
    for name, r in sorted(report.items()):
        if r.get("new"):
            lines.append(f"| {name} | — | — | {r['fresh']:.4g} | — | "
                         "new (no trend yet) |")
            continue
        verdict = "**REGRESSED**" if r["regressed"] else "ok"
        lines.append(
            f"| {name} | {r['mean']:.4g} | {r['floor']:.4g} | "
            f"{r['fresh']:.4g} | {r['delta_frac']:+.1%} | {verdict} |")
    regressed = sorted(n for n, r in report.items() if r["regressed"])
    lines += ["", ("Regressions: " + ", ".join(regressed)
                   if regressed else "No regressions."), ""]
    return "\n".join(lines)


# -------------------------------------------------------- check mode

def _fixture_check(window: int) -> List[str]:
    """Deterministic gate-logic replay: the synthetic injected
    regression MUST flag and the flat series MUST pass, or the gate's
    own logic has rotted. Pure arithmetic — no bench run."""
    problems: List[str] = []
    flat = [100.0, 101.0, 99.0, 100.5][-window:]
    ok = gate_metric(flat, 100.0, DEFAULT_THRESHOLD, DEFAULT_NSIGMA)
    if ok["regressed"]:
        problems.append("fixture: flat series (100,101,99,100.5 -> "
                        "100.0) flagged as regression")
    injected = gate_metric(flat, 60.0, DEFAULT_THRESHOLD, DEFAULT_NSIGMA)
    if not injected["regressed"]:
        problems.append("fixture: injected -40% regression "
                        "(priors ~100 -> 60.0) NOT flagged")
    improved = gate_metric(flat, 140.0, DEFAULT_THRESHOLD, DEFAULT_NSIGMA)
    if improved["regressed"]:
        problems.append("fixture: +40% improvement flagged as "
                        "regression (gate must be one-sided)")
    return problems


def run_check(history_dir: str, window: int) -> int:
    """--check: committed-history schema validation + the gate-logic
    fixture. Prints one line per problem; exit 0 clean, 2 otherwise."""
    problems: List[str] = []
    try:
        rounds = load_history(history_dir)
        if not rounds:
            problems.append(f"no {HISTORY_PATTERN} history found in "
                            f"{history_dir}")
    except (TrendError, json.JSONDecodeError) as e:
        problems.append(str(e))
        rounds = []
    problems.extend(_fixture_check(window))
    if problems:
        for p in problems:
            print(f"bench_trend --check: {p}")
        return 2
    print(f"bench_trend --check: {len(rounds)} committed rounds valid, "
          "gate fixture green")
    return 0


# --------------------------------------------------------------- main

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json "
                    "(default: repo root)")
    ap.add_argument("--fresh", default=None,
                    help="candidate payload: a saved bench.py stdout "
                    "JSON file (default: gate the latest committed "
                    "round against the rounds before it)")
    ap.add_argument("--out", default=None,
                    help="TREND.md path (default: <history>/TREND.md)")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="prior rounds in the noise band "
                    f"(default {DEFAULT_WINDOW})")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="fractional regression threshold "
                    f"(default {DEFAULT_THRESHOLD})")
    ap.add_argument("--nsigma", type=float, default=DEFAULT_NSIGMA,
                    help="stddev multiplier widening the band "
                    f"(default {DEFAULT_NSIGMA})")
    ap.add_argument("--check", action="store_true",
                    help="schema-only: validate committed history + "
                    "replay the gate-logic fixture (no gating, no "
                    "TREND.md)")
    args = ap.parse_args(argv)

    if args.check:
        return run_check(args.history, args.window)

    try:
        rounds = load_history(args.history)
        if args.fresh is not None:
            with open(args.fresh) as f:
                fresh_payload = _validate_payload(json.load(f),
                                                  args.fresh)
            label = os.path.basename(args.fresh)
            history = rounds
        else:
            if len(rounds) < 2:
                raise TrendError(
                    f"need >=2 committed rounds to gate the latest "
                    f"(found {len(rounds)} in {args.history})")
            n, fresh_payload = rounds[-1]
            label = f"r{n:02d} (latest committed round)"
            history = rounds[:-1]
        if not history:
            raise TrendError("no prior rounds to trend against")
    except (TrendError, json.JSONDecodeError, OSError) as e:
        print(f"bench_trend: {e}", file=sys.stderr)
        return 2

    report = gate(history, fresh_payload, args.window,
                  args.threshold, args.nsigma)
    out_path = args.out or os.path.join(args.history, "TREND.md")
    md = render_trend_md(report, [n for n, _ in history], args.window,
                         args.threshold, args.nsigma, label)
    with open(out_path, "w") as f:
        f.write(md)

    regressed = sorted(n for n, r in report.items() if r["regressed"])
    gated = sum(1 for r in report.values() if not r.get("new"))
    print(f"bench_trend: {gated} metrics gated, "
          f"{len(report) - gated} new, "
          f"{len(regressed)} regressed -> {out_path}")
    for name in regressed:
        r = report[name]
        print(f"  REGRESSED {name}: {r['fresh']:.4g} < floor "
              f"{r['floor']:.4g} (prior mean {r['mean']:.4g}, "
              f"{r['delta_frac']:+.1%})")
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
