#!/usr/bin/env python
"""Validate emitted telemetry against the checked-in schema.

Guards the three monitor/ wire formats against drift (a renamed field
silently breaks every downstream consumer — Perfetto, Prometheus
scrapers, BENCH attribution):

- JSONL event streams (``monitor.enable_tracing(jsonl_path=...)``)
- request-trace JSONL (``monitor/reqtrace.py`` flight-recorder dumps /
  ``UiServer /debug/traces``): span records whose parent edges must
  resolve, one root per trace, per-process monotonic timestamps —
  plus :func:`validate_migration_coverage`, the durable-decode bar
  that a migrated stream's token-gap is fully attributed by spans
- Chrome ``trace_event`` JSON exports (``PhaseTracer.chrome_trace``)
- Prometheus text exposition (``MetricsRegistry.prometheus_text`` /
  ``UiServer /metrics``)

Importable (``tests/test_monitor.py`` wires it into tier-1) and a CLI::

    python scripts/check_telemetry_schema.py run/events.jsonl \
        run/trace.json --metrics metrics.txt

Exit 0 when everything validates; 1 with one line per violation.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, Iterable, List

# ----------------------------------------------------------- JSONL events

EVENT_TYPES = {"span", "event"}
# required key -> allowed python types, per event type
SPAN_KEYS = {"type": str, "name": str, "ts_us": (int, float),
             "dur_us": (int, float), "pid": int, "tid": int}
INSTANT_KEYS = {"type": str, "name": str, "ts_us": (int, float),
                "pid": int, "tid": int}
OPTIONAL_KEYS = {"attrs": dict}


def validate_event(obj: Any, where: str = "event") -> List[str]:
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: not a JSON object"]
    etype = obj.get("type")
    if etype not in EVENT_TYPES:
        return [f"{where}: type {etype!r} not in {sorted(EVENT_TYPES)}"]
    required = SPAN_KEYS if etype == "span" else INSTANT_KEYS
    for key, types in required.items():
        if key not in obj:
            errors.append(f"{where}: missing required key {key!r}")
        elif not isinstance(obj[key], types):
            errors.append(f"{where}: key {key!r} has type "
                          f"{type(obj[key]).__name__}")
    for key in obj:
        if key not in required and key not in OPTIONAL_KEYS:
            errors.append(f"{where}: unknown key {key!r}")
    if "attrs" in obj and not isinstance(obj["attrs"], dict):
        errors.append(f"{where}: attrs must be an object")
    if not errors:
        if not obj["name"]:
            errors.append(f"{where}: empty name")
        if obj["ts_us"] < 0:
            errors.append(f"{where}: negative ts_us")
        if etype == "span" and obj["dur_us"] < 0:
            errors.append(f"{where}: negative dur_us")
    return errors


def validate_events_lines(lines: Iterable[str],
                          where: str = "events") -> List[str]:
    errors: List[str] = []
    n = 0
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        n += 1
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"{where}:{i}: invalid JSON: {e}")
            continue
        errors.extend(validate_event(obj, f"{where}:{i}"))
    if n == 0:
        errors.append(f"{where}: no events (empty stream)")
    return errors


def validate_events_file(path: str) -> List[str]:
    with open(path) as f:
        return validate_events_lines(f, path)


# ------------------------------------------- request traces (reqtrace)

# monitor/reqtrace.py span records: the cross-process request-trace
# JSONL (flight-recorder dumps, UiServer /debug/traces). One record
# per span; parent edges must RESOLVE inside the merged trace.
REQSPAN_KEYS = {"type": str, "trace": str, "span": str, "name": str,
                "ts_us": (int, float), "dur_us": (int, float),
                "pid": int, "tid": int}
REQSPAN_OPTIONAL = {"attrs": dict}
FLIGHT_EVENT_KEYS = {"type": str, "kind": str, "ts_us": (int, float),
                     "pid": int}


def validate_reqspan(obj: Any, where: str = "reqspan") -> List[str]:
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: not a JSON object"]
    if obj.get("type") != "reqspan":
        return [f"{where}: type {obj.get('type')!r} != 'reqspan'"]
    for key, types in REQSPAN_KEYS.items():
        if key not in obj:
            errors.append(f"{where}: missing required key {key!r}")
        elif not isinstance(obj[key], types):
            errors.append(f"{where}: key {key!r} has type "
                          f"{type(obj[key]).__name__}")
    if "parent" not in obj:
        errors.append(f"{where}: missing required key 'parent'")
    elif obj["parent"] is not None and not isinstance(obj["parent"], str):
        errors.append(f"{where}: parent must be a span id or null")
    for key in obj:
        if key not in REQSPAN_KEYS and key != "parent" \
                and key not in REQSPAN_OPTIONAL:
            errors.append(f"{where}: unknown key {key!r}")
    if not errors:
        if not obj["name"]:
            errors.append(f"{where}: empty name")
        if obj["ts_us"] < 0:
            errors.append(f"{where}: negative ts_us")
        if obj["dur_us"] < 0:
            errors.append(f"{where}: negative dur_us")
    return errors


def validate_trace_spans(spans: List[Any], where: str = "trace",
                         require_single_root: bool = True) -> List[str]:
    """Structural validity of ONE merged request trace: every span
    record well-formed, span ids unique, every parent edge resolves
    (no orphan spans), exactly one root, and per-(pid, tid) record
    order monotonic in span END time — a process whose clock ran
    backwards (or a buggy producer recording out of order) fails here,
    while cross-process clock skew (different origins) does not."""
    errors: List[str] = []
    for i, s in enumerate(spans):
        errors.extend(validate_reqspan(s, f"{where}[{i}]"))
    if errors:
        return errors
    if not spans:
        return [f"{where}: empty trace (no spans)"]
    traces = {s["trace"] for s in spans}
    if len(traces) != 1:
        errors.append(f"{where}: spans from {len(traces)} trace ids "
                      f"in one trace")
    ids = [s["span"] for s in spans]
    if len(set(ids)) != len(ids):
        errors.append(f"{where}: duplicate span ids")
    known = set(ids)
    roots = 0
    for i, s in enumerate(spans):
        if s["parent"] is None:
            roots += 1
        elif s["parent"] not in known:
            errors.append(f"{where}[{i}]: orphan span {s['span']!r} "
                          f"({s['name']}): parent {s['parent']!r} does "
                          f"not resolve")
    if require_single_root and roots != 1:
        errors.append(f"{where}: {roots} root spans (want exactly 1)")
    # per-process monotonicity: records land in close order, so within
    # one (pid, tid) the END timestamps must be non-decreasing in list
    # order (1us slack for the 3-decimal rounding)
    last_end: Dict[tuple, float] = {}
    for i, s in enumerate(spans):
        key = (s["pid"], s["tid"])
        end = s["ts_us"] + s["dur_us"]
        prev = last_end.get(key)
        if prev is not None and end < prev - 1.0:
            errors.append(
                f"{where}[{i}]: non-monotonic timestamps in pid "
                f"{s['pid']}/tid {s['tid']}: span {s['name']} ends at "
                f"{end:.1f}us after a record ending {prev:.1f}us")
        last_end[key] = max(prev or 0.0, end)
    return errors


def validate_migration_coverage(spans: List[Dict[str, Any]],
                                where: str = "trace",
                                tol_us: float = 5e3) -> List[str]:
    """The durable-decode acceptance bar, checked on ONE migrated
    stream's merged trace: the migration token-gap must be fully
    attributed — a ``silence_wait`` span (last chunk → failure
    detection), a ``repin`` span (re-pin + resume re-submit), a resume
    ``dispatch`` carrying the journaled prefix, the resume re-prefill
    (``prefill`` span with ``resume: true``), and a first post-resume
    ``decode_burst`` — and those spans must TILE the interval from
    silence start to the end of the resume prefill with no hole larger
    than ``tol_us``."""
    errors: List[str] = []
    by = lambda n: [s for s in spans if s["name"] == n]
    sw, rp = by("silence_wait"), by("repin")
    resume_pre = [s for s in by("prefill")
                  if (s.get("attrs") or {}).get("resume")]
    disp = by("dispatch")
    resume_disp = [s for s in disp
                   if (s.get("attrs") or {}).get("resume_prefix")]
    if not sw:
        errors.append(f"{where}: migrated stream has no silence_wait span")
    if not rp:
        errors.append(f"{where}: no repin span")
    if len(disp) < 2:
        errors.append(f"{where}: fewer than 2 dispatch spans for a "
                      f"migrated stream")
    if not resume_disp:
        errors.append(f"{where}: no dispatch carrying a resume prefix")
    if not resume_pre:
        errors.append(f"{where}: resume re-prefill not attributed "
                      f"(no prefill span with resume=true)")
    if not errors:
        t_rp = max(s["ts_us"] for s in rp)
        bursts_after = [s for s in by("decode_burst")
                        if s["ts_us"] >= t_rp - 1.0]
        if not bursts_after:
            errors.append(f"{where}: no decode_burst span after the "
                          f"resume (first resumed burst unattributed)")
    if errors:
        return errors
    # gap coverage (one merged clock): from silence start to the end of
    # the resume re-prefill, the migration machinery's spans must tile
    # the interval — any hole is unattributed token-gap time
    t0 = min(s["ts_us"] for s in sw)
    t1 = max(s["ts_us"] + s["dur_us"] for s in resume_pre)
    segs = sorted(
        (s["ts_us"], s["ts_us"] + s["dur_us"]) for s in spans
        if s["name"] in ("silence_wait", "repin", "dispatch",
                         "queue_wait", "prefill", "decode_burst"))
    cover = t0
    for a, b in segs:
        if b <= cover:
            continue
        if a > cover + tol_us:
            errors.append(
                f"{where}: migration gap hole "
                f"{cover:.0f}..{a:.0f}us uncovered by spans")
            return errors
        cover = max(cover, b)
        if cover >= t1:
            break
    if cover < t1 - tol_us:
        errors.append(f"{where}: migration gap uncovered after "
                      f"{cover:.0f}us (resume prefill ends {t1:.0f}us)")
    return errors


def validate_flight_lines(lines: Iterable[str],
                          where: str = "flight") -> List[str]:
    """Validate a flight-recorder JSONL dump (or UiServer
    /debug/traces body): ``flight_event`` records, ``trace`` records
    (each embedded span list fully validated), and bare ``reqspan``
    streams."""
    errors: List[str] = []
    n = 0
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        n += 1
        w = f"{where}:{i}"
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"{w}: invalid JSON: {e}")
            continue
        if not isinstance(obj, dict):
            errors.append(f"{w}: not a JSON object")
            continue
        t = obj.get("type")
        if t == "flight_event":
            for key, types in FLIGHT_EVENT_KEYS.items():
                if key not in obj:
                    errors.append(f"{w}: missing required key {key!r}")
                elif not isinstance(obj[key], types):
                    errors.append(f"{w}: key {key!r} has type "
                                  f"{type(obj[key]).__name__}")
        elif t == "trace":
            for key in ("trace", "root", "name", "spans"):
                if key not in obj:
                    errors.append(f"{w}: missing required key {key!r}")
            if isinstance(obj.get("spans"), list):
                errors.extend(validate_trace_spans(obj["spans"], w))
            else:
                errors.append(f"{w}: spans is not an array")
        elif t == "reqspan":
            errors.extend(validate_reqspan(obj, w))
        else:
            errors.append(f"{w}: unknown record type {t!r}")
    if n == 0:
        errors.append(f"{where}: no records (empty stream)")
    return errors


def validate_flight_file(path: str) -> List[str]:
    with open(path) as f:
        return validate_flight_lines(f, path)


def validate_jsonl_file(path: str) -> List[str]:
    """Sniff a .jsonl file: flight-recorder / reqtrace records get the
    request-trace validation, everything else the PhaseTracer event
    schema."""
    with open(path) as f:
        lines = f.readlines()
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            t = json.loads(line).get("type")
        except Exception:
            break
        if t in ("reqspan", "flight_event", "trace"):
            return validate_flight_lines(lines, path)
        break
    return validate_events_lines(lines, path)


# ------------------------------------------------------ Chrome trace JSON

def validate_chrome_trace(obj: Any, where: str = "trace") -> List[str]:
    errors: List[str] = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return [f"{where}: must be an object with a traceEvents array"]
    events = obj["traceEvents"]
    if not isinstance(events, list):
        return [f"{where}: traceEvents is not an array"]
    phases_seen = 0
    for i, e in enumerate(events):
        w = f"{where}.traceEvents[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{w}: not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "M", "B", "E"):
            errors.append(f"{w}: unknown ph {ph!r}")
            continue
        if "name" not in e or "pid" not in e:
            errors.append(f"{w}: missing name/pid")
        if ph == "X":
            phases_seen += 1
            for k in ("ts", "dur", "tid"):
                if not isinstance(e.get(k), (int, float)):
                    errors.append(f"{w}: ph=X needs numeric {k}")
        if ph == "i" and not isinstance(e.get("ts"), (int, float)):
            errors.append(f"{w}: ph=i needs numeric ts")
    if phases_seen == 0:
        errors.append(f"{where}: no complete (ph=X) span events")
    return errors


def validate_chrome_trace_file(path: str) -> List[str]:
    try:
        with open(path) as f:
            obj = json.load(f)
    except json.JSONDecodeError as e:
        return [f"{path}: invalid JSON: {e}"]
    return validate_chrome_trace(obj, path)


# ------------------------------------------------ known dl4j metric names

# The pinned registry of in-tree ``dl4j_``-prefixed metric families.
# A renamed family silently breaks every downstream consumer (BENCH
# attribution, Prometheus dashboards), so ``validate_known_metrics``
# flags any dl4j_ family an exposition declares that is not listed
# here — add new names HERE in the same PR that introduces them.
KNOWN_DL4J_METRICS = {
    # monitor core (tracing / step health / listeners)
    "dl4j_phase_duration_ms",
    "dl4j_step_duration_ms",
    "dl4j_step_duration_p50_ms",
    "dl4j_step_duration_p99_ms",
    "dl4j_score",
    "dl4j_nan_scores_total",
    "dl4j_slow_steps_total",
    "dl4j_iterations_total",
    "dl4j_iterations_per_sec",
    "dl4j_examples_per_sec",
    # streaming pipelines
    "dl4j_stream_batches_total",
    "dl4j_stream_buffer_examples",
    "dl4j_stream_examples_total",
    "dl4j_stream_requests_total",
    # device-feed pipeline (datasets/iterators.py + the fit() paths)
    "dl4j_feed_h2d_bytes_total",
    "dl4j_feed_queue_depth",
    "dl4j_feed_padded_batches_total",
    "dl4j_jit_cache_miss_total",
    "dl4j_score_sync_total",
    # serving plane (parallel/inference.py ParallelInference)
    "dl4j_infer_requests_total",
    "dl4j_infer_batches_total",
    "dl4j_infer_batch_size",
    "dl4j_infer_queue_depth",
    "dl4j_infer_padded_ratio",
    "dl4j_infer_latency_ms",
    # generation plane (nn/generate.py fused autoregressive decode,
    # served via ParallelInference.submit_generate)
    "dl4j_decode_requests_total",
    "dl4j_decode_prefill_tokens_total",
    "dl4j_decode_tokens_total",
    "dl4j_decode_prefill_latency_ms",
    "dl4j_decode_latency_ms",
    # multi-model serving plane (serving/registry.py ModelRegistry +
    # the registry-mode ParallelInference): per-model traffic/latency,
    # lifecycle events (deploys by outcome, rollbacks by reason,
    # budget evictions), active-version / breaker / pinned-bytes gauges
    "dl4j_model_requests_total",
    "dl4j_model_errors_total",
    "dl4j_model_latency_ms",
    "dl4j_model_deploys_total",
    "dl4j_model_rollbacks_total",
    "dl4j_model_evictions_total",
    "dl4j_model_active_version",
    "dl4j_model_breaker_open",
    "dl4j_model_pinned_bytes",
    # continuous batching plane (serving/continuous.py decode
    # scheduler + nn/kvpool.py paged KV block pool): pool occupancy /
    # exhaustion and the iteration-level scheduler's admit / retire /
    # preempt / burst accounting
    "dl4j_kvpool_blocks_total",
    "dl4j_kvpool_blocks_free",
    "dl4j_kvpool_alloc_failures_total",
    "dl4j_sched_admitted_rows_total",
    "dl4j_sched_retired_rows_total",
    "dl4j_sched_preemptions_total",
    "dl4j_sched_bursts_total",
    "dl4j_sched_burst_latency_ms",
    "dl4j_sched_active_sequences",
    "dl4j_sched_queued_prefills",
    # cross-request prefix cache (serving/prefixcache.py PrefixCache
    # over the refcounted paged pool): admission hit/miss volume,
    # deterministic LRU evictions, copy-on-write block duplications,
    # cached/shared block gauges, and the prompt tokens whose prefill
    # was skipped because their KV blocks were already cached
    "dl4j_prefixcache_hits_total",
    "dl4j_prefixcache_misses_total",
    "dl4j_prefixcache_evictions_total",
    "dl4j_prefixcache_cow_copies_total",
    "dl4j_prefixcache_cached_blocks",
    "dl4j_prefixcache_shared_blocks",
    "dl4j_prefixcache_saved_prefill_tokens_total",
    # horizontal serving tier (serving/router.py InferenceRouter)
    "dl4j_router_requests_total",
    "dl4j_router_shed_total",
    "dl4j_router_hedges_total",
    "dl4j_router_failovers_total",
    "dl4j_router_queue_wait_ms",
    "dl4j_router_latency_ms",
    "dl4j_router_endpoint_healthy",
    # wire/transport data plane (serving/wire.py v4 binary framing +
    # the router's event-loop core): frames/bytes packed by framing
    # (legacy npz vs v4 zero-copy segments), stream deltas that rode a
    # coalesced burst frame, and the router timer-loop's firing lag
    "dl4j_wire_frames_total",
    "dl4j_wire_bytes_total",
    "dl4j_wire_coalesced_chunks_total",
    "dl4j_router_loop_lag_ms",
    # end-to-end request tracing + SLO attribution
    # (monitor/reqtrace.py): per-request phase decomposition, TTFT /
    # TPOT as the caller observed them, per-model SLO burn outcomes,
    # span volume / bounded-buffer drops / open-trace gauge, and
    # flight-recorder triggers (each dumps the trace+event rings as
    # JSONL when a dump dir is armed)
    "dl4j_req_phase_ms",
    "dl4j_req_ttft_ms",
    "dl4j_req_tpot_ms",
    "dl4j_req_slo_burn_total",
    "dl4j_trace_spans_total",
    "dl4j_trace_dropped_total",
    "dl4j_trace_active",
    "dl4j_trace_flight_dumps_total",
    # durable decode streams (chunked token deltas, session journals,
    # cross-engine migration resume): chunks emitted by the decode
    # plane, migrations by reason, live journal bytes, and the resume
    # cost in re-submitted prefix tokens
    "dl4j_stream_chunks_total",
    "dl4j_session_migrations_total",
    "dl4j_session_journal_bytes",
    "dl4j_router_resume_prefix_tokens_total",
    # mesh plane (parallel/mesh.py MeshPlane): active named-axis
    # topology (devices + per-axis size) and checkpoint restores that
    # re-lowered saved shards onto a different mesh shape
    "dl4j_mesh_devices",
    "dl4j_mesh_axis_size",
    "dl4j_mesh_restore_relayouts_total",
    # mesh-sharded serving slices (parallel/inference.py slice_plane= +
    # serving/fleet.py): per-slice topology/degraded state, elastic
    # narrower-width rebuilds, and disaggregated prefill→decode KV
    # handoffs (zero prompt tokens recomputed on the decode side)
    "dl4j_slice_devices",
    "dl4j_slice_degraded",
    "dl4j_slice_rebuilds_total",
    "dl4j_disagg_kv_handoffs_total",
    # quantized serving plane (nn/quantize.py weight quantization +
    # the nn/kvpool.py quantized paged KV pool): quantized-net count
    # by dtype, quantized-pool block gauge, per-matrix dequant scale
    # stats, and the accuracy-gate pass/fail verdict counter
    "dl4j_quant_models",
    "dl4j_quant_kv_blocks",
    "dl4j_quant_scale_absmax",
    "dl4j_quant_accuracy_gate_outcome_total",
    # fault-tolerance plane (supervisor / quarantine / dead-letter /
    # checkpoint integrity — see monitor/__init__.py FAULT_* names)
    "dl4j_fault_events_total",
    "dl4j_fault_rollbacks_total",
    "dl4j_fault_quarantined_replicas",
    "dl4j_fault_dead_letter_total",
    "dl4j_fault_checkpoint_integrity_failures_total",
    # capacity observatory — windowed time-series (monitor/timeseries.py
    # TimeSeriesStore behind the registry; the dl4j_ts_* names are
    # SERIES keys answered by query(name, window), carried in stats()
    # payloads and served at UiServer /timeseries rather than exposed
    # as Prometheus families — pinned here all the same, one name one
    # meaning):
    "dl4j_ts_sched_active_rows",
    "dl4j_ts_sched_queued_prefills",
    "dl4j_ts_sched_pool_occupancy",
    "dl4j_ts_sched_prefix_hit_rate",
    "dl4j_ts_router_queue_depth",
    "dl4j_ts_router_admit_error_ms",
    "dl4j_ts_router_shed",
    "dl4j_ts_engine_fill_ratio",
    "dl4j_ts_engine_jit_miss",
    "dl4j_ts_slo_burn",
    "dl4j_ts_worker_served",
    # capacity observatory — per-owner resource attribution
    # (nn/kvpool.py byte-seconds + serving/continuous.py token/queue
    # accounting, label model=/owner=):
    "dl4j_attr_kv_byte_seconds",
    "dl4j_attr_prefill_tokens_total",
    "dl4j_attr_decode_tokens_total",
    "dl4j_attr_queue_ms_total",
    # speculative decoding (nn/generate.py spec programs +
    # serving/continuous.py fused draft/verify rounds, label model=):
    # proposed/accepted/rejected count draft tokens through the exact
    # rejection sampler; accept_rate is the running acceptance rate
    # (compare against the deploy-time quality-gate prior in
    # registry stats); draft_latency_ms is the draft-phase wall time
    "dl4j_spec_proposed_tokens_total",
    "dl4j_spec_accepted_tokens_total",
    "dl4j_spec_rejected_tokens_total",
    "dl4j_spec_accept_rate",
    "dl4j_spec_draft_latency_ms",
    # KV tiering + session hibernation (nn/kvpool.py host-RAM tier +
    # serving/continuous.py swap-aware scheduler + serving/router.py
    # durable session handles): swap traffic both directions,
    # prefix-cache demote-to-host rescues, host-tier occupancy and
    # per-direction swap latency, hibernated-session volume, restores
    # by exactness rung (label path=host|ship|journal), and host-tier
    # byte-seconds attribution (label owner=)
    "dl4j_kvtier_swap_out_total",
    "dl4j_kvtier_swap_in_total",
    "dl4j_kvtier_demotions_total",
    "dl4j_kvtier_hibernated_sessions_total",
    "dl4j_kvtier_restore_total",
    "dl4j_kvtier_host_blocks",
    "dl4j_kvtier_swap_latency_ms",
    "dl4j_prefixcache_demotions_total",
    "dl4j_attr_kv_host_byte_seconds",
}


def validate_known_metrics(text: str, where: str = "metrics") -> List[str]:
    """Flag dl4j_ families not in the pinned registry (drift guard)."""
    errors: List[str] = []
    for i, line in enumerate(text.splitlines(), 1):
        if not line.startswith("# TYPE "):
            continue
        parts = line.split()
        if len(parts) != 4:
            continue  # malformed TYPE lines are validate_prometheus_text's job
        name = parts[2]
        if name.startswith("dl4j_") and name not in KNOWN_DL4J_METRICS:
            errors.append(
                f"{where}:{i}: unknown dl4j_ metric family {name!r} — "
                "add it to KNOWN_DL4J_METRICS if it is intentional")
    return errors


# -------------------------------------------------- Prometheus exposition

_METRIC_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?"
    r" (?P<value>-?[0-9.eE+]+|NaN|\+Inf|-Inf)"
    r"( -?[0-9]+)?$")
# label values may escape ONLY backslash, double-quote and newline
# (text-format spec 0.0.4) — any other backslash escape is malformed
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\["\\n])*"$')
# HELP text may escape ONLY backslash and newline (quotes stay literal)
_HELP_TEXT_RE = re.compile(r"^(?:[^\\]|\\\\|\\n)*$")


def _base_family(name: str, families: Dict[str, str]) -> str:
    """Map a sample name to its declared family (histogram samples use
    the ``_bucket``/``_sum``/``_count`` suffixes)."""
    if name in families:
        return name
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix) and name[:-len(suffix)] in families:
            return name[:-len(suffix)]
    return name


def validate_prometheus_text(text: str,
                             where: str = "metrics") -> List[str]:
    errors: List[str] = []
    families: Dict[str, str] = {}  # name -> kind
    helps: Dict[str, int] = {}     # name -> HELP line number
    samples: Dict[str, List[Dict[str, str]]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        w = f"{where}:{i}"
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                errors.append(f"{w}: malformed TYPE line")
                continue
            if parts[2] in families:
                errors.append(f"{w}: duplicate TYPE for {parts[2]}")
            families[parts[2]] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)  # "#", "HELP", name, text
            if len(parts) < 3:
                errors.append(f"{w}: malformed HELP line")
                continue
            hname = parts[2]
            if hname in helps:
                errors.append(f"{w}: duplicate HELP for {hname}")
            helps[hname] = i
            htext = parts[3] if len(parts) == 4 else ""
            if not _HELP_TEXT_RE.match(htext):
                errors.append(
                    f"{w}: HELP text for {hname} has an invalid escape "
                    "(only \\\\ and \\n are allowed)")
            continue
        if line.startswith("#"):
            continue  # other comments
        m = _METRIC_RE.match(line)
        if m is None:
            errors.append(f"{w}: unparseable sample: {line!r}")
            continue
        name = m.group("name")
        labels: Dict[str, str] = {}
        raw = (m.group("labels") or "{}")[1:-1]
        if raw:
            for part in raw.split(","):
                if not _LABEL_RE.match(part):
                    errors.append(f"{w}: malformed label {part!r}")
                    continue
                k, v = part.split("=", 1)
                labels[k] = v[1:-1]
        fam = _base_family(name, families)
        if fam not in families:
            errors.append(f"{w}: sample {name} has no preceding # TYPE")
            continue
        samples.setdefault(fam, []).append(
            {"name": name, "labels": labels, "value": m.group("value")})
    # every HELP line must name a family that a TYPE line declares
    for hname, hline in helps.items():
        if hname not in families:
            errors.append(f"{where}:{hline}: HELP for {hname} has no "
                          f"matching # TYPE declaration")
    # histogram families must ship the full bucket/sum/count triple with a
    # +Inf bucket whose count equals _count
    for fam, kind in families.items():
        fam_samples = samples.get(fam, [])
        if not fam_samples:
            errors.append(f"{where}: family {fam} declared but no samples")
            continue
        if kind != "histogram":
            continue
        names = {s["name"] for s in fam_samples}
        for suffix in ("_bucket", "_sum", "_count"):
            if fam + suffix not in names:
                errors.append(f"{where}: histogram {fam} missing {suffix}")
        by_key: Dict[tuple, Dict[str, float]] = {}
        for s in fam_samples:
            key = tuple(sorted((k, v) for k, v in s["labels"].items()
                               if k != "le"))
            slot = by_key.setdefault(key, {})
            if s["name"] == fam + "_bucket" and s["labels"].get("le") == "+Inf":
                slot["inf"] = float(s["value"])
            if s["name"] == fam + "_count":
                slot["count"] = float(s["value"])
        for key, slot in by_key.items():
            if "inf" not in slot:
                errors.append(f"{where}: histogram {fam}{dict(key)} "
                              f"missing le=\"+Inf\" bucket")
            elif slot.get("count") is not None and slot["inf"] != slot["count"]:
                errors.append(f"{where}: histogram {fam}{dict(key)} +Inf "
                              f"bucket {slot['inf']} != count {slot['count']}")
    return errors


def validate_prometheus_file(path: str) -> List[str]:
    with open(path) as f:
        return validate_prometheus_text(f.read(), path)


# ---------------------------------------------------------------- CLI

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("paths", nargs="*",
                    help=".jsonl = event stream, .json = Chrome trace")
    ap.add_argument("--metrics", action="append", default=[],
                    help="Prometheus text exposition file(s)")
    ap.add_argument("--check-names", action="store_true",
                    help="additionally flag dl4j_ metric families missing "
                         "from the pinned KNOWN_DL4J_METRICS registry")
    args = ap.parse_args(argv)
    if not args.paths and not args.metrics:
        ap.error("nothing to validate")
    errors: List[str] = []
    for path in args.paths:
        if path.endswith(".jsonl"):
            errors.extend(validate_jsonl_file(path))
        else:
            errors.extend(validate_chrome_trace_file(path))
    for path in args.metrics:
        errors.extend(validate_prometheus_file(path))
        if args.check_names:
            with open(path) as f:
                errors.extend(validate_known_metrics(f.read(), path))
    for e in errors:
        print(e, file=sys.stderr)
    total = len(args.paths) + len(args.metrics)
    if not errors:
        print(f"ok: {total} file(s) validated")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
