#!/usr/bin/env python
"""Metric-name lint — THIN SHIM over the ``metric-name`` rule of the
unified static-analysis engine (``deeplearning4j_tpu/analysis/``; run
everything via ``scripts/analyze.py``).

The invariant, unchanged since PR 13: every ``dl4j_*`` metric-name
literal under ``deeplearning4j_tpu/`` must be pinned in
``KNOWN_DL4J_METRICS`` (``scripts/check_telemetry_schema.py``) so the
schema drift guard covers it BY CONSTRUCTION — "new counter, forgot
the schema" is a tier-1 failure, not a latent dashboard break.
Non-metric ``dl4j_``-prefixed literals (file-format magics) are
allowlisted in the rule's ``NON_METRIC_LITERALS``.

Importable (tier-1 runs :func:`check_repo`) and a CLI::

    python scripts/check_metric_names.py [package_root]

Exit 0 when the tree is clean; 1 with one line per violation.
"""

from __future__ import annotations

import os
import sys
from typing import List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from deeplearning4j_tpu.analysis.engine import Project  # noqa: E402
from deeplearning4j_tpu.analysis.rules.metric_names import \
    MetricNameRule  # noqa: E402

_RULE = MetricNameRule()


def check_file(path: str, rel: str) -> List[str]:
    """Violations ([] = clean) for one file."""
    project = Project(os.path.dirname(path) or ".", paths=[path],
                      rels=[rel])
    m = project.modules[0]
    if m.parse_error is not None:
        return [f"{rel}: syntax error: {m.parse_error}"]
    return [f"{f.path}:{f.line}: {f.message}"
            for f in _RULE.check(project)
            if not m.suppressed(_RULE.name, f.line)]


def check_repo(root: str) -> List[str]:
    """Lint every ``.py`` under ``<root>/deeplearning4j_tpu``. ``root``
    is the repo root (the directory containing the package)."""
    project = Project(root)
    out = []
    for f in sorted(_RULE.check(project),
                    key=lambda f: (f.path, f.line)):
        m = project.by_rel.get(f.path)
        if m is not None and m.suppressed(_RULE.name, f.line):
            continue
        out.append(f"{f.path}:{f.line}: {f.message}")
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    root = args[0] if args else _ROOT
    errors = check_repo(root)
    for e in errors:
        print(e, file=sys.stderr)
    if not errors:
        print("ok: every in-tree dl4j_ metric name is pinned")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
