#!/usr/bin/env python
"""Metric-name lint: every ``dl4j_*`` metric-name literal in the tree
must be pinned in ``KNOWN_DL4J_METRICS``.

The telemetry schema is only as strong as its coverage: PR after PR
the failure mode has been "new counter, forgot the schema" — a metric
family ships, works, and silently never gets pinned, so the drift
guard (``check_telemetry_schema.validate_known_metrics``) cannot
protect it and a later rename breaks dashboards without a test
failing. This lint closes the gap BY CONSTRUCTION: it walks every
``.py`` under ``deeplearning4j_tpu/`` and flags any string literal
shaped like a metric family name (``dl4j_`` + snake_case) that is not
in the pinned registry. Adding a metric without adding its name to
``KNOWN_DL4J_METRICS`` is now a tier-1 failure, not a latent hazard.

Non-metric ``dl4j_``-prefixed literals (file-format magics) are
explicitly allowlisted — the list is the documentation of why they are
not metrics.

Importable (a tier-1 test runs :func:`check_repo`) and a CLI::

    python scripts/check_metric_names.py [package_root]

Exit 0 when the tree is clean; 1 with one line per violation.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
from check_telemetry_schema import KNOWN_DL4J_METRICS  # noqa: E402

#: a string literal is treated as a metric family name iff it matches
#: this shape exactly (whole string): dl4j_ + snake_case words. Label
#: values, topic names (dl4j-tpu-… use dashes) and docstrings never
#: match whole.
METRIC_RE = re.compile(r"^dl4j_[a-z0-9]+(?:_[a-z0-9]+)*$")

#: dl4j_-prefixed literals that are NOT metric names (and why):
#: - dl4j_tpu_dataset_export_v1: the datasets/export.py file-format
#:   magic string; versioned data artifact, not telemetry.
NON_METRIC_LITERALS = {
    "dl4j_tpu_dataset_export_v1",
}


def check_file(path: str, rel: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [f"{rel}: syntax error: {e}"]
    errors: List[str] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)):
            continue
        s = node.value
        if not METRIC_RE.match(s) or s in NON_METRIC_LITERALS:
            continue
        if s not in KNOWN_DL4J_METRICS:
            errors.append(
                f"{rel}:{node.lineno}: dl4j_ metric name {s!r} is not "
                "pinned in KNOWN_DL4J_METRICS "
                "(scripts/check_telemetry_schema.py) — add it there in "
                "the same change, or allowlist it in "
                "NON_METRIC_LITERALS if it is not a metric")
    return errors


def check_repo(root: str) -> List[str]:
    """Lint every ``.py`` under ``<root>/deeplearning4j_tpu``. ``root``
    is the repo root (the directory containing the package)."""
    pkg = os.path.join(root, "deeplearning4j_tpu")
    errors: List[str] = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            errors.extend(check_file(path, os.path.relpath(path, root)))
    return errors


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    root = args[0] if args else os.path.dirname(_HERE)
    errors = check_repo(root)
    for e in errors:
        print(e, file=sys.stderr)
    if not errors:
        print("ok: every in-tree dl4j_ metric name is pinned")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
