"""Profile the GPT train step on the real chip and attribute MFU.

Usage: python scripts/profile_gpt.py [--trace] [--d-model N] ...
Prints tokens/sec + MFU; with --trace, aggregates device op self-times
from the captured trace into components (attention fwd/bwd, matmuls,
loss, elementwise, other) — the BASELINE.md attribution workflow.
"""
import argparse
import collections
import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/root/.cache/jax_comp_cache")

import numpy as np

PEAK_BF16 = 197e12


def aggregate_trace(log_dir):
    """Aggregate XLA-Ops-lane SELF times (events nest: jit_run > while >
    fusion — walk each lane's intervals with a stack and subtract child
    time) from the newest trace.json.gz under ``log_dir``.
    Returns [(group_name, hlo_category, total_us, count)] sorted by
    time, where group_name strips trailing .N instance suffixes."""
    import re
    paths = sorted(glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        return None
    with gzip.open(paths[-1], "rt") as f:
        ev = json.load(f)["traceEvents"]
    # device lanes: pid whose process_name metadata mentions TPU/device
    dev_pids = set()
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "")
            if "TPU" in name or "/device" in name.lower():
                dev_pids.add(e["pid"])
    lanes = collections.defaultdict(list)
    for e in ev:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            lanes[(e["pid"], e.get("tid"))].append(e)
    agg = collections.Counter()
    cnt = collections.Counter()
    cat = {}
    for lane in lanes.values():
        lane.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack = []  # [end_ts, event, child_dur]

        def pop_one():
            end0, e0, child0 = stack.pop()
            key = re.sub(r"(\.\d+)+$", "", e0["name"])
            c = e0.get("args", {}).get("hlo_category", "?")
            # whole-module/step container lanes mirror total time;
            # keep only real HLO ops (they carry hlo_category)
            if c != "?":
                agg[key] += max(e0.get("dur", 0) - child0, 0)
                cnt[key] += 1
                cat[key] = c
            if stack:
                stack[-1][2] += e0.get("dur", 0)

        for e in lane:
            while stack and e["ts"] >= stack[-1][0]:
                pop_one()
            stack.append([e["ts"] + e.get("dur", 0), e, 0])
        while stack:
            pop_one()
    return sorted(((n, cat[n], d, cnt[n]) for n, d in agg.items()),
                  key=lambda t: -t[2])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", action="store_true")
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=12)
    args = ap.parse_args()

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo.transformer import (
        gpt, gpt_train_flops_per_token)

    net = gpt(vocab_size=args.vocab, d_model=args.d_model,
              n_layers=args.layers, max_len=args.seq).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, args.vocab, (args.batch * args.steps, args.seq))
    data = DataSet(ids.astype(np.float32),
                   np.roll(ids, -1, axis=1).astype(np.float32))
    staged = net.stage_scan(data, args.batch)
    t0 = time.perf_counter()
    net.fit_scan(None, args.batch, epochs=args.epochs, staged=staged)
    print(f"compile+warmup: {time.perf_counter()-t0:.1f}s")

    dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        scores = net.fit_scan(None, args.batch, epochs=args.epochs,
                              staged=staged)
        dt = min(dt, time.perf_counter() - t0)
    tokens = args.epochs * args.steps * args.batch * args.seq
    tps = tokens / dt
    fpt = gpt_train_flops_per_token(args.vocab, args.d_model, args.layers,
                                    args.seq)
    print(f"d_model={args.d_model} L={args.layers} seq={args.seq} "
          f"b={args.batch}: {tps:.0f} tok/s  mfu={tps*fpt/PEAK_BF16:.4f}  "
          f"ms/step={1000*dt/(args.epochs*args.steps):.2f}")
    assert np.isfinite(np.asarray(scores)).all()

    if args.trace:
        from deeplearning4j_tpu.util import profiler
        log_dir = "/tmp/jax-trace-gpt-r5"
        net.fit_scan(None, args.batch, epochs=1, staged=staged)  # warm
        with profiler.trace(log_dir):
            net.fit_scan(None, args.batch, epochs=1, staged=staged)
        rows = aggregate_trace(log_dir)
        if rows is None:
            print("no trace captured")
            return
        total = sum(d for _, _, d, _ in rows)
        print(f"\ndevice self-time total: {total/1e3:.1f} ms "
              f"over {len(rows)} op groups")
        buckets = collections.Counter()
        for _, c, d, _ in rows:
            buckets[c] += d
        print("by hlo_category:")
        for b, d in buckets.most_common():
            print(f"  {b:28s} {d/1e3:8.1f} ms  {100*d/total:5.1f}%")
        print("\ntop 20 op groups:")
        for n, c, d, k in rows[:20]:
            print(f"  {d/1e3:8.1f} ms  x{k:<5d} [{c}] {n[:70]}")


if __name__ == "__main__":
    main()
