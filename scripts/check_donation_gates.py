#!/usr/bin/env python
"""Donation-gate lint — THIN SHIM over the ``donation-gate`` rule of
the unified static-analysis engine (``deeplearning4j_tpu/analysis/``;
run everything via ``scripts/analyze.py``).

The invariant, unchanged since PR 7: every ``jax.jit(...,
donate_argnums=...)`` call site must be CPU-gated, because on this
jaxlib's CPU backend donated-buffer aliasing corrupts the process heap
(the PR-1/2/6 hazard family: garbage rows in converged tables,
double-free aborts at interpreter exit, nondeterministic corruption in
whatever compiles NEXT — see ``util/jit.py``). The accepted forms:

- route the jit through ``util/jit.py cpu_safe_jit``, or
- an inline gate: the ``donate_argnums`` value conditioned on
  ``jax.default_backend() != "cpu"`` within a few lines of the call.

Importable (tier-1 runs :func:`check_repo`) and a CLI::

    python scripts/check_donation_gates.py [root]

Exit 0 when every donation site is gated; 1 with one line per
violation.
"""

from __future__ import annotations

import os
import sys
from typing import List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from deeplearning4j_tpu.analysis.engine import Project  # noqa: E402
from deeplearning4j_tpu.analysis.rules.donation_gate import \
    DonationGateRule  # noqa: E402

_RULE = DonationGateRule()


def check_file(path: str, rel: str = "") -> List[str]:
    """Violations ([] = clean) for one file."""
    rel = rel or path
    project = Project(os.path.dirname(path) or ".", paths=[path],
                      rels=[rel])
    m = project.modules[0]
    if m.parse_error is not None:
        return [f"{rel}: unparseable ({m.parse_error})"]
    return [f"{f.path}:{f.line}: {f.message}"
            for f in _RULE.check(project)
            if not m.suppressed(_RULE.name, f.line)]


def check_repo(root: str) -> List[str]:
    """Violations across every ``.py`` file under ``root``."""
    project = Project(root)
    out = []
    for f in sorted(_RULE.check(project),
                    key=lambda f: (f.path, f.line)):
        m = project.by_rel.get(f.path)
        if m is not None and m.suppressed(_RULE.name, f.line):
            continue
        out.append(f"{f.path}:{f.line}: {f.message}")
    return out


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else _ROOT
    problems = check_repo(root)
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        print(f"ok: every donation site under {root} is gated")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
