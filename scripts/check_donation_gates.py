#!/usr/bin/env python
"""Donation-gate lint: every ``jax.jit(..., donate_argnums=...)`` call
site must be CPU-gated.

On this jaxlib's CPU backend, donated-buffer aliasing corrupts the
process heap (the PR-1/2/6 hazard family: garbage rows in converged
tables, double-free aborts at interpreter exit, nondeterministic
corruption in whatever compiles NEXT — see ``util/jit.py``). The fix
discipline is one of:

- route the jit through ``util/jit.py cpu_safe_jit`` (module-level
  decorators — donation dropped lazily when the backend is CPU), or
- an inline gate at the call site: the ``donate_argnums`` value is
  conditioned on ``jax.default_backend() != "cpu"`` within a few lines
  of the ``jax.jit`` call (the pattern every nn/parallel site uses).

This lint enforces the discipline STATICALLY so the w2v heap-corruption
class cannot recur: it AST-walks every tracked ``.py`` file for
``jax.jit`` calls carrying ``donate_argnums`` and fails unless the
surrounding window contains a backend gate. ``cpu_safe_jit`` sites
don't match (they are not ``jax.jit`` calls) and ``util/jit.py`` itself
is the one allowed raw site.

Importable (a tier-1 test runs :func:`check_repo`) and a CLI::

    python scripts/check_donation_gates.py [root]

Exit 0 when every donation site is gated; 1 with one line per
violation.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

#: files allowed to call jax.jit(donate_argnums=...) ungated — the gate
#: implementation itself.
ALLOWED_FILES = ("util/jit.py",)

#: how many lines around the call may carry the inline gate. The gate
#: conventionally sits on the ``donate = ... if backend != "cpu"`` line
#: directly above the jit call (or in the same statement).
GATE_WINDOW_BEFORE = 12
GATE_WINDOW_AFTER = 2

GATE_TOKEN = "default_backend()"
CPU_TOKEN = '"cpu"'
CPU_TOKEN_SQ = "'cpu'"


def _is_jax_jit(node: ast.Call) -> bool:
    """Match ``jax.jit(...)`` (the module-qualified spelling every
    in-tree site uses; a bare ``jit`` import would rename the hazard,
    which reviewers catch — the lint pins the dominant form)."""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "jit"
            and isinstance(f.value, ast.Name) and f.value.id == "jax")


def _donates(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            # a literal empty tuple donates nothing — not a hazard
            if isinstance(kw.value, ast.Tuple) and not kw.value.elts:
                return False
            return True
    return False


def _gated(lines: List[str], lineno: int) -> bool:
    """True when the inline CPU gate appears in the window around the
    1-based ``lineno``."""
    lo = max(0, lineno - 1 - GATE_WINDOW_BEFORE)
    hi = min(len(lines), lineno + GATE_WINDOW_AFTER)
    window = "\n".join(lines[lo:hi])
    return GATE_TOKEN in window and (CPU_TOKEN in window
                                     or CPU_TOKEN_SQ in window)


def check_file(path: str, rel: str = "") -> List[str]:
    """Violations ([] = clean) for one file."""
    rel = rel or path
    if any(rel.endswith(a) for a in ALLOWED_FILES):
        return []
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{rel}: unparseable ({e})"]
    lines = src.splitlines()
    problems = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jax_jit(node) \
                and _donates(node):
            if not _gated(lines, node.lineno):
                problems.append(
                    f"{rel}:{node.lineno}: jax.jit(donate_argnums=...) "
                    "without a CPU gate — route through util/jit.py "
                    "cpu_safe_jit or condition donation on "
                    'jax.default_backend() != "cpu" at the call site '
                    "(CPU donation aliasing corrupts the heap)")
    return problems


def _tracked_py_files(root: str) -> List[Tuple[str, str]]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in (".git", "__pycache__", ".pytest_cache",
                                    "node_modules")]
        for name in filenames:
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                out.append((path, os.path.relpath(path, root)))
    return sorted(out)


def check_repo(root: str) -> List[str]:
    """Violations across every ``.py`` file under ``root``."""
    problems: List[str] = []
    for path, rel in _tracked_py_files(root):
        problems.extend(check_file(path, rel))
    return problems


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check_repo(root)
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        print(f"ok: every donation site under {root} is gated")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
