"""Ablate flash-attention forward kernel costs on the real chip.

Variants (non-causal, 16k, b1 h8 d128): full online-softmax kernel vs
kernels with pieces removed — isolates VPU pass costs (max chain, exp,
astype) from MXU/DMA floor. Timing: best of 3 repeats x 8 iters.
"""
import functools, time
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def timeit(fn, iters=8, repeats=3):
    float(fn())
    best = 1e9
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn()
        float(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def make_kernel(mode):
    def kern(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, bq, bk):
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(kj == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mode == "matmul_only":
            acc_ref[:] += jax.lax.dot_general(
                s.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif mode == "exp_only":  # no max/l chain
            p = jnp.exp(s)
            acc_ref[:] += jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif mode == "exp_sum":  # + denominator, still no max
            p = jnp.exp(s)
            l_ref[:, :1] += jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] += jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif mode == "full":
            m_prev = m_ref[:, :1]
            l_prev = l_ref[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        elif mode == "full_lane0":  # partial-lane m/l stores
            m_prev = m_ref[:, :1]
            l_prev = l_ref[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:, :1] = m_new
            l_ref[:, :1] = l_new

        @pl.when(kj == nk - 1)
        def _final():
            o_ref[0] = acc_ref[:].astype(o_ref.dtype)

    return kern


def run(mode, bq, bk, t=16384, bh=8, d=128):
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (bh, t, d),
                                 jnp.bfloat16) * 0.1 for i in range(3))
    kern = functools.partial(make_kernel(mode), bq=bq, bk=bk)
    vmem = dict(memory_space=pltpu.VMEM)
    f = pl.pallas_call(
        kern,
        grid=(bh, t // bq, t // bk),
        in_specs=[pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **vmem),
                  pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **vmem),
                  pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **vmem)],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **vmem),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )
    fj = jax.jit(lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32)))
    dt = timeit(lambda: fj(q, k, v))
    fl = 4 * bh * t * t * d / dt
    steps = bh * (t // bq) * (t // bk)
    print(f"{mode:12s} bq={bq:4d} bk={bk:4d}: {dt*1e3:6.2f}ms "
          f"{fl/1e12:5.1f} TF/s  {dt/steps*1e6:5.2f}us/step")


if __name__ == "__main__" and __import__("sys").argv[-1] != "causal":
    for mode in ("matmul_only", "exp_only", "exp_sum", "full", "full_lane0"):
        run(mode, 512, 1024)
    for bq, bk in ((512, 2048), (1024, 1024), (256, 1024)):
        run("full", bq, bk)


def make_causal_kernel(mode):
    def kern(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, d_ref, *, bq, bk):
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when((qi == 0) & (kj == 0))
        def _initD():
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            d_ref[:] = rows - cols

        @pl.when(kj == 0)
        def _init():
            m_ref[:, :1] = jnp.full((bq, 1), -1e30, jnp.float32)
            l_ref[:, :1] = jnp.zeros((bq, 1), jnp.float32)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        live = kj * bk <= qi * bq + bq - 1

        def _step():
            s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if mode == "iota":
                rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
                ok = (qi * bq + rows) >= (kj * bk + cols)
            else:
                ok = d_ref[:] >= kj * bk - qi * bq
            s = jnp.where(ok, s, -1e30)
            m_prev = m_ref[:, :1]
            l_prev = l_ref[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:, :1] = m_new
            l_ref[:, :1] = l_new

        pl.when(live)(_step)

        @pl.when(kj == nk - 1)
        def _final():
            o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)

    return kern


def run_causal(mode, bq, bk, t=16384, bh=8, d=128):
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (bh, t, d),
                                 jnp.bfloat16) * 0.1 for i in range(3))
    kern = functools.partial(make_causal_kernel(mode), bq=bq, bk=bk)
    vmem = dict(memory_space=pltpu.VMEM)
    f = pl.pallas_call(
        kern,
        grid=(bh, t // bq, t // bk),
        in_specs=[pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **vmem),
                  pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **vmem),
                  pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), **vmem)],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), **vmem),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, bk), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )
    fj = jax.jit(lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32)))
    dt = timeit(lambda: fj(q, k, v))
    fl = 4 * bh * t * t * d / 2 / dt
    print(f"causal/{mode:8s} bq={bq:4d} bk={bk:4d}: {dt*1e3:6.2f}ms {fl/1e12:5.1f} TF/s")


if __name__ == "__main__" and __import__("sys").argv[-1] == "causal":
    for mode in ("iota", "dscratch"):
        for bq, bk in ((512, 1024), (512, 512), (1024, 512), (2048, 512), (1024, 1024)):
            run_causal(mode, bq, bk)
