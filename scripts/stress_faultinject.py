#!/usr/bin/env python
"""Stress the fault-injection suite: rerun it K times with rotating
seeds and fail on ANY nondeterminism.

Order-dependent flakes (the w2v trained-vector family PR 6 root-caused
to CPU donation aliasing) present as tests whose outcome depends on
what ran before them — a single green run proves nothing. This tool
pins the determinism contract the ``faultinject`` marker promises
("a failing test replays bit-identically") two ways:

**Full mode (CLI)** — spawn ``pytest -m faultinject`` in a FRESH
process K times (subprocess-per-run is mandatory: fork-after-jax is
unreliable on this box, and a fresh interpreter is the only honest
replay), rotating ``PYTHONHASHSEED`` / ``DL4J_TPU_STRESS_SEED`` across
runs. Any test whose outcome differs between runs is nondeterministic
→ exit 1 (a test that fails identically every run is a deterministic
failure — also exit 1, but reported as such)::

    python scripts/stress_faultinject.py --runs 3 [--seed-base 0]
        [-m faultinject] [--pytest-args ...]

**Quick mode (importable — wired into tier-1)** — :func:`quick_check`
first runs SECTION 0: the unified static-analysis engine
(``scripts/analyze.py --json`` semantics — every rule, repo-wide,
suppressions + baseline applied) and FAILS FAST on any new finding
before a single chaos phase spends time — a lock-order inversion or an
untyped wire raise is cheaper to report from the AST than to hunt in a
drill log. Then it replays the in-process deterministic injector
battery (seeded NaN/raise schedules, flaky-broker schedules,
torn-write counting, replica/model poison sequences, burst-kill
windows, mesh-shrink drills, and the composed ChaosSchedule event
clock, the prefix-cache refcount/COW/eviction accounting drill, and
the slice-kill / slice-drill schedules, the quantized-pool ×
prefix-cache accounting drill, the speculative-decoding dual-lane
(draft + target) accounting drill, the wire-v4 torn-frame /
reassembly drill, and the host-tier (KV tiering) swap /
budget-pressure / reclaimer-chain accounting drill — sections 1–13)
twice per seed
across rotating seeds and compares the full event logs bit-for-bit.
It runs in milliseconds with no subprocess and no jax compute, so the
tier-1 sweep carries it on every run; the full mode is the pre-merge /
CI deep check.

**Chaos mode (CLI)** — ``--chaos`` runs the COMPOSED drill
(:func:`deeplearning4j_tpu.faultinject.chaos.run_chaos_drill` — every
injector on one seeded event clock against a live 3-endpoint fleet)
twice per rotating seed in fresh subprocesses, failing on any global
invariant violation (lost/duplicated tokens, stranded futures, leaked
KV blocks, unhealthy fleet) or ANY outcome drift between the two
replays of one seed::

    python scripts/stress_faultinject.py --chaos --runs 3

**Hibernation mode (CLI)** — ``--hibernation`` runs the
SESSION-HIBERNATION drill
(:func:`deeplearning4j_tpu.faultinject.chaos.run_hibernation_drill` —
hibernate N sessions into the host KV tier, kill the seeded endpoint,
resume every session on the survivors down the host → shipped-blocks
→ journaled-prefix exactness ladder, the second half under
``HostTierPressure``) twice per rotating seed in fresh subprocesses,
failing on any invariant violation (token mismatches, dup/gap
offsets, leaked blocks on either tier, stranded handles) or outcome
drift between replays::

    python scripts/stress_faultinject.py --hibernation --runs 3
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
from typing import Dict, List

# runnable from anywhere: the repo root (the package's parent) must be
# importable when invoked as a script rather than through pytest
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# ----------------------------------------------------------- quick mode


def _scenario_log(seed: int) -> str:
    """One deterministic pass over the injector battery; returns the
    full event log. The determinism contract: same seed → identical
    log, bit for bit."""
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.faultinject import (BurstKill, ChipFailure,
                                                FailingDataSetIterator,
                                                FlakyBroker, InjectedFault,
                                                MeshShrink, ModelPoison,
                                                ReplicaPoison, TornWrites)
    from deeplearning4j_tpu.streaming.broker import InMemoryBroker

    events: List[str] = []

    # 1) seeded NaN/raise schedules across resets
    rng = np.random.default_rng(seed)
    ds = DataSet(rng.standard_normal((8, 3)).astype(np.float32),
                 np.tile(np.eye(2, dtype=np.float32), (4, 1)))
    it = FailingDataSetIterator(ListDataSetIterator(ds, batch_size=2),
                                nan_at=(seed % 3,), raise_at=(5,),
                                p_nan=0.3, seed=seed)
    for epoch in range(2):
        it.reset()
        while it.has_next():
            try:
                batch = it.next()
            except InjectedFault as e:
                events.append(f"iter raise: {e}")
                continue
            nan = bool(np.isnan(np.asarray(batch.features)).any())
            events.append(f"iter batch nan={nan}")
    events.append(f"iter injected nan={it.injected_nan} "
                  f"raise={it.injected_raise}")

    # 2) flaky broker schedules + seeded random failures
    broker = FlakyBroker(InMemoryBroker(), fail_publishes=(1,),
                         fail_consumes=(0,), p_fail=0.25, seed=seed)
    for i in range(6):
        try:
            broker.publish("t", f"m{i}".encode())
            events.append(f"pub {i} ok")
        except ConnectionError as e:
            events.append(f"pub {i} fail: {e}")
    for i in range(8):
        try:
            msg = broker.consume("t", timeout=0)
            events.append(f"con {i} -> "
                          f"{msg.decode() if msg is not None else None}")
        except ConnectionError as e:
            events.append(f"con {i} fail: {e}")
    events.append(f"broker faults={broker.faults_injected}")

    # 3) torn-write crash scheduling (counted os.replace/rename installs)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        with TornWrites(crash_on_call=2, path_substr="unit") as torn:
            for i in range(3):
                tmp = os.path.join(td, f"t{i}")
                dst = os.path.join(td, f"unit{i}")
                with open(tmp, "w") as f:
                    f.write("x")
                try:
                    os.replace(tmp, dst)
                    events.append(f"install {i} ok")
                except InjectedFault:
                    # log the index, not the message — the tempdir path
                    # inside it is fresh per run by design
                    events.append(f"install {i} crash")
        events.append(f"torn calls={torn.calls}")

    # 4) replica/model poison hit sequences
    rp = ReplicaPoison(replica=1, failures=2)
    for i in range(4):
        for replica in (0, 1):
            try:
                rp(replica, (1, 3))
                events.append(f"rp {i}/{replica} ok")
            except InjectedFault:
                events.append(f"rp {i}/{replica} hit")
    mp = ModelPoison("m", failures=3)
    for i in range(5):
        for model in ("m", "other"):
            try:
                mp(i % 2, (1, 3), model)
                events.append(f"mp {i}/{model} ok")
            except InjectedFault:
                events.append(f"mp {i}/{model} hit")
    events.append(f"rp hits={rp.hits} mp hits={mp.hits}")

    # 5) kill-mid-burst schedules (continuous decode scheduler seam):
    # seeded window, lane-scoped filtering — the injector the
    # tests/test_continuous.py kill-mid-burst scenario arms; here its
    # hit schedule itself is pinned deterministic
    bk = BurstKill(after=seed % 3, failures=2)
    bk_lane = BurstKill(after=0, failures=2, lane=("m", 1))
    for i in range(6):
        for lane in ((None, None), ("m", 1)):
            for inj in (bk, bk_lane):
                try:
                    inj(lane, i)
                    events.append(f"bk {i}/{lane} ok")
                except InjectedFault:
                    events.append(f"bk {i}/{lane} hit")
    events.append(f"bk hits={bk.hits} lane_hits={bk_lane.hits}")

    # 6) mesh-shrink drill schedule (the MeshShrink/ChipFailure seam
    # tests/test_mesh_plane.py arms against a real training loop): the
    # failure STEP and the seeded SURVIVOR SET are pinned deterministic
    # here — so the full drill's kill → checkpoint fallback → resume-on-
    # smaller-mesh sequence replays identically across stress reruns
    ms = MeshShrink(fail_at_step=seed % 4 + 1, survivors=4, total=8,
                    seed=seed)
    for i in range(8):
        try:
            idx = ms.step()
            events.append(f"ms step {idx} ok")
        except ChipFailure as e:
            events.append(f"ms step {i} chipfail survivors="
                          f"{list(e.survivor_ids)}")
    events.append(f"ms survivors={list(ms.survivor_ids())} "
                  f"fired={ms.fired} seen={ms.steps_seen}")

    # 7) composed chaos schedule (faultinject/chaos.py ChaosSchedule —
    # the seeded event clock run_chaos_drill replays against a live
    # fleet): the schedule ITSELF is pinned deterministic here (same
    # seed ⇒ identical ticks/actions/targets/heals, and wedge
    # injector state transitions replay); the full live drill runs in
    # fresh subprocesses via `--chaos` (outcome-drift contract)
    from deeplearning4j_tpu.faultinject import ChaosSchedule
    for n_events, n_eps in ((4, 3), (seed % 5 + 2, 3)):
        cs = ChaosSchedule(seed, n_events=n_events, n_endpoints=n_eps)
        events.append(f"chaos[{n_events}x{n_eps}]={cs.signature()}")

    # 8) prefix-cache refcount/COW/eviction accounting (serving/
    # prefixcache.py over the refcounted paged pool): seeded
    # interleavings of admit (match + share + alloc, COW-releasing a
    # matched partial), retire (insert-then-free), kill (free without
    # insert — the burst-kill shape) and eviction pressure on a tiny
    # pool — the free-list order, refcounts and cached-node counts
    # must replay bit-identically, the drill must drain to fully-free
    # with ZERO leaked blocks, and a double free must raise (caught
    # here, logged as part of the pinned schedule)
    from deeplearning4j_tpu.nn.kvpool import PagedKVCachePool
    from deeplearning4j_tpu.serving.prefixcache import PrefixCache
    rng8 = np.random.default_rng(seed * 31 + 5)
    pool = PagedKVCachePool(17, 2, num_layers=1, num_heads=1, head_dim=2,
                            name=f"qc{seed}")
    cache = PrefixCache(pool)
    lane = ("m", 1)
    live: List[tuple] = []
    for i in range(28):
        op = int(rng8.integers(0, 4))
        if op == 0:
            t = int(rng8.integers(3, 9))
            toks = [int(x) for x in rng8.integers(0, 4, t)]
            m, full, part = cache.match(lane, toks)
            got = pool.alloc(pool.blocks_for(t) - len(full))
            if got is None:
                pool.free_blocks(full
                                 + ([part] if part is not None else []))
                events.append(f"pc {i} admit-short m={m}")
                continue
            if part is not None:
                # COW: the fresh block stands in, the shared ref drops
                blocks = full + got
                pool.free_blocks([part])
            else:
                blocks = full + got
            live.append((blocks, toks))
            events.append(f"pc {i} admit m={m} blocks={blocks}")
        elif op == 1 and live:
            blocks, toks = live.pop(int(rng8.integers(0, len(live))))
            pinned = cache.insert(lane, toks, blocks)
            pool.free_blocks(blocks)
            events.append(f"pc {i} retire pinned={pinned} "
                          f"free={pool.free_count}")
        elif op == 2 and live:
            blocks, _ = live.pop(int(rng8.integers(0, len(live))))
            pool.free_blocks(blocks)
            events.append(f"pc {i} kill free={pool.free_count}")
        else:
            freed = cache.reclaim(int(rng8.integers(1, 4)))
            events.append(f"pc {i} evict freed={freed} "
                          f"cached={cache.cached_blocks()}")
    for blocks, _ in live:
        pool.free_blocks(blocks)
    cache.clear()
    try:
        pool.free_blocks([1])
        events.append("pc double-free MISSED")
    except RuntimeError:
        events.append("pc double-free caught")
    events.append(f"pc final free={pool.free_count}/{pool.total_blocks} "
                  f"shared={pool.shared_count()} "
                  f"leaked={pool.total_blocks - pool.free_count}")

    # 9) slice-kill schedule determinism (faultinject.SliceKill — the
    # kill-a-chip-inside-a-live-slice injector LocalFleet.kill_chip
    # arms): the seeded victim chip, the survivor set and the failure
    # tick must replay bit-identically, and a dead chip NEVER heals —
    # every dispatch from the tick on fails (the reason recovery is an
    # elastic rebuild, not a retry). The slice-drill ChaosSchedule
    # (slice_kill/partition_hb/wedge action set) is pinned alongside,
    # the same way section 7 pins the main drill's clock.
    from deeplearning4j_tpu.faultinject import SliceKill
    from deeplearning4j_tpu.faultinject.chaos import SLICE_ACTIONS
    sk = SliceKill([0, 1, 2, 3], seed=seed, fail_at=seed % 3 + 1)
    for i in range(6):
        try:
            sk(("lane", None), i)
            events.append(f"sk {i} ok")
        except ChipFailure as e:
            events.append(f"sk {i} chipfail "
                          f"survivors={list(e.survivor_ids)}")
    events.append(f"sk victim={sk.victim} hits={sk.hits} "
                  f"devices={list(sk.devices)}")
    for n_events in (3, seed % 4 + 2):
        cs = ChaosSchedule(seed, n_events=n_events, n_endpoints=2,
                           actions=SLICE_ACTIONS)
        events.append(f"slice_chaos[{n_events}]={cs.signature()}")

    # 10) quantized-KV × prefix-cache interop (nn/quantize.py + the
    # kvpool quant variant): the section-8 admit/retire/kill/evict
    # battery replayed on a TINY INT8 pool — block ids, refcounts,
    # shared/COW accounting and the free list must replay
    # bit-identically (scale arrays ride the same block addressing, so
    # accounting is the whole sharing contract), the pool must drain
    # to fully-free with zero leaks, a double free must raise, and the
    # quantized layout facts are pinned: a quantized spec NEVER
    # matches the fp32 spec (a quantized lane cannot silently share an
    # fp32 pool) and its per-block bytes land in the 2-4x compression
    # band that buys the extra decode rows.
    qpool = PagedKVCachePool(17, 2, num_layers=1, num_heads=1, head_dim=8,
                             name=f"qq{seed}", quant="int8")
    fpool = PagedKVCachePool(3, 2, num_layers=1, num_heads=1, head_dim=8,
                             name=f"qf{seed}")
    events.append(f"qkv spec_differs={qpool.spec != fpool.spec} "
                  f"ratio={fpool.block_bytes() / qpool.block_bytes():.3f} "
                  f"scales={sorted(qpool.layers[0])}")
    qcache = PrefixCache(qpool)
    rngA = np.random.default_rng(seed * 131 + 7)
    qlive: List[tuple] = []
    for i in range(24):
        op = int(rngA.integers(0, 4))
        if op == 0:
            t = int(rngA.integers(3, 9))
            toks = [int(x) for x in rngA.integers(0, 4, t)]
            m, full, part = qcache.match(lane, toks)
            got = qpool.alloc(qpool.blocks_for(t) - len(full))
            if got is None:
                qpool.free_blocks(full
                                  + ([part] if part is not None else []))
                events.append(f"qkv {i} admit-short m={m}")
                continue
            if part is not None:
                # COW on a quantized pool: the fresh block stands in
                # (its scale rows clone with it on device), the shared
                # reference drops — accounting identical to fp32
                blocks = full + got
                qpool.free_blocks([part])
                events.append(f"qkv {i} cow m={m}")
            else:
                blocks = full + got
            qlive.append((blocks, toks))
            events.append(f"qkv {i} admit m={m} blocks={blocks}")
        elif op == 1 and qlive:
            blocks, toks = qlive.pop(int(rngA.integers(0, len(qlive))))
            pinned = qcache.insert(lane, toks, blocks)
            qpool.free_blocks(blocks)
            events.append(f"qkv {i} retire pinned={pinned} "
                          f"free={qpool.free_count}")
        elif op == 2 and qlive:
            blocks, _ = qlive.pop(int(rngA.integers(0, len(qlive))))
            qpool.free_blocks(blocks)
            events.append(f"qkv {i} kill free={qpool.free_count}")
        else:
            freed = qcache.reclaim(int(rngA.integers(1, 4)))
            events.append(f"qkv {i} evict freed={freed} "
                          f"cached={qcache.cached_blocks()}")
    for blocks, _ in qlive:
        qpool.free_blocks(blocks)
    qcache.clear()
    try:
        qpool.free_blocks([1])
        events.append("qkv double-free MISSED")
    except RuntimeError:
        events.append("qkv double-free caught")
    events.append(f"qkv final free={qpool.free_count}/{qpool.total_blocks} "
                  f"shared={qpool.shared_count()} "
                  f"leaked={qpool.total_blocks - qpool.free_count}")

    # 11) speculative-decoding dual-lane accounting (the PR-17
    # scheduler's contract): every stream holds blocks on TWO pools —
    # the target lane and the draft lane — and every lifecycle edge
    # (admit, spec-round growth, preempt, rollback, burst-kill, retire)
    # must free or carry BOTH sides in lockstep. A draft-lane leak is
    # invisible to the target pool's audit, which is why the draft pool
    # is dedicated; this drill replays a seeded battery of those edges
    # and pins that both pools drain to fully-free, that a draft-side
    # double free raises, and that an admit whose draft alloc falls
    # short degrades to a DRAFT-LESS row (spec fallback) instead of
    # failing the admission — speculation is an accelerator, never a
    # correctness dependency.
    tpool = PagedKVCachePool(13, 4, num_layers=1, num_heads=1, head_dim=8,
                             name=f"spec_t{seed}")
    dpool = PagedKVCachePool(9, 4, num_layers=1, num_heads=1, head_dim=8,
                             name=f"spec_d{seed}", quant="int8")
    rngS = np.random.default_rng(seed * 157 + 11)
    k_spec = int(rngS.integers(2, 5))
    # live rows: (target_blocks, draft_blocks or [], pos)
    slive: List[list] = []
    for i in range(28):
        op = int(rngS.integers(0, 5))
        if op == 0:
            t = int(rngS.integers(2, 10))
            tb = tpool.alloc(tpool.blocks_for(t))
            if tb is None:
                events.append(f"spec {i} admit-short")
                continue
            db = dpool.alloc(dpool.blocks_for(t))
            if db is None:
                # draft-less admission: the row serves on plain bursts
                events.append(f"spec {i} admit draftless pos={t}")
                slive.append([tb, [], t])
            else:
                events.append(f"spec {i} admit tb={tb} db={db}")
                slive.append([tb, db, t])
        elif op == 1 and slive:
            # spec round: grow BOTH lanes to pos + k_spec + 1, accept a
            # seeded prefix, roll pos forward (rollback of rejected
            # positions is pure pos bookkeeping — stale KV is
            # overwritten by the next round's writes, never freed)
            row = slive[int(rngS.integers(0, len(slive)))]
            tb, db, pos = row
            if not db:
                events.append(f"spec {i} round skipped (draftless)")
                continue
            horizon = pos + k_spec + 1
            ok = True
            for pool_, blocks in ((tpool, tb), (dpool, db)):
                delta = pool_.blocks_for(horizon) - len(blocks)
                if delta > 0:
                    got = pool_.alloc(delta)
                    if got is None:
                        ok = False
                        break
                    blocks.extend(got)
            if not ok:
                events.append(f"spec {i} grow-short pos={pos}")
                continue
            a = int(rngS.integers(0, k_spec + 1))
            row[2] = pos + a + 1
            events.append(f"spec {i} round a={a} pos={row[2]} "
                          f"tb={len(tb)} db={len(db)}")
        elif op == 2 and slive:
            # preempt: target KV may ship or drop; the draft lane NEVER
            # ships (it re-prefills on resume) — both freed here
            tb, db, pos = slive.pop(int(rngS.integers(0, len(slive))))
            tpool.free_blocks(tb)
            if db:
                dpool.free_blocks(db)
            events.append(f"spec {i} preempt tfree={tpool.free_count} "
                          f"dfree={dpool.free_count}")
        elif op == 3 and slive:
            # burst-kill: every row's BOTH lanes freed
            for tb, db, _ in slive:
                tpool.free_blocks(tb)
                if db:
                    dpool.free_blocks(db)
            slive.clear()
            events.append(f"spec {i} burstkill tfree={tpool.free_count} "
                          f"dfree={dpool.free_count}")
        elif slive:
            tb, db, pos = slive.pop(int(rngS.integers(0, len(slive))))
            tpool.free_blocks(tb)
            if db:
                dpool.free_blocks(db)
            events.append(f"spec {i} retire pos={pos}")
    for tb, db, _ in slive:
        tpool.free_blocks(tb)
        if db:
            dpool.free_blocks(db)
    try:
        dpool.free_blocks([1])
        events.append("spec draft double-free MISSED")
    except RuntimeError:
        events.append("spec draft double-free caught")
    events.append(f"spec final t={tpool.free_count}/{tpool.total_blocks} "
                  f"d={dpool.free_count}/{dpool.total_blocks} "
                  f"tleak={tpool.total_blocks - tpool.free_count} "
                  f"dleak={dpool.total_blocks - dpool.free_count}")

    # 12) wire-v4 torn-frame drill (the PR-18 data plane's contract):
    # the zero-copy binary framing must fail TYPED on ANY truncation —
    # a half-written frame (torn write, worker killed mid-publish, cut
    # connection) surfaces as WireFrameError, never a garbled tensor —
    # while a fragmented-but-complete delivery reassembles byte-exact,
    # including the shipped-KV disagg segments, and a coalesced
    # token-chunk frame decodes back to every stream's exact delta.
    from deeplearning4j_tpu.serving import wire
    rngW = np.random.default_rng(seed * 211 + 5)
    kv = rngW.standard_normal((2, 2, 4, 8)).astype(np.float32)
    ids = rngW.integers(0, 997,
                        (1, int(rngW.integers(3, 9)))).astype(np.int32)
    frame = wire.pack_request_v4(f"w{seed}", "rsp", wire.KIND_GENERATE,
                                 ids, gen={"kv": True}, tensors={"kv": kv})
    events.append(f"wire frame len={len(frame)}")
    for c in sorted(int(c) for c in rngW.integers(0, len(frame), 6)):
        try:
            wire.unpack_frame_v4(frame[:c])
            events.append(f"wire cut {c} MISSED")
        except wire.WireFrameError:
            events.append(f"wire cut {c} typed")
    try:
        wire.unpack_frame_v4(b"\x00\x00" + frame[2:])
        events.append("wire bad-magic MISSED")
    except wire.WireFrameError:
        events.append("wire bad-magic caught")
    parts, off = [], 0
    while off < len(frame):
        n = int(rngW.integers(1, max(2, len(frame) // 3)))
        parts.append(frame[off:off + n])
        off += n
    meta, x, segs = wire.unpack_request_any(b"".join(parts))
    events.append(f"wire reassembled frags={len(parts)} "
                  f"ids={bool(np.array_equal(x, ids))} "
                  f"kv_byte_exact={segs['kv'].tobytes() == kv.tobytes()} "
                  f"v={meta['v']}")
    entries = [(f"s{j}", int(rngW.integers(0, 50)),
                rngW.integers(0, 11,
                              int(rngW.integers(1, 5))).astype(np.int64))
               for j in range(3)]
    evs = wire.decode_reply_events(wire.pack_chunks_v4(entries))
    exact = all(ev["id"] == c and ev["off"] == o and
                list(ev["tokens"]) == [int(t) for t in toks]
                for ev, (c, o, toks) in zip(evs, entries))
    events.append(f"wire coalesced n={len(evs)} exact={exact}")

    # 13) host-tier (KV tiering) accounting drill: a seeded battery of
    # swap_out / swap_in / host_export→host_insert (the shipped-blocks
    # round trip) / free_host edges on a tiny tiered pool, with a
    # deterministic HostTierPressure window mid-drill (budget squeezed
    # to 0 ⇒ every demotion and landing-dock insert REFUSES and the
    # caller takes its pre-tier fallback — the exactness ladder's
    # degrade path), plus the reclaimer CHAIN consulted in
    # registration order (demote-to-host before drop — the order the
    # prefix cache registers). Both tiers must drain to empty, a
    # host-side double free must raise, and the whole log replays
    # bit-for-bit.
    import zlib

    from deeplearning4j_tpu.faultinject import HostTierPressure
    hpool = PagedKVCachePool(11, 2, num_layers=1, num_heads=1, head_dim=2,
                             name=f"ht{seed}", host_blocks=5)
    rngH = np.random.default_rng(seed * 31 + 13)
    hlive: List[list] = []      # device rows
    hparked: List[list] = []    # host handle batches
    squeeze = HostTierPressure(hpool, budget=0)
    for i in range(30):
        if i == 14:
            squeeze.squeeze()
            events.append(f"ht {i} squeeze budget={hpool.host_budget()}")
        if i == 20:
            squeeze.heal()
            events.append(f"ht {i} heal budget={hpool.host_budget()}")
        op = int(rngH.integers(0, 5))
        if op == 0:
            got = hpool.alloc(int(rngH.integers(1, 4)))
            if got is None:
                events.append(f"ht {i} admit-short")
            else:
                hlive.append(got)
                events.append(f"ht {i} admit blocks={got}")
        elif op == 1 and hlive:
            blocks = hlive.pop(int(rngH.integers(0, len(hlive))))
            hs = hpool.swap_out(blocks, owner="lm@v1")
            if hs is None:
                hlive.append(blocks)  # refusal: caller keeps device refs
                events.append(f"ht {i} swapout-refused "
                              f"used={hpool.host_blocks_used()}")
            else:
                hparked.append(hs)
                events.append(f"ht {i} swapout handles={hs} "
                              f"free={hpool.free_count}")
        elif op == 2 and hparked:
            hs = hparked.pop(int(rngH.integers(0, len(hparked))))
            got = hpool.swap_in(hs, owner="lm@v1")
            if got is None:
                hparked.append(hs)  # handles stay valid on refusal
                events.append(f"ht {i} swapin-short")
            else:
                hlive.append(got)
                events.append(f"ht {i} swapin blocks={got} "
                              f"used={hpool.host_blocks_used()}")
        elif op == 3 and hparked:
            hs = hparked[int(rngH.integers(0, len(hparked)))]
            shipped = hpool.host_export(hs)
            crc = zlib.crc32(b"".join(
                v.tobytes() for b in shipped
                for _, v in sorted(b.items())))
            ins = hpool.host_insert(shipped, owner="ship")
            if ins is None:
                events.append(f"ht {i} insert-refused crc={crc}")
            else:
                back = zlib.crc32(b"".join(
                    v.tobytes() for b in hpool.host_export(ins)
                    for _, v in sorted(b.items())))
                hparked.append(ins)
                events.append(f"ht {i} shipped crc={crc} "
                              f"byte_exact={crc == back} "
                              f"used={hpool.host_blocks_used()}")
        elif hparked:
            hs = hparked.pop(int(rngH.integers(0, len(hparked))))
            hpool.free_host(hs, owner="lm@v1")
            events.append(f"ht {i} freehost "
                          f"used={hpool.host_blocks_used()}")
    squeeze.heal()
    for blocks in hlive:
        hpool.free_blocks(blocks)
    doomed = list(hparked)
    for hs in doomed:
        hpool.free_host(hs)
    try:
        if doomed and doomed[0]:
            hpool.free_host(doomed[0])
            events.append("ht double-free MISSED")
        else:
            raise RuntimeError("no parked handles to double-free")
    except RuntimeError:
        events.append("ht double-free caught")
    events.append(f"ht final free={hpool.free_count}/{hpool.total_blocks} "
                  f"host_used={hpool.host_blocks_used()}")

    # reclaimer-chain order: exhaustion consults the seams in
    # registration order (demote first, drop second) and stops as soon
    # as the free list covers the request
    cpool = PagedKVCachePool(7, 2, num_layers=1, num_heads=1, head_dim=2,
                             name=f"hc{seed}")
    held = cpool.alloc(cpool.free_count)
    chain: List[str] = []

    def demote(n_short):
        chain.append(f"demote({n_short})")
        if held:
            cpool.free_blocks([held.pop()])
            return 1
        return 0

    def drop(n_short):
        chain.append(f"drop({n_short})")
        freed = len(held)
        if held:
            cpool.free_blocks(held)
            held.clear()
        return freed

    cpool.register_reclaimer(demote)
    cpool.register_reclaimer(drop)
    got1 = cpool.alloc(1)
    got3 = cpool.alloc(3)
    events.append(f"ht chain={chain} got1={got1} got3={got3}")
    cpool.free_blocks((got1 or []) + (got3 or []))
    events.append(f"ht chain final free={cpool.free_count}"
                  f"/{cpool.total_blocks}")
    return "\n".join(events)


def analysis_section() -> List[str]:
    """SECTION 0 — static analysis, fail fast: run the unified engine
    (``deeplearning4j_tpu/analysis``, same report ``scripts/analyze.py
    --json`` emits) repo-wide and surface every NEW finding
    (suppressions and the committed baseline already applied). A
    finding here aborts quick_check before any chaos phase runs."""
    from deeplearning4j_tpu.analysis import analyze
    report = analyze(_ROOT)
    return [f"analysis: {f.render()}" for f in report.new]


def bench_trend_section() -> List[str]:
    """SECTION 0b — the perf-trend gate's schema contract: run
    ``scripts/bench_trend.py --check`` in-process (committed
    ``BENCH_r*.json`` rounds parse + validate, and the gate-logic
    fixture still flags an injected regression and passes a flat
    series). Schema-only — no bench run, so quick_check stays
    seconds."""
    from scripts.bench_trend import _fixture_check, load_history
    from scripts.bench_trend import DEFAULT_WINDOW, TrendError
    problems: List[str] = []
    try:
        rounds = load_history(_ROOT)
        if not rounds:
            problems.append("bench_trend: no BENCH_r*.json history "
                            f"found in {_ROOT}")
    except (TrendError, ValueError) as e:
        problems.append(f"bench_trend: {e}")
    problems.extend(f"bench_trend: {p}"
                    for p in _fixture_check(DEFAULT_WINDOW))
    return problems


def quick_check(seeds=(0, 1, 2), runs_per_seed: int = 2) -> List[str]:
    """Section 0 (static analysis, fail fast), section 0b (bench-trend
    schema gate), then replay the injector battery ``runs_per_seed``
    times per seed; returns violations ([] = clean + deterministic).
    Tier-1 runs this."""
    problems: List[str] = list(analysis_section())
    if problems:
        return problems  # fail fast: no chaos phase on a dirty tree
    problems.extend(bench_trend_section())
    if problems:
        return problems
    for seed in seeds:
        logs = [_scenario_log(int(seed)) for _ in range(runs_per_seed)]
        for i, log in enumerate(logs[1:], 2):
            if log != logs[0]:
                a, b = logs[0].splitlines(), log.splitlines()
                diff = next((j for j, (x, y) in enumerate(zip(a, b))
                             if x != y), min(len(a), len(b)))
                problems.append(
                    f"seed {seed}: run {i} diverged from run 1 at event "
                    f"{diff}: {a[diff] if diff < len(a) else '<end>'!r} vs "
                    f"{b[diff] if diff < len(b) else '<end>'!r}")
    return problems


# ----------------------------------------------------------- chaos mode


def _run_chaos_subprocess(seed: int, n_requests: int,
                          n_events: int) -> Dict[str, object]:
    """One composed chaos drill in a FRESH interpreter (the only
    honest replay on this box — see the full-mode rationale); returns
    the drill's invariant summary, or a synthetic failure record when
    the subprocess died."""
    import json
    code = (
        "import json\n"
        "from deeplearning4j_tpu.faultinject.chaos import run_chaos_drill\n"
        f"out = run_chaos_drill(seed={int(seed)}, "
        f"n_requests={int(n_requests)}, n_events={int(n_events)})\n"
        "print('CHAOS_JSON ' + json.dumps(out, sort_keys=True))\n")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONHASHSEED"] = str(seed)
    proc = subprocess.run([sys.executable, "-c", code], cwd=_ROOT,
                          capture_output=True, text=True, env=env,
                          timeout=900)
    for line in proc.stdout.splitlines():
        if line.startswith("CHAOS_JSON "):
            return json.loads(line[len("CHAOS_JSON "):])
    return {"error": f"rc={proc.returncode}",
            "stderr": proc.stderr[-2000:]}


def _run_hibernation_subprocess(seed: int,
                                n_sessions: int) -> Dict[str, object]:
    """One hibernation drill in a fresh interpreter; returns its
    invariant summary or a synthetic failure record."""
    import json
    code = (
        "import json\n"
        "from deeplearning4j_tpu.faultinject.chaos import "
        "run_hibernation_drill\n"
        f"out = run_hibernation_drill(seed={int(seed)}, "
        f"n_sessions={int(n_sessions)})\n"
        "print('HIB_JSON ' + json.dumps(out, sort_keys=True))\n")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONHASHSEED"] = str(seed)
    proc = subprocess.run([sys.executable, "-c", code], cwd=_ROOT,
                          capture_output=True, text=True, env=env,
                          timeout=900)
    for line in proc.stdout.splitlines():
        if line.startswith("HIB_JSON "):
            return json.loads(line[len("HIB_JSON "):])
    return {"error": f"rc={proc.returncode}",
            "stderr": proc.stderr[-2000:]}


def run_hibernation(runs: int, seed_base: int,
                    n_sessions: int = 4) -> int:
    """The `hibernation` section: the session-hibernation drill twice
    per seed in fresh subprocesses; fail on any invariant violation or
    outcome drift between the two replays of one seed."""
    bad = 0
    for i in range(runs):
        seed = seed_base + i
        print(f"hibernation seed {seed} ({i + 1}/{runs}) ...", flush=True)
        a = _run_hibernation_subprocess(seed, n_sessions)
        b = _run_hibernation_subprocess(seed, n_sessions)
        for run_id, out in (("run1", a), ("run2", b)):
            if "error" in out:
                print(f"  {run_id} DIED: {out}", file=sys.stderr)
                bad += 1
                continue
            violations = [
                k for k, want in (
                    ("token_mismatches", 0), ("dup_offsets", 0),
                    ("gap_events", 0), ("leaked_blocks", 0),
                    ("leaked_host_blocks", 0), ("stranded_handles", 0))
                if out.get(k) != want]
            if out.get("resumed") != out.get("sessions"):
                violations.append("resumed")
            if out.get("handles_shipped") != out.get("sessions"):
                violations.append("handles_shipped")
            if violations:
                print(f"  {run_id} INVARIANT VIOLATIONS {violations}: "
                      f"{out}", file=sys.stderr)
                bad += 1
        if "error" not in a and "error" not in b and a != b:
            drift = sorted(k for k in set(a) | set(b)
                           if a.get(k) != b.get(k))
            print(f"  OUTCOME DRIFT between replays of seed {seed}: "
                  f"{drift}", file=sys.stderr)
            bad += 1
        elif "error" not in a:
            print(f"  ok: {a['sessions']} sessions hibernated + "
                  f"resumed across the death of {a['victim']}",
                  flush=True)
    if not bad:
        print(f"ok: hibernation drill deterministic + invariant-clean "
              f"over {runs} seeds x 2 fresh-process replays")
    return 1 if bad else 0


def run_chaos(runs: int, seed_base: int, n_requests: int = 14,
              n_events: int = 4) -> int:
    """The `chaos` section: run the composed drill TWICE per seed in
    fresh subprocesses across rotating seeds; fail on any invariant
    violation OR any outcome drift between the two replays of one
    seed — the same determinism contract sections 1–11 pin for the
    injectors, applied to the whole composed drill."""
    bad = 0
    for i in range(runs):
        seed = seed_base + i
        print(f"chaos seed {seed} ({i + 1}/{runs}) ...", flush=True)
        a = _run_chaos_subprocess(seed, n_requests, n_events)
        b = _run_chaos_subprocess(seed, n_requests, n_events)
        for run_id, out in (("run1", a), ("run2", b)):
            if "error" in out:
                print(f"  {run_id} DIED: {out}", file=sys.stderr)
                bad += 1
                continue
            violations = [
                k for k, want in (
                    ("failed", 0), ("stranded_futures", 0),
                    ("token_mismatches", 0), ("dup_offsets", 0),
                    ("gap_events", 0), ("leaked_blocks", 0))
                if out.get(k) != want]
            if out.get("healthy_endpoints") != 3:
                violations.append("healthy_endpoints")
            if out.get("completed") != out.get("submitted"):
                violations.append("completed")
            if violations:
                print(f"  {run_id} INVARIANT VIOLATIONS {violations}: "
                      f"{out}", file=sys.stderr)
                bad += 1
        if "error" not in a and "error" not in b and a != b:
            drift = sorted(k for k in set(a) | set(b)
                           if a.get(k) != b.get(k))
            print(f"  OUTCOME DRIFT between replays of seed {seed}: "
                  f"{drift}", file=sys.stderr)
            bad += 1
        elif "error" not in a:
            print(f"  ok: {a['submitted']} requests, "
                  f"schedule {a['schedule']}", flush=True)
    if not bad:
        print(f"ok: composed chaos drill deterministic + invariant-clean "
              f"over {runs} seeds x 2 fresh-process replays")
    return 1 if bad else 0


# ------------------------------------------------------------ full mode

_RESULT_RE = re.compile(r"^(PASSED|FAILED|ERROR|XFAIL|XPASS|SKIPPED) "
                        r"(\S+)", re.MULTILINE)


def _run_suite(seed: int, marker: str, extra: List[str]) -> Dict[str, str]:
    """One fresh-process pytest run; returns {test_id: outcome}."""
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = str(seed)
    env["DL4J_TPU_STRESS_SEED"] = str(seed)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "pytest", "tests/", "-m", marker, "-q",
           "-rA", "--tb=no", "-p", "no:cacheprovider", "-p", "no:randomly",
           "--continue-on-collection-errors", *extra]
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env)
    outcomes: Dict[str, str] = {}
    for m in _RESULT_RE.finditer(proc.stdout):
        outcomes[m.group(2)] = m.group(1)
    if not outcomes:
        outcomes["<collection>"] = f"rc={proc.returncode}"
    return outcomes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--runs", type=int, default=3,
                    help="fresh-process pytest runs (default 3)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("-m", "--marker", default="faultinject",
                    help="pytest marker expression (default: faultinject)")
    ap.add_argument("--quick", action="store_true",
                    help="run only the in-process injector battery "
                         "(what tier-1 wires in)")
    ap.add_argument("--chaos", action="store_true",
                    help="run the COMPOSED chaos drill in fresh "
                         "subprocesses (2 replays per rotating seed), "
                         "failing on invariant violations or outcome "
                         "drift")
    ap.add_argument("--chaos-requests", type=int, default=14)
    ap.add_argument("--chaos-events", type=int, default=4)
    ap.add_argument("--hibernation", action="store_true",
                    help="run the session-hibernation drill in fresh "
                         "subprocesses (2 replays per rotating seed), "
                         "failing on invariant violations or outcome "
                         "drift")
    ap.add_argument("--hibernation-sessions", type=int, default=4)
    ap.add_argument("--pytest-args", nargs=argparse.REMAINDER, default=[],
                    help="extra args forwarded to pytest")
    args = ap.parse_args(argv)

    if args.chaos:
        return run_chaos(args.runs, args.seed_base,
                         n_requests=args.chaos_requests,
                         n_events=args.chaos_events)

    if args.hibernation:
        return run_hibernation(args.runs, args.seed_base,
                               n_sessions=args.hibernation_sessions)

    if args.quick:
        problems = quick_check(
            seeds=range(args.seed_base, args.seed_base + args.runs))
        for p in problems:
            print(p, file=sys.stderr)
        if not problems:
            print(f"ok: injector battery deterministic over {args.runs} "
                  "seeds x 2 runs")
        return 1 if problems else 0

    runs: List[Dict[str, str]] = []
    for i in range(args.runs):
        seed = args.seed_base + i
        print(f"run {i + 1}/{args.runs} (seed {seed}) ...", flush=True)
        outcomes = _run_suite(seed, args.marker, args.pytest_args)
        n_fail = sum(1 for o in outcomes.values()
                     if o in ("FAILED", "ERROR"))
        print(f"  {len(outcomes)} tests, {n_fail} failed", flush=True)
        runs.append(outcomes)

    flaky: List[str] = []
    all_tests = sorted(set().union(*runs))
    for test in all_tests:
        seen = {r.get(test, "<missing>") for r in runs}
        if len(seen) > 1:
            flaky.append(f"NONDETERMINISTIC {test}: "
                         + " / ".join(sorted(seen)))
    deterministic_failures = sorted(
        t for t in all_tests
        if all(r.get(t) in ("FAILED", "ERROR") for r in runs))
    for f in flaky:
        print(f, file=sys.stderr)
    for t in deterministic_failures:
        print(f"DETERMINISTIC FAILURE {t}", file=sys.stderr)
    if not flaky and not deterministic_failures:
        print(f"ok: {len(all_tests)} tests deterministic over "
              f"{args.runs} runs")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
