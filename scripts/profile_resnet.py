"""Profile / ablate the ResNet-50 train step on the real chip.

Usage: python scripts/profile_resnet.py [--trace] [--batch N] [--steps N]
Prints examples/sec + MFU for the configured variant.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.models.zoo.resnet import (
    resnet50, resnet50_train_flops_per_example)

PEAK_BF16 = 197e12


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", action="store_true")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--image-size", type=int, default=224)
    args = ap.parse_args()

    net = resnet50()
    rng = np.random.default_rng(0)
    n = args.batch * args.steps
    x = rng.standard_normal((n, args.image_size, args.image_size, 3)).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, n)]
    mds = MultiDataSet([x], [y])

    t0 = time.perf_counter()
    staged = net.stage_scan(mds, args.batch)
    print(f"stage: {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    net.fit_scan(None, args.batch, epochs=args.epochs, staged=staged)
    print(f"compile+warmup: {time.perf_counter()-t0:.1f}s")

    if args.trace:
        from deeplearning4j_tpu.util import profiler
        net.fit_scan(None, args.batch, epochs=1, staged=staged)  # warm epochs=1 program
        with profiler.trace("/tmp/jax-trace-resnet"):
            net.fit_scan(None, args.batch, epochs=1, staged=staged)
        print("trace written to /tmp/jax-trace-resnet")

    t0 = time.perf_counter()
    scores = net.fit_scan(None, args.batch, epochs=args.epochs, staged=staged)
    dt = time.perf_counter() - t0
    eps = args.epochs * n / dt
    mfu = eps * resnet50_train_flops_per_example(args.image_size) / PEAK_BF16
    assert np.isfinite(np.asarray(scores)).all()
    print(f"batch={args.batch} eps={eps:.1f} mfu={mfu:.4f} "
          f"ms/step={1000*dt/(args.epochs*args.steps):.1f}")


if __name__ == "__main__":
    main()
