#!/usr/bin/env bash
# TPU pod provisioning — thin CLI over the TESTED command-plan builder
# deeplearning4j_tpu/parallel/provisioning.py (the deeplearning4j-aws
# analog: Ec2BoxCreator.java create-request construction +
# ClusterSetup.java artifact fan-out; see that module's docstring for
# the TPU re-design notes).
#
# Usage:
#   ./provision_tpu_pod.sh create <name> <zone> <accel-type> [--spot]
#   ./provision_tpu_pod.sh setup  <name> <zone>
#   ./provision_tpu_pod.sh run    <name> <zone> --command '<cmd>'
#   ./provision_tpu_pod.sh delete <name> <zone>
#   ./provision_tpu_pod.sh plan   <name> <zone> <accel-type> [--command '<cmd>']
#
# Pass --dry-run to print the gcloud commands without executing.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m deeplearning4j_tpu.parallel.provisioning "$@"
