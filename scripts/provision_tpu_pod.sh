#!/usr/bin/env bash
# TPU pod provisioning — the deeplearning4j-aws analog (SURVEY.md §2.6:
# ec2/Ec2BoxCreator.java cluster create, provision/ClusterSetup.java
# rsync+ssh fan-out, s3 data iterators). Where the reference spun up
# EC2 boxes and rsynced jars, a TPU deployment creates ONE queued
# multi-host TPU VM resource and runs the same command on every host;
# jax.distributed + deeplearning4j_tpu.parallel.multihost discover the
# mesh from the TPU runtime, so there is no Spark-master analog to
# provision.
#
# Usage:
#   ./provision_tpu_pod.sh create  <name> <zone> <accel-type> [version]
#   ./provision_tpu_pod.sh setup   <name> <zone>          # ship the framework
#   ./provision_tpu_pod.sh run     <name> <zone> -- <cmd> # run on ALL hosts
#   ./provision_tpu_pod.sh delete  <name> <zone>
#
# Example (v5e-64, 16 hosts x 4 chips):
#   ./provision_tpu_pod.sh create  dl4j-pod us-west4-a v5litepod-64
#   ./provision_tpu_pod.sh setup   dl4j-pod us-west4-a
#   ./provision_tpu_pod.sh run     dl4j-pod us-west4-a -- \
#       python -m examples.train_resnet50 --data gs://my-bucket/imagenet
#
# Data plane: the S3 reader analog is a GCS-backed RecordReader — mount
# via gcsfuse or stream with gsutil; see datavec/records.py.

set -euo pipefail

cmd=${1:?create|setup|run|delete}
name=${2:?tpu name}
zone=${3:?zone}

case "$cmd" in
  create)
    accel=${4:?accelerator type, e.g. v5litepod-64}
    version=${5:-tpu-ubuntu2204-base}
    # queued resources survive capacity waits; --spot for preemptible
    gcloud compute tpus queued-resources create "$name" \
      --node-id "$name" --zone "$zone" \
      --accelerator-type "$accel" --runtime-version "$version"
    ;;
  setup)
    # ship the framework to every host (ClusterSetup.java rsync role);
    # jax/libtpu ship preinstalled on TPU runtime images
    tar czf /tmp/dl4j_tpu.tgz deeplearning4j_tpu tests bench.py pyproject.toml
    gcloud compute tpus tpu-vm scp /tmp/dl4j_tpu.tgz "$name":~ \
      --zone "$zone" --worker=all
    gcloud compute tpus tpu-vm ssh "$name" --zone "$zone" --worker=all \
      --command "tar xzf dl4j_tpu.tgz && python -c 'import deeplearning4j_tpu'"
    ;;
  run)
    shift 3; [ "${1:-}" = "--" ] && shift
    # same command on every host: the TPU runtime provides coordinator
    # discovery; jax.distributed.initialize() no-args inside the program
    gcloud compute tpus tpu-vm ssh "$name" --zone "$zone" --worker=all \
      --command "$*"
    ;;
  delete)
    gcloud compute tpus queued-resources delete "$name" --zone "$zone" --force
    ;;
  *)
    echo "unknown command: $cmd" >&2; exit 2
    ;;
esac
