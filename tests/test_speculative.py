"""Speculative decoding tests (nn/generate.py spec programs +
serving/continuous.py fused draft/verify rounds + registry pairing).

The ISSUE-17 battery: greedy output token-for-token vs
``generate_eager`` with int8 self-speculation; seeded-sampled replay
determinism; preempt/resume mid-speculation parity (greedy AND
sampled) with zero leaked blocks on BOTH the draft and target KV
lanes; the BurstKill mid-speculation recovery contract; the
zero-steady-state-compile assertion across the accept ladder via
``dl4j_jit_cache_miss_total`` plus the spec_max_rows fallback; the
``deploy(draft=...)`` pairing + persisted quality-gate verdict
(the acceptance prior) in registry ``stats()``; and the
``dl4j_spec_*`` schema pinning.
"""

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import BurstKill
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.generate import generate_eager
from deeplearning4j_tpu.nn.quantize import make_quality_gate, quantize
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving.continuous import (
    ContinuousDecodeScheduler,
    DecodeBurstError,
)
from deeplearning4j_tpu.serving.registry import ModelRegistry

VOCAB = 11


def _tiny_gpt(seed=0, **kw):
    return gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=seed, **kw).init()


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


def _sched(net, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("burst_tokens", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("start", False)
    kw.setdefault("speculative", True)
    kw.setdefault("spec_tokens", 3)
    kw.setdefault("spec_max_rows", 4)
    return ContinuousDecodeScheduler(net=net, **kw)


def _drive(sched, futures, max_steps=200):
    for _ in range(max_steps):
        if all(f.done() for f in futures):
            return
        sched.step()
    raise AssertionError(
        f"schedule did not converge in {max_steps} steps; "
        f"events={list(sched.events)}")


def _drain_audit(st):
    """Both lanes fully free after drain — a draft-side leak must be
    attributable to the draft pool, so it is audited separately."""
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
    assert st["draft_pool"]["blocks_free"] == st["draft_pool"]["blocks_total"]


# --------------------------------------------------------- exactness

def test_spec_greedy_matches_eager(rng):
    """Greedy speculative output is token-for-token equal to
    ``generate_eager`` — the rejection sampler accepts exactly the
    positions where the int8 draft's argmax agrees with the target's,
    and the correction token IS the target argmax, so speculation can
    only change latency, never a token."""
    net = _tiny_gpt()
    s = _sched(net)
    prompts = [rng.integers(0, VOCAB, (1, t)) for t in (5, 3, 6)]
    futs = [s.submit(p, 10) for p in prompts]
    _drive(s, futs)
    for f, p in zip(futs, prompts):
        assert np.array_equal(f.result(0), generate_eager(net, p, 10))
    st = s.stats()
    spec = st["speculative"]
    assert spec["enabled"] and spec["rounds"] > 0
    assert spec["proposed_tokens"] > 0
    assert spec["proposed_tokens"] == (spec["accepted_tokens"]
                                       + spec["rejected_tokens"])
    assert 0.0 <= spec["accept_rate"] <= 1.0
    _drain_audit(st)


def test_spec_greedy_eos_and_budget(rng):
    """EOS inside an accepted run is honored at its first occurrence
    (tokens past it in the same round are discarded) and the max_new
    budget truncates an over-long accepted run — both identical to the
    eager oracle's stopping behaviour."""
    net = _tiny_gpt()
    s = _sched(net)
    prompts = [rng.integers(0, VOCAB, (2, 4)), rng.integers(0, VOCAB, (1, 5))]
    futs = [s.submit(prompts[0], 12, eos_token=3),
            s.submit(prompts[1], 7, eos_token=3)]
    _drive(s, futs)
    assert np.array_equal(futs[0].result(0),
                          generate_eager(net, prompts[0], 12, eos_token=3))
    assert np.array_equal(futs[1].result(0),
                          generate_eager(net, prompts[1], 7, eos_token=3))
    _drain_audit(s.stats())


def test_spec_sampled_deterministic_replay(rng):
    """Seeded sampled speculation replays token-for-token: every draw
    rides a (row key, salted lane, fold index) clock, so the same
    seeds yield the same accepted/corrected tokens run over run."""
    net = _tiny_gpt()
    prompts = [rng.integers(0, VOCAB, (1, t)) for t in (4, 6)]

    def run():
        s = _sched(net)
        futs = [s.submit(p, 9, temperature=0.8, top_k=5, seed=11 + i)
                for i, p in enumerate(prompts)]
        _drive(s, futs)
        st = s.stats()
        _drain_audit(st)
        return [f.result(0) for f in futs], st["speculative"]

    outs1, spec1 = run()
    outs2, spec2 = run()
    for a, b in zip(outs1, outs2):
        assert np.array_equal(a, b)
    # the whole round schedule replays: same acceptance accounting
    assert spec1["accepted_tokens"] == spec2["accepted_tokens"]
    assert spec1["rejected_tokens"] == spec2["rejected_tokens"]


# --------------------------------------------- preempt/resume parity

@pytest.mark.parametrize("temperature", [0.0, 1.0])
def test_spec_preempt_resume_matches_uninterrupted(rng, temperature):
    """A sequence preempted MID-SPECULATION (tiny target pool) and
    resumed must be token-for-token identical to an uninterrupted run
    — greedy and seeded-sampled. The pending-carry resume keeps the
    per-row fold clock aligned, and BOTH lanes drain leak-free."""
    net = _tiny_gpt()
    prompts = [rng.integers(0, VOCAB, (1, 5)) for _ in range(3)]

    def run(num_blocks):
        kw = {} if num_blocks is None else {"num_blocks": num_blocks}
        s = _sched(net, **kw)
        futs = [s.submit(p, 10, temperature=temperature, top_k=4,
                         seed=21 + i)
                for i, p in enumerate(prompts)]
        _drive(s, futs)
        st = s.stats()
        _drain_audit(st)
        return [f.result(0) for f in futs], st

    outs_tiny, st_tiny = run(9)       # 8 usable blocks: must preempt
    outs_big, _ = run(None)           # roomy pool: uninterrupted
    assert st_tiny["preemptions"] > 0
    for a, b in zip(outs_tiny, outs_big):
        assert np.array_equal(a, b)
    if temperature == 0.0:
        for out, p in zip(outs_tiny, prompts):
            assert np.array_equal(out, generate_eager(net, p, 10))


# ------------------------------------------------------- fault domain

@pytest.mark.faultinject
def test_spec_burstkill_mid_speculation(rng, fresh_registry):
    """BurstKill firing inside a speculative round: the riding futures
    fail typed (DecodeBurstError), BOTH lanes free every block, and
    the scheduler keeps serving — exact output — afterwards."""
    net = _tiny_gpt()
    kill = BurstKill(after=1, failures=1)  # 2nd dispatch dies: n_gen>0
    s = _sched(net, burst_hook=kill)
    p1 = rng.integers(0, VOCAB, (2, 5))
    f1 = s.submit(p1, 10)
    for _ in range(60):
        if f1.done():
            break
        s.step()
    with pytest.raises(DecodeBurstError):
        f1.result(0)
    st = s.stats()
    _drain_audit(st)
    # the lane recovers: a fresh request still decodes exactly
    p2 = rng.integers(0, VOCAB, (1, 4))
    f2 = s.submit(p2, 8)
    _drive(s, [f2])
    assert np.array_equal(f2.result(0), generate_eager(net, p2, 8))
    _drain_audit(s.stats())
    assert fresh_registry.family_total(monitor.FAULT_EVENTS_COUNTER) >= 1


# ------------------------------------- compile discipline + fallback

def test_spec_zero_steady_state_compiles_and_fallback(rng, fresh_registry):
    """After ``warmup()`` a mixed greedy/sampled speculative workload
    compiles NOTHING (accept lengths never shape a program — the
    accept ladder is host truncation), and offered load past
    spec_max_rows falls back to plain bursts instead of speculating."""
    net = _tiny_gpt()
    s = _sched(net, spec_max_rows=2)
    s.warmup([3, 5], 8)
    miss0 = fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
    # the two short rows retire first: the opening 4-row phase is over
    # the cap (fallback plain bursts), the 2-row tail speculates
    futs = [s.submit(rng.integers(0, VOCAB, (1, t)), mn,
                     temperature=temp, seed=i)
            for i, (t, mn, temp) in enumerate(
                [(3, 3, 0.0), (5, 8, 0.7), (3, 3, 0.0), (5, 8, 0.9)])]
    _drive(s, futs)
    assert fresh_registry.family_total(
        monitor.JIT_CACHE_MISS_COUNTER) == miss0
    st = s.stats()
    spec = st["speculative"]
    assert spec["rounds"] > 0
    assert spec["fallbacks"] > 0  # 4 live rows > spec_max_rows=2
    _drain_audit(st)


# ------------------------------------------------- registry pairing

def test_registry_draft_pairing_and_quality_prior(rng, fresh_registry):
    """deploy(draft=...) is a version attribute: 'self' resolves
    lazily to the int8 quantized net (cached), the persisted
    quality-gate verdict surfaces greedy_match_rate in stats() as the
    speculation acceptance prior, and a bogus sentinel is rejected."""
    net1, net2 = _tiny_gpt(seed=1), _tiny_gpt(seed=1)
    reg = ModelRegistry()
    reg.register("lm", net=net1)
    with pytest.raises(ValueError):
        reg.deploy("lm", net=net2, draft="turbo")
    v2 = reg.deploy("lm", net=net2, draft="self",
                    quality_gate=make_quality_gate(min_greedy_match=0.0,
                                                   max_eval_delta=1e9))
    ver = reg.version("lm", v2)
    dn = ver.draft()
    assert dn is not None and dn is ver.draft()  # resolved once, cached
    assert dn is not ver.net()  # a distinct (quantized) net
    # satellite fix: the gate verdict is PERSISTED, not discarded
    assert ver.quality is not None and "greedy_match_rate" in ver.quality
    st = reg.stats()["lm"]["versions"][str(v2)]
    assert st["spec_accept_prior"] == pytest.approx(
        ver.quality["greedy_match_rate"], abs=1e-4)
    assert st["draft_paired"] is True
    assert st["quality_gate"]["passed"] is True
    # v1 never ran a gate and paired no draft
    st1 = reg.stats()["lm"]["versions"]["1"]
    assert st1["spec_accept_prior"] is None
    assert st1["draft_paired"] is False
    assert reg.version("lm", 1).draft() is None


def test_engine_speculative_registry_pairing_serves_exact(
        rng, fresh_registry):
    """End-to-end: a speculative engine over a registry whose active
    version pairs draft='self' serves greedy output token-for-token
    equal to the eager oracle, and a mid-stream deploy never switches
    a session's draft (the lane pins the resolved version)."""
    net1 = _tiny_gpt(seed=2)
    reg = ModelRegistry()
    reg.register("lm", net=net1)
    v2 = reg.deploy("lm", net=_tiny_gpt(seed=2), draft="self")
    assert reg.active_version("lm") == v2
    eng = ParallelInference(registry=reg, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4, speculative=True,
                            spec_tokens=3)
    try:
        p = rng.integers(0, VOCAB, (1, 5))
        got = eng.submit_generate(p, 8, model="lm").result(30)
        assert np.array_equal(
            got, generate_eager(reg.version("lm", v2).net(), p, 8))
        sched = eng._scheduler
        st = sched.stats()
        assert st["speculative"]["rounds"] > 0
        _drain_audit(st)
    finally:
        eng.shutdown()


def test_engine_speculative_net_mode_knobs(rng):
    """Net-mode knob threading: speculative=/spec_tokens=/draft_net=
    reach the scheduler, and an explicit draft net overrides the int8
    self-speculation default. speculative= without continuous= is a
    build-time error."""
    net = _tiny_gpt(seed=3)
    with pytest.raises(ValueError):
        ParallelInference(net, replicas=1, speculative=True, start=False)
    eng = ParallelInference(net, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4, speculative=True,
                            spec_tokens=2, draft_net=quantize(net, "int8"))
    try:
        p = rng.integers(0, VOCAB, (1, 4))
        assert np.array_equal(
            eng.submit_generate(p, 8).result(30),
            generate_eager(net, p, 8))
        assert eng._scheduler.stats()["speculative"]["k"] == 2
    finally:
        eng.shutdown()


# --------------------------------------------------------- telemetry

def test_spec_metrics_schema_and_emission(rng, fresh_registry):
    """The dl4j_spec_* family is pinned in monitor constants AND the
    telemetry-schema gate, and a speculative run actually emits it
    with conserving counts."""
    import importlib.util
    import os
    spec_path = os.path.join(os.path.dirname(__file__), os.pardir,
                             "scripts", "check_telemetry_schema.py")
    spec = importlib.util.spec_from_file_location("cts", spec_path)
    cts = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cts)
    names = {monitor.SPEC_PROPOSED_TOKENS_COUNTER,
             monitor.SPEC_ACCEPTED_TOKENS_COUNTER,
             monitor.SPEC_REJECTED_TOKENS_COUNTER,
             monitor.SPEC_ACCEPT_RATE_GAUGE,
             monitor.SPEC_DRAFT_LATENCY_HISTOGRAM}
    assert names <= cts.KNOWN_DL4J_METRICS
    net = _tiny_gpt()
    s = _sched(net)
    f = s.submit(rng.integers(0, VOCAB, (1, 5)), 10)
    _drive(s, [f])
    reg = fresh_registry
    proposed = reg.family_total(monitor.SPEC_PROPOSED_TOKENS_COUNTER)
    accepted = reg.family_total(monitor.SPEC_ACCEPTED_TOKENS_COUNTER)
    rejected = reg.family_total(monitor.SPEC_REJECTED_TOKENS_COUNTER)
    assert proposed > 0 and proposed == accepted + rejected
    text = reg.prometheus_text()
    for name in names:
        assert name in text
    assert not cts.validate_known_metrics(text)
