"""Perf-regression trend gate tests (scripts/bench_trend.py, ISSUE 16).

The gate's arithmetic (noise band ``mean - max(threshold·mean, nσ)``,
one-sided: improvements never flag), the strict payload schema
(malformed history is exit 2, never a silent skip), the history loader
against the repo's own committed ``BENCH_r*.json`` rounds, the
``--check`` fixture mode ``stress_faultinject.quick_check`` wires in,
and the end-to-end CLI: real history stays green, a synthetic injected
regression exits 1 and names the metric in TREND.md.
"""

import json
import os

import pytest

from scripts.bench_trend import (
    DEFAULT_NSIGMA,
    DEFAULT_THRESHOLD,
    DEFAULT_WINDOW,
    REPO_ROOT,
    TrendError,
    _fixture_check,
    _validate_payload,
    extract_metrics,
    gate,
    gate_metric,
    load_history,
    main,
    run_check,
)


def _payload(value=100.0, **subs):
    return {"metric": "tokens_per_sec", "value": value, "unit": "tok/s",
            "schema_version": 1,
            "sub_benchmarks": {k: {"value": v} for k, v in subs.items()}}


# ----------------------------------------------------- gate arithmetic

def test_gate_metric_flat_series_passes():
    r = gate_metric([100.0, 101.0, 99.0, 100.5], 100.0,
                    DEFAULT_THRESHOLD, DEFAULT_NSIGMA)
    assert not r["regressed"]
    assert r["mean"] == pytest.approx(100.125)
    assert r["floor"] == pytest.approx(100.125 - 0.10 * 100.125)


def test_gate_metric_injected_regression_flags():
    r = gate_metric([100.0, 101.0, 99.0, 100.5], 60.0,
                    DEFAULT_THRESHOLD, DEFAULT_NSIGMA)
    assert r["regressed"] and r["fresh"] < r["floor"]
    assert r["delta_frac"] == pytest.approx((60.0 - 100.125) / 100.125)


def test_gate_metric_one_sided():
    """Improvements NEVER flag — only the downside is gated."""
    r = gate_metric([100.0, 101.0, 99.0, 100.5], 500.0,
                    DEFAULT_THRESHOLD, DEFAULT_NSIGMA)
    assert not r["regressed"]


def test_gate_metric_noisy_series_widens_band():
    """The σ term: a drop that the 10% threshold alone would flag
    passes when the prior window is honestly that noisy."""
    noisy = [100.0, 140.0, 80.0, 120.0]
    mean = sum(noisy) / 4
    r = gate_metric(noisy, mean * 0.85, DEFAULT_THRESHOLD, DEFAULT_NSIGMA)
    assert r["floor"] < mean * 0.9  # 3σ beat the 10% band
    assert not r["regressed"]


def test_gate_marks_new_metrics_without_verdict():
    history = [(1, _payload(100.0, a=10.0)), (2, _payload(101.0, a=11.0))]
    fresh = _payload(100.5, a=10.5, brand_new=7.0)
    report = gate(history, fresh, DEFAULT_WINDOW, DEFAULT_THRESHOLD,
                  DEFAULT_NSIGMA)
    assert report["brand_new"] == {"fresh": 7.0, "new": True,
                                   "regressed": False}
    assert not report["headline"]["regressed"]
    assert report["a"]["priors"] == [10.0, 11.0]


# ------------------------------------------------------ payload schema

@pytest.mark.parametrize("payload,fragment", [
    ([1, 2], "expected object"),
    ({"value": 1.0, "unit": "x"}, "missing required key 'metric'"),
    ({"metric": "m", "value": "fast", "unit": "x"}, "key 'value' is str"),
    ({"metric": "m", "value": 1.0, "unit": "x", "schema_version": 99},
     "schema_version 99"),
    ({"metric": "m", "value": 1.0, "unit": "x", "sub_benchmarks": []},
     "sub_benchmarks is list"),
    ({"metric": "m", "value": 1.0, "unit": "x",
      "sub_benchmarks": {"s": {"value": None}}}, "expected number"),
])
def test_validate_payload_rejects(payload, fragment):
    with pytest.raises(TrendError) as e:
        _validate_payload(payload, "where")
    assert fragment in str(e.value)


def test_validate_payload_accepts_failed_sub_with_error():
    p = {"metric": "m", "value": 1.0, "unit": "x",
         "sub_benchmarks": {"s": {"error": "OOM"}}}
    assert _validate_payload(p, "w") is p
    assert extract_metrics(p) == {"headline": 1.0}  # errored sub skipped


def test_extract_metrics_orders_and_filters():
    p = _payload(5.0, b=2.0, a=1.0)
    p["sub_benchmarks"]["broken"] = {"error": "boom"}
    assert extract_metrics(p) == {"headline": 5.0, "a": 1.0, "b": 2.0}


# --------------------------------------------------- committed history

def test_load_history_real_repo_rounds():
    rounds = load_history(REPO_ROOT)
    assert len(rounds) >= 2
    assert [n for n, _ in rounds] == sorted(n for n, _ in rounds)
    for _, payload in rounds:
        assert isinstance(payload["value"], (int, float))


def test_load_history_rejects_malformed(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({"rc": 0}))
    with pytest.raises(TrendError, match="missing 'parsed'"):
        load_history(str(tmp_path))


def test_fixture_check_green():
    assert _fixture_check(DEFAULT_WINDOW) == []


def test_run_check_real_history(capsys):
    assert run_check(REPO_ROOT, DEFAULT_WINDOW) == 0
    assert "gate fixture green" in capsys.readouterr().out


def test_run_check_empty_dir_fails(tmp_path, capsys):
    assert run_check(str(tmp_path), DEFAULT_WINDOW) == 2
    assert "no BENCH_r*.json history" in capsys.readouterr().out


# ------------------------------------------------------- CLI end-to-end

def _write_history(d, values):
    for i, v in enumerate(values, start=1):
        rec = {"n": i, "cmd": "bench", "rc": 0, "tail": "",
               "parsed": _payload(v, gemm=v * 2)}
        (d / f"BENCH_r{i:02d}.json").write_text(json.dumps(rec))


def test_main_latest_round_green(tmp_path, capsys):
    _write_history(tmp_path, [100.0, 102.0, 99.0, 101.0, 100.0])
    assert main(["--history", str(tmp_path)]) == 0
    md = (tmp_path / "TREND.md").read_text()
    assert "No regressions." in md and "| headline |" in md
    assert "r05 (latest committed round)" in md


def test_main_injected_regression_exits_1(tmp_path, capsys):
    _write_history(tmp_path, [100.0, 102.0, 99.0, 101.0])
    fresh = tmp_path / "fresh.json"
    bad = _payload(100.5, gemm=120.0)  # headline fine, gemm tanked
    fresh.write_text(json.dumps(bad))
    assert main(["--history", str(tmp_path), "--fresh", str(fresh)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED gemm" in out
    md = (tmp_path / "TREND.md").read_text()
    assert "**REGRESSED**" in md
    assert md.count("ok") >= 1  # the clean headline still renders ok


def test_main_malformed_candidate_exits_2(tmp_path, capsys):
    _write_history(tmp_path, [100.0, 101.0])
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps({"metric": "m"}))  # no value/unit
    assert main(["--history", str(tmp_path), "--fresh", str(fresh)]) == 2
    assert "missing required key" in capsys.readouterr().err


def test_main_too_little_history_exits_2(tmp_path, capsys):
    _write_history(tmp_path, [100.0])
    assert main(["--history", str(tmp_path)]) == 2
    assert "need >=2 committed rounds" in capsys.readouterr().err


def test_main_real_history_green():
    """The committed rounds must pass their own gate (acceptance bar:
    the default invocation stays exit-0 on the real repo history)."""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        out = os.path.join(d, "TREND.md")
        assert main(["--history", REPO_ROOT, "--out", out]) == 0
        assert "No regressions." in open(out).read()


def test_bench_schema_version_pinned():
    """bench.py stamps the schema_version this gate knows."""
    import bench
    from scripts.bench_trend import KNOWN_SCHEMA_VERSIONS
    assert bench.BENCH_SCHEMA_VERSION in KNOWN_SCHEMA_VERSIONS


def test_quick_check_wires_bench_trend_section():
    from scripts.stress_faultinject import bench_trend_section
    assert bench_trend_section() == []
