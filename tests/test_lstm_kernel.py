"""Fused LSTM Pallas kernel vs the XLA-scan oracle.

Doctrine as for flash attention (tests/test_flash_attention.py): the
``_lstm_scan`` XLA formulation is the correctness oracle; the kernel
must match forward, gradients, carries, and the reverse direction, and
the layer dispatch must be transparent.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.nn.layers.recurrent as rec
import deeplearning4j_tpu.ops.lstm_kernel as lk
from deeplearning4j_tpu.ops.lstm_kernel import (
    fused_lstm_applicable, fused_lstm_scan)


def _params(rng, nin, n):
    mk = lambda *s: jnp.asarray(rng.standard_normal(s) * 0.2, jnp.float32)
    return {"Wx": mk(nin, 4 * n), "Wr": mk(n, 4 * n),
            "b": jnp.asarray(rng.standard_normal(4 * n) * 0.1, jnp.float32),
            "wci": mk(n) * 0.5, "wcf": mk(n) * 0.5, "wco": mk(n) * 0.5}


def _setup(rng, b=16, t=9, nin=8, n=128):
    p = _params(rng, nin, n)
    x = jnp.asarray(rng.standard_normal((b, t, nin)), jnp.float32)
    h0 = jnp.asarray(rng.standard_normal((b, n)) * 0.1, jnp.float32)
    c0 = jnp.asarray(rng.standard_normal((b, n)) * 0.1, jnp.float32)
    return p, x, h0, c0


def _kernel_forward(p, x, h0, c0, reverse=False):
    xg = jnp.einsum("btf,fg->btg", x, p["Wx"]) + p["b"]
    xg_t = jnp.swapaxes(xg, 0, 1)
    if reverse:
        xg_t = xg_t[::-1]
    h_seq, (h, c) = fused_lstm_scan(xg_t, p["Wr"], p["wci"], p["wcf"],
                                    p["wco"], h0, c0)
    if reverse:
        h_seq = h_seq[::-1]
    return jnp.swapaxes(h_seq, 0, 1), (h, c)


def test_forward_matches_oracle(rng):
    p, x, h0, c0 = _setup(rng)
    want, (hw, cw) = rec._lstm_scan(p, x, h0, c0, "sigmoid", "tanh")
    got, (hg, cg) = _kernel_forward(p, x, h0, c0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hg), np.asarray(hw),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cg), np.asarray(cw),
                               rtol=1e-5, atol=1e-5)


def test_reverse_matches_oracle(rng):
    p, x, h0, c0 = _setup(rng)
    want, _ = rec._lstm_scan(p, x, h0, c0, "sigmoid", "tanh", reverse=True)
    got, _ = _kernel_forward(p, x, h0, c0, reverse=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_oracle(rng):
    p, x, h0, c0 = _setup(rng, b=8, t=5)

    def loss_ref(p, x, h0, c0):
        out, (h, c) = rec._lstm_scan(p, x, h0, c0, "sigmoid", "tanh")
        return jnp.sum(out ** 2) + jnp.sum(h * c)

    def loss_k(p, x, h0, c0):
        out, (h, c) = _kernel_forward(p, x, h0, c0)
        return jnp.sum(out ** 2) + jnp.sum(h * c)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(p, x, h0, c0)
    gk = jax.grad(loss_k, argnums=(0, 1, 2, 3))(p, x, h0, c0)
    for a, b in zip(jax.tree.leaves(gr), jax.tree.leaves(gk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_applicability_gate(monkeypatch):
    monkeypatch.setattr(lk, "_on_tpu", lambda: True)
    assert fused_lstm_applicable(16, 128, "sigmoid", "tanh", None)
    assert not fused_lstm_applicable(16, 100, "sigmoid", "tanh", None)
    assert not fused_lstm_applicable(16, 128, "hardsigmoid", "tanh", None)
    assert not fused_lstm_applicable(16, 128, "sigmoid", "relu", None)
    assert not fused_lstm_applicable(16, 128, "sigmoid", "tanh",
                                     jnp.ones((16, 4)))
    assert not fused_lstm_applicable(7, 128, "sigmoid", "tanh", None)
    # off-TPU hosts never dispatch (the interpreter would be glacial)
    monkeypatch.setattr(lk, "_on_tpu", lambda: False)
    assert not fused_lstm_applicable(16, 128, "sigmoid", "tanh", None)


def test_train_applicability_honors_bwd_env(monkeypatch):
    """DL4J_TPU_LSTM_BWD=xla is the documented A/B seam back to the
    plain XLA scan: the TRAIN gate must refuse too (mirroring
    _use_pallas_bwd), not silently dispatch the slower fused-fwd +
    XLA-bwd combination (21% vs 28.8% MFU, r3/r4)."""
    monkeypatch.setattr(lk, "_on_tpu", lambda: True)
    assert lk.fused_lstm_train_applicable(16, 128, "sigmoid", "tanh", None)
    monkeypatch.setenv("DL4J_TPU_LSTM_BWD", "xla")
    assert not lk.fused_lstm_train_applicable(16, 128, "sigmoid", "tanh",
                                              None)
    # inference-only dispatch is untouched by the backward seam
    assert fused_lstm_applicable(16, 128, "sigmoid", "tanh", None)
    monkeypatch.delenv("DL4J_TPU_LSTM_BWD")
    assert lk.fused_lstm_train_applicable(16, 128, "sigmoid", "tanh", None)


def test_layer_inference_dispatch_transparent(rng, monkeypatch):
    """MLN.output through the kernel equals the XLA path bit-for-bit at
    test tolerance — the dispatch must be invisible to users."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(5).activation("tanh").list()
            .layer(GravesLSTM(n_in=8, n_out=128))
            .layer(RnnOutputLayer(n_in=128, n_out=4, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((16, 7, 8)).astype(np.float32)
    # force the kernel path even on the CPU test host (interpreter)
    monkeypatch.setattr(lk, "_on_tpu", lambda: True)
    out_kernel = net.output(x)

    # disable the kernel dispatch and recompute through the XLA scan
    monkeypatch.setattr(lk, "fused_lstm_applicable",
                        lambda *a, **k: False)
    net._jits.clear()  # drop the cached compiled forward
    out_xla = net.output(x)
    np.testing.assert_allclose(out_kernel, out_xla, rtol=1e-5, atol=1e-5)


def test_rnn_time_step_streaming_with_kernel(rng):
    """Stateful single-step inference (kernel path at t=1) matches the
    full-window forward."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(2).activation("tanh").list()
            .layer(GravesLSTM(n_in=8, n_out=128))
            .layer(RnnOutputLayer(n_in=128, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((8, 5, 8)).astype(np.float32)
    full = net.output(x)
    net.rnn_clear_previous_state()
    steps = [net.rnn_time_step(x[:, t]) for t in range(5)]
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               rtol=1e-4, atol=1e-4)


def test_pallas_bwd_matches_scan_bwd(rng, monkeypatch):
    """r5: the fused Pallas BPTT must produce the same gradients as the
    XLA residual scan (DL4J_TPU_LSTM_BWD=xla selects the old path)."""
    import os
    import jax
    import numpy as np
    p, x, h0, c0 = _setup(rng, b=16, t=7, nin=8, n=128)

    def loss(p, x, h0, c0):
        h, (hl, cl) = _kernel_forward(p, x, h0, c0)
        return (jnp.sum(h * h) + jnp.sum(hl) + jnp.sum(cl * cl))

    grads_pallas = jax.grad(loss, argnums=(0, 1, 2, 3))(p, x, h0, c0)
    monkeypatch.setenv("DL4J_TPU_LSTM_BWD", "xla")
    jax.clear_caches()
    grads_scan = jax.grad(loss, argnums=(0, 1, 2, 3))(p, x, h0, c0)
    monkeypatch.delenv("DL4J_TPU_LSTM_BWD")
    jax.clear_caches()
    for gp, gs in zip(jax.tree.leaves(grads_pallas),
                      jax.tree.leaves(grads_scan)):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                                   rtol=2e-2, atol=2e-3)


def test_layer_training_dispatch_matches_xla(rng, monkeypatch):
    """r5: the TRAIN path through the fused fwd+Pallas-BPTT kernels
    (the default on TPU) produces the same fit trajectory as the XLA
    scan — guarded here on the interpreter so CI covers the layer-level
    dispatch, not just direct kernel calls."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(5).learning_rate(0.05).updater("sgd")
                .activation("tanh").list()
                .layer(GravesLSTM(n_in=8, n_out=128))
                .layer(RnnOutputLayer(n_in=128, n_out=4,
                                      activation="softmax",
                                      loss_function="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    x = rng.standard_normal((16, 7, 8)).astype(np.float32)
    y = np.zeros((16, 7, 4), np.float32)
    y[np.arange(16)[:, None], np.arange(7)[None, :],
      rng.integers(0, 4, (16, 7))] = 1.0
    ds = DataSet(x, y)

    monkeypatch.setattr(lk, "_on_tpu", lambda: True)  # interpreter path
    net_fused = build()
    assert lk.fused_lstm_train_applicable(16, 128, "sigmoid", "tanh", None)
    for _ in range(2):
        net_fused.fit(ds, batch_size=16)

    monkeypatch.setenv("DL4J_TPU_LSTM_TRAIN", "xla")
    import jax
    jax.clear_caches()
    net_xla = build()
    for _ in range(2):
        net_xla.fit(ds, batch_size=16)

    for ln in net_fused.params:
        for pn in net_fused.params[ln]:
            np.testing.assert_allclose(
                np.asarray(net_fused.params[ln][pn]),
                np.asarray(net_xla.params[ln][pn]),
                rtol=1e-4, atol=1e-5, err_msg=f"{ln}/{pn}")


def test_blstm_training_dispatch_matches_xla(rng, monkeypatch):
    """r5: the bidirectional train path (reverse direction flips xg
    into and h_seq out of the fused kernels) must match the XLA scan
    trajectory too."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import (
        GravesBidirectionalLSTM, RnnOutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(9).learning_rate(0.05).updater("sgd")
                .activation("tanh").list()
                .layer(GravesBidirectionalLSTM(n_in=8, n_out=128))
                .layer(RnnOutputLayer(n_in=128, n_out=4,
                                      activation="softmax",
                                      loss_function="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    x = rng.standard_normal((16, 6, 8)).astype(np.float32)
    y = np.zeros((16, 6, 4), np.float32)
    y[np.arange(16)[:, None], np.arange(6)[None, :],
      rng.integers(0, 4, (16, 6))] = 1.0
    ds = DataSet(x, y)

    monkeypatch.setattr(lk, "_on_tpu", lambda: True)  # interpreter path
    net_fused = build()
    net_fused.fit(ds, batch_size=16)

    monkeypatch.setenv("DL4J_TPU_LSTM_TRAIN", "xla")
    import jax
    jax.clear_caches()
    net_xla = build()
    net_xla.fit(ds, batch_size=16)

    for ln in net_fused.params:
        for pn in net_fused.params[ln]:
            np.testing.assert_allclose(
                np.asarray(net_fused.params[ln][pn]),
                np.asarray(net_xla.params[ln][pn]),
                rtol=1e-4, atol=1e-5, err_msg=f"{ln}/{pn}")
