"""Round-trip tests for the reference-layout word-vector interchange
formats (WordVectorSerializer.java :493/:605/:891/:964/:1081/:1606)."""

import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.models.embeddings import serializer as ser
from deeplearning4j_tpu.models.glove.glove import Glove
from deeplearning4j_tpu.models.paragraphvectors.paragraphvectors import (
    ParagraphVectors)
from deeplearning4j_tpu.models.word2vec.word2vec import Word2Vec

CORPUS = [["king", "queen", "royal", "palace"],
          ["cat", "dog", "pet", "animal"],
          ["king", "palace", "throne"],
          ["dog", "animal", "bark"],
          ["queen", "royal", "throne"]] * 4

DOCS = [("the king sat in the palace", ["royalty"]),
        ("the dog and the cat are pets", ["pets"]),
        ("the queen rules from the throne", ["royalty"]),
        ("the animal barked at the dog", ["pets"])] * 2


def _tiny_w2v(use_hs=False):
    m = Word2Vec(layer_size=16, window_size=2, epochs=1, negative_sample=3,
                 use_hierarchic_softmax=use_hs, batch_size=64, seed=7,
                 device_pairgen=False)
    m.fit(CORPUS)
    return m


def test_b64_helpers_match_reference_layout():
    assert ser.encode_b64("day") == "B64:ZGF5"  # fixed fixture
    assert ser.decode_b64("B64:ZGF5") == "day"
    assert ser.decode_b64("plain") == "plain"   # pass-through
    word = "white space & ünïcode"
    assert ser.decode_b64(ser.encode_b64(word)) == word


def test_word2vec_model_zip_round_trip(tmp_path):
    m = _tiny_w2v()
    path = str(tmp_path / "w2v_full_model.zip")
    ser.write_word2vec_model(m, path)
    # reference entry set (writeWord2VecModel :493)
    with zipfile.ZipFile(path) as z:
        assert {"syn0.txt", "syn1.txt", "codes.txt", "huffman.txt",
                "frequencies.txt", "config.json"} <= set(z.namelist())
        # syn0 is the HEADERLESS B64 table format (:380)
        first = z.read("syn0.txt").decode().splitlines()[0]
        assert first.startswith("B64:")
    back = ser.read_word2vec_model(path)
    assert back.vocab.words() == m.vocab.words()
    np.testing.assert_allclose(back.lookup_table.syn0,
                               m.lookup_table.syn0, rtol=1e-6)
    np.testing.assert_allclose(back.lookup_table.syn1neg,
                               m.lookup_table.syn1neg, rtol=1e-6)
    assert (back.words_nearest("king", 3) == m.words_nearest("king", 3))
    # frequencies restored, not the loadTxt placeholder 1s
    assert (back.vocab.word_frequencies()
            == m.vocab.word_frequencies()).all()


def test_word2vec_hs_codes_points_survive(tmp_path):
    m = _tiny_w2v(use_hs=True)
    path = str(tmp_path / "w2v_hs_model.zip")
    ser.write_word2vec_model(m, path)
    back = ser.read_word2vec_model(path)
    assert back.use_hs
    np.testing.assert_allclose(back.lookup_table.syn1,
                               m.lookup_table.syn1, rtol=1e-6)
    for w in m.vocab._index:
        b = back.vocab.word_for(w.word)
        assert list(b.codes or []) == list(w.codes or []), w.word
        assert list(b.points or []) == list(w.points or []), w.word


def test_paragraph_vectors_zip_round_trip(tmp_path):
    pv = ParagraphVectors(layer_size=16, window_size=2, epochs=1,
                          negative_sample=3, batch_size=64, seed=7,
                          device_pairgen=False)
    pv.fit(DOCS)
    path = str(tmp_path / "paravec_model.zip")
    ser.write_paragraph_vectors(pv, path)
    with zipfile.ZipFile(path) as z:  # :605 adds labels.txt
        assert "labels.txt" in z.namelist()
    back = ser.read_paragraph_vectors(path)
    assert back.labels == pv.labels
    assert back.vocab.words() == pv.vocab.words()
    np.testing.assert_allclose(back.doc_vectors, pv.doc_vectors, rtol=1e-6)
    np.testing.assert_allclose(back.lookup_table.syn0,
                               pv.lookup_table.syn0, rtol=1e-6)
    # the restored model answers queries
    for l in back.labels:
        assert back.get_label_vector(l).shape == (16,)


def test_paragraph_vectors_legacy_text_round_trip(tmp_path):
    pv = ParagraphVectors(layer_size=8, window_size=2, epochs=1,
                          negative_sample=2, batch_size=64, seed=7,
                          device_pairgen=False)
    pv.fit(DOCS)
    path = str(tmp_path / "paravec_legacy.txt")
    ser.write_paragraph_vectors_text(pv, path)
    with open(path) as f:
        tags = {ln.split(" ", 1)[0] for ln in f if ln.strip()}
    assert tags == {"L", "E"}  # :1124 line tags
    back = ser.read_paragraph_vectors_text(path)
    assert back.labels == pv.labels
    assert back.vocab.words() == pv.vocab.words()
    np.testing.assert_allclose(back.doc_vectors, pv.doc_vectors, rtol=1e-6)


def test_glove_round_trip_nearest_neighbors(tmp_path):
    g = Glove(layer_size=8, window=3, epochs=3, batch_size=256, seed=5)
    g.fit([" ".join(s) for s in CORPUS])
    path = str(tmp_path / "glove_vectors.txt")
    ser.write_glove(g, path)
    back = ser.read_glove(path)
    assert back.vocab.words() == g.vocab.words()
    np.testing.assert_allclose(back.vectors, g.vectors, rtol=1e-6)
    assert (back.word_vectors().words_nearest("king", 3)
            == g.word_vectors().words_nearest("king", 3))


def test_load_txt_header_autodetect_and_b64(tmp_path):
    # headered Google-style file loads identically to headerless (:1606)
    rows = [("alpha", [0.1, 0.2, 0.3, 0.4]), ("two words", [1.0, 2.0, 3.0, 4.0])]
    headerless, headered = str(tmp_path / "lt_nohdr.txt"), str(tmp_path / "lt_hdr.txt")
    with open(headerless, "w") as f:
        for w, v in rows:
            f.write(ser.encode_b64(w) + " " + " ".join(map(str, v)) + "\n")
    with open(headered, "w") as f:
        f.write("2 4\n")
        for w, v in rows:
            f.write(ser.encode_b64(w) + " " + " ".join(map(str, v)) + "\n")
    for p in (headerless, headered):
        words, vecs = ser.load_txt(p)
        assert words == ["alpha", "two words"], p
        np.testing.assert_allclose(vecs, [r[1] for r in rows])


def test_read_word2vec_from_text_four_files(tmp_path):
    m = _tiny_w2v(use_hs=True)
    base = str(tmp_path / "w2v_hs_text")
    paths = [f"{base}_{k}.txt" for k in ("syn0", "syn1", "codes", "points")]
    with open(paths[0], "w") as f:
        ser._write_table_text(m.vocab.words(), m.lookup_table.syn0, f)
    with open(paths[1], "w") as f:
        for row in m.lookup_table.syn1:
            f.write(" ".join(repr(float(x)) for x in row) + "\n")
    with open(paths[2], "w") as f:
        f.write(ser._codes_lines(m.vocab))
    with open(paths[3], "w") as f:
        f.write(ser._points_lines(m.vocab))
    back = ser.read_word2vec_from_text(*paths, config={"window": 2})
    assert back.use_hs and back.vocab.words() == m.vocab.words()
    np.testing.assert_allclose(back.lookup_table.syn0,
                               m.lookup_table.syn0, rtol=1e-6)
    np.testing.assert_allclose(back.lookup_table.syn1,
                               m.lookup_table.syn1, rtol=1e-6)
    for w in m.vocab._index:
        b = back.vocab.word_for(w.word)
        assert list(b.codes or []) == list(w.codes or [])
        assert list(b.points or []) == list(w.points or [])


def test_unicode_and_space_words_cross_the_boundary(tmp_path):
    m = Word2Vec(layer_size=8, window_size=2, epochs=1, negative_sample=2,
                 batch_size=32, seed=3, device_pairgen=False)
    m.fit([["日本語", "naïve", "multi word", "plain"] for _ in range(6)])
    path = str(tmp_path / "w2v_unicode.zip")
    ser.write_word2vec_model(m, path)
    back = ser.read_word2vec_model(path)
    assert set(back.vocab.words()) == {"日本語", "naïve", "multi word", "plain"}


def test_glove_d2_round_trip_no_header_mangle(tmp_path):
    """Code-review r5: a d<3 table written by our writer must not lose
    its first row to the reference's header heuristic."""
    from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
    from deeplearning4j_tpu.models.glove.glove import Glove
    g = Glove(layer_size=2)
    g.vocab = VocabCache.from_ordered(["first", "second"])
    g.vectors = np.asarray([[0.1, 0.2], [0.3, 0.4]], np.float32)
    ser.write_glove(g, str(tmp_path / "glove_d2.txt"))
    back = ser.read_glove(str(tmp_path / "glove_d2.txt"))
    assert back.vocab.words() == ["first", "second"]
    np.testing.assert_allclose(back.vectors, g.vectors)


def test_paragraph_vectors_hs_zip_round_trip_consistent(tmp_path):
    """Code-review r5: an HS PV zip restores with use_hs set and both
    tables populated, and re-serializes without crashing."""
    pv = ParagraphVectors(layer_size=8, window_size=2, epochs=1,
                          negative_sample=0, batch_size=64, seed=7,
                          device_pairgen=False)
    pv.use_hs = True
    pv.fit(DOCS)
    ser.write_paragraph_vectors(pv, str(tmp_path / "paravec_hs.zip"))
    back = ser.read_paragraph_vectors(str(tmp_path / "paravec_hs.zip"))
    assert back.use_hs
    assert back.lookup_table.syn1 is not None
    assert back.lookup_table.syn1neg is not None
    ser.write_paragraph_vectors(back, str(tmp_path / "paravec_hs2.zip"))  # round 2
    again = ser.read_paragraph_vectors(str(tmp_path / "paravec_hs2.zip"))
    np.testing.assert_allclose(again.lookup_table.syn1,
                               back.lookup_table.syn1, rtol=1e-6)


def test_shared_label_word_lookup_prefers_word_row(tmp_path):
    """Code-review r5: reading a PV zip through read_word2vec_model
    (a label sharing a corpus word's surface) must resolve name lookups
    to the WORD row, not the appended doc-vector row."""
    pv = ParagraphVectors(layer_size=8, window_size=2, epochs=1,
                          negative_sample=2, batch_size=64, seed=7,
                          device_pairgen=False)
    pv.fit([("dog and cat are pets", ["pets"]),
            ("the pets ran home", ["pets"])] * 3)
    path = str(tmp_path / "pv_shared.zip")
    ser.write_paragraph_vectors(pv, path)
    w2v = ser.read_word2vec_model(path)  # flat view over the same zip
    i = pv.vocab.index_of("pets")
    np.testing.assert_allclose(w2v.get_word_vector("pets"),
                               pv.lookup_table.syn0[i], rtol=1e-6)


def test_literal_sentinel_word_survives_zip_round_trip(tmp_path):
    """Code-review r5: a surface literally containing _Az92_ is B64 on
    the zip path and must round-trip verbatim."""
    m = Word2Vec(layer_size=8, window_size=2, epochs=1, negative_sample=2,
                 batch_size=32, seed=3, device_pairgen=False)
    m.fit([["weird_Az92_token", "plain", "other"] for _ in range(6)])
    path = str(tmp_path / "sentinel.zip")
    ser.write_word2vec_model(m, path)
    back = ser.read_word2vec_model(path)
    assert "weird_Az92_token" in back.vocab.words()


def test_read_word_vectors_any_autodetects(tmp_path):
    """loadStaticModel role: one loader for every shipped format, by
    byte sniffing."""
    m = _tiny_w2v()
    from deeplearning4j_tpu.models.embeddings.lookup_table import WordVectors
    zipp = str(tmp_path / "any_model.zip")
    ser.write_word2vec_model(m, zipp)
    binp = str(tmp_path / "any_vectors.bin")
    ser.write_word_vectors_binary(m._wv(), binp)
    txtp = str(tmp_path / "any_vectors.txt")
    ser.write_word_vectors(m._wv(), txtp)
    tblp = str(tmp_path / "any_table.txt")
    with open(tblp, "w") as f:
        ser._write_table_text(m.vocab.words(), m.lookup_table.syn0, f)

    for p in (zipp, binp, txtp, tblp):
        got = ser.read_word_vectors_any(p)
        wv = got.word_vectors() if hasattr(got, "word_vectors") else got
        assert isinstance(wv, WordVectors) or hasattr(wv, "words_nearest")
        np.testing.assert_allclose(
            np.asarray(wv.get_word_vector("king")
                       if hasattr(wv, "get_word_vector")
                       else wv.vectors[wv.vocab.index_of("king")]),
            m.lookup_table.syn0[m.vocab.index_of("king")],
            rtol=1e-4, atol=1e-5)  # %.6f text rounding on ~0 values
    import pytest
    bad = str(tmp_path / "bad.bin")
    with open(bad, "wb") as f:
        f.write(b"\x00\x01nonsense")
    with pytest.raises(ValueError, match="unrecognized|not a word-vector"):
        ser.read_word_vectors_any(bad)


def test_read_word_vectors_any_multibyte_cut_at_sample_boundary(tmp_path):
    """Format sniffing reads a 512-byte sample; a multibyte char cut at
    that boundary must NOT reroute a headered TEXT file to the binary
    reader (the incremental-decoder rule _detect_ipadic_encoding uses)."""
    dim = 4
    vec = " ".join(f"{0.25 * (k + 1):.6f}" for k in range(dim))
    lines = ["2 4"]
    # pad the first word so the sample boundary (byte 512) lands INSIDE
    # the 2-byte UTF-8 encoding of the é that follows it
    pad = "a" * (511 - len(lines[0].encode()) - 1)
    first_word = pad + "ééé"
    lines.append(f"{first_word} {vec}")
    lines.append(f"king {vec}")
    payload = ("\n".join(lines) + "\n").encode("utf-8")
    # boundary check: byte 512 cuts a multibyte char → the old
    # rest.decode("utf-8") raised and misrouted to the binary reader
    try:
        payload[:512].partition(b"\n")[2].decode("utf-8")
        cut = False
    except UnicodeDecodeError:
        cut = True
    assert cut, "test setup: boundary must cut a multibyte char"
    p = str(tmp_path / "cut.txt")
    with open(p, "wb") as f:
        f.write(payload)
    wv = ser.read_word_vectors_any(p)
    assert wv.vocab.index_of("king") == 1
    np.testing.assert_allclose(wv.get_word_vector("king"),
                               [0.25, 0.5, 0.75, 1.0], rtol=1e-6)
