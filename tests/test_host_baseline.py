"""The numpy SGNS host baseline (the bench's external word2vec anchor)."""
import numpy as np

from deeplearning4j_tpu.models.sequencevectors.host_baseline import (
    sgns_host_benchmark, sgns_pairs)


def test_sgns_pairs_window_semantics():
    flat = np.arange(40, dtype=np.int32) % 7
    sent_id = np.repeat(np.arange(4, dtype=np.int32), 10)
    c, x = sgns_pairs(flat, sent_id, window=3, rng=np.random.default_rng(0))
    assert c.shape == x.shape and c.size > 0
    # every pair must co-occur within the max window inside one sentence
    for cc, xx in list(zip(c, x))[:200]:
        found = any(
            i != j and abs(i - j) <= 3 and sent_id[i] == sent_id[j]
            for i in np.flatnonzero(flat == cc)
            for j in np.flatnonzero(flat == xx))
        assert found, (cc, xx)


def test_host_sgns_reports_throughput():
    # deterministic bigram structure: 2k always followed by 2k+1
    v = 10
    sents = [[2 * k, 2 * k + 1] * 10 for k in range(v // 2)] * 20
    r = sgns_host_benchmark(sents, v, dim=16, window=2, K=3, lr=0.1,
                            seed=3, batch=512, max_seconds=5.0)
    assert r["tokens_per_sec"] > 0 and np.isfinite(r["tokens_per_sec"])
    assert r["pairs"] > 1000


def test_host_benchmark_tiny_corpus_nonzero():
    """A corpus with fewer pairs than one batch still reports a real
    (nonzero) throughput — bench divides by this number."""
    sents = [[0, 1, 2, 3]] * 4
    r = sgns_host_benchmark(sents, 4, dim=8, window=2, K=2,
                            batch=4096, max_seconds=1.0)
    assert r["tokens_per_sec"] > 0 and np.isfinite(r["tokens_per_sec"])
    for k in ("tokens", "pairs", "seconds", "pairs_per_token"):
        assert np.isfinite(r[k])


def test_host_benchmark_trains_tail_pairs():
    """ADVICE r4: the final clamped batch covers the tail — every pair
    in a sub-timeout corpus is counted exactly through to N."""
    sents = [[0, 1, 2, 3, 4, 5]] * 6
    r = sgns_host_benchmark(sents, 6, dim=8, window=2, K=2,
                            batch=32, max_seconds=30.0)
    # all generated pairs trained: done ran to exactly the pair count
    assert r["pairs"] == int(r["pairs_per_token"] * r["tokens"])
    assert r["tokens"] == sum(len(s) for s in sents)
