"""Sentence-iterator variants (text/sentenceiterator parity tail)."""
from deeplearning4j_tpu.text.sentenceiterator import (
    AggregatingSentenceIterator,
    CollectionSentenceIterator,
    LabelAwareListSentenceIterator,
    PrefetchingSentenceIterator,
    SentencePreProcessor,
)


class _Up(SentencePreProcessor):
    def pre_process(self, s):
        return s.upper()


def test_aggregating_chains_sources():
    a = CollectionSentenceIterator(["one", "two"])
    b = CollectionSentenceIterator(["three"])
    it = AggregatingSentenceIterator([a, b], preprocessor=_Up())
    assert list(it) == ["ONE", "TWO", "THREE"]
    assert list(it) == ["ONE", "TWO", "THREE"]  # reset via __iter__


def test_prefetching_matches_wrapped():
    src = [f"s{i}" for i in range(250)]
    it = PrefetchingSentenceIterator(CollectionSentenceIterator(src),
                                     fetch_size=16)
    assert list(it) == src
    assert list(it) == src  # reset restarts the worker cleanly


def test_label_aware_list():
    it = LabelAwareListSentenceIterator(["hello world", "bye"],
                                        labels=["greet", "farewell"])
    docs = list(it)
    assert [d.labels for d in docs] == [["greet"], ["farewell"]]
    it2 = LabelAwareListSentenceIterator(["a", "b"])
    assert [d.labels[0] for d in it2] == ["doc_0", "doc_1"]


def test_prefetching_edge_cases():
    """Review r4: post-exhaustion has_next stays False (no deadlock),
    worker exceptions propagate, reset does not drain the corpus."""
    import pytest

    src = CollectionSentenceIterator([f"s{i}" for i in range(50)])
    it = PrefetchingSentenceIterator(src, fetch_size=8)
    assert len(list(it)) == 50
    assert it.has_next() is False
    assert it.has_next() is False  # second call must not block

    class Boom(CollectionSentenceIterator):
        def next_sentence(self):
            s = super().next_sentence()
            if s == "s3":
                raise IOError("disk gone")
            return s

    bad = PrefetchingSentenceIterator(Boom([f"s{i}" for i in range(6)]),
                                      fetch_size=2)
    got = []
    with pytest.raises(IOError, match="disk gone"):
        for s in bad:
            got.append(s)
    assert got == ["s0", "s1", "s2"]

    class Counting(CollectionSentenceIterator):
        pulls = 0

        def next_sentence(self):
            Counting.pulls += 1
            return super().next_sentence()

    Counting.pulls = 0
    big = PrefetchingSentenceIterator(Counting([f"s{i}" for i in range(10000)]),
                                      fetch_size=4)
    assert big.has_next()
    big.next_sentence()
    big.reset()
    assert Counting.pulls < 100, Counting.pulls  # no full-corpus drain
    assert len(list(big)) == 10000  # replays completely after reset


def test_prefetching_close_stops_abandoned_worker():
    import time

    src = CollectionSentenceIterator([f"s{i}" for i in range(100000)])
    it = PrefetchingSentenceIterator(src, fetch_size=2)
    assert it.has_next()
    it.next_sentence()  # abandon mid-stream
    worker = it._thread
    it.close()
    time.sleep(0.05)
    assert worker is None or not worker.is_alive()


def test_prefetching_has_next_after_close_returns_false():
    """ADVICE r4: a consumer that keeps iterating after close() must see
    end-of-stream, not block forever on an empty queue."""
    src = CollectionSentenceIterator([f"s{i}" for i in range(100000)])
    it = PrefetchingSentenceIterator(src, fetch_size=2)
    assert it.has_next()
    it.next_sentence()
    it.close()
    assert it.has_next() is False  # must return, not hang


def test_synchronized_iterator_parallel_consumers():
    """SynchronizedSentenceIterator.java:10 — N threads drain one
    stream; every sentence delivered exactly once."""
    import threading
    from deeplearning4j_tpu.text.sentenceiterator import (
        SynchronizedSentenceIterator)

    n = 5000
    it = SynchronizedSentenceIterator(
        CollectionSentenceIterator([f"s{i}" for i in range(n)]))
    got, lock = [], threading.Lock()

    def drain():
        while True:
            with lock:  # has_next+next must still pair atomically at
                ok = it.has_next()  # the consumer level (ref. contract)
                s = it.next_sentence() if ok else None
            if s is None:
                return
            got.append(s)

    ts = [threading.Thread(target=drain) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert sorted(got) == sorted(f"s{i}" for i in range(n))


def test_basic_result_set_iterator_sqlite():
    """BasicResultSetIterator.java:16 over a PEP 249 cursor: column by
    name, peeked-row bookkeeping, reset by re-execute."""
    import sqlite3
    from deeplearning4j_tpu.text.sentenceiterator import (
        BasicResultSetIterator)

    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE docs (id INTEGER, body TEXT)")
    conn.executemany("INSERT INTO docs VALUES (?, ?)",
                     [(i, f"sentence {i}") for i in range(7)])
    it = BasicResultSetIterator(
        lambda: conn.execute("SELECT id, body FROM docs ORDER BY id"),
        column="body")
    # repeated has_next calls must not skip rows (nextCalled bookkeeping)
    assert it.has_next() and it.has_next()
    assert list(it) == [f"sentence {i}" for i in range(7)]
    assert list(it) == [f"sentence {i}" for i in range(7)]  # reset works

    class Upper:
        def pre_process(self, s):
            return s.upper()

    it.set_pre_processor(Upper())
    it.reset()
    assert it.next_sentence() == "SENTENCE 0"
    # positional column + unknown-name diagnostic
    it2 = BasicResultSetIterator(
        lambda: conn.execute("SELECT body FROM docs LIMIT 1"), column=0)
    assert list(it2) == ["sentence 0"]
    it3 = BasicResultSetIterator(
        lambda: conn.execute("SELECT body FROM docs"), column="nope")
    try:
        it3.next_sentence()
        raise AssertionError("expected KeyError")
    except KeyError as e:
        assert "nope" in str(e)


def test_synchronized_close_delegates_to_prefetcher():
    """Code-review r5: SynchronizedSentenceIterator(Prefetching...)
    must stop the worker thread on close()."""
    from deeplearning4j_tpu.text.sentenceiterator import (
        SynchronizedSentenceIterator)

    inner = PrefetchingSentenceIterator(
        CollectionSentenceIterator([f"s{i}" for i in range(50000)]),
        fetch_size=2)
    it = SynchronizedSentenceIterator(inner)
    assert it.has_next()
    it.next_sentence()
    it.close()
    assert inner.has_next() is False  # worker stopped, clean EOS


def test_synchronized_close_unblocks_stalled_consumer():
    """Code-review r5: close() is lock-free — it must interrupt a
    consumer blocked inside the wrapped prefetcher's has_next() while
    holding the sync lock."""
    import threading
    import time
    from deeplearning4j_tpu.text.sentenceiterator import (
        SynchronizedSentenceIterator)

    class Stalled(CollectionSentenceIterator):
        def __init__(self):
            super().__init__(["one"])
            self.release = threading.Event()

        def has_next(self):
            if not super().has_next():
                self.release.wait(timeout=10.0)  # simulate a hung source
            return super().has_next()

    it = SynchronizedSentenceIterator(PrefetchingSentenceIterator(
        Stalled(), fetch_size=1))
    assert it.next_sentence() == "one"
    out = []
    t = threading.Thread(target=lambda: out.append(it.has_next()))
    t.start()
    time.sleep(0.3)  # consumer is now inside the prefetch wait, lock held
    it.close()       # must not block on the lock
    t.join(timeout=5.0)
    assert not t.is_alive(), "close() deadlocked against the consumer"
    assert out == [False]


def test_sentence_iterator_converter_positional_labels():
    """interoperability/SentenceIteratorConverter.java:20 — plain
    corpora become labeled documents for ParagraphVectors."""
    from deeplearning4j_tpu.text.sentenceiterator import (
        LabelsSource, SentenceIteratorConverter)

    conv = SentenceIteratorConverter(
        CollectionSentenceIterator(["alpha beta", "gamma delta"]))
    docs = list(conv)
    assert [d.content for d in docs] == ["alpha beta", "gamma delta"]
    assert [d.labels for d in docs] == [["SENT_0"], ["SENT_1"]]
    docs2 = list(conv)  # reset() replays with fresh positional labels
    assert [d.labels for d in docs2] == [["SENT_0"], ["SENT_1"]]
    custom = SentenceIteratorConverter(
        CollectionSentenceIterator(["x"]), LabelsSource("DOC_%d"))
    assert next(iter(custom)).labels == ["DOC_0"]


def test_label_aware_file_sentence_iterator(tmp_path):
    """labelaware/LabelAwareFileSentenceIterator — folder-per-class
    corpora: the parent directory names the label."""
    from deeplearning4j_tpu.text.sentenceiterator import (
        LabelAwareFileSentenceIterator)

    (tmp_path / "pos").mkdir()
    (tmp_path / "neg").mkdir()
    (tmp_path / "pos" / "a.txt").write_text("good one\ngreat two\n")
    (tmp_path / "neg" / "b.txt").write_text("bad one\n")
    it = LabelAwareFileSentenceIterator(str(tmp_path))
    docs = list(it)
    assert {(d.content, d.labels[0]) for d in docs} == {
        ("good one", "pos"), ("great two", "pos"), ("bad one", "neg")}
    assert len(list(it)) == 3  # reset replays
