"""Layer-wise greedy pretraining driver tests.

Parity: ``MultiLayerNetwork.pretrain(iter)`` (MultiLayerNetwork.java:163,
reached from fit :1037 when conf.pretrain) — RBM CD-k and denoising-AE
reconstruction phases, then supervised fine-tune.
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import RBM, AutoEncoder, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _dbn_conf():
    return (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.1).updater("sgd").activation("sigmoid")
            .list()
            .layer(RBM(n_in=12, n_out=8, loss_function="xent"))
            .layer(AutoEncoder(n_in=8, n_out=4, loss_function="mse"))
            .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .pretrain(True)
            .build())


def _data(rng):
    base = rng.random((64, 12)) < 0.3
    x = base.astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(base.sum(1) > 3).astype(int)]
    return DataSet(x, y)


def test_pretrain_reduces_reconstruction_loss(rng):
    ds = _data(rng)
    short = MultiLayerNetwork(_dbn_conf()).init().pretrain(ds, epochs=1)
    long = MultiLayerNetwork(_dbn_conf()).init().pretrain(ds, epochs=20)
    # AE reconstruction is a true loss — must improve with more pretraining
    assert long["layer1"] < short["layer1"]
    assert set(long) == {"layer0", "layer1"}  # output layer not pretrained


def test_fit_runs_pretrain_once_then_supervised(rng):
    ds = _data(rng)
    net = MultiLayerNetwork(_dbn_conf()).init()
    net.fit(ds)
    assert net._pretrained
    s0 = net.score()
    for _ in range(10):
        net.fit(ds)
    assert net.score() < s0
    # re-init resets the pretrain phase
    net.init()
    assert not net._pretrained
