"""ComputationGraph MLN-parity tests: iterator fit, fit_scan, TBPTT,
rnnTimeStep, pretrain, bf16 compute.

Parity: ``ComputationGraph.java`` fit(DataSetIterator) :621,
fit(MultiDataSet) :677, TBPTT :887, rnnTimeStep :1063, plus the CG
pretrain path — the round-1 gaps (VERDICT r1 weak #3).
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.datasets.iterators import ListMultiDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    AutoEncoder, DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer)
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration)


def _base(seed=1, act="relu", cd="float32"):
    return (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .updater("adam").activation(act).compute_dtype(cd).build())


def _ff_graph(cd="float32"):
    return (ComputationGraphConfiguration.builder(_base(cd=cd))
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=10, n_out=16), "in")
            .add_layer("out", OutputLayer(n_in=16, n_out=3, activation="softmax",
                                          loss_function="mcxent"), "d1")
            .set_outputs("out").build())


def test_cg_iterator_fit_and_fit_scan(rng):
    x = rng.standard_normal((64, 10)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    mds = MultiDataSet([x], [y])
    g = ComputationGraph(_ff_graph()).init()
    g.fit(ListMultiDataSetIterator(mds, 16), epochs=2)
    s0 = g.score(mds)
    scores = g.fit_scan(mds, 16, epochs=4)
    assert scores.shape == (16,)
    assert scores[-1] < s0


def test_cg_bf16_trains(rng):
    import jax
    import jax.numpy as jnp
    x = rng.standard_normal((32, 10)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    mds = MultiDataSet([x], [y])
    g = ComputationGraph(_ff_graph(cd="bfloat16")).init()
    g.fit(mds)
    s0 = g.score(mds)
    for _ in range(15):
        g.fit(mds)
    assert g.score(mds) < s0
    for leaf in jax.tree.leaves(g.params):
        assert leaf.dtype == jnp.float32


def test_cg_tbptt_and_rnn_time_step(rng):
    conf = (ComputationGraphConfiguration.builder(_base(seed=2, act="tanh"))
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=5, n_out=8), "in")
            .add_layer("out", RnnOutputLayer(n_in=8, n_out=2, activation="softmax",
                                             loss_function="mcxent"), "lstm")
            .set_outputs("out")
            .backprop_type("truncated_bptt").t_bptt_forward_length(4)
            .build())
    g = ComputationGraph(conf).init()
    x = rng.standard_normal((8, 12, 5)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (8, 12))]
    g.fit(MultiDataSet([x], [y]))  # 12 > 4 → TBPTT path
    assert np.isfinite(g.score(MultiDataSet([x], [y])))
    # streaming single steps must equal a burst over the same timesteps
    o1 = g.rnn_time_step(x[:, 0])
    o2 = g.rnn_time_step(x[:, 1])
    g.rnn_clear_previous_state()
    burst = g.rnn_time_step(x[:, :2])
    np.testing.assert_allclose(burst[0][:, 0], o1[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(burst[0][:, 1], o2[0], rtol=1e-5, atol=1e-6)


def test_cg_pretrain(rng):
    x = rng.standard_normal((48, 10)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 48)]
    conf = (ComputationGraphConfiguration.builder(_base(seed=3, act="sigmoid"))
            .add_inputs("in")
            .add_layer("ae", AutoEncoder(n_in=10, n_out=6, loss_function="mse"), "in")
            .add_layer("out", OutputLayer(n_in=6, n_out=3, activation="softmax",
                                          loss_function="mcxent"), "ae")
            .set_outputs("out").pretrain(True).build())
    short = ComputationGraph(conf).init().pretrain(MultiDataSet([x], [y]), epochs=1)
    long = ComputationGraph(conf).init().pretrain(MultiDataSet([x], [y]), epochs=15)
    assert long["ae"] < short["ae"]
    # fit() drives the pretrain phase exactly once
    g = ComputationGraph(conf).init()
    g.fit(MultiDataSet([x], [y]))
    assert g._pretrained


def test_cg_config_roundtrip_tbptt_fields():
    conf = (ComputationGraphConfiguration.builder(_base())
            .add_inputs("in")
            .add_layer("out", OutputLayer(n_in=10, n_out=2, activation="softmax",
                                          loss_function="mcxent"), "in")
            .set_outputs("out")
            .pretrain(True).backprop_type("truncated_bptt")
            .t_bptt_forward_length(7).t_bptt_backward_length(7)
            .build())
    c2 = ComputationGraphConfiguration.from_json(conf.to_json())
    assert c2.pretrain and c2.backprop_type == "truncated_bptt"
    assert c2.tbptt_fwd_length == 7 and c2.tbptt_back_length == 7
