"""Multi-host distributed equivalence test.

The cluster analog of round-1's single-process mesh equivalence tests
and the reference's Spark-vs-local doctrine
(``TestCompareParameterAveragingSparkVsSingleMachine.java:41``,
``BaseSparkTest.java:90`` local[N]): 2 REAL processes × 2 CPU devices
each, connected by ``jax.distributed`` + gloo, train data-parallel over
the 4-device global mesh; final params must match a single-process run
on the same global batch.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(pid, nproc, port, out, local_devices=4, mode="dp"):
    env = dict(os.environ)
    # the box's sitecustomize registers a TPU plugin at interpreter start
    # when this var is set — must be removed BEFORE the child starts
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the worker needs ITS OWN device count, not whatever the parent's
    # XLA_FLAGS carries (conftest forces 8 — blindly popping the var,
    # as this spawner used to, silently left the count to a jax config
    # option this jax does not even have); set the flag explicitly and
    # the worker re-asserts the resulting count after backend init
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}")
    env["GRAFT_LOCAL_DEVICES"] = str(local_devices)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(nproc), str(port), out, mode],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)


def _run_equivalence(tmp_path, mode):
    """2 processes × 4 devices vs 1 process × 8 devices — a REAL
    8-device global mesh (the same width conftest forces in-process),
    same global mesh semantics; final params must match."""
    port = _free_port()
    out_multi = str(tmp_path / f"multi_{mode}.npz")
    out_single = str(tmp_path / f"single_{mode}.npz")

    procs = [_spawn(i, 2, port, out_multi, mode=mode) for i in range(2)]
    for p in procs:
        stdout, stderr = p.communicate(timeout=540)
        assert p.returncode == 0, f"worker failed:\n{stdout}\n{stderr[-3000:]}"

    single = _spawn(0, 1, port, out_single, local_devices=8, mode=mode)
    stdout, stderr = single.communicate(timeout=540)
    assert single.returncode == 0, f"single failed:\n{stdout}\n{stderr[-3000:]}"

    a = np.load(out_multi)
    b = np.load(out_single)
    assert set(a.files) == set(b.files)
    for k in a.files:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                   err_msg=f"{mode}:{k}")


def test_two_process_dp_matches_single_process(tmp_path):
    _run_equivalence(tmp_path, "dp")


def test_two_process_fsdp_matches_single_process(tmp_path):
    """VERDICT r4 #6: ZeRO-3 param/opt shards span the process boundary
    (asserted inside the worker) and the trajectory matches the
    single-process run."""
    _run_equivalence(tmp_path, "fsdp")


def test_two_process_tp_matches_single_process(tmp_path):
    """VERDICT r4 #6: tensor-parallel with the model axis ACROSS
    processes — per-layer collectives ride the process boundary."""
    _run_equivalence(tmp_path, "tp")


def test_make_multihost_mesh_single_process_shapes():
    """In-process sanity: data absorbs free devices; explicit ICI axes
    stay inner (rightmost = fastest-varying = on-host)."""
    import jax
    from deeplearning4j_tpu.parallel.multihost import make_multihost_mesh
    n = len(jax.devices())
    m = make_multihost_mesh()
    assert dict(m.shape) == {"data": n}
    if n % 2 == 0:
        m2 = make_multihost_mesh(ici_axes={"model": 2})
        assert dict(m2.shape) == {"data": n // 2, "model": 2}
        assert tuple(m2.axis_names) == ("data", "model")
