"""Checkpoint format regression tests against FROZEN fixtures.

Parity: ``regressiontest/RegressionTest050.java`` / ``RegressionTest060``
— the reference freezes models saved by old releases and re-verifies
them forever. The fixtures under tests/fixtures/ were written by round
2's serializer and must stay loadable (and produce identical outputs)
in every future round; regenerating them to make a test pass defeats
the point — fix the loader instead.

Also: YAML config round-trip (real YAML now, weak #4 of VERDICT r1) and
Google word2vec text/binary interop incl. the gensim no-trailing-newline
convention.
"""

import os

import numpy as np

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def test_frozen_mln_checkpoint_loads_and_matches():
    from deeplearning4j_tpu.util.model_serializer import restore_multi_layer_network
    net = restore_multi_layer_network(os.path.join(FIXTURES, "mln_r2.zip"))
    exp = np.load(os.path.join(FIXTURES, "mln_r2_expected.npz"))
    out = net.output(exp["x"])
    np.testing.assert_allclose(out, exp["out"], rtol=1e-5, atol=1e-6)
    # updater state restored too (adam moments present)
    assert net.opt_state is not None and "updater" in net.opt_state


def test_frozen_cg_checkpoint_loads_and_matches():
    from deeplearning4j_tpu.util.model_serializer import restore_computation_graph
    g = restore_computation_graph(os.path.join(FIXTURES, "cg_r2.zip"))
    exp = np.load(os.path.join(FIXTURES, "cg_r2_expected.npz"))
    out = g.output(exp["x"])
    np.testing.assert_allclose(out, exp["out"], rtol=1e-5, atol=1e-6)


def test_yaml_roundtrip_is_real_yaml():
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.builder().seed(9).learning_rate(0.1)
            .updater("adam").activation("relu")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    y = conf.to_yaml()
    assert not y.lstrip().startswith("{")  # block-style YAML, not JSON
    assert "layers:" in y
    c2 = MultiLayerConfiguration.from_yaml(y)
    assert c2.to_json() == conf.to_json()


def test_word2vec_binary_gensim_convention(tmp_path, rng):
    """Binary files WITHOUT per-record trailing newlines (gensim's
    save_word2vec_format) must load identically to word2vec.c-style."""
    from deeplearning4j_tpu.models.embeddings.serializer import (
        read_word_vectors_binary)
    words = ["alpha", "beta", "gamma"]
    vecs = rng.standard_normal((3, 4)).astype("<f4")
    c_style = tmp_path / "c.bin"
    with open(c_style, "wb") as f:
        f.write(b"3 4\n")
        for w, v in zip(words, vecs):
            f.write(w.encode() + b" " + v.tobytes() + b"\n")
    gensim_style = tmp_path / "g.bin"
    with open(gensim_style, "wb") as f:
        f.write(b"3 4\n")
        for w, v in zip(words, vecs):
            f.write(w.encode() + b" " + v.tobytes())
    for path in (c_style, gensim_style):
        wv = read_word_vectors_binary(str(path))
        assert [wv.vocab.word_at_index(i) for i in range(3)] == words
        np.testing.assert_allclose(wv.vectors, vecs, rtol=1e-6)


# ------------------------- r5 interchange-format frozen fixtures

def test_frozen_paravec_zip_still_loads():
    """Byte-layout stability: a PV zip written by the r5 serializer is
    a committed fixture — readers must keep loading it verbatim."""
    import os
    import numpy as np
    from deeplearning4j_tpu.models.embeddings import serializer as ser

    path = os.path.join(os.path.dirname(__file__), "fixtures",
                        "paravec_r5.zip")
    pv = ser.read_paragraph_vectors(path)
    assert sorted(pv.labels) == ["pets", "royalty"]
    assert pv.doc_vectors.shape == (2, 8)
    assert "king" in pv.vocab.words() and "dog" in pv.vocab.words()
    assert np.isfinite(pv.lookup_table.syn0).all()
    assert pv.predict("the king in the palace") in pv.labels


def test_frozen_glove_txt_still_loads():
    import os
    import numpy as np
    from deeplearning4j_tpu.models.embeddings import serializer as ser

    path = os.path.join(os.path.dirname(__file__), "fixtures",
                        "glove_r5.txt")
    g = ser.read_glove(path)
    assert {"king", "queen", "cat", "dog"} <= set(g.vocab.words())
    assert g.vectors.shape[1] == 6 and np.isfinite(g.vectors).all()


def test_frozen_w2v_hs_zip_still_loads():
    import os
    import numpy as np
    from deeplearning4j_tpu.models.embeddings import serializer as ser

    path = os.path.join(os.path.dirname(__file__), "fixtures",
                        "w2v_hs_r5.zip")
    m = ser.read_word2vec_model(path)
    assert m.use_hs and m.vocab.num_words() == 4
    for w in m.vocab._index:  # HS codes/points survived the freeze
        assert w.codes is not None and w.points is not None
    assert np.isfinite(m.lookup_table.syn1).all()
