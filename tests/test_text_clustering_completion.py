"""VP-tree, document iterators, stopwords, CJK tokenizer tests.

Parity: ``clustering/vptree/VPTree.java``, ``text/documentiterator/``,
``text/stopwords``, and the pluggable tokenizer seam standing in for
``deeplearning4j-nlp-japanese`` / ``-korean``.
"""

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree, knn_brute
from deeplearning4j_tpu.text.documentiterator import (
    FileDocumentIterator, LabelledCollectionIterator, LabelsSource)
from deeplearning4j_tpu.text.stopwords import (
    get_stop_words, remove_stop_words)
from deeplearning4j_tpu.text.tokenization import tokenizer_factory


def test_vptree_matches_brute_force(rng):
    pts = rng.standard_normal((200, 8))
    queries = rng.standard_normal((10, 8))
    tree = VPTree(pts, metric="euclidean")
    bidx, bdist = knn_brute(pts, queries, k=5)
    for qi, q in enumerate(queries):
        tidx, tdist = tree.search(q, k=5)
        np.testing.assert_allclose(sorted(tdist), sorted(bdist[qi]), rtol=1e-5)
        assert set(tidx) == set(bidx[qi].tolist())


def test_vptree_cosine(rng):
    pts = rng.standard_normal((64, 6))
    tree = VPTree(pts, metric="cosine")
    idx, dist = tree.search(pts[7], k=1)
    assert idx[0] == 7
    assert dist[0] < 1e-9


def test_document_iterators(tmp_path):
    (tmp_path / "pos").mkdir()
    (tmp_path / "neg").mkdir()
    (tmp_path / "pos" / "a.txt").write_text("good great")
    (tmp_path / "neg" / "b.txt").write_text("bad awful")
    it = FileDocumentIterator(str(tmp_path))
    docs = []
    while it.has_next():
        d = it.next_document()
        docs.append((d, it.current_label()))
    assert ("good great", "pos") in docs and ("bad awful", "neg") in docs

    lit = LabelledCollectionIterator(["x y", "z"], ["A", "B"])
    assert lit.next_document() == "x y" and lit.current_label() == "A"

    src = LabelsSource()
    assert src.next_label() == "DOC_0" and src.next_label() == "DOC_1"
    assert src.get_labels() == ["DOC_0", "DOC_1"]


def test_stopwords():
    assert "the" in get_stop_words()
    assert remove_stop_words("the quick fox".split()) == ["quick", "fox"]


def test_cjk_tokenizer_registry():
    toks = tokenizer_factory("cjk").create("東京 hello").get_tokens()
    assert "東" in toks and "京" in toks and "東京" in toks and "hello" in toks
    default = tokenizer_factory("default").create("a b").get_tokens()
    assert default == ["a", "b"]


def test_viterbi_decode_matches_brute_force(rng):
    from itertools import product
    from deeplearning4j_tpu.util.viterbi import viterbi_decode
    t, k = 5, 3
    em = rng.standard_normal((t, k))
    A = rng.standard_normal((k, k))
    path, score = viterbi_decode(em, A)
    # brute force over all 3^5 paths
    best, best_p = -np.inf, None
    for p in product(range(k), repeat=t):
        s = em[0, p[0]] + sum(A[p[i - 1], p[i]] + em[i, p[i]] for i in range(1, t))
        if s > best:
            best, best_p = s, p
    assert tuple(path) == best_p
    assert abs(score - best) < 1e-4


def test_moving_window_matrix(rng):
    from deeplearning4j_tpu.util.viterbi import moving_window_matrix
    a = np.arange(12).reshape(3, 4)
    w = moving_window_matrix(a, 2, 2)
    assert w.shape == (6, 2, 2)
    np.testing.assert_array_equal(w[0], [[0, 1], [4, 5]])
    r = moving_window_matrix(a, 2, 2, rotate=1)
    assert r.shape == (6, 2, 2)


class TestLatticeTokenizer:
    """VERDICT r2 missing #3: Kuromoji's Viterbi-lattice role
    (``com/atilika/kuromoji/viterbi/ViterbiBuilder.java``) — dictionary
    segmentation must beat the n-gram fallback on known sentences."""

    def test_known_sentences_segment_to_words(self):
        from deeplearning4j_tpu.text.lattice import JapaneseTokenizerFactory

        f = JapaneseTokenizerFactory()
        assert f.create("私は東京大学の学生です").get_tokens() == \
            ["私", "は", "東京大学", "の", "学生", "です"]
        assert f.create("今日は日本語を勉強します").get_tokens() == \
            ["今日", "は", "日本語", "を", "勉強", "します"]

    def test_beats_ngram_fallback(self):
        """The n-gram fallback sprays overlapping bigrams; the lattice
        returns the actual word segmentation."""
        from deeplearning4j_tpu.text.lattice import JapaneseTokenizerFactory
        from deeplearning4j_tpu.text.tokenization import CJKTokenizerFactory

        text = "私は学生です"
        words = JapaneseTokenizerFactory().create(text).get_tokens()
        ngrams = CJKTokenizerFactory().create(text).get_tokens()
        assert words == ["私", "は", "学生", "です"]
        assert words != ngrams and len(ngrams) > len(words)

    def test_unknown_runs_merge(self):
        from deeplearning4j_tpu.text.lattice import (
            LatticeDictionary, viterbi_segment)

        seg = viterbi_segment("私はキセキです", LatticeDictionary.japanese())
        toks = [t for t, _ in seg]
        assert toks == ["私", "は", "キセキ", "です"]
        known = {t: k for t, k in seg}
        assert known["キセキ"] is False
        assert known["私"] is True

    def test_user_dictionary_tsv(self, tmp_path):
        from deeplearning4j_tpu.text.lattice import (
            JapaneseTokenizerFactory, LatticeDictionary, viterbi_segment)

        path = tmp_path / "user.tsv"
        path.write_text("キセキ\t3.0\n# comment\n", encoding="utf-8")
        d = LatticeDictionary.japanese().load_tsv(str(path))
        seg = viterbi_segment("私はキセキです", d)
        assert ("キセキ", True) in seg

    def test_mixed_scripts(self):
        from deeplearning4j_tpu.text.lattice import JapaneseTokenizerFactory

        toks = JapaneseTokenizerFactory().create("私はJAXが好き").get_tokens()
        assert "JAX" in toks and "私" in toks and "は" in toks

    def test_factory_registered(self):
        from deeplearning4j_tpu.text import lattice  # noqa: F401
        from deeplearning4j_tpu.text.lattice import JapaneseTokenizerFactory
        from deeplearning4j_tpu.text.tokenization import tokenizer_factory

        assert isinstance(tokenizer_factory("japanese"),
                          JapaneseTokenizerFactory)
