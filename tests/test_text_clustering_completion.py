"""VP-tree, document iterators, stopwords, CJK tokenizer tests.

Parity: ``clustering/vptree/VPTree.java``, ``text/documentiterator/``,
``text/stopwords``, and the pluggable tokenizer seam standing in for
``deeplearning4j-nlp-japanese`` / ``-korean``.
"""

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree, knn_brute
from deeplearning4j_tpu.text.documentiterator import (
    FileDocumentIterator, LabelledCollectionIterator, LabelsSource)
from deeplearning4j_tpu.text.stopwords import (
    get_stop_words, remove_stop_words)
from deeplearning4j_tpu.text.tokenization import tokenizer_factory


def test_vptree_matches_brute_force(rng):
    pts = rng.standard_normal((200, 8))
    queries = rng.standard_normal((10, 8))
    tree = VPTree(pts, metric="euclidean")
    bidx, bdist = knn_brute(pts, queries, k=5)
    for qi, q in enumerate(queries):
        tidx, tdist = tree.search(q, k=5)
        np.testing.assert_allclose(sorted(tdist), sorted(bdist[qi]), rtol=1e-5)
        assert set(tidx) == set(bidx[qi].tolist())


def test_vptree_cosine(rng):
    pts = rng.standard_normal((64, 6))
    tree = VPTree(pts, metric="cosine")
    idx, dist = tree.search(pts[7], k=1)
    assert idx[0] == 7
    assert dist[0] < 1e-9


def test_document_iterators(tmp_path):
    (tmp_path / "pos").mkdir()
    (tmp_path / "neg").mkdir()
    (tmp_path / "pos" / "a.txt").write_text("good great")
    (tmp_path / "neg" / "b.txt").write_text("bad awful")
    it = FileDocumentIterator(str(tmp_path))
    docs = []
    while it.has_next():
        d = it.next_document()
        docs.append((d, it.current_label()))
    assert ("good great", "pos") in docs and ("bad awful", "neg") in docs

    lit = LabelledCollectionIterator(["x y", "z"], ["A", "B"])
    assert lit.next_document() == "x y" and lit.current_label() == "A"

    src = LabelsSource()
    assert src.next_label() == "DOC_0" and src.next_label() == "DOC_1"
    assert src.get_labels() == ["DOC_0", "DOC_1"]


def test_stopwords():
    assert "the" in get_stop_words()
    assert remove_stop_words("the quick fox".split()) == ["quick", "fox"]


def test_cjk_tokenizer_registry():
    toks = tokenizer_factory("cjk").create("東京 hello").get_tokens()
    assert "東" in toks and "京" in toks and "東京" in toks and "hello" in toks
    default = tokenizer_factory("default").create("a b").get_tokens()
    assert default == ["a", "b"]


def test_viterbi_decode_matches_brute_force(rng):
    from itertools import product
    from deeplearning4j_tpu.util.viterbi import viterbi_decode
    t, k = 5, 3
    em = rng.standard_normal((t, k))
    A = rng.standard_normal((k, k))
    path, score = viterbi_decode(em, A)
    # brute force over all 3^5 paths
    best, best_p = -np.inf, None
    for p in product(range(k), repeat=t):
        s = em[0, p[0]] + sum(A[p[i - 1], p[i]] + em[i, p[i]] for i in range(1, t))
        if s > best:
            best, best_p = s, p
    assert tuple(path) == best_p
    assert abs(score - best) < 1e-4


def test_moving_window_matrix(rng):
    from deeplearning4j_tpu.util.viterbi import moving_window_matrix
    a = np.arange(12).reshape(3, 4)
    w = moving_window_matrix(a, 2, 2)
    assert w.shape == (6, 2, 2)
    np.testing.assert_array_equal(w[0], [[0, 1], [4, 5]])
    r = moving_window_matrix(a, 2, 2, rotate=1)
    assert r.shape == (6, 2, 2)
