"""Phase-timer instrumentation tests.

Parity: ``spark/stats/CommonSparkTrainingStats.java`` /
``StatsUtils.java`` — per-phase timings, export, cross-worker merge;
wired into ParallelWrapper via ``collect_stats=True``
(``setCollectTrainingStats`` role).
"""

import json
import time

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.training_stats import TrainingStats
from deeplearning4j_tpu.parallel import ParallelWrapper


def test_basic_aggregation_and_export(tmp_path):
    stats = TrainingStats()
    for ms in (1.0, 3.0, 2.0):
        stats.add("step", ms)
    with stats.time("data_wait"):
        time.sleep(0.01)
    s = stats.summary()
    assert s["step"]["count"] == 3
    assert s["step"]["total_ms"] == 6.0
    assert s["step"]["min_ms"] == 1.0 and s["step"]["max_ms"] == 3.0
    assert s["data_wait"]["mean_ms"] >= 9.0
    assert len(stats.timeline()) == 4
    path = stats.export_json(str(tmp_path / "stats.json"))
    loaded = json.load(open(path))
    assert loaded["summary"]["step"]["count"] == 3
    assert loaded["timeline"][0]["phase"] == "step"


def test_merge_namespacing():
    master, worker = TrainingStats(), TrainingStats()
    worker.add("step", 5.0)
    worker.add("step", 7.0)
    master.add("average", 1.0)
    master.merge(worker, prefix="worker1/")
    s = master.summary()
    assert s["worker1/step"]["count"] == 2
    assert s["average"]["count"] == 1
    # merging same-named phases accumulates
    master.merge(worker, prefix="worker1/")
    assert master.summary()["worker1/step"]["count"] == 4


def _net_and_data(rng):
    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
    return net, DataSet(x, y)


def test_parallel_wrapper_collects_phases(rng):
    net, ds = _net_and_data(rng)
    pw = ParallelWrapper(net, collect_stats=True)
    pw.fit(ds)
    s = pw.stats.summary()
    assert {"data_wait", "stage", "step"} <= set(s)
    assert s["step"]["count"] == 1
    assert all(v["total_ms"] >= 0 for v in s.values())


def test_parallel_wrapper_averaging_collects_average_phase(rng):
    net, ds = _net_and_data(rng)
    pw = ParallelWrapper(net, mode="averaging",
                         averaging_frequency=1, collect_stats=True)
    pw.fit(ds)
    s = pw.stats.summary()
    assert {"data_wait", "stage", "step", "average"} <= set(s)


def test_refit_same_iterator_with_stats(rng):
    """collect_stats must keep the for-loop reset semantics: fitting the
    same iterator twice trains both epochs (regression: _timed_batches
    skipped __iter__ -> reset())."""
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    net, ds = _net_and_data(rng)
    it = ListDataSetIterator(ds, 16)  # 32 examples -> 2 batches
    pw = ParallelWrapper(net, collect_stats=True)
    pw.fit(it)
    pw.fit(it)
    assert pw.stats.summary()["step"]["count"] == 4


def test_stats_off_by_default(rng):
    net, ds = _net_and_data(rng)
    pw = ParallelWrapper(net)
    pw.fit(ds)
    assert pw.stats is None
