"""Flash-attention Pallas kernel vs the XLA oracle.

The exact formulation in ``ops/attention.py`` is the correctness
oracle (same doctrine as ring attention); the kernel must match it in
forward AND gradients, causal and not, square and cross-length.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.attention import scaled_dot_product_attention
from deeplearning4j_tpu.ops.flash_attention import flash_attention


def _qkv(rng, b=2, tq=128, tk=128, h=2, d=64):
    mk = lambda t: jnp.asarray(
        rng.standard_normal((b, t, h, d)), jnp.float32)
    return mk(tq), mk(tk), mk(tk)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_oracle(rng, causal):
    q, k, v = _qkv(rng)
    got = flash_attention(q, k, v, causal=causal)
    want = scaled_dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_cross_length_causal(rng):
    """tq != tk exercises the diagonal offset (tril k=tk-tq)."""
    q, k, v = _qkv(rng, tq=64, tk=256)
    got = flash_attention(q, k, v, causal=True)
    want = scaled_dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_multi_kblock_accumulation(rng):
    """Long keys force several online-softmax steps per q block."""
    q, k, v = _qkv(rng, tq=32, tk=512, d=32)
    got = flash_attention(q, k, v, block_q=32, block_k=128)
    want = scaled_dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_backward_with_oversized_caller_blocks(rng):
    """A caller block > 512 that divides t while NO candidate <= 512
    does (t=1028 = 4·257: none of 512..8 divide it) must not
    ZeroDivisionError in the backward — it falls back to the forward
    block size."""
    t = 1028
    q, k, v = _qkv(rng, b=1, tq=t, tk=t, h=1, d=32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=t, block_k=t) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def loss_ref(q, k, v):
        return jnp.sum(scaled_dot_product_attention(q, k, v) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_oracle(rng, causal):
    q, k, v = _qkv(rng, b=1, tq=64, tk=64, h=1, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(scaled_dot_product_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_bf16_inputs(rng):
    q, k, v = _qkv(rng)
    got = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                          v.astype(jnp.bfloat16))
    assert got.dtype == jnp.bfloat16
    want = scaled_dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=3e-2, atol=3e-2)


def test_mask_falls_back(rng):
    """Key-validity masks take the XLA path — results must still match."""
    q, k, v = _qkv(rng, b=2, tq=16, tk=16)
    mask = np.ones((2, 16), np.float32)
    mask[:, 10:] = 0.0
    got = flash_attention(q, k, v, mask=jnp.asarray(mask))
    want = scaled_dot_product_attention(q, k, v, mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_odd_lengths_fall_back(rng):
    q, k, v = _qkv(rng, tq=17, tk=23, d=16)
    got = flash_attention(q, k, v)
    want = scaled_dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_jit_and_under_vmap(rng):
    q, k, v = _qkv(rng, b=1, tq=32, tk=32, d=32)
    jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    np.testing.assert_allclose(
        np.asarray(jitted(q, k, v)),
        np.asarray(scaled_dot_product_attention(q, k, v, causal=True)),
        rtol=2e-5, atol=2e-5)
