"""ZeRO-1 / FSDP sharding equivalence tests.

No reference counterpart (SURVEY §2.6 note 5); the oracle is replicated
training — placement must not change the math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import MeshContext, make_mesh
from deeplearning4j_tpu.parallel.zero import apply_fsdp, apply_zero1, fsdp_specs


def _net():
    conf = (NeuralNetConfiguration.builder().seed(17).learning_rate(0.05)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=8, n_out=16))
            .layer(DenseLayer(n_in=16, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _steps(net, ctx, x, y, n=5):
    step = net._get_jit("train", fm=False, lm=False)
    xs, ys = ctx.shard_batch(x, y)
    zero = jnp.zeros((), jnp.float32)
    key = jax.random.PRNGKey(3)
    for _ in range(n):
        net.params, net.opt_state, net.states, score = step(
            net.params, net.opt_state, net.states, xs, ys, zero, zero, key)
    return float(score), jax.device_get(net.params)


def _data(rng):
    x = rng.standard_normal((32, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
    return x, y


@pytest.mark.parametrize("apply_fn", [apply_fsdp, apply_zero1])
def test_sharded_training_matches_replicated(rng, apply_fn):
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh({"data": 8}, devices=devs[:8])
    ctx = MeshContext(mesh)
    x, y = _data(rng)

    ref = _net()
    score_ref, params_ref = _steps(ref, ctx, x, y)

    net = _net()
    apply_fn(net, mesh)
    score_sh, params_sh = _steps(net, ctx, x, y)

    assert score_sh == pytest.approx(score_ref, rel=1e-5)
    for ln in params_ref:
        for pn in params_ref[ln]:
            np.testing.assert_allclose(params_sh[ln][pn], params_ref[ln][pn],
                                       rtol=1e-5, atol=1e-6)


def test_fsdp_specs_pick_divisible_dims(rng):
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh({"data": 8}, devices=devs[:8])
    net = _net()
    specs = fsdp_specs(net, mesh)
    # 16-dim axes are divisible by 8; the [8,16] W shards its dim-1 (16)
    assert specs["layer0"]["W"] == jax.sharding.PartitionSpec(None, "data")
    assert specs["layer1"]["W"] in (jax.sharding.PartitionSpec("data", None),
                                    jax.sharding.PartitionSpec(None, "data"))
    # 4-dim bias of the output layer is indivisible -> absent (replicated)
    assert "b" not in specs.get("layer2", {})


def test_zero1_shards_only_optimizer_state(rng):
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh({"data": 8}, devices=devs[:8])
    net = _net()
    apply_zero1(net, mesh)
    # params replicated
    p_shard = net.params["layer0"]["W"].sharding
    assert p_shard.is_fully_replicated
    # adam moments sharded
    m = net.opt_state["updater"]["layer0"]["W"]
    leaf = jax.tree.leaves(m)[0]
    assert not leaf.sharding.is_fully_replicated
