"""Updater math vs hand-computed values — port of the reference's
``nn/updater/TestUpdaters.java`` doctrine (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.updater import (
    GradientNormalization,
    LearningRatePolicy,
    Updater,
    UpdaterConfig,
    apply_updater,
    effective_learning_rate,
    init_updater_state,
    normalize_gradient,
)


def _step(cfg, grad, state, it=0):
    return apply_updater(cfg, jnp.asarray(grad), state, jnp.asarray(it))


class TestUpdaterMath:
    def test_sgd(self):
        cfg = UpdaterConfig(updater="sgd", learning_rate=0.5)
        upd, _ = _step(cfg, [2.0, -4.0], {})
        np.testing.assert_allclose(upd, [1.0, -2.0])

    def test_none_passthrough(self):
        cfg = UpdaterConfig(updater="none")
        upd, _ = _step(cfg, [3.0], {})
        np.testing.assert_allclose(upd, [3.0])

    def test_adam_first_step_hand_math(self):
        lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
        cfg = UpdaterConfig(updater="adam", learning_rate=lr, adam_mean_decay=b1,
                            adam_var_decay=b2, epsilon=eps)
        g = np.array([0.5, -1.0])
        st = init_updater_state(cfg, jnp.asarray(g))
        upd, st2 = _step(cfg, g, st, it=0)
        m = (1 - b1) * g
        v = (1 - b2) * g * g
        alpha = lr * np.sqrt(1 - b2) / (1 - b1)
        np.testing.assert_allclose(upd, alpha * m / (np.sqrt(v) + eps), rtol=3e-5)  # pow() on this backend has ~1e-5 noise
        np.testing.assert_allclose(st2["m"], m, rtol=1e-6)
        np.testing.assert_allclose(st2["v"], v, rtol=1e-6)

    def test_adagrad_accumulates(self):
        cfg = UpdaterConfig(updater="adagrad", learning_rate=0.1, epsilon=1e-8)
        g = np.array([1.0, 2.0])
        st = init_updater_state(cfg, jnp.asarray(g))
        upd1, st = _step(cfg, g, st)
        np.testing.assert_allclose(upd1, 0.1 * g / (np.abs(g) + 1e-8), rtol=1e-6)
        _, st = _step(cfg, g, st)
        np.testing.assert_allclose(st["h"], 2 * g * g, rtol=1e-6)

    def test_nesterov_mu_zero_is_sgd(self):
        cfg = UpdaterConfig(updater="nesterovs", learning_rate=0.2, momentum=0.0)
        g = np.array([1.0])
        st = init_updater_state(cfg, jnp.asarray(g))
        upd, _ = _step(cfg, g, st)
        np.testing.assert_allclose(upd, [0.2], rtol=1e-6)

    def test_nesterov_momentum_hand_math(self):
        mu, lr = 0.9, 0.1
        cfg = UpdaterConfig(updater="nesterovs", learning_rate=lr, momentum=mu)
        g = np.array([1.0])
        st = init_updater_state(cfg, jnp.asarray(g))
        upd, st = _step(cfg, g, st)
        v1 = -lr * g  # mu*0 - lr*g
        np.testing.assert_allclose(upd, mu * 0 - (1 + mu) * v1, rtol=1e-6)
        np.testing.assert_allclose(st["v"], v1, rtol=1e-6)

    def test_rmsprop_hand_math(self):
        lr, d, eps = 0.01, 0.95, 1e-8
        cfg = UpdaterConfig(updater="rmsprop", learning_rate=lr, rms_decay=d, epsilon=eps)
        g = np.array([2.0])
        st = init_updater_state(cfg, jnp.asarray(g))
        upd, st = _step(cfg, g, st)
        cache = (1 - d) * g * g
        np.testing.assert_allclose(upd, lr * g / (np.sqrt(cache) + eps), rtol=1e-6)

    def test_adadelta_no_lr_dependence(self):
        cfg = UpdaterConfig(updater="adadelta", rho=0.95, epsilon=1e-6)
        g = np.array([1.5])
        st = init_updater_state(cfg, jnp.asarray(g))
        upd, st2 = _step(cfg, g, st)
        msg = 0.05 * g * g
        expected = g * np.sqrt(0.0 + 1e-6) / np.sqrt(msg + 1e-6)
        np.testing.assert_allclose(upd, expected, rtol=1e-5)


class TestLrPolicies:
    def test_exponential(self):
        cfg = UpdaterConfig(learning_rate=1.0, lr_policy="exponential", lr_policy_decay_rate=0.5)
        np.testing.assert_allclose(effective_learning_rate(cfg, jnp.asarray(2)), 0.25, rtol=1e-5)

    def test_step(self):
        cfg = UpdaterConfig(learning_rate=1.0, lr_policy="step", lr_policy_decay_rate=0.1,
                            lr_policy_steps=10.0)
        np.testing.assert_allclose(effective_learning_rate(cfg, jnp.asarray(25)), 0.01, rtol=1e-5)

    def test_schedule_map(self):
        cfg = UpdaterConfig(learning_rate=0.1, lr_policy="schedule",
                            lr_schedule={5: 0.01, 10: 0.001})
        np.testing.assert_allclose(effective_learning_rate(cfg, jnp.asarray(0)), 0.1)
        np.testing.assert_allclose(effective_learning_rate(cfg, jnp.asarray(7)), 0.01)
        np.testing.assert_allclose(effective_learning_rate(cfg, jnp.asarray(100)), 0.001)

    def test_poly(self):
        cfg = UpdaterConfig(learning_rate=1.0, lr_policy="poly", lr_policy_power=2.0,
                            max_iterations=10)
        np.testing.assert_allclose(effective_learning_rate(cfg, jnp.asarray(5)), 0.25, rtol=1e-5)


class TestGradientNormalization:
    def test_clip_elementwise(self):
        g = {"W": jnp.array([3.0, -0.2]), "b": jnp.array([-9.0])}
        out = normalize_gradient(GradientNormalization.CLIP_ELEMENTWISE_ABSOLUTE_VALUE, g, 1.0)
        np.testing.assert_allclose(out["W"], [1.0, -0.2])
        np.testing.assert_allclose(out["b"], [-1.0])

    def test_renormalize_l2_per_layer(self):
        g = {"W": jnp.array([3.0]), "b": jnp.array([4.0])}
        out = normalize_gradient(GradientNormalization.RENORMALIZE_L2_PER_LAYER, g)
        np.testing.assert_allclose(out["W"], [0.6], rtol=1e-5)
        np.testing.assert_allclose(out["b"], [0.8], rtol=1e-5)

    def test_clip_l2_per_layer_noop_when_small(self):
        g = {"W": jnp.array([0.1])}
        out = normalize_gradient(GradientNormalization.CLIP_L2_PER_LAYER, g, threshold=5.0)
        np.testing.assert_allclose(out["W"], [0.1], rtol=1e-6)
