"""Clustering algorithm framework tests.

Parity: ``clustering/algorithm/`` (VERDICT r2 missing #1) — strategy
setup/termination/optimization semantics mirror
``BaseClusteringAlgorithm.java`` / ``FixedClusterCountStrategy.java`` /
``OptimisationStrategy.java`` and the three conditions.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    BaseClusteringAlgorithm,
    ClusteringOptimizationType,
    ConvergenceCondition,
    FixedClusterCountStrategy,
    FixedIterationCountCondition,
    OptimisationStrategy,
    VarianceVariationCondition,
)
from deeplearning4j_tpu.clustering.algorithm import (
    ClusterSetInfo,
    IterationHistory,
    IterationInfo,
)


def _blobs(rng, k=3, per=50, d=4, spread=0.15):
    centers = rng.standard_normal((k, d)) * 4.0
    pts = np.concatenate([c + spread * rng.standard_normal((per, d))
                          for c in centers])
    return pts.astype(np.float32), centers


def _info(var=1.0, moved=0, counts=(5, 5), n=10, avg=None, mx=None):
    counts = np.asarray(counts)
    k = len(counts)
    return ClusterSetInfo(
        points_count=n, cluster_point_counts=counts,
        average_point_distance=np.asarray(avg if avg is not None else [0.5] * k),
        max_point_distance=np.asarray(mx if mx is not None else [1.0] * k),
        distance_variance=var, point_location_change=moved)


def _history(infos):
    h = IterationHistory()
    for i, info in enumerate(infos, start=1):
        h.add(IterationInfo(i, info))
    return h


class TestConditions:
    def test_fixed_iteration_count(self):
        cond = FixedIterationCountCondition.iteration_count_greater_than(3)
        assert not cond.is_satisfied(_history([_info()] * 2))
        assert cond.is_satisfied(_history([_info()] * 3))

    def test_convergence_needs_two_iterations(self):
        cond = ConvergenceCondition.distribution_variation_rate_less_than(0.1)
        assert not cond.is_satisfied(_history([_info(moved=0)]))

    def test_convergence_rate(self):
        cond = ConvergenceCondition.distribution_variation_rate_less_than(0.1)
        # 3/10 points moved -> 0.3 >= 0.1: not converged
        assert not cond.is_satisfied(_history([_info(), _info(moved=3)]))
        # 0/10 moved -> 0.0 < 0.1: converged
        assert cond.is_satisfied(_history([_info(), _info(moved=0)]))

    def test_variance_variation_over_period(self):
        cond = VarianceVariationCondition.variance_variation_less_than(0.05, 2)
        # needs more than `period` iterations
        assert not cond.is_satisfied(_history([_info(var=1.0), _info(var=1.0)]))
        # stable variance across the window: satisfied
        assert cond.is_satisfied(
            _history([_info(var=1.0), _info(var=1.01), _info(var=1.012)]))
        # a >=5% jump inside the window: not satisfied
        assert not cond.is_satisfied(
            _history([_info(var=1.0), _info(var=1.5), _info(var=1.51)]))


class TestFixedClusterCount:
    def test_recovers_blobs(self, rng):
        pts, _ = _blobs(rng, k=3)
        strategy = (FixedClusterCountStrategy.setup(3, "euclidean")
                    .end_when_distribution_variation_rate_less_than(0.01))
        cs = BaseClusteringAlgorithm.setup(strategy, seed=7).apply_to(pts)
        assert len(cs) == 3
        sizes = sorted(len(c) for c in cs)
        assert sizes == [50, 50, 50]

    def test_iteration_count_termination(self, rng):
        pts, _ = _blobs(rng, k=2, per=30)
        strategy = (FixedClusterCountStrategy.setup(2, "euclidean")
                    .end_when_iteration_count_equals(4))
        algo = BaseClusteringAlgorithm.setup(strategy, seed=3)
        algo.apply_to(pts)
        assert algo.history.get_iteration_count() >= 4

    def test_history_records_stats(self, rng):
        pts, _ = _blobs(rng, k=2, per=20)
        strategy = (FixedClusterCountStrategy.setup(2, "euclidean")
                    .end_when_iteration_count_equals(3))
        algo = BaseClusteringAlgorithm.setup(strategy, seed=1)
        algo.apply_to(pts)
        info = algo.history.get_most_recent_cluster_set_info()
        assert info.points_count == 40
        assert info.cluster_point_counts.sum() == 40
        assert np.isfinite(info.point_distance_from_cluster_variance)
        # converged: nobody moves on the last iteration
        assert info.point_location_change == 0

    def test_default_termination_installed(self, rng):
        pts, _ = _blobs(rng, k=2, per=10)
        algo = BaseClusteringAlgorithm.setup(
            FixedClusterCountStrategy.setup(2), seed=5)
        cs = algo.apply_to(pts)  # must terminate without explicit cond
        assert len(cs) == 2


class TestOptimisationStrategy:
    def test_splits_to_meet_average_distance_bound(self, rng):
        """Starting with fewer clusters than natural blobs, the
        optimization splits wide clusters until the bound holds."""
        pts, _ = _blobs(rng, k=4, per=40, spread=0.05)
        strategy = (OptimisationStrategy.setup(2, "euclidean")
                    .optimize(ClusteringOptimizationType.
                              MINIMIZE_AVERAGE_POINT_TO_CENTER_DISTANCE, 1.0)
                    .optimize_when_iteration_count_multiple_of(1)
                    .end_when_distribution_variation_rate_less_than(0.01))
        algo = BaseClusteringAlgorithm.setup(strategy, seed=11)
        cs = algo.apply_to(pts)
        assert len(cs) >= 4  # split up from the initial 2
        info = algo.history.get_most_recent_cluster_set_info()
        live = info.cluster_point_counts > 0
        assert (info.average_point_distance[live] <= 1.0).all()

    def test_no_split_when_bound_already_met(self, rng):
        pts, _ = _blobs(rng, k=2, per=30, spread=0.05)
        strategy = (OptimisationStrategy.setup(2, "euclidean")
                    .optimize(ClusteringOptimizationType.
                              MINIMIZE_MAXIMUM_POINT_TO_CENTER_DISTANCE, 50.0)
                    .optimize_when_iteration_count_multiple_of(1)
                    .end_when_distribution_variation_rate_less_than(0.01))
        cs = BaseClusteringAlgorithm.setup(strategy, seed=2).apply_to(pts)
        assert len(cs) == 2

    def test_unimplemented_types_are_noops(self, rng):
        """Reference parity: ClusterUtils.applyOptimization only acts on
        the two point-to-center types (ClusterUtils.java:215-235)."""
        pts, _ = _blobs(rng, k=2, per=20)
        strategy = (OptimisationStrategy.setup(2, "euclidean")
                    .optimize(ClusteringOptimizationType.
                              MINIMIZE_PER_CLUSTER_POINT_COUNT, 1.0)
                    .optimize_when_iteration_count_multiple_of(1)
                    .end_when_iteration_count_equals(3))
        cs = BaseClusteringAlgorithm.setup(strategy, seed=2).apply_to(pts)
        assert len(cs) == 2


def test_cluster_set_result_api(rng):
    """The framework returns the same queryable ClusterSet the direct
    KMeansClustering path builds."""
    pts, _ = _blobs(rng, k=2, per=25)
    strategy = (FixedClusterCountStrategy.setup(2, "euclidean")
                .end_when_distribution_variation_rate_less_than(0.01))
    cs = BaseClusteringAlgorithm.setup(strategy, seed=9).apply_to(pts)
    c = cs.cluster_of(pts[0])
    assert 0 in c.point_indices
    assert cs.total_average_distance() >= 0.0


def test_strategy_json_round_trip(rng):
    """Strategies/conditions serialize like the reference's
    Serializable framework — config survives a JSON round trip and the
    restored strategy clusters identically."""
    import json

    from deeplearning4j_tpu.clustering.algorithm import ClusteringStrategy

    s = (OptimisationStrategy.setup(2, "euclidean")
         .optimize(ClusteringOptimizationType.
                   MINIMIZE_AVERAGE_POINT_TO_CENTER_DISTANCE, 1.0)
         .optimize_when_iteration_count_multiple_of(1)
         .end_when_distribution_variation_rate_less_than(0.01))
    d = json.loads(json.dumps(s.to_dict()))
    r = ClusteringStrategy.from_dict(d)
    assert isinstance(r, OptimisationStrategy)
    assert r.get_clustering_optimization_value() == 1.0
    assert r.is_optimization_defined()

    pts, _ = _blobs(rng, k=3, per=30)
    a = BaseClusteringAlgorithm.setup(s, seed=4).apply_to(pts)
    b = BaseClusteringAlgorithm.setup(r, seed=4).apply_to(pts)
    assert len(a) == len(b)

    f = (FixedClusterCountStrategy.setup(3)
         .end_when_iteration_count_equals(5))
    r2 = ClusteringStrategy.from_dict(json.loads(json.dumps(f.to_dict())))
    assert isinstance(r2, FixedClusterCountStrategy)
    assert r2.initial_cluster_count == 3
