"""Continuous batching tests (serving/continuous.py + nn/kvpool.py).

The ISSUE-8 battery: token-for-token parity vs ``generate_eager`` for
sequences admitted mid-stream, preempted + resumed, and served across
a PR-7 canary cutover (the session keeps its version); the
deterministic lowest-priority/youngest-first preemption order under a
tiny pool; the zero-steady-state-compile assertion via
``dl4j_jit_cache_miss_total``; paged-vs-dense decode_step parity; pool
accounting (free returns to total after drain, typed exhaustion,
bounded-queue shedding); the kill-mid-burst recovery contract; and the
``stats()`` / ``/healthz/ready`` scheduler-warmup gate + the
``dl4j_kvpool_*`` / ``dl4j_sched_*`` schema pinning.
"""

import json
import sys
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import BurstKill, InjectedFault
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.generate import build_generator, generate_eager
from deeplearning4j_tpu.nn.kvpool import PagedKVCachePool
from deeplearning4j_tpu.parallel.inference import (InferenceBackpressure,
                                                   ParallelInference)
from deeplearning4j_tpu.serving.continuous import (
    ContinuousDecodeScheduler,
    DecodeBurstError,
    KVPoolExhausted,
)
from deeplearning4j_tpu.serving.registry import ModelRegistry

VOCAB = 11


def _tiny_gpt(seed=0, **kw):
    return gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=seed, **kw).init()


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


def _sched(net, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("burst_tokens", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("start", False)
    return ContinuousDecodeScheduler(net=net, **kw)


def _drive(sched, futures, max_steps=200):
    for _ in range(max_steps):
        if all(f.done() for f in futures):
            return
        sched.step()
    raise AssertionError(
        f"schedule did not converge in {max_steps} steps; "
        f"events={list(sched.events)}")


# ------------------------------------------------- paged decode_step

def test_paged_decode_step_matches_dense(rng):
    """The block-table gather/scatter branch must reproduce the dense
    decode_step at every position: same token, same cache values, just
    paged through the shared pool."""
    net = _tiny_gpt()
    blk = net.impls[1]
    params = net.params[blk.name]
    b, d, bs, mb, nb_pool = 2, 16, 4, 3, 8
    dense = blk.init_cache(b, mb * bs)
    kp = {"k": jnp.zeros((nb_pool, bs, 2, 8)),
          "v": jnp.zeros((nb_pool, bs, 2, 8))}
    # distinct blocks per row, allocated out of order on purpose
    table = jnp.asarray([[3, 1, 5], [2, 6, 4]], jnp.int32)
    pos = np.zeros(b, np.int32)
    for step in range(7):
        x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
        pv = jnp.asarray(pos)
        y_dense, dense = blk.decode_step(params, x, dense, pv)
        y_paged, paged = blk.decode_step(
            params, x, {"k": kp["k"], "v": kp["v"], "table": table}, pv,
            write_mask=jnp.ones(b, bool))
        kp = {"k": paged["k"], "v": paged["v"]}
        np.testing.assert_allclose(np.asarray(y_dense), np.asarray(y_paged),
                                   rtol=1e-5, atol=1e-5)
        pos += 1
    # the paged pool holds exactly the dense cache's rows, block-permuted
    for row in range(b):
        gathered = np.asarray(kp["k"])[np.asarray(table)[row]].reshape(
            mb * bs, 2, 8)
        np.testing.assert_allclose(
            gathered[:7], np.asarray(dense["k"])[row, :7], rtol=0, atol=0)


def test_kvpool_accounting():
    pool = PagedKVCachePool(8, 4, num_layers=2, num_heads=2, head_dim=8)
    assert pool.total_blocks == 7 and pool.free_count == 7
    a = pool.alloc(3)
    assert a == [1, 2, 3] and pool.free_count == 4
    assert pool.alloc(5) is None  # exhausted: nothing claimed
    assert pool.free_count == 4 and pool.stats()["alloc_failures"] == 1
    pool.free_blocks(a)
    assert pool.free_count == 7
    assert pool.alloc(1) == [1]  # lowest-id-first: deterministic replay
    with pytest.raises(ValueError):
        pool.free_blocks([0])  # the trash block is never allocatable


# ------------------------------------------------------ parity battery

def test_staggered_admission_matches_eager(rng):
    """A request admitted MID-STREAM (slots already decoding) must be
    token-for-token identical to its solo eager run."""
    net = _tiny_gpt()
    s = _sched(net)
    p1 = rng.integers(0, VOCAB, (2, 5))
    f1 = s.submit(p1, 10)
    s.step()  # p1 admitted + first burst dispatched
    assert s.stats()["active_sequences"] == 2
    p2 = rng.integers(0, VOCAB, (1, 3))
    f2 = s.submit(p2, 6)  # arrives one burst after dispatch
    _drive(s, [f1, f2])
    assert np.array_equal(f1.result(0), generate_eager(net, p1, 10))
    assert np.array_equal(f2.result(0), generate_eager(net, p2, 6))
    st = s.stats()
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]


def test_eos_retires_between_bursts_and_fills(rng):
    """EOS rows retire between bursts (blocks freed immediately) and a
    finished row's remaining slots are filled with the EOS id — the
    whole-burst contract, kept."""
    net = _tiny_gpt()
    prompt = rng.integers(0, VOCAB, (2, 4))
    want = generate_eager(net, prompt, 12, eos_token=3)
    s = _sched(net)
    f = s.submit(prompt, 12, eos_token=3)
    _drive(s, [f])
    assert np.array_equal(f.result(0), want)
    st = s.stats()
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]


def test_preempt_resume_matches_eager(rng):
    """A pool too small for the offered load must preempt (blocks
    freed, victim re-queued with its generated prefix) and the resumed
    sequences must still match their uninterrupted eager runs."""
    net = _tiny_gpt()
    # 8 usable blocks of 4 tokens; three sequences growing to 15 tokens
    # each (4 blocks) cannot coexist
    s = _sched(net, num_blocks=9)
    prompts = [rng.integers(0, VOCAB, (1, 5)) for _ in range(3)]
    futs = [s.submit(p, 10) for p in prompts]
    _drive(s, futs)
    for f, p in zip(futs, prompts):
        assert np.array_equal(f.result(0), generate_eager(net, p, 10))
    st = s.stats()
    assert st["preemptions"] > 0
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]


def test_deterministic_preemption_order(rng):
    """The victim policy is lowest-priority first, youngest-admitted
    tie-break — and the whole schedule replays identically."""
    net = _tiny_gpt()
    prompts = [rng.integers(0, VOCAB, (1, 5)) for _ in range(3)]

    def run():
        s = _sched(net, num_blocks=9)
        futs = [s.submit(p, 10, priority=pr)
                for p, pr in zip(prompts, (5, 1, 1))]
        _drive(s, futs)
        return s, futs

    s1, futs1 = run()
    preempts = [e for e in s1.events if e.startswith("preempt")]
    assert preempts, "tiny pool must preempt"
    # seq_id 2 and 3 share the lowest priority (1); the YOUNGEST (3)
    # loses first, and seq 1 (priority 5) is never a victim
    assert preempts[0].startswith("preempt seq=3 prio=1")
    assert not any("seq=1 " in e for e in preempts)
    s2, futs2 = run()
    assert list(s1.events) == list(s2.events)
    for a, b in zip(futs1, futs2):
        assert np.array_equal(a.result(0), b.result(0))


def test_sampled_draws_invariant_to_cotenants(rng):
    """A temperature-sampled request's draws ride its own per-row PRNG
    clock: the same seed yields the same tokens whether it runs alone
    or crowded by cotenants (and across preemption-free replays)."""
    net = _tiny_gpt()
    p = rng.integers(0, VOCAB, (1, 4))
    s1 = _sched(net)
    f_alone = s1.submit(p, 8, temperature=0.8, top_k=5, seed=7)
    _drive(s1, [f_alone])
    s2 = _sched(net)
    crowd = [s2.submit(rng.integers(0, VOCAB, (1, 6)), 10, seed=i)
             for i in range(2)]
    f_crowded = s2.submit(p, 8, temperature=0.8, top_k=5, seed=7)
    _drive(s2, crowd + [f_crowded])
    assert np.array_equal(f_alone.result(0), f_crowded.result(0))


# -------------------------------------------- engine + canary cutover

def test_engine_routes_and_canary_cutover_session_pins(rng, fresh_registry):
    """``ParallelInference(continuous=True, registry=...)``: a decode
    session admitted on v1 keeps resolving v1 through a deploy (the
    KV blocks and programs live with the version), new sessions get
    v2, and both lanes share ONE block pool."""
    net1, net2 = _tiny_gpt(seed=1), _tiny_gpt(seed=9)
    reg = ModelRegistry()
    reg.register("lm", net=net1)
    eng = ParallelInference(registry=reg, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4, kv_block_size=4)
    try:
        p = rng.integers(0, VOCAB, (1, 5))
        assert np.array_equal(
            eng.submit_generate(p, 8, model="lm", session="s1").result(30),
            generate_eager(net1, p, 8))
        reg.deploy("lm", net=net2)  # atomic cutover to v2
        # same session: still v1 — a mid-stream hot-swap never switches
        # the KV-cache owner
        assert np.array_equal(
            eng.submit_generate(p, 8, model="lm", session="s1").result(30),
            generate_eager(net1, p, 8))
        # fresh session: the new active version
        assert np.array_equal(
            eng.submit_generate(p, 8, model="lm", session="s2").result(30),
            generate_eager(net2, p, 8))
        st = eng.stats()["scheduler"]
        assert st["lanes"] == 2 and len(st["pools"]) == 1
        assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
    finally:
        eng.shutdown()


def test_zero_steady_state_compiles(rng, fresh_registry):
    """After ``warmup_generate`` the continuous path serves ANY request
    mix inside the warmed buckets with zero XLA compiles — the fixed
    (slots × K × max_blocks) burst shape is sequence-independent."""
    net = _tiny_gpt()
    eng = ParallelInference(net, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4, kv_block_size=4)
    try:
        compiled = eng.warmup_generate([2, 4, 8], 8)
        assert compiled > 0
        assert eng.stats()["scheduler"]["warmed"]
        miss0 = fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        futs = [eng.submit_generate(rng.integers(0, VOCAB, (1, t)), mn,
                                    temperature=temp, seed=i)
                for i, (t, mn, temp) in enumerate(
                    [(3, 8, 0.0), (5, 4, 0.5), (8, 6, 0.0), (2, 3, 0.9)])]
        for f in futs:
            f.result(30)
        assert fresh_registry.family_total(
            monitor.JIT_CACHE_MISS_COUNTER) == miss0
    finally:
        eng.shutdown()


# ----------------------------------------------- shedding + exhaustion

def test_pool_exhausted_fails_typed(rng):
    """A sequence that cannot fit even alone fails fast and typed —
    never a deadlocked queue."""
    net = _tiny_gpt()
    s = _sched(net, num_blocks=3)  # 2 usable blocks = 8 tokens
    f = s.submit(rng.integers(0, VOCAB, (1, 10)), 8)
    for _ in range(5):
        if f.done():
            break
        s.step()
    with pytest.raises(KVPoolExhausted):
        f.result(0)
    assert s.stats()["pool"]["blocks_free"] == s.stats()["pool"]["blocks_total"]


def test_queue_full_sheds(rng):
    net = _tiny_gpt()
    s = _sched(net, queue_capacity=2)
    s.submit(rng.integers(0, VOCAB, (2, 4)), 4)
    with pytest.raises(InferenceBackpressure):
        s.submit(rng.integers(0, VOCAB, (1, 4)), 4)
    _drive(s, [])  # drain what was accepted
    s.shutdown()


def test_recurrent_net_rejected():
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.01).updater("adam").activation("tanh")
            .list()
            .layer(GravesLSTM(n_in=7, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=7, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="continuous batching"):
        ContinuousDecodeScheduler(net=net, start=False)


# ------------------------------------------------------- fault domain

@pytest.mark.faultinject
def test_kill_mid_burst_frees_blocks_and_fails_typed(rng, fresh_registry):
    """The BurstKill contract: a burst dying under live sequences fails
    their futures typed (DecodeBurstError ← InjectedFault), frees every
    riding block immediately, and the scheduler keeps serving — pool
    free returns to total after drain, never a leaked block."""
    net = _tiny_gpt()
    kill = BurstKill(after=1, failures=1)
    s = _sched(net, burst_hook=kill)
    p1 = rng.integers(0, VOCAB, (2, 5))
    f1 = s.submit(p1, 10)
    for _ in range(60):
        if f1.done():
            break
        s.step()
    with pytest.raises(DecodeBurstError) as ei:
        f1.result(0)
    assert isinstance(ei.value.__cause__, InjectedFault)
    assert kill.hits == 1
    st = s.stats()
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
    # the scheduler survives: the next request serves normally
    p2 = rng.integers(0, VOCAB, (1, 4))
    f2 = s.submit(p2, 6)
    _drive(s, [f2])
    assert np.array_equal(f2.result(0), generate_eager(net, p2, 6))
    st = s.stats()
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
    assert fresh_registry.family_total(monitor.FAULT_EVENTS_COUNTER) >= 1


@pytest.mark.faultinject
def test_engine_kill_mid_burst_seam(rng, fresh_registry):
    """The engine-level seam (decode_burst_hook=) arms the same
    injector through ParallelInference."""
    net = _tiny_gpt()
    kill = BurstKill(after=0, failures=1)
    eng = ParallelInference(net, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4, decode_burst_hook=kill)
    try:
        f = eng.submit_generate(rng.integers(0, VOCAB, (1, 5)), 8)
        with pytest.raises(DecodeBurstError):
            f.result(30)
        p = rng.integers(0, VOCAB, (1, 4))
        assert np.array_equal(eng.submit_generate(p, 6).result(30),
                              generate_eager(net, p, 6))
        st = eng.stats()["scheduler"]
        assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
        assert eng.drain(5)
    finally:
        eng.shutdown()


# ------------------------------------- durable streams (token deltas)

class _Collector:
    """on_tokens audit: asserts append-only delivery while recording."""

    def __init__(self):
        self.chunks = []

    def __call__(self, off, toks):
        self.chunks.append((int(off),
                            [int(t) for t in np.asarray(toks).reshape(-1)]))

    def tokens(self, base=0):
        """Concatenated deltas, asserting contiguous offsets from
        ``base`` (0 for a fresh stream, len(prefix) for a resume)."""
        toks = []
        for off, ts in self.chunks:
            assert off == base + len(toks), \
                f"gap/repeat at {off}: {self.chunks}"
            toks.extend(ts)
        return toks


def test_stream_deltas_match_eager(rng, fresh_registry):
    """on_tokens receives per-burst deltas whose concatenation IS the
    eager sequence — offsets contiguous from 0, chunk counter ticks."""
    net = _tiny_gpt()
    p = rng.integers(0, VOCAB, (1, 5))
    want = generate_eager(net, p, 10)
    coll = _Collector()
    s = _sched(net)
    f = s.submit(p, 10, on_tokens=coll)
    _drive(s, [f])
    assert np.array_equal(f.result(0), want)
    assert coll.tokens() == [int(t) for t in want[0, 5:]]
    assert len(coll.chunks) > 1  # genuinely incremental, not terminal
    assert fresh_registry.family_total(
        monitor.STREAM_CHUNKS_COUNTER) == len(coll.chunks)


def test_stream_deltas_survive_preemption(rng):
    """A preempted-and-resumed stream keeps its delivery cursor: no
    token is re-emitted after the resume, and the delivered stream is
    still the uninterrupted eager sequence."""
    net = _tiny_gpt()
    s = _sched(net, num_blocks=9)  # tiny pool: forces preemption
    prompts = [rng.integers(0, VOCAB, (1, 5)) for _ in range(3)]
    colls = [_Collector() for _ in prompts]
    futs = [s.submit(p, 10, on_tokens=c) for p, c in zip(prompts, colls)]
    _drive(s, futs)
    assert s.stats()["preemptions"] > 0
    for f, p, c in zip(futs, prompts, colls):
        want = generate_eager(net, p, 10)
        assert np.array_equal(f.result(0), want)
        assert c.tokens() == [int(t) for t in want[0, 5:]]


def test_prefix_resume_matches_eager_and_reprefills_only_prefix(rng):
    """The cross-engine migration contract, scheduler-level: a stream
    interrupted after k tokens resumes on a FRESH scheduler from
    prompt + prefix — greedy AND seeded-sampled output token-for-token
    equal to an uninterrupted run, offsets continuing at k, and the
    resume admitted ONE row prefilled at t0 + k (resumed, not
    restarted — pinned via the admit event and the admitted-rows
    count)."""
    net = _tiny_gpt()
    p = rng.integers(0, VOCAB, (1, 5))
    for sampler in ({}, {"temperature": 0.8, "top_k": 5, "seed": 7}):
        want = generate_eager(net, p, 10, **sampler)
        k = 4
        prefix = np.asarray([int(t) for t in want[0, 5:5 + k]])
        s2 = _sched(net)
        coll = _Collector()
        f = s2.submit(p, 10, prefix=prefix, on_tokens=coll, **sampler)
        _drive(s2, [f])
        assert np.array_equal(f.result(0), want), sampler
        # delivered offsets CONTINUE after the prefix — nothing re-emitted
        assert coll.chunks[0][0] == k
        assert coll.tokens(base=k) == [int(t) for t in want[0, 5 + k:]]
        # resumed, not restarted: one admission, prefilled at t0+k
        admits = [e for e in s2.events if e.startswith("admit")]
        assert len(admits) == 1 and f" t={5 + k} " in admits[0], admits
        assert s2.stats()["admitted_rows"] == 1
        st = s2.stats()
        assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]


def test_prefix_covering_max_new_short_circuits(rng):
    """Only the terminal frame was lost: a resume whose prefix already
    holds every token resolves immediately, no admission at all."""
    net = _tiny_gpt()
    p = rng.integers(0, VOCAB, (1, 5))
    want = generate_eager(net, p, 6)
    s = _sched(net)
    f = s.submit(p, 6, prefix=np.asarray(want[0, 5:]))
    assert f.done()
    assert np.array_equal(f.result(0), want)
    assert s.stats()["admitted_rows"] == 0
    assert s.drain(1)  # accounting stayed consistent


def test_streaming_requires_single_row(rng):
    net = _tiny_gpt()
    s = _sched(net)
    with pytest.raises(ValueError, match="per-stream"):
        s.submit(rng.integers(0, VOCAB, (2, 5)), 4, on_tokens=lambda o, t: 0)
    with pytest.raises(ValueError, match="per-stream"):
        s.submit(rng.integers(0, VOCAB, (2, 5)), 4, prefix=np.asarray([1]))


def test_engine_stream_and_prefix_seams(rng, fresh_registry):
    """ParallelInference plumbs on_tokens/prefix: the continuous
    engine streams per-burst deltas and resumes from a prefix; the
    whole-burst engine degrades to ONE terminal chunk and rejects
    prefix typed (resume rides the iteration-level machinery)."""
    net = _tiny_gpt()
    p = rng.integers(0, VOCAB, (1, 5))
    want = generate_eager(net, p, 8)
    cont = ParallelInference(net, replicas=1, continuous=True,
                             decode_slots=4, decode_burst=4,
                             kv_block_size=4)
    try:
        coll = _Collector()
        f = cont.submit_generate(p, 8, on_tokens=coll)
        assert np.array_equal(f.result(30), want)
        assert coll.tokens() == [int(t) for t in want[0, 5:]]
        coll2 = _Collector()
        f2 = cont.submit_generate(p, 8, prefix=np.asarray(want[0, 5:8]),
                                  on_tokens=coll2)
        assert np.array_equal(f2.result(30), want)
        assert coll2.tokens(base=3) == [int(t) for t in want[0, 8:]]
    finally:
        cont.shutdown()
    whole = ParallelInference(net, replicas=1)
    try:
        coll3 = _Collector()
        f3 = whole.submit_generate(p, 8, on_tokens=coll3)
        assert np.array_equal(f3.result(30), want)
        assert _spin(lambda: len(coll3.chunks) == 1)
        assert coll3.tokens() == [int(t) for t in want[0, 5:]]
        with pytest.raises(ValueError, match="continuous"):
            whole.submit_generate(p, 8, prefix=np.asarray([1, 2]))
    finally:
        whole.shutdown()


def _spin(cond, timeout=10.0):
    import time
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.005)
    return True


# ------------------------------------------------ stats / healthz / schema

def test_stats_and_ready_gate(rng, fresh_registry):
    """stats() exposes the decode-scheduler state and /healthz/ready
    503s until the scheduler is warmed — the models_ready pattern."""
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    from deeplearning4j_tpu.ui.server import UiServer
    net = _tiny_gpt()
    eng = ParallelInference(net, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4, kv_block_size=4)
    eng._warmed = True  # classify plane warmed: isolate the decode gate
    srv = UiServer(InMemoryStatsStorage(), inference_engine=eng,
                   registry=fresh_registry).start()
    try:
        st = eng.stats()["scheduler"]
        assert {"warmed", "active_sequences", "queued_prefills",
                "pool"} <= set(st)

        def ready():
            try:
                with urllib.request.urlopen(srv.url + "/healthz/ready",
                                            timeout=5) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, body = ready()
        assert code == 503 and body["scheduler_ready"] is False
        eng.warmup_generate([4], 8)
        code, body = ready()
        assert code == 200 and body["scheduler_ready"] is True
        sched = body["inference"]["scheduler"]
        assert sched["warmed"] and sched["active_sequences"] == 0
        assert sched["pool"]["blocks_total"] > 0
    finally:
        srv.stop()
        eng.shutdown()


def test_metric_schema_pinned(rng, fresh_registry):
    """The dl4j_kvpool_* / dl4j_sched_* families validate as Prometheus
    exposition and are pinned in KNOWN_DL4J_METRICS."""
    sys.path.insert(0, "scripts")
    try:
        from check_telemetry_schema import (KNOWN_DL4J_METRICS,
                                            validate_known_metrics,
                                            validate_prometheus_text)
    finally:
        sys.path.pop(0)
    for name in ("dl4j_kvpool_blocks_total", "dl4j_kvpool_blocks_free",
                 "dl4j_kvpool_alloc_failures_total",
                 "dl4j_sched_admitted_rows_total",
                 "dl4j_sched_retired_rows_total",
                 "dl4j_sched_preemptions_total", "dl4j_sched_bursts_total",
                 "dl4j_sched_burst_latency_ms",
                 "dl4j_sched_active_sequences",
                 "dl4j_sched_queued_prefills"):
        assert name in KNOWN_DL4J_METRICS, name
    net = _tiny_gpt()
    s = _sched(net, num_blocks=9)
    futs = [s.submit(rng.integers(0, VOCAB, (1, 5)), 10) for _ in range(3)]
    _drive(s, futs)
    text = fresh_registry.prometheus_text()
    assert validate_prometheus_text(text) == []
    assert validate_known_metrics(text) == []
    for family in ("dl4j_kvpool_blocks_total", "dl4j_kvpool_blocks_free",
                   "dl4j_sched_admitted_rows_total",
                   "dl4j_sched_retired_rows_total",
                   "dl4j_sched_bursts_total",
                   "dl4j_sched_burst_latency_ms"):
        assert f"# TYPE {family}" in text, family
    # the tiny pool preempted: the counter and failure metrics moved
    assert "dl4j_sched_preemptions_total" in text
    assert "dl4j_kvpool_alloc_failures_total" in text
