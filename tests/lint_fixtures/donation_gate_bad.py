"""SEEDED VIOLATION: an ungated donation site (the w2v heap-corruption
shape)."""
import jax

f = jax.jit(lambda x: x, donate_argnums=(0,))
