"""SEEDED VIOLATIONS: bare RuntimeError/Exception raises reachable
from a wire frame handler — they cross the wire untyped and degrade
to EndpointError on the caller."""


class Handler:
    def handle_frame(self, payload):  # dl4j-lint: wire-handler
        return self.do_submit(payload)

    def do_submit(self, payload):
        if payload is None:
            raise RuntimeError("engine is shut down")   # bare: violation
        return self.deeper(payload)

    def deeper(self, payload):
        raise Exception("boom")                         # bare: violation
