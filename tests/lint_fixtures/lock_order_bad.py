"""SEEDED VIOLATION: an artificial lock-order INVERSION — A takes its
lock then calls into B (which takes B's lock), while B takes its lock
then calls back into A (which takes A's lock): A->B and B->A, the
classic two-thread deadlock."""
import threading


class PeerA:
    def __init__(self, b: "PeerB"):
        self._lock = threading.Lock()
        self.b = b

    def forward(self, b: "PeerB"):
        with self._lock:
            b.poke()                # holds A, acquires B

    def poke(self):
        with self._lock:
            pass


class PeerB:
    def __init__(self, a: "PeerA"):
        self._lock = threading.Lock()
        self.a = a

    def backward(self, a: "PeerA"):
        with self._lock:
            a.poke()                # holds B, acquires A — inversion

    def poke(self):
        with self._lock:
            pass
