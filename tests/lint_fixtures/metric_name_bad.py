"""SEEDED VIOLATION: a dl4j_ metric family not pinned in
KNOWN_DL4J_METRICS."""
from deeplearning4j_tpu.monitor import get_registry

get_registry().counter("dl4j_totally_unpinned_total", "oops").inc()
