"""CLEAN: a wire frame handler whose error paths all raise TYPED
subclasses (registrable in wire._typed_error_registry)."""


class FixtureShutdown(RuntimeError):
    """Typed: a subclass, never the bare class."""


class Handler:
    def handle_frame(self, payload):  # dl4j-lint: wire-handler
        return self.do_submit(payload)

    def do_submit(self, payload):
        if payload is None:
            raise FixtureShutdown("engine is shut down")
        if not isinstance(payload, bytes):
            raise ValueError("not a frame")  # ValueError is not bare
        return payload
