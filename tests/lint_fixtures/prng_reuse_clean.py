"""CLEAN: every extra draw derives a fresh key first (split/fold_in);
loop draws fold by index."""
import jax


def sample_pair(seed):
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    b = jax.random.gumbel(k2, (4,))
    return a, b


def sample_loop(seed, n):
    key = jax.random.PRNGKey(seed)
    out = []
    for i in range(n):
        k = jax.random.fold_in(key, i)   # fresh key per iteration
        out.append(jax.random.uniform(k, ()))
    return out


def branch_draws(seed, flag):
    key = jax.random.PRNGKey(seed)
    if flag:
        return jax.random.normal(key, ())
    return jax.random.gumbel(key, ())    # exclusive arms: one draw


SPEC_DRAFT_SALT = 101
SPEC_ACCEPT_SALT = 102


def spec_disjoint_lanes(seed, n_gen):
    # speculative decoding's dual clock done RIGHT: draft proposals and
    # accept-test uniforms fold on DISJOINT salted lanes, each draw on
    # a fresh fold of its own lane
    key = jax.random.PRNGKey(seed)
    dkey = jax.random.fold_in(key, SPEC_DRAFT_SALT)
    akey = jax.random.fold_in(key, SPEC_ACCEPT_SALT)
    props = jax.random.gumbel(jax.random.fold_in(dkey, n_gen), (4,))
    u = jax.random.uniform(jax.random.fold_in(akey, n_gen), (4,))
    return props, u
