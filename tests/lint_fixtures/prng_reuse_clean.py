"""CLEAN: every extra draw derives a fresh key first (split/fold_in);
loop draws fold by index."""
import jax


def sample_pair(seed):
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    b = jax.random.gumbel(k2, (4,))
    return a, b


def sample_loop(seed, n):
    key = jax.random.PRNGKey(seed)
    out = []
    for i in range(n):
        k = jax.random.fold_in(key, i)   # fresh key per iteration
        out.append(jax.random.uniform(k, ()))
    return out


def branch_draws(seed, flag):
    key = jax.random.PRNGKey(seed)
    if flag:
        return jax.random.normal(key, ())
    return jax.random.gumbel(key, ())    # exclusive arms: one draw
