"""CLEAN: two locks acquired in ONE consistent order (outer → inner),
including through a call — no cycle."""
import threading


class Inner:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1


class Outer:
    def __init__(self, inner: "Inner"):
        self._lock = threading.Lock()
        self.inner = inner

    def direct(self, inner: "Inner"):
        with self._lock:
            with inner._lock:       # Outer -> Inner, consistently
                pass

    def via_call(self, inner: "Inner"):
        with self._lock:
            inner.bump()            # Outer -> Inner again: same order
