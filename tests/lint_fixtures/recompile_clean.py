"""CLEAN: every data-dependent shape reaches the program getters
through the pinned ladders."""
from deeplearning4j_tpu.datasets.iterators import bucket_for, bucket_sizes


class Sched:
    def __init__(self, gen, pool, block_size):
        self.gen = gen
        self.pool = pool
        self.block_size = block_size

    def admit(self, prompt, entries):
        t_pad = self.gen.prompt_bucket(len(prompt), 1)   # pinned
        rows = bucket_for(len(entries), (1, 2, 4))        # pinned
        need = self.pool.blocks_for(len(prompt))          # pinned
        pre = self.gen.prefill_program(t_pad)
        scat = self.gen.scatter_program(rows, need, self.block_size)
        return pre, scat, bucket_sizes(64)
