"""SEEDED VIOLATIONS: device→host syncs inside a hot-path function —
.item(), np.asarray of a dispatch result, int() of a call-result
local, device_get and block_until_ready."""
import jax
import numpy as np


def hot_burst(program, params, table):  # dl4j-lint: hot-path
    out = program(params, table)
    score = out[0].item()               # sync 1: .item()
    toks = np.asarray(program(params, table))   # sync 2: fetch dispatch
    n = int(out)                        # sync 3: int() of call result
    host = jax.device_get(out)          # sync 4: device_get
    jax.block_until_ready(out)          # sync 5: fence
    return score, toks, n, host
