"""CLEAN: pinned metric names, dash-named topics, allowlisted magic."""
from deeplearning4j_tpu.monitor import get_registry

get_registry().counter("dl4j_router_requests_total", "pinned").inc()
TOPIC = "dl4j-tpu-worker"           # dashes: topic, not a metric
MAGIC = "dl4j_tpu_dataset_export_v1"  # allowlisted file-format magic
