"""CLEAN: gated and non-donating jit sites."""
import jax

# inline gate: donation conditioned on the backend
donate = (0,) if jax.default_backend() != "cpu" else ()
f = jax.jit(lambda x: x, donate_argnums=donate)

# literal empty tuple donates nothing
g = jax.jit(lambda x: x, donate_argnums=())

# no donation at all
h = jax.jit(lambda x: x + 1)
