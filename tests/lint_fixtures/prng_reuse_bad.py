"""SEEDED VIOLATIONS: a key consumed twice without an interleaving
split/fold_in — sequentially, and across loop iterations."""
import jax


def double_draw(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (4,))
    b = jax.random.gumbel(key, (4,))     # REUSE: correlated draws
    return a, b


def loop_reuse(seed, n):
    key = jax.random.PRNGKey(seed)
    out = []
    for _ in range(n):
        out.append(jax.random.uniform(key, ()))   # REUSE each iteration
    return out
