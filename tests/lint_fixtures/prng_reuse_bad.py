"""SEEDED VIOLATIONS: a key consumed twice without an interleaving
split/fold_in — sequentially, and across loop iterations."""
import jax


def double_draw(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (4,))
    b = jax.random.gumbel(key, (4,))     # REUSE: correlated draws
    return a, b


def loop_reuse(seed, n):
    key = jax.random.PRNGKey(seed)
    out = []
    for _ in range(n):
        out.append(jax.random.uniform(key, ()))   # REUSE each iteration
    return out


def spec_shared_lane(seed, n_gen):
    # speculative decoding's dual clock done WRONG: the draft proposal
    # draw and the accept-test uniforms ride the SAME lane key — the
    # verifier's u is correlated with the proposal it judges
    key = jax.random.PRNGKey(seed)
    lane = jax.random.fold_in(key, n_gen)
    props = jax.random.gumbel(lane, (4,))
    u = jax.random.uniform(lane, (4,))       # REUSE: same lane as props
    return props, u
