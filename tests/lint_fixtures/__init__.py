"""Fixture corpora for tests/test_lint.py — one CLEAN and one
SEEDED-VIOLATION file per rule.

These files are parsed by the analysis engine, never imported or
executed. The directory is excluded from every repo-wide walk
(``engine.EXCLUDED_DIRS``) precisely because the ``*_bad.py`` files
carry deliberate violations; tests analyze them via explicit-path
``Project``\\ s.
"""
