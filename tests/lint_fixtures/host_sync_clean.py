"""CLEAN: a hot-path function that only dispatches and does host-list
bookkeeping — no device→host sync."""
import numpy as np


def hot_dispatch(program, params, table, generated):  # dl4j-lint: hot-path
    out = program(params, table)      # dispatch only; no fetch
    host_ids = np.asarray(generated)  # host list → host array: no sync
    return out, host_ids


def cold_fetch(program, params):
    # NOT marked hot: syncing here is legal (e.g. a warmup/test path)
    return np.asarray(program(params))
