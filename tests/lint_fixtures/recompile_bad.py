"""SEEDED VIOLATIONS: raw data-dependent shapes reaching program
getters (fresh XLA compile per distinct request shape), plus an inline
jax.jit invocation (retrace per call)."""
import jax


class Sched:
    def __init__(self, gen):
        self.gen = gen

    def admit(self, prompt, x):
        pre = self.gen.prefill_program(len(prompt))       # raw len()
        scat = self.gen.scatter_program(x.shape[0])       # raw .shape
        t = len(prompt) + 1
        tail = self.gen.tail_prefill_program(t)           # tainted local
        return pre, scat, tail

    def fresh_jit(self, f, x):
        return jax.jit(f)(x)                              # inline jit
