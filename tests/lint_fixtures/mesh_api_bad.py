"""SEEDED VIOLATIONS: the dead jax.shard_map attribute, a rogue
shard_map import, and raw Mesh construction outside parallel/mesh.py."""
import jax
from jax.sharding import Mesh
from jax.experimental.shard_map import shard_map

f = jax.shard_map(lambda x: x, mesh=None, in_specs=None, out_specs=None)
m = Mesh([], ("data",))
