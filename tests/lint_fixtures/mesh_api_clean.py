"""CLEAN: mesh use through the sanctioned plane API."""
from deeplearning4j_tpu.parallel.mesh import MeshPlane, device_collective

plane = MeshPlane.build({"data": 2})
out = device_collective(lambda x: x, plane, None, None)
