"""DropConnect (NeuralNetConfiguration.useDropConnect;
BaseLayer.java:350 + ConvolutionLayer.java:189 -> util/Dropout.java:13)."""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _net(use_dc, dropout=0.5, seed=9):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("sgd").activation("tanh")
            .dropout(dropout).use_drop_connect(use_dc)
            .list()
            .layer(DenseLayer(n_in=5, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(rng, n=32):
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def test_inference_deterministic_and_mask_free(rng):
    """Eval-mode output ignores DropConnect entirely (inverted scaling:
    no inference-time rescale, matching this framework's dropout)."""
    ds = _data(rng)
    a, b = _net(True), _net(False)
    b.set_params_flat(a.params_flat())  # identical weights
    oa = a.output(ds.features)
    ob = b.output(ds.features)
    np.testing.assert_allclose(np.asarray(oa), np.asarray(ob), atol=1e-6)
    np.testing.assert_allclose(np.asarray(a.output(ds.features)),
                               np.asarray(oa), atol=1e-6)  # deterministic


def test_training_is_stochastic_in_weights(rng):
    """Two different-seed fits from identical inits diverge (the weight
    mask is resampled per step), and training still learns."""
    ds = _data(rng, 64)
    a, b = _net(True, seed=1), _net(True, seed=2)
    b.set_params_flat(a.params_flat())
    s0 = float(a.score(ds))
    for _ in range(10):
        a.fit(ds)
        b.fit(ds)
    assert not np.allclose(np.asarray(a.params_flat()),
                           np.asarray(b.params_flat())), \
        "different rng streams produced identical weight-mask training"
    for _ in range(40):
        a.fit(ds)
    assert float(a.score(ds)) < s0


def test_dropconnect_masks_weights_not_inputs(rng):
    """Reference semantics: useDropConnect redirects the dropout prob to
    the WEIGHTS; input activations are NOT also dropped
    (BaseLayer.java:449 has !useDropConnect in the input branch).
    Verified by exact hand-computation of the masked-weight forward."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.layers.base import apply_dropout

    ds = _data(rng)
    net = _net(True, dropout=0.5)
    impl = net.impls[0]
    p = net.params[impl.name]
    x = jnp.asarray(ds.features)
    key = jax.random.PRNGKey(0)
    out, _ = impl.forward(p, x, {}, True, rng=key)
    Wm = apply_dropout(p["W"], 0.5, jax.random.fold_in(key, 0x0D20))
    want = jnp.tanh(x @ Wm + p["b"])  # x UNdropped
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=1e-5)


def test_config_roundtrip(rng):
    net = _net(True)
    js = net.conf.to_json()
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2.conf.use_drop_connect is True


def test_non_dropconnect_layers_keep_input_dropout(rng):
    """Layers without a weight-mask path (e.g. GravesLSTM) must keep
    their input dropout when use_drop_connect is on — the global flag
    may not silently strip a layer's only stochastic regularization."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(4).learning_rate(0.1).updater("sgd").activation("tanh")
            .dropout(0.5).use_drop_connect(True)
            .list()
            .layer(GravesLSTM(n_in=3, n_out=6))
            .layer(RnnOutputLayer(n_in=6, n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    impl = net.impls[0]
    assert impl.applies_drop_connect is False
    x = jnp.asarray(rng.standard_normal((2, 5, 3)), jnp.float32)
    o1, _ = impl.forward(net.params[impl.name], x, {}, True,
                         rng=jax.random.PRNGKey(0))
    o2, _ = impl.forward(net.params[impl.name], x, {}, True,
                         rng=jax.random.PRNGKey(1))
    assert not np.allclose(np.asarray(o1), np.asarray(o2)), \
        "input dropout was suppressed for a non-dropconnect layer"


def test_roc_nan_scores_never_predicted_positive():
    from deeplearning4j_tpu.eval.roc import ROC

    y = np.array([1, 0, 1, 0])
    p = np.array([0.9, np.nan, np.nan, 0.2])
    r = ROC(10)
    r.eval(y, p)
    # old per-threshold `p >= t` semantics: NaN contributes nowhere
    assert r.tp[0] == 1 and r.fp[0] == 1  # only the finite scores
    assert r.tp[-1] == 0 and r.fp[-1] == 0
