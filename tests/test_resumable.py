"""Preemption-safe training: kill mid-run, resume, finish identically.

Beyond-parity doctrine (SURVEY.md §5): a preempted-and-resumed run must
produce EXACTLY the parameters of the uninterrupted run — model,
updater state, and data cursor all round-trip.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.export import (
    ExportedDataSetIterator, export_dataset)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.resumable import ResumableTrainer


def _net():
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.05)
            .updater("adam").activation("tanh").list()
            .layer(DenseLayer(n_in=6, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _spill(rng, tmp_path, n_chunks=5, chunk=24):
    def gen():
        for _ in range(n_chunks):
            x = rng.standard_normal((chunk, 6)).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, chunk)]
            yield DataSet(x, y)
    d = str(tmp_path / "data")
    export_dataset(gen(), d, batch_size=24)
    return d


def test_preempted_run_equals_uninterrupted(rng, tmp_path):
    data_dir = _spill(rng, tmp_path)
    epochs = 3

    # uninterrupted reference run
    ref = ResumableTrainer(_net(), str(tmp_path / "ref"), checkpoint_every=2)
    ref.fit(ExportedDataSetIterator(data_dir, shuffle=True, seed=9),
            epochs=epochs)
    want = np.asarray(ref.model.params_flat())

    # "preempted" run: die after 7 batches, then a FRESH process
    # (fresh trainer + iterator) resumes from disk and finishes
    ck = str(tmp_path / "ck")
    t1 = ResumableTrainer(_net(), ck, checkpoint_every=2)
    it1 = ExportedDataSetIterator(data_dir, shuffle=True, seed=9)
    t1.fit(it1, epochs=epochs, max_steps=7)
    del t1, it1  # the dead incarnation

    t2 = ResumableTrainer(_net(), ck, checkpoint_every=2)
    it2 = ExportedDataSetIterator(data_dir, shuffle=True, seed=9)
    t2.resume_or_start(it2)
    assert t2.steps_done == 7
    t2.fit(it2, epochs=epochs)
    got = np.asarray(t2.model.params_flat())

    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_resume_restores_updater_schedule(rng, tmp_path):
    """Adam moments survive the checkpoint: resuming must NOT restart
    the optimizer cold (bit-equality above implies it, this pins the
    state explicitly)."""
    data_dir = _spill(rng, tmp_path)
    ck = str(tmp_path / "ck")
    t1 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    it1 = ExportedDataSetIterator(data_dir)
    t1.fit(it1, epochs=1, max_steps=3)
    step_before = int(t1.model.opt_state["step"])
    assert step_before == 3

    t2 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    t2.resume_or_start(ExportedDataSetIterator(data_dir))
    assert int(t2.model.opt_state["step"]) == step_before
    m = t2.model.opt_state["updater"]["layer0"]["W"]
    assert any(np.abs(np.asarray(v)).max() > 0 for v in
               (m.values() if isinstance(m, dict) else [m]))


def test_no_checkpoint_starts_fresh(rng, tmp_path):
    t = ResumableTrainer(_net(), str(tmp_path / "empty"))
    assert not t.has_checkpoint()
    model = t.resume_or_start()
    assert model is t.model and t.steps_done == 0


def test_atomic_checkpoint_never_partial(rng, tmp_path, monkeypatch):
    """A crash mid-save must leave the PREVIOUS checkpoint intact."""
    import deeplearning4j_tpu.optimize.resumable as R

    data_dir = _spill(rng, tmp_path)
    ck = str(tmp_path / "ck")
    t1 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    t1.fit(ExportedDataSetIterator(data_dir), epochs=1, max_steps=2)
    unit = f"{ck}/checkpoint"
    good_model = open(f"{unit}/model.zip", "rb").read()
    good_cursor = open(f"{unit}/cursor.json").read()

    def exploding_write(model, path):
        with open(path, "wb") as f:
            f.write(b"partial")
        raise RuntimeError("simulated preemption mid-write")

    monkeypatch.setattr(R, "write_model", exploding_write)
    t1.steps_done += 1
    with pytest.raises(RuntimeError, match="preemption"):
        t1._save(ExportedDataSetIterator(data_dir))
    # the WHOLE unit (model AND cursor, one atomic dir) is untouched
    assert open(f"{unit}/model.zip", "rb").read() == good_model
    assert open(f"{unit}/cursor.json").read() == good_cursor
    assert not [f for f in os.listdir(ck) if f.startswith(".ckpt_tmp_")]



def test_old_unit_is_valid_recovery_point(rng, tmp_path):
    """A crash between the two install renames leaves only
    checkpoint.old — resume must use it, not restart from scratch."""
    data_dir = _spill(rng, tmp_path)
    ck = str(tmp_path / "ck")
    t1 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    t1.fit(ExportedDataSetIterator(data_dir), epochs=1, max_steps=3)
    # simulate the crash window: the new unit vanished mid-install
    os.rename(f"{ck}/checkpoint", f"{ck}/checkpoint.old")

    t2 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    assert t2.has_checkpoint()
    t2.resume_or_start(ExportedDataSetIterator(data_dir))
    assert t2.steps_done == 3
    # and the next save clears the stale .old instead of erroring
    t2.fit(ExportedDataSetIterator(data_dir), epochs=1, max_steps=1)
    assert os.path.isdir(f"{ck}/checkpoint")
    assert not os.path.isdir(f"{ck}/checkpoint.old")


def test_non_resumable_iterator_rejected_on_resume(rng, tmp_path):
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    data_dir = _spill(rng, tmp_path)
    ck = str(tmp_path / "ck")
    t1 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    t1.fit(ExportedDataSetIterator(data_dir), epochs=1, max_steps=2)

    x = np.zeros((8, 6), np.float32)
    y = np.eye(3, dtype=np.float32)[np.zeros(8, np.int64)]
    plain = ListDataSetIterator(DataSet(x, y), 4)
    t2 = ResumableTrainer(_net(), ck)
    with pytest.raises(ValueError, match="restore"):
        t2.resume_or_start(plain)


def test_old_only_save_keeps_a_unit_visible_at_every_instant(
        rng, tmp_path, monkeypatch):
    """ADVICE r3 (medium): starting from a .old-only recovery state,
    _save must never pass through an instant with NO complete unit on
    disk — every rename/rmtree step is checked."""
    import shutil as _shutil

    import deeplearning4j_tpu.optimize.resumable as R

    data_dir = _spill(rng, tmp_path)
    ck = str(tmp_path / "ck")
    t1 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    t1.fit(ExportedDataSetIterator(data_dir), epochs=1, max_steps=2)
    os.rename(f"{ck}/checkpoint", f"{ck}/checkpoint.old")  # crash window

    def a_unit_visible():
        return any(
            os.path.exists(os.path.join(ck, u, "model.zip"))
            and os.path.exists(os.path.join(ck, u, "cursor.json"))
            for u in ("checkpoint", "checkpoint.old"))

    assert a_unit_visible()
    real_rename, real_rmtree = os.rename, _shutil.rmtree

    def checked_rename(src, dst):
        real_rename(src, dst)
        assert a_unit_visible(), f"no unit after rename {src} -> {dst}"

    def checked_rmtree(path, **kw):
        real_rmtree(path, **kw)
        assert a_unit_visible(), f"no unit after rmtree {path}"

    monkeypatch.setattr(R.os, "rename", checked_rename)
    monkeypatch.setattr(R.shutil, "rmtree", checked_rmtree)
    t2 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    t2.resume_or_start(ExportedDataSetIterator(data_dir))
    t2.fit(ExportedDataSetIterator(data_dir), epochs=1, max_steps=1)
    assert os.path.isdir(f"{ck}/checkpoint")
    assert not os.path.isdir(f"{ck}/checkpoint.old")


def test_stale_tmp_dirs_swept_on_init(rng, tmp_path):
    data_dir = _spill(rng, tmp_path)
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / ".ckpt_tmp_dead").mkdir()
    (ck / ".ckpt_tmp_dead" / "model.zip").write_bytes(b"partial")
    ResumableTrainer(_net(), str(ck), checkpoint_every=1)
    assert not (ck / ".ckpt_tmp_dead").exists()
