"""Golden-set segmentation tests for the dictionary-backed lattice
(VERDICT r3 missing #2 / next #6 — the ViterbiBuilder.java +
deeplearning4j-nlp-korean role, demo dictionaries bundled as TSV)."""
import numpy as np
import pytest

from deeplearning4j_tpu.text.lattice import (
    JapaneseTokenizerFactory,
    KoreanTokenizerFactory,
    LatticeDictionary,
    viterbi_segment,
)

JA_GOLDEN = [
    ("私は東京大学の学生です", ["私", "は", "東京大学", "の", "学生", "です"]),
    ("私はキセキです", ["私", "は", "キセキ", "です"]),          # unknown katakana grouped
    ("今日は新しい仕事で勉強します", ["今日", "は", "新しい", "仕事", "で", "勉強", "します"]),
    ("日本語を話す人", ["日本語", "を", "話す", "人"]),
    ("明日学校へ行く", ["明日", "学校", "へ", "行く"]),
]

KO_GOLDEN = [
    ("저는 한국어를 공부합니다", ["저", "는", "한국어", "를", "공부", "합니다"]),
    ("서울에서 학교까지", ["서울", "에서", "학교", "까지"]),
    ("오늘은 사람이 없다", ["오늘", "은", "사람", "이", "없다"]),
    ("선생님과 학생", ["선생님", "과", "학생"]),
]


@pytest.mark.parametrize("text,want", JA_GOLDEN)
def test_japanese_golden(text, want):
    assert JapaneseTokenizerFactory().create(text).get_tokens() == want


@pytest.mark.parametrize("text,want", KO_GOLDEN)
def test_korean_golden(text, want):
    assert KoreanTokenizerFactory().create(text).get_tokens() == want


def test_korean_registered_as_lattice():
    from deeplearning4j_tpu.text.tokenization import tokenizer_factory
    f = tokenizer_factory("korean")
    assert isinstance(f, KoreanTokenizerFactory)


def test_tsv_roundtrip_with_pos(tmp_path):
    p = tmp_path / "user.tsv"
    p.write_text("# user dictionary\nキセキ\t3.0\tN\n",
                 encoding="utf-8")
    d = LatticeDictionary.japanese().load_tsv(str(p))
    seg = viterbi_segment("私はキセキです", d)
    assert ("キセキ", True) in seg  # now a KNOWN word


def test_connection_costs_prefer_particle_after_noun():
    """は after a noun beats the UNK reading when costs tie — the
    ConnectionCosts role is live in the DP, not decorative."""
    d = LatticeDictionary.japanese()
    assert d.connection("N", "PRT") < 0
    seg = viterbi_segment("今日は", d)      # 今日は
    assert seg == [("今日", True), ("は", True)]


def test_unknown_run_lengths_allow_dictionary_interrupt():
    """A dictionary word inside an unknown-class run still wins: the
    unknown edges are offered at EVERY length, not only maximal."""
    d = LatticeDictionary(
        {"キセ": (1.0, "N")})  # "キセ" known, "キ" unknown
    seg = viterbi_segment("キセキ", d)
    assert seg == [("キセ", True), ("キ", False)]


def test_backward_compat_plain_cost_entries():
    d = LatticeDictionary({"ab": 1.0, "c": 2.0})
    assert d.costs == {"ab": 1.0, "c": 2.0}
    seg = viterbi_segment("abc", d)
    assert seg == [("ab", True), ("c", True)]


def test_multiple_readings_per_surface(tmp_path):
    """One surface with several TSV rows = several readings, all in the
    lattice (Kuromoji convention); re-loading does not duplicate."""
    p = tmp_path / "multi.tsv"
    p.write_text("x\t3.6\tV\nx\t2.0\tN\n", encoding="utf-8")
    d = LatticeDictionary().load_tsv(str(p))
    assert sorted(d.entries["x"]) == [(2.0, "N"), (3.6, "V")]
    d.load_tsv(str(p))
    assert len(d.entries["x"]) == 2  # idempotent re-load


def test_halfwidth_katakana_and_iteration_mark():
    from deeplearning4j_tpu.text.lattice import _char_class
    assert _char_class("ｱ") == "KATAKANA"  # halfwidth
    assert _char_class("々") == "KANJI"
    # mixed-width katakana stays one unknown run
    seg = viterbi_segment("アｱ", LatticeDictionary.japanese())
    assert seg == [("アｱ", False)]
    seg = viterbi_segment("人々", LatticeDictionary.japanese())
    # 人 is in the dictionary; 々 may attach as unknown or the pair
    # stays one kanji-class token — either way no OTHER-class split
    assert len(seg) <= 2


def test_lazy_registry_no_side_effect_import():
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        f"import sys; sys.path.insert(0, {repo!r})\n"
        "from deeplearning4j_tpu.text.tokenization import tokenizer_factory\n"
        "f = tokenizer_factory('korean')\n"
        "print(type(f).__name__)\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr
    assert "KoreanTokenizerFactory" in r.stdout


def test_hangul_jamo_blocks_class_as_hangul():
    """ADVICE r4: Compatibility Jamo and extended Jamo blocks must not
    split an otherwise uniform Hangul unknown run."""
    from deeplearning4j_tpu.text.lattice import _char_class
    for ch in ("ㄱ", "ㅏ", "ㆎ",   # compatibility jamo
               "ꥠ", "ힰ",             # extended A / B
               "가", "ᄀ"):            # syllables / classic jamo
        assert _char_class(ch) == "HANGUL", hex(ord(ch))
