"""Golden-set segmentation tests for the dictionary-backed lattice
(VERDICT r3 missing #2 / next #6 — the ViterbiBuilder.java +
deeplearning4j-nlp-korean role, demo dictionaries bundled as TSV)."""
import numpy as np
import pytest

from deeplearning4j_tpu.text.lattice import (
    JapaneseTokenizerFactory,
    KoreanTokenizerFactory,
    LatticeDictionary,
    viterbi_segment,
)

JA_GOLDEN = [
    ("私は東京大学の学生です", ["私", "は", "東京大学", "の", "学生", "です"]),
    ("私はキセキです", ["私", "は", "キセキ", "です"]),          # unknown katakana grouped
    ("今日は新しい仕事で勉強します", ["今日", "は", "新しい", "仕事", "で", "勉強", "します"]),
    ("日本語を話す人", ["日本語", "を", "話す", "人"]),
    ("明日学校へ行く", ["明日", "学校", "へ", "行く"]),
]

KO_GOLDEN = [
    ("저는 한국어를 공부합니다", ["저", "는", "한국어", "를", "공부", "합니다"]),
    ("서울에서 학교까지", ["서울", "에서", "학교", "까지"]),
    ("오늘은 사람이 없다", ["오늘", "은", "사람", "이", "없다"]),
    ("선생님과 학생", ["선생님", "과", "학생"]),
]


@pytest.mark.parametrize("text,want", JA_GOLDEN)
def test_japanese_golden(text, want):
    assert JapaneseTokenizerFactory().create(text).get_tokens() == want


@pytest.mark.parametrize("text,want", KO_GOLDEN)
def test_korean_golden(text, want):
    assert KoreanTokenizerFactory().create(text).get_tokens() == want


def test_korean_registered_as_lattice():
    from deeplearning4j_tpu.text.tokenization import tokenizer_factory
    f = tokenizer_factory("korean")
    assert isinstance(f, KoreanTokenizerFactory)


def test_tsv_roundtrip_with_pos(tmp_path):
    p = tmp_path / "user.tsv"
    p.write_text("# user dictionary\nキセキ\t3.0\tN\n",
                 encoding="utf-8")
    d = LatticeDictionary.japanese().load_tsv(str(p))
    seg = viterbi_segment("私はキセキです", d)
    assert ("キセキ", True) in seg  # now a KNOWN word


def test_connection_costs_prefer_particle_after_noun():
    """は after a noun beats the UNK reading when costs tie — the
    ConnectionCosts role is live in the DP, not decorative."""
    d = LatticeDictionary.japanese()
    assert d.connection("N", "PRT") < 0
    seg = viterbi_segment("今日は", d)      # 今日は
    assert seg == [("今日", True), ("は", True)]


def test_unknown_run_lengths_allow_dictionary_interrupt():
    """A dictionary word inside an unknown-class run still wins: the
    unknown edges are offered at EVERY length, not only maximal."""
    d = LatticeDictionary(
        {"キセ": (1.0, "N")})  # "キセ" known, "キ" unknown
    seg = viterbi_segment("キセキ", d)
    assert seg == [("キセ", True), ("キ", False)]


def test_backward_compat_plain_cost_entries():
    d = LatticeDictionary({"ab": 1.0, "c": 2.0})
    assert d.costs == {"ab": 1.0, "c": 2.0}
    seg = viterbi_segment("abc", d)
    assert seg == [("ab", True), ("c", True)]


def test_multiple_readings_per_surface(tmp_path):
    """One surface with several TSV rows = several readings, all in the
    lattice (Kuromoji convention); re-loading does not duplicate."""
    p = tmp_path / "multi.tsv"
    p.write_text("x\t3.6\tV\nx\t2.0\tN\n", encoding="utf-8")
    d = LatticeDictionary().load_tsv(str(p))
    assert sorted(d.entries["x"]) == [(2.0, "N"), (3.6, "V")]
    d.load_tsv(str(p))
    assert len(d.entries["x"]) == 2  # idempotent re-load


def test_halfwidth_katakana_and_iteration_mark():
    from deeplearning4j_tpu.text.lattice import _char_class
    assert _char_class("ｱ") == "KATAKANA"  # halfwidth
    assert _char_class("々") == "KANJI"
    # mixed-width katakana stays one unknown run
    seg = viterbi_segment("アｱ", LatticeDictionary.japanese())
    assert seg == [("アｱ", False)]
    seg = viterbi_segment("人々", LatticeDictionary.japanese())
    # 人 is in the dictionary; 々 may attach as unknown or the pair
    # stays one kanji-class token — either way no OTHER-class split
    assert len(seg) <= 2


def test_lazy_registry_no_side_effect_import():
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        f"import sys; sys.path.insert(0, {repo!r})\n"
        "from deeplearning4j_tpu.text.tokenization import tokenizer_factory\n"
        "f = tokenizer_factory('korean')\n"
        "print(type(f).__name__)\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr
    assert "KoreanTokenizerFactory" in r.stdout


def test_hangul_jamo_blocks_class_as_hangul():
    """ADVICE r4: Compatibility Jamo and extended Jamo blocks must not
    split an otherwise uniform Hangul unknown run."""
    from deeplearning4j_tpu.text.lattice import _char_class
    for ch in ("ㄱ", "ㅏ", "ㆎ",   # compatibility jamo
               "ꥠ", "ힰ",             # extended A / B
               "가", "ᄀ"):            # syllables / classic jamo
        assert _char_class(ch) == "HANGUL", hex(ord(ch))


# ------------------------------------------- MeCab-IPADIC loader (r5)

def _write_ipadic(dirpath, encoding="utf-8", n_filler=10000):
    """Generate a synthetic dictionary in the stock MeCab-IPADIC layout:
    multiple *.csv entry files, matrix.def, unk.def. Context ids:
    0=BOS/EOS, 1=noun, 2=particle, 5=unknown-katakana."""
    import os
    import random
    os.makedirs(dirpath, exist_ok=True)
    nouns = [("すもも", 4000), ("もも", 4500), ("うち", 4500),
             ("東京", 3000), ("京都", 3000), ("東京都", 2500), ("都", 3500)]
    particles = [("も", 5000), ("の", 4000), ("に", 4000)]

    def row(surface, l, r, cost, pos):
        return f"{surface},{l},{r},{cost},{pos},*,*,*,*,*,{surface},*,*\n"

    with open(os.path.join(dirpath, "Noun.csv"), "w", encoding=encoding) as f:
        for w, c in nouns:
            f.write(row(w, 1, 1, c, "名詞"))
        rng = random.Random(42)
        kanji_pool = [chr(0x4E00 + i) for i in range(500)]
        for _ in range(n_filler):  # ≥10k generated compounds
            w = "".join(rng.choices(kanji_pool, k=rng.randint(2, 3)))
            f.write(row(w, 1, 1, rng.randint(3000, 9000), "名詞"))
    with open(os.path.join(dirpath, "Particle.csv"), "w",
              encoding=encoding) as f:
        for w, c in particles:
            f.write(row(w, 2, 2, c, "助詞"))
    with open(os.path.join(dirpath, "matrix.def"), "w",
              encoding=encoding) as f:
        f.write("6 6\n")
        costs = {(0, 1): -500, (0, 2): 3000, (1, 0): -500, (2, 0): 500,
                 (1, 1): 1000, (1, 2): -3000, (2, 1): -3000, (2, 2): 2000,
                 (5, 0): 0, (0, 5): 0, (5, 1): 0, (1, 5): 0,
                 (5, 2): -1000, (2, 5): 0}
        for (a, b), c in costs.items():
            f.write(f"{a} {b} {c}\n")
    with open(os.path.join(dirpath, "unk.def"), "w", encoding=encoding) as f:
        f.write("DEFAULT,0,0,6000,記号,*,*,*,*,*,*,*,*\n")
        f.write("KATAKANA,5,5,3000,名詞,*,*,*,*,*,*,*,*\n")
        f.write("KATAKANA,5,5,9000,感動詞,*,*,*,*,*,*,*,*\n")  # min wins


def test_ipadic_loader_golden_segmentations(tmp_path):
    from deeplearning4j_tpu.text.lattice import load_ipadic, viterbi_segment
    d = _write_ipadic(tmp_path / "ipadic") or load_ipadic(
        str(tmp_path / "ipadic"))
    assert len(d.entries) >= 5000  # 10k generated rows (some collide)
    assert d.matrix is not None and d.matrix.shape == (6, 6)
    # the classic lattice sentence
    toks = [t for t, _ in viterbi_segment("すもももももももものうち", d)]
    assert toks == ["すもも", "も", "もも", "も", "もも", "の", "うち"], toks
    # longest-match via cost, not greed: 東京都 beats 東京+都
    toks = [t for t, _ in viterbi_segment("東京都に", d)]
    assert toks == ["東京都", "に"], toks


def test_ipadic_unknowns_use_unk_def(tmp_path):
    from deeplearning4j_tpu.text.lattice import load_ipadic, viterbi_segment
    _write_ipadic(tmp_path / "ipadic", n_filler=0)
    d = load_ipadic(str(tmp_path / "ipadic"))
    assert d.unknowns["KATAKANA"][1] == 3000.0  # cheapest row won
    # unknown katakana run stays ONE token and connects like a noun
    seg = viterbi_segment("パソコンのうち", d)
    assert [t for t, _ in seg] == ["パソコン", "の", "うち"], seg
    assert seg[0][1] is False  # marked unknown


def test_ipadic_eucjp_autodetection(tmp_path):
    from deeplearning4j_tpu.text.lattice import load_ipadic, viterbi_segment
    _write_ipadic(tmp_path / "euc", encoding="euc_jp", n_filler=0)
    d = load_ipadic(str(tmp_path / "euc"))  # no encoding= passed
    toks = [t for t, _ in viterbi_segment("すもももももも", d)]
    assert toks == ["すもも", "も", "もも", "も"], toks


def test_ipadic_tokenizer_factory_integration(tmp_path):
    from deeplearning4j_tpu.text.lattice import (
        LatticeTokenizerFactory, load_ipadic)
    _write_ipadic(tmp_path / "ipadic", n_filler=0)
    d = load_ipadic(str(tmp_path / "ipadic"))
    toks = LatticeTokenizerFactory(d).create(
        "東京都の うち").get_tokens()
    assert toks == ["東京都", "の", "うち"], toks


def test_ipadic_missing_unk_def_synthesizes_cost_scale(tmp_path):
    """Code-review r5: without unk.def, unknown costs must live on the
    dictionary's own scale — katakana dictionary words must beat the
    always-invoked unknown path."""
    import os
    from deeplearning4j_tpu.text.lattice import load_ipadic, viterbi_segment
    _write_ipadic(tmp_path / "ipadic", n_filler=0)
    os.remove(tmp_path / "ipadic" / "unk.def")
    with open(tmp_path / "ipadic" / "Noun.csv", "a", encoding="utf-8") as f:
        f.write("コンピュータ,1,1,3000,名詞,*,*,*,*,*,コンピュータ,*,*\n")
    d = load_ipadic(str(tmp_path / "ipadic"))
    assert d.unknowns["OTHER"][1] >= 3000  # synthesized at dict scale
    seg = viterbi_segment("コンピュータのうち", d)
    assert seg[0] == ("コンピュータ", True), seg  # dictionary word WON
