"""KV tiering + session hibernation tests (ISSUE-19 battery).

Exercises the host-RAM tier end to end, deterministic at every level:

- **pool**: swap_out/swap_in round trips are bitwise (quantized values
  and their per-token scales ride as raw stored bytes), budget refusal
  touches nothing, double frees raise the typed
  :class:`KVHostTierError`, the reclaimer CHAIN runs in registration
  order (cache-demote before cache-drop) and stops once covered;
- **wire**: hibernation payloads survive the v4 raw-segment frame
  round trip bit-identically; a truncated frame raises the typed
  :class:`WireFrameError`, never garbage;
- **scheduler**: preemption swaps out instead of freeing and the
  resumed stream is bitwise the uninterrupted run; end-of-turn
  hibernation + resume swaps in instead of re-prefilling;
  hibernate_export/hibernate_import moves a session across schedulers
  bitwise; with the tier off, behavior is identical to pre-tier;
- **engine**: ``kv_host_blocks`` requires continuous batching; local
  resume restores via swap-in; a shipped ``kv_state`` payload restores
  on a DIFFERENT engine bitwise;
- **router**: the full three-rung restore ladder — host (pin alive),
  shipped blocks (pin dead), journaled prefix (no payload) — each
  bitwise vs the uninterrupted oracle with contiguous stream offsets
  across the hibernation boundary, then a zero-leak audit of BOTH
  tiers across every surviving engine.
"""

import time

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import HostTierPressure, kill_endpoint
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.generate import generate_eager
from deeplearning4j_tpu.nn.kvpool import KVHostTierError, PagedKVCachePool
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving import (InferenceRouter, LocalFleet,
                                        ModelRegistry)
from deeplearning4j_tpu.serving.continuous import ContinuousDecodeScheduler
from deeplearning4j_tpu.serving.wire import (WireFrameError,
                                             decode_reply_events,
                                             pack_hibernation_v4,
                                             unpack_frame_v4)

pytestmark = pytest.mark.faultinject

VOCAB = 11


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


@pytest.fixture(scope="module")
def net():
    return gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=0).init()


def _drive(s, futs, max_steps=400):
    for _ in range(max_steps):
        if all(f.done() for f in futs):
            return
        s.step()
    raise AssertionError(f"no convergence; events={list(s.events)}")


# ------------------------------------------------------------ pool tier

def _host_blocks_like(pool, rng, n):
    """Synthetic block contents in the host_export flat layout, dtype-
    exact for the pool (quantized pools get int storage + f32 scales)."""
    out = []
    shape = (pool.block_size, pool.num_heads, pool.head_dim)
    for _ in range(n):
        flat = {}
        for li in range(pool.num_layers):
            for comp in ("k", "v"):
                if pool.quant is not None:
                    flat[f"{comp}{li}"] = rng.integers(
                        -120, 120, shape).astype(np.int8)
                    flat[f"{comp}_scale{li}"] = rng.random(
                        shape[:2]).astype(np.float32)
                else:
                    flat[f"{comp}{li}"] = rng.random(shape).astype(
                        np.float32)
        out.append(flat)
    return out


@pytest.mark.parametrize("quant", [None, "int8"])
def test_host_roundtrip_bitwise(quant, rng):
    """insert -> swap_in (H2D) -> swap_out (D2H) -> export returns the
    exact stored bytes — quantized values AND scales bit-identical."""
    pool = PagedKVCachePool(6, 4, num_layers=2, num_heads=2, head_dim=4,
                            quant=quant, host_blocks=8, name="rt")
    blocks = _host_blocks_like(pool, rng, 3)
    h = pool.host_insert(blocks, owner="lm@v1")
    assert h is not None and pool.host_blocks_used() == 3
    dev = pool.swap_in(h, owner="lm@v1")
    assert dev is not None and pool.host_blocks_used() == 0
    h2 = pool.swap_out(dev, owner="lm@v1")
    assert h2 is not None
    assert pool.free_count == pool.total_blocks  # device refs released
    out = pool.host_export(h2)
    for got, want in zip(out, blocks):
        assert sorted(got) == sorted(want)
        for key in want:
            assert got[key].dtype == want[key].dtype, key
            np.testing.assert_array_equal(got[key], want[key], err_msg=key)
    assert pool.swap_in_cost_ms() is not None  # EWMA primed
    pool.free_host(h2, owner="lm@v1")
    assert pool.host_blocks_used() == 0


def test_host_budget_refusal_touches_nothing(rng):
    pool = PagedKVCachePool(8, 4, num_layers=1, num_heads=1, head_dim=4,
                            host_blocks=2, name="budget")
    dev = pool.alloc(3, "lm@v1")
    # batch over budget: refused atomically, device refs stay ours
    assert pool.swap_out(dev, owner="lm@v1") is None
    assert pool.free_count == pool.total_blocks - 3
    assert pool.host_blocks_used() == 0
    h = pool.swap_out(dev[:2], owner="lm@v1")
    assert h is not None and pool.host_blocks_used() == 2
    assert pool.swap_out(dev[2:], owner="lm@v1") is None  # tier full
    # pressure squeeze: existing entries survive, NEW demotions refuse
    with HostTierPressure(pool, budget=0):
        assert pool.host_blocks_used() == 2
        assert pool.swap_out(dev[2:], owner="lm@v1") is None
        assert pool.host_insert(_host_blocks_like(pool, rng, 1)) is None
    assert pool.host_budget() == 2  # healed
    pool.free_host(h, owner="lm@v1")
    pool.free_blocks(dev[2:], "lm@v1")
    assert pool.free_count == pool.total_blocks


def test_host_double_free_raises_typed():
    pool = PagedKVCachePool(4, 2, num_layers=1, num_heads=1, head_dim=2,
                            host_blocks=4, name="df")
    dev = pool.alloc(1, "a")
    (h,) = pool.swap_out(dev, owner="a")
    pool.free_host([h], owner="a")
    with pytest.raises(KVHostTierError):
        pool.free_host([h], owner="a")
    with pytest.raises(KVHostTierError):
        pool.swap_in([h], owner="a")
    # typed but still a RuntimeError: pre-tier catch sites keep working
    assert issubclass(KVHostTierError, RuntimeError)


def test_disabled_tier_refuses_until_budget_set(rng):
    pool = PagedKVCachePool(4, 2, num_layers=1, num_heads=1, head_dim=2,
                            name="off")
    assert not pool.host_enabled
    dev = pool.alloc(1, "a")
    assert pool.swap_out(dev, owner="a") is None
    assert pool.host_insert(_host_blocks_like(pool, rng, 1)) is None
    pool.set_host_budget(4)
    assert pool.host_enabled
    h = pool.swap_out(dev, owner="a")
    assert h is not None
    pool.free_host(h, owner="a")


def test_reclaimer_chain_registration_order_and_early_stop():
    """The chain is consulted in registration order (cache-demote
    before cache-drop) and stops as soon as the free list covers the
    request — demotion satisfies small shortfalls without drops."""
    pool = PagedKVCachePool(7, 2, num_layers=1, num_heads=1, head_dim=2,
                            name="chain")
    held = pool.alloc(pool.free_count, "cache")
    calls = []

    def demote(n_short):
        calls.append(("demote", n_short))
        pool.free_blocks(held[:1], "cache")
        del held[:1]
        return 1

    def drop(n_short):
        calls.append(("drop", n_short))
        k = min(n_short, len(held))
        pool.free_blocks(held[:k], "cache")
        del held[:k]
        return k

    pool.register_reclaimer(demote)
    pool.register_reclaimer(drop)
    got1 = pool.alloc(1, "live")
    assert got1 is not None
    assert calls == [("demote", 1)]  # covered: drop never consulted
    got3 = pool.alloc(3, "live")
    assert got3 is not None
    assert calls[1][0] == "demote" and calls[2][0] == "drop"
    pool.free_blocks(got1 + got3, "live")
    pool.free_blocks(held, "cache")
    assert pool.free_count == pool.total_blocks


# ------------------------------------------------------------ wire v4

def test_hibernation_frame_roundtrip_bitwise(rng):
    pool = PagedKVCachePool(4, 4, num_layers=2, num_heads=2, head_dim=4,
                            quant="int8", host_blocks=4, name="wire")
    payload = {
        "blocks": _host_blocks_like(pool, rng, 2),
        "covered": 7,
        "tokens": np.arange(8, dtype=np.int64),
        "model": "lm", "version": 3,
        "prompt": rng.integers(1, VOCAB, (1, 4)),
        "generated": np.arange(4, dtype=np.int64),
    }
    frame = pack_hibernation_v4("corr-9", payload)
    events = decode_reply_events(frame)
    hib = [e for e in events if e["type"] == "hibernation"]
    assert len(hib) == 1 and hib[0]["id"] == "corr-9"
    got = hib[0]["payload"]
    assert got["covered"] == 7 and got["model"] == "lm"
    assert got["version"] == 3
    np.testing.assert_array_equal(got["tokens"], payload["tokens"])
    np.testing.assert_array_equal(got["prompt"], payload["prompt"])
    np.testing.assert_array_equal(got["generated"], payload["generated"])
    assert len(got["blocks"]) == 2
    for gb, wb in zip(got["blocks"], payload["blocks"]):
        assert sorted(gb) == sorted(wb)
        for key in wb:
            assert gb[key].dtype == wb[key].dtype, key
            np.testing.assert_array_equal(gb[key], wb[key], err_msg=key)
    # payload outlives the frame buffer (copied out of the views)
    assert got["blocks"][0]["k0"].flags.owndata or \
        got["blocks"][0]["k0"].base is not frame
    with pytest.raises(WireFrameError):
        unpack_frame_v4(frame[4:-3])


# ------------------------------------------------------- scheduler tier

def test_scheduler_preempt_swaps_out_and_resumes_bitwise(net, rng):
    """Tiny pool forces preemption; victims demote to host and resume
    via swap-in — outputs bitwise the uninterrupted oracle, both tiers
    drain to zero."""
    s = ContinuousDecodeScheduler(net=net, slots=4, burst_tokens=4,
                                  block_size=4, start=False, num_blocks=9,
                                  host_kv_blocks=16)
    prompts = [rng.integers(1, VOCAB, (1, 5)) for _ in range(3)]
    oracle = [np.asarray(generate_eager(net, p, 12, seed=7,
                                        temperature=0.8, top_k=5))
              for p in prompts]
    futs = [s.submit(p, 12, seed=7, temperature=0.8, top_k=5)
            for p in prompts]
    _drive(s, futs)
    for f, o in zip(futs, oracle):
        np.testing.assert_array_equal(np.asarray(f.result()), o)
    st = s.stats()
    assert st["preemptions"] > 0, "pool was supposed to be tight"
    assert st["kvtier"]["preempt_swapouts"] > 0
    assert st["kvtier"]["swap_restores"] > 0
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
    assert st["kvtier"]["host_blocks_used"] == 0


def test_scheduler_hibernate_resume_bitwise(net, rng):
    s = ContinuousDecodeScheduler(net=net, slots=4, burst_tokens=4,
                                  block_size=4, start=False,
                                  host_kv_blocks=16)
    p = rng.integers(1, VOCAB, (1, 4))
    full = np.asarray(generate_eager(net, p, 14, seed=3, temperature=0.9,
                                     top_k=4))
    f1 = s.submit(p, 6, seed=3, temperature=0.9, top_k=4,
                  session="sess-a", hibernate=True)
    _drive(s, [f1])
    turn1 = np.asarray(f1.result())
    np.testing.assert_array_equal(turn1, full[:, :p.shape[1] + 6])
    assert s.hibernated_count() == 1
    assert s.stats()["kvtier"]["host_blocks_used"] > 0
    # resume: same session, prefix = turn-1 generated tokens
    pre = turn1[0, p.shape[1]:]
    f2 = s.submit(p, 14, seed=3, temperature=0.9, top_k=4,
                  session="sess-a", prefix=pre, hibernate=True)
    _drive(s, [f2])
    np.testing.assert_array_equal(np.asarray(f2.result()), full)
    assert s.hibernated_count() == 1  # turn 2 re-hibernated
    assert any(e.startswith("swap_in") for e in s.events), \
        "resume must swap in, not re-prefill"
    assert s.stats()["kvtier"]["swap_restores"] >= 1
    assert s.hibernate_release("sess-a")
    st = s.stats()
    assert st["kvtier"]["host_blocks_used"] == 0
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]


def test_scheduler_export_import_cross_scheduler_bitwise(net, rng):
    p = rng.integers(1, VOCAB, (1, 4))
    full = np.asarray(generate_eager(net, p, 14, seed=3, temperature=0.9,
                                     top_k=4))
    s1 = ContinuousDecodeScheduler(net=net, slots=4, burst_tokens=4,
                                   block_size=4, start=False,
                                   host_kv_blocks=16)
    f1 = s1.submit(p, 6, seed=3, temperature=0.9, top_k=4,
                   session="sess-b", hibernate=True)
    _drive(s1, [f1])
    pre = np.asarray(f1.result())[0, p.shape[1]:]
    payload = s1.hibernate_export("sess-b")
    assert payload is not None and payload["covered"] == p.shape[1] + 6 - 1
    s2 = ContinuousDecodeScheduler(net=net, slots=4, burst_tokens=4,
                                   block_size=4, start=False,
                                   host_kv_blocks=16)
    assert s2.hibernate_import("sess-b", payload["blocks"],
                               payload["covered"], payload["tokens"],
                               model=payload["model"],
                               version=payload["version"],
                               prompt=payload["prompt"],
                               generated=payload["generated"])
    f2 = s2.submit(p, 14, seed=3, temperature=0.9, top_k=4,
                   session="sess-b", prefix=pre)
    _drive(s2, [f2])
    np.testing.assert_array_equal(np.asarray(f2.result()), full)
    assert any(e.startswith("swap_in") for e in s2.events)
    assert s1.hibernate_release("sess-b")
    st = s2.stats()
    assert st["kvtier"]["host_blocks_used"] == 0
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
    assert s1.stats()["kvtier"]["host_blocks_used"] == 0


def test_scheduler_tier_off_is_pre_tier_behavior(net, rng):
    s = ContinuousDecodeScheduler(net=net, slots=4, burst_tokens=4,
                                  block_size=4, start=False, num_blocks=9)
    prompts = [rng.integers(1, VOCAB, (1, 5)) for _ in range(3)]
    oracle = [np.asarray(generate_eager(net, p, 12, seed=7,
                                        temperature=0.8, top_k=5))
              for p in prompts]
    futs = [s.submit(p, 12, seed=7, temperature=0.8, top_k=5)
            for p in prompts]
    _drive(s, futs)
    for f, o in zip(futs, oracle):
        np.testing.assert_array_equal(np.asarray(f.result()), o)
    assert s.stats()["kvtier"]["enabled"] is False


# ---------------------------------------------------------- engine tier

def test_engine_host_tier_requires_continuous(net):
    with pytest.raises(ValueError, match="continuous"):
        ParallelInference(net=net, kv_host_blocks=8)


def test_engine_local_resume_and_cross_engine_ship(net, rng):
    p = rng.integers(1, VOCAB, (1, 4))
    full = np.asarray(generate_eager(net, p, 14, seed=3, temperature=0.9,
                                     top_k=4))
    eng = ParallelInference(net=net, continuous=True, decode_slots=4,
                            decode_burst=4, kv_block_size=4,
                            kv_host_blocks=16)
    try:
        f1 = eng.submit_generate(p, 6, seed=3, temperature=0.9, top_k=4,
                                 session="s", hibernate=True)
        turn1 = np.asarray(f1.result(timeout=120))
        np.testing.assert_array_equal(turn1, full[:, :p.shape[1] + 6])
        assert eng.hibernated_count() == 1
        payload = eng.hibernate_export("s")
        assert payload is not None
        pre = turn1[0, p.shape[1]:]
        f2 = eng.submit_generate(p, 14, seed=3, temperature=0.9, top_k=4,
                                 session="s", prefix=pre)
        np.testing.assert_array_equal(np.asarray(f2.result(timeout=120)),
                                      full)
        assert eng.hibernated_count() == 0
        st = eng.stats()["scheduler"]["kvtier"]
        assert st["swap_restores"] >= 1 and st["host_blocks_used"] == 0
    finally:
        eng.shutdown()
    # the exported payload lands on a DIFFERENT engine via kv_state
    eng2 = ParallelInference(net=net, continuous=True, decode_slots=4,
                             decode_burst=4, kv_block_size=4,
                             kv_host_blocks=16)
    try:
        f3 = eng2.submit_generate(p, 14, seed=3, temperature=0.9, top_k=4,
                                  session="s", prefix=pre,
                                  kv_state=payload)
        np.testing.assert_array_equal(np.asarray(f3.result(timeout=120)),
                                      full)
        st = eng2.stats()["scheduler"]["kvtier"]
        assert st["swap_restores"] >= 1 and st["host_blocks_used"] == 0
    finally:
        eng2.shutdown()


# ---------------------------------------------------------- router tier

class _Coll:
    """Session-long stream collector: resume offsets CONTINUE from the
    hibernated turn, so one collector spanning both turns must see
    zero dups and zero gaps."""

    def __init__(self):
        self.tokens, self.dups, self.gaps = [], 0, 0

    def __call__(self, off, toks):
        for i, t in enumerate(np.asarray(toks).reshape(-1).tolist()):
            idx = int(off) + i
            if idx < len(self.tokens):
                self.dups += 1
            elif idx == len(self.tokens):
                self.tokens.append(int(t))
            else:
                self.gaps += 1


def test_router_restore_ladder_and_leak_audit(net, rng, fresh_registry):
    """The acceptance scenario over a real broker fleet: hibernated
    sessions resume bitwise through all three rungs — local swap-in,
    shipped blocks after endpoint death, journaled prefix when no
    payload exists — and every surviving engine drains BOTH tiers to
    zero."""
    engines = []

    def factory():
        mreg = ModelRegistry()
        mreg.register("lm", net=net)
        eng = ParallelInference(registry=mreg, replicas=1,
                                max_batch_size=8, max_latency_ms=1.0,
                                queue_capacity=512, continuous=True,
                                decode_slots=4, decode_burst=4,
                                kv_block_size=4, kv_host_blocks=32)
        engines.append(eng)
        return eng

    router = InferenceRouter(per_try_timeout_s=15.0, eject_backoff_s=0.1,
                             max_attempts=6)
    fleet = LocalFleet(factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=15.0, heartbeat_timeout_s=0.5)
    for _ in range(3):
        fleet.add_endpoint()
    assert fleet.wait_ready(30)

    def oracle(p, n, seed):
        return np.asarray(generate_eager(net, p, n, temperature=0.9,
                                         seed=seed, top_k=4))

    try:
        # rung 1: host — pin alive, local swap-in restores
        p = rng.integers(1, VOCAB, (1, 4))
        full = oracle(p, 14, seed=21)
        coll = _Coll()
        t1 = np.asarray(router.generate(p, 6, temperature=0.9, seed=21,
                                        top_k=4, model="lm", session="h",
                                        hibernate=True, on_tokens=coll,
                                        timeout=120))
        np.testing.assert_array_equal(t1, full[:, :4 + 6])
        handle = router.hibernation_handle("h")
        assert handle is not None and "payload" in handle
        assert router.hibernated_sessions() == ["h"]
        assert router.fleet_snapshot()["hibernated_sessions"] == 1
        got = np.asarray(router.resume_generate(
            "h", 14, model="lm", temperature=0.9, seed=21, top_k=4,
            on_tokens=coll).result(timeout=120))
        np.testing.assert_array_equal(got, full)
        assert coll.dups == 0 and coll.gaps == 0
        assert coll.tokens == [int(t) for t in full[0, 4:]]
        assert router.hibernation_handle("h") is None  # consumed
        restores = sum(e._scheduler.stats()["kvtier"]["swap_restores"]
                       for e in engines if e._scheduler is not None)
        assert restores >= 1, "must restore via swap-in, not re-prefill"

        # rung 2: ship — pin dead, payload rides to a survivor
        p2 = rng.integers(1, VOCAB, (1, 5))
        full2 = oracle(p2, 13, seed=22)
        coll = _Coll()
        t1 = np.asarray(router.generate(p2, 5, temperature=0.9, seed=22,
                                        top_k=4, model="lm", session="s",
                                        hibernate=True, on_tokens=coll,
                                        timeout=120))
        np.testing.assert_array_equal(t1, full2[:, :5 + 5])
        assert "payload" in router.hibernation_handle("s")
        pin_s = router._affinity.get("s")[0]
        kill_endpoint(fleet, pin_s)
        got = np.asarray(router.resume_generate(
            "s", 13, model="lm", temperature=0.9, seed=22, top_k=4,
            on_tokens=coll).result(timeout=120))
        np.testing.assert_array_equal(got, full2)
        assert coll.dups == 0 and coll.gaps == 0
        assert router._affinity.get("s")[0] != pin_s  # re-pinned

        # rung 3: journal — pin dead AND no payload (v3 peer) -> the
        # journaled prefix re-prefills, still bitwise
        p3 = rng.integers(1, VOCAB, (1, 4))
        full3 = oracle(p3, 12, seed=23)
        coll = _Coll()
        t1 = np.asarray(router.generate(p3, 4, temperature=0.9, seed=23,
                                        top_k=4, model="lm", session="j",
                                        hibernate=True, on_tokens=coll,
                                        timeout=120))
        np.testing.assert_array_equal(t1, full3[:, :4 + 4])
        with router._lock:
            router._hibernated["j"].pop("payload", None)
        pin_j = router._affinity.get("j")[0]
        if pin_j != pin_s:  # may already be dead from rung 2
            kill_endpoint(fleet, pin_j)
        got = np.asarray(router.resume_generate(
            "j", 12, model="lm", temperature=0.9, seed=23, top_k=4,
            on_tokens=coll).result(timeout=120))
        np.testing.assert_array_equal(got, full3)
        assert coll.dups == 0 and coll.gaps == 0

        # zero leaked blocks, both tiers, every engine still alive
        for eng in engines:
            if eng._closed:
                continue
            eng.drain(timeout=30)
            sched = eng._scheduler
            if sched is None:
                continue
            for c in sched.prefix_caches():
                c.clear()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                st = sched.stats()
                if (st["pool"]["blocks_free"] >= st["pool"]["blocks_total"]
                        and st["kvtier"]["host_blocks_used"] == 0):
                    break
                time.sleep(0.02)
            st = sched.stats()
            assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]
            assert st["kvtier"]["host_blocks_used"] == 0
    finally:
        try:
            fleet.shutdown(drain=False)
        except BaseException:
            pass
        router.close()
