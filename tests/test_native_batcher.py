"""Native batch-assembly tests: C++ path vs NumPy semantics.

Parity: the host data plane's native half (SURVEY.md §1 layer 1/4 —
libnd4j row ops + DataVec feed threads); doctrine as in
tests/test_native_io.py — identical results whichever path runs.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.native_batcher import (
    NativeBatchIterator, gather_rows, one_hot)
from deeplearning4j_tpu.native import get_lib


def test_gather_matches_numpy(rng):
    src = rng.standard_normal((100, 7)).astype(np.float32)
    idx = rng.integers(0, 100, 33)
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_nd_features(rng):
    src = rng.standard_normal((40, 4, 5, 2)).astype(np.float32)
    idx = rng.integers(0, 40, 16)
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_normalize_fused(rng):
    src = rng.standard_normal((60, 9)).astype(np.float32) * 3 + 1
    idx = rng.integers(0, 60, 25)
    mean, std = src.mean(0), src.std(0)
    got = gather_rows(src, idx, mean, std)
    want = (src[idx] - mean) / std
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_gather_zero_std_guard(rng):
    src = np.ones((10, 3), np.float32)
    got = gather_rows(src, np.arange(10), src.mean(0), src.std(0))
    assert np.isfinite(got).all()


def test_gather_oob_raises(rng):
    src = rng.standard_normal((10, 3)).astype(np.float32)
    with pytest.raises(IndexError):
        gather_rows(src, np.array([0, 10]))
    with pytest.raises(IndexError):
        gather_rows(src, np.array([-1]))


def test_one_hot_matches_numpy(rng):
    ids = rng.integers(0, 7, 50)
    np.testing.assert_array_equal(one_hot(ids, 7),
                                  np.eye(7, dtype=np.float32)[ids])
    with pytest.raises(IndexError):
        one_hot(np.array([7]), 7)


def test_native_lib_has_batch_kernels():
    lib = get_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    assert hasattr(lib, "dl4j_gather_rows")


class TestNativeBatchIterator:
    def test_covers_all_examples_shuffled(self, rng):
        x = rng.standard_normal((83, 5)).astype(np.float32)
        y = rng.integers(0, 4, 83)
        it = NativeBatchIterator(x, y, batch_size=16, num_classes=4, seed=3)
        seen, n = [], 0
        while it.has_next():
            b = it.next()
            n += b.num_examples()
            seen.append(b)
        assert n == 83
        assert seen[-1].num_examples() == 83 % 16
        # one-hot labels round-trip to the original ids
        ids = np.concatenate([np.argmax(np.asarray(b.labels), -1)
                              for b in seen])
        assert sorted(ids.tolist()) == sorted(y.tolist())
        order0 = it._order.copy()
        it.reset()
        assert not np.array_equal(it._order, order0)  # reshuffled

    def test_normalized_training(self, rng):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        centers = rng.standard_normal((3, 6)) * 5 + 10  # needs normalization
        ids = rng.integers(0, 3, 200)
        x = centers[ids] + 0.3 * rng.standard_normal((200, 6))
        it = NativeBatchIterator(x.astype(np.float32), ids, batch_size=32,
                                 normalize=True, num_classes=3, seed=1)
        b = it.next()
        assert abs(float(np.asarray(b.features).mean())) < 1.0  # standardized
        it.reset()

        conf = (NeuralNetConfiguration.builder().seed(2).learning_rate(0.1)
                .updater("adam").activation("tanh").list()
                .layer(DenseLayer(n_in=6, n_out=16))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(8):
            net.fit(it)
            it.reset()
        acc = float(np.mean(net.predict(
            gather_rows(x.astype(np.float32), np.arange(200),
                        it.mean, it.std)) == ids))
        assert acc > 0.9, acc

    def test_sparse_int_labels_pass_through(self, rng):
        x = rng.standard_normal((20, 4)).astype(np.float32)
        y = rng.integers(0, 5, 20)
        it = NativeBatchIterator(x, y, batch_size=8, num_classes=None)
        b = it.next()
        assert b.labels.shape == (8,)  # sparse ids, ops/losses convention

    def test_length_mismatch_raises(self, rng):
        with pytest.raises(ValueError, match="mismatch"):
            NativeBatchIterator(np.zeros((4, 2), np.float32),
                                np.zeros(5, np.int64), 2)
