"""Composed chaos drill tests (faultinject/chaos.py).

The ISSUE-10 battery: the seeded event schedule replays bit-identically
(the clock the whole drill hangs off), and one live composed drill —
several injectors firing against a 3-endpoint fleet under mixed
decode-stream + classify load — ends with every global invariant
intact: zero lost/duplicated tokens, zero stranded futures, zero
leaked KV blocks, ``/healthz`` converged healthy. The cross-process
outcome-drift contract (same seed ⇒ same final counters in fresh
interpreters) runs via ``scripts/stress_faultinject.py --chaos``; in
tier-1 the schedule half of that contract is carried by
``quick_check`` section 7.
"""

import jax
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import ChaosSchedule
from deeplearning4j_tpu.faultinject.chaos import (ACTIONS, SLICE_ACTIONS,
                                                  run_chaos_drill,
                                                  run_slice_drill)

pytestmark = pytest.mark.faultinject


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


def test_chaos_schedule_is_seed_deterministic():
    """Same seed ⇒ identical ticks, actions, targets and heal ticks;
    different seeds diverge; every action drawn is a known injector."""
    a = ChaosSchedule(5, n_events=8, n_endpoints=3)
    b = ChaosSchedule(5, n_events=8, n_endpoints=3)
    assert a.signature() == b.signature()
    assert len(a.events) == 8
    for ev in a.events:
        assert ev.action in ACTIONS
        assert 0 <= ev.target < 3
        assert ev.heal_tick > ev.tick
    ticks = [ev.tick for ev in a.events]
    assert ticks == sorted(ticks) and len(set(ticks)) == len(ticks)
    assert ChaosSchedule(6, n_events=8).signature() != a.signature()


def test_composed_chaos_drill_invariants(fresh_registry):
    """One live composed drill: every submitted request resolves with
    the exact uninterrupted output, streams deliver append-only, no
    KV block leaks, and the fleet converges healthy after the storm."""
    out = run_chaos_drill(seed=0, n_requests=10, n_events=3)
    assert out["submitted"] == 10
    assert out["completed"] == out["submitted"], out
    assert out["failed"] == 0, out
    assert out["stranded_futures"] == 0, out
    assert out["token_mismatches"] == 0, out
    assert out["dup_offsets"] == 0 and out["gap_events"] == 0, out
    assert out["leaked_blocks"] == 0, out
    assert out["healthy_endpoints"] == 3, out
    # request-trace invariants (ISSUE 13): every delivered stream's
    # merged trace is parent-complete, and a resumed migration's gap
    # is fully attributed (silence_wait / repin / resume prefill /
    # first resumed burst) — violations counted by the extended
    # schema checker inside the drill
    assert out["trace_violations"] == 0, out
    # the schedule recorded in the summary is the seeded one
    assert out["schedule"] == ChaosSchedule(0, n_events=3,
                                            n_endpoints=3).signature()


def test_composed_slice_drill_invariants(fresh_registry):
    """The MESH-SLICE composed drill (ISSUE 12): chip death inside a
    live 2-chip slice composes with heartbeat partitions and wedges —
    every request resolves with the exact single-device output
    (bitwise classify, token-for-token streams THROUGH the chip
    death), append-only delivery, zero leaked KV blocks across every
    engine ever alive (dead slices included), elastic rebuilds land at
    the narrower width, and the fleet converges."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    out = run_slice_drill(seed=0, n_requests=10, n_events=2)
    assert out["submitted"] == 10
    assert out["completed"] == out["submitted"], out
    assert out["failed"] == 0 and out["stranded_futures"] == 0, out
    assert out["token_mismatches"] == 0, out
    assert out["dup_offsets"] == 0 and out["gap_events"] == 0, out
    assert out["leaked_blocks"] == 0, out
    assert out["healthy_endpoints"] == 2, out
    assert out["schedule"] == ChaosSchedule(
        0, n_events=2, n_endpoints=2, actions=SLICE_ACTIONS).signature()
    # every rebuild narrowed the slice (2 → 1 on this drill's width)
    assert all(w == 1 for w in out["rebuilt_widths"]), out
