"""End-to-end slice tests: config → MLN → train.

Ports of the reference test doctrine (SURVEY.md §4):
- ``BackPropMLPTest.java``: one SGD step vs hand-rolled numpy math
- ``GradientCheckTests.java``: finite differences vs analytic
- ``MultiLayerTest.java``: small net learns Iris
- ``NeuralNetConfigurationTest.java``: JSON round-trip equality
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator, load_iris_dataset
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _mlp_conf(n_in=4, n_hidden=5, n_out=3, activation="sigmoid", lr=0.1, updater="sgd",
              l1=0.0, l2=0.0, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(updater)
            .activation(activation).weight_init("xavier").l1(l1).l2(l2)
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=n_hidden))
            .layer(OutputLayer(n_in=n_hidden, n_out=n_out, activation="softmax",
                               loss_function="mcxent"))
            .build())


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


class TestBackPropMLPHandMath:
    """One full SGD iteration vs hand-computed numpy (BackPropMLPTest)."""

    def test_single_step_matches_hand_math(self):
        conf = _mlp_conf(activation="sigmoid", lr=0.1)
        net = MultiLayerNetwork(conf).init(dtype=jnp.float64)
        rng = np.random.default_rng(0)
        x = rng.random((10, 4))
        y = np.eye(3)[rng.integers(0, 3, 10)]

        W0 = np.asarray(net.params["layer0"]["W"]).copy()
        b0 = np.asarray(net.params["layer0"]["b"]).copy()
        W1 = np.asarray(net.params["layer1"]["W"]).copy()
        b1 = np.asarray(net.params["layer1"]["b"]).copy()

        net.fit(DataSet(x, y))

        # hand math (f64)
        z1 = x @ W0 + b0
        a1 = _sigmoid(z1)
        z2 = a1 @ W1 + b1
        e = np.exp(z2 - z2.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        n = x.shape[0]
        score = -np.mean(np.sum(y * np.log(p), axis=1))
        dz2 = (p - y) / n
        gW1 = a1.T @ dz2
        gb1 = dz2.sum(axis=0)
        da1 = dz2 @ W1.T
        dz1 = da1 * a1 * (1 - a1)
        gW0 = x.T @ dz1
        gb0 = dz1.sum(axis=0)

        np.testing.assert_allclose(net.score(), score, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(net.params["layer1"]["W"]), W1 - 0.1 * gW1, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(net.params["layer1"]["b"]), b1 - 0.1 * gb1, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(net.params["layer0"]["W"]), W0 - 0.1 * gW0, rtol=1e-4, atol=1e-7)
        np.testing.assert_allclose(np.asarray(net.params["layer0"]["b"]), b0 - 0.1 * gb0, rtol=1e-4, atol=1e-7)


class TestGradientChecks:
    """GradientCheckTests.java analog — the correctness oracle."""

    def _run(self, activation, updater="sgd", l1=0.0, l2=0.0):
        conf = _mlp_conf(activation=activation, l1=l1, l2=l2)
        net = MultiLayerNetwork(conf).init(dtype=jnp.float64)
        rng = np.random.default_rng(3)
        x = rng.standard_normal((8, 4))
        y = np.eye(3)[rng.integers(0, 3, 8)]
        res = check_gradients(net, DataSet(x, y))
        assert res.ok, f"act={activation} l1={l1} l2={l2}: {res.n_failed}/{res.n_checked} failed; " + \
            "; ".join(res.failures[:3])

    def test_mlp_tanh(self):
        self._run("tanh")

    def test_mlp_relu(self):
        self._run("relu")

    def test_mlp_sigmoid_l2(self):
        self._run("sigmoid", l2=0.01)

    def test_mlp_l1(self):
        self._run("tanh", l1=0.01)


class TestIrisTraining:
    """MultiLayerTest-style integration: Iris MLP reaches high accuracy."""

    def test_iris_mlp_learns(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(12345).learning_rate(0.5).updater("nesterovs").momentum(0.9)
                .activation("relu").weight_init("relu")
                .list()
                .layer(DenseLayer(n_out=16))
                .layer(OutputLayer(n_out=3, activation="softmax", loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        assert conf.layers[0].n_in == 4  # auto-wired
        net = MultiLayerNetwork(conf).init()
        ds = load_iris_dataset(shuffle_seed=6)
        first_score = None
        for _ in range(150):
            net.fit(ds)
            if first_score is None:
                first_score = net.score()
        preds = net.predict(ds.features)
        acc = float(np.mean(preds == np.argmax(ds.labels, axis=1)))
        assert acc >= 0.95, f"accuracy {acc}"
        assert net.score() < first_score / 3

    def test_iris_via_iterator_and_adam(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(1).learning_rate(0.02).updater("adam")
                .activation("tanh").list()
                .layer(DenseLayer(n_in=4, n_out=10))
                .layer(OutputLayer(n_in=10, n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        it = IrisDataSetIterator(batch=50)
        for _ in range(60):
            net.fit(it)
        ds = load_iris_dataset(shuffle_seed=6)
        acc = float(np.mean(net.predict(ds.features) == np.argmax(ds.labels, axis=1)))
        assert acc >= 0.9, f"accuracy {acc}"


class TestFlatParamViews:
    def test_round_trip(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        flat = net.params_flat()
        assert flat.ndim == 1 and flat.size == net.num_params()
        net2 = MultiLayerNetwork(_mlp_conf()).init()
        net2.set_params_flat(flat)
        np.testing.assert_array_equal(net2.params_flat(), flat)
        x = np.random.default_rng(0).random((4, 4))
        np.testing.assert_allclose(net.output(x), net2.output(x), rtol=1e-6)


class TestConfSerialization:
    def test_json_round_trip(self):
        conf = _mlp_conf(l2=0.01, updater="adam")
        s = conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(s)
        assert conf2.to_json() == s
        # and the deserialized conf builds an identical network
        n1 = MultiLayerNetwork(conf).init()
        n2 = MultiLayerNetwork(conf2).init()
        np.testing.assert_array_equal(n1.params_flat(), n2.params_flat())

    def test_builder_typo_surfaces_at_build(self):
        b = NeuralNetConfiguration.builder().learning_rate(0.1).bogus_field(3)
        try:
            b.build()
            assert False, "expected TypeError"
        except TypeError:
            pass


class TestFitScan:
    def test_scan_matches_per_step_fit(self):
        """Device-resident scanned epoch == per-step fit (same math)."""
        import jax.numpy as jnp
        ds = load_iris_dataset(shuffle_seed=3)[:96]
        a = MultiLayerNetwork(_mlp_conf(lr=0.2)).init()
        b = MultiLayerNetwork(_mlp_conf(lr=0.2)).init()
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        for _ in range(2):
            a.fit(ListDataSetIterator(ds, 32))
        scores = b.fit_scan(ds, 32, epochs=2)
        assert scores.shape == (6,)
        np.testing.assert_allclose(a.params_flat(), b.params_flat(), rtol=1e-5, atol=1e-7)
