"""Disk-staged dataset export tests.

Parity (VERDICT r2 missing #4): ``spark/data/BatchAndExportDataSetsFunction.java``
re-batch/export semantics + training from spilled files without
materializing the dataset (``exportIfRequired`` :815 doctrine).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.export import (
    ExportedDataSetIterator, export_dataset)


def _gen(rng, n_chunks=6, chunk=25, f=5, c=3):
    """A generator stream — nothing holds the full data."""
    for _ in range(n_chunks):
        x = rng.standard_normal((chunk, f)).astype(np.float32)
        y = np.eye(c, dtype=np.float32)[rng.integers(0, c, chunk)]
        yield DataSet(x, y)


class TestExport:
    def test_rebatch_uniform_with_tail(self, rng, tmp_path):
        """150 examples re-batched at 32: files of exactly 32 + one
        22-example tail (BatchAndExportDataSetsFunction semantics)."""
        n = export_dataset(_gen(rng), str(tmp_path), batch_size=32)
        assert n == 5
        it = ExportedDataSetIterator(str(tmp_path))
        sizes = [b.num_examples() for b in it]
        assert sizes == [32, 32, 32, 32, 22]
        assert it.total_examples() == 150

    def test_round_trips_content_exactly(self, rng, tmp_path):
        x = rng.standard_normal((40, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 40)]
        export_dataset(DataSet(x, y), str(tmp_path), batch_size=16)
        it = ExportedDataSetIterator(str(tmp_path))
        got_x = np.concatenate([np.asarray(b.features) for b in it])
        np.testing.assert_array_equal(got_x, x)

    def test_trains_from_spilled_dataset(self, rng, tmp_path):
        """A net trains straight from the exported files — the iterator
        holds one batch at a time (fit auto-wraps in async prefetch)."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        export_dataset(_gen(rng), str(tmp_path), batch_size=32)
        conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
                .updater("adam").activation("tanh").list()
                .layer(DenseLayer(n_in=5, n_out=16))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        it = ExportedDataSetIterator(str(tmp_path))
        net.fit(it)
        s0 = net.score()
        for _ in range(15):
            it.reset()
            net.fit(it)
        assert np.isfinite(net.score()) and net.score() < s0

    def test_resume_mid_epoch(self, rng, tmp_path):
        export_dataset(_gen(rng), str(tmp_path), batch_size=25)
        it = ExportedDataSetIterator(str(tmp_path), shuffle=True, seed=3)
        seen = [it.next() for _ in range(3)]
        cursor = it.state()
        # drain via has_next/next: `for b in it` resets (DataSetIterator
        # contract puts reset in __iter__)
        drain = lambda i: [np.asarray(i.next().features) for _ in
                           iter(i.has_next, False)]
        rest_a = drain(it)

        it2 = ExportedDataSetIterator(str(tmp_path), shuffle=True,
                                      seed=3).restore(cursor)
        rest_b = drain(it2)
        assert len(rest_a) == len(rest_b) == 3
        for a, b in zip(rest_a, rest_b):
            np.testing.assert_array_equal(a, b)
        with pytest.raises(ValueError, match="mismatch"):
            ExportedDataSetIterator(str(tmp_path)).restore(cursor)

    def test_shuffle_order_changes_per_epoch(self, rng, tmp_path):
        export_dataset(_gen(rng, n_chunks=8), str(tmp_path), batch_size=25)
        it = ExportedDataSetIterator(str(tmp_path), shuffle=True, seed=1)
        first = [it._order[:]]
        it.reset()
        assert it._order != first[0]

    def test_masked_datasets_export_without_rebatch(self, rng, tmp_path):
        x = rng.standard_normal((10, 4, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (10, 4))]
        m = np.ones((10, 4), np.float32)
        export_dataset([DataSet(x, y, labels_mask=m)], str(tmp_path))
        b = ExportedDataSetIterator(str(tmp_path)).next()
        assert b.labels_mask is not None
        with pytest.raises(ValueError, match="masked"):
            export_dataset([DataSet(x, y, labels_mask=m)],
                           str(tmp_path / "x"), batch_size=4)
