"""Unit tests for the functional op layer (activations, losses, inits).

Mirrors the reference's ND4J-op-level unit coverage (SURVEY.md §4:
construct small inputs, assert hand-computed values).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.activations import Activation, activate, activation_gradient
from deeplearning4j_tpu.ops.losses import LossFunction, compute_loss
from deeplearning4j_tpu.nn.weights import WeightInit, init_weights


class TestActivations:
    def test_relu(self):
        x = jnp.array([-2.0, -0.5, 0.0, 1.5])
        np.testing.assert_allclose(activate("relu", x), [0, 0, 0, 1.5])

    def test_sigmoid_values(self):
        x = jnp.array([0.0])
        np.testing.assert_allclose(activate("sigmoid", x), [0.5])

    def test_softmax_rows_sum_to_one(self):
        x = jnp.arange(12.0).reshape(3, 4)
        s = activate("softmax", x)
        np.testing.assert_allclose(jnp.sum(s, axis=-1), np.ones(3), rtol=1e-6)

    def test_hardtanh(self):
        x = jnp.array([-5.0, -0.3, 0.3, 5.0])
        np.testing.assert_allclose(activate("hardtanh", x), [-1, -0.3, 0.3, 1])

    def test_cube(self):
        np.testing.assert_allclose(activate("cube", jnp.array([2.0])), [8.0])

    @pytest.mark.parametrize("name", [a for a in Activation if a is not Activation.SOFTMAX])
    def test_gradient_matches_jax(self, name):
        x = jnp.linspace(-2.0, 2.0, 7)
        g = activation_gradient(name, x)
        g_ref = jax.vmap(jax.grad(lambda v: activate(name, v)))(x)
        np.testing.assert_allclose(g, g_ref, rtol=1e-6, atol=1e-6)

    def test_all_finite_on_extremes(self):
        x = jnp.array([-50.0, 50.0])
        for a in Activation:
            y = activate(a, x)
            assert bool(jnp.all(jnp.isfinite(y))), a


class TestLosses:
    def test_mse_hand_computed(self):
        # DL4J convention: sum of squared error over features, mean over batch
        labels = jnp.array([[1.0, 0.0], [0.0, 1.0]])
        preds = jnp.array([[0.5, 0.5], [0.0, 1.0]])
        val = compute_loss("mse", labels, preds)
        np.testing.assert_allclose(val, (0.25 + 0.25) / 2.0, rtol=1e-6)

    def test_mcxent_one_hot(self):
        labels = jnp.array([[1.0, 0.0]])
        preds = jnp.array([[0.25, 0.75]])
        np.testing.assert_allclose(compute_loss("mcxent", labels, preds), -np.log(0.25), rtol=1e-5)

    def test_mcxent_from_logits_matches_softmax_path(self):
        key = jax.random.PRNGKey(0)
        logits = jax.random.normal(key, (4, 5))
        labels = jax.nn.one_hot(jnp.array([0, 2, 4, 1]), 5)
        a = compute_loss("mcxent", labels, jax.nn.softmax(logits), from_logits=False)
        b = compute_loss("mcxent", labels, logits, from_logits=True)
        np.testing.assert_allclose(a, b, rtol=1e-5)

    def test_xent_from_logits_matches_sigmoid_path(self):
        logits = jnp.array([[0.3, -1.2, 2.0]])
        labels = jnp.array([[1.0, 0.0, 1.0]])
        a = compute_loss("xent", labels, jax.nn.sigmoid(logits), from_logits=False)
        b = compute_loss("xent", labels, logits, from_logits=True)
        np.testing.assert_allclose(a, b, rtol=1e-5)

    def test_mask_excludes_examples(self):
        labels = jnp.array([[1.0], [1.0]])
        preds = jnp.array([[0.0], [1.0]])
        mask = jnp.array([1.0, 0.0])
        # only first example counts: (1-0)^2 = 1
        np.testing.assert_allclose(compute_loss("mse", labels, preds, mask=mask), 1.0)

    @pytest.mark.parametrize("name", list(LossFunction))
    def test_all_losses_finite_and_scalar(self, name):
        key = jax.random.PRNGKey(3)
        labels = jax.nn.softmax(jax.random.normal(key, (6, 4)))
        preds = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(4), (6, 4)))
        v = compute_loss(name, labels, preds)
        assert v.shape == ()
        assert bool(jnp.isfinite(v))


class TestWeightInit:
    def test_zero_ones(self):
        k = jax.random.PRNGKey(0)
        assert float(jnp.sum(init_weights(k, (3, 3), "zero", 3, 3))) == 0.0
        assert float(jnp.sum(init_weights(k, (3, 3), "ones", 3, 3))) == 9.0

    def test_xavier_std(self):
        k = jax.random.PRNGKey(1)
        w = init_weights(k, (500, 500), WeightInit.XAVIER, 500, 500)
        expected = np.sqrt(2.0 / 1000.0)
        assert abs(float(jnp.std(w)) - expected) < 0.1 * expected

    def test_uniform_bounds(self):
        k = jax.random.PRNGKey(2)
        w = init_weights(k, (100, 100), WeightInit.UNIFORM, 100, 100)
        a = 1.0 / np.sqrt(100)
        assert float(jnp.max(jnp.abs(w))) <= a

    def test_deterministic_given_key(self):
        k = jax.random.PRNGKey(7)
        w1 = init_weights(k, (4, 4), "xavier", 4, 4)
        w2 = init_weights(k, (4, 4), "xavier", 4, 4)
        np.testing.assert_array_equal(w1, w2)


def test_sparse_mcxent_matches_onehot(rng):
    """Integer-id labels == one-hot labels for mcxent/nll, logits and
    probability paths, 2-D and 3-D, masked and not."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.losses import compute_loss

    b, t, c = 4, 5, 7
    logits = jnp.asarray(rng.standard_normal((b, t, c)), jnp.float32)
    ids = rng.integers(0, c, (b, t))
    onehot = jnp.asarray(np.eye(c, dtype=np.float32)[ids])
    sparse = jnp.asarray(ids, jnp.float32)
    mask = jnp.asarray((rng.random((b, t)) > 0.4), jnp.float32)
    for from_logits in (True, False):
        preds = logits if from_logits else jax.nn.softmax(logits, axis=-1)
        for m in (None, mask):
            a = compute_loss("mcxent", onehot, preds, mask=m,
                             from_logits=from_logits)
            s = compute_loss("mcxent", sparse, preds, mask=m,
                             from_logits=from_logits)
            np.testing.assert_allclose(np.asarray(s), np.asarray(a),
                                       rtol=1e-6, atol=1e-7)
    # 2-D case
    a2 = compute_loss("negativeloglikelihood", onehot[:, 0], logits[:, 0],
                      from_logits=True)
    s2 = compute_loss("negativeloglikelihood", sparse[:, 0], logits[:, 0],
                      from_logits=True)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(a2), rtol=1e-6)
    # sparse labels reject non-xent losses loudly
    import pytest
    with pytest.raises(ValueError, match="sparse"):
        compute_loss("mse", sparse, logits)


def test_sparse_mcxent_ignore_index(rng):
    """Negative ids contribute zero loss and are excluded from the mean
    (the ignore-index convention)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.losses import compute_loss

    logits = jnp.asarray(rng.standard_normal((6, 5)), jnp.float32)
    ids = rng.integers(0, 5, 6)
    sparse = jnp.asarray(ids, jnp.float32)
    ignored = sparse.at[2].set(-1.0).at[4].set(-1.0)
    keep = jnp.asarray([1, 1, 0, 1, 0, 1], jnp.float32)
    want = compute_loss("mcxent", sparse, logits, mask=keep, from_logits=True)
    got = compute_loss("mcxent", ignored, logits, from_logits=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


class TestMaxpoolMaskVJP:
    """The opt-in equality-mask maxpool backward (ops/pooling.py)."""

    def test_matches_xla_backward_on_distinct_values(self, rng):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from deeplearning4j_tpu.ops.pooling import maxpool2d

        x = jnp.asarray(rng.permutation(8 * 9 * 9 * 3).reshape(8, 9, 9, 3),
                        jnp.float32)

        def ref(x):
            return jnp.sum(lax.reduce_window(
                x * x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                ((0, 0), (1, 1), (1, 1), (0, 0))))

        def got(x):
            return jnp.sum(maxpool2d(x * x, (3, 3), (2, 2), (1, 1)))

        np.testing.assert_allclose(np.asarray(jax.grad(got)(x)),
                                   np.asarray(jax.grad(ref)(x)), rtol=1e-6)

    def test_tie_mass_preserved(self, rng):
        """With exact ties, each window's gradient splits evenly across
        maximal cells — total mass per window preserved (ADVICE r3)."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.ops.pooling import maxpool2d

        x = jnp.ones((1, 4, 4, 1), jnp.float32)  # every cell ties
        g = jax.grad(lambda x: jnp.sum(maxpool2d(x, (2, 2), (2, 2), (0, 0))))(x)
        # 4 windows, each distributing 1.0 over 4 tied cells
        np.testing.assert_allclose(np.asarray(g), 0.25)
        assert float(jnp.sum(g)) == 4.0
