"""Layer-breadth tests: CNN / BN / LRN / LSTM / embedding / autoencoder.

Ports of ``CNNGradientCheckTest.java``, ``BNGradientCheckTest.java``,
``LRNGradientCheckTests.java``, ``GradientCheckTests`` LSTM cases and
``GradientCheckTestsMasking.java`` (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    AutoEncoder,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LocalResponseNormalization,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _net(layers, input_type=None, **conf_kw):
    b = NeuralNetConfiguration.builder().seed(42)
    for k, v in conf_kw.items():
        b = getattr(b, k)(v)
    lb = b.list()
    for l in layers:
        lb = lb.layer(l)
    if input_type is not None:
        lb = lb.set_input_type(input_type)
    return MultiLayerNetwork(lb.build()).init(dtype=jnp.float64)


def _assert_gc(net, ds, train=False, subset=None):
    res = check_gradients(net, ds, subset=subset, train=train)
    assert res.ok, f"{res.n_failed}/{res.n_checked} failed; " + "; ".join(res.failures[:3])


class TestCNNGradients:
    def test_conv_pool_dense(self, rng):
        net = _net(
            [ConvolutionLayer(n_out=2, kernel_size=(2, 2), stride=(1, 1)),
             SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
             OutputLayer(n_out=3, activation="softmax", loss_function="mcxent")],
            input_type=InputType.convolutional(6, 6, 1),
            activation="tanh", weight_init="xavier")
        x = rng.standard_normal((4, 6, 6, 1))
        y = np.eye(3)[rng.integers(0, 3, 4)]
        _assert_gc(net, DataSet(x, y))

    def test_conv_same_mode_avg_pool(self, rng):
        net = _net(
            [ConvolutionLayer(n_out=2, kernel_size=(3, 3), stride=(1, 1), convolution_mode="same"),
             SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type="avg"),
             OutputLayer(n_out=2, activation="softmax", loss_function="mcxent")],
            input_type=InputType.convolutional(4, 4, 2),
            activation="relu", weight_init="xavier")
        x = rng.standard_normal((3, 4, 4, 2))
        y = np.eye(2)[rng.integers(0, 2, 3)]
        _assert_gc(net, DataSet(x, y))

    def test_shapes_lenet_style(self, rng):
        net = _net(
            [ConvolutionLayer(n_out=4, kernel_size=(5, 5)),
             SubsamplingLayer(),
             ConvolutionLayer(n_out=6, kernel_size=(5, 5)),
             SubsamplingLayer(),
             DenseLayer(n_out=10),
             OutputLayer(n_out=10, activation="softmax", loss_function="mcxent")],
            input_type=InputType.convolutional(28, 28, 1),
            activation="relu", weight_init="relu")
        x = rng.standard_normal((2, 28, 28, 1))
        out = net.output(x)
        assert out.shape == (2, 10)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


class TestBatchNorm:
    def test_bn_gradcheck_train_mode(self, rng):
        net = _net(
            [DenseLayer(n_in=4, n_out=5),
             BatchNormalization(n_in=5, n_out=5),
             OutputLayer(n_in=5, n_out=3, activation="softmax", loss_function="mcxent")],
            activation="tanh")
        x = rng.standard_normal((8, 4))
        y = np.eye(3)[rng.integers(0, 3, 8)]
        _assert_gc(net, DataSet(x, y), train=True)

    def test_bn_moving_stats_update_and_freeze(self, rng):
        net = _net(
            [BatchNormalization(n_in=3, n_out=3),
             OutputLayer(n_in=3, n_out=2, activation="softmax", loss_function="mcxent")])
        x = rng.standard_normal((16, 3)) * 3.0 + 1.0
        y = np.eye(2)[rng.integers(0, 2, 16)]
        st0 = net.states["layer0"]
        np.testing.assert_array_equal(np.asarray(st0["mean"]), 0.0)
        net.fit(DataSet(x, y))
        st1 = net.states["layer0"]
        assert float(np.abs(np.asarray(st1["mean"])).sum()) > 0  # stats moved
        # eval output must use moving stats (deterministic, no batch dependence)
        o1 = net.output(x[:4])
        o2 = net.output(np.concatenate([x[:4], x[4:8] * 10]))[:4]
        np.testing.assert_allclose(o1, o2, rtol=1e-6)


class TestLRN:
    def test_lrn_gradcheck(self, rng):
        net = _net(
            [ConvolutionLayer(n_out=6, kernel_size=(2, 2)),
             LocalResponseNormalization(),
             OutputLayer(n_out=2, activation="softmax", loss_function="mcxent")],
            input_type=InputType.convolutional(4, 4, 1),
            activation="tanh")
        x = rng.standard_normal((3, 4, 4, 1))
        y = np.eye(2)[rng.integers(0, 2, 3)]
        _assert_gc(net, DataSet(x, y), subset=60)


class TestLSTM:
    def test_lstm_gradcheck(self, rng):
        net = _net(
            [GravesLSTM(n_in=3, n_out=4),
             RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss_function="mcxent")],
            activation="tanh")
        x = rng.standard_normal((3, 5, 3))
        y = np.eye(2)[rng.integers(0, 2, (3, 5))]
        _assert_gc(net, DataSet(x, y))

    def test_bidirectional_lstm_gradcheck(self, rng):
        net = _net(
            [GravesBidirectionalLSTM(n_in=3, n_out=3),
             RnnOutputLayer(n_in=3, n_out=2, activation="softmax", loss_function="mcxent")],
            activation="tanh")
        x = rng.standard_normal((2, 4, 3))
        y = np.eye(2)[rng.integers(0, 2, (2, 4))]
        _assert_gc(net, DataSet(x, y), subset=120)

    def test_lstm_masking_gradcheck(self, rng):
        """GradientCheckTestsMasking: variable-length sequences."""
        net = _net(
            [GravesLSTM(n_in=3, n_out=4),
             RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss_function="mcxent")],
            activation="tanh")
        x = rng.standard_normal((3, 6, 3))
        y = np.eye(2)[rng.integers(0, 2, (3, 6))]
        mask = np.ones((3, 6))
        mask[0, 4:] = 0
        mask[2, 2:] = 0
        ds = DataSet(x, y, features_mask=mask, labels_mask=mask)
        _assert_gc(net, ds, subset=120)

    def test_masked_steps_do_not_affect_output(self, rng):
        net = _net(
            [GravesLSTM(n_in=2, n_out=3),
             RnnOutputLayer(n_in=3, n_out=2, activation="softmax", loss_function="mcxent")])
        x = rng.standard_normal((1, 5, 2))
        mask = np.array([[1, 1, 1, 0, 0.0]])
        x2 = x.copy()
        x2[0, 3:] = 99.0  # garbage in masked region
        o1 = net.output(x, features_mask=mask)
        o2 = net.output(x2, features_mask=mask)
        np.testing.assert_allclose(o1[0, :3], o2[0, :3], rtol=1e-6)

    def test_rnn_time_step_matches_full_forward(self, rng):
        from deeplearning4j_tpu.nn.layers.base import build_layer
        net = _net(
            [GravesLSTM(n_in=2, n_out=3),
             RnnOutputLayer(n_in=3, n_out=2, activation="softmax", loss_function="mcxent")])
        impl = net.impls[0]
        params = net.params["layer0"]
        x = jnp.asarray(rng.standard_normal((2, 4, 2)))
        full, _ = impl.forward(params, x, {}, False)
        state = {}
        for t in range(4):
            step_out, state = impl.rnn_time_step(params, x[:, t, :], state)
            np.testing.assert_allclose(np.asarray(step_out), np.asarray(full[:, t, :]),
                                       rtol=1e-5, atol=1e-8)


class TestEmbedding:
    def test_embedding_forward_is_row_lookup(self, rng):
        net = _net(
            [EmbeddingLayer(n_in=7, n_out=4, activation="identity"),
             OutputLayer(n_in=4, n_out=3, activation="softmax", loss_function="mcxent")])
        W = np.asarray(net.params["layer0"]["W"])
        idx = np.array([[2], [5]])
        acts = net.feed_forward(idx.astype(np.float64))
        np.testing.assert_allclose(acts[0], W[[2, 5]], rtol=1e-6)


class TestGlobalPooling:
    def test_masked_mean_pooling(self, rng):
        net = _net(
            [GravesLSTM(n_in=2, n_out=3),
             GlobalPoolingLayer(pooling_type="avg"),
             OutputLayer(n_in=3, n_out=2, activation="softmax", loss_function="mcxent")])
        x = rng.standard_normal((2, 5, 2))
        mask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1.0]])
        y = np.eye(2)[[0, 1]]
        ds = DataSet(x, y, features_mask=mask)
        _assert_gc(net, ds, subset=80)


class TestAutoEncoderPretrain:
    def test_pretrain_loss_decreases(self, rng):
        from deeplearning4j_tpu.nn.layers.feedforward import AutoEncoderImpl
        from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration as NNC
        gc = NNC(seed=1, activation="sigmoid", weight_init="xavier")
        conf = AutoEncoder(n_in=8, n_out=4, corruption_level=0.0)
        impl = AutoEncoderImpl(gc, conf, "ae")
        params = impl.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.random((16, 8)))
        loss_fn = jax.jit(lambda p: impl.pretrain_loss(p, x, None))
        g_fn = jax.jit(jax.grad(lambda p: impl.pretrain_loss(p, x, None)))
        l0 = float(loss_fn(params))
        for _ in range(200):
            g = g_fn(params)
            params = jax.tree.map(lambda p, gg: p - 1.0 * gg, params, g)
        assert float(loss_fn(params)) < l0 * 0.8


class TestDistributionWeightInit:
    """nn/conf/distribution/ parity: Normal/Uniform/Binomial behind
    WeightInit.DISTRIBUTION via the layer's dist field."""

    def test_uniform_distribution_bounds(self, rng):
        import jax
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.weights import Distribution

        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DenseLayer(n_in=20, n_out=30,
                                  weight_init="distribution",
                                  dist=Distribution.uniform(0.25, 0.75)))
                .layer(OutputLayer(n_in=30, n_out=2, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(conf).init()
        W = np.asarray(net.params["layer0"]["W"])
        assert W.min() >= 0.25 and W.max() <= 0.75
        assert W.std() > 0.05  # actually random, not constant

    def test_binomial_distribution_counts(self, rng):
        from deeplearning4j_tpu.nn.weights import Distribution
        import jax
        v = np.asarray(Distribution.binomial(8, 0.5).sample(
            jax.random.PRNGKey(0), (500,)))
        assert v.min() >= 0 and v.max() <= 8
        assert abs(v.mean() - 4.0) < 0.4

    def test_dist_json_roundtrip(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.weights import Distribution

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_in=3, n_out=4,
                                  weight_init="distribution",
                                  dist=Distribution.uniform(-0.1, 0.1)))
                .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        back = MultiLayerConfiguration.from_json(conf.to_json())
        d = back.layers[0].dist
        assert isinstance(d, Distribution)
        assert (d.kind, d.lower, d.upper) == ("uniform", -0.1, 0.1)

    def test_dist_reaches_every_layer_family(self, rng):
        """WeightInit.DISTRIBUTION + dist must not silently fall back to
        N(0,1) anywhere (review r4): check one weight per family."""
        import jax
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import (
            AttentionLayer, GravesLSTM, MoELayer, TransformerBlock)
        from deeplearning4j_tpu.nn.layers.base import build_layer
        from deeplearning4j_tpu.nn.weights import Distribution

        gc = NeuralNetConfiguration()
        dist = Distribution.uniform(0.1, 0.2)
        mk = dict(weight_init="distribution", dist=dist)
        layers = [
            (GravesLSTM(n_in=8, n_out=8, **mk), "Wx"),
            (AttentionLayer(n_in=8, n_out=8, num_heads=2, **mk), "Wq"),
            (TransformerBlock(n_in=8, n_out=8, num_heads=2, **mk), "Wqkv"),
            (MoELayer(n_in=8, n_out=8, num_experts=2, **mk), "W1"),
        ]
        for conf, wname in layers:
            impl = build_layer(gc, conf, "l")
            W = np.asarray(impl.init_params(jax.random.PRNGKey(0))[wname])
            assert W.min() >= 0.1 and W.max() <= 0.2, \
                f"{type(conf).__name__}.{wname} ignored dist: " \
                f"[{W.min():.3f}, {W.max():.3f}]"
