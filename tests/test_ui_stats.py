"""Observability subsystem tests: StatsListener → StatsStorage → HTML.

Parity: ``StatsListener.java:46-187``, ``StatsStorage.java`` +
``MapDBStatsStorage.java:21``, ``UiServer.java`` dashboard role
(static HTML export here).
"""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    FileStatsStorage, InMemoryStatsStorage, StatsListener, StatsReport,
    render_html, save_report)


def _train(storage, rng, histograms=False, n_iters=6):
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(storage, session_id="s1",
                                    histograms=histograms))
    x = rng.standard_normal((16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    for _ in range(n_iters):
        net.fit(DataSet(x, y))
    return net


def test_stats_collected_in_memory(rng):
    storage = InMemoryStatsStorage()
    _train(storage, rng)
    assert storage.list_sessions() == ["s1"]
    reports = storage.get_reports("s1")
    assert len(reports) == 6
    r = reports[-1]
    assert np.isfinite(r.score)
    assert set(r.param_norms) == {"layer0/W", "layer0/b", "layer1/W", "layer1/b"}
    assert all(v >= 0 for v in r.param_norms.values())
    # update magnitudes appear from the second report on
    assert reports[1].update_norms and not reports[0].update_norms
    assert np.isfinite(reports[-1].duration_ms)


def test_file_storage_roundtrip_and_report(rng, tmp_path):
    storage = FileStatsStorage(str(tmp_path / "stats"))
    _train(storage, rng, histograms=True, n_iters=4)
    # fresh handle reads back what the listener wrote
    storage2 = FileStatsStorage(str(tmp_path / "stats"))
    reports = storage2.get_reports("s1")
    assert len(reports) == 4
    assert reports[-1].param_histograms["layer0/W"]["counts"]
    html_text = render_html(storage2, "s1")
    assert "<svg" in html_text and "Score vs iteration" in html_text
    out = save_report(storage2, "s1", str(tmp_path / "report.html"))
    assert open(out).read().startswith("<!DOCTYPE html>")


def test_change_listener(rng):
    storage = InMemoryStatsStorage()
    seen = []
    storage.add_listener(lambda r: seen.append(r.iteration))
    _train(storage, rng, n_iters=3)
    assert len(seen) == 3


def _scripted_clock(values):
    """Fake perf_counter: scripted readings, then keep ticking — patching
    the stdlib attribute is process-wide, so stray callers in other
    threads must not exhaust the script."""
    state = {"i": 0, "last": values[-1]}

    def clock():
        i = state["i"]
        if i < len(values):
            state["i"] = i + 1
            return values[i]
        state["last"] += 1.0
        return state["last"]

    return clock


def test_duration_is_windowed_mean_with_frequency(monkeypatch):
    """With frequency > 1, duration_ms must be the mean per-iteration
    duration over the whole reporting window — not the gap since the
    last single call (the bug this pins down reported only the final
    iteration's duration)."""
    import types

    from deeplearning4j_tpu.ui import stats as stats_mod

    # the clock is read on report iterations only (2 and 4)
    monkeypatch.setattr(stats_mod.time, "perf_counter",
                        _scripted_clock([11.0, 20.0]))
    storage = InMemoryStatsStorage()
    listener = stats_mod.StatsListener(storage, frequency=2)
    model = types.SimpleNamespace(params=None)
    for it in range(1, 5):
        listener.iteration_done(model, it, 0.5)
    reports = storage.get_reports("default")
    assert [r.iteration for r in reports] == [2, 4]
    assert np.isnan(reports[0].duration_ms)  # no prior report window
    # window it2(t=11) -> it4(t=20): 9s over 2 iterations = 4500ms/iter
    # (the pre-fix behavior reported the last gap alone: 8000ms)
    assert reports[1].duration_ms == pytest.approx(4500.0)


def test_duration_windowed_mean_publishes_to_registry(monkeypatch):
    import types

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.ui import stats as stats_mod

    reg = monitor.MetricsRegistry()
    monkeypatch.setattr(stats_mod.time, "perf_counter",
                        _scripted_clock([1.0, 2.0]))
    listener = stats_mod.StatsListener(InMemoryStatsStorage(), registry=reg)
    model = types.SimpleNamespace(params=None)
    listener.iteration_done(model, 1, 0.25)
    listener.iteration_done(model, 2, float("nan"))
    assert reg.get("dl4j_score", session="default",
                   worker="worker0").value == 0.25
    assert reg.family_total("dl4j_nan_scores_total") == 1
    hist = reg.get("dl4j_step_duration_ms", session="default",
                   worker="worker0")
    assert hist.count == 1 and hist.sum == pytest.approx(1000.0)


def test_from_dict_restores_histogram_nans():
    """to_dict scrubs non-finite floats to null for strict JSON; the
    round-trip must restore param_histograms the way it already restores
    param_norms/update_norms/memory (a diverged run's histogram min/max
    are NaN)."""
    report = StatsReport(
        session_id="s", worker_id="w", iteration=3, timestamp=1.0,
        score=float("nan"),
        param_norms={"l0/W": float("nan")},
        param_histograms={"l0/W": {"counts": [1, 2, 3],
                                   "min": float("nan"),
                                   "max": float("inf")}})
    back = StatsReport.from_dict(json.loads(json.dumps(report.to_dict())))
    assert np.isnan(back.score) and np.isnan(back.param_norms["l0/W"])
    h = back.param_histograms["l0/W"]
    assert h["counts"] == [1, 2, 3]
    assert np.isnan(h["min"]) and np.isnan(h["max"])  # inf scrubs to null too
    # finite payloads round-trip exactly
    fin = StatsReport(session_id="s", worker_id="w", iteration=4,
                      timestamp=2.0, score=0.5, duration_ms=2.5,
                      param_histograms={"l0/W": {"counts": [4],
                                                 "min": -1.0, "max": 1.0}})
    assert StatsReport.from_dict(
        json.loads(json.dumps(fin.to_dict()))) == fin
