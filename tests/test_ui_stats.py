"""Observability subsystem tests: StatsListener → StatsStorage → HTML.

Parity: ``StatsListener.java:46-187``, ``StatsStorage.java`` +
``MapDBStatsStorage.java:21``, ``UiServer.java`` dashboard role
(static HTML export here).
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    FileStatsStorage, InMemoryStatsStorage, StatsListener, render_html, save_report)


def _train(storage, rng, histograms=False, n_iters=6):
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(storage, session_id="s1",
                                    histograms=histograms))
    x = rng.standard_normal((16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    for _ in range(n_iters):
        net.fit(DataSet(x, y))
    return net


def test_stats_collected_in_memory(rng):
    storage = InMemoryStatsStorage()
    _train(storage, rng)
    assert storage.list_sessions() == ["s1"]
    reports = storage.get_reports("s1")
    assert len(reports) == 6
    r = reports[-1]
    assert np.isfinite(r.score)
    assert set(r.param_norms) == {"layer0/W", "layer0/b", "layer1/W", "layer1/b"}
    assert all(v >= 0 for v in r.param_norms.values())
    # update magnitudes appear from the second report on
    assert reports[1].update_norms and not reports[0].update_norms
    assert np.isfinite(reports[-1].duration_ms)


def test_file_storage_roundtrip_and_report(rng, tmp_path):
    storage = FileStatsStorage(str(tmp_path / "stats"))
    _train(storage, rng, histograms=True, n_iters=4)
    # fresh handle reads back what the listener wrote
    storage2 = FileStatsStorage(str(tmp_path / "stats"))
    reports = storage2.get_reports("s1")
    assert len(reports) == 4
    assert reports[-1].param_histograms["layer0/W"]["counts"]
    html_text = render_html(storage2, "s1")
    assert "<svg" in html_text and "Score vs iteration" in html_text
    out = save_report(storage2, "s1", str(tmp_path / "report.html"))
    assert open(out).read().startswith("<!DOCTYPE html>")


def test_change_listener(rng):
    storage = InMemoryStatsStorage()
    seen = []
    storage.add_listener(lambda r: seen.append(r.iteration))
    _train(storage, rng, n_iters=3)
    assert len(seen) == 3
