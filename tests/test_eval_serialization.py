"""Evaluation metrics, checkpoint round-trip, early stopping, listeners.

Ports of ``EvaluationTests``, ``ModelSerializerTest.java``,
``earlystopping`` tests (SURVEY.md §4).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iris import load_iris_dataset
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.eval import Evaluation, ROC, RegressionEvaluation
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import CollectScoresIterationListener, ScoreIterationListener
from deeplearning4j_tpu.util.model_serializer import (
    restore_multi_layer_network,
    write_model,
)


def _small_net(seed=1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.3).updater("adam")
            .activation("tanh").list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax", loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


class TestEvaluation:
    def test_perfect_predictions(self):
        e = Evaluation(3)
        labels = np.eye(3)[[0, 1, 2, 0]]
        e.eval(labels, labels)
        assert e.accuracy() == 1.0
        assert e.f1() == 1.0

    def test_known_confusion(self):
        e = Evaluation(2)
        labels = np.eye(2)[[0, 0, 1, 1]]
        preds = np.eye(2)[[0, 1, 1, 1]]
        e.eval(labels, preds)
        assert e.accuracy() == 0.75
        assert e.confusion.get_count(0, 1) == 1
        # class-1: tp=2 fp=1 fn=0
        assert e.precision(1) == pytest.approx(2 / 3)
        assert e.recall(1) == 1.0

    def test_time_series_masked(self):
        e = Evaluation(2)
        labels = np.zeros((1, 3, 2))
        labels[0, :, 0] = 1
        preds = np.zeros((1, 3, 2))
        preds[0, 0, 0] = 1  # correct
        preds[0, 1, 1] = 1  # wrong
        preds[0, 2, 1] = 1  # wrong but masked
        mask = np.array([[1, 1, 0.0]])
        e.eval(labels, preds, mask=mask)
        assert e.confusion.counts.sum() == 2
        assert e.accuracy() == 0.5

    def test_meta_attribution(self):
        e = Evaluation(2)
        labels = np.eye(2)[[0, 1]]
        preds = np.eye(2)[[1, 1]]
        e.eval(labels, preds, meta=["exA", "exB"])
        assert e.get_meta(0, 1) == ["exA"]
        assert e.get_meta(1, 1) == ["exB"]

    def test_sparse_labels_match_one_hot(self):
        dense, sparse = Evaluation(3), Evaluation(3)
        ids = np.array([0, 1, 2, 1])
        preds = np.eye(3)[[0, 2, 2, 1]]
        dense.eval(np.eye(3)[ids], preds)
        sparse.eval(ids, preds)
        np.testing.assert_array_equal(dense.confusion.counts,
                                      sparse.confusion.counts)

    def test_sparse_label_out_of_range_raises_clearly(self):
        """ADVICE r2: an id >= prediction width must fail loudly with the
        offending value, not deep inside np.add.at."""
        e = Evaluation(3)
        preds = np.eye(3)[[0, 1]]
        with pytest.raises(ValueError, match="sparse label id 7"):
            e.eval(np.array([0, 7]), preds)


class TestROC:
    def test_separable_auc_is_one(self):
        roc = ROC(threshold_steps=50)
        labels = np.array([0, 0, 0, 1, 1, 1])
        scores = np.array([0.1, 0.2, 0.3, 0.7, 0.8, 0.9])
        roc.eval(labels, scores)
        assert roc.calculate_auc() == pytest.approx(1.0, abs=0.02)

    def test_random_auc_half(self):
        rng = np.random.default_rng(0)
        roc = ROC(threshold_steps=100)
        labels = rng.integers(0, 2, 2000)
        scores = rng.random(2000)
        roc.eval(labels, scores)
        assert roc.calculate_auc() == pytest.approx(0.5, abs=0.05)


class TestRegressionEvaluation:
    def test_known_values(self):
        r = RegressionEvaluation(2)
        labels = np.array([[1.0, 2.0], [3.0, 4.0]])
        preds = np.array([[1.5, 2.0], [2.5, 3.0]])
        r.eval(labels, preds)
        assert r.mean_squared_error(0) == pytest.approx(0.25)
        assert r.mean_absolute_error(0) == pytest.approx(0.5)
        assert r.mean_absolute_error(1) == pytest.approx(0.5)

    def test_perfect_r2(self):
        r = RegressionEvaluation(1)
        y = np.linspace(0, 1, 10)[:, None]
        r.eval(y, y)
        assert r.r_squared(0) == pytest.approx(1.0)
        assert r.pearson_correlation(0) == pytest.approx(1.0)


class TestModelSerializer:
    def test_round_trip_identical_outputs(self, tmp_path):
        net = _small_net()
        ds = load_iris_dataset(shuffle_seed=1)
        net.fit(ListDataSetIterator(ds, 50))
        path = os.path.join(tmp_path, "model.zip")
        write_model(net, path)
        net2 = restore_multi_layer_network(path)
        np.testing.assert_allclose(net2.output(ds.features), net.output(ds.features),
                                   rtol=1e-6)
        # updater state restored: continued training matches
        assert int(net2.opt_state["step"]) == int(net.opt_state["step"])
        net.fit(ds[:32])
        net2.fit(ds[:32])
        np.testing.assert_allclose(net2.params_flat(), net.params_flat(), rtol=1e-5)

    def test_wrong_type_raises(self, tmp_path):
        from deeplearning4j_tpu.util.model_serializer import restore_computation_graph
        net = _small_net()
        path = os.path.join(tmp_path, "model.zip")
        write_model(net, path)
        with pytest.raises(ValueError, match="MultiLayerNetwork"):
            restore_computation_graph(path)


class TestEarlyStopping:
    def test_max_epochs_and_best_model(self):
        net = _small_net()
        ds = load_iris_dataset(shuffle_seed=2)
        train, test = ds.split_test_and_train(120)
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(8)],
            score_calculator=DataSetLossCalculator(ListDataSetIterator(test, 30)),
            model_saver=InMemoryModelSaver())
        result = EarlyStoppingTrainer(cfg, net, ListDataSetIterator(train, 40)).fit()
        assert result.total_epochs == 8
        assert result.termination_reason == "EpochTerminationCondition"
        assert result.best_model is not None
        assert result.best_model_score < 2.0

    def test_divergence_guard(self):
        net = _small_net()
        ds = load_iris_dataset()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(50)],
            iteration_termination_conditions=[MaxScoreIterationTerminationCondition(1e-9)])
        result = EarlyStoppingTrainer(cfg, net, ListDataSetIterator(ds, 50)).fit()
        assert result.termination_reason == "IterationTerminationCondition"

    def test_score_improvement_patience(self):
        c = ScoreImprovementEpochTerminationCondition(2)
        c.initialize()
        assert not c.terminate(0, 1.0)
        assert not c.terminate(1, 1.1)   # no improvement x1
        assert c.terminate(2, 1.2)       # no improvement x2 -> stop


class TestListeners:
    def test_collect_scores(self):
        net = _small_net()
        coll = CollectScoresIterationListener()
        net.set_listeners(coll, ScoreIterationListener(5))
        ds = load_iris_dataset()
        for _ in range(5):
            net.fit(ds)
        assert len(coll.scores) == 5
        assert coll.scores[-1][1] < coll.scores[0][1]  # learning
