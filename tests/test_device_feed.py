"""Device-feed pipeline: ordering/reset/mid-epoch-abandon races,
deferred score sync, and shape-bucketed tail-batch parity.

Mirrors the test_observed_sync doctrine: the async seams get many-trial
race tests, the exactness claims get bitwise assertions. The parity
claim verified here: a ragged tail batch padded to the canonical batch
size with a zeroing labels mask trains EXACTLY like the unpadded batch
— the masked mean divides by the real example count and padded rows
back-propagate exact zeros (ops/losses.py ``_masked_mean`` additionally
reproduces ``jnp.mean``'s forward rounding so the scores match bitwise;
parameters agree bitwise for the pinned seed and to one float32 ulp
across seeds — reductions over different batch shapes may associate
differently inside XLA, which is the irreducible floor).
"""

import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    DeviceFeedIterator,
    ListDataSetIterator,
    ShapeBucketingIterator,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener


@pytest.fixture
def registry():
    reg = monitor.MetricsRegistry()
    old = monitor.set_registry(reg)
    try:
        yield reg
    finally:
        monitor.set_registry(old)


def _mlp(seed=7, bn=False):
    b = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
         .updater("sgd").activation("tanh").list()
         .layer(DenseLayer(n_in=4, n_out=8)))
    if bn:
        b = b.layer(BatchNormalization(n_out=8))
    conf = b.layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent")).build()
    return MultiLayerNetwork(conf).init()


def _data(n, dseed=0):
    rng = np.random.default_rng(dseed)
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


# ------------------------------------------------------ device feed stage

def _feed_over(ds, batch, depth=2, place=None):
    return DeviceFeedIterator(
        AsyncDataSetIterator(ListDataSetIterator(ds, batch)),
        depth=depth, place=place)


def test_device_feed_preserves_order_and_values(registry):
    ds = _data(70)
    ref = [b for b in ListDataSetIterator(ds, 16)]
    feed = _feed_over(ds, 16)
    for epoch in range(2):  # second pass proves __iter__ -> reset works
        got = [b for b in feed]
        assert len(got) == len(ref)
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(g.features), r.features)
            np.testing.assert_array_equal(np.asarray(g.labels), r.labels)


def test_device_feed_places_on_device(registry):
    import jax.numpy as jnp
    ds = _data(32)
    place = lambda b: DataSet(jnp.asarray(b.features), jnp.asarray(b.labels))
    got = list(_feed_over(ds, 16, place=place))
    assert all(isinstance(b.features, jax.Array) for b in got)
    # h2d traffic visible through the gauge family (set by the worker)
    assert registry.get(monitor.FEED_QUEUE_DEPTH_GAUGE) is not None


def test_device_feed_reset_mid_epoch(registry):
    ds = _data(80)
    feed = _feed_over(ds, 16)
    assert feed.has_next()
    feed.next()
    feed.next()  # two batches consumed, three still in flight
    feed.reset()
    got = [b for b in feed]
    ref = [b for b in ListDataSetIterator(ds, 16)]
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(g.features), r.features)


def test_device_feed_abandon_race(registry):
    """Mid-epoch abandonment: close() must stop the worker even while
    it is blocked on a full queue, across many interleavings."""
    for trial in range(20):
        ds = _data(200, dseed=trial)
        feed = _feed_over(ds, 8, depth=2)
        k = trial % 5
        for _ in range(k):
            if feed.has_next():
                feed.next()
        if trial % 3 == 0:
            time.sleep(0.002)  # let the worker fill the buffer
        feed.close()
        t = feed._thread
        assert t is None or not t.is_alive(), f"worker leaked on trial {trial}"


def test_device_feed_worker_error_propagates(registry):
    class Boom(DataSetIterator):
        def __init__(self):
            self.i = 0

        def reset(self):
            self.i = 0

        def has_next(self):
            return self.i < 5

        def _next_impl(self):
            self.i += 1
            if self.i == 3:
                raise ValueError("bad record")
            return _data(4)

        def batch(self):
            return 4

    feed = DeviceFeedIterator(Boom(), depth=2)
    got = 0
    with pytest.raises(ValueError, match="bad record"):
        while feed.has_next():
            feed.next()
            got += 1
    assert got == 2  # both good batches arrived before the error


def test_async_iterator_close_stops_worker(registry):
    ds = _data(100)
    it = AsyncDataSetIterator(ListDataSetIterator(ds, 4), queue_size=2)
    assert it.has_next()
    it.next()
    it.close()
    t = it._thread
    assert t is None or not t.is_alive()


# -------------------------------------------------------- shape bucketing

def test_bucketing_pads_only_ragged_tail(registry):
    ds = _data(3 * 16 + 5)
    it = ShapeBucketingIterator(ListDataSetIterator(ds, 16))
    batches = list(it)
    assert [b.num_examples() for b in batches] == [16, 16, 16, 16]
    assert [b.labels_mask is None for b in batches] == [True, True, True, False]
    tail = batches[-1]
    np.testing.assert_array_equal(tail.labels_mask[:5], np.ones(5, np.float32))
    np.testing.assert_array_equal(tail.labels_mask[5:], np.zeros(11, np.float32))
    np.testing.assert_array_equal(tail.features[5:], 0.0)
    assert registry.family_total(monitor.FEED_PADDED_BATCHES_COUNTER) == 1


def test_bucketing_passthrough_for_masked_batches(registry):
    ds = _data(20)
    ds.labels_mask = np.ones(20, np.float32)
    it = ShapeBucketingIterator(ListDataSetIterator(ds, 16))
    batches = list(it)
    assert [b.num_examples() for b in batches] == [16, 4]
    assert registry.family_total(monitor.FEED_PADDED_BATCHES_COUNTER) == 0


def test_bucketing_parity_bitwise(registry):
    """The acceptance bar: padded tail-batch training is bitwise-
    identical to the unpadded run — scores and parameters."""
    ds = _data(3 * 16 + 5, dseed=0)
    a, b = _mlp(), _mlp()
    ca, cb = CollectScoresIterationListener(), CollectScoresIterationListener()
    a.set_listeners(ca)
    b.set_listeners(cb)
    for _ in range(2):
        a.fit(ListDataSetIterator(ds, 16), feed_pipeline=False)  # unpadded
        b.fit(ListDataSetIterator(ds, 16), feed_pipeline=True)   # bucketed
    assert ca.scores == cb.scores, "per-step scores diverged"
    np.testing.assert_array_equal(a.params_flat(), b.params_flat())


def test_bucketing_parity_across_seeds_one_ulp(registry):
    """Semantic exactness across data draws: scores bitwise, params
    within one float32 ulp (reductions over different batch shapes may
    associate differently inside XLA — the irreducible floor)."""
    for dseed in range(4):
        ds = _data(2 * 16 + 7, dseed=dseed)
        a, b = _mlp(seed=11), _mlp(seed=11)
        ca, cb = CollectScoresIterationListener(), CollectScoresIterationListener()
        a.set_listeners(ca)
        b.set_listeners(cb)
        a.fit(ListDataSetIterator(ds, 16), feed_pipeline=False)
        b.fit(ListDataSetIterator(ds, 16), feed_pipeline=True)
        assert ca.scores == cb.scores, f"scores diverged for dseed={dseed}"
        np.testing.assert_allclose(a.params_flat(), b.params_flat(),
                                   rtol=0, atol=6e-8)


def test_bucketing_skipped_for_batch_statistics_layers(registry):
    """BatchNormalization batch moments would be polluted by padded
    rows — the container must fall back to the legacy ragged tail."""
    ds = _data(16 + 5)
    net = _mlp(bn=True)
    assert not net._pad_tail_safe()
    net.fit(ListDataSetIterator(ds, 16), feed_pipeline=True)
    assert registry.family_total(monitor.FEED_PADDED_BATCHES_COUNTER) == 0
    assert np.isfinite(net.score())


# ----------------------------------------------------- deferred score sync

def test_zero_per_iteration_syncs_after_warmup(registry):
    """The acceptance bar: fit() on an unmasked in-memory iterator does
    ZERO per-iteration host syncs after warmup — one batched score
    resolution per fit call (end-of-fit flush), and at most one compile
    across ragged tail batches."""
    ds = _data(3 * 16 + 5)
    net = _mlp()
    net.fit(ListDataSetIterator(ds, 16))  # warmup: compiles both programs
    warm_misses = registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
    assert warm_misses == 2  # full-batch program + ONE canonical tail program
    base = registry.family_total(monitor.SCORE_SYNC_COUNTER)
    epochs = 3
    for _ in range(epochs):
        net.fit(ListDataSetIterator(ds, 16))
    syncs = registry.family_total(monitor.SCORE_SYNC_COUNTER) - base
    iterations = epochs * 4
    assert syncs == epochs, f"{syncs} syncs for {iterations} iterations"
    # no further compiles: the padded tail reuses the canonical program
    assert registry.family_total(monitor.JIT_CACHE_MISS_COUNTER) == warm_misses


def test_pipeline_off_keeps_per_iteration_sync_and_extra_compiles(registry):
    ds = _data(3 * 16 + 5)
    net = _mlp()
    net.fit(ListDataSetIterator(ds, 16), feed_pipeline=False)
    assert registry.family_total(monitor.JIT_CACHE_MISS_COUNTER) == 2
    assert registry.family_total(monitor.SCORE_SYNC_COUNTER) == 4  # one per step


def test_deferred_scores_reach_listeners_exactly(registry):
    """Listeners get every (iteration, score) pair in order, with
    exact values, whether resolution is immediate or deferred."""
    ds = _data(64)
    a, b = _mlp(), _mlp()
    ca = CollectScoresIterationListener(frequency=4)  # tolerates deferral
    cb = CollectScoresIterationListener(frequency=4)
    a.set_listeners(ca)
    b.set_listeners(cb)
    a.fit(ListDataSetIterator(ds, 16), feed_pipeline=True)
    b.fit(ListDataSetIterator(ds, 16), feed_pipeline=False)
    assert ca.scores == cb.scores
    assert [i for i, _ in ca.scores] == [4]  # frequency honored


def test_frequency_one_listener_forces_immediate_resolution(registry):
    """A listener with no declared frequency demands per-iteration
    resolution — legacy semantics preserved for plain callables."""
    ds = _data(48)
    net = _mlp()
    seen = []
    net.set_listeners(lambda m, i, s: seen.append((i, float(s))))
    net.fit(ListDataSetIterator(ds, 16), feed_pipeline=True)
    assert len(seen) == 3
    assert registry.family_total(monitor.SCORE_SYNC_COUNTER) == 3
    assert all(isinstance(s, float) and np.isfinite(s) for _, s in seen)


def test_score_resolves_on_demand(registry):
    ds = _data(32)
    net = _mlp()
    net.fit(ListDataSetIterator(ds, 16))
    s = net.score()
    assert isinstance(s, float) and np.isfinite(s)


def test_host_step_mirror_survives_and_invalidates(registry):
    from deeplearning4j_tpu.optimize.deferred import HOST_STEP_MIRROR, host_step
    ds = _data(32)
    net = _mlp()
    net.fit(ListDataSetIterator(ds, 16))
    assert net.__dict__[HOST_STEP_MIRROR] == 2
    assert host_step(net) == int(net.opt_state["step"]) == 2
    # an external opt_state write (checkpoint restore) invalidates it
    net.opt_state = net.opt_state
    assert HOST_STEP_MIRROR not in net.__dict__
    assert host_step(net) == 2  # lazily re-resolved


def test_deferred_flush_race_single_resolution(registry):
    """Two threads racing flush() on the same sink resolve each pending
    score exactly once (the ring is swapped out before fetching)."""
    from deeplearning4j_tpu.optimize.deferred import DeferredScoreSync
    import jax.numpy as jnp

    class Model:
        listeners = []
        _score = float("nan")

    for trial in range(20):
        m = Model()
        calls = []
        m.listeners = [CollectScoresIterationListener(frequency=1000)]
        sink = DeferredScoreSync(m, capacity=1000)
        for i in range(8):
            sink.push(i + 1, jnp.float32(i))
        m.listeners[0].scores = calls  # capture replays
        ts = [threading.Thread(target=sink.flush) for _ in range(2)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert len(sink) == 0
        assert m._score == 7.0


# --------------------------------------------------------- graph container

def test_graph_fit_pipeline_single_compile_and_parity(registry):
    from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    def make():
        conf = (ComputationGraphConfiguration.builder(
                    NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
                    .updater("sgd").activation("tanh").build())
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_in=4, n_out=8), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                              loss_function="mcxent"), "h")
                .set_outputs("out")
                .build())
        return ComputationGraph(conf).init()

    ds = _data(2 * 16 + 5, dseed=3)
    a, b = make(), make()
    a.fit(ListDataSetIterator(ds, 16), feed_pipeline=False)
    base = registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
    b.fit(ListDataSetIterator(ds, 16), feed_pipeline=True)
    misses = registry.family_total(monitor.JIT_CACHE_MISS_COUNTER) - base
    assert misses == 2  # full-batch signature + ONE canonical tail signature
    np.testing.assert_allclose(a.params_flat(), b.params_flat(),
                               rtol=0, atol=6e-8)
    assert np.isfinite(b.score())


# -------------------------------------------------------- parallel wrapper

def test_parallel_allreduce_pipeline_matches_legacy(registry):
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    ds = _data(64, dseed=2)
    a, b = _mlp(), _mlp()
    ParallelWrapper(a, feed_pipeline=False).fit(ListDataSetIterator(ds, 32))
    ParallelWrapper(b, feed_pipeline=True).fit(ListDataSetIterator(ds, 32))
    np.testing.assert_array_equal(a.params_flat(), b.params_flat())
    assert registry.family_total(monitor.H2D_BYTES_COUNTER) > 0


def test_feed_metrics_in_pinned_schema_registry(registry):
    """The feed-pipeline families are known to the telemetry schema
    checker, and a real pipeline run's exposition passes both the
    format and the name-drift validation."""
    import importlib.util
    import os as _os
    script = _os.path.join(_os.path.dirname(__file__), _os.pardir,
                           "scripts", "check_telemetry_schema.py")
    spec = importlib.util.spec_from_file_location("check_telemetry_schema2",
                                                  script)
    schema = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(schema)
    for name in (monitor.H2D_BYTES_COUNTER, monitor.FEED_QUEUE_DEPTH_GAUGE,
                 monitor.FEED_PADDED_BATCHES_COUNTER,
                 monitor.JIT_CACHE_MISS_COUNTER, monitor.SCORE_SYNC_COUNTER):
        assert name in schema.KNOWN_DL4J_METRICS, name
    net = _mlp()
    net.fit(ListDataSetIterator(_data(2 * 16 + 5), 16))
    text = registry.prometheus_text()
    assert "dl4j_score_sync_total" in text
    assert "dl4j_jit_cache_miss_total" in text
    assert "dl4j_feed_padded_batches_total" in text
    assert schema.validate_prometheus_text(text) == []
    assert schema.validate_known_metrics(text) == []
    # drift is flagged
    bad = "# TYPE dl4j_totally_new_thing counter\ndl4j_totally_new_thing 1\n"
    assert schema.validate_known_metrics(bad) != []


def test_parallel_allreduce_pipeline_pads_ragged_for_sharding(registry):
    """A tail batch not divisible by the data axis previously raised in
    shard_batch; bucketing pads it to the canonical (divisible) batch."""
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    ds = _data(32 + 5, dseed=4)
    net = _mlp()
    pw = ParallelWrapper(net, feed_pipeline=True)
    pw.fit(ListDataSetIterator(ds, 32))
    assert registry.family_total(monitor.FEED_PADDED_BATCHES_COUNTER) == 1
    assert np.isfinite(net.score())
