"""Sharded checkpoint tests: save under one placement, restore under
another — the checkpoint is topology-free.

Parity: SURVEY §5 checkpoint/resume TPU equivalent (tensorstore-style
sharded format); oracle is the in-memory model.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.sharded_checkpoint import (
    restore_checkpoint, save_checkpoint)


def _net_and_data(rng):
    conf = (NeuralNetConfiguration.builder().seed(21).learning_rate(0.05)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=8, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((32, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
    return net, DataSet(x, y)


def test_roundtrip_without_model(rng, tmp_path):
    net, ds = _net_and_data(rng)
    for _ in range(3):
        net.fit(ds)
    save_checkpoint(net, str(tmp_path / "ckpt"))
    restored = restore_checkpoint(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(restored.output(ds.features),
                               net.output(ds.features), rtol=1e-6)
    # optimizer state continues training identically
    net.fit(ds)
    restored.fit(ds)
    np.testing.assert_allclose(restored.output(ds.features),
                               net.output(ds.features), rtol=1e-6)


def test_sharded_save_restore_replicated(rng, tmp_path):
    """Save while FSDP-sharded over 8 devices; restore into a fresh
    single-placement model — placements are not part of the format."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.zero import apply_fsdp

    net, ds = _net_and_data(rng)
    net.fit(ds)
    mesh = make_mesh({"data": 8}, devices=devs[:8])
    apply_fsdp(net, mesh)
    out_before = np.asarray(net.output(ds.features))
    save_checkpoint(net, str(tmp_path / "sharded"))

    restored = restore_checkpoint(str(tmp_path / "sharded"))
    np.testing.assert_allclose(np.asarray(restored.output(ds.features)),
                               out_before, rtol=1e-5)


def test_restore_into_sharded_model(rng, tmp_path):
    """Save replicated; restore into an FSDP-sharded model — arrays
    land under the live model's placements."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.zero import apply_fsdp

    net, ds = _net_and_data(rng)
    net.fit(ds)
    save_checkpoint(net, str(tmp_path / "repl"))
    out_before = np.asarray(net.output(ds.features))

    target, _ = _net_and_data(rng)
    mesh = make_mesh({"data": 8}, devices=devs[:8])
    apply_fsdp(target, mesh)
    restored = restore_checkpoint(str(tmp_path / "repl"), model=target)
    # placements preserved (sharded), values identical
    assert not restored.params["layer0"]["W"].sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(restored.output(ds.features)),
                               out_before, rtol=1e-5)


def test_early_stopping_with_sharded_saver(rng, tmp_path):
    """Early stopping snapshots best/latest models in the sharded
    format; get_best_model restores a working model from disk."""
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.earlystopping import (
        DataSetLossCalculator, EarlyStoppingConfiguration,
        EarlyStoppingTrainer, MaxEpochsTerminationCondition,
        ShardedCheckpointSaver)

    net, ds = _net_and_data(rng)
    saver = ShardedCheckpointSaver(str(tmp_path / "es"))
    conf = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
        score_calculator=DataSetLossCalculator(ListDataSetIterator(ds, 32)),
        model_saver=saver, save_last_model=True)
    result = EarlyStoppingTrainer(conf, net, ListDataSetIterator(ds, 16)).fit()
    assert result.total_epochs == 3
    best = saver.get_best_model()
    assert best is not None
    np.testing.assert_allclose(best.score(ds), result.best_model_score,
                               rtol=1e-5)
    assert saver.get_latest_model() is not None
