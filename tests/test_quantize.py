"""Quantized serving tests (nn/quantize.py + the nn/kvpool.py
quantized paged KV pool + the registry quality gate — ISSUE 14).

The numeric contract under test: the quantized lane is EXACT versus
itself — greedy tokens bitwise-reproducible across runs, fused ==
eager, invariant to coalescing/preemption/cotenants, the house
determinism bar — while being only bounded-delta versus fp32 (the
accuracy gate's thresholds are the bound). Plus the plumbing
invariants: per-output-channel weight quantization round-trips within
its grid, a quantized pool never shares a spec with an fp32 one, its
block bytes land in the 2-4x compression band, shared/COW quantized
blocks carry their scales through clone/preempt/retire with zero
leaks, the registry charges a quantized version its ACTUAL pinned
bytes, a quality-gated deploy rejects a bad candidate while the
stable keeps serving, zero steady-state compiles on warmed quantized
ladders, and the dl4j_quant_* schema is pinned.
"""

import sys

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.generate import generate, generate_eager
from deeplearning4j_tpu.nn.kvpool import PagedKVCachePool, pool_spec
from deeplearning4j_tpu.nn.quantize import (QSCALE, accuracy_gate,
                                            dequantize_array, kv_dequantize,
                                            kv_quantize, make_quality_gate,
                                            quantize, quantize_array,
                                            quantized_param_bytes)
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving.continuous import ContinuousDecodeScheduler
from deeplearning4j_tpu.serving.registry import (ModelRegistry,
                                                 QualityGateFailed)

VOCAB = 11


def _tiny_gpt(seed=0, **kw):
    return gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=seed, **kw).init()


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


def _sched(net, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("burst_tokens", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("start", False)
    kw.setdefault("kv_quant", "int8")
    return ContinuousDecodeScheduler(net=net, **kw)


def _drive(sched, futures, max_steps=300):
    for _ in range(max_steps):
        if all(f.done() for f in futures):
            return
        sched.step()
    raise AssertionError(
        f"schedule did not converge in {max_steps} steps; "
        f"events={list(sched.events)}")


# ---------------------------------------------------- weight quantization

def test_quantize_array_roundtrip(rng):
    w = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    q, sc = quantize_array(w, "int8")
    assert q.dtype == jnp.int8 and sc.dtype == jnp.float32
    assert sc.shape == (8,)
    # per-channel int8: error bounded by half a quantization step
    err = np.abs(np.asarray(dequantize_array(q, sc)) - np.asarray(w))
    assert np.all(err <= np.asarray(sc)[None, :] * 0.5 + 1e-7)
    q8, sc8 = quantize_array(w, "fp8")
    assert q8.dtype == jnp.float8_e4m3fn
    with pytest.raises(ValueError):
        quantize_array(w, "int4")


def test_quantize_net_layout_and_footprint(rng):
    net = _tiny_gpt()
    q = quantize(net, "int8")
    # same layer/param names + _qscale companions; storage is int8
    blk = q.params["layer1"]
    for name in ("Wqkv", "Wo", "W1", "W2"):
        assert blk[name].dtype == jnp.int8
        assert blk[name + QSCALE].dtype == jnp.float32
    assert q.params["layer0"]["W"].dtype == jnp.int8       # embedding
    assert q.params["layer0"]["P"].dtype == jnp.float32    # positions stay
    assert q.params["layer3"]["W"].dtype == jnp.int8       # output head
    # the byte win the registry budget sees (scales cost a little back)
    ratio = quantized_param_bytes(net.params) / quantized_param_bytes(
        q.params)
    assert ratio > 2.0
    assert q.quantized == "int8"
    # the original net is untouched and a quantized net cannot re-quantize
    assert net.params["layer1"]["Wqkv"].dtype == jnp.float32
    with pytest.raises(ValueError):
        quantize(q, "int8")
    # serving-only: fit refuses quantized weights loudly
    with pytest.raises(ValueError, match="quantized"):
        q.fit(np.zeros((2, 4), np.float32), np.zeros((2, 4, VOCAB),
                                                     np.float32))


def test_quantized_classify_and_generate_self_exact(rng):
    """The house bar inside the quantized contract: bitwise-identical
    outputs across runs, fused decode == eager decode, bounded delta
    vs fp32."""
    net = _tiny_gpt()
    q = quantize(net, "int8")
    x = rng.integers(0, VOCAB, (3, 9)).astype(np.float32)
    o1 = np.asarray(q.output(x))
    o2 = np.asarray(q.output(x))
    np.testing.assert_array_equal(o1, o2)
    # bounded vs fp32 (classify probabilities)
    of = np.asarray(net.output(x))
    assert float(np.max(np.abs(o1 - of))) < 0.05
    prompt = rng.integers(1, VOCAB, (2, 6))
    a = generate(q, prompt, 10, seed=3)
    b = generate(q, prompt, 10, seed=3)
    e = generate_eager(q, prompt, 10, seed=3)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, e)
    # sampled draws too (per-row PRNG schedule is quantization-blind)
    s1 = generate(q, prompt, 10, temperature=1.3, top_k=5, seed=9)
    s2 = generate_eager(q, prompt, 10, temperature=1.3, top_k=5, seed=9)
    np.testing.assert_array_equal(s1, s2)


def test_fp8_generate_self_exact(rng):
    q = quantize(_tiny_gpt(), "fp8")
    prompt = rng.integers(1, VOCAB, (1, 5))
    a = generate(q, prompt, 8, seed=1)
    b = generate_eager(q, prompt, 8, seed=1)
    np.testing.assert_array_equal(a, b)


# -------------------------------------------------------- quantized pool

def test_quantized_pool_spec_layout_and_bytes():
    pool = PagedKVCachePool(9, 4, num_layers=2, num_heads=2, head_dim=8,
                            quant="int8", name="q")
    ref = PagedKVCachePool(9, 4, num_layers=2, num_heads=2, head_dim=8,
                           name="f")
    # a quantized pool NEVER shares a spec with an fp32 one
    assert pool.spec != ref.spec
    assert pool.spec == pool_spec(2, 2, 8, 4, jnp.float32, "int8")
    entry = pool.layers[0]
    assert entry["k"].dtype == jnp.int8
    assert entry["k_scale"].shape == (9, 4, 2)
    assert entry["k_scale"].dtype == jnp.float32
    # the 2-4x compression band (hd=8: 4*8/(8+4) = 2.67x)
    ratio = ref.block_bytes() / pool.block_bytes()
    assert 2.0 <= ratio <= 4.0
    assert pool.stats()["quant"] == "int8"
    # byte-budget sizing: same budget, ~ratio x the blocks
    bb_f = PagedKVCachePool.bytes_per_block(2, 4, 2, 8)
    bb_q = PagedKVCachePool.bytes_per_block(2, 4, 2, 8, quant="int8")
    assert bb_f == ref.block_bytes() and bb_q == pool.block_bytes()


def test_kv_quantize_dequantize_bounds(rng):
    x = jnp.asarray(rng.standard_normal((3, 5, 2, 8)) * 4.0, jnp.float32)
    q, sc = kv_quantize(x, jnp.int8)
    assert q.shape == x.shape and sc.shape == (3, 5, 2)
    back = np.asarray(kv_dequantize(q, sc, jnp.float32))
    err = np.abs(back - np.asarray(x))
    assert np.all(err <= np.asarray(sc)[..., None] * 0.5 + 1e-7)
    # zeros stay exactly zero (the unwritten-position property)
    qz, scz = kv_quantize(jnp.zeros((2, 2, 4)), jnp.int8)
    assert np.all(np.asarray(kv_dequantize(qz, scz, jnp.float32)) == 0.0)


def test_paged_quantized_decode_step_close_and_deterministic(rng):
    """The quantized paged branch reproduces the dense fp32 step within
    quantization error, and bit-identically across replays."""
    net = _tiny_gpt()
    blk = net.impls[1]
    params = net.params[blk.name]
    b, d, bs, mb, nb_pool = 2, 16, 4, 3, 8
    dense = blk.init_cache(b, mb * bs)
    mk = lambda: {
        "k": jnp.zeros((nb_pool, bs, 2, 8), jnp.int8),
        "v": jnp.zeros((nb_pool, bs, 2, 8), jnp.int8),
        "k_scale": jnp.zeros((nb_pool, bs, 2)),
        "v_scale": jnp.zeros((nb_pool, bs, 2))}
    qp, qp2 = mk(), mk()
    table = jnp.asarray([[3, 1, 5], [2, 6, 4]], jnp.int32)
    pos = np.zeros(b, np.int32)
    xs = [jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
          for _ in range(6)]
    for step, x in enumerate(xs):
        pv = jnp.asarray(pos)
        y_dense, dense = blk.decode_step(params, x, dense, pv)
        c1 = dict(qp); c1["table"] = table
        y_q, c1 = blk.decode_step(params, x, c1, pv,
                                  write_mask=jnp.ones(b, bool))
        qp = {n: c1[n] for n in qp}
        c2 = dict(qp2); c2["table"] = table
        y_q2, c2 = blk.decode_step(params, x, c2, pv,
                                   write_mask=jnp.ones(b, bool))
        qp2 = {n: c2[n] for n in qp2}
        np.testing.assert_array_equal(np.asarray(y_q), np.asarray(y_q2))
        np.testing.assert_allclose(np.asarray(y_dense), np.asarray(y_q),
                                   rtol=0.12, atol=0.12)
        pos += 1


# ---------------------------------------- scheduler: the quantized lane

def test_quantized_lane_serves_exact_vs_eager(rng):
    net = _tiny_gpt()
    q = quantize(net, "int8")
    s = _sched(q)
    prompts = [rng.integers(1, VOCAB, (1, t)) for t in (3, 5, 7)]
    futs = [s.submit(p, 10, seed=i) for i, p in enumerate(prompts)]
    _drive(s, futs)
    for i, (p, f) in enumerate(zip(prompts, futs)):
        np.testing.assert_array_equal(
            f.result(0), generate_eager(q, p, 10, seed=i))
    st = s.stats()
    assert st["kv_quant"] == "int8"
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]


def test_quantized_pool_preempt_resume_invariant(rng):
    """Preemption on a quantized pool: the per-token scale granularity
    makes a resume's re-prefill store bit-identical blocks, so the
    preempted run's tokens equal the unpreempted run's exactly."""
    net = _tiny_gpt()
    q = quantize(net, "int8")
    prompts = [rng.integers(1, VOCAB, (1, t)) for t in (3, 5, 7)]
    big = _sched(q)
    fb = [big.submit(p, 10, temperature=1.1, seed=i)
          for i, p in enumerate(prompts)]
    _drive(big, fb)
    tiny = _sched(q, num_blocks=9)
    ft = [tiny.submit(p, 10, temperature=1.1, seed=i)
          for i, p in enumerate(prompts)]
    _drive(tiny, ft)
    assert tiny.stats()["preemptions"] >= 1
    for a, b in zip(fb, ft):
        np.testing.assert_array_equal(a.result(0), b.result(0))
    st = tiny.stats()["pool"]
    assert st["blocks_free"] == st["blocks_total"]


def test_quantized_prefix_cache_share_and_cow_bitwise(rng):
    """Shared + COW'd quantized blocks carry their scales: cached
    admissions (full-block shares AND a partial-tail COW) produce
    bitwise the tokens an uncached quantized run produces, and the
    pool drains with zero leaks."""
    net = _tiny_gpt()
    q = quantize(net, "int8")
    cached = _sched(q, prefix_cache=True)
    # shared-preamble fan-out: full-block shares
    pre = rng.integers(1, VOCAB, (1, 10))
    tails = [rng.integers(1, VOCAB, (1, 3)) for _ in range(3)]
    full = [np.concatenate([pre, t], axis=1) for t in tails]
    fc = []
    for i, p in enumerate(full):
        fc.append(cached.submit(p, 8, seed=50 + i))
        _drive(cached, fc)
    assert cached.stats()["prefix_cache"]["hits"] >= 1
    for a, p, i in zip(fc, full, range(len(full))):
        np.testing.assert_array_equal(
            a.result(0), generate_eager(q, p, 8, seed=50 + i))
    # COW: B = A's prompt + its first generated token — the match
    # reaches INTO A's cached partial tail block, whose int8 values AND
    # scale rows must clone together for B to decode bitwise
    pA = rng.integers(1, VOCAB, (1, 10))
    wantA = generate_eager(q, pA, 2)
    fA = cached.submit(pA, 2)
    _drive(cached, [fA])
    np.testing.assert_array_equal(fA.result(0), wantA)
    pB = np.concatenate([pA, wantA[:, 10:11]], axis=1)
    wantB = generate_eager(q, pB, 6)
    fB = cached.submit(pB, 6)
    _drive(cached, [fB])
    np.testing.assert_array_equal(fB.result(0), wantB)
    st = cached.stats()["prefix_cache"]
    assert st["cow_copies"] >= 1
    # the originator's cached content survived the COW untouched
    fA2 = cached.submit(pA, 2)
    _drive(cached, [fA2])
    np.testing.assert_array_equal(fA2.result(0), wantA)
    for c in cached.prefix_caches():
        c.clear()
    ps = cached.stats()["pool"]
    assert ps["blocks_free"] == ps["blocks_total"]
    assert ps["alloc_failures"] == 0


def test_quantized_engine_zero_steady_state_compiles(rng, fresh_registry):
    net = _tiny_gpt()
    q = quantize(net, "int8")
    eng = ParallelInference(q, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4, kv_quant="int8")
    try:
        eng.warmup_generate([3, 5, 7], 10)
        before = fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        futs = [eng.submit_generate(rng.integers(1, VOCAB, (1, t)), 10,
                                    temperature=tmp, seed=i)
                for i, (t, tmp) in enumerate(
                    [(3, 0.0), (5, 1.2), (7, 0.0), (4, 0.8)])]
        for f in futs:
            f.result(30)
        after = fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        assert after == before, f"{after - before} steady-state compiles"
        assert eng.stats()["scheduler"]["kv_quant"] == "int8"
    finally:
        eng.shutdown()


def test_engine_kv_quant_needs_continuous():
    net = _tiny_gpt()
    with pytest.raises(ValueError, match="continuous"):
        ParallelInference(net, kv_quant="int8", start=False)
    with pytest.raises(ValueError, match="exclusive"):
        ContinuousDecodeScheduler(net=net, start=False, num_blocks=9,
                                  kv_bytes_budget=1 << 20)
    with pytest.raises(ValueError, match="kv_quant"):
        ContinuousDecodeScheduler(net=net, start=False, kv_quant="int4")


def test_kv_bytes_budget_buys_more_quantized_blocks(rng):
    net = _tiny_gpt()
    q = quantize(net, "int8")
    budget = 24 * PagedKVCachePool.bytes_per_block(2, 4, 2, 8)
    s_f = ContinuousDecodeScheduler(net=net, slots=4, burst_tokens=4,
                                    block_size=4, start=False,
                                    kv_bytes_budget=budget)
    s_q = ContinuousDecodeScheduler(net=q, slots=4, burst_tokens=4,
                                    block_size=4, start=False,
                                    kv_quant="int8",
                                    kv_bytes_budget=budget)
    f = s_f.submit(rng.integers(1, VOCAB, (1, 4)), 2)
    _drive(s_f, [f])
    g = s_q.submit(rng.integers(1, VOCAB, (1, 4)), 2)
    _drive(s_q, [g])
    bf = s_f.stats()["pool"]["blocks_total"]
    bq = s_q.stats()["pool"]["blocks_total"]
    assert bq >= 2 * bf, (bf, bq)


# ------------------------------------------- registry: gate + pinned bytes

def test_accuracy_gate_passes_self_and_fails_garbage(fresh_registry):
    net = _tiny_gpt()
    g = accuracy_gate(net, net, rows=4, length=12)
    assert g["passed"] and g["greedy_match_rate"] == 1.0
    assert g["logit_mse"] == 0.0
    other = _tiny_gpt(seed=123)  # a different model is NOT within bounds
    g2 = accuracy_gate(net, other, rows=4, length=12)
    assert not g2["passed"]
    text = fresh_registry.prometheus_text()
    assert "dl4j_quant_accuracy_gate_outcome_total" in text


def test_registry_quality_gate_and_actual_pinned_bytes(rng,
                                                      fresh_registry):
    import jax

    net = _tiny_gpt()
    q = quantize(net, "int8")
    registry = ModelRegistry()
    registry.register("m", net=net)
    # a bad candidate (different weights entirely) is rejected BEFORE
    # any traffic shifts; the stable version keeps serving
    bad = _tiny_gpt(seed=99)
    with pytest.raises(QualityGateFailed) as ei:
        registry.deploy("m", net=bad, warm=False,
                        quality_gate=make_quality_gate(rows=4, length=12))
    assert ei.value.verdict is not None
    assert registry.active_version("m") == 1
    assert registry.versions("m") == {1: "active"}
    # the quantized candidate passes its gate (loose thresholds — the
    # tiny random-init net's flat logits are not the gate's regime;
    # bench gates the trained net at the tight production thresholds)
    v2 = registry.deploy("m", net=q, warm=False,
                         quality_gate=make_quality_gate(
                             rows=4, length=12, min_greedy_match=0.5,
                             max_eval_delta=0.05))
    assert registry.active_version("m") == v2
    # pinned-bytes satellite: the pin charges the ACTUAL pytree bytes —
    # the quantized version pins ~4x fewer weight bytes than fp32
    dev = jax.devices()[0]
    registry.acquire("m", 1, dev)
    fp32_pinned = registry.pinned_bytes()
    registry.acquire("m", v2, dev)
    q_pinned = registry.pinned_bytes() - fp32_pinned
    assert 0 < q_pinned < fp32_pinned / 2, (q_pinned, fp32_pinned)
    # unpin releases exactly what was charged
    registry._unpin_all(registry.version("m", 1))
    registry._unpin_all(registry.version("m", v2))
    assert registry.pinned_bytes() == 0
    # a quantized CANARY rides the same gate + the PR-7 watch plane
    q2 = quantize(net, "fp8")
    v3 = registry.deploy("m", net=q2, warm=False, canary_fraction=0.5,
                         quality_gate=make_quality_gate(
                             rows=4, length=12, min_greedy_match=0.5,
                             max_eval_delta=0.05))
    assert registry.versions("m")[v3] == "canary"
    assert registry.active_version("m") == v2  # stable still active
    registry.rollback("m", reason="manual")    # reject the canary
    assert registry.versions("m")[v3] == "rejected"
    assert registry.active_version("m") == v2
    # deploy outcomes + rollback reason counted
    text = fresh_registry.prometheus_text()
    assert 'outcome="rejected_quality"' in text
    assert 'reason="quality_gate"' in text


# ------------------------------------------------------- schema pinning

def test_quant_metric_schema_pinned(rng, fresh_registry):
    sys.path.insert(0, "scripts")
    try:
        from check_telemetry_schema import (KNOWN_DL4J_METRICS,
                                            validate_known_metrics,
                                            validate_prometheus_text)
    finally:
        sys.path.pop(0)
    for name in ("dl4j_quant_models", "dl4j_quant_kv_blocks",
                 "dl4j_quant_scale_absmax",
                 "dl4j_quant_accuracy_gate_outcome_total"):
        assert name in KNOWN_DL4J_METRICS, name
    net = _tiny_gpt()
    q = quantize(net, "int8")
    accuracy_gate(net, q, rows=2, length=8)
    s = _sched(q)
    f = s.submit(rng.integers(1, VOCAB, (1, 4)), 4)
    _drive(s, [f])
    text = fresh_registry.prometheus_text()
    assert validate_prometheus_text(text) == []
    assert validate_known_metrics(text) == []
    for family in ("dl4j_quant_models", "dl4j_quant_kv_blocks",
                   "dl4j_quant_scale_absmax",
                   "dl4j_quant_accuracy_gate_outcome_total"):
        assert family in text, family


def test_quick_check_section_10_runs():
    """The stress battery's quantized-pool section exists and the whole
    battery stays deterministic (tier-1 runs quick_check elsewhere too;
    this pins that section 10's events are part of the replayed log)."""
    sys.path.insert(0, "scripts")
    try:
        from stress_faultinject import _scenario_log, quick_check
    finally:
        sys.path.pop(0)
    log = _scenario_log(0)
    assert "qkv spec_differs=True" in log
    assert "qkv double-free caught" in log
    assert "leaked=0" in log
    assert quick_check(seeds=(0,), runs_per_seed=2) == []
