"""Thread-safety of the lazy observer sync (ADVICE r3)."""
import threading
import time

from deeplearning4j_tpu.nn.observed import SyncedStateAttr, clear_pending_sync


class Box:
    params = SyncedStateAttr("params")


def test_two_readers_run_thunk_exactly_once():
    b = Box()
    b.params = "stale"
    runs = []

    def thunk():
        time.sleep(0.05)  # widen the race window
        runs.append(1)
        b.params = "fresh"

    b._observer_sync = thunk
    out = [None, None]
    ts = [threading.Thread(target=lambda i=i: out.__setitem__(i, b.params))
          for i in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(runs) == 1
    # the reader that ran the thunk saw fresh; the other may have read
    # before the thunk was installed-complete or after — but never a
    # torn state, and a THIRD read is definitely fresh
    assert b.params == "fresh"


def test_clear_blocks_until_reader_thunk_finishes():
    b = Box()
    b.params = "stale"
    started = threading.Event()
    order = []

    def thunk():
        started.set()
        time.sleep(0.05)
        order.append("thunk-done")
        b.params = "fresh"

    b._observer_sync = thunk
    reader = threading.Thread(target=lambda: b.params)
    reader.start()
    started.wait()
    clear_pending_sync(b)  # must wait for the in-flight thunk
    order.append("clear-returned")
    reader.join()
    assert order == ["thunk-done", "clear-returned"]
