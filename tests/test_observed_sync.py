"""Thread-safety of the lazy observer sync (ADVICE r3)."""
import threading
import time

from deeplearning4j_tpu.nn.observed import SyncedStateAttr, clear_pending_sync


class Box:
    params = SyncedStateAttr("params")


def test_two_readers_run_thunk_exactly_once():
    b = Box()
    b.params = "stale"
    runs = []

    def thunk():
        time.sleep(0.05)  # widen the race window
        runs.append(1)
        b.params = "fresh"

    b._observer_sync = thunk
    out = [None, None]
    ts = [threading.Thread(target=lambda i=i: out.__setitem__(i, b.params))
          for i in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(runs) == 1
    # the reader that ran the thunk saw fresh; the other may have read
    # before the thunk was installed-complete or after — but never a
    # torn state, and a THIRD read is definitely fresh
    assert b.params == "fresh"


def test_clear_blocks_until_reader_thunk_finishes():
    b = Box()
    b.params = "stale"
    started = threading.Event()
    order = []

    def thunk():
        started.set()
        time.sleep(0.05)
        order.append("thunk-done")
        b.params = "fresh"

    b._observer_sync = thunk
    reader = threading.Thread(target=lambda: b.params)
    reader.start()
    started.wait()
    clear_pending_sync(b)  # must wait for the in-flight thunk
    order.append("clear-returned")
    reader.join()
    assert order == ["thunk-done", "clear-returned"]


def test_readers_racing_clear_run_thunk_at_most_once_untorn():
    """Readers racing ``clear_pending_sync`` (the ABBA/donation seam
    documented at nn/observed.py:17-33): over many trials the thunk runs
    at most once per install, the final state is never torn (either the
    thunk fully ran or it never started), and a reader that began the
    thunk always completes it before clear returns — so the training
    thread may donate the buffers the moment clear comes back."""
    for _ in range(50):
        b = Box()
        b.params = "stale"
        runs = []

        def thunk():
            runs.append(1)
            b.params = "fresh"

        b._observer_sync = thunk
        barrier = threading.Barrier(3)

        def read(i):
            barrier.wait()
            out[i] = b.params

        def clear():
            barrier.wait()
            clear_pending_sync(b)

        out = [None, None]
        ts = [threading.Thread(target=read, args=(0,)),
              threading.Thread(target=read, args=(1,)),
              threading.Thread(target=clear)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert len(runs) <= 1, "thunk ran twice"
        # post-clear invariant: the pending sync is gone and the state is
        # exactly one of the two legal values
        assert b.__dict__["_observer_sync"] is None or runs
        assert b.params in ("stale", "fresh")
        if runs:
            # every reader that observed the post-thunk world saw it whole
            assert b.params == "fresh"
        else:
            assert b.params == "stale"
        for v in out:
            assert v in ("stale", "fresh")


def test_two_reader_threads_with_pending_sync_run_thunk_exactly_once():
    """The satellite contract verbatim: two threads racing reads of a
    model's params while a pending sync is installed → the thunk runs
    exactly once, even across many trials with varied interleaving."""
    for trial in range(50):
        b = Box()
        b.params = "stale"
        runs = []

        def thunk():
            if trial % 5 == 0:
                time.sleep(0.001)  # widen the window on some trials
            runs.append(1)
            b.params = "fresh"

        b._observer_sync = thunk
        barrier = threading.Barrier(2)

        def read(i):
            barrier.wait()
            out[i] = b.params

        out = [None, None]
        ts = [threading.Thread(target=read, args=(i,)) for i in range(2)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert len(runs) == 1, f"thunk ran {len(runs)}x on trial {trial}"
        assert b.params == "fresh"  # a post-join read is definitely fresh
        # a racing reader may legally observe the pre-thunk value (probe
        # after get-and-clear, before the thunk's write-through) — but
        # never a torn one
        for v in out:
            assert v in ("stale", "fresh")


def test_cross_object_thunk_does_not_deadlock():
    """ADVICE r4: a thunk on one model that reads a synced attr of a
    DIFFERENT model (itself with a pending sync) must not self-deadlock
    on a shared non-reentrant lock — locks are per instance now."""
    a, b = Box(), Box()
    a.params = "a-stale"
    b.params = "b-stale"
    b._observer_sync = lambda: setattr(b, "params", "b-fresh")

    def a_thunk():
        assert b.params == "b-fresh"  # triggers b's sync under b's lock
        a.params = "a-fresh"

    a._observer_sync = a_thunk
    done = []
    t = threading.Thread(target=lambda: done.append(a.params))
    t.start()
    t.join(timeout=5.0)
    assert not t.is_alive(), "cross-object observer sync deadlocked"
    assert done == ["a-fresh"]
