"""Embedding QUALITY gate for the device engine's capped accumulation
(VERDICT r4 weak #3 / next #4).

The device SGNS replaces the reference's sequential per-pair updates
(``SkipGram.java:204``) with batched scatter-adds capped per row
(``engine._ROW_UPDATE_CAP``). Throughput is anchored in bench.py; this
file anchors *embedding quality* on a corpus with planted class
structure AND a 30%-frequency head word that exceeds the cap ~20x per
batch, two ways:

1. cap-on vs cap-off at identical settings — isolates the cap itself.
   Measured here (2026-07-30, CPU mesh, purity@3): cap=64 -> 0.256,
   uncapped -> 0.117 at 2 epochs; at 8 epochs uncapped DIVERGES to
   non-finite tables while cap=64 reaches 0.953. An over-tight cap=8
   starves head rows (0.097/0.206). The shipped cap both prevents
   divergence and trains BETTER than exact-sum batching.
2. device vs the uncapped near-sequential host baseline
   (``sgns_host_train``, batch=64) — the reference-semantics anchor.
   At equal epochs a 4096-batch takes ~64x fewer optimizer steps than
   the batch-64 host, a step-starvation effect of large-batch SGD that
   has nothing to do with capping (device batch=512 at the same epoch
   count moves 0.256 -> only 0.336, while 4x epochs reaches 0.95).
   The user-facing contract is quality per WALL-CLOCK: bench.py
   measures the device engine ~15x the host throughput, so the gate
   grants the device 4x the epochs (still >=3x faster end-to-end) and
   requires it to match-or-beat host quality.
"""

import jax
import numpy as np
import pytest

import deeplearning4j_tpu.models.sequencevectors.engine as eng
from deeplearning4j_tpu.models.sequencevectors.host_baseline import (
    sgns_host_train)
from deeplearning4j_tpu.models.word2vec.word2vec import Word2Vec

N_CLASSES, WORDS_PER_CLASS = 12, 10
HEAD = "the"  # global head word: ~30% of tokens, cap-binding by design
DIM, WINDOW, K, LR = 48, 3, 5, 0.025
HOST_EPOCHS = 2
DEVICE_EPOCHS = 8  # 4x: still >=3x less wall-clock at the 15x bench margin


def _corpus(n_sentences=900, noise=0.35, seed=0):
    """Class-pure sentences with cross-class noise words: purity@3 sits
    well below 1.0, so the gate has headroom to detect degradation in
    either direction."""
    rng = np.random.default_rng(seed)
    classes = [[f"w{c}_{i}" for i in range(WORDS_PER_CLASS)]
               for c in range(N_CLASSES)]
    class_p = (np.arange(1, N_CLASSES + 1) ** -0.8)
    class_p /= class_p.sum()
    sents = []
    for _ in range(n_sentences):
        c = rng.choice(N_CLASSES, p=class_p)
        out = []
        for _ in range(10):
            src = (classes[rng.choice(N_CLASSES, p=class_p)]
                   if rng.random() < noise else classes[c])
            if rng.random() < 0.45:
                out.append(HEAD)
            out.append(str(rng.choice(src)))
        sents.append(out)
    return sents, classes


def _purity_at_k(vectors, vocab_index, classes, k=3):
    """Fraction of top-k cosine neighbors sharing the query's class
    (the head word is not a query and not in the candidate set)."""
    words = [w for cls in classes for w in cls]
    cls_of = {w: c for c, cls in enumerate(classes) for w in cls}
    idx = np.asarray([vocab_index(w) for w in words])
    V = vectors / np.maximum(
        np.linalg.norm(vectors, axis=1, keepdims=True), 1e-12)
    sub = V[idx]                      # [n_words, d], class-ordered
    sims = sub @ sub.T
    np.fill_diagonal(sims, -np.inf)
    hits = total = 0
    for qi, w in enumerate(words):
        top = np.argsort(-sims[qi])[:k]
        for t in top:
            hits += cls_of[words[t]] == cls_of[w]
            total += 1
    return hits / total


def _fit_device(sents, classes, epochs):
    m = Word2Vec(layer_size=DIM, window_size=WINDOW, epochs=epochs,
                 learning_rate=LR, negative_sample=K, batch_size=4096,
                 seed=7, device_pairgen=True)
    m.fit(sents)
    return m, _purity_at_k(m.lookup_table.syn0, m.vocab.index_of, classes)


@pytest.fixture()
def corpus():
    sents, classes = _corpus()
    n_head = sum(w == HEAD for s in sents for w in s)
    n_tok = sum(len(s) for s in sents)
    assert n_head / n_tok > 0.25  # the cap genuinely binds (>>64/batch)
    return sents, classes


def test_cap_does_not_degrade_vs_uncapped(corpus):
    """The cap itself must cost nothing: capped >= uncapped quality at
    identical settings (it measurably HELPS — uncapped head-row updates
    overshoot, and diverge outright at higher epoch counts)."""
    sents, classes = corpus
    assert eng._ROW_UPDATE_CAP == 64.0  # gate guards the shipped value
    m_c, capped = _fit_device(sents, classes, HOST_EPOCHS)
    old = eng._ROW_UPDATE_CAP
    try:
        eng._ROW_UPDATE_CAP = 1e9  # effectively off
        jax.clear_caches()         # constant is baked at trace time
        m_u, uncapped = _fit_device(sents, classes, HOST_EPOCHS)
    finally:
        eng._ROW_UPDATE_CAP = old
        jax.clear_caches()
    # vacuousness guard: if the two trajectories are IDENTICAL the test
    # is comparing capped to itself — either a future caching change
    # defeated the retrace, or a corpus/batch change made the cap never
    # bind (no row exceeds 64 per batch); both mean the gate is dead
    assert not np.allclose(m_c.lookup_table.syn0, m_u.lookup_table.syn0), (
        "cap override had no effect: either the jitted programs did not "
        "retrace after the _ROW_UPDATE_CAP change, or the corpus no "
        "longer makes the cap bind — fix the gate, it guards nothing")
    print(f"purity@3 capped={capped:.3f} uncapped={uncapped:.3f}")
    assert capped >= uncapped - 0.02, (
        f"_ROW_UPDATE_CAP degrades quality: {capped:.3f} vs "
        f"uncapped {uncapped:.3f}")


def test_device_matches_host_quality_per_wallclock(corpus):
    """Reference-semantics anchor: the device engine at 4x the epochs
    (>=3x less wall-clock at the bench's ~15x throughput margin) must
    match-or-beat the near-sequential uncapped host baseline."""
    sents, classes = corpus
    m, dev_purity = _fit_device(sents, classes, DEVICE_EPOCHS)
    assert np.isfinite(m.lookup_table.syn0).all()

    ids = [[m.vocab.index_of(w) for w in s] for s in sents]
    host_w0 = sgns_host_train(ids, m.vocab.num_words(), dim=DIM,
                              window=WINDOW, K=K, lr=LR,
                              epochs=HOST_EPOCHS, seed=7, batch=64)
    host_purity = _purity_at_k(host_w0, m.vocab.index_of, classes)

    chance = (WORDS_PER_CLASS - 1) / (N_CLASSES * WORDS_PER_CLASS - 1)
    print(f"purity@3 device={dev_purity:.3f} host={host_purity:.3f} "
          f"chance={chance:.3f}")
    assert host_purity > 3 * chance, "host baseline failed to learn"
    assert dev_purity > 3 * chance, "device engine failed to learn"
    assert dev_purity >= host_purity, (
        f"device trains measurably worse than reference semantics even "
        f"with the wall-clock margin: purity@3 {dev_purity:.3f} vs "
        f"host {host_purity:.3f}")
