"""Mixed-precision (bf16 compute / f32 params) policy tests.

TPU-first extension (no reference counterpart — ND4J buffers are
singly-typed): ``compute_dtype("bfloat16")`` casts layer compute to
bf16 inside the traced step while parameters, updater state, layer
states, and the loss stay float32 (util/dtypes.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _mlp_conf(cd):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("sgd").activation("relu")
            .compute_dtype(cd)
            .list()
            .layer(DenseLayer(n_in=6, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())


def test_bf16_trains_and_keeps_f32_params(rng):
    x = rng.standard_normal((24, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
    net = MultiLayerNetwork(_mlp_conf("bfloat16")).init()
    ds = DataSet(x, y)
    net.fit(ds)
    s0 = net.score()
    for _ in range(25):
        net.fit(ds)
    assert net.score() < s0
    for leaf in jax.tree.leaves(net.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree.leaves(net.states):
        assert leaf.dtype == jnp.float32


def test_bf16_close_to_f32_single_step(rng):
    # one SGD step in bf16 stays within bf16 tolerance of the f32 step
    x = rng.standard_normal((16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    nets = {cd: MultiLayerNetwork(_mlp_conf(cd)).init() for cd in ("float32", "bfloat16")}
    for net in nets.values():
        net.fit(DataSet(x, y))
    w32 = np.asarray(nets["float32"].params["layer0"]["W"], np.float32)
    w16 = np.asarray(nets["bfloat16"].params["layer0"]["W"], np.float32)
    np.testing.assert_allclose(w16, w32, atol=5e-2, rtol=5e-2)


def test_bf16_lstm_fit_scan(rng):
    # scan-carried states must stay dtype-stable under the cast policy
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.05).updater("adam").activation("tanh")
            .compute_dtype("bfloat16")
            .list()
            .layer(GravesLSTM(n_in=4, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((8, 5, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (8, 5))]
    scores = net.fit_scan(DataSet(x, y), 4, epochs=2)
    assert np.isfinite(np.asarray(scores)).all()
    for leaf in jax.tree.leaves(net.params):
        assert leaf.dtype == jnp.float32
