"""Horizontal serving tier tests (deeplearning4j_tpu/serving/).

The ISSUE-6 battery, all deterministic (explicit fault seams, bounded
spins on observable state, no blind sleeps in assertions):

- routed classify/generate results are bitwise the inline run;
- **kill-an-engine failover**: with 3 endpoints under concurrent load,
  killing one mid-flight loses ZERO requests (every future resolves
  through failover), the router ejects the dead endpoint, and
  reinstates it after recovery (half-open probe);
- hedged retry: a stalled endpoint's request resolves from the hedge,
  the stalled endpoint's late reply is dropped (no duplicate
  delivery), exactly one hedge is counted;
- deadline admission: an unmeetable deadline sheds with
  :class:`RetryAfter` (retry_after_s > 0) BEFORE any future exists —
  nothing strands — and lower priority classes shed earlier;
- session affinity keeps a multi-burst decode stream on one endpoint
  and re-pins when that endpoint dies;
- broker liveness: ``ping()`` / ``last_seen`` / server ``peers()``;
- ``/healthz`` liveness-vs-readiness split + fleet aggregation;
- ScalePolicy add/remove decisions with hysteresis, applied by
  LocalFleet;
- dl4j_router_* Prometheus schema pinning.
"""

import json
import time

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import NetworkPartition, kill_endpoint
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving import (EngineWorker, InferenceRouter,
                                        LocalEndpoint, LocalFleet,
                                        RemoteEndpoint, RetryAfter,
                                        ScaleDecision, ScalePolicy)
from deeplearning4j_tpu.streaming.broker import (InMemoryBroker, TcpBroker,
                                                 TcpBrokerServer)

pytestmark = pytest.mark.faultinject

N_IN, N_OUT = 6, 3


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _spin_until(cond, timeout=60.0, tick=0.005):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() >= deadline:
            return False
        time.sleep(tick)
    return True


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


@pytest.fixture
def net():
    return _net()


def _mk_fleet(net, router=None, n=3, **kw):
    def engine_factory():
        return ParallelInference(net, max_batch_size=8, max_latency_ms=1.0,
                                 replicas=1)
    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=kw.pop("request_timeout_s", 2.0),
                       heartbeat_timeout_s=0.5, **kw)
    for _ in range(n):
        fleet.add_endpoint()
    assert fleet.wait_ready(10)
    return fleet


# ------------------------------------------------------- broker liveness

def test_broker_ping_and_last_seen():
    srv = TcpBrokerServer().start()
    try:
        host, port = srv.address
        c = TcpBroker(host, port, max_retries=0)
        assert c.last_seen is None
        rtt = c.ping()
        assert rtt >= 0.0 and c.last_seen is not None
        t0 = c.last_seen
        c.publish("t", b"x")
        assert c.last_seen >= t0
        # the server tracked the peer's activity
        peers = srv.peers()
        assert len(peers) == 1
        c.close()
    finally:
        srv.stop()


def test_broker_ping_dead_transport_raises():
    from deeplearning4j_tpu.streaming.broker import BrokerUnavailable
    srv = TcpBrokerServer().start()
    host, port = srv.address
    c = TcpBroker(host, port, max_retries=0, backoff_base_s=1e-3)
    assert c.ping() >= 0.0
    srv.stop()
    # sever the established connection the way a broker-host death
    # would (the threading server keeps accepted sockets alive past
    # stop(), so drop the client side deterministically)
    c._drop()
    with pytest.raises(BrokerUnavailable):
        c.ping()
    c.close()
    # and a fresh client against the dead address raises at connect
    with pytest.raises(BrokerUnavailable):
        TcpBroker(host, port, max_retries=0, backoff_base_s=1e-3)


def test_inmemory_broker_ping():
    b = InMemoryBroker()
    assert b.last_seen is None
    assert b.ping() >= 0.0
    assert b.last_seen is not None


# ------------------------------------------------------- routed identity

def test_routed_classify_bitwise_and_remote_generate(net, rng,
                                                     fresh_registry):
    router = InferenceRouter(per_try_timeout_s=5.0)
    fleet = _mk_fleet(net, router)
    try:
        x = rng.standard_normal((3, N_IN)).astype(np.float32)
        inline = np.asarray(net.output(x))
        routed = router.output(x, timeout=30)
        np.testing.assert_array_equal(routed, inline)
    finally:
        fleet.shutdown()


def test_routed_generate_matches_solo(rng, fresh_registry):
    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
            compute_dtype="float32", learning_rate=0.01).init()
    router = InferenceRouter(per_try_timeout_s=30.0)
    fleet = _mk_fleet(g, router, n=2, request_timeout_s=30.0)
    try:
        prompt = rng.integers(0, 11, (2, 3))
        solo = np.asarray(g.generate(prompt, 6))
        routed = router.generate(prompt, 6, timeout=60)
        np.testing.assert_array_equal(routed, solo)
    finally:
        fleet.shutdown()


# --------------------------------------------- kill-an-engine failover

def test_kill_one_of_three_loses_zero_requests(net, rng, fresh_registry):
    """The acceptance scenario: 3 endpoints, concurrent load, one
    killed mid-flight → every future resolves via failover, the victim
    is marked out of the pool, and after recovery + probe it rejoins."""
    router = InferenceRouter(per_try_timeout_s=1.0, eject_backoff_s=0.1,
                             max_attempts=4)
    fleet = _mk_fleet(net, router, n=3, request_timeout_s=1.0)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        inline = np.asarray(net.output(x))
        # warm the routing plane so every endpoint has seen traffic
        for _ in range(6):
            router.output(x, timeout=30)
        victim = fleet.names()[0]
        futs = [router.submit(x) for _ in range(10)]
        kill_endpoint(fleet, victim)
        futs += [router.submit(x) for _ in range(30)]
        results = [f.result(timeout=30) for f in futs]  # ZERO lost
        assert len(results) == 40
        for r in results:
            np.testing.assert_array_equal(r, inline)
        # the victim is positively out of the pool (heartbeats stale
        # and/or ejected after its timeouts)
        assert _spin_until(
            lambda: not router.fleet_snapshot()["endpoints"][victim]["in_pool"])
        snap = router.fleet_snapshot()
        assert snap["healthy_endpoints"] == 2 and snap["degraded"]
        # recovery: restart + collapse the ejection backoff; traffic
        # probes the half-open endpoint back into the pool
        fleet.restart(victim)
        assert _spin_until(
            lambda: router.fleet_snapshot()["endpoints"][victim]["alive"])
        router.probe_now()
        for _ in range(10):
            router.output(x, timeout=30)
        assert _spin_until(
            lambda: router.fleet_snapshot()["endpoints"][victim]["in_pool"])
        assert not router.fleet_snapshot()["degraded"]
    finally:
        fleet.shutdown(drain=False)


def test_killed_endpoint_requests_fail_over_not_strand(net, rng,
                                                       fresh_registry):
    """Requests already accepted by the killed worker (consumed, never
    replied) resolve through the endpoint timeout → router failover:
    the in-flight path, not just the not-yet-dispatched one."""
    router = InferenceRouter(per_try_timeout_s=0.3, eject_backoff_s=0.1,
                             max_attempts=4)
    fleet = _mk_fleet(net, router, n=2, request_timeout_s=0.3)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        inline = np.asarray(net.output(x))
        for _ in range(4):
            router.output(x, timeout=30)
        victim = fleet.names()[0]
        # kill, then immediately race a burst in — some will be routed
        # to the dead endpoint before its heartbeat goes stale
        kill_endpoint(fleet, victim)
        futs = [router.submit(x) for _ in range(20)]
        for f in futs:
            np.testing.assert_array_equal(f.result(timeout=30), inline)
        assert monitor.get_registry().family_total(
            monitor.ROUTER_FAILOVERS_COUNTER) >= 0  # may be 0 if hb won
    finally:
        fleet.shutdown(drain=False)


# ------------------------------------------------------------- hedging

class _StallingEndpoint(LocalEndpoint):
    """LocalEndpoint whose replies are withheld until released — the
    deterministic stand-in for a wedged-but-alive engine."""

    def __init__(self, engine, name):
        super().__init__(engine, name)
        import threading
        self.release = threading.Event()
        self.submitted = 0

    def submit(self, x, timeout_s=None):
        from concurrent.futures import Future
        import threading
        self.submitted += 1
        inner = self.engine.submit(x)
        out = Future()

        def hold():
            r = inner.result()
            self.release.wait(30)
            if not out.done():
                out.set_result(r)
        threading.Thread(target=hold, daemon=True).start()
        return out


def test_hedged_request_wins_without_duplicate_delivery(net, rng,
                                                        fresh_registry):
    slow_eng = ParallelInference(net, max_batch_size=4, replicas=1)
    fast_eng = ParallelInference(net, max_batch_size=4, replicas=1)
    slow = _StallingEndpoint(slow_eng, "slow")
    fast = LocalEndpoint(fast_eng, "fast")
    # deterministic: the stalled endpoint is the ONLY one at submit
    # time (primary dispatch guaranteed), the fast one arrives before
    # the hedge timer fires and becomes the hedge target
    router = InferenceRouter([slow], hedge_after_ms=30.0, max_attempts=2)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        inline = np.asarray(net.output(x))
        fut = router.submit(x)
        assert slow.submitted == 1
        router.add_endpoint(fast)
        y = fut.result(timeout=30)  # resolved by the hedge
        np.testing.assert_array_equal(y, inline)
        reg = monitor.get_registry()
        assert reg.family_total(monitor.ROUTER_HEDGES_COUNTER) == 1
        # exactly one delivery counted end-to-end (first reply won)
        assert reg.get(monitor.ROUTER_LATENCY_HISTOGRAM).count == 1
        # no duplicate delivery: releasing the stalled reply must not
        # change the resolved future
        slow.release.set()
        assert _spin_until(lambda: slow.release.is_set())
        time.sleep(0.05)  # let the late reply land (and be dropped)
        np.testing.assert_array_equal(fut.result(), y)
        assert reg.get(monitor.ROUTER_LATENCY_HISTOGRAM).count == 1
    finally:
        router.close()
        slow_eng.shutdown()
        fast_eng.shutdown()


# -------------------------------------------------- deadline admission

def test_deadline_shed_returns_retry_after(net, rng, fresh_registry):
    ep = LocalEndpoint(ParallelInference(net, max_batch_size=4, replicas=1),
                       "e0")
    router = InferenceRouter([ep])
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        for _ in range(3):  # seed the EWMA so the estimate is nonzero
            router.output(x, timeout=30)
        snap = router.fleet_snapshot()
        assert snap["endpoints"]["e0"]["ewma_ms"] > 0
        with pytest.raises(RetryAfter) as ei:
            router.submit(x, deadline_ms=1e-6)
        assert ei.value.retry_after_s > 0
        reg = monitor.get_registry()
        assert reg.family_total(monitor.ROUTER_SHED_COUNTER) == 1
        # shed happened AT ADMISSION: no future was created, so nothing
        # can strand; the engine never saw the request
        assert router.fleet_snapshot()["endpoints"]["e0"]["inflight"] == 0
        # a no-deadline request still flows
        np.testing.assert_array_equal(router.output(x, timeout=30),
                                      np.asarray(net.output(x)))
    finally:
        router.close()
        ep.close()


def test_priority_classes_shed_low_first(net, rng, fresh_registry):
    ep = LocalEndpoint(ParallelInference(net, max_batch_size=4, replicas=1),
                       "e0")
    router = InferenceRouter([ep])
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        for _ in range(3):
            router.output(x, timeout=30)
        ewma = router.fleet_snapshot()["endpoints"]["e0"]["ewma_ms"]
        # deadline between best_effort's 0.4x headroom and
        # interactive's 1.0x: interactive admits, best_effort sheds
        deadline = ewma / 0.6
        np.testing.assert_array_equal(
            router.submit(x, deadline_ms=deadline,
                          priority="interactive").result(timeout=30),
            np.asarray(net.output(x)))
        with pytest.raises(RetryAfter):
            router.submit(x, deadline_ms=deadline, priority="best_effort")
    finally:
        router.close()
        ep.close()


def test_no_endpoint_sheds(fresh_registry):
    router = InferenceRouter([])
    with pytest.raises(RetryAfter):
        router.submit(np.zeros((1, N_IN), np.float32))
    assert monitor.get_registry().family_total(
        monitor.ROUTER_SHED_COUNTER) == 1


# ---------------------------------------------------- session affinity

def test_decode_session_sticks_to_one_endpoint(rng, fresh_registry):
    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
            compute_dtype="float32", learning_rate=0.01).init()
    router = InferenceRouter(per_try_timeout_s=30.0)
    fleet = _mk_fleet(g, router, n=3, request_timeout_s=30.0)
    try:
        prompt = rng.integers(0, 11, (1, 3))
        solo = np.asarray(g.generate(prompt, 4))
        for burst in range(4):  # multi-burst decode stream
            y = router.generate(prompt, 4, session="conv-1", timeout=60)
            np.testing.assert_array_equal(y, solo)
        pinned = router.session_endpoint("conv-1")
        assert pinned is not None
        served = {n: fleet.endpoint(n).stats().get("served", 0)
                  for n in fleet.names()}
        # all 4 bursts landed on the pinned endpoint (heartbeats lag,
        # so spin until its served count catches up)
        assert _spin_until(lambda: fleet.endpoint(pinned).stats()
                           .get("served", 0) >= 4)
        for name in fleet.names():
            if name != pinned:
                assert fleet.endpoint(name).stats().get("served", 0) == 0, \
                    served
    finally:
        fleet.shutdown()


def test_affinity_repins_when_endpoint_dies(net, rng, fresh_registry):
    router = InferenceRouter(per_try_timeout_s=0.5, eject_backoff_s=0.1,
                             max_attempts=4)
    fleet = _mk_fleet(net, router, n=2, request_timeout_s=0.5)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        router.submit(x, session="s").result(timeout=30)
        first = router.session_endpoint("s")
        kill_endpoint(fleet, first)
        assert _spin_until(
            lambda: not router.fleet_snapshot()["endpoints"][first]["in_pool"])
        router.submit(x, session="s").result(timeout=30)
        second = router.session_endpoint("s")
        assert second is not None and second != first
    finally:
        fleet.shutdown(drain=False)


# --------------------------------------------------- drain-for-shutdown

def test_remove_endpoint_drains_without_loss(net, rng, fresh_registry):
    router = InferenceRouter(per_try_timeout_s=10.0)
    fleet = _mk_fleet(net, router, n=2, request_timeout_s=10.0)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        inline = np.asarray(net.output(x))
        futs = [router.submit(x) for _ in range(16)]
        victim = fleet.names()[0]
        fleet.remove_endpoint(victim)  # drains: zero lost requests
        for f in futs:
            np.testing.assert_array_equal(f.result(timeout=30), inline)
        assert victim not in router.endpoints()
    finally:
        fleet.shutdown()


def test_engine_drain_contract(net, rng):
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            replicas=1)
    try:
        futs = [eng.submit(rng.standard_normal((1, N_IN)).astype(np.float32))
                for _ in range(8)]
        assert eng.drain(timeout=30)
        assert all(f.done() for f in futs)
        assert eng.stats()["inflight"] == 0
    finally:
        eng.shutdown()


# -------------------------------------------------- network partitions

def test_partitioned_heartbeats_mark_endpoint_dead(net, rng,
                                                   fresh_registry):
    broker = InMemoryBroker()
    part = NetworkPartition(broker, topic_substr=".hb", silent=True)
    eng = ParallelInference(net, max_batch_size=4, replicas=1)
    worker = EngineWorker(eng, broker, "svc-p", heartbeat_s=0.05)
    ep = RemoteEndpoint(part, "svc-p", request_timeout_s=1.0,
                        heartbeat_timeout_s=0.3)
    try:
        assert _spin_until(ep.alive, timeout=10)
        part.partition()  # heartbeats black-hole endpoint-side
        assert _spin_until(lambda: not ep.alive(), timeout=10)
        assert part.dropped > 0
        part.heal()
        assert _spin_until(ep.alive, timeout=10)
    finally:
        ep.close()
        worker.kill()
        eng.shutdown(drain=False)


# ----------------------------------------------------------- autoscale

def test_scale_policy_decisions_are_deterministic():
    pol = ScalePolicy(min_endpoints=1, max_endpoints=4,
                      target_queue_per_endpoint=4.0, queue_low=0.5,
                      p99_high_ms=100.0, cooldown_s=10.0)

    def snap(total, healthy, depth, p99=None, eps=None):
        return {"total_endpoints": total, "healthy_endpoints": healthy,
                "queue_depth": depth, "p99_ms": p99,
                "endpoints": eps or {}}

    # backlog over target → add
    d = pol.decide(snap(2, 2, 20.0), now=0.0)
    assert d == [ScaleDecision("add", None, d[0].reason)]
    # cooldown gates the next decision
    assert pol.decide(snap(2, 2, 20.0), now=5.0) == []
    # p99 breach alone also adds
    assert pol.decide(snap(2, 2, 0.0, p99=250.0),
                      now=20.0)[0].action == "add"
    # idle fleet shrinks to the least-loaded member, not below min
    eps = {"a": {"in_pool": True, "inflight": 3, "stats": {"queue_depth": 1}},
           "b": {"in_pool": True, "inflight": 0, "stats": {"queue_depth": 0}}}
    d = pol.decide(snap(2, 2, 0.0, p99=10.0, eps=eps), now=40.0)
    assert d[0].action == "remove" and d[0].endpoint == "b"
    # at max, no add even under pressure
    pol2 = ScalePolicy(max_endpoints=2, cooldown_s=0.0)
    assert pol2.decide(snap(2, 2, 100.0), now=0.0) == []
    # below min always adds
    pol3 = ScalePolicy(min_endpoints=2, cooldown_s=0.0)
    assert pol3.decide(snap(1, 1, 0.0), now=0.0)[0].action == "add"


def test_fleet_applies_scale_decisions(net, fresh_registry):
    router = InferenceRouter()
    fleet = _mk_fleet(net, router, n=1)
    try:
        pol = ScalePolicy(min_endpoints=1, max_endpoints=3,
                          target_queue_per_endpoint=0.0, cooldown_s=0.0)
        # force an add: any backlog beats target 0... use decide on a
        # synthetic pressure snapshot, apply through the fleet
        log = fleet.apply([ScaleDecision("add", None, "test pressure")])
        assert len(log) == 1 and len(fleet.names()) == 2
        assert len(router.endpoints()) == 2
        victim = fleet.names()[-1]
        log = fleet.apply([ScaleDecision("remove", victim, "test idle")])
        assert len(log) == 1 and victim not in fleet.names()
        assert victim not in router.endpoints()
    finally:
        fleet.shutdown()


# ------------------------------------------------ /healthz split + UI

def test_healthz_liveness_readiness_split(net, rng, fresh_registry):
    import http.client

    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    from deeplearning4j_tpu.ui.server import UiServer

    eng = ParallelInference(net, max_batch_size=4, replicas=1)
    server = UiServer(InMemoryStatsStorage(), registry=fresh_registry,
                      inference_engine=eng).start()

    def get(path):
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("GET", path)
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        return resp.status, body

    try:
        # un-warmed engine: live 200, ready 503, /healthz stays 200
        status, body = get("/healthz/live")
        assert status == 200 and body["live"]
        status, body = get("/healthz/ready")
        assert status == 503 and body["status"] == "unwarmed"
        status, body = get("/healthz")
        assert status == 200 and body["ready"] is False
        eng.warmup([(N_IN,)])
        status, body = get("/healthz/ready")
        assert status == 200 and body["ready"] is True
    finally:
        server.stop()
        eng.shutdown()


def test_healthz_aggregates_fleet_state(net, rng, fresh_registry):
    import http.client

    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    from deeplearning4j_tpu.ui.server import UiServer

    router = InferenceRouter(per_try_timeout_s=0.5, eject_backoff_s=0.1)
    fleet = _mk_fleet(net, router, n=2, request_timeout_s=0.5)
    server = UiServer(InMemoryStatsStorage(), registry=fresh_registry,
                      router=router).start()

    def get(path):
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("GET", path)
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        return resp.status, body

    try:
        status, body = get("/healthz")
        assert status == 200
        assert body["fleet"]["healthy_endpoints"] == 2
        victim = fleet.names()[0]
        kill_endpoint(fleet, victim)
        assert _spin_until(
            lambda: get("/healthz")[1]["fleet"]["healthy_endpoints"] == 1)
        status, body = get("/healthz")
        assert status == 503  # degraded fleet: reduced capacity
        assert body["fleet"]["endpoints"][victim]["in_pool"] is False
        status, _ = get("/healthz/live")
        assert status == 200  # degraded-but-serving is NOT dead
    finally:
        server.stop()
        fleet.shutdown(drain=False)


# ------------------------------------------------------ metrics schema

def test_router_metric_schema(net, rng, fresh_registry):
    import scripts.check_telemetry_schema as schema

    ep = LocalEndpoint(ParallelInference(net, max_batch_size=4, replicas=1),
                       "e0")
    router = InferenceRouter([ep])
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        for _ in range(3):
            router.output(x, timeout=30)
        with pytest.raises(RetryAfter):
            router.submit(x, deadline_ms=1e-6)
        text = fresh_registry.prometheus_text()
        assert schema.validate_prometheus_text(text) == []
        assert schema.validate_known_metrics(text) == []
        for name in (monitor.ROUTER_REQUESTS_COUNTER,
                     monitor.ROUTER_SHED_COUNTER,
                     monitor.ROUTER_QUEUE_WAIT_HISTOGRAM,
                     monitor.ROUTER_LATENCY_HISTOGRAM,
                     monitor.ROUTER_ENDPOINT_HEALTHY_GAUGE):
            assert name in text, name
        assert {monitor.ROUTER_HEDGES_COUNTER,
                monitor.ROUTER_FAILOVERS_COUNTER} <= set(
                    schema.KNOWN_DL4J_METRICS)
    finally:
        router.close()
        ep.close()


# ----------------------- typed engine errors across the wire boundary
# (ISSUE-7 satellite: a remote worker's shed/quarantine must surface to
# the router caller as the SAME exception type as a LocalEndpoint's,
# for both classify and generate paths)

def _shedding_engine(net):
    """An engine that sheds deterministically: nothing consumes the
    1-slot admission queue (start=False), so the second submit raises
    InferenceBackpressure synchronously."""
    return ParallelInference(net, queue_capacity=1, reject_when_full=True,
                             replicas=1, start=False)


def _first_error(router, submit):
    """Submit one request at a time (the first may park in a 1-slot
    queue and never resolve); returns the first engine error seen —
    checked after EVERY submit so a router-side ejection can't mask
    the typed error under test."""
    futs = []
    for _ in range(3):
        try:
            futs.append(submit())
        except Exception as e:
            return e
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            err = next((f.exception() for f in futs
                        if f.done() and f.exception() is not None), None)
            if err is not None:
                return err
            if all(f.done() for f in futs):
                break
            time.sleep(0.01)
    raise AssertionError("engine never shed")


def test_backpressure_shed_same_type_local_and_remote(net, rng,
                                                      fresh_registry):
    from deeplearning4j_tpu.parallel.inference import InferenceBackpressure
    x = rng.standard_normal((1, N_IN)).astype(np.float32)
    prompt = rng.integers(0, 11, (1, 3))
    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
            compute_dtype="float32", learning_rate=0.01).init()

    # local path: the engine's typed exception reaches the router caller
    local_errs = {}
    for kind, engine, submit_args in (
            ("classify", _shedding_engine(net), ("submit", (x,))),
            ("generate", _shedding_engine(g), ("submit_generate", (prompt, 2)))):
        router = InferenceRouter([LocalEndpoint(engine, "solo")],
                                 max_attempts=1)
        try:
            local_errs[kind] = _first_error(
                router, lambda: getattr(router, submit_args[0])(*submit_args[1]))
        finally:
            router.close()
            engine.shutdown()

    # remote path: the worker packs the typed error, the endpoint
    # reconstructs it, the router caller sees the SAME class
    remote_errs = {}
    for kind, engine, submit_args in (
            ("classify", _shedding_engine(net), ("submit", (x,))),
            ("generate", _shedding_engine(g), ("submit_generate", (prompt, 2)))):
        broker = InMemoryBroker()
        from deeplearning4j_tpu.serving import EngineWorker
        worker = EngineWorker(engine, broker, f"shed-{kind}",
                              heartbeat_s=0.05)
        ep = RemoteEndpoint(broker, f"shed-{kind}", request_timeout_s=30.0)
        router = InferenceRouter([ep], max_attempts=1)
        try:
            assert _spin_until(ep.alive, timeout=10)
            remote_errs[kind] = _first_error(
                router, lambda: getattr(router, submit_args[0])(*submit_args[1]))
        finally:
            router.close()
            worker.kill()
            ep.close()
            engine.shutdown()

    for kind in ("classify", "generate"):
        assert isinstance(local_errs[kind], InferenceBackpressure), kind
        assert type(remote_errs[kind]) is type(local_errs[kind]), (
            kind, remote_errs[kind], local_errs[kind])


def test_model_quarantine_same_type_local_and_remote(net, rng,
                                                     fresh_registry):
    from deeplearning4j_tpu.serving import (EngineWorker, ModelQuarantined,
                                            ModelRegistry)

    def quarantined_engine():
        reg = ModelRegistry()
        reg.register("m", net=net)
        eng = ParallelInference(registry=reg, max_batch_size=4, replicas=1)
        with reg._lock:  # deterministic: breaker opened by hand
            reg._models["m"].breaker_open = True
        return eng

    x = rng.standard_normal((1, N_IN)).astype(np.float32)
    local = quarantined_engine()
    router = InferenceRouter([LocalEndpoint(local, "solo")], max_attempts=1)
    try:
        local_err = _first_error(router, lambda: router.submit(x, model="m"))
    finally:
        router.close()
        local.shutdown()

    remote = quarantined_engine()
    broker = InMemoryBroker()
    worker = EngineWorker(remote, broker, "quar", heartbeat_s=0.05)
    ep = RemoteEndpoint(broker, "quar", request_timeout_s=30.0)
    router = InferenceRouter([ep], max_attempts=1)
    try:
        assert _spin_until(ep.alive, timeout=10)
        remote_err = _first_error(router, lambda: router.submit(x, model="m"))
    finally:
        router.close()
        worker.kill()
        ep.close()
        remote.shutdown()

    assert isinstance(local_err, ModelQuarantined)
    assert type(remote_err) is type(local_err)
    assert "quarantined" in str(remote_err)


def test_retry_after_roundtrips_typed_through_wire():
    from deeplearning4j_tpu.serving import wire
    payload = wire.pack_reply("c1", error=RetryAfter("try later", 1.5))
    header, result = wire.unpack_reply(payload)
    assert result is None and header["ok"] is False
    err = wire.typed_error(header)
    assert isinstance(err, RetryAfter)
    assert err.retry_after_s == 1.5 and "try later" in str(err)


# ----------------------------- durable decode streams (ISSUE 10)

class _Chunks:
    """Router-side delivery audit: offsets must be contiguous from 0
    across ANY number of migrations (no gap, no repeat)."""

    def __init__(self):
        self.chunks = []

    def __call__(self, off, toks):
        self.chunks.append((int(off),
                            [int(t) for t in np.asarray(toks).reshape(-1)]))

    def tokens(self):
        toks = []
        for off, ts in self.chunks:
            assert off == len(toks), f"gap/repeat at {off}: {self.chunks}"
            toks.extend(ts)
        return toks


def _mk_gpt_fleet(net, router, n=2, hooks=None, request_timeout_s=30.0):
    """Continuous-decode engine fleet; ``hooks[i]`` arms a
    decode_burst_hook on the i-th engine built (None = no hook)."""
    built = []

    def engine_factory():
        hook = None
        if hooks is not None and len(built) < len(hooks):
            hook = hooks[len(built)]
        eng = ParallelInference(net, replicas=1, continuous=True,
                                decode_slots=4, decode_burst=4,
                                kv_block_size=4, decode_burst_hook=hook)
        built.append(eng)
        return eng

    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=request_timeout_s,
                       heartbeat_timeout_s=1.0)
    for _ in range(n):
        fleet.add_endpoint()
    assert fleet.wait_ready(30)
    return fleet


def _warm_endpoint(fleet, name, prompt, max_new):
    """Pre-compile one endpoint's decode programs by dispatching to it
    DIRECTLY (bypassing router placement), so a later migration's
    resume isn't racing XLA compiles against the silence timeout."""
    fleet.endpoint(name).submit_generate(prompt, max_new).result(60)


def _scale_timeouts(router, fleet, name, prompt, max_new,
                    floor_s, cap_s):
    """Deflake (PR-10 wall-clock-timeout family on 1-core boxes):
    tier-1 runs this file under heavy parallel load, where a WARM
    healthy dispatch alone can approach a fixed 1.5-3s reply budget —
    the timeout then fires on a healthy engine and the test flakes.
    Time one warmed dispatch on this box RIGHT NOW and scale every
    reply/silence deadline off it (floor = the original tight budget,
    so an idle box keeps the original timing; cap keeps the failure
    path inside the test's own result() budget). Returns the budget."""
    t0 = time.perf_counter()
    _warm_endpoint(fleet, name, prompt, max_new)  # warmed: measures load
    warm_s = time.perf_counter() - t0
    budget = min(cap_s, max(floor_s, 10.0 * warm_s))
    router.per_try_timeout = budget
    for n in fleet.names():
        fleet.endpoint(n).request_timeout = budget
    return budget


def test_stream_migrates_on_burst_kill_resumed_not_restarted(rng,
                                                             fresh_registry):
    """THE acceptance scenario, deterministic: the pinned engine's
    second decode burst dies under the stream (typed DecodeBurstError
    across the wire) → the router migrates the stream with its
    journaled prefix → the surviving engine RESUMES (re-prefills
    prompt + prefix only, pinned via its scheduler's admit event and
    the resume-prefix counter) → delivered tokens are token-for-token
    the uninterrupted generate_eager run with zero duplicate/missing
    offsets."""
    from deeplearning4j_tpu.faultinject import BurstKill
    from deeplearning4j_tpu.nn.generate import generate_eager
    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=64,
            compute_dtype="float32", learning_rate=0.01).init()
    for sampler in ({}, {"temperature": 0.8, "top_k": 5, "seed": 3}):
        reg = monitor.set_registry(monitor.MetricsRegistry())
        router = InferenceRouter(per_try_timeout_s=15.0,
                                 eject_backoff_s=0.1, max_attempts=4)
        kill = BurstKill(after=1, failures=1)
        fleet = _mk_gpt_fleet(g, router, n=2, hooks=[kill])
        try:
            prompt = rng.integers(0, 11, (1, 5))
            want = generate_eager(g, prompt, 16, **sampler)
            coll = _Chunks()
            fut = router.submit_generate(prompt, 16, session="mig",
                                         on_tokens=coll, **sampler)
            got = fut.result(90)
            np.testing.assert_array_equal(got, want)
            assert coll.tokens() == [int(t) for t in want[0, 5:]]
            assert kill.hits == 1
            mreg = monitor.get_registry()
            assert mreg.family_total(monitor.SESSION_MIGRATIONS_COUNTER) == 1
            prefix = mreg.family_total(monitor.ROUTER_RESUME_PREFIX_COUNTER)
            assert prefix > 0  # resumed from the journal, not restarted
            # the survivor admitted the resume at t0 + prefix — it
            # prefilled the prefix instead of re-generating it
            survivor = fleet._members["engine-1"].worker.engine
            admits = [e for e in survivor._scheduler.events
                      if e.startswith("admit")]
            assert len(admits) == 1
            assert f" t={5 + int(prefix)} " in admits[0], (admits, prefix)
            snap = router.fleet_snapshot()
            assert snap["migrations"] == 1
            assert snap["resume_prefix_tokens"] == int(prefix)
            assert snap["active_streams"] == 0  # terminal frame landed
            assert router.session_endpoint("mig") == "engine-1"
        finally:
            fleet.shutdown(drain=False)
            router.close()
            monitor.set_registry(reg)


def test_stream_survives_stalled_endpoint_timeout(rng, fresh_registry):
    """The wedged-mid-burst shape: the pinned engine stalls (burst
    gated, no chunks, no reply — but heartbeats keep flowing) → the
    stream's silence deadline fires → migration with prefix → exact
    tokens; the stalled engine's LATE chunks are dropped by the
    dispatch epoch, never double-delivered."""
    import threading
    from deeplearning4j_tpu.nn.generate import generate_eager

    class _Gate:
        def __init__(self):
            self.ev = threading.Event()
            self.calls = 0

        def __call__(self, lane, idx):
            self.calls += 1
            if self.calls == 2:
                self.ev.wait(60)

    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=64,
            compute_dtype="float32", learning_rate=0.01).init()
    router = InferenceRouter(per_try_timeout_s=3.0, eject_backoff_s=0.1,
                             max_attempts=4)
    gate = _Gate()
    fleet = _mk_gpt_fleet(g, router, n=2, hooks=[gate],
                          request_timeout_s=3.0)
    try:
        prompt = rng.integers(0, 11, (1, 5))
        want = generate_eager(g, prompt, 16)
        # warm the survivor — original shape AND the resume shape
        # (prompt+prefix prefill is a different bucket) — so the
        # migrated dispatch isn't racing XLA compiles against the
        # silence budget on a loaded box
        _warm_endpoint(fleet, "engine-1", prompt, 16)
        _warm_endpoint(fleet, "engine-1",
                       rng.integers(0, 11, (1, 10)), 11)
        # then scale the silence/reply budget off this box's measured
        # warm-dispatch cost (the stalled engine holds its burst for
        # 60s, so any finite budget still fires the migration)
        _scale_timeouts(router, fleet, "engine-1", prompt, 16,
                        floor_s=3.0, cap_s=20.0)
        coll = _Chunks()
        fut = router.submit_generate(prompt, 16, session="stall",
                                     on_tokens=coll)
        got = fut.result(90)
        np.testing.assert_array_equal(got, want)
        gate.ev.set()  # release the stalled engine: late chunks fire
        time.sleep(0.2)  # ...and are dropped (epoch + swept pending)
        assert coll.tokens() == [int(t) for t in want[0, 5:]]
        reg = monitor.get_registry()
        assert reg.family_total(monitor.SESSION_MIGRATIONS_COUNTER) >= 1
        assert router.session_endpoint("stall") == "engine-1"
    finally:
        gate.ev.set()
        fleet.shutdown(drain=False)
        router.close()


def test_mid_generation_kill_restarted_stream_matches_eager(rng,
                                                            fresh_registry):
    """The satellite regression pinning (pre-journal) behavior for
    NON-streaming sessions: kill the pinned endpoint mid-generation —
    the request restarts elsewhere (no journal ⇒ zero resume prefix)
    and the result still matches eager exactly."""
    import threading
    from deeplearning4j_tpu.nn.generate import generate_eager

    class _Gate:
        def __init__(self):
            self.ev = threading.Event()
            self.calls = 0

        def __call__(self, lane, idx):
            self.calls += 1
            if self.calls == 2:
                self.ev.wait(60)

    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=64,
            compute_dtype="float32", learning_rate=0.01).init()
    router = InferenceRouter(per_try_timeout_s=1.5, eject_backoff_s=0.1,
                             max_attempts=4)
    gate = _Gate()
    fleet = _mk_gpt_fleet(g, router, n=2, hooks=[gate],
                          request_timeout_s=1.5)
    try:
        prompt = rng.integers(0, 11, (1, 5))
        want = generate_eager(g, prompt, 16)
        _warm_endpoint(fleet, "engine-1", prompt, 16)
        # scale the reply budget off measured load (the kill is
        # detected by reply timeout — a fixed 1.5s budget also fires
        # on a HEALTHY loaded engine and flakes the restart count)
        _scale_timeouts(router, fleet, "engine-1", prompt, 16,
                        floor_s=1.5, cap_s=15.0)
        fut = router.submit_generate(prompt, 16, session="res")
        assert _spin_until(lambda: gate.calls >= 2, timeout=30)
        kill_endpoint(fleet, "engine-0")  # mid-generation engine death
        np.testing.assert_array_equal(fut.result(90), want)
        reg = monitor.get_registry()
        assert reg.family_total(monitor.SESSION_MIGRATIONS_COUNTER) >= 1
        # no journal (non-streaming): restarted, not resumed
        assert reg.family_total(monitor.ROUTER_RESUME_PREFIX_COUNTER) == 0
        assert router.session_endpoint("res") == "engine-1"
    finally:
        gate.ev.set()
        fleet.shutdown(drain=False)
        router.close()


def test_router_stream_generator_yields_deltas(rng, fresh_registry):
    from deeplearning4j_tpu.nn.generate import generate_eager
    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
            compute_dtype="float32", learning_rate=0.01).init()
    router = InferenceRouter(per_try_timeout_s=30.0)
    fleet = _mk_gpt_fleet(g, router, n=1)
    try:
        prompt = rng.integers(0, 11, (1, 4))
        want = generate_eager(g, prompt, 8)
        toks = []
        for off, delta in router.stream(prompt, 8, timeout=60):
            assert off == len(toks)
            toks.extend(int(t) for t in delta)
        assert toks == [int(t) for t in want[0, 4:]]
    finally:
        fleet.shutdown(drain=False)
        router.close()


# -------------------------------------------- wedged-endpoint watchdog

def test_wedged_endpoint_detected_ejected_migrated(net, rng,
                                                   fresh_registry):
    """Heartbeats prove liveness, not progress: a wedged worker (keeps
    beating, drops every request) is ejected by the progress watchdog
    BEFORE any reply timeout scores a failure, its in-flight request
    resolves via timeout → failover, and after healing it probes back
    into the pool."""
    from deeplearning4j_tpu.faultinject import WedgeEndpoint
    router = InferenceRouter(per_try_timeout_s=2.0, eject_backoff_s=0.2,
                             max_attempts=4, wedge_timeout_s=0.3)
    fleet = _mk_fleet(net, router, n=2, request_timeout_s=2.0)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        inline = np.asarray(net.output(x))
        for _ in range(4):
            router.output(x, timeout=30)
        victim = "engine-0"
        with WedgeEndpoint(fleet, victim):
            fut = router.submit(x)  # may land on the wedged endpoint
            assert _spin_until(lambda: router.fleet_snapshot()
                               ["endpoints"][victim]["wedged"], timeout=20)
            snap = router.fleet_snapshot()
            assert snap["endpoints"][victim]["alive"]  # still beating!
            assert not snap["endpoints"][victim]["in_pool"]
            # the stuck request resolves (timeout → failover), new
            # traffic avoids the wedge
            np.testing.assert_array_equal(fut.result(30), inline)
            np.testing.assert_array_equal(router.output(x, timeout=30),
                                          inline)
        # healed: probe reinstates, wedged flag clears
        def reinstated():
            router.probe_now()
            try:
                router.output(x, timeout=30)
            except BaseException:
                return False
            ep = router.fleet_snapshot()["endpoints"][victim]
            return ep["in_pool"] and not ep["wedged"]
        assert _spin_until(reinstated, timeout=30, tick=0.05)
    finally:
        fleet.shutdown(drain=False)
        router.close()


# --------------------------------------- scale-down drain vs migration

def test_scale_down_drains_active_stream_zero_token_loss(rng,
                                                         fresh_registry):
    """drain_and_stop × migration: removing the endpoint a live stream
    is pinned to must let the stream FINISH there (every token
    delivered exactly once, no migration needed) before the goodbye
    frame; the session re-pins for its next burst."""
    import threading
    from deeplearning4j_tpu.nn.generate import generate_eager

    class _Gate:
        def __init__(self):
            self.ev = threading.Event()
            self.calls = 0

        def __call__(self, lane, idx):
            self.calls += 1
            if self.calls == 2:
                self.ev.wait(60)

    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=64,
            compute_dtype="float32", learning_rate=0.01).init()
    router = InferenceRouter(per_try_timeout_s=30.0)
    gate = _Gate()
    fleet = _mk_gpt_fleet(g, router, n=2, hooks=[gate])
    try:
        prompt = rng.integers(0, 11, (1, 5))
        want = generate_eager(g, prompt, 16)
        coll = _Chunks()
        fut = router.submit_generate(prompt, 16, session="sd",
                                     on_tokens=coll)
        assert _spin_until(lambda: gate.calls >= 2, timeout=30)
        assert router.session_endpoint("sd") == "engine-0"
        # scale down the pinned endpoint while the stream is gated
        done = []
        th = threading.Thread(
            target=lambda: done.append(fleet.remove_endpoint("engine-0")))
        th.start()
        time.sleep(0.2)
        assert not fut.done()  # drain is WAITING on the live stream
        gate.ev.set()          # release: the stream finishes on the drainer
        got = fut.result(90)
        th.join(60)
        np.testing.assert_array_equal(got, want)
        assert coll.tokens() == [int(t) for t in want[0, 5:]]
        # zero-loss hand-off: no migration was needed for the stream
        reg = monitor.get_registry()
        assert reg.family_total(monitor.ROUTER_RESUME_PREFIX_COUNTER) == 0
        # the session's NEXT burst lands on the survivor
        y = router.generate(prompt, 8, session="sd", timeout=90)
        np.testing.assert_array_equal(y, generate_eager(g, prompt, 8))
        assert router.session_endpoint("sd") == "engine-1"
    finally:
        gate.ev.set()
        fleet.shutdown(drain=False)
        router.close()


# ----------------------------------------------- wire protocol version

def test_wire_version_skew_rejected_typed(net, rng, fresh_registry):
    """A frame from a NEWER protocol is rejected with a typed
    WireVersionError reply — never served garbled. Pinned end-to-end:
    a crafted v99 request through a live worker surfaces the SAME
    exception class at the endpoint's future."""
    from deeplearning4j_tpu.serving import wire
    # unit: check_version + typed roundtrip
    with pytest.raises(wire.WireVersionError):
        wire.check_version({"v": wire.WIRE_VERSION + 1})
    wire.check_version({})          # legacy v1 headers stay accepted
    header, _ = wire.unpack_reply(
        wire.pack_reply("c", error=wire.WireVersionError("skew")))
    assert isinstance(wire.typed_error(header), wire.WireVersionError)
    # end-to-end: live worker rejects a v99 frame typed
    eng = ParallelInference(net, max_batch_size=4, replicas=1)
    broker = InMemoryBroker()
    worker = EngineWorker(eng, broker, "vskew", heartbeat_s=0.05)
    ep = RemoteEndpoint(broker, "vskew", request_timeout_s=10.0)
    try:
        assert _spin_until(ep.alive, timeout=10)
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        fut = ep.submit(x)
        corr = list(ep._pending)[0]
        # re-publish the same correlation id as a FUTURE-version frame
        import json as _json
        import struct as _struct
        payload = wire.pack_request(corr, ep.reply_topic,
                                    wire.KIND_CLASSIFY, x)
        hlen = _struct.unpack(">I", payload[:4])[0]
        hdr = _json.loads(payload[4:4 + hlen])
        hdr["v"] = 99
        h = _json.dumps(hdr, separators=(",", ":")).encode()
        broker.publish("vskew" + wire.REQ_SUFFIX,
                       _struct.pack(">I", len(h)) + h + payload[4 + hlen:])
        with pytest.raises(wire.WireVersionError):
            fut.result(30)
    finally:
        ep.close()
        worker.kill()
        eng.shutdown(drain=False)


def test_wire_v4_binary_roundtrip_and_damage_typed(fresh_registry):
    """The v4 binary framing contract: byte-exact zero-copy tensor
    segments, coalesced chunk decode, and — the chaos half — EVERY
    truncation point surfaces as a typed WireFrameError, never a
    garbled tensor. The broker's ping header constants are pinned to
    the wire's (they are mirrored across the import-graph boundary)."""
    from deeplearning4j_tpu.serving import wire
    from deeplearning4j_tpu.streaming import broker as broker_mod
    # the transport-level ping rides the SAME v4 prologue
    assert broker_mod.PING_MAGIC == wire.WIRE_MAGIC
    assert broker_mod.PING_VERSION == wire.WIRE_VERSION
    rng = np.random.default_rng(7)
    kv = rng.standard_normal((2, 3, 4, 5)).astype(np.float32)
    ids = rng.integers(0, 999, (1, 7)).astype(np.int32)
    payload = wire.pack_request_v4(
        "c1", "rsp", wire.KIND_GENERATE, ids,
        gen={"max_new": 4, "kv": True}, model="m", session="s",
        tensors={"kv": kv})
    assert wire.is_binary_frame(payload)
    meta, x, segs = wire.unpack_request_any(payload)
    assert meta["id"] == "c1" and meta["v"] == wire.WIRE_VERSION
    assert meta["model"] == "m" and meta["session"] == "s"
    assert x.dtype == ids.dtype
    np.testing.assert_array_equal(x, ids)
    assert segs["kv"].dtype == kv.dtype
    assert segs["kv"].tobytes() == kv.tobytes()  # byte-exact
    # legacy frames pass through the same seam untouched
    leg, lx, lsegs = wire.unpack_request_any(
        wire.pack_request("c2", "rsp", wire.KIND_CLASSIFY, ids))
    assert leg["id"] == "c2" and lsegs == {}
    np.testing.assert_array_equal(lx, ids)
    # coalesced chunk frame: one frame, every stream's delta
    frame = wire.pack_chunks_v4([
        ("a", 0, np.array([1, 2], np.int64)),
        ("b", 5, np.array([9], np.int64))])
    evs = wire.decode_reply_events(frame)
    assert [(e["type"], e["id"], e["off"]) for e in evs] == \
        [("chunk", "a", 0), ("chunk", "b", 5)]
    assert list(evs[0]["tokens"]) == [1, 2] and list(evs[1]["tokens"]) == [9]
    # truncation sweep: every cut of the binary frame fails TYPED
    for cut in range(len(payload)):
        with pytest.raises(wire.WireFrameError):
            wire.unpack_frame_v4(payload[:cut])
    # typed across the wire like every other registered engine error
    hdr, _ = wire.unpack_reply(
        wire.pack_reply("c", error=wire.WireFrameError("cut")))
    assert isinstance(wire.typed_error(hdr), wire.WireFrameError)


def test_wire_v4_version_skew_matrix(net, rng, fresh_registry):
    """Rolling-upgrade matrix, pinned end-to-end: a v4 endpoint serves
    against a v3-pinned worker (negotiation downgrades the framing per
    the worker's advertised heartbeat ceiling), a v3-pinned endpoint
    serves against a v4 worker (requests stay legacy; the worker
    replies in kind), and a RAW v4 binary frame forced at the v3
    worker is rejected with a typed WireVersionError — the only skew
    that may fail, and it fails typed."""
    from deeplearning4j_tpu.serving import wire
    x = rng.standard_normal((1, N_IN)).astype(np.float32)
    want = np.asarray(net.output(x))

    # v4 router ↔ v3 worker: keeps serving, all frames legacy
    eng = ParallelInference(net, max_batch_size=4, replicas=1)
    broker = InMemoryBroker()
    worker = EngineWorker(eng, broker, "skew-a", heartbeat_s=0.05,
                          wire_version=3)
    ep = RemoteEndpoint(broker, "skew-a", request_timeout_s=10.0)
    try:
        assert _spin_until(ep.alive, timeout=10)
        assert ep.negotiated_wire() == 3  # downgraded by the heartbeat
        np.testing.assert_array_equal(ep.submit(x).result(30), want)
        # a raw v4 frame AT the v3 worker: typed rejection, live corr
        fut = ep.submit(x)
        corr = list(ep._pending)[0]
        broker.publish("skew-a" + wire.REQ_SUFFIX, wire.pack_request_v4(
            corr, ep.reply_topic, wire.KIND_CLASSIFY, x))
        with pytest.raises(wire.WireVersionError):
            fut.result(30)
    finally:
        ep.close()
        worker.kill()
        eng.shutdown(drain=False)

    # v3 router ↔ v4 worker: requests stay legacy, replies in kind
    eng = ParallelInference(net, max_batch_size=4, replicas=1)
    broker = InMemoryBroker()
    worker = EngineWorker(eng, broker, "skew-b", heartbeat_s=0.05)
    ep = RemoteEndpoint(broker, "skew-b", request_timeout_s=10.0,
                        wire_version=3)
    try:
        assert _spin_until(ep.alive, timeout=10)
        assert ep.negotiated_wire() == 3
        np.testing.assert_array_equal(ep.submit(x).result(30), want)
    finally:
        ep.close()
        worker.kill()
        eng.shutdown(drain=False)

    # v4 ↔ v4: once the heartbeat proves the peer, the hot path goes
    # binary (before the first heartbeat the endpoint stays legacy)
    eng = ParallelInference(net, max_batch_size=4, replicas=1)
    broker = InMemoryBroker()
    worker = EngineWorker(eng, broker, "skew-c", heartbeat_s=0.05)
    ep = RemoteEndpoint(broker, "skew-c", request_timeout_s=10.0)
    try:
        assert _spin_until(ep.alive, timeout=10)
        assert ep.negotiated_wire() == 4
        reg = monitor.get_registry()
        before = reg.counter(monitor.WIRE_FRAMES_COUNTER,
                             transport="v4").value
        np.testing.assert_array_equal(ep.submit(x).result(30), want)
        assert reg.counter(monitor.WIRE_FRAMES_COUNTER,
                           transport="v4").value >= before + 2  # req+reply
    finally:
        ep.close()
        worker.kill()
        eng.shutdown(drain=False)


def test_wire_v4_stream_coalesced_and_disagg_byte_exact(rng,
                                                        fresh_registry):
    """The v4 hot path end-to-end on a continuous-decode engine:
    streamed tokens arrive through COALESCED burst frames (the
    coalesced-chunks counter ticks; offsets stay gapless), and the
    disagg prefill→decode handoff is BYTE-exact over raw v4 segments —
    same dtype, same bytes, same tokens as the fused local run."""
    from deeplearning4j_tpu.nn.generate import generate_eager
    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2,
            max_len=64, compute_dtype="float32", learning_rate=0.01).init()
    eng = ParallelInference(g, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4)
    broker = InMemoryBroker()
    worker = EngineWorker(eng, broker, "v4gpt", heartbeat_s=0.05)
    ep = RemoteEndpoint(broker, "v4gpt", request_timeout_s=30.0,
                        heartbeat_timeout_s=1.0)
    try:
        assert _spin_until(ep.alive, timeout=10)
        assert _spin_until(lambda: ep.negotiated_wire() == 4, timeout=10)
        prompt = rng.integers(0, 11, (1, 5))
        want = generate_eager(g, prompt, 12)
        coll = _Chunks()
        got = ep.submit_generate(prompt, 12, on_tokens=coll).result(90)
        np.testing.assert_array_equal(got, want)
        assert coll.tokens() == [int(t) for t in want[0, 5:]]
        reg = monitor.get_registry()
        assert reg.family_total(monitor.WIRE_COALESCED_COUNTER) > 0
        # disagg: shipped KV byte-exact over v4 framing
        st = ep.submit_prefill(prompt).result(60)
        local = eng.prefill_export(prompt.astype(np.int32))
        assert np.asarray(st["kv"]).dtype == np.asarray(local["kv"]).dtype
        assert np.asarray(st["kv"]).tobytes() == \
            np.asarray(local["kv"]).tobytes()
        np.testing.assert_array_equal(np.asarray(st["logits"]),
                                      np.asarray(local["logits"]))
        got2 = ep.submit_generate(
            prompt, 12, kv_state={"kv": st["kv"], "logits": st["logits"],
                                  "t_in": st["t_in"]}).result(90)
        np.testing.assert_array_equal(got2, want)
    finally:
        ep.close()
        worker.kill()
        eng.shutdown(drain=False)


# ------------------------------------------- stream metrics + healthz

def test_stream_metric_schema_and_healthz_counts(rng, fresh_registry):
    import scripts.check_telemetry_schema as schema
    from deeplearning4j_tpu.nn.generate import generate_eager
    for name in ("dl4j_stream_chunks_total",
                 "dl4j_session_migrations_total",
                 "dl4j_session_journal_bytes",
                 "dl4j_router_resume_prefix_tokens_total",
                 monitor.WIRE_FRAMES_COUNTER,
                 monitor.WIRE_BYTES_COUNTER,
                 monitor.WIRE_COALESCED_COUNTER,
                 monitor.ROUTER_LOOP_LAG_HISTOGRAM):
        assert name in schema.KNOWN_DL4J_METRICS, name
    from deeplearning4j_tpu.faultinject import BurstKill
    g = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=64,
            compute_dtype="float32", learning_rate=0.01).init()
    router = InferenceRouter(per_try_timeout_s=15.0, eject_backoff_s=0.1,
                             max_attempts=4)
    fleet = _mk_gpt_fleet(g, router, n=2,
                          hooks=[BurstKill(after=1, failures=1)])
    try:
        prompt = rng.integers(0, 11, (1, 5))
        want = generate_eager(g, prompt, 16)
        fut = router.submit_generate(prompt, 16, session="m",
                                     on_tokens=lambda o, t: None)
        np.testing.assert_array_equal(fut.result(90), want)
        text = fresh_registry.prometheus_text()
        assert schema.validate_prometheus_text(text) == []
        assert schema.validate_known_metrics(text) == []
        for family in ("dl4j_stream_chunks_total",
                       "dl4j_session_migrations_total",
                       "dl4j_session_journal_bytes",
                       "dl4j_router_resume_prefix_tokens_total"):
            assert f"# TYPE {family}" in text, family
        assert 'reason="burst_error"' in text
        snap = router.fleet_snapshot()
        for key in ("active_streams", "journal_bytes", "migrations",
                    "resume_prefix_tokens"):
            assert key in snap, key
        assert snap["migrations"] == 1
    finally:
        fleet.shutdown(drain=False)
        router.close()


# ---------------------- session (endpoint, model, version) vs cutover

def test_router_session_pins_endpoint_model_and_version(fresh_registry):
    from deeplearning4j_tpu.serving import ModelRegistry
    g1 = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
             compute_dtype="float32", learning_rate=0.01, seed=1).init()
    g2 = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
             compute_dtype="float32", learning_rate=0.01, seed=9).init()
    reg = ModelRegistry()
    reg.register("g", net=g1)
    eng = ParallelInference(registry=reg, max_batch_size=8,
                            max_latency_ms=0.0, replicas=1)
    ep = LocalEndpoint(eng, "e0")
    router = InferenceRouter([ep])
    try:
        prompt = np.asarray([[1, 2, 3]], np.int64)
        solo1 = np.asarray(g1.generate(prompt, 5))
        solo2 = np.asarray(g2.generate(prompt, 5))
        assert not np.array_equal(solo1, solo2)
        np.testing.assert_array_equal(
            router.generate(prompt, 5, session="s1", model="g", timeout=60),
            solo1)
        assert router.session_pin("s1") == ("e0", "g")
        reg.deploy("g", net=g2, warm=False)  # hot-swap mid-stream
        # the pinned stream finishes on the version it started on; the
        # version half of the pin lives engine-side on the session key
        np.testing.assert_array_equal(
            router.generate(prompt, 5, session="s1", model="g", timeout=60),
            solo1)
        np.testing.assert_array_equal(
            router.generate(prompt, 5, session="s2", model="g", timeout=60),
            solo2)
    finally:
        router.close()
        eng.shutdown()
