"""Mesh-plane parity + robustness suite (ISSUE 9 tentpole).

Three contracts on the rebuilt NamedSharding plane:

1. **Layout parity** — one fit step under each layout (dp / fsdp / tp /
   pipeline) on the forced-8-device CPU mesh matches the plain
   single-device run: allclose where GSPMD inserts collectives, BITWISE
   where the program is identical (same mesh, same placement).
2. **Checkpoint mesh portability** — a unit written on 8 devices
   restores on 4 and on 1 (``restore_checkpoint(mesh=...)`` re-lowers
   the recorded SpecLayout), forward outputs allclose across shapes and
   bitwise on the shape-identical round trip; training resumes.
3. **Mesh-shrink drill** — the ``faultinject.MeshShrink`` scenario
   (kill mid-epoch → checkpoint fallback → MeshPlane rebuild from the
   survivors → resume) is deterministic: reruns produce bitwise-equal
   restored forwards.

Plus the satellite guards: the check_mesh_api lint keeps the repo clean
(and catches crafted violations), the dl4j_mesh_* metric family is
schema-pinned, and /healthz reports the active topology.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import (MeshPlane, SpecLayout,
                                              active_plane, make_mesh)
from deeplearning4j_tpu.parallel.tensor_parallel import (apply_shardings,
                                                         dense_tp_specs)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.zero import apply_fsdp, apply_zero1
from deeplearning4j_tpu.util.sharded_checkpoint import (restore_checkpoint,
                                                        save_checkpoint)
from jax.sharding import NamedSharding, PartitionSpec as P

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _need8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")


def _net(seed=21):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=8, n_out=16))
            .layer(DenseLayer(n_in=16, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(rng, n=32):
    x = rng.standard_normal((n, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return DataSet(x, y)


# ------------------------------------------------------------- SpecLayout

def test_speclayout_roundtrip_and_restriction():
    layout = SpecLayout({"layer0": {"W": P(None, "data"), "b": P("data")},
                         "layer1": {"W": P(("fsdp", "tp"), None)}})
    back = SpecLayout.from_payload(layout.to_payload())
    assert back == layout
    # restriction: a mesh without 'fsdp'/'tp' drops those axes; a dim
    # that stops dividing falls back to replication
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    assert back.restricted_spec("layer0", "W", (8, 16), mesh) == \
        P(None, "data")
    assert back.restricted_spec("layer1", "W", (16, 16), mesh) == P()
    # indivisible: 6 % 4 != 0 → replicated
    assert back.restricted_spec("layer0", "b", (6,), mesh) == P()
    # unknown param → replicated
    assert back.restricted_spec("layerX", "W", (4, 4), mesh) == P()


def test_speclayout_from_live_params():
    _need8()
    net = _net()
    mesh = make_mesh({"data": 8})
    apply_fsdp(net, mesh)
    layout = SpecLayout.from_params(net.params)
    assert layout  # something was sharded
    assert layout.get("layer0", "W") == P(None, "data")
    assert net.mesh_plane is not None
    assert net.mesh_plane.topology()["axes"] == {"data": 8}


# ---------------------------------------------------- layout parity suite

def _one_step_ref(rng_seed=0):
    rng = np.random.default_rng(rng_seed)
    ds = _batch(rng)
    ref = _net()
    ref.fit(ds)
    return ds, np.asarray(ref.params_flat())


def test_parity_dp_one_step():
    """One allreduce fit step over data=8 vs the single-device step."""
    _need8()
    ds, ref_flat = _one_step_ref()
    net = _net()
    pw = ParallelWrapper(net, mesh=MeshPlane.build({"data": 8}))
    pw.fit(ds)
    np.testing.assert_allclose(np.asarray(net.params_flat()), ref_flat,
                               rtol=2e-5, atol=1e-6)


def test_parity_fsdp_one_step():
    _need8()
    ds, ref_flat = _one_step_ref()
    net = _net()
    apply_fsdp(net, make_mesh({"data": 8}))
    net.fit(ds)
    np.testing.assert_allclose(np.asarray(net.params_flat()), ref_flat,
                               rtol=2e-5, atol=1e-6)


def test_parity_tp_one_step():
    _need8()
    ds, ref_flat = _one_step_ref()
    net = _net()
    mesh = make_mesh({"model": 8})
    apply_shardings(net, mesh, dense_tp_specs(["layer0", "layer1"]))
    assert net.mesh_plane is not None  # applier pinned the plane
    net.fit(ds)
    np.testing.assert_allclose(np.asarray(net.params_flat()), ref_flat,
                               rtol=2e-5, atol=1e-6)


def test_parity_pipeline_one_step():
    """One SGD step through the stage pipeline == the sequential stack:
    same loss gradient, same updated stage params (allclose — the
    pipelined program psums over the pp axis)."""
    _need8()
    from deeplearning4j_tpu.parallel.pipeline import pipeline_apply

    p_stages, width, b = 8, 8, 16
    rng = np.random.default_rng(3)
    W = jnp.asarray(rng.standard_normal((p_stages, width, width)) * 0.2,
                    jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, width)), jnp.float32)
    mesh = make_mesh({"pp": p_stages})
    fn = lambda w, h: jnp.tanh(h @ w)

    def loss_pp(W):
        return jnp.sum(pipeline_apply(W, fn, x, mesh, "pp") ** 2)

    def loss_seq(W):
        h = x
        for s in range(p_stages):
            h = fn(W[s], h)
        return jnp.sum(h ** 2)

    lr = 0.01
    g_pp = jax.grad(loss_pp)(W)
    g_seq = jax.grad(loss_seq)(W)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(W - lr * g_pp),
                               np.asarray(W - lr * g_seq),
                               rtol=2e-4, atol=2e-5)


def test_parity_same_mesh_is_bitwise():
    """Where the program IS identical (same mesh, same placement, same
    batch), two runs are bitwise equal — the deterministic half of the
    parity contract."""
    _need8()
    rng = np.random.default_rng(7)
    ds = _batch(rng)
    outs = []
    for _ in range(2):
        net = _net()
        apply_fsdp(net, make_mesh({"data": 8}))
        net.fit(ds)
        outs.append(np.asarray(net.params_flat()))
    np.testing.assert_array_equal(outs[0], outs[1])


# ------------------------------------------- checkpoint mesh portability

def test_checkpoint_mesh_reshape_8_4_1_8(rng, tmp_path):
    """Save FSDP-sharded on 8 devices; restore on 4, on 1, and back on
    8. Forward outputs allclose across mesh shapes, BITWISE on the
    shape-identical round trip; the relayout counter ticks only for the
    actual reshapes; training resumes on the shrunken mesh."""
    _need8()
    from deeplearning4j_tpu.monitor import (MESH_RESTORE_RELAYOUT_COUNTER,
                                            get_registry)

    ds = _batch(rng)
    net = _net()
    net.fit(ds)
    mesh8 = make_mesh({"data": 8})
    apply_fsdp(net, mesh8)
    net.fit(ds)
    ref = np.asarray(net.output(ds.features))
    path = save_checkpoint(net, str(tmp_path / "ckpt"))
    with open(os.path.join(path, "layout.json")) as f:
        layout = json.load(f)
    assert layout["mesh"]["axes"] == {"data": 8}
    assert layout["params"]["layer0"]["W"] == [None, "data"]

    before = get_registry().counter(
        MESH_RESTORE_RELAYOUT_COUNTER, "").value

    mesh4 = make_mesh({"data": 4}, devices=jax.devices()[:4])
    r4 = restore_checkpoint(str(tmp_path / "ckpt"), mesh=mesh4)
    assert r4.params["layer0"]["W"].sharding.spec == P(None, "data")
    assert r4.params["layer0"]["W"].sharding.mesh.shape["data"] == 4
    np.testing.assert_allclose(np.asarray(r4.output(ds.features)), ref,
                               rtol=1e-5, atol=1e-6)

    mesh1 = make_mesh({"data": 1}, devices=jax.devices()[:1])
    r1 = restore_checkpoint(str(tmp_path / "ckpt"), mesh=mesh1)
    np.testing.assert_allclose(np.asarray(r1.output(ds.features)), ref,
                               rtol=1e-5, atol=1e-6)

    r8 = restore_checkpoint(str(tmp_path / "ckpt"), mesh=mesh8)
    np.testing.assert_array_equal(np.asarray(r8.output(ds.features)), ref)

    after = get_registry().counter(MESH_RESTORE_RELAYOUT_COUNTER, "").value
    assert after - before == 2  # 8→4 and 8→1 relayouts; 8→8 is not one

    # the restored-on-4 model trains on and its plane is pinned
    assert r4.mesh_plane is not None
    assert r4.mesh_plane.topology()["axes"] == {"data": 4}
    r4.fit(ds)
    assert np.isfinite(float(r4.score()))


def test_checkpoint_zero1_asymmetric_roundtrip(rng, tmp_path):
    """ZeRO-1 (params replicated, updater sharded) round-trips: the
    updater layout is recorded separately and re-lowered; params stay
    replicated on restore."""
    _need8()
    ds = _batch(rng)
    net = _net()
    net.fit(ds)
    mesh8 = make_mesh({"data": 8})
    apply_zero1(net, mesh8)
    # NOTE: saved BEFORE any further step — a fit would let GSPMD's
    # output-sharding propagation move the updated params to a sharded
    # placement (updater is sharded), which the layout would then
    # truthfully record; the asymmetric ZeRO-1 placement under test is
    # the post-apply state
    ref = np.asarray(net.output(ds.features))
    save_checkpoint(net, str(tmp_path / "z1"))
    with open(str(tmp_path / "z1" / "layout.json")) as f:
        layout = json.load(f)
    assert layout["params"] == {}          # replicated params → empty
    assert layout["updater"]["layer0"]["W"] == [None, "data"]

    mesh4 = make_mesh({"data": 4}, devices=jax.devices()[:4])
    r4 = restore_checkpoint(str(tmp_path / "z1"), mesh=mesh4)
    w = r4.params["layer0"]["W"]
    assert w.sharding.is_fully_replicated
    m = jax.tree.leaves(r4.opt_state["updater"]["layer0"]["W"])[0]
    assert not m.sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(r4.output(ds.features)), ref,
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------- supervisor on shards

def test_supervisor_rollback_on_sharded_pytree(rng):
    """NaN batch under an FSDP-sharded model: the supervisor rolls back
    to the pre-batch snapshot BITWISE and the restored params keep
    their shardings (per-shard capture, no relayout)."""
    _need8()
    from deeplearning4j_tpu.faultinject import FailingDataSetIterator
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.optimize.supervisor import TrainingSupervisor

    ds = _batch(rng, n=64)
    net = _net()
    apply_fsdp(net, make_mesh({"data": 8}))
    net.fit(ds)
    snap_flat = np.asarray(net.params_flat())
    snap_sharding = net.params["layer0"]["W"].sharding

    sup = TrainingSupervisor(net, max_rollbacks=2, enabled=True)
    it = FailingDataSetIterator(ListDataSetIterator(ds, 64), nan_at=(0,))
    it.reset()
    took = sup.step(it.next())
    assert not took and sup.rollbacks == 1
    # bitwise rollback, placement preserved
    np.testing.assert_array_equal(np.asarray(net.params_flat()), snap_flat)
    assert net.params["layer0"]["W"].sharding.spec == snap_sharding.spec
    assert net.params["layer0"]["W"].sharding.mesh.shape == \
        snap_sharding.mesh.shape
    # and the next healthy batch takes
    assert sup.step(ds)


# ---------------------------------------------------- mesh-shrink drill

def _run_shrink_drill(tmp_path, tag, seed=5):
    """One full MeshShrink drill: train FSDP on 8 devices checkpointing
    every step, die mid-epoch, rebuild a plane from the survivors,
    restore the newest unit onto it, return (survivors, restored step,
    post-restore forward bits, resumed forward bits)."""
    from deeplearning4j_tpu.faultinject import ChipFailure, MeshShrink
    from deeplearning4j_tpu.util.sharded_checkpoint import checkpoint_steps

    rng = np.random.default_rng(seed)
    batches = [_batch(rng) for _ in range(6)]
    eval_x = batches[0].features
    ckdir = str(tmp_path / f"drill_{tag}")

    net = _net()
    apply_fsdp(net, make_mesh({"data": 8}))
    ms = MeshShrink(fail_at_step=3, survivors=4, total=8, seed=seed)
    try:
        for i, b in enumerate(batches):
            ms.step()
            net.fit(b)
            save_checkpoint(net, ckdir, keep=3, step=i)
        pytest.fail("drill never fired")
    except ChipFailure as e:
        survivors = [d for d in jax.devices() if d.id in e.survivor_ids]
        small = make_mesh({"data": len(survivors)}, devices=survivors)
        restored = restore_checkpoint(ckdir, mesh=small)
        step = checkpoint_steps(ckdir)[-1]
        fwd = np.asarray(restored.output(eval_x))
        restored.fit(batches[3])  # resume where the dead run stopped
        resumed = np.asarray(restored.output(eval_x))
        return e.survivor_ids, step, fwd, resumed


@pytest.mark.faultinject
def test_mesh_shrink_drill_deterministic(tmp_path):
    """kill → checkpoint fallback → resume on the smaller mesh, twice:
    the survivor set, restored step, restored forward AND the resumed
    forward are bitwise identical across reruns."""
    _need8()
    s1, step1, fwd1, res1 = _run_shrink_drill(tmp_path, "a")
    s2, step2, fwd2, res2 = _run_shrink_drill(tmp_path, "b")
    assert s1 == s2 and len(s1) == 4
    assert step1 == step2 == 2  # failed entering step 3 → newest unit is 2
    np.testing.assert_array_equal(fwd1, fwd2)
    np.testing.assert_array_equal(res1, res2)
    assert np.all(np.isfinite(res1))


# --------------------------------------------------- satellite guards

def test_mesh_api_lint_repo_clean_and_catches_violations(tmp_path):
    lint = _load_script("check_mesh_api")
    root = os.path.dirname(_SCRIPTS)
    assert lint.check_repo(root) == []
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "from jax.sharding import Mesh\n"
        "f = jax.shard_map(lambda x: x, mesh=None, in_specs=None,"
        " out_specs=None)\n"
        "m = Mesh([], ('data',))\n"
        "from jax.experimental.shard_map import shard_map\n")
    problems = lint.check_file(str(bad))
    assert len(problems) == 3
    assert any("jax.shard_map does not exist" in p for p in problems)
    assert any("raw Mesh(...)" in p for p in problems)
    assert any("shard_map import" in p for p in problems)
    good = tmp_path / "good.py"
    good.write_text(
        "from deeplearning4j_tpu.parallel.mesh import make_mesh,"
        " device_collective\n"
        "m = make_mesh({'data': 8})\n")
    assert lint.check_file(str(good)) == []


def test_mesh_metrics_pinned_and_exposed():
    _need8()
    from deeplearning4j_tpu.monitor import get_registry

    schema = _load_script("check_telemetry_schema")
    for name in ("dl4j_mesh_devices", "dl4j_mesh_axis_size",
                 "dl4j_mesh_restore_relayouts_total"):
        assert name in schema.KNOWN_DL4J_METRICS
    MeshPlane.build({"data": 4, "tp": 2})
    text = get_registry().prometheus_text()
    assert 'dl4j_mesh_devices 8' in text
    assert 'dl4j_mesh_axis_size{axis="data"} 4' in text
    assert 'dl4j_mesh_axis_size{axis="tp"} 2' in text
    assert schema.validate_prometheus_text(text) == []


def test_healthz_reports_mesh_topology():
    _need8()
    import urllib.request

    from deeplearning4j_tpu.ui import InMemoryStatsStorage, UiServer

    plane = MeshPlane.build({"data": 8})
    assert active_plane() is plane
    srv = UiServer(InMemoryStatsStorage()).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz") as r:
            body = json.loads(r.read())
        assert body["mesh"]["devices"] == 8
        assert body["mesh"]["axes"] == {"data": 8}
    finally:
        srv.stop()
