"""End-to-end request tracing + SLO attribution (ISSUE 13).

The battery: the tracer primitives (bounded buffers, sampling, flight
recorder), the schema checker's new trace validation (parent
completeness, per-process monotonicity, migration-gap coverage), trace
propagation through every serving layer — local engine, continuous
scheduler, the full router → wire → EngineWorker → scheduler path —
plus the two skew contracts (an untraced/older hop ignores the
``trace`` header and serves correctly; the merged trace degrades to
gappy, never corrupt), the flight-recorder dump firing on endpoint
ejection, the SLO burn counters riding ``fleet_snapshot()``, and the
metric-name AST lint (every in-tree ``dl4j_*`` literal pinned).
"""

import importlib.util
import json
import os
import threading
import time
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import reqtrace

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")
if not os.path.isdir(_SCRIPTS):  # package layout: repo root on path
    _SCRIPTS = os.path.join(os.getcwd(), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name + "_reqtrace_test", os.path.join(_SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


schema = _load_script("check_telemetry_schema")


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


@pytest.fixture
def tracer(fresh_registry):
    prev = reqtrace.request_tracer()
    t = reqtrace.enable_request_tracing()
    yield t
    reqtrace.set_request_tracer(prev)


def _tiny_gpt(vocab=16):
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    return gpt(vocab_size=vocab, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=0).init()


def _clf_net():
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------ tracer primitives

def test_tracer_spans_parents_and_completion(tracer):
    root = tracer.begin_trace("request", kind="test")
    assert root is not None
    child = tracer.start_span("dispatch", root.ctx, endpoint="e0")
    grand = tracer.record_span(child.ctx, "engine_queue", 10.0, 5.0)
    assert grand.trace_id == root.ctx.trace_id
    child.close(outcome="ok")
    tracer.event(root.ctx, "hedge")
    spans = tracer.finish_trace(root, outcome="ok")
    entry = tracer.completed_trace(root.ctx.trace_id)
    assert entry is not None and entry["spans"] == spans
    by_name = {s["name"]: s for s in spans}
    assert by_name["engine_queue"]["parent"] == child.ctx.span_id
    assert by_name["dispatch"]["parent"] == root.ctx.span_id
    assert by_name["request"]["parent"] is None
    assert by_name["hedge"]["dur_us"] == 0.0
    assert schema.validate_trace_spans(spans) == []
    # every span also fed the phase histogram (the SLO half)
    reg = monitor.get_registry()
    assert reg.get(monitor.REQ_PHASE_HISTOGRAM, phase="dispatch").count == 1


def test_tracer_sampling_and_bounds(fresh_registry):
    t = reqtrace.RequestTracer(sample=0.0)
    assert t.begin_trace() is None
    t = reqtrace.RequestTracer(sample=0.5)
    kept = sum(t.begin_trace() is not None for _ in range(200))
    assert 80 <= kept <= 120  # low-discrepancy ≈ the rate
    # span cap: the buffer never outgrows max_spans_per_trace
    t = reqtrace.RequestTracer(max_spans_per_trace=8)
    root = t.begin_trace()
    for _ in range(50):
        t.record_span(root.ctx, "x", 0.0, 1.0)
    spans = t.finish_trace(root)
    assert len(spans) == 8
    assert t.dropped >= 42


def test_wire_context_roundtrip():
    ctx = reqtrace.TraceContext("t1", "s1")
    assert reqtrace.from_wire(ctx.wire()).trace_id == "t1"
    assert reqtrace.from_wire(None) is None
    assert reqtrace.from_wire({"id": 3}) is None  # malformed: ignored


def test_use_trace_thread_local(tracer):
    ctx = reqtrace.TraceContext("t1", "s1")
    assert reqtrace.current_trace() is None
    with reqtrace.use_trace(ctx):
        assert reqtrace.current_trace() is ctx
        seen = []
        th = threading.Thread(
            target=lambda: seen.append(reqtrace.current_trace()))
        th.start()
        th.join()
        assert seen == [None]  # contexts do not leak across threads
    assert reqtrace.current_trace() is None


# ------------------------------------------------- schema checker rules

def _span(trace="t1", span="1-1", parent=None, name="request", ts=0.0,
          dur=10.0, pid=1, tid=1, **attrs):
    rec = {"type": "reqspan", "trace": trace, "span": span,
           "parent": parent, "name": name, "ts_us": ts, "dur_us": dur,
           "pid": pid, "tid": tid}
    if attrs:
        rec["attrs"] = attrs
    return rec


def test_schema_checker_catches_corrupt_traces():
    ok = [_span(), _span(span="1-2", parent="1-1", name="dispatch")]
    assert schema.validate_trace_spans(ok) == []
    orphan = [_span(), _span(span="1-2", parent="1-99", name="dispatch")]
    assert any("orphan" in e for e in schema.validate_trace_spans(orphan))
    two_roots = [_span(), _span(span="1-2", name="dispatch")]
    assert any("root" in e for e in schema.validate_trace_spans(two_roots))
    backwards = [_span(ts=100.0, dur=50.0),
                 _span(span="1-2", parent="1-1", name="dispatch",
                       ts=10.0, dur=5.0)]
    assert any("non-monotonic" in e
               for e in schema.validate_trace_spans(backwards))
    # cross-process skew is NOT an error (different clock origins)
    cross = [_span(ts=100.0, dur=50.0),
             _span(span="2-1", parent="1-1", name="wire_ingress",
                   ts=10.0, dur=5.0, pid=2)]
    assert schema.validate_trace_spans(cross) == []


def test_schema_checker_migration_coverage():
    t0 = 1000.0
    good = [
        _span(ts=0.0, dur=9000.0),
        _span(span="1-2", parent="1-1", name="dispatch", ts=10.0,
              dur=900.0),
        _span(span="1-3", parent="1-1", name="silence_wait", ts=t0,
              dur=2000.0, reason="timeout"),
        _span(span="1-4", parent="1-1", name="repin", ts=t0 + 2000.0,
              dur=500.0),
        _span(span="1-5", parent="1-1", name="dispatch",
              ts=t0 + 2100.0, dur=5000.0, resume_prefix=7),
        _span(span="1-6", parent="1-5", name="prefill",
              ts=t0 + 3000.0, dur=800.0, resume=True),
        _span(span="1-7", parent="1-5", name="decode_burst",
              ts=t0 + 4000.0, dur=400.0),
    ]
    assert schema.validate_migration_coverage(good) == []
    no_silence = [s for s in good if s["name"] != "silence_wait"]
    assert any("silence_wait" in e
               for e in schema.validate_migration_coverage(no_silence))
    # a HOLE between silence end and the resume machinery is flagged:
    # the silence span ends 1.9ms before the repin starts and nothing
    # covers the interval
    holey = [dict(s) for s in good]
    holey[2] = dict(holey[2], dur_us=100.0)  # silence ends early
    assert any("hole" in e
               for e in schema.validate_migration_coverage(
                   holey, tol_us=500.0))


def test_flight_dump_jsonl_schema(tmp_path, tracer):
    fr = reqtrace.configure_flight_recorder(dump_dir=str(tmp_path))
    root = tracer.begin_trace("request", kind="test")
    tracer.record_span(root.ctx, "dispatch", 0.0, 5.0, endpoint="e0")
    tracer.finish_trace(root, outcome="ok")
    fr.note_event("ejection", endpoint="e0")
    path = fr.trigger("ejection", endpoint="e0")
    assert path is not None and os.path.exists(path)
    assert schema.validate_flight_file(path) == []
    # the sniffing entry point routes .jsonl flight dumps correctly
    assert schema.validate_jsonl_file(path) == []
    lines = [json.loads(l) for l in open(path) if l.strip()]
    kinds = {r.get("kind") for r in lines if r["type"] == "flight_event"}
    assert "ejection" in kinds and "trigger" in kinds
    assert any(r["type"] == "trace" for r in lines)


# -------------------------------------------------- engine-level traces

def test_engine_classify_spans_under_ambient_context(tracer):
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    net = _clf_net()
    eng = ParallelInference(net, replicas=1)
    try:
        root = tracer.begin_trace("request", kind="classify")
        with reqtrace.use_trace(root.ctx):
            fut = eng.submit(np.zeros((1, 4), np.float32))
        fut.result(30)
        spans = tracer.finish_trace(root)
        names = [s["name"] for s in spans]
        assert "engine_queue" in names and "engine_dispatch" in names
        assert schema.validate_trace_spans(spans) == []
    finally:
        eng.shutdown()


def test_continuous_scheduler_self_roots_and_decomposes(tracer):
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    eng = ParallelInference(_tiny_gpt(), replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4)
    try:
        chunks = []
        fut = eng.submit_generate(
            np.arange(1, 6)[None], 8,
            on_tokens=lambda off, t: chunks.append((off, list(t))))
        fut.result(60)
        tid = fut.trace_id
        entry = tracer.completed_trace(tid)
        assert entry is not None
        names = [s["name"] for s in entry["spans"]]
        for want in ("queue_wait", "prefill", "decode_burst",
                     "chunk_deliver", "decode_request"):
            assert want in names, names
        assert schema.validate_trace_spans(entry["spans"]) == []
        assert entry["attrs"]["outcome"] == "ok"
        assert entry["attrs"]["ttft_ms"] > 0
        # burst spans carry the ladder attributes the issue pins
        burst = next(s for s in entry["spans"]
                     if s["name"] == "decode_burst")
        assert "slot_bucket" in burst["attrs"] and "tier" in burst["attrs"]
    finally:
        eng.shutdown()


def test_multi_row_request_trace_stays_monotonic(tracer):
    """Both rows of one request share one trace; the scheduler's
    two-pass admission recording keeps the span stream close-order
    monotonic (the per-process rule the schema checker enforces)."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    eng = ParallelInference(_tiny_gpt(), replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4)
    try:
        prompt = np.tile(np.arange(1, 6)[None], (2, 1))
        fut = eng.submit_generate(prompt, 6)
        fut.result(60)
        entry = tracer.completed_trace(fut.trace_id)
        assert entry is not None
        assert schema.validate_trace_spans(entry["spans"]) == []
        rows = {(s.get("attrs") or {}).get("row")
                for s in entry["spans"] if s["name"] == "queue_wait"}
        assert rows == {0, 1}
    finally:
        eng.shutdown()


# ------------------------------------------- router + wire, end to end

def _fleet(engine_factory, **router_kw):
    from deeplearning4j_tpu.serving import InferenceRouter, LocalFleet
    router = InferenceRouter(per_try_timeout_s=5.0, eject_backoff_s=0.2,
                             **router_kw)
    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=5.0, heartbeat_timeout_s=0.5)
    return router, fleet


def test_router_wire_trace_merges_across_hops(tracer):
    """The full path — router admission → wire header → EngineWorker →
    continuous scheduler — yields ONE merged parent-complete trace with
    the admission decision, the dispatch, the wire hop and the
    engine-side decomposition all present."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    def factory():
        return ParallelInference(_tiny_gpt(), replicas=1,
                                 continuous=True, decode_slots=4,
                                 decode_burst=4, kv_block_size=4)

    router, fleet = _fleet(factory)
    try:
        fleet.add_endpoint()
        assert fleet.wait_ready(30)
        toks = []
        fut = router.submit_generate(
            np.arange(1, 6)[None], 6, session="s0",
            on_tokens=lambda off, t: toks.append(list(t)))
        fut.result(60)
        entry = tracer.completed_trace(fut.trace_id)
        assert entry is not None
        spans = entry["spans"]
        names = [s["name"] for s in spans]
        for want in ("admission", "dispatch", "wire_ingress",
                     "queue_wait", "prefill", "decode_burst"):
            assert want in names, names
        assert schema.validate_trace_spans(spans) == []
        adm = next(s for s in spans if s["name"] == "admission")
        assert adm["attrs"]["decision"] == "admitted"
        assert "est_wait_ms" in adm["attrs"]
        # the wire hop's span parents to the router's dispatch span
        wire_span = next(s for s in spans if s["name"] == "wire_ingress")
        disp = next(s for s in spans if s["name"] == "dispatch")
        assert wire_span["parent"] == disp["span"]
    finally:
        fleet.shutdown(drain=False)
        router.close()


class _StubEngine:
    """A minimal engine with NO tracing awareness — stands in for a
    worker built before the trace header existed."""

    def __init__(self):
        self._closed = False

    def submit(self, x, **kw):
        fut = Future()
        fut.set_result(np.asarray(x) * 2.0)
        return fut

    def stats(self):
        return {"queue_depth": 0, "resolved": 1}

    def drain(self, timeout=None):
        return True

    def shutdown(self, **kw):
        self._closed = True


def test_wire_skew_traced_request_to_untraced_worker(fresh_registry):
    """A traced request frame (trace field in the header) reaching a
    worker whose engine predates tracing is served correctly — the
    field is ignored, never fatal (same discipline as every other
    optional header field)."""
    from deeplearning4j_tpu.serving import wire
    from deeplearning4j_tpu.serving.worker import EngineWorker
    from deeplearning4j_tpu.streaming.broker import InMemoryBroker

    assert reqtrace.request_tracer() is None  # worker side: tracing OFF
    broker = InMemoryBroker()
    worker = EngineWorker(_StubEngine(), broker, "svc", poll_s=0.01)
    try:
        frame = wire.pack_request(
            "c1", "svc.rsp.test", wire.KIND_CLASSIFY,
            np.ones((1, 3), np.float32),
            trace={"id": "t-newer-router", "span": "1-7"})
        broker.publish("svc.req", frame)
        deadline = time.monotonic() + 10
        msg = None
        while msg is None and time.monotonic() < deadline:
            msg = broker.consume("svc.rsp.test", timeout=0.05)
        assert msg is not None, "worker never replied to a traced frame"
        header, result = wire.unpack_reply(msg)
        assert header["ok"] and np.allclose(result, 2.0)
    finally:
        worker.kill()


def test_untraced_hop_yields_gappy_not_corrupt_trace(tracer):
    """A traced request through an endpoint that propagates nothing
    (older hop) still completes a VALID trace — router spans only,
    parent-complete, just without engine-side decomposition."""
    from deeplearning4j_tpu.serving import InferenceRouter
    from deeplearning4j_tpu.serving.endpoint import EngineEndpoint

    class _PlainEndpoint(EngineEndpoint):
        name = "plain"

        def submit(self, x, timeout_s=None, **kw):
            fut = Future()
            fut.set_result(np.asarray(x) + 1.0)
            return fut

        def stats(self):
            return {"queue_depth": 0}

        def alive(self):
            return True

        @property
        def last_seen(self):
            return time.monotonic()

    router = InferenceRouter([_PlainEndpoint()])
    try:
        fut = router.submit(np.zeros((1, 2), np.float32))
        fut.result(10)
        entry = tracer.completed_trace(fut.trace_id)
        names = [s["name"] for s in entry["spans"]]
        assert "admission" in names and "dispatch" in names
        assert "engine_queue" not in names  # the hop is gappy...
        assert schema.validate_trace_spans(entry["spans"]) == []  # ...not corrupt
    finally:
        router.close()


def test_flight_dump_fires_on_ejection(tmp_path, tracer):
    """Endpoint ejection is a flight-recorder trigger: with a dump_dir
    armed, the rings land as schema-valid JSONL naming the ejected
    endpoint."""
    from deeplearning4j_tpu.serving import InferenceRouter
    from deeplearning4j_tpu.serving.endpoint import (EndpointError,
                                                     EngineEndpoint)

    reqtrace.configure_flight_recorder(dump_dir=str(tmp_path))

    class _FailingEndpoint(EngineEndpoint):
        name = "bad"

        def submit(self, x, timeout_s=None, **kw):
            raise EndpointError("injected")

        def stats(self):
            return {}

        def alive(self):
            return True

        @property
        def last_seen(self):
            return time.monotonic()

    router = InferenceRouter([_FailingEndpoint()], eject_threshold=2,
                             max_attempts=1)
    try:
        for _ in range(2):
            with pytest.raises(BaseException):
                router.submit(np.zeros((1, 2), np.float32)).result(5)
        dumps = sorted(tmp_path.glob("flight-*.jsonl"))
        assert dumps, "ejection did not dump the flight recorder"
        assert schema.validate_flight_file(str(dumps[-1])) == []
        recs = [json.loads(l) for l in open(dumps[-1]) if l.strip()]
        trig = [r for r in recs if r["type"] == "flight_event"
                and r.get("kind") == "trigger"]
        assert any(t["attrs"]["reason"] == "ejection"
                   and t["attrs"]["endpoint"] == "bad" for t in trig)
        reg = monitor.get_registry()
        assert reg.family_total(monitor.TRACE_FLIGHT_DUMPS_COUNTER) >= 1
    finally:
        router.close()
        reqtrace.configure_flight_recorder()  # drop the tmp dump_dir


def test_slo_burn_and_fleet_snapshot(tracer):
    """Deadline verdicts and admission sheds feed the per-model SLO
    burn counter; ``fleet_snapshot()['slo']`` surfaces burn, TTFT and
    the phase decomposition."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import InferenceRouter, RetryAfter
    from deeplearning4j_tpu.serving.endpoint import LocalEndpoint

    eng = ParallelInference(_clf_net(), replicas=1)
    router = InferenceRouter([LocalEndpoint(eng, "e0")])
    try:
        router.submit(np.zeros((1, 4), np.float32),
                      deadline_ms=60_000).result(30)
        snap = router.fleet_snapshot()
        assert snap["slo"]["burn"]["default"].get("met", 0) == 1
        assert "admission" in snap["slo"]["phases"]
        assert snap["slo"]["ttft_ms"]["default"]["count"] >= 1
    finally:
        eng.shutdown()
        router.close()
    empty = InferenceRouter([])
    try:
        with pytest.raises(RetryAfter):
            empty.submit(np.zeros((1, 4), np.float32))
        snap = empty.fleet_snapshot()
        assert snap["slo"]["burn"]["default"].get("shed", 0) == 1
        assert snap["slo"]["burned"] >= 1
    finally:
        empty.close()


def test_debug_traces_endpoint(tracer):
    """UiServer /debug/traces serves the flight recorder rings as
    schema-valid JSONL."""
    from deeplearning4j_tpu.ui import InMemoryStatsStorage, UiServer

    reqtrace.configure_flight_recorder()
    root = tracer.begin_trace("request", kind="debug")
    tracer.record_span(root.ctx, "dispatch", 0.0, 1.0)
    tracer.finish_trace(root, outcome="ok")
    reqtrace.flight_event("quarantine", replica=0)
    srv = UiServer(InMemoryStatsStorage(), port=0).start()
    try:
        with urllib.request.urlopen(srv.url + "/debug/traces",
                                    timeout=5) as r:
            body = r.read().decode()
        assert schema.validate_flight_lines(body.splitlines()) == []
        assert '"quarantine"' in body and '"trace"' in body
    finally:
        srv.stop()


# -------------------------------------------------- metric-name lint

def test_metric_name_lint_repo_clean_and_catches(tmp_path):
    lint = _load_script("check_metric_names")
    root = os.path.dirname(_SCRIPTS)
    assert lint.check_repo(root) == []
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from deeplearning4j_tpu.monitor import get_registry\n"
        "get_registry().counter('dl4j_totally_new_total', 'x').inc()\n")
    errs = lint.check_file(str(bad), "bad.py")
    assert len(errs) == 1 and "dl4j_totally_new_total" in errs[0]
    # allowlisted non-metric literals and dash-named topics pass
    ok = tmp_path / "ok.py"
    ok.write_text("MAGIC = 'dl4j_tpu_dataset_export_v1'\n"
                  "TOPIC = 'dl4j-tpu-worker'\n")
    assert lint.check_file(str(ok), "ok.py") == []
