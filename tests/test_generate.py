"""Fused autoregressive generation tests (nn/generate.py).

The ISSUE-5 battery: greedy fused == per-token eager reference
token-for-token (transformer AND LSTM, including MoE no-drop routing
and decode_step-vs-forward prefix parity), seeded sampler determinism,
EOS early-exit, the bucketed-prefill single-compile contract,
submit_generate concurrent identity + the shutdown race, and the
dl4j_decode_* schema pinning.
"""

import threading

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
    SequenceEmbeddingLayer,
    TransformerBlock,
)
from deeplearning4j_tpu.nn.generate import (
    build_generator,
    generate,
    generate_eager,
)
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph,
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _tiny_gpt(vocab=11, d=16, layers=2, max_len=32, **kw):
    return gpt(vocab_size=vocab, d_model=d, n_layers=layers, num_heads=2,
               max_len=max_len, compute_dtype="float32",
               learning_rate=0.01, **kw).init()


def _char_rnn(vocab=13, hidden=16, seed=1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.01).updater("adam")
            .activation("tanh")
            .list()
            .layer(GravesLSTM(n_in=vocab, n_out=hidden))
            .layer(GravesLSTM(n_in=hidden, n_out=hidden))
            .layer(RnnOutputLayer(n_in=hidden, n_out=vocab,
                                  activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _full_forward_oracle(net, prompt, max_new):
    """The strongest greedy reference: re-run the whole net on the
    growing window, one O(t²) forward per token."""
    want = np.asarray(prompt, np.int64)
    for _ in range(max_new):
        logits = net.output(want.astype(np.float32))
        nxt = np.argmax(logits[:, -1], axis=-1)
        want = np.concatenate([want, nxt[:, None]], axis=1)
    return want


# ------------------------------------------------------- greedy parity

def test_greedy_matches_eager_and_full_forward(rng):
    net = _tiny_gpt()
    prompt = rng.integers(0, 11, (2, 3))
    fused = net.generate(prompt, 8)
    assert np.array_equal(fused, generate_eager(net, prompt, 8))
    assert np.array_equal(fused, _full_forward_oracle(net, prompt, 8))


def test_moe_no_drop_decode_matches_forward(rng):
    """decode_step/prefill must match forward at every prefix INCLUDING
    MoE routing: with capacity_factor == num_experts the training-time
    forward routes no-drop, exactly the decode-time policy."""
    net = _tiny_gpt(layers=1, num_experts=2, capacity_factor=2.0)
    prompt = rng.integers(0, 11, (3, 4))
    fused = net.generate(prompt, 6)
    assert np.array_equal(fused, _full_forward_oracle(net, prompt, 6))
    assert np.array_equal(fused, generate_eager(net, prompt, 6))


def test_lstm_greedy_matches_rnn_time_step(rng):
    net = _char_rnn()
    prompt = rng.integers(0, 13, (3, 5))
    fused = net.generate(prompt, 7)
    assert np.array_equal(fused, generate_eager(net, prompt, 7))
    # oracle: the stateful rnnTimeStep streaming loop
    net.rnn_clear_previous_state()
    burst = net.rnn_time_step(np.eye(13, dtype=np.float32)[prompt])
    tok = np.argmax(burst[:, -1], axis=-1)
    toks = [tok]
    for _ in range(6):
        out = net.rnn_time_step(np.eye(13, dtype=np.float32)[tok])
        tok = np.argmax(out, axis=-1)
        toks.append(tok)
    want = np.concatenate([prompt, np.stack(toks, axis=1)], axis=1)
    assert np.array_equal(fused, want)


def test_cg_generate_linear_chain(rng):
    base = (NeuralNetConfiguration.builder().seed(2).learning_rate(0.1)
            .updater("adam").activation("identity").build())
    conf = (ComputationGraphConfiguration.builder(base)
            .add_inputs("ids")
            .add_layer("emb", SequenceEmbeddingLayer(n_in=11, n_out=16,
                                                     max_len=32), "ids")
            .add_layer("blk", TransformerBlock(n_in=16, n_out=16,
                                               num_heads=2, causal=True),
                       "emb")
            .add_layer("lm", RnnOutputLayer(n_in=16, n_out=11,
                                            activation="softmax",
                                            loss_function="mcxent"), "blk")
            .set_outputs("lm").build())
    cg = ComputationGraph(conf).init()
    prompt = rng.integers(0, 11, (2, 4))
    got = cg.generate(prompt, 6)
    want = np.asarray(prompt, np.int64)
    for _ in range(6):
        logits = cg.outputs(want.astype(np.float32))[0]
        nxt = np.argmax(logits[:, -1], axis=-1)
        want = np.concatenate([want, nxt[:, None]], axis=1)
    assert np.array_equal(got, want)


def test_generate_rejects_unsupported(rng):
    conf = (NeuralNetConfiguration.builder()
            .seed(0).learning_rate(0.1).updater("sgd").activation("relu")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="generate"):
        build_generator(net)
    with pytest.raises(ValueError, match="max_len"):
        _tiny_gpt(max_len=8).generate(rng.integers(0, 11, (1, 4)), 100)


# ------------------------------------------------------------ sampling

def test_seeded_sampling_determinism(rng):
    net = _tiny_gpt()
    prompt = rng.integers(0, 11, (4, 3))
    for kw in ({"temperature": 1.0},
               {"temperature": 0.8, "top_k": 4},
               {"temperature": 1.0, "top_p": 0.8}):
        a = net.generate(prompt, 5, seed=7, **kw)
        b = net.generate(prompt, 5, seed=7, **kw)
        np.testing.assert_array_equal(a, b)
        assert (a[:, 3:] >= 0).all() and (a[:, 3:] < 11).all()
        # the eager per-token path replays the same per-row PRNG
        # schedule — sampled decode agrees token-for-token too
        e = generate_eager(net, prompt, 5, seed=7, **kw)
        np.testing.assert_array_equal(a, e)
    # top-k=1 degenerates to greedy at any temperature
    np.testing.assert_array_equal(
        net.generate(prompt, 5, temperature=9.0, top_k=1),
        net.generate(prompt, 5))
    # a different seed moves at least one sampled token at temp 1.5
    a = net.generate(prompt, 8, temperature=1.5, seed=1)
    b = net.generate(prompt, 8, temperature=1.5, seed=2)
    assert not np.array_equal(a, b)


def test_eos_early_exit(rng):
    net = _tiny_gpt()
    prompt = rng.integers(0, 11, (2, 3))
    plain = net.generate(prompt, 8)
    # pick the token row 0 emits at its second step as the EOS id
    eos = int(plain[0, 4])
    out = net.generate(prompt, 8, eos_token=eos)
    gen = out[:, 3:]
    for row in gen:
        hits = np.nonzero(row == eos)[0]
        if hits.size:  # everything after the first EOS is EOS fill
            assert (row[hits[0]:] == eos).all()
    assert (out[0, 4:] == eos).all()  # row 0 finished at its 2nd token
    # tokens BEFORE the eos are unchanged vs the unconstrained run
    first = np.nonzero(gen[0] == eos)[0][0]
    np.testing.assert_array_equal(gen[0][:first], plain[0, 3:3 + first])
    # eager reference implements the identical EOS fill
    np.testing.assert_array_equal(
        out, generate_eager(net, prompt, 8, eos_token=eos))


# ----------------------------------------------------- bucketed prefill

def test_bucketed_prefill_single_compile(rng):
    """Prompt lengths inside one bucket share ONE compiled prefill (the
    length is a traced per-row vector), and re-running a shape is a
    pure cache hit: zero new jit misses."""
    net = _tiny_gpt(max_len=64)
    reg = monitor.get_registry()
    net.generate(rng.integers(0, 11, (2, 5)), 4)   # bucket 8, compiles
    before = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
    net.generate(rng.integers(0, 11, (2, 6)), 4)   # same bucket 8
    net.generate(rng.integers(0, 11, (2, 8)), 4)   # still bucket 8
    net.generate(rng.integers(0, 11, (2, 5)), 4)   # repeat
    assert reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) == before
    # a different bucket (or max_new) is a fresh program pair
    net.generate(rng.integers(0, 11, (2, 9)), 4)   # bucket 16
    assert reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) > before


def test_decode_metrics_and_schema(rng):
    import importlib.util
    import os

    _script = os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                           "check_telemetry_schema.py")
    _spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema_gen", _script)
    sch = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(sch)

    for name in ("dl4j_decode_requests_total",
                 "dl4j_decode_prefill_tokens_total",
                 "dl4j_decode_tokens_total",
                 "dl4j_decode_prefill_latency_ms",
                 "dl4j_decode_latency_ms"):
        assert name in sch.KNOWN_DL4J_METRICS, name
    prev = monitor.set_registry(monitor.MetricsRegistry())
    try:
        net = _tiny_gpt()
        net.generate(rng.integers(0, 11, (2, 3)), 4)
        reg = monitor.get_registry()
        assert reg.family_total(monitor.DECODE_REQUESTS_COUNTER) == 1
        assert reg.family_total(monitor.DECODE_PREFILL_TOKENS_COUNTER) == 6
        assert reg.family_total(monitor.DECODE_TOKENS_COUNTER) == 8
        text = reg.prometheus_text()
        assert sch.validate_prometheus_text(text) == []
        assert sch.validate_known_metrics(text) == []
    finally:
        monitor.set_registry(prev)


# -------------------------------------------------------- served decode

def test_submit_generate_concurrent_identity(rng):
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    net = _tiny_gpt()
    dev = jax.devices()[0]
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=2.0,
                            devices=[dev, dev])
    try:
        compiled = eng.warmup_generate([3, 5], max_new_tokens=6)
        assert compiled > 0
        prompts = [rng.integers(0, 11, (2, 3)),
                   rng.integers(0, 11, (1, 5)),
                   rng.integers(0, 11, (2, 4))]
        solo = [net.generate(p, 6) for p in prompts]
        reg = monitor.get_registry()
        before = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        errors = []

        def worker(i):
            try:
                got = eng.generate(prompts[i % 3], 6, timeout=60)
                if not np.array_equal(got, solo[i % 3]):
                    raise AssertionError(f"row identity broke for {i}")
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # warmup covered every (bucket, rows, replica): steady-state
        # served decode performs zero XLA compiles
        assert reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) == before
        # sampled requests are coalescing-invariant too (per-row keys)
        s_solo = net.generate(prompts[0], 6, temperature=1.0, seed=9)
        s_served = eng.generate(prompts[0], 6, temperature=1.0, seed=9,
                                timeout=60)
        np.testing.assert_array_equal(s_solo, s_served)
    finally:
        eng.shutdown()


def test_submit_generate_shutdown_race(rng):
    """A submit_generate racing shutdown must never strand its Future:
    it resolves with tokens or raises the shutdown error."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    net = _tiny_gpt()
    for _ in range(3):
        eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0)
        prompt = rng.integers(0, 11, (1, 3))
        futs = [eng.submit_generate(prompt, 4) for _ in range(4)]
        stopper = threading.Thread(target=eng.shutdown)
        stopper.start()
        racing = []
        try:
            racing.append(eng.submit_generate(prompt, 4))
        except RuntimeError:
            pass  # already closed — acceptable side of the race
        stopper.join()
        for f in futs + racing:
            try:
                out = f.result(timeout=30)
                assert out.shape == (1, 7)
            except RuntimeError:
                pass  # resolved with the shutdown error, not stranded
    # after shutdown, submit_generate raises cleanly
    with pytest.raises(RuntimeError, match="shut down"):
        eng.submit_generate(prompt, 4)


def test_submit_generate_lstm(rng):
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    net = _char_rnn()
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=2.0)
    try:
        prompt = rng.integers(0, 13, (2, 4))
        solo = net.generate(prompt, 5)
        np.testing.assert_array_equal(eng.generate(prompt, 5, timeout=60),
                                      solo)
    finally:
        eng.shutdown()


# ----------------------------------------------- CG scanned rnn parity

def test_cg_rnn_time_step_is_scanned(rng):
    """The DAG rnn_time_step now runs one XLA program per burst (the
    MLN doctrine): step-by-step and burst outputs agree, and the
    compiled pair is cached on the graph."""
    base = (NeuralNetConfiguration.builder().seed(2).learning_rate(0.1)
            .updater("adam").activation("tanh").build())
    conf = (ComputationGraphConfiguration.builder(base)
            .add_inputs("in")
            .add_layer("l1", GravesLSTM(n_in=5, n_out=8), "in")
            .add_layer("l2", GravesLSTM(n_in=8, n_out=8), "l1")
            .add_layer("out", RnnOutputLayer(n_in=8, n_out=2,
                                             activation="softmax",
                                             loss_function="mcxent"), "l2")
            .set_outputs("out").build())
    g = ComputationGraph(conf).init()
    x = rng.standard_normal((4, 6, 5)).astype(np.float32)
    steps = [g.rnn_time_step(x[:, t])[0] for t in range(6)]
    g.rnn_clear_previous_state()
    burst = g.rnn_time_step(x)[0]
    assert burst.shape == (4, 6, 2)
    for t in range(6):
        np.testing.assert_allclose(burst[:, t], steps[t],
                                   rtol=1e-5, atol=1e-6)
    assert ("rnn_step",) in g._jits
    # state carries across bursts: same input, advanced state
    o1 = g.rnn_time_step(x[:, :1])
    o2 = g.rnn_time_step(x[:, :1])
    assert np.abs(o1[0] - o2[0]).max() > 0
