"""Multi-model serving + zero-downtime model lifecycle tests
(serving/registry.py + the registry-mode ParallelInference).

The ISSUE-7 battery, all deterministic (explicit fault seams, bounded
spins on observable state, no blind sleeps in assertions):

- registry-mode routing is bitwise each model's inline run; batches
  never mix models;
- per-model bucket ladders + ``warmup_model`` → zero steady-state XLA
  compiles;
- deficit-weighted round-robin keeps a hot model from starving its
  cotenants (unit-level DRR ordering + an integration flood);
- device-memory budget: LRU/priority eviction with lazy reload from
  the PR-4 checkpoint format;
- **zero-downtime deploy**: atomic cutover under load, instant
  rollback, corrupt-checkpoint deploys rejected while the old version
  keeps serving;
- **canary**: deterministic fraction routing, promote, NaN-output and
  error-rate auto-rollback (the poisoned-canary acceptance scenario);
- **isolation**: ``faultinject.poison_model`` opens the per-model
  circuit breaker — cotenants serve bitwise throughout, submits fail
  fast with ``ModelQuarantined``, and a probe heals the model;
- session version pinning across a cutover (a decode stream finishes
  on the version it started on; new sessions get the new version);
- model/version routing across the ``serving/wire.py`` boundary +
  ``/healthz/ready`` per-model readiness;
- ``dl4j_model_*`` Prometheus schema pinning;
- satellite guards: the donation-gate lint is clean over the repo (and
  catches a crafted violation), and the fault-injection stress quick
  check is deterministic.
"""

import importlib.util
import json
import os
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import poison_model, poison_replica
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.inference import (ParallelInference,
                                                   _FairBatchQueue)
from deeplearning4j_tpu.serving import (ModelQuarantined, ModelRegistry,
                                        ModelUnavailable)
from deeplearning4j_tpu.util.model_serializer import (CheckpointCorruptError,
                                                      write_model)

pytestmark = pytest.mark.faultinject

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


N_IN, N_OUT = 6, 3


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _spin_until(cond, timeout=60.0, tick=0.005):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() >= deadline:
            return False
        time.sleep(tick)
    return True


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def _mk_engine(reg, **kw):
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("max_latency_ms", 1.0)
    kw.setdefault("replicas", 1)
    return ParallelInference(registry=reg, **kw)


# ------------------------------------------------------------- routing

def test_multi_model_routing_bitwise(rng, fresh_registry):
    a, b = _net(1), _net(2)
    reg = ModelRegistry()
    reg.register("a", net=a)
    reg.register("b", net=b)
    eng = _mk_engine(reg)
    try:
        x = rng.standard_normal((16, N_IN)).astype(np.float32)
        futs = []
        for i in range(8):
            futs.append(("a", x[i:i + 2], eng.submit(x[i:i + 2], model="a")))
            futs.append(("b", x[i:i + 2], eng.submit(x[i:i + 2], model="b")))
        for name, rows, fut in futs:
            inline = np.asarray((a if name == "a" else b).output(rows))
            np.testing.assert_array_equal(fut.result(timeout=30), inline)
    finally:
        eng.shutdown()


def test_registry_mode_requires_model_and_legacy_rejects_model(rng):
    reg = ModelRegistry()
    reg.register("a", net=_net(1))
    eng = _mk_engine(reg)
    try:
        with pytest.raises(ValueError, match="requires model="):
            eng.submit(np.zeros((1, N_IN), np.float32))
        with pytest.raises(ModelUnavailable):
            eng.submit(np.zeros((1, N_IN), np.float32), model="nope")
    finally:
        eng.shutdown()
    legacy = ParallelInference(_net(1), replicas=1)
    try:
        with pytest.raises(ValueError, match="registry"):
            legacy.submit(np.zeros((1, N_IN), np.float32), model="a")
    finally:
        legacy.shutdown()


def test_per_model_buckets_and_warmup_zero_steady_state_compiles(
        rng, fresh_registry):
    reg = ModelRegistry()
    reg.register("a", net=_net(1), warm_shapes=[(N_IN,)], buckets=(2, 4))
    eng = _mk_engine(reg)
    try:
        compiled = eng.warmup_model("a")
        assert compiled > 0
        before = fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        for n in (1, 2, 3, 4, 1):
            eng.output(rng.standard_normal((n, N_IN)).astype(np.float32),
                       model="a", timeout=30)
        assert fresh_registry.family_total(
            monitor.JIT_CACHE_MISS_COUNTER) == before
    finally:
        eng.shutdown()


# ------------------------------------------------------ fair scheduling

class _FakeBatch:
    def __init__(self, model, rows, tag):
        self.model = model
        self.rows = rows
        self.tag = tag


def test_fair_queue_interleaves_hot_and_cold_models():
    q = _FairBatchQueue(quantum=4)
    for i in range(10):
        q.put(_FakeBatch("hot", 4, f"h{i}"))
    q.put(_FakeBatch("cold", 4, "c0"))
    q.put(_FakeBatch("cold", 4, "c1"))
    order = [q.get().tag for _ in range(12)]
    # DRR: the cold model's two batches must NOT wait out the hot
    # model's entire backlog — both land in the first half
    assert order.index("c0") < 6 and order.index("c1") < 6
    # single-model degenerates to FIFO
    q2 = _FairBatchQueue(quantum=4)
    for i in range(5):
        q2.put(_FakeBatch("only", 4, f"b{i}"))
    assert [q2.get().tag for _ in range(5)] == [f"b{i}" for i in range(5)]


def test_fair_queue_respects_weights():
    weights = {"heavy": 2.0, "light": 1.0}
    q = _FairBatchQueue(quantum=4, weight_of=lambda m: weights[m])
    for i in range(8):
        q.put(_FakeBatch("heavy", 4, f"H{i}"))
        q.put(_FakeBatch("light", 4, f"L{i}"))
    first8 = [q.get().tag for _ in range(8)]
    h = sum(1 for t in first8 if t.startswith("H"))
    l8 = sum(1 for t in first8 if t.startswith("L"))
    # 2:1 weighting: heavy gets about twice the early service
    assert h > l8


def test_hot_model_cannot_starve_cotenant(rng, fresh_registry):
    a, b = _net(1), _net(2)
    reg = ModelRegistry()
    reg.register("hot", net=a)
    reg.register("cold", net=b)
    eng = _mk_engine(reg, max_latency_ms=0.0, queue_capacity=4096)
    try:
        x = rng.standard_normal((4, N_IN)).astype(np.float32)
        hot_futs = [eng.submit(x, model="hot") for _ in range(200)]
        cold_futs = [eng.submit(x, model="cold") for _ in range(5)]
        # every cold future resolves even while the hot flood drains
        for f in cold_futs:
            np.testing.assert_array_equal(f.result(timeout=60),
                                          np.asarray(b.output(x)))
        for f in hot_futs:
            f.result(timeout=60)
    finally:
        eng.shutdown()


# ------------------------------------------------- memory budget / LRU

def test_memory_budget_evicts_lru_and_reloads_lazily(rng, tmp_path,
                                                     fresh_registry):
    a, b, c = _net(1), _net(2), _net(3)
    zip_a = str(tmp_path / "a.zip")
    write_model(a, zip_a)
    from deeplearning4j_tpu.serving.registry import _tree_nbytes
    size = _tree_nbytes(a.params)
    reg = ModelRegistry(memory_budget_bytes=int(size * 2.5))
    reg.register("a", net=None, path=zip_a)   # checkpoint-backed
    reg.register("b", net=b)
    reg.register("c", net=c)
    eng = _mk_engine(reg)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        ya1 = eng.output(x, model="a", timeout=30)
        eng.output(x, model="b", timeout=30)
        # pinning c exceeds the budget: a (least-recently-used) evicts
        eng.output(x, model="c", timeout=30)
        assert fresh_registry.counter(
            monitor.MODEL_EVICTIONS_COUNTER, "", model="a").value >= 1
        assert not reg.version("a", 1).pins
        # evicted + checkpoint-backed → lazy reload on next use, same
        # results bitwise
        ya2 = eng.output(x, model="a", timeout=30)
        np.testing.assert_array_equal(ya1, ya2)
        assert reg.pinned_bytes() <= int(size * 2.5)
    finally:
        eng.shutdown()


def test_priority_orders_eviction_before_recency(rng, tmp_path,
                                                 fresh_registry):
    a, b, c = _net(1), _net(2), _net(3)
    zip_low = str(tmp_path / "low.zip")
    write_model(a, zip_low)
    from deeplearning4j_tpu.serving.registry import _tree_nbytes
    size = _tree_nbytes(a.params)
    reg = ModelRegistry(memory_budget_bytes=int(size * 2.5))
    reg.register("low", path=zip_low, priority=0)
    reg.register("high", net=b, priority=10)
    reg.register("third", net=c, priority=0)
    eng = _mk_engine(reg)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        eng.output(x, model="high", timeout=30)
        eng.output(x, model="low", timeout=30)
        # pinning "third" must evict: "high" is the LRU pin but its
        # priority protects it — the fresher low-priority pin goes
        eng.output(x, model="third", timeout=30)
        assert reg.version("high", 1).pins
        assert not reg.version("low", 1).pins
    finally:
        eng.shutdown()


# --------------------------------------------------- deploy / rollback

def test_deploy_cutover_is_atomic_and_rollback_instant(rng, fresh_registry):
    v1net, v2net = _net(1), _net(4)
    reg = ModelRegistry()
    reg.register("m", net=v1net, warm_shapes=[(N_IN,)])
    eng = _mk_engine(reg)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        y1 = np.asarray(v1net.output(x))
        y2 = np.asarray(v2net.output(x))
        np.testing.assert_array_equal(eng.output(x, model="m", timeout=30), y1)
        # deploy v2 while requests are in flight: nothing is lost, and
        # post-deploy submits serve v2
        inflight = [eng.submit(x, model="m") for _ in range(16)]
        v = reg.deploy("m", net=v2net)
        assert v == 2 and reg.active_version("m") == 2
        for f in inflight:  # every pre/post-cutover future resolves
            out = f.result(timeout=30)
            assert np.array_equal(out, y1) or np.array_equal(out, y2)
        np.testing.assert_array_equal(eng.output(x, model="m", timeout=30), y2)
        # the new version was AOT-warmed by the deploy
        assert reg.version("m", 2).warmed
        # instant rollback via the retained version
        assert reg.rollback("m") == 1
        np.testing.assert_array_equal(eng.output(x, model="m", timeout=30), y1)
        # pinned explicit versions stay reachable while retained
        with pytest.raises(ModelUnavailable):
            eng.submit(x, model="m", version=99)
    finally:
        eng.shutdown()


def test_corrupt_deploy_rejected_while_old_keeps_serving(
        rng, tmp_path, fresh_registry):
    from deeplearning4j_tpu.faultinject import corrupt_file
    v1net, v2net = _net(1), _net(4)
    reg = ModelRegistry()
    reg.register("m", net=v1net)
    eng = _mk_engine(reg)
    try:
        bad = str(tmp_path / "v2.zip")
        write_model(v2net, bad)
        corrupt_file(bad, offset=-100)
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        with pytest.raises(CheckpointCorruptError):
            reg.deploy("m", path=bad)
        # the deploy never touched the serving plane
        assert reg.active_version("m") == 1
        assert reg.versions("m") == {1: "active"}
        np.testing.assert_array_equal(
            eng.output(x, model="m", timeout=30),
            np.asarray(v1net.output(x)))
        assert fresh_registry.counter(
            monitor.MODEL_DEPLOYS_COUNTER, "", model="m",
            outcome="rejected_corrupt").value == 1
    finally:
        eng.shutdown()


# --------------------------------------------------------------- canary

def test_canary_fraction_routes_deterministically_and_promotes(
        rng, fresh_registry):
    v1net, v2net = _net(1), _net(4)
    reg = ModelRegistry()
    reg.register("m", net=v1net)
    eng = _mk_engine(reg, max_latency_ms=0.0)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        y1 = np.asarray(v1net.output(x))
        y2 = np.asarray(v2net.output(x))
        reg.deploy("m", net=v2net, canary_fraction=0.5, warm=False)
        assert reg.active_version("m") == 1  # canary does NOT cut over
        hits = {"v1": 0, "v2": 0}
        for _ in range(12):
            out = eng.output(x, model="m", timeout=30)
            hits["v2" if np.array_equal(out, y2) else "v1"] += 1
        # fraction 0.5 = every 2nd request, deterministically
        assert hits == {"v1": 6, "v2": 6}
        reg.promote("m")
        assert reg.active_version("m") == 2
        np.testing.assert_array_equal(eng.output(x, model="m", timeout=30), y2)
    finally:
        eng.shutdown()


def test_poisoned_canary_nan_output_auto_rolls_back(rng, fresh_registry):
    v1net = _net(1)
    bad = _net(4)
    # poison the canary's params: every output row goes NaN
    bad.params["layer0"]["W"] = jax.numpy.asarray(
        np.full_like(np.asarray(bad.params["layer0"]["W"]), np.nan))
    reg = ModelRegistry()
    reg.register("m", net=v1net)
    eng = _mk_engine(reg, max_latency_ms=0.0)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        y1 = np.asarray(v1net.output(x))
        reg.deploy("m", net=bad, canary_fraction=0.5, warm=False)
        # drive traffic until the watch sees the NaN canary output
        assert _spin_until(
            lambda: (eng.output(x, model="m", timeout=30) is not None
                     and reg.entry("m").canary is None), timeout=30)
        # canary rejected, stable version never stopped serving
        assert reg.versions("m")[2] == "rejected"
        assert reg.active_version("m") == 1
        for _ in range(4):
            np.testing.assert_array_equal(
                eng.output(x, model="m", timeout=30), y1)
        assert fresh_registry.counter(
            monitor.MODEL_ROLLBACKS_COUNTER, "", model="m",
            reason="canary_nan").value == 1
    finally:
        eng.shutdown()


def test_erroring_canary_auto_rolls_back_and_engine_heals(
        rng, fresh_registry):
    dev = jax.devices()[0]
    v1net, v2net = _net(1), _net(4)
    reg = ModelRegistry()
    reg.register("m", net=v1net)
    eng = ParallelInference(registry=reg, max_batch_size=8,
                            max_latency_ms=0.0, devices=[dev, dev],
                            probe_interval_ms=3600_000.0)
    try:
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        y1 = np.asarray(v1net.output(x))
        eng.output(x, model="m", timeout=30)  # known-good probe shape
        v2 = reg.deploy("m", net=v2net, canary_fraction=1.0, warm=False)
        poison_model(eng, "m", failures=4, version=v2)
        # the canary's cross-replica faults roll IT back, not the model
        errs = 0
        for _ in range(4):
            try:
                eng.output(x, model="m", timeout=30)
            except Exception:
                errs += 1
            if reg.entry("m").canary is None:
                break
        assert reg.versions("m")[v2] == "rejected"
        assert not reg.breaker_open("m")
        assert fresh_registry.counter(
            monitor.MODEL_ROLLBACKS_COUNTER, "", model="m",
            reason="canary_error_rate").value == 1
        # stable version serves; the transiently-quarantined replica
        # reinstates on probe
        np.testing.assert_array_equal(eng.output(x, model="m", timeout=30), y1)
        eng.probe_now()
        assert _spin_until(lambda: eng.stats()["healthy_replicas"] == 2)
    finally:
        eng.shutdown()


# ------------------------------------------------------------ isolation

def test_model_breaker_isolates_cotenants_and_probe_heals(
        rng, fresh_registry):
    dev = jax.devices()[0]
    m, n = _net(1), _net(2)
    reg = ModelRegistry()
    reg.register("m", net=m)
    reg.register("n", net=n)
    eng = ParallelInference(registry=reg, max_batch_size=8,
                            max_latency_ms=0.0, devices=[dev, dev],
                            probe_interval_ms=3600_000.0)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        yn = np.asarray(n.output(x))
        eng.output(x, model="m", timeout=30)
        eng.output(x, model="n", timeout=30)
        poison = poison_model(eng, "m")  # 2 batches × (1+1 attempts)
        with pytest.raises(ModelQuarantined):
            eng.output(x, model="m", timeout=30)
        assert reg.breaker_open("m")
        assert poison.remaining == 0
        # isolation: submits for m now fail FAST at admission...
        with pytest.raises(ModelQuarantined):
            eng.submit(x, model="m")
        # ...while the cotenant keeps serving bitwise on every request
        for _ in range(4):
            np.testing.assert_array_equal(
                eng.output(x, model="n", timeout=30), yn)
        assert eng.stats()["models_quarantined"] == ["m"]
        assert eng.stats()["degraded"]
        # poison exhausted → the model probe closes the breaker and the
        # replica probe reinstates the transiently-quarantined replica
        eng.probe_now()
        assert not reg.breaker_open("m")
        assert _spin_until(lambda: eng.stats()["healthy_replicas"] == 2)
        np.testing.assert_array_equal(
            eng.output(x, model="m", timeout=30), np.asarray(m.output(x)))
        assert not eng.stats()["degraded"]
    finally:
        eng.shutdown()


def test_replica_fault_still_quarantines_replica_not_model(
        rng, fresh_registry):
    dev = jax.devices()[0]
    reg = ModelRegistry()
    m = _net(1)
    reg.register("m", net=m)
    eng = ParallelInference(registry=reg, max_batch_size=8,
                            max_latency_ms=0.0, devices=[dev, dev],
                            probe_interval_ms=3600_000.0)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        eng.output(x, model="m", timeout=30)
        poison = poison_replica(eng, replica=0, failures=2)
        # drive until the poisoned replica catches a batch: it fails
        # twice on replica 0, redispatches to replica 1 and SUCCEEDS →
        # replica-scoped quarantine, model untouched
        for _ in range(50):
            np.testing.assert_array_equal(
                eng.output(x, model="m", timeout=30),
                np.asarray(m.output(x)))
            if poison.hits >= 2:
                break
        assert poison.hits == 2
        assert _spin_until(lambda: eng.stats()["healthy_replicas"] == 1)
        assert not reg.breaker_open("m")
        eng.probe_now()
        assert _spin_until(lambda: eng.stats()["healthy_replicas"] == 2)
    finally:
        eng.shutdown()


def test_deploying_fixed_version_heals_quarantined_model(
        rng, fresh_registry):
    dev = jax.devices()[0]
    m = _net(1)
    fixed = _net(4)
    reg = ModelRegistry()
    reg.register("m", net=m, warm_shapes=[(N_IN,)])
    eng = ParallelInference(registry=reg, max_batch_size=8,
                            max_latency_ms=0.0, devices=[dev, dev],
                            probe_interval_ms=3600_000.0)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        eng.output(x, model="m", timeout=30)
        poison_model(eng, "m", failures=10_000)  # sick until replaced
        with pytest.raises(ModelQuarantined):
            eng.output(x, model="m", timeout=30)
        assert reg.breaker_open("m")
        # the recovery path for a quarantined model IS deploying a
        # fixed version: the deploy warms (explicit version bypasses
        # the breaker), cuts over, and resets the breaker — but the
        # poison targets the MODEL, so warmup itself still faults: heal
        # the poison as the fixed deploy would ship fixed code
        eng._poison_hook = None
        v = reg.deploy("m", net=fixed)
        assert v == 2 and not reg.breaker_open("m")
        eng.probe_now()
        np.testing.assert_array_equal(
            eng.output(x, model="m", timeout=30),
            np.asarray(fixed.output(x)))
    finally:
        eng.shutdown()


# --------------------------------------- session affinity vs cutover

def test_session_finishes_stream_on_its_version_across_cutover(
        fresh_registry):
    g1 = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
             compute_dtype="float32", learning_rate=0.01, seed=1).init()
    g2 = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2, max_len=32,
             compute_dtype="float32", learning_rate=0.01, seed=9).init()
    reg = ModelRegistry()
    reg.register("g", net=g1)
    eng = _mk_engine(reg, max_latency_ms=0.0)
    try:
        prompt = np.asarray([[1, 2, 3]], np.int64)
        solo1 = np.asarray(g1.generate(prompt, 5))
        solo2 = np.asarray(g2.generate(prompt, 5))
        assert not np.array_equal(solo1, solo2)
        # burst 1 of the pinned stream resolves v1
        np.testing.assert_array_equal(
            eng.generate(prompt, 5, session="s1", model="g", timeout=60),
            solo1)
        reg.deploy("g", net=g2, warm=False)  # hot-swap mid-stream
        # the pinned session MUST finish on the version it started on —
        # a silent KV-cache owner switch is the bug this pins
        np.testing.assert_array_equal(
            eng.generate(prompt, 5, session="s1", model="g", timeout=60),
            solo1)
        # a NEW session gets the new version
        np.testing.assert_array_equal(
            eng.generate(prompt, 5, session="s2", model="g", timeout=60),
            solo2)
        # releasing the old session re-resolves to the active version
        eng.release_session("s1")
        np.testing.assert_array_equal(
            eng.generate(prompt, 5, session="s1", model="g", timeout=60),
            solo2)
    finally:
        eng.shutdown()


# ----------------------------------------------------- wire + healthz

def test_model_routing_crosses_the_wire(rng, fresh_registry):
    from deeplearning4j_tpu.serving import EngineWorker, RemoteEndpoint
    from deeplearning4j_tpu.streaming.broker import InMemoryBroker
    a, b = _net(1), _net(2)
    reg = ModelRegistry()
    reg.register("a", net=a)
    reg.register("b", net=b)
    eng = _mk_engine(reg)
    broker = InMemoryBroker()
    worker = EngineWorker(eng, broker, "svc", heartbeat_s=0.05)
    ep = RemoteEndpoint(broker, "svc", request_timeout_s=30.0)
    try:
        assert _spin_until(ep.alive, timeout=10)
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        np.testing.assert_array_equal(
            ep.submit(x, model="a").result(timeout=30),
            np.asarray(a.output(x)))
        np.testing.assert_array_equal(
            ep.submit(x, model="b").result(timeout=30),
            np.asarray(b.output(x)))
        # unknown model surfaces TYPED across the wire
        err = ep.submit(x, model="zzz").exception(timeout=30)
        assert isinstance(err, ModelUnavailable)
    finally:
        worker.kill()
        ep.close()
        eng.shutdown()


def test_healthz_ready_gates_on_per_model_state(rng, fresh_registry):
    from deeplearning4j_tpu.ui.server import UiServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    dev = jax.devices()[0]
    reg = ModelRegistry()
    reg.register("m", net=_net(1), warm_shapes=[(N_IN,)])
    eng = ParallelInference(registry=reg, max_batch_size=8,
                            max_latency_ms=0.0, devices=[dev, dev],
                            probe_interval_ms=3600_000.0)
    srv = UiServer(InMemoryStatsStorage(), inference_engine=eng,
                   registry=fresh_registry).start()
    try:
        def ready():
            try:
                with urllib.request.urlopen(srv.url + "/healthz/ready",
                                            timeout=5) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, body = ready()
        assert code == 503 and body["models_ready"] == {"m": False}
        eng.warmup_model("m")
        code, body = ready()
        assert code == 200 and body["models_ready"] == {"m": True}
        # open breaker → not ready, per-model detail says which
        x = rng.standard_normal((1, N_IN)).astype(np.float32)
        poison_model(eng, "m")
        with pytest.raises(ModelQuarantined):
            eng.output(x, model="m", timeout=30)
        code, body = ready()
        assert code == 503 and body["models_ready"] == {"m": False}
        # breaker probe is synchronous; replica reinstatement rides the
        # woken probe threads — spin on the observable state
        eng.probe_now()
        assert _spin_until(lambda: ready()[0] == 200)
    finally:
        srv.stop()
        eng.shutdown()


def test_model_metric_schema(rng, fresh_registry):
    schema = _load_script("check_telemetry_schema")
    reg = ModelRegistry()
    a = _net(1)
    reg.register("m", net=a)
    eng = _mk_engine(reg)
    try:
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        eng.output(x, model="m", timeout=30)
        reg.deploy("m", net=_net(4), warm=False)
        reg.rollback("m")
        text = fresh_registry.prometheus_text()
        assert schema.validate_prometheus_text(text) == []
        assert schema.validate_known_metrics(text) == []
        for fam in ("dl4j_model_requests_total", "dl4j_model_latency_ms",
                    "dl4j_model_deploys_total", "dl4j_model_rollbacks_total",
                    "dl4j_model_active_version"):
            assert fam in text, fam
            assert fam in schema.KNOWN_DL4J_METRICS
    finally:
        eng.shutdown()


# ------------------------------------------------- satellite guards

def test_donation_gates_lint_repo_clean_and_catches_violation(tmp_path):
    lint = _load_script("check_donation_gates")
    root = os.path.dirname(_SCRIPTS)
    assert lint.check_repo(root) == []
    # a crafted ungated site is flagged...
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "f = jax.jit(lambda x: x, donate_argnums=(0,))\n")
    assert len(lint.check_file(str(bad))) == 1
    # ...while the inline-gated and empty-tuple forms pass
    good = tmp_path / "good.py"
    good.write_text(
        "import jax\n"
        'donate = (0,) if jax.default_backend() != "cpu" else ()\n'
        "f = jax.jit(lambda x: x, donate_argnums=donate)\n"
        "g = jax.jit(lambda x: x, donate_argnums=())\n")
    assert lint.check_file(str(good)) == []


def test_stress_faultinject_quick_mode_deterministic():
    stress = _load_script("stress_faultinject")
    assert stress.quick_check(seeds=(0, 1), runs_per_seed=2) == []
