"""Annotation pipeline tests (UIMA-analog).

Parity: ``deeplearning4j-nlp-uima`` annotators — sentence split,
offset-preserving tokens, POS, lemmas, and the ``UimaTokenizerFactory``
adapter into the tokenizer SPI.
"""

from deeplearning4j_tpu.text.annotation import (
    AnnotatedTokenizerFactory, AnnotationPipeline, LemmaAnnotator,
    PosAnnotator, SentenceAnnotator, TokenizerAnnotator, default_pipeline)
from deeplearning4j_tpu.text.tokenization import (
    LowCasePreprocessor, tokenizer_factory)


def test_sentence_split():
    doc = SentenceAnnotator().process(
        __import__("deeplearning4j_tpu.text.annotation",
                   fromlist=["AnnotatedDocument"]).AnnotatedDocument(
            text="Dr. Smith went home. It was late! Was it? Yes."))
    assert doc.sentences == ["Dr. Smith went home.", "It was late!",
                             "Was it?", "Yes."]


def test_tokens_have_offsets_and_sentences():
    doc = default_pipeline().annotate("The cats sat. Dogs ran fast.")
    texts = [t.text for t in doc.tokens]
    assert texts == ["The", "cats", "sat", ".", "Dogs", "ran", "fast", "."]
    for t in doc.tokens:
        assert doc.text[t.start:t.end] == t.text
    assert [t.sentence for t in doc.tokens] == [0, 0, 0, 0, 1, 1, 1, 1]


def test_pos_tags():
    doc = default_pipeline().annotate("The quick dogs quickly ran in 42 parks.")
    by_word = {t.text: t.pos for t in doc.tokens}
    assert by_word["The"] == "DET"
    assert by_word["quickly"] == "ADV"
    assert by_word["in"] == "ADP"
    assert by_word["42"] == "NUM"
    assert by_word["."] == "PUNCT"
    assert by_word["dogs"] == "NOUN"


def test_lemmas():
    doc = default_pipeline().annotate(
        "The children were running and stopped; she tried the boxes.")
    by_word = {t.text.lower(): t.lemma for t in doc.tokens}
    assert by_word["children"] == "child"
    assert by_word["were"] == "be"
    assert by_word["running"] == "run"
    assert by_word["stopped"] == "stop"
    assert by_word["tried"] == "try"
    assert by_word["boxes"] == "box"


def test_tokenizer_factory_adapter():
    fac = AnnotatedTokenizerFactory()
    fac.set_token_pre_processor(LowCasePreprocessor())
    toks = fac.create("The children were running. Fast!").get_tokens()
    assert toks == ["the", "child", "be", "run", "fast"]  # PUNCT dropped


def test_registered_in_factory_registry():
    fac = tokenizer_factory("annotated")
    assert isinstance(fac, AnnotatedTokenizerFactory)
    assert fac.create("Cats sat.").get_tokens() == ["cat", "sit"]


def test_custom_annotator_plugs_in():
    class UpperAnnotator:
        def process(self, doc):
            for t in doc.tokens:
                t.lemma = (t.lemma or t.text).upper()
            return doc

    pipe = AnnotationPipeline([SentenceAnnotator(), TokenizerAnnotator(),
                               PosAnnotator(), LemmaAnnotator(),
                               UpperAnnotator()])
    doc = pipe.annotate("cats ran")
    assert [t.lemma for t in doc.tokens] == ["CAT", "RUN"]


def test_callable_tag_annotator_plugs_external_tagger():
    """The MIGRATION.md seam: any tokens->tags callable slots into the
    pipeline where the reference required OpenNLP model files."""
    from deeplearning4j_tpu.text.annotation import (
        AnnotationPipeline, CallableTagAnnotator, SentenceAnnotator,
        TokenizerAnnotator)

    def my_model(tokens):
        return ["TAGGED-" + t.upper() for t in tokens]

    pipe = AnnotationPipeline([SentenceAnnotator(), TokenizerAnnotator(),
                               CallableTagAnnotator(my_model)])
    doc = pipe.annotate("dogs run")
    assert [t.pos for t in doc.tokens] == ["TAGGED-DOGS", "TAGGED-RUN"]
    pipe2 = AnnotationPipeline([SentenceAnnotator(), TokenizerAnnotator(),
                                CallableTagAnnotator(lambda ts: ts,
                                                     attr="lemma")])
    assert [t.lemma for t in pipe2.annotate("dogs run").tokens] == [
        "dogs", "run"]
    import pytest
    with pytest.raises(ValueError, match="attr"):
        CallableTagAnnotator(my_model, attr="bogus")
