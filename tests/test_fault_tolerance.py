"""Fault-injection suite: every recovery path in the stack, driven
deliberately.

Doctrine: a recovery path that has never executed is a bug waiting for
an outage. Each test injects ONE fault class through
``deeplearning4j_tpu.faultinject`` (deterministic schedules — no random
flakiness, no wall-clock sleeps in assertions) and pins the recovery
contract:

- torn / bit-flipped checkpoints  → restore falls back to the newest
  VALID unit (zip + sharded);
- NaN step                         → supervisor rollback + LR backoff +
  batch skip, clean ``TrainingDiverged`` give-up, bitwise pass-through
  when no fault fires;
- replica device errors            → quarantine keeps serving
  bitwise-correct results at reduced capacity, probe reinstates;
- broker outage / poison message   → transparent reconnect,
  ``BrokerUnavailable`` (never a silent ``None``), dead-letter routing.
"""

import json
import os
import threading
import time
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (DeviceFeedIterator,
                                                   ListDataSetIterator)
from deeplearning4j_tpu.faultinject import (FailingDataSetIterator,
                                            FlakyBroker, InjectedFault,
                                            ReplicaPoison, TornWrites,
                                            corrupt_file, poison_replica,
                                            tear_file)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.resumable import ResumableTrainer
from deeplearning4j_tpu.optimize.supervisor import (TrainingDiverged,
                                                    TrainingSupervisor,
                                                    supervisor_enabled)
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.streaming import (BrokerUnavailable, InMemoryBroker,
                                          StreamingInference, StreamingTrainer,
                                          TcpBroker, TcpBrokerServer,
                                          ndarray_from_bytes,
                                          ndarray_to_bytes)
from deeplearning4j_tpu.streaming.pipeline import (publish_dataset,
                                                   publish_stop)
from deeplearning4j_tpu.util import sharded_checkpoint as sc
from deeplearning4j_tpu.util.model_serializer import (CheckpointCorruptError,
                                                      restore_model,
                                                      verify_model_file,
                                                      write_model)

pytestmark = pytest.mark.faultinject

N_IN, N_OUT = 4, 3


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(rng, n=6, rows=8):
    return [DataSet(rng.standard_normal((rows, N_IN)).astype(np.float32),
                    np.eye(N_OUT, dtype=np.float32)[
                        rng.integers(0, N_OUT, rows)])
            for _ in range(n)]


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


def _spin_until(cond, timeout=60.0, tick=0.005):
    """Bounded wait on a condition that a background thread flips —
    assertions never sleep blindly; they poll an observable state."""
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() >= deadline:
            return False
        time.sleep(tick)
    return True


# ------------------------------------------------- checkpoint integrity

def test_zip_checkpoint_atomic_and_verified(rng, tmp_path, fresh_registry):
    net = _net()
    net.fit(_batches(rng, 1)[0])
    path = str(tmp_path / "model.zip")
    write_model(net, path)
    assert verify_model_file(path) == []
    with zipfile.ZipFile(path) as z:
        assert "manifest.json" in z.namelist()
    # no temp litter after a successful atomic install
    assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []
    # bit flip → detected, CheckpointCorruptError (not a random npz error)
    corrupt_file(path, offset=len(open(path, "rb").read()) // 2 - 1)
    assert verify_model_file(path) != []
    with pytest.raises(CheckpointCorruptError):
        restore_model(path)
    assert fresh_registry.family_total(
        monitor.FAULT_CKPT_INTEGRITY_COUNTER) >= 1


def test_zip_write_crash_leaves_previous_checkpoint(rng, tmp_path):
    net = _net()
    path = str(tmp_path / "model.zip")
    write_model(net, path)
    before = open(path, "rb").read()
    net.fit(_batches(rng, 1)[0])
    with TornWrites(crash_on_call=1, path_substr="model.zip"):
        with pytest.raises(InjectedFault):
            write_model(net, path)
    # the installed file is byte-identical to the previous good one
    assert open(path, "rb").read() == before
    assert verify_model_file(path) == []


def test_sharded_restore_falls_back_to_newest_valid(rng, tmp_path,
                                                    fresh_registry):
    net = _net()
    ds = _batches(rng, 1)[0]
    root = str(tmp_path / "hist")
    flats = {}
    for step in (1, 2, 3):
        net.fit(ds)
        sc.save_checkpoint(net, root, keep=3, step=step)
        flats[step] = net.params_flat().copy()
    assert sc.checkpoint_steps(root) == [1, 2, 3]
    # tear the newest unit: truncate a manifest-listed payload file
    newest = os.path.join(root, "ckpt-0000000003")
    manifest = json.load(open(os.path.join(newest, "manifest.json")))
    victim = sorted(manifest["crc32"])[-1]
    tear_file(os.path.join(newest, victim), keep_fraction=0.25)
    restored = sc.restore_checkpoint(root)
    np.testing.assert_array_equal(restored.params_flat(), flats[2])
    assert fresh_registry.family_total(
        monitor.FAULT_CKPT_INTEGRITY_COUNTER) >= 1
    # every unit torn → CheckpointCorruptError, not garbage params
    for step in (1, 2):
        unit = os.path.join(root, f"ckpt-{step:010d}")
        man = json.load(open(os.path.join(unit, "manifest.json")))
        corrupt_file(os.path.join(unit, sorted(man["crc32"])[-1]))
    with pytest.raises(CheckpointCorruptError):
        sc.restore_checkpoint(root)


def test_sharded_save_crash_keeps_previous_unit(rng, tmp_path):
    net = _net()
    ds = _batches(rng, 1)[0]
    single = str(tmp_path / "single")
    net.fit(ds)
    sc.save_checkpoint(net, single)
    good = net.params_flat().copy()
    net.fit(ds)
    # crash on the FIRST install rename of the checkpoint unit
    with TornWrites(crash_on_call=1, path_substr="single"):
        with pytest.raises(InjectedFault):
            sc.save_checkpoint(net, single)
    restored = sc.restore_checkpoint(single)
    np.testing.assert_array_equal(restored.params_flat(), good)


def test_resumable_tolerates_half_written_unit(rng, tmp_path, caplog):
    net = _net()
    ck = str(tmp_path / "ck")
    t1 = ResumableTrainer(net, ck, checkpoint_every=1)
    t1.fit(ListDataSetIterator(
        DataSet(np.concatenate([b.features for b in _batches(rng, 4)]),
                np.concatenate([b.labels for b in _batches(rng, 4)])), 8),
        epochs=1, max_steps=2)
    # sabotage the newest unit: model.zip torn mid-write
    unit = os.path.join(ck, "checkpoint")
    tear_file(os.path.join(unit, "model.zip"), keep_fraction=0.3)
    t2 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    model = t2.resume_or_start()  # warns + starts fresh, never raises
    assert model is t2.model
    assert t2.steps_done == 0
    assert any("unreadable" in r.message or "starting fresh" in r.message
               for r in caplog.records)


# --------------------------------------------------- supervisor (training)

def test_supervisor_noop_run_is_bitwise_identical(rng):
    batches = _batches(rng)
    supervised, plain = _net(), _net()
    sup = TrainingSupervisor(supervised)
    scores_sup, scores_plain = [], []
    for ds in batches:
        sup.step(ds)
        scores_sup.append(supervised.score())
    for ds in batches:
        plain.fit(ds)
        scores_plain.append(plain.score())
    assert scores_sup == scores_plain  # bitwise: exact float equality
    np.testing.assert_array_equal(supervised.params_flat(),
                                  plain.params_flat())
    assert sup.rollbacks == 0 and sup.report()["events"] == []


def test_supervisor_nan_rollback_lr_backoff_and_skip(rng, fresh_registry):
    batches = _batches(rng)
    net = _net()
    base_lr = net.gc.learning_rate
    it = FailingDataSetIterator(
        ListDataSetIterator(
            DataSet(np.concatenate([b.features for b in batches]),
                    np.concatenate([b.labels for b in batches])), 8),
        nan_at={2})
    sup = TrainingSupervisor(net, max_rollbacks=3)
    report = sup.fit(it, epochs=1)
    assert report["rollbacks"] == 1
    assert report["batches_skipped"] == [2]
    assert report["events"][0]["action"] == "rollback"
    assert net.gc.learning_rate == pytest.approx(base_lr * 0.5)
    assert np.isfinite(net.score())
    assert np.isfinite(net.params_flat()).all()
    assert fresh_registry.family_total(monitor.FAULT_ROLLBACKS_COUNTER) == 1
    assert fresh_registry.get(monitor.FAULT_EVENTS_COUNTER,
                              domain="training").value == 1
    json.dumps(report)  # structured = JSON-serializable


def test_supervisor_rollback_recovers_last_good_params(rng):
    """After a rollback the params are EXACTLY the pre-NaN-batch params:
    train a twin on the same stream minus the poison batch."""
    batches = _batches(rng, n=4)
    nan_batch = DataSet(np.full((8, N_IN), np.nan, np.float32),
                        batches[0].labels)
    guarded, twin = _net(), _net()
    sup = TrainingSupervisor(guarded)
    for ds in batches[:2] + [nan_batch] + batches[2:]:
        sup.step(ds)
    # the twin never sees the poison batch; after the rollback the
    # guarded run continues from the same params BUT at the backed-off
    # LR, so compare at the rollback point: replay twin to batch 2
    for ds in batches[:2]:
        twin.fit(ds)
    twin_flat = twin.params_flat()
    # guarded net at the moment of rollback had exactly these params —
    # verify by rolling its LR back up and replaying the remaining
    # batches on the twin with the backed-off LR
    twin.gc.learning_rate *= sup.lr_backoff
    twin._jits = {}
    for ds in batches[2:]:
        twin.fit(ds)
    np.testing.assert_array_equal(guarded.params_flat(), twin.params_flat())
    assert sup.rollbacks == 1


def test_supervisor_gives_up_with_structured_report(rng, fresh_registry):
    net = _net()
    nan_batch = DataSet(np.full((8, N_IN), np.nan, np.float32),
                        np.eye(N_OUT, dtype=np.float32)[
                            np.zeros(8, np.int64)])
    sup = TrainingSupervisor(net, max_rollbacks=2)
    with pytest.raises(TrainingDiverged) as exc:
        for _ in range(10):
            sup.step(nan_batch)
    report = exc.value.report
    assert report["rollbacks"] == 3 and report["max_rollbacks"] == 2
    assert report["events"][-1]["action"] == "give_up"
    json.dumps(report)
    assert fresh_registry.family_total(monitor.FAULT_ROLLBACKS_COUNTER) == 3


def test_supervisor_escape_hatch_env(rng, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_DISABLE_SUPERVISOR", "1")
    assert not supervisor_enabled()
    net = _net()
    sup = TrainingSupervisor(net)
    assert not sup.enabled
    nan_batch = DataSet(np.full((8, N_IN), np.nan, np.float32),
                        np.eye(N_OUT, dtype=np.float32)[
                            np.zeros(8, np.int64)])
    sup.step(nan_batch)  # pass-through: no rollback, NaN flows
    assert not np.isfinite(net.score())
    assert sup.rollbacks == 0


def test_supervisor_policy_survives_resume(rng, tmp_path):
    """ResumableTrainer integration: the rollback/LR state rides the
    cursor, so a resumed run replays the same policy."""
    feats = np.concatenate([b.features for b in _batches(rng, 4)])
    labels = np.concatenate([b.labels for b in _batches(rng, 4)])

    def make_it():
        return FailingDataSetIterator(
            ListDataSetIterator(DataSet(feats, labels), 8), nan_at={1})

    ck = str(tmp_path / "ck")
    net1 = _net()
    t1 = ResumableTrainer(net1, ck, checkpoint_every=1)
    sup1 = TrainingSupervisor(net1, max_rollbacks=3)
    t1.fit(make_it(), epochs=1, max_steps=3, supervisor=sup1)
    assert sup1.rollbacks == 1
    base_lr = _net().gc.learning_rate

    t2 = ResumableTrainer(_net(), ck, checkpoint_every=1)
    sup2 = TrainingSupervisor(t2.model, max_rollbacks=3)
    t2.resume_or_start(supervisor=sup2)
    assert sup2.model is t2.model  # rebound to the restored model
    assert sup2.rollbacks == 1
    assert sup2.model.gc.learning_rate == pytest.approx(base_lr * 0.5)


# ------------------------------------------------- feed-pipeline close race

def test_device_feed_close_after_worker_death(rng):
    """Regression: close() after the staging worker died must neither
    hang nor raise; a fresh iteration afterwards works."""
    data = ListDataSetIterator(
        DataSet(rng.standard_normal((32, N_IN)).astype(np.float32),
                np.eye(N_OUT, dtype=np.float32)[
                    rng.integers(0, N_OUT, 32)]), 8)
    calls = {"n": 0}

    def exploding_place(batch):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise InjectedFault("staging died")
        return batch

    feed = DeviceFeedIterator(data, depth=1, place=exploding_place)
    assert feed.has_next()
    feed.next()
    with pytest.raises(InjectedFault):
        while feed.has_next():  # worker error surfaces on the consumer
            feed.next()
    feed.close()  # after the death: returns promptly, no second raise
    assert feed._thread is None
    # close again (double-close is a no-op, not a double-raise)
    feed.close()
    # the iterator remains usable: reset semantics replay the source
    calls["n"] = -10_000  # disarm
    assert feed.has_next()


def test_device_feed_close_without_consuming_after_error(rng):
    """The worker dies while the consumer never pulls: close() must not
    deadlock against the full staging queue."""
    data = ListDataSetIterator(
        DataSet(rng.standard_normal((32, N_IN)).astype(np.float32),
                np.eye(N_OUT, dtype=np.float32)[
                    rng.integers(0, N_OUT, 32)]), 8)

    def exploding_place(batch):
        raise InjectedFault("staging died immediately")

    feed = DeviceFeedIterator(data, depth=1, place=exploding_place)
    with pytest.raises(InjectedFault):
        feed.has_next()  # starts the worker, which dies at once
    feed.close()
    assert feed._thread is None


def test_async_iterator_propagates_source_error(rng):
    """AsyncDataSetIterator used to silently truncate the epoch when the
    source raised; now the error reaches the consumer, and close() after
    it is clean."""
    from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator

    inner = FailingDataSetIterator(
        ListDataSetIterator(
            DataSet(rng.standard_normal((32, N_IN)).astype(np.float32),
                    np.eye(N_OUT, dtype=np.float32)[
                        rng.integers(0, N_OUT, 32)]), 8),
        raise_at={1})
    it = AsyncDataSetIterator(inner, queue_size=2)
    with pytest.raises(InjectedFault):
        while it.has_next():
            it.next()
    it.close()
    assert it._thread is None


# --------------------------------------------------- serving (quarantine)

def _drive_until_quarantined(eng, net, rng, max_requests=200):
    """Submit traffic (verifying every result bitwise) until the poisoned
    replica trips its quarantine; bounded, no blind sleeps."""
    for i in range(max_requests):
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        np.testing.assert_array_equal(eng.output(x, timeout=60),
                                      np.asarray(net.output(x)))
        if eng.stats()["quarantined"]:
            return i + 1
    raise AssertionError("poisoned replica never quarantined")


def test_replica_quarantine_keeps_serving_bitwise(rng, fresh_registry):
    net = _net()
    import jax
    dev = jax.devices()[0]
    # two replicas on one device: the quarantine logic only cares about
    # worker identity, so this exercises redispatch without multi-chip
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            devices=[dev, dev],
                            probe_interval_ms=3600_000.0)  # probe_now only
    try:
        eng.warmup([(N_IN,)])
        poison = poison_replica(eng, replica=0, failures=2)
        served = _drive_until_quarantined(eng, net, rng)
        s = eng.stats()
        assert s["quarantined"] == [0] and s["degraded"]
        assert s["healthy_replicas"] == 1
        assert poison.hits == 2  # initial attempt + one same-replica retry
        assert fresh_registry.get(
            monitor.FAULT_QUARANTINED_GAUGE).value == 1
        assert fresh_registry.get(monitor.FAULT_EVENTS_COUNTER,
                                  domain="serving").value >= 2
        # degraded engine keeps serving bitwise-correct results
        for _ in range(5):
            x = rng.standard_normal((3, N_IN)).astype(np.float32)
            np.testing.assert_array_equal(eng.output(x, timeout=60),
                                          np.asarray(net.output(x)))
        # poison exhausted → the probe passes → replica reinstated
        assert _spin_until(
            lambda: (eng.probe_now() or not eng.stats()["quarantined"]))
        s = eng.stats()
        assert s["quarantined"] == [] and not s["degraded"]
        assert fresh_registry.get(
            monitor.FAULT_QUARANTINED_GAUGE).value == 0
        x = rng.standard_normal((2, N_IN)).astype(np.float32)
        np.testing.assert_array_equal(eng.output(x, timeout=60),
                                      np.asarray(net.output(x)))
        assert served >= 1
    finally:
        eng.shutdown()  # recovered faults must NOT poison shutdown


def test_all_replicas_down_fails_futures_then_heals(rng):
    net = _net()
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            replicas=1, probe_interval_ms=3600_000.0)
    eng.warmup([(N_IN,)])
    poison = poison_replica(eng, replica=0, failures=2)
    x = rng.standard_normal((2, N_IN)).astype(np.float32)
    fut = eng.submit(x)
    # futures are never stranded: with no survivor the error lands here
    with pytest.raises(InjectedFault):
        fut.result(timeout=60)
    assert eng.stats()["quarantined"] == [0]
    assert eng.stats()["healthy_replicas"] == 0
    # poison exhausted → probe heals → the engine serves again
    assert _spin_until(
        lambda: (eng.probe_now() or not eng.stats()["quarantined"]))
    np.testing.assert_array_equal(eng.output(x, timeout=60),
                                  np.asarray(net.output(x)))
    with pytest.raises(InjectedFault):
        eng.shutdown()  # first worker error re-raised (futures carried it)


def test_healthz_reports_quarantine_degraded(rng):
    import http.client

    from deeplearning4j_tpu.ui.server import UiServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    net = _net()
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            replicas=1, probe_interval_ms=3600_000.0)
    eng.warmup([(N_IN,)])
    server = UiServer(InMemoryStatsStorage(), port=0,
                      registry=monitor.MetricsRegistry(),
                      inference_engine=eng).start()
    try:
        poison_replica(eng, replica=0, failures=2)
        fut = eng.submit(np.zeros((2, N_IN), np.float32))
        with pytest.raises(InjectedFault):
            fut.result(timeout=60)

        def healthz():
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=10)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            return resp.status, body

        status, body = healthz()
        assert status == 503
        assert body["status"] == "degraded"
        assert body["inference"]["quarantined"] == [0]
        assert _spin_until(
            lambda: (eng.probe_now() or not eng.stats()["quarantined"]))
        status, body = healthz()
        assert status == 200 and body["status"] == "ok"
    finally:
        server.stop()
        try:
            eng.shutdown()
        except InjectedFault:
            pass


# ---------------------------------------------------- transport resilience

def test_tcp_broker_reconnects_transparently():
    srv = TcpBrokerServer(poll_timeout=0.05).start()
    try:
        host, port = srv.address
        broker = TcpBroker(host, port, max_retries=3, backoff_base_s=0.01)
        broker.publish("t", b"one")
        assert broker.consume("t", timeout=5) == b"one"
        broker._sock.close()  # sever the connection underneath
        broker.publish("t", b"two")  # reconnect + resend, no caller error
        assert broker.consume("t", timeout=5) == b"two"
        # a genuine poll timeout still returns None (healthy broker)
        assert broker.consume("t", timeout=0.2) is None
    finally:
        srv.stop()


def test_tcp_broker_unavailable_after_bounded_retries(fresh_registry):
    srv = TcpBrokerServer(poll_timeout=0.05).start()
    host, port = srv.address
    broker = TcpBroker(host, port, max_retries=2, backoff_base_s=0.01)
    broker.publish("t", b"x")
    srv.stop()
    broker._sock.close()
    # a dead broker is an EXCEPTION, never a None masquerading as idle
    with pytest.raises(BrokerUnavailable):
        broker.consume("t", timeout=5)
    assert fresh_registry.get(monitor.FAULT_EVENTS_COUNTER,
                              domain="transport").value >= 1
    # constructing against a dead broker is also bounded
    with pytest.raises(BrokerUnavailable):
        TcpBroker(host, port, max_retries=1, backoff_base_s=0.01,
                  connect_timeout=0.5)


def test_flaky_broker_surfaces_as_broker_error(rng):
    """A FlakyBroker transport error kills neither silently nor
    ambiguously: StreamingTrainer surfaces it on join()."""
    broker = FlakyBroker(InMemoryBroker(), fail_consumes={1},
                         exc=BrokerUnavailable)
    net = _net()
    x = rng.standard_normal((8, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, 8)]
    publish_dataset(broker, "train", DataSet(x, y))
    trainer = StreamingTrainer(net, broker, "train", batch_size=8,
                               idle_timeout=30.0).start()
    with pytest.raises(BrokerUnavailable):
        trainer.join(timeout=60)
    assert broker.faults_injected == 1


def test_streaming_trainer_dead_letters_and_keeps_training(
        rng, fresh_registry):
    broker = InMemoryBroker()
    net = _net()
    poison = b"\x00not an npz at all"
    x = rng.standard_normal((8, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, 8)]
    broker.publish("train", poison)
    publish_dataset(broker, "train", DataSet(x, y))
    broker.publish("train", poison)
    publish_dataset(broker, "train", DataSet(x, y))
    publish_stop(broker, "train")
    trainer = StreamingTrainer(net, broker, "train", batch_size=8)
    assert trainer.run() == 2  # both good batches trained
    # both poison payloads are on the DLQ, byte-identical, in order
    assert broker.consume("train.deadletter", timeout=5) == poison
    assert broker.consume("train.deadletter", timeout=5) == poison
    assert fresh_registry.get(monitor.FAULT_DEAD_LETTER_COUNTER,
                              topic="train").value == 2


def test_streaming_inference_dead_letters_poison_requests(
        rng, fresh_registry):
    broker = InMemoryBroker()
    net = _net()
    xs = [rng.standard_normal((2, N_IN)).astype(np.float32)
          for _ in range(3)]
    broker.publish("in", b"poison request")
    for x in xs:
        broker.publish("in", ndarray_to_bytes(x))
    publish_stop(broker, "in")
    serve = StreamingInference(net, broker, "in", "out")
    assert serve.run() == 3
    # good requests answered IN ORDER despite the interleaved poison
    for x in xs:
        pred = ndarray_from_bytes(broker.consume("out", timeout=5))
        np.testing.assert_array_equal(pred, np.asarray(net.output(x)))
    assert broker.consume("in.deadletter", timeout=5) == b"poison request"
    assert fresh_registry.get(monitor.FAULT_DEAD_LETTER_COUNTER,
                              topic="in").value == 1


# --------------------------------------------------------- schema pinning

def test_fault_metric_families_pinned_in_schema(fresh_registry):
    import scripts.check_telemetry_schema as schema

    monitor.record_fault("training")
    monitor.record_fault("serving")
    monitor.record_fault("transport")
    monitor.record_fault("checkpoint")
    reg = fresh_registry
    reg.counter(monitor.FAULT_ROLLBACKS_COUNTER, "h").inc()
    reg.gauge(monitor.FAULT_QUARANTINED_GAUGE, "h").set(0)
    reg.counter(monitor.FAULT_DEAD_LETTER_COUNTER, "h", topic="t").inc()
    reg.counter(monitor.FAULT_CKPT_INTEGRITY_COUNTER, "h").inc()
    text = reg.prometheus_text()
    assert schema.validate_prometheus_text(text) == []
    assert schema.validate_known_metrics(text) == []
    for name in (monitor.FAULT_EVENTS_COUNTER,
                 monitor.FAULT_ROLLBACKS_COUNTER,
                 monitor.FAULT_QUARANTINED_GAUGE,
                 monitor.FAULT_DEAD_LETTER_COUNTER,
                 monitor.FAULT_CKPT_INTEGRITY_COUNTER):
        assert name in schema.KNOWN_DL4J_METRICS
