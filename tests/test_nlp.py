"""NLP vertical tests — ports of the reference's word2vec sanity tests
(nearest neighbors of trained vectors), tokenizer unit tests, serializer
round-trips (SURVEY.md §4 NLP row).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.bagofwords import BagOfWordsVectorizer, TfidfVectorizer
from deeplearning4j_tpu.models.embeddings.serializer import (
    read_full_model,
    read_word_vectors,
    read_word_vectors_binary,
    write_full_model,
    write_word_vectors,
    write_word_vectors_binary,
)
from deeplearning4j_tpu.models.glove import Glove
from deeplearning4j_tpu.models.paragraphvectors import ParagraphVectors
from deeplearning4j_tpu.models.word2vec import Huffman, VocabCache, Word2Vec
from deeplearning4j_tpu.text.sentenceiterator import (
    CollectionSentenceIterator,
    LineSentenceIterator,
)
from deeplearning4j_tpu.text.tokenization import (
    CommonPreprocessor,
    DefaultTokenizer,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
)


def _toy_corpus(n_repeats=200, seed=0):
    """Two topic clusters: fruit words co-occur, vehicle words co-occur."""
    rng = np.random.default_rng(seed)
    fruit = ["apple", "banana", "cherry", "mango"]
    vehicle = ["car", "truck", "bus", "train"]
    sents = []
    for _ in range(n_repeats):
        f = list(rng.permutation(fruit))
        v = list(rng.permutation(vehicle))
        sents.append(" ".join(f))
        sents.append(" ".join(v))
    return sents


def _wide_corpus(n=600, seed=0, words_per_topic=12, sent_len=6):
    """Larger two-topic corpus (sampled sentences). The 4-word permuted
    corpus is degenerate for CBOW: every context word in a sentence gets
    an identical gradient, so only the shared component trains."""
    rng = np.random.default_rng(seed)
    ta = [f"a{i}" for i in range(words_per_topic)]
    tb = [f"b{i}" for i in range(words_per_topic)]
    return [" ".join(rng.choice(ta if rng.random() < 0.5 else tb,
                                sent_len, replace=False)) for _ in range(n)]


class TestTokenization:
    def test_default_tokenizer(self):
        t = DefaultTokenizer("Hello World  foo")
        assert t.get_tokens() == ["Hello", "World", "foo"]

    def test_common_preprocessor(self):
        f = DefaultTokenizerFactory(CommonPreprocessor())
        assert f.create("Hello, World! 123").get_tokens() == ["hello", "world"]

    def test_ngrams(self):
        f = NGramTokenizerFactory(DefaultTokenizerFactory(), 1, 2)
        toks = f.create("a b c").get_tokens()
        assert toks == ["a", "b", "c", "a b", "b c"]

    def test_sentence_iterators(self, tmp_path):
        ci = CollectionSentenceIterator(["one", "two"])
        assert list(ci) == ["one", "two"]
        assert list(ci) == ["one", "two"]  # reset works
        p = os.path.join(tmp_path, "f.txt")
        with open(p, "w") as f:
            f.write("l1\nl2\nl3\n")
        li = LineSentenceIterator(p)
        assert list(li) == ["l1", "l2", "l3"]


class TestVocabHuffman:
    def test_vocab_ordering_and_filter(self):
        vc = VocabCache.build_from_sentences(
            [["a", "a", "a", "b", "b", "c"]], min_word_frequency=2)
        assert vc.num_words() == 2
        assert vc.word_at_index(0) == "a"
        assert vc.index_of("c") == -1

    def test_huffman_codes_prefix_free(self):
        vc = VocabCache.build_from_sentences(
            [["a"] * 8 + ["b"] * 4 + ["c"] * 2 + ["d"]])
        h = Huffman(vc)
        codes = {}
        for i in range(vc.num_words()):
            L = int(h.code_lengths[i])
            codes[vc.word_at_index(i)] = tuple(h.codes[i, :L].astype(int))
        # most frequent word gets shortest code
        assert len(codes["a"]) <= len(codes["d"])
        # prefix-free
        cs = list(codes.values())
        for i, a in enumerate(cs):
            for j, b in enumerate(cs):
                if i != j:
                    assert a != b[:len(a)]


class TestWord2Vec:
    @pytest.mark.parametrize("kwargs", [
        dict(negative_sample=5),
        dict(negative_sample=0, use_hierarchic_softmax=True),
    ])
    def test_topic_clusters(self, kwargs):
        kw = dict(layer_size=24, window_size=3, epochs=12, learning_rate=0.025,
                  batch_size=128, seed=7)
        kw.update(kwargs)
        w2v = Word2Vec(**kw)
        w2v.fit(_toy_corpus())
        # in-topic similarity must beat cross-topic
        in_topic = w2v.similarity("apple", "banana")
        cross = w2v.similarity("apple", "car")
        assert in_topic > cross + 0.1, (in_topic, cross, kwargs)

    def test_cbow_topic_clusters(self):
        w2v = Word2Vec(layer_size=32, window_size=3, epochs=15, learning_rate=0.05,
                       batch_size=256, seed=7,
                       elements_learning_algorithm="cbow", negative_sample=5)
        w2v.fit(_wide_corpus())
        ins = np.mean([w2v.similarity("a0", x) for x in ["a1", "a2", "a3"]])
        crs = np.mean([w2v.similarity("a0", x) for x in ["b1", "b2", "b3"]])
        assert ins > crs + 0.1, (ins, crs)

    def test_words_nearest(self):
        w2v = Word2Vec(layer_size=24, window_size=3, epochs=15, learning_rate=0.025,
                       batch_size=128, seed=3)
        w2v.fit(_toy_corpus())
        nearest = w2v.words_nearest("apple", 3)
        assert set(nearest) <= {"banana", "cherry", "mango"}, nearest

    @pytest.mark.parametrize("device_pairgen", [True, False])
    def test_zipf_large_batch_stays_bounded(self, device_pairgen):
        """Divergence regression: with a zipf head word occurring
        hundreds of times per batch, unbounded scatter-sum accumulation
        blew the tables up to inf (both engine paths, any batch >~1k on
        natural-text frequencies). Capped accumulation (engine._sgns_math)
        must keep the loss finite and decreasing."""
        rng = np.random.default_rng(0)
        vocab = 200
        probs = 1.0 / np.arange(1, vocab + 1)
        probs /= probs.sum()
        sents = [[f"w{t}" for t in rng.choice(vocab, 20, p=probs)]
                 for _ in range(600)]
        w2v = Word2Vec(layer_size=32, window_size=5, epochs=3, batch_size=8192,
                       negative_sample=5, seed=1,
                       device_pairgen=device_pairgen)
        w2v.fit(sents)
        hist = w2v._loss_history
        assert np.isfinite(hist).all(), hist[-3:]
        assert hist[-1] < hist[0] - 0.3, (hist[0], hist[-1])
        assert np.abs(w2v.lookup_table.syn0).max() < 50.0

    def test_sgns_math_mismatched_table_sizes(self):
        """ParagraphVectors trains doc vectors (syn0, n_docs rows)
        against the word output table (syn1neg, V rows >> n_docs); the
        cap denominators must be sized per-table or word ids beyond
        n_docs get dropped/clamped."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.sequencevectors.engine import _sgns_math

        rng = np.random.default_rng(3)
        n_docs, V, d, B, K = 4, 40, 8, 16, 3
        syn0 = jnp.asarray(rng.standard_normal((n_docs, d)), jnp.float32)
        syn1 = jnp.asarray(rng.standard_normal((V, d)), jnp.float32)
        centers = jnp.asarray(rng.integers(0, n_docs, B), jnp.int32)
        contexts = jnp.asarray(rng.integers(n_docs, V, B), jnp.int32)
        negatives = jnp.asarray(rng.integers(n_docs, V, (B, K)), jnp.int32)
        w = jnp.ones(B, jnp.float32)
        for dense in (False, True):
            s0, s1, _ = _sgns_math(syn0, syn1, centers, contexts, negatives,
                                   jnp.float32(0.1), w, dense)
            # every context row >= n_docs must actually receive an update
            touched = np.unique(np.asarray(contexts))
            diff = np.abs(np.asarray(s1) - np.asarray(syn1)).sum(axis=1)
            assert (diff[touched] > 0).all(), (dense, touched, diff[touched])

    def test_scan_and_host_paths_agree_on_structure(self):
        """The device-pairgen scan path and the host per-batch path use
        different RNG streams so vectors differ, but both must learn
        the same topical structure."""
        for dp in (True, False):
            w2v = Word2Vec(layer_size=24, window_size=3, epochs=12,
                           batch_size=128, seed=7, device_pairgen=dp)
            w2v.fit(_toy_corpus())
            in_topic = w2v.similarity("apple", "banana")
            cross = w2v.similarity("apple", "car")
            assert in_topic > cross + 0.1, (dp, in_topic, cross)


class TestSerializer:
    def _small_wv(self):
        w2v = Word2Vec(layer_size=8, epochs=2, seed=1)
        w2v.fit(_toy_corpus(30))
        return w2v

    def test_text_round_trip(self, tmp_path):
        w2v = self._small_wv()
        wv = w2v.word_vectors()
        p = os.path.join(tmp_path, "vec.txt")
        write_word_vectors(wv, p)
        wv2 = read_word_vectors(p)
        np.testing.assert_allclose(wv2.get_word_vector("apple"),
                                   wv.get_word_vector("apple"), atol=1e-5)

    def test_binary_round_trip(self, tmp_path):
        w2v = self._small_wv()
        wv = w2v.word_vectors()
        p = os.path.join(tmp_path, "vec.bin")
        write_word_vectors_binary(wv, p)
        wv2 = read_word_vectors_binary(p)
        np.testing.assert_allclose(wv2.get_word_vector("truck"),
                                   wv.get_word_vector("truck"), atol=1e-6)

    def test_full_model_round_trip(self, tmp_path):
        w2v = self._small_wv()
        p = os.path.join(tmp_path, "model.zip")
        write_full_model(w2v, p)
        w2v2 = read_full_model(p)
        assert w2v2.vocab.words() == w2v.vocab.words()
        np.testing.assert_allclose(w2v2.lookup_table.syn0, w2v.lookup_table.syn0)


class TestParagraphVectors:
    def test_doc_labels_cluster(self):
        docs = []
        for i in range(40):
            docs.append(("apple banana cherry mango apple banana", [f"fruit_{i % 2}"]))
            docs.append(("car truck bus train car truck", [f"vehicle_{i % 2}"]))
        pv = ParagraphVectors(layer_size=16, epochs=8, learning_rate=0.025,
                              batch_size=128, seed=2)
        pv.fit(docs)
        f0, f1 = pv.get_label_vector("fruit_0"), pv.get_label_vector("fruit_1")
        v0 = pv.get_label_vector("vehicle_0")
        cos = lambda a, b: float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))
        assert cos(f0, f1) > cos(f0, v0)

    def test_infer_vector_close_to_label(self):
        rng = np.random.default_rng(1)
        ta = [f"a{i}" for i in range(12)]
        tb = [f"b{i}" for i in range(12)]
        docs = []
        for _ in range(60):
            docs.append((" ".join(rng.choice(ta, 6, replace=False)), ["topicA"]))
            docs.append((" ".join(rng.choice(tb, 6, replace=False)), ["topicB"]))
        pv = ParagraphVectors(layer_size=24, epochs=10, learning_rate=0.025,
                              batch_size=128, seed=4)
        pv.fit(docs)
        assert pv.predict("a1 a2 a3 a4") == "topicA"
        assert pv.predict("b1 b2 b3 b4") == "topicB"


class TestGlove:
    def test_loss_decreases_and_clusters(self):
        g = Glove(layer_size=16, window=3, epochs=20, learning_rate=0.1,
                  batch_size=2048, seed=5)
        g.fit(_toy_corpus(100))
        assert g.loss_history[-1] < g.loss_history[0]
        assert g.similarity("apple", "banana") > g.similarity("apple", "car")


class TestVectorizers:
    def test_bow_counts(self):
        v = BagOfWordsVectorizer()
        v.fit(["a b a", "b c"])
        vec = v.transform("a a c")
        assert vec[v.vocab.index_of("a")] == 2
        assert vec[v.vocab.index_of("c")] == 1

    def test_tfidf_downweights_common(self):
        v = TfidfVectorizer()
        v.fit(["common rare1", "common rare2", "common rare3"])
        vec = v.transform("common rare1")
        assert vec[v.vocab.index_of("rare1")] > vec[v.vocab.index_of("common")]

    def test_vectorize_to_dataset(self):
        v = TfidfVectorizer()
        v.fit(["x y", "z w"])
        ds = v.vectorize(["x y", "z w"], [0, 1])
        assert ds.features.shape == (2, 4)
        assert ds.labels.shape == (2, 2)


def test_paragraph_vectors_host_fallback_path():
    """device_pairgen=False exposes the per-batch host path (the
    equivalence-test path) through the public constructor."""
    from deeplearning4j_tpu.models.paragraphvectors.paragraphvectors import (
        ParagraphVectors)

    docs = [("apple banana cherry fruit sweet", ["food"]),
            ("car engine wheel road drive", ["auto"])] * 10
    pv = ParagraphVectors(layer_size=16, epochs=4, batch_size=32,
                          seed=3, device_pairgen=False)
    pv.fit(docs)
    assert pv.doc_vectors.shape == (2, 16)
    assert np.isfinite(pv.doc_vectors).all()


def test_cbow_hierarchical_softmax_trains():
    """CBOW + HS (both public builder knobs, CBOW.java HS branch):
    previously crashed; must train topical structure."""
    w2v = Word2Vec(layer_size=32, window_size=3, epochs=15,
                   learning_rate=0.05, batch_size=256, seed=7,
                   elements_learning_algorithm="cbow",
                   negative_sample=0, use_hierarchic_softmax=True)
    w2v.fit(_wide_corpus())
    ins = np.mean([w2v.similarity("a0", x) for x in ["a1", "a2", "a3"]])
    crs = np.mean([w2v.similarity("a0", x) for x in ["b1", "b2", "b3"]])
    assert ins > crs + 0.1, (ins, crs)
