"""DeepWalk, k-means, KD-tree, t-SNE tests — ports of the reference's
``deeplearning4j-graph`` tests and clustering/plot coverage."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering
from deeplearning4j_tpu.graph import DeepWalk, Graph, RandomWalkIterator, WeightedRandomWalkIterator
from deeplearning4j_tpu.graph.graph import load_edge_list
from deeplearning4j_tpu.plot import TSNE


def _two_cliques(n=8):
    """Two n-cliques joined by a single bridge edge."""
    g = Graph(2 * n)
    for base in (0, n):
        for i in range(n):
            for j in range(i + 1, n):
                g.add_edge(base + i, base + j)
    g.add_edge(0, n)
    return g


class TestGraph:
    def test_adjacency(self):
        g = Graph(3)
        g.add_edge(0, 1)
        g.add_edge(1, 2)
        assert g.get_connected_vertices(1) == [0, 2]
        assert g.degree(0) == 1

    def test_directed(self):
        g = Graph(2, directed=True)
        g.add_edge(0, 1)
        assert g.get_connected_vertices(0) == [1]
        assert g.get_connected_vertices(1) == []

    def test_edge_list_loader(self, tmp_path):
        p = os.path.join(tmp_path, "edges.txt")
        with open(p, "w") as f:
            f.write("# comment\n0 1\n1 2 2.5\n")
        g = load_edge_list(p)
        assert g.num_vertices() == 3
        assert g.get_connected_with_weights(1) == [(0, 1.0), (2, 2.5)]

    def test_random_walks(self):
        g = _two_cliques(4)
        walks = list(RandomWalkIterator(g, walk_length=5, seed=1))
        assert len(walks) == 8
        for w in walks:
            assert len(w) == 6
            for a, b in zip(w, w[1:]):
                assert b in g.get_connected_vertices(a) or a == b

    def test_weighted_walks_prefer_heavy_edges(self):
        g = Graph(3)
        g.add_edge(0, 1, 100.0)
        g.add_edge(0, 2, 0.01)
        it = WeightedRandomWalkIterator(g, walk_length=1, seed=0, walks_per_vertex=50)
        firsts = [w[1] for w in it if w[0] == 0]
        assert firsts.count(1) > firsts.count(2)


class TestDeepWalk:
    def test_clique_structure_embeds(self):
        g = _two_cliques(8)
        dw = DeepWalk(vector_size=16, window_size=4, walk_length=20,
                      walks_per_vertex=8, epochs=3, learning_rate=0.05,
                      batch_size=256, seed=3)
        dw.fit(g)
        in_clique = dw.similarity(1, 2)
        cross = dw.similarity(1, 9)
        assert in_clique > cross, (in_clique, cross)

    def test_save_load(self, tmp_path):
        g = _two_cliques(4)
        dw = DeepWalk(vector_size=8, walk_length=8, epochs=1, batch_size=128)
        dw.fit(g)
        p = os.path.join(tmp_path, "dw.txt")
        dw.save(p)
        wv = DeepWalk.load(p, g)
        np.testing.assert_allclose(wv.get_word_vector("3"),
                                   dw.get_vertex_vector(3), atol=1e-5)


class TestKMeans:
    def test_separated_blobs(self):
        rng = np.random.default_rng(0)
        a = rng.normal((0, 0), 0.2, (50, 2))
        b = rng.normal((5, 5), 0.2, (50, 2))
        c = rng.normal((0, 5), 0.2, (50, 2))
        x = np.concatenate([a, b, c])
        km = KMeansClustering(k=3, seed=4).fit(x)
        labels = km.predict(x)
        # each blob maps to exactly one cluster
        for blob in (labels[:50], labels[50:100], labels[100:]):
            assert len(set(blob.tolist())) == 1
        assert len({labels[0], labels[50], labels[100]}) == 3

    def test_cosine_distance(self):
        x = np.array([[1, 0], [2, 0], [0, 1], [0, 3.0]])
        km = KMeansClustering(k=2, distance="cosine", seed=1).fit(x)
        l = km.predict(x)
        assert l[0] == l[1] and l[2] == l[3] and l[0] != l[2]

    def test_k_larger_than_n_raises(self):
        with np.testing.assert_raises(ValueError):
            KMeansClustering(k=5).fit(np.zeros((3, 2)))


class TestKDTree:
    def test_nn_matches_brute_force(self):
        rng = np.random.default_rng(1)
        pts = rng.standard_normal((200, 3))
        tree = KDTree(pts)
        for _ in range(20):
            q = rng.standard_normal(3)
            i, d = tree.nn(q)
            bi = int(np.argmin(np.linalg.norm(pts - q, axis=1)))
            assert i == bi

    def test_knn_sorted(self):
        rng = np.random.default_rng(2)
        pts = rng.standard_normal((100, 2))
        tree = KDTree(pts)
        q = np.zeros(2)
        res = tree.knn(q, 5)
        dists = [d for _, d in res]
        assert dists == sorted(dists)
        brute = np.sort(np.linalg.norm(pts - q, axis=1))[:5]
        np.testing.assert_allclose(dists, brute, rtol=1e-9)


class TestTSNE:
    def test_blobs_stay_separated(self):
        rng = np.random.default_rng(3)
        a = rng.normal(0, 0.3, (30, 10))
        b = rng.normal(4, 0.3, (30, 10))
        x = np.concatenate([a, b])
        emb = TSNE(perplexity=10, n_iter=300, seed=5).fit_transform(x)
        assert emb.shape == (60, 2)
        ca, cb = emb[:30].mean(0), emb[30:].mean(0)
        spread = max(emb[:30].std(), emb[30:].std())
        assert np.linalg.norm(ca - cb) > 2 * spread


def test_cluster_set_api(rng):
    """ClusterSet framework (ClusterSet.java role): membership with
    distances, nearest-cluster lookup, summary stats."""
    from deeplearning4j_tpu.clustering.kmeans import ClusterSet, KMeansClustering

    blobs = np.concatenate([
        rng.standard_normal((30, 2)) * 0.2 + c
        for c in ([0, 0], [5, 5], [0, 5])]).astype(np.float32)
    km = KMeansClustering(k=3, seed=5).fit(blobs)
    cs = ClusterSet(km, blobs)
    assert len(cs) == 3
    assert sum(len(c) for c in cs) == 90
    # each original blob lands in one cluster
    lab = km.predict(blobs)
    for start in (0, 30, 60):
        assert len(set(lab[start:start + 30])) == 1
    near = cs.cluster_of(np.array([5.1, 4.9], np.float32))
    assert np.linalg.norm(near.center - [5, 5]) < 1.0
    assert cs.total_average_distance() > 0
    assert near.max_distance() >= near.average_distance()
