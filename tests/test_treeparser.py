"""Treebank parser depth (VERDICT r3 missing #3): head finding, tree
transforms, vectorization — treeparser/HeadWordFinder.java,
CollapseUnaries.java, BinarizeTreeTransformer.java, TreeVectorizer.java."""
import numpy as np

from deeplearning4j_tpu.text.treeparser import (
    BinarizeTreeTransformer,
    CollapseUnaries,
    HeadWordFinder,
    TreeVectorizer,
)
from deeplearning4j_tpu.text.trees import Tree


def _pt(pos, tok):
    return Tree(pos, [Tree(tok, token=tok)])


def _np():
    # (NP (DT the) (JJ quick) (NN fox))
    return Tree("NP", [_pt("DT", "the"), _pt("JJ", "quick"), _pt("NN", "fox")])


def _s():
    vp = Tree("VP", [_pt("VBZ", "jumps"),
                     Tree("PP", [_pt("IN", "over"), _np()])])
    return Tree("S", [_np(), vp])


class TestHeadWordFinder:
    def test_np_head_is_noun(self):
        h = HeadWordFinder()
        assert h.head_token(_np()) == "fox"

    def test_s_head_percolates_through_vp(self):
        # S -> VP (head1), VP -> VBZ (head1) => "jumps"
        assert HeadWordFinder().head_token(_s()) == "jumps"

    def test_pp_head_is_preposition(self):
        pp = Tree("PP", [_pt("IN", "over"), _np()])
        assert HeadWordFinder().head_token(pp) == "over"

    def test_same_label_fallback(self):
        # no head1/head2 rule for (FOO (BAR x) (FOO y)): same-label wins
        t = Tree("FOO", [_pt("BAR", "x"), Tree("FOO", [_pt("BAR", "y")])])
        assert HeadWordFinder().head_token(t) == "y"

    def test_top_unwraps(self):
        top = Tree("TOP", [_s()])
        assert HeadWordFinder().head_token(top) == "jumps"


class TestTransformers:
    def test_collapse_unaries(self):
        # (X (Y (Z (NN dog)))) -> preterminal chain collapses
        t = Tree("X", [Tree("Y", [Tree("Z", [_pt("NN", "dog")])])])
        out = CollapseUnaries().transform(t)
        assert out.label == "X"
        assert out.yield_tokens() == ["dog"]
        # only branching/preterminal/leaf nodes remain
        for st in out.subtrees():
            assert st.is_leaf() or st.is_preterminal() or len(st.children) > 1

    def test_binarize_left(self):
        out = BinarizeTreeTransformer("left").transform(_np())
        assert out.yield_tokens() == ["the", "quick", "fox"]
        for st in out.subtrees():
            assert len(st.children) <= 2
        assert out.label == "NP"  # root label preserved

    def test_binarize_right(self):
        wide = Tree("NP", [_pt("DT", "a"), _pt("JJ", "b"), _pt("JJ", "c"),
                           _pt("NN", "d")])
        out = BinarizeTreeTransformer("right").transform(wide)
        assert out.yield_tokens() == ["a", "b", "c", "d"]
        for st in out.subtrees():
            assert len(st.children) <= 2

    def test_binarize_markov_suffix_bounded(self):
        wide = Tree("NP", [_pt("JJ", c) for c in "abcde"])
        out = BinarizeTreeTransformer("left", horizontal_markov=2).transform(wide)
        for st in out.subtrees():
            if "-(" in st.label:
                assert st.label.count("-") <= 3  # <=2 child labels in suffix

    def test_head_survives_binarize_collapse(self):
        t = CollapseUnaries().transform(
            BinarizeTreeTransformer().transform(_s()))
        assert HeadWordFinder().head_token(t) == "jumps"


class TestTreeVectorizer:
    class _Lookup:
        def vector(self, word):
            if word == "unknownword":
                return None
            return np.full(4, float(len(word)), np.float32)

    def test_get_trees_binarized(self):
        tv = TreeVectorizer()
        trees = tv.get_trees("The quick brown fox jumps over the lazy dog.")
        assert trees
        for t in trees:
            for st in t.subtrees():
                assert len(st.children) <= 2

    def test_vectorize_attaches_leaf_vectors(self):
        tv = TreeVectorizer()
        vecs = tv.vectorize("The dog runs", self._Lookup())
        assert vecs and vecs[0]
        for tok, v in vecs[0].items():
            assert v.shape == (4,) and v[0] == len(tok)


def test_binarize_labels_balanced_sexpr():
    """Introduced labels close their parenthesis, so the serialized
    tree is a parseable s-expression (balanced parens)."""
    wide = Tree("NP", [_pt("JJ", c) for c in "abcd"])
    for factor in ("left", "right"):
        out = BinarizeTreeTransformer(factor).transform(wide)
        s = out.to_sexpr()
        assert s.count("(") == s.count(")"), s


def test_include_pp_head():
    # (X (XX (NN y)) (PP ...)): level 5 skips PP by default, so the
    # earlier non-terminal wins; with include_pp_head the later PP also
    # qualifies at level 5 and replaces it (reference cascade order)
    pp = Tree("PP", [_pt("IN", "over")])
    t = Tree("X", [Tree("XX", [_pt("NN", "y")]), pp])
    assert HeadWordFinder().head_token(t) == "y"
    assert HeadWordFinder(include_pp_head=True).head_token(t) == "over"
