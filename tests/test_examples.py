"""Examples smoke tests: every shipped example must run end-to-end.

The dl4j-examples role — these are the first thing a migrating user
runs, so they are CI-gated in smoke mode (tiny shapes, CPU mesh).
"""

import numpy as np


def test_lenet_example():
    from examples.train_lenet_mnist import main
    acc = main(smoke=True, report_path="/tmp/test_lenet_report.html")
    assert 0.0 <= acc <= 1.0
    assert open("/tmp/test_lenet_report.html").read().startswith("<!DOCTYPE")


def test_char_rnn_example():
    from examples.train_char_rnn import main
    assert np.isfinite(main(smoke=True))


def test_word2vec_example():
    from examples.train_word2vec import main
    w2v = main(smoke=True)
    assert len(w2v.words_nearest("king", 3)) == 3


def test_gpt_example_variants():
    from examples.train_gpt import main
    assert np.isfinite(main(smoke=True))
    assert np.isfinite(main(smoke=True, num_experts=2))
    assert np.isfinite(main(smoke=True, seq_parallel=True))


def test_resnet_example():
    from examples.train_resnet50 import main
    assert np.isfinite(main(smoke=True))


def test_pipelined_gpt_example():
    import jax
    import pytest

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    from examples.train_gpt_pipelined import main
    assert np.isfinite(main(smoke=True, stages=2))


def test_train_from_export_example():
    from examples.train_from_export import main
    assert np.isfinite(main(smoke=True))


def test_train_with_ui_example():
    from examples.train_with_ui import main
    assert np.isfinite(main(smoke=True))


def test_word2vec_cjk_example():
    from examples.train_word2vec_cjk import main
    w2v = main(smoke=True)
    assert len(w2v.words_nearest("日本語", 3)) == 3
    w2v_ko = main(smoke=True, korean=True)
    assert len(w2v_ko.words_nearest("한국어", 3)) == 3


def test_tsne_mnist_view_example():
    from examples.tsne_mnist_view import main
    coords = main(smoke=True)
    assert coords.shape == (60, 2) and np.isfinite(coords).all()


def test_serve_fleet_example():
    from examples.serve_fleet import main
    snap = main(["--endpoints", "2", "--requests", "8"])
    assert snap["failovers"] >= 0 and snap["total_endpoints"] == 2
