"""Native IO kernel tests (C++ via ctypes, Python fallback parity).

The native side of the data plane (deeplearning4j_tpu/native): where
the reference's feed path bottoms out in libnd4j/DataVec native code,
ours compiles a small C++ library on first use and falls back to NumPy
transparently.
"""

import struct

import numpy as np
import pytest

from deeplearning4j_tpu.native import (
    _csv_read_floats_py, csv_read_floats, get_lib, idx_read)


def test_csv_native_matches_python(tmp_path, rng):
    data = rng.standard_normal((500, 7)).astype(np.float32)
    path = str(tmp_path / "data.csv")
    np.savetxt(path, data, delimiter=",", fmt="%.6f")
    a = csv_read_floats(path)
    b = _csv_read_floats_py(path, 0)
    np.testing.assert_allclose(a, b, rtol=1e-6)
    np.testing.assert_allclose(a, data, atol=1e-5)


def test_csv_skip_rows_and_non_numeric(tmp_path):
    path = str(tmp_path / "h.csv")
    with open(path, "w") as f:
        f.write("colA,colB\n1.5,2.5\nx,4.0\n")
    a = csv_read_floats(path, skip_rows=1)
    np.testing.assert_allclose(a, [[1.5, 2.5], [0.0, 4.0]])


def test_idx_native_roundtrip(tmp_path, rng):
    if get_lib() is None:
        pytest.skip("no native toolchain")
    arr = rng.integers(0, 255, (40, 5, 6)).astype(np.uint8)
    path = str(tmp_path / "t.idx")
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())
    got = idx_read(path)
    np.testing.assert_array_equal(got, arr)


def test_idx_float_dtype(tmp_path, rng):
    if get_lib() is None:
        pytest.skip("no native toolchain")
    arr = rng.standard_normal((8, 3)).astype(">f4")
    path = str(tmp_path / "f.idx")
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x0D, 2))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())
    got = idx_read(path)
    np.testing.assert_allclose(got, arr.astype(np.float32), rtol=1e-6)


def test_sequence_reader_uses_native_path(tmp_path):
    from deeplearning4j_tpu.datavec import CSVSequenceRecordReader
    p = tmp_path / "seq.csv"
    p.write_text("1,2\n3,4\n")
    r = CSVSequenceRecordReader([str(p)])
    np.testing.assert_allclose(r.next_record(), [[1, 2], [3, 4]])


def test_csv_quoted_cells_and_blank_lines(tmp_path):
    path = str(tmp_path / "q.csv")
    with open(path, "w") as f:
        f.write('\n"1.5","2.5"\n   \n3.0,4.0\n')
    a = csv_read_floats(path)
    b = _csv_read_floats_py(path, 0)
    np.testing.assert_allclose(a, [[1.5, 2.5], [3.0, 4.0]])
    np.testing.assert_allclose(a, b)


def test_csv_strict_raises_on_string_column(tmp_path):
    path = str(tmp_path / "s.csv")
    with open(path, "w") as f:
        f.write("1.0,cat\n2.0,dog\n")
    with pytest.raises(ValueError):
        csv_read_floats(path, strict=True)
    with pytest.raises(ValueError):
        _csv_read_floats_py(path, 0, strict=True)


def test_python_idx_fallback_big_endian(tmp_path, rng):
    # the pure-python IDX parser must byte-swap like the native one
    from deeplearning4j_tpu.datasets.mnist import _read_idx
    arr = rng.standard_normal((4, 3)).astype(">f4")
    path = str(tmp_path / "be.idx.gz")  # .gz path skips the native reader
    import gzip, struct as st
    with gzip.open(path, "wb") as f:
        f.write(st.pack(">HBB", 0, 0x0D, 2))
        for d in arr.shape:
            f.write(st.pack(">I", d))
        f.write(arr.tobytes())
    got = _read_idx(path)
    np.testing.assert_allclose(got, arr.astype(np.float32), rtol=1e-6)
