"""Per-model resource attribution tests (ISSUE 16).

The capacity observatory's billing half: owner-tagged KV block
byte-seconds in ``PagedKVCachePool`` obey the conservation law (the
per-owner sums equal the pool's independently integrated total —
EXACTLY, under an integer logical clock) through seeded
alloc/share/free interleavings including copy-on-write-style sharing;
a shared block bills every holder; untagged references land in the
visible ``_untagged`` bucket; mismatched-owner releases fall back
without breaking refcounts. The host tier (ISSUE 19) bills
SEPARATELY — host RAM is a different budget than device HBM — and the
same conservation law holds PER TIER through seeded interleavings of
swap_out/swap_in/share_host/free_host alongside the device ops. Above the pool, the scheduler's
``attribution()`` block meters prefill/decode tokens and queue time
per ``model[@vN]`` lane — a canary and its stable version bill
SEPARATELY through a cutover — and ``ModelRegistry.attribution()``
aggregates it across engines, with the ``/healthz`` top-K consumers
ranking riding on top.
"""

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.kvpool import UNTAGGED_OWNER, PagedKVCachePool
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving.continuous import (ContinuousDecodeScheduler,
                                                   _owner_key)
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.ui.server import _top_consumers

VOCAB = 11


def _tiny_gpt(seed=0, **kw):
    return gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=seed, **kw).init()


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


class LogicalClock:
    def __init__(self, t=0):
        self.t = t

    def tick(self, dt=1):
        self.t += dt

    def __call__(self):
        return self.t


def _pool(clock, num_blocks=16):
    return PagedKVCachePool(num_blocks, 4, num_layers=2, num_heads=2,
                            head_dim=8, clock=clock)


def _conserved(pool):
    """The conservation law, exact under the integer logical clock —
    and it holds PER TIER (host RAM bills separately from device HBM)."""
    attr = pool.attribution()
    assert sum(attr["byte_seconds"].values()) == attr["total_byte_seconds"]
    assert sum(attr["host_byte_seconds"].values()) == \
        attr["host_total_byte_seconds"]
    return attr


# ------------------------------------------------- conservation law

def test_byte_seconds_conservation_seeded_interleaving(fresh_registry):
    """Random owner-tagged alloc/share/free interleavings (the COW and
    preempt shapes included): per-owner byte-seconds sum EXACTLY to
    the pool's independently integrated total at every step, and the
    meters survive a full drain."""
    clock = LogicalClock()
    pool = _pool(clock, num_blocks=16)
    rng = np.random.default_rng(7)
    owners = ["lm@v1", "lm@v2", "embed", None]  # None -> _untagged
    # one entry per REFERENCE an owner holds: (owner_tag, block_id)
    refs = {o: [] for o in owners}
    for _ in range(300):
        clock.tick(int(rng.integers(0, 4)))
        o = owners[rng.integers(0, len(owners))]
        op = rng.integers(0, 3)
        if op == 0:  # alloc 1-3 blocks under this owner
            got = pool.alloc(int(rng.integers(1, 4)), owner=o)
            if got is not None:
                refs[o].extend(got)
        elif op == 1:  # share someone's live block (prefix-cache shape)
            donors = [d for d in owners if refs[d]]
            if donors:
                d = donors[rng.integers(0, len(donors))]
                b = refs[d][rng.integers(0, len(refs[d]))]
                pool.share_blocks([b], owner=o)
                refs[o].append(b)
        else:  # free a random subset of this owner's references
            if refs[o]:
                k = int(rng.integers(1, len(refs[o]) + 1))
                idx = rng.choice(len(refs[o]), size=k, replace=False)
                drop = [refs[o][i] for i in idx]
                pool.free_blocks(drop, owner=o)
                refs[o] = [b for i, b in enumerate(refs[o])
                           if i not in set(idx.tolist())]
        attr = _conserved(pool)
        held = {(t if t is not None else UNTAGGED_OWNER): len(r)
                for t, r in refs.items() if r}
        assert attr["held_refs"] == held
    # drain: every reference released, blocks all return, the integral
    # stops growing but never resets
    clock.tick(5)
    for o in owners:
        if refs[o]:
            pool.free_blocks(refs[o], owner=o)
            refs[o] = []
    assert pool.free_count == pool.total_blocks
    attr = _conserved(pool)
    assert attr["held_refs"] == {}
    total = attr["total_byte_seconds"]
    clock.tick(100)  # nobody holds anything: no further billing
    assert _conserved(pool)["total_byte_seconds"] == total


def test_tiered_byte_seconds_conservation_seeded_interleaving(
        fresh_registry):
    """The 300-op battery with the host tier in play: seeded
    interleavings of alloc/share/free AND swap_out/swap_in/free_host —
    per-owner sums equal each tier's independently integrated total
    EXACTLY at every step, and both tiers drain to zero held refs."""
    clock = LogicalClock()
    pool = PagedKVCachePool(16, 4, num_layers=2, num_heads=2, head_dim=8,
                            clock=clock, host_blocks=10)
    rng = np.random.default_rng(11)
    owners = ["lm@v1", "lm@v2", "embed", None]
    refs = {o: [] for o in owners}    # device references per owner
    hrefs = {o: [] for o in owners}   # host handle references per owner
    for _ in range(300):
        clock.tick(int(rng.integers(0, 4)))
        o = owners[rng.integers(0, len(owners))]
        op = rng.integers(0, 6)
        if op == 0:  # alloc 1-3 device blocks
            got = pool.alloc(int(rng.integers(1, 4)), owner=o)
            if got is not None:
                refs[o].extend(got)
        elif op == 1:  # share someone's live device block
            donors = [d for d in owners if refs[d]]
            if donors:
                d = donors[rng.integers(0, len(donors))]
                b = refs[d][rng.integers(0, len(refs[d]))]
                pool.share_blocks([b], owner=o)
                refs[o].append(b)
        elif op == 2:  # free a random subset of device references
            if refs[o]:
                k = int(rng.integers(1, len(refs[o]) + 1))
                idx = rng.choice(len(refs[o]), size=k, replace=False)
                pool.free_blocks([refs[o][i] for i in idx], owner=o)
                refs[o] = [b for i, b in enumerate(refs[o])
                           if i not in set(idx.tolist())]
        elif op == 3:  # demote: device refs -> host handles (preempt
            if refs[o]:  # / end-of-turn shape); refusal touches nothing
                k = int(rng.integers(1, min(3, len(refs[o])) + 1))
                idx = rng.choice(len(refs[o]), size=k, replace=False)
                got = pool.swap_out([refs[o][i] for i in idx], owner=o)
                if got is not None:
                    hrefs[o].extend(got)
                    refs[o] = [b for i, b in enumerate(refs[o])
                               if i not in set(idx.tolist())]
        elif op == 4:  # promote: host handles -> device refs (resume
            if hrefs[o]:  # shape); None = device full, handles stay
                k = int(rng.integers(1, min(3, len(hrefs[o])) + 1))
                idx = rng.choice(len(hrefs[o]), size=k, replace=False)
                hs = [hrefs[o][i] for i in idx]
                got = pool.swap_in(hs, owner=o)
                if got is not None:
                    refs[o].extend(got)
                    hrefs[o] = [h for i, h in enumerate(hrefs[o])
                                if i not in set(idx.tolist())]
        else:  # free a random subset of host handles
            if hrefs[o]:
                k = int(rng.integers(1, len(hrefs[o]) + 1))
                idx = rng.choice(len(hrefs[o]), size=k, replace=False)
                pool.free_host([hrefs[o][i] for i in idx], owner=o)
                hrefs[o] = [h for i, h in enumerate(hrefs[o])
                            if i not in set(idx.tolist())]
        attr = _conserved(pool)
        held = {(t if t is not None else UNTAGGED_OWNER): len(r)
                for t, r in refs.items() if r}
        host_held = {(t if t is not None else UNTAGGED_OWNER): len(r)
                     for t, r in hrefs.items() if r}
        assert attr["held_refs"] == held
        assert attr["held_host_refs"] == host_held
    # drain BOTH tiers: meters freeze, blocks and budget all return
    clock.tick(5)
    for o in owners:
        if refs[o]:
            pool.free_blocks(refs[o], owner=o)
        if hrefs[o]:
            pool.free_host(hrefs[o], owner=o)
    assert pool.free_count == pool.total_blocks
    assert pool.host_blocks_used() == 0
    attr = _conserved(pool)
    assert attr["held_refs"] == {} and attr["held_host_refs"] == {}
    dev_total, host_total = (attr["total_byte_seconds"],
                             attr["host_total_byte_seconds"])
    assert host_total > 0  # the battery really exercised the tier
    clock.tick(100)
    attr = _conserved(pool)
    assert attr["total_byte_seconds"] == dev_total
    assert attr["host_total_byte_seconds"] == host_total


def test_host_tier_bills_separately_and_exactly(fresh_registry):
    """Demotion moves the bill across tiers at the swap instant, a
    shared host handle bills every holder, and a drained tier stops
    billing — all exact under the logical clock."""
    clock = LogicalClock()
    pool = PagedKVCachePool(16, 4, num_layers=2, num_heads=2, head_dim=8,
                            clock=clock, host_blocks=8)
    bb = pool.block_bytes()
    dev = pool.alloc(2, owner="stable")
    clock.tick(10)                      # device: 2 refs x 10 s
    h = pool.swap_out(dev, owner="stable")
    assert h is not None
    clock.tick(5)                       # host: 2 handles x 5 s
    attr = _conserved(pool)
    assert attr["byte_seconds"]["stable"] == 10 * 2 * bb
    assert attr["host_byte_seconds"]["stable"] == 5 * 2 * bb
    pool.share_host(h, owner="canary")  # durable-handle pin shape
    clock.tick(3)
    attr = _conserved(pool)
    assert attr["host_byte_seconds"]["stable"] == (5 + 3) * 2 * bb
    assert attr["host_byte_seconds"]["canary"] == 3 * 2 * bb
    assert attr["held_host_refs"] == {"stable": 2, "canary": 2}
    pool.free_host(h, owner="canary")
    pool.free_host(h, owner="stable")
    assert pool.host_blocks_used() == 0
    attr = _conserved(pool)
    host_total = attr["host_total_byte_seconds"]
    clock.tick(50)                      # nobody holds anything
    assert _conserved(pool)["host_total_byte_seconds"] == host_total


def test_shared_block_bills_every_holder(fresh_registry):
    clock = LogicalClock()
    pool = _pool(clock)
    bb = pool.block_bytes()
    a = pool.alloc(2, owner="stable")
    clock.tick(10)
    pool.share_blocks(a, owner="canary")  # COW share: +1 ref per block
    clock.tick(5)
    attr = _conserved(pool)
    # stable held 2 refs for 15 s, canary 2 refs for 5 s — a shared
    # block is capacity BOTH are consuming
    assert attr["byte_seconds"]["stable"] == 15 * 2 * bb
    assert attr["byte_seconds"]["canary"] == 5 * 2 * bb
    assert attr["total_byte_seconds"] == (15 * 2 + 5 * 2) * bb
    pool.free_blocks(a, owner="stable")
    pool.free_blocks(a, owner="canary")
    assert pool.free_count == pool.total_blocks


def test_untagged_and_mismatched_owner_fallback(fresh_registry):
    """Untagged references bill the visible ``_untagged`` bucket, and
    a release naming an owner the block never carried still releases
    (billing is best-effort, refcounts are the law)."""
    clock = LogicalClock()
    pool = _pool(clock)
    got = pool.alloc(1)  # no owner tag
    clock.tick(3)
    attr = _conserved(pool)
    assert attr["byte_seconds"] == {
        UNTAGGED_OWNER: 3 * pool.block_bytes()}
    pool.free_blocks(got)
    tagged = pool.alloc(1, owner="lm")
    clock.tick(2)
    pool.free_blocks(tagged, owner="ghost")  # falls back to newest tag
    assert pool.free_count == pool.total_blocks
    attr = _conserved(pool)
    assert attr["held_refs"] == {}
    assert attr["byte_seconds"]["lm"] == 2 * pool.block_bytes()


# ------------------------------------------ scheduler + canary lanes

def test_owner_key_lane_naming():
    assert _owner_key(("lm", None)) == "lm"
    assert _owner_key(("lm", 3)) == "lm@v3"
    assert _owner_key((None, None)) == "default"


def test_scheduler_stats_attribution_block(rng, fresh_registry):
    net = _tiny_gpt()
    s = ContinuousDecodeScheduler(net=net, slots=4, burst_tokens=4,
                                  block_size=4, start=False)
    p = rng.integers(0, VOCAB, (1, 5))
    f = s.submit(p, 6)
    for _ in range(200):
        if f.done():
            break
        s.step()
    assert f.done()
    attr = s.stats()["attribution"]
    d = attr["models"]["default"]  # net-mode lane bills "default"
    # prefill computes the prompt AND emits the first token; decode
    # bills the remaining max_new - 1
    assert d["prefill_tokens"] >= 5 and d["decode_tokens"] == 5
    assert d["queue_ms"] >= 0.0
    (pool_attr,) = attr["kv_pools"]
    # wall clock here: conservation is float-rounding-close, not exact
    assert sum(pool_attr["byte_seconds"].values()) == pytest.approx(
        pool_attr["total_byte_seconds"], rel=1e-9, abs=1e-6)
    assert pool_attr["held_refs"] == {}  # drained after retirement
    assert pool_attr["byte_seconds"]["default"] > 0


def test_attribution_exact_under_canary_cutover(rng, fresh_registry):
    """A session pinned to v1 through a deploy keeps billing the v1
    lane; fresh sessions bill v2 — the cutover's cost split is exact
    per ``model@vN`` owner even though both lanes share ONE pool."""
    net1, net2 = _tiny_gpt(seed=1), _tiny_gpt(seed=9)
    reg = ModelRegistry()
    reg.register("lm", net=net1)
    eng = ParallelInference(registry=reg, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4, kv_block_size=4)
    try:
        p = rng.integers(0, VOCAB, (1, 5))
        eng.submit_generate(p, 8, model="lm", session="s1").result(30)
        reg.deploy("lm", net=net2)  # canary cutover to v2
        eng.submit_generate(p, 8, model="lm", session="s1").result(30)
        eng.submit_generate(p, 8, model="lm", session="s2").result(30)
        attr = eng.stats()["scheduler"]["attribution"]
        v1, v2 = attr["models"]["lm@v1"], attr["models"]["lm@v2"]
        # v1 served two 8-token generations, v2 one — exactly (the
        # first token of each rides its prefill: 7 decodes per request)
        assert v1["decode_tokens"] == 14 and v2["decode_tokens"] == 7
        assert v1["prefill_tokens"] >= v2["prefill_tokens"] >= 5
        (pool_attr,) = attr["kv_pools"]  # one SHARED pool, two lanes
        assert {"lm@v1", "lm@v2"} <= set(pool_attr["byte_seconds"])
        assert sum(pool_attr["byte_seconds"].values()) == pytest.approx(
            pool_attr["total_byte_seconds"], rel=1e-9, abs=1e-6)
        # the registry-level merge sees the same bill
        reg_attr = reg.attribution()
        assert reg_attr["models"] == attr["models"]
        assert len(reg_attr["kv_pools"]) == 1
    finally:
        eng.shutdown()


# --------------------------------------------- /healthz top consumers

def test_top_consumers_ranking():
    attr = {
        "models": {
            "lm@v1": {"prefill_tokens": 10, "decode_tokens": 40,
                      "queue_ms": 1.5},
            "lm@v2": {"prefill_tokens": 5, "decode_tokens": 8,
                      "queue_ms": 0.5},
            "idle": {"prefill_tokens": 99, "decode_tokens": 99,
                     "queue_ms": 0.0},
        },
        "kv_pools": [
            {"byte_seconds": {"lm@v1": 100.0, "lm@v2": 500.0}},
            {"byte_seconds": {"lm@v1": 50.0, UNTAGGED_OWNER: 700.0}},
        ],
    }
    ranked = _top_consumers(attr, k=3)
    # byte-seconds rank first (summed across pools), tokens tie-break
    assert [o["owner"] for o in ranked] == [UNTAGGED_OWNER, "lm@v2",
                                            "lm@v1"]
    assert ranked[2]["kv_byte_seconds"] == 150.0
    assert ranked[2]["prefill_tokens"] == 10
    # k truncates AFTER ranking: "idle" (no KV held) fell off
    assert _top_consumers(attr, k=4)[-1]["owner"] == "idle"
