"""Capacity-observatory time-series tests (monitor/timeseries.py).

The ISSUE-16 determinism battery: aligned-bucket placement under a
logical clock (bit-identical repeat queries), the downsample-agreement
property (a coarse-tier query equals the direct aggregation of the
fine buckets it covers, open bucket included), strictly-oldest-first
ring eviction with fold-before-evict, the deterministic
keep-the-earliest sample cap with visible ``dropped_samples``,
nearest-rank percentiles, the bounded ``TimeSeriesStore`` (absence ->
None, oldest-created eviction at ``max_series``), heartbeat
``summary()`` / ``merge_summaries`` arithmetic, the
``set_timeseries_enabled`` kill switch around ``ts_record``, the
``UiServer /timeseries`` JSON endpoint, and the flight recorder's
sustained-SLO-burn auto-trigger riding ``dl4j_ts_slo_burn``.
"""

import json
import math
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import reqtrace
from deeplearning4j_tpu.monitor.timeseries import (
    DEFAULT_TIERS,
    TS_SLO_BURN,
    TimeSeries,
    TimeSeriesStore,
    merge_summaries,
    set_timeseries_enabled,
    timeseries_enabled,
    ts_query,
    ts_record,
)
from deeplearning4j_tpu.ui import InMemoryStatsStorage, UiServer


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


class LogicalClock:
    """Injectable deterministic clock: ``tick()`` advances, call reads."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def tick(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# --------------------------------------------------- aligned buckets

def test_aligned_bucket_placement_and_repeat_query_identity():
    """A sample at time t lands in floor(t / width) of the finest tier,
    and the same query against the same clock is bit-identical —
    windows are aligned, never sliding."""
    clock = LogicalClock()
    store = TimeSeriesStore(clock=clock)
    for t, v in [(0.2, 1.0), (0.7, 3.0), (1.1, 5.0), (2.9, 7.0)]:
        clock.t = t
        store.record("m", v)
    view = store.series("m").tier_view(0)
    assert [(b["index"], b["count"], b["total"]) for b in view] == [
        (0, 2, 4.0), (1, 1, 5.0), (2, 1, 7.0)]
    clock.t = 3.0
    q1 = store.query("m", 10.0)
    q2 = store.query("m", 10.0)
    assert q1 == q2  # repeat query: bit-identical under a fixed clock
    assert q1["count"] == 4 and q1["rate"] == 4 / 10.0
    assert q1["mean"] == 4.0 and q1["min"] == 1.0 and q1["max"] == 7.0
    # a window covering only the newest buckets excludes older ones:
    # lo = floor(3.0) - 2 + 1 = 2 -> bucket 2 only
    q = store.query("m", 2.0)
    assert q["count"] == 1 and q["mean"] == 7.0


def test_query_empty_window_is_nan_not_error():
    clock = LogicalClock()
    store = TimeSeriesStore(clock=clock)
    store.record("m", 1.0)
    clock.t = 500.0  # every 1s bucket long out of the 60s window
    q = store.query("m", 60.0)
    assert q["count"] == 0 and math.isnan(q["mean"])
    assert math.isnan(q["min"]) and math.isnan(q["p99"])
    with pytest.raises(ValueError):
        store.series("m").query(0.0, clock.t)


# ----------------------------------------------- downsample agreement

def test_downsample_tier_agreement():
    """A coarse-tier query equals the direct aggregation of the raw
    values it covers: folds are eager on advance(), the open fine
    bucket is folded in at read time, so nothing is double- or
    under-counted across the tier boundary."""
    clock = LogicalClock()
    store = TimeSeriesStore(clock=clock)
    values = []
    for i in range(100):  # one sample per second over 100 s
        clock.t = float(i)
        v = float((i * 7) % 13)
        store.record("m", v)
        values.append(v)
    clock.t = 99.5
    # window 600 s > 1s-tier span (120 s handles it too, so force the
    # coarse path with a long window served from the 10 s tier)
    q = store.query("m", 600.0)
    assert q["tier_s"] == 10.0
    assert q["count"] == len(values)
    assert q["mean"] == pytest.approx(sum(values) / len(values))
    assert q["min"] == min(values) and q["max"] == max(values)
    s = sorted(values)
    assert q["p50"] == s[max(1, math.ceil(0.50 * len(s))) - 1]
    assert q["p99"] == s[max(1, math.ceil(0.99 * len(s))) - 1]
    # and the fine-tier answer over its own span agrees with the raw
    # tail of the stream
    qf = store.query("m", 50.0)
    assert qf["tier_s"] == 1.0
    tail = values[-50:]
    assert qf["count"] == 50 and qf["mean"] == pytest.approx(
        sum(tail) / 50)


def test_fold_before_evict_keeps_downsampled_history():
    """Fine buckets evicted from their ring have already folded into
    every coarser tier — the ring never loses a bucket's downsampled
    contribution (and eviction is strictly oldest-first)."""
    clock = LogicalClock()
    ts = TimeSeries("m", tiers=((1.0, 5), (10.0, 120)))
    for i in range(10):
        ts.record(float(i), float(i))
    # fine ring: only the 5 newest buckets survive, oldest-first out
    assert [b["index"] for b in ts.tier_view(0)] == [5, 6, 7, 8, 9]
    # coarse bucket 0 carries the CLOSED fine buckets 0..8 (bucket 9
    # is still open), including the five already evicted from the ring
    (coarse,) = ts.tier_view(1)
    assert coarse["index"] == 0
    assert coarse["count"] == 9 and coarse["total"] == sum(range(9))
    # a coarse query folds the open fine bucket back in: all 10 values
    q = ts.query(600.0, now=9.0)
    assert q["tier_s"] == 10.0
    assert q["count"] == 10 and q["mean"] == pytest.approx(4.5)


# ------------------------------------------- sample cap + percentiles

def test_keep_earliest_sample_cap_counts_dropped():
    clock = LogicalClock()
    store = TimeSeriesStore(clock=clock, samples_per_bucket=4)
    for v in range(1, 11):  # ten samples into one 1 s bucket
        store.record("m", float(v))
    q = store.query("m", 10.0)
    assert q["count"] == 10        # aggregates never truncate
    assert q["sampled"] == 4       # the earliest four survive
    assert q["dropped_samples"] == 6
    assert q["p50"] == 2.0 and q["p99"] == 4.0  # over [1, 2, 3, 4]
    assert q["max"] == 10.0        # min/max track ALL values


def test_nearest_rank_percentiles():
    clock = LogicalClock()
    store = TimeSeriesStore(clock=clock)
    for v in range(1, 101):
        store.record("m", float(v))
    q = store.query("m", 10.0)
    assert q["p50"] == 50.0 and q["p99"] == 99.0
    store.record("single", 42.0)
    q1 = store.query("single", 10.0)
    assert q1["p50"] == 42.0 and q1["p99"] == 42.0


# --------------------------------------------------------- the store

def test_store_absent_series_and_bounded_eviction():
    clock = LogicalClock()
    store = TimeSeriesStore(clock=clock, max_series=2)
    assert store.query("never", 60.0) is None  # absence is an answer
    store.record("a", 1.0)
    store.record("b", 2.0)
    store.record("c", 3.0)  # evicts "a" — oldest-created first
    assert store.names() == ["b", "c"]
    assert store.query("a", 60.0) is None
    assert store.query("c", 60.0)["count"] == 1


def test_summary_and_merge_summaries():
    clock = LogicalClock()
    s1 = TimeSeriesStore(clock=clock)
    s1.record("x", 2.0)
    s1.record("x", 4.0)
    s1.record("only1", 1.0)
    s2 = TimeSeriesStore(clock=clock)
    for _ in range(4):
        s2.record("x", 6.0)
    a, b = s1.summary(), s2.summary()
    assert a["window_s"] == 60.0
    assert a["series"]["x"] == {"count": 2, "rate": round(2 / 60.0, 6),
                                "mean": 3.0, "p99": 4.0}
    merged = merge_summaries([a, b, None, {"junk": 1}])  # junk skipped
    mx = merged["series"]["x"]
    assert mx["count"] == 6                        # counts add
    assert mx["rate"] == pytest.approx(6 / 60.0)   # rates add
    assert mx["mean"] == pytest.approx(5.0)        # count-weighted
    assert mx["p99"] == 6.0                        # max: upper bound
    assert merged["series"]["only1"]["count"] == 1
    assert merge_summaries([]) == {"window_s": None, "series": {}}


def test_summary_name_filter():
    clock = LogicalClock()
    store = TimeSeriesStore(clock=clock)
    store.record("keep", 1.0)
    store.record("drop", 1.0)
    out = store.summary(names=["keep", "ghost"])
    assert list(out["series"]) == ["keep"]


# ------------------------------------- module hooks + the kill switch

def test_ts_record_roundtrip_and_kill_switch(fresh_registry):
    assert timeseries_enabled()
    ts_record("dl4j_ts_sched_active_rows", 3.0)
    q = ts_query("dl4j_ts_sched_active_rows", 60.0)
    assert q is not None and q["count"] == 1 and q["mean"] == 3.0
    prev = set_timeseries_enabled(False)
    try:
        assert prev is True and not timeseries_enabled()
        ts_record("dl4j_ts_sched_active_rows", 9.0)  # dropped: disabled
    finally:
        set_timeseries_enabled(prev)
    assert ts_query("dl4j_ts_sched_active_rows", 60.0)["count"] == 1
    assert ts_query("dl4j_ts_never_recorded", 60.0) is None


def test_registry_store_is_lazy_and_per_registry(fresh_registry):
    reg2 = monitor.MetricsRegistry()
    assert reg2._timeseries is None  # built on first touch only
    reg2.timeseries.record("m", 1.0)
    assert fresh_registry.timeseries.query("m", 60.0) is None
    assert reg2.timeseries.query("m", 60.0)["count"] == 1


# ------------------------------------------------ /timeseries endpoint

def test_ui_timeseries_endpoint(fresh_registry):
    fresh_registry.timeseries.record("dl4j_ts_router_shed", 1.0)
    srv = UiServer(InMemoryStatsStorage(), registry=fresh_registry,
                   port=0).start()
    try:
        one = json.loads(urllib.request.urlopen(
            srv.url + "/timeseries?name=dl4j_ts_router_shed&window=60"
        ).read())
        assert one["name"] == "dl4j_ts_router_shed"
        assert one["count"] == 1 and one["window_s"] == 60.0
        snap = json.loads(urllib.request.urlopen(
            srv.url + "/timeseries").read())
        assert "dl4j_ts_router_shed" in snap["process"]
        assert set(snap["process"]["dl4j_ts_router_shed"]) == {
            "10.0", "60.0", "600.0"}
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/timeseries?name=ghost")
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                srv.url + "/timeseries?name=x&window=banana")
        assert e.value.code == 400
    finally:
        srv.stop()


# ------------------------------------------- SLO-burn flight trigger

def test_slo_burn_auto_trigger_threshold_and_cooldown(fresh_registry):
    """The burn auto-trigger fires exactly when the trailing-window
    burn count crosses the armed threshold, and the cooldown collapses
    a sustained incident into one trigger."""
    try:
        rec = reqtrace.configure_flight_recorder(burn_threshold=3,
                                                 burn_window_s=60.0,
                                                 burn_cooldown_s=3600.0)
        for i in range(5):
            ts_record(TS_SLO_BURN, 1.0)
            reqtrace.note_slo_burn("missed", model="lm")
        triggers = [e for e in rec.records()
                    if e.get("kind") == "trigger"]
        assert len(triggers) == 1  # fired at 3, cooled down at 4 and 5
        t = triggers[0]
        assert t["attrs"]["reason"] == "slo_burn"
        assert t["attrs"]["burned"] == 3 and t["attrs"]["threshold"] == 3
        assert t["attrs"]["model"] == "lm"
    finally:
        reqtrace.configure_flight_recorder()  # disarm: threshold=None


def test_slo_burn_trigger_disarmed_by_default(fresh_registry):
    reqtrace.configure_flight_recorder()  # no burn_threshold
    ts_record(TS_SLO_BURN, 1.0)
    assert reqtrace.note_slo_burn("missed") is None


# ---------------------------------------------------------- defaults

def test_default_tiers_are_finest_first_and_bounded():
    assert DEFAULT_TIERS == ((1.0, 120), (10.0, 120), (60.0, 120))
    with pytest.raises(ValueError):
        TimeSeries("bad", tiers=((10.0, 4), (1.0, 4)))
    with pytest.raises(ValueError):
        TimeSeries("bad", tiers=())
