"""AttentionLayer tests: gradcheck, masking, ring-attention auto-select.

VERDICT r1 #8: attention as a first-class layer backed by
``ops/attention.py`` with ring attention auto-selected under a
``sequence_mesh`` context. No reference counterpart (SURVEY §7.7).
"""

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import AttentionLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import make_mesh, sequence_mesh


def _conf(causal=False, residual=True, f=8, heads=2):
    return (NeuralNetConfiguration.builder().seed(11).learning_rate(0.05)
            .updater("adam").activation("tanh").weight_init("xavier")
            .list()
            .layer(AttentionLayer(n_in=f, n_out=f, num_heads=heads,
                                  causal=causal, residual=residual))
            .layer(RnnOutputLayer(n_in=f, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())


def test_attention_layer_trains_and_gradchecks(rng):
    net = MultiLayerNetwork(_conf()).init(dtype=jax.numpy.float64)
    x = rng.standard_normal((4, 6, 8))
    y = np.eye(3)[rng.integers(0, 3, (4, 6))]
    res = check_gradients(net, DataSet(x, y))
    assert res.ok, res
    net32 = MultiLayerNetwork(_conf(causal=True)).init()
    ds = DataSet(x.astype(np.float32), y.astype(np.float32))
    net32.fit(ds)
    s0 = net32.score()
    for _ in range(15):
        net32.fit(ds)
    assert net32.score() < s0


def test_attention_causality(rng):
    """With causal=True, output at time t must not depend on inputs >t."""
    net = MultiLayerNetwork(_conf(causal=True, residual=False)).init()
    x = rng.standard_normal((2, 6, 8)).astype(np.float32)
    base = net.output(x)
    x2 = x.copy()
    x2[:, -1] += 10.0  # perturb only the last timestep
    out2 = net.output(x2)
    np.testing.assert_allclose(out2[:, :-1], base[:, :-1], rtol=1e-4, atol=1e-5)
    assert np.abs(out2[:, -1] - base[:, -1]).max() > 1e-4


def test_attention_mask_zeroes_padded_steps(rng):
    net = MultiLayerNetwork(_conf()).init()
    x = rng.standard_normal((2, 5, 8)).astype(np.float32)
    mask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 5))]
    net.fit(DataSet(x, y, features_mask=mask, labels_mask=mask))
    assert np.isfinite(net.score())


def test_ring_attention_dp_sp_composition_matches_full(rng):
    """DP×SP: batch sharded over 'data', time ringed over 'seq' in ONE
    mesh — output must equal single-device full attention."""
    devs = jax.devices()
    if len(devs) < 8:
        import pytest
        pytest.skip("needs 8 CPU devices")
    net = MultiLayerNetwork(_conf(causal=True)).init()
    x = rng.standard_normal((4, 8, 8)).astype(np.float32)
    full = net.output(x)
    mesh = make_mesh({"data": 2, "seq": 4}, devices=devs[:8])
    with sequence_mesh(mesh):
        composed = net.output(x)
    np.testing.assert_allclose(composed, full, rtol=2e-4, atol=2e-5)


def test_ring_attention_auto_select_matches_full(rng):
    """Same params, same input: output under a seq mesh (ring kernel)
    must match the single-device full-attention output."""
    devs = jax.devices()
    if len(devs) < 4:
        import pytest
        pytest.skip("needs 4 CPU devices")
    net = MultiLayerNetwork(_conf(causal=True)).init()
    x = rng.standard_normal((2, 8, 8)).astype(np.float32)
    full = net.output(x)  # traced OUTSIDE the context first — the jit
    mesh = make_mesh({"seq": 4}, devices=devs[:4])
    with sequence_mesh(mesh):  # cache must key on the seq context
        ringed = net.output(x)
    full2 = net.output(x)  # and revert cleanly after exit
    np.testing.assert_allclose(ringed, full, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(full2, full, rtol=1e-6)
