"""Distributed evaluation equivalence tests.

Parity: ``SparkDl4jMultiLayer.evaluate`` / evaluation reduce
(SURVEY.md §2.6) — mesh-sharded confusion counts must equal the
host-side ``Evaluation`` over the same data.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.evaluation import evaluate_sharded


def _ff_net():
    conf = (NeuralNetConfiguration.builder().seed(11).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=6, n_out=12))
            .layer(OutputLayer(n_in=12, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _host_eval(net, ds):
    ev = Evaluation()
    ev.eval(ds.labels, net.output(ds.features),
            mask=ds.labels_mask)
    return ev


def test_matches_host_eval(rng):
    net = _ff_net()
    x = rng.standard_normal((64, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    ds = DataSet(x, y)
    host = _host_eval(net, ds)
    dist = evaluate_sharded(net, ds)
    np.testing.assert_array_equal(dist.confusion.counts, host.confusion.counts)
    assert dist.accuracy() == host.accuracy()


def test_ragged_batches_and_iterator(rng):
    """61 examples over 8 devices: every batch has a padded tail."""
    net = _ff_net()
    x = rng.standard_normal((61, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 61)]
    ds = DataSet(x, y)
    host = _host_eval(net, ds)
    dist = evaluate_sharded(net, ListDataSetIterator(ds, 16))
    np.testing.assert_array_equal(dist.confusion.counts, host.confusion.counts)


def test_num_classes_wider_than_labels(rng):
    """num_classes > label width embeds counts (classes absent from the
    split); narrower raises (regression: used to crash on broadcast)."""
    import pytest

    net = _ff_net()
    x = rng.standard_normal((16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    ds = DataSet(x, y)
    dist = evaluate_sharded(net, ds, num_classes=5)
    assert dist.confusion.counts.shape == (5, 5)
    assert dist.confusion.counts[:3, :3].sum() == 16
    assert dist.confusion.counts[3:, :].sum() == 0
    with pytest.raises(ValueError):
        evaluate_sharded(net, ds, num_classes=2)


def test_regression_sharded_matches_host(rng):
    from deeplearning4j_tpu.eval.regression import RegressionEvaluation
    from deeplearning4j_tpu.parallel.evaluation import evaluate_regression_sharded

    conf = (NeuralNetConfiguration.builder().seed(2).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=5, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="identity",
                               loss_function="mse"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((37, 5)).astype(np.float32)  # ragged over 8 devs
    y = rng.standard_normal((37, 2)).astype(np.float32)
    host = RegressionEvaluation()
    host.eval(y, net.output(x))
    dist = evaluate_regression_sharded(net, DataSet(x, y), batch_size=16)
    for c in range(2):
        assert dist.mean_squared_error(c) == pytest.approx(
            host.mean_squared_error(c), rel=1e-6)
        assert dist.r_squared(c) == pytest.approx(host.r_squared(c), rel=1e-5)
        assert dist.pearson_correlation(c) == pytest.approx(
            host.pearson_correlation(c), rel=1e-5)


def test_roc_sharded_matches_host(rng):
    from deeplearning4j_tpu.eval.roc import ROC
    from deeplearning4j_tpu.parallel.evaluation import evaluate_roc_sharded

    conf = (NeuralNetConfiguration.builder().seed(4).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((45, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 45)]
    host = ROC(50)
    host.eval(y, net.output(x))
    dist = evaluate_roc_sharded(net, DataSet(x, y), threshold_steps=50)
    np.testing.assert_array_equal(dist.tp, host.tp)
    np.testing.assert_array_equal(dist.fp, host.fp)
    assert (dist.pos, dist.neg) == (host.pos, host.neg)
    assert dist.calculate_auc() == pytest.approx(host.calculate_auc())


def test_time_series_with_mask(rng):
    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(GravesLSTM(n_in=4, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    b, t = 24, 7
    x = rng.standard_normal((b, t, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (b, t))]
    lmask = (rng.random((b, t)) > 0.3).astype(np.float32)
    lmask[:, 0] = 1.0
    ds = DataSet(x, y, labels_mask=lmask)
    host = _host_eval(net, ds)
    dist = evaluate_sharded(net, ds)
    np.testing.assert_array_equal(dist.confusion.counts, host.confusion.counts)
    assert dist.confusion.counts.sum() == int(lmask.sum())


def test_dense_classifier_with_class_count_matching_time_dim(rng):
    """ADVICE r2 regression: [b, 3, 2, 1] image features with 3 one-hot
    classes — y.shape == x.shape[:2] by coincidence, but the model emits
    [b, 3] (rank-2) predictions, so this must stay a per-ROW evaluation,
    not become a bogus [b, 3] 'time series' with a broadcast crash."""
    from deeplearning4j_tpu.nn.conf.preprocessors import (
        CnnToFeedForwardPreProcessor)

    conf = (NeuralNetConfiguration.builder().seed(9).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=6, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .input_preprocessor(0, CnnToFeedForwardPreProcessor())
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((16, 3, 2, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    assert y.shape == x.shape[:2]  # the coincidence under test
    host = Evaluation()
    host.eval(y, net.output(x))
    dist = evaluate_sharded(net, DataSet(x, y))
    np.testing.assert_array_equal(dist.confusion.counts,
                                  host.confusion.counts)


def test_sparse_labels_match_onehot_eval(rng):
    """Sparse int-id labels give the same confusion counts as one-hot —
    host Evaluation and mesh-sharded eval, incl. ignore-index."""
    net = _ff_net()
    x = rng.standard_normal((24, 6)).astype(np.float32)
    ids = rng.integers(0, 3, 24)
    onehot = np.eye(3, dtype=np.float32)[ids]
    sparse = ids.astype(np.float32)
    preds = net.output(x)

    host_a = Evaluation(); host_a.eval(onehot, preds)
    host_b = Evaluation(); host_b.eval(sparse, preds)
    np.testing.assert_array_equal(host_a.confusion.counts,
                                  host_b.confusion.counts)

    dist = evaluate_sharded(net, DataSet(x, sparse))
    np.testing.assert_array_equal(dist.confusion.counts,
                                  host_a.confusion.counts)
    # ignore-index rows drop out of the counts
    sparse_ig = sparse.copy(); sparse_ig[:5] = -1.0
    host_c = Evaluation(); host_c.eval(sparse_ig, preds)
    assert host_c.confusion.counts.sum() == 19
    dist_ig = evaluate_sharded(net, DataSet(x, sparse_ig))
    np.testing.assert_array_equal(dist_ig.confusion.counts,
                                  host_c.confusion.counts)


def test_sparse_label_out_of_range_raises_in_sharded_eval(rng):
    """Same loud contract as host Evaluation.eval: an id >= the class
    width must not silently vanish from the device one-hot counts."""
    net = _ff_net()
    x = rng.standard_normal((8, 6)).astype(np.float32)
    bad = np.array([0, 1, 2, 7, 0, 1, 2, 0], np.float32)  # 7 >= 3 classes
    with pytest.raises(ValueError, match="sparse label id 7"):
        evaluate_sharded(net, DataSet(x, bad))


def test_masked_sentinel_ids_do_not_raise(rng):
    """Out-of-range ids at MASKED timesteps are padding sentinels, not
    errors — only unmasked entries are validated."""
    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(GravesLSTM(n_in=4, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    b, t = 8, 5
    x = rng.standard_normal((b, t, 4)).astype(np.float32)
    ids = rng.integers(0, 3, (b, t)).astype(np.float32)
    mask = np.ones((b, t), np.float32)
    mask[:, -2:] = 0.0
    ids[:, -2:] = 99.0  # sentinel well past the class width, masked out
    dist = evaluate_sharded(net, DataSet(x, ids, labels_mask=mask))
    assert dist.confusion.counts.sum() == int(mask.sum())
    # but an UNMASKED out-of-range id still raises
    ids2 = ids.copy(); ids2[0, 0] = 99.0
    with pytest.raises(ValueError, match="sparse label id 99"):
        evaluate_sharded(net, DataSet(x, ids2, labels_mask=mask))


def test_computation_graph_sharded_eval(rng):
    """The sharded evaluators also accept a ComputationGraph (the
    SparkComputationGraph.evaluate role) — equal to host eval."""
    from deeplearning4j_tpu.nn.graph import (
        ComputationGraph, ComputationGraphConfiguration)

    b = (ComputationGraphConfiguration.GraphBuilder()
         .add_inputs("in")
         .add_layer("d1", DenseLayer(n_in=6, n_out=10), "in")
         .add_layer("out", OutputLayer(n_in=10, n_out=3,
                                       activation="softmax",
                                       loss_function="mcxent"), "d1")
         .set_outputs("out"))
    net = ComputationGraph(b.build()).init()
    x = rng.standard_normal((32, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    host = Evaluation()
    host.eval(y, net.output(x))
    dist = evaluate_sharded(net, DataSet(x, y))
    np.testing.assert_array_equal(dist.confusion.counts,
                                  host.confusion.counts)


def test_multi_output_graph_rejected(rng):
    from deeplearning4j_tpu.nn.graph import (
        ComputationGraph, ComputationGraphConfiguration)

    b = (ComputationGraphConfiguration.GraphBuilder()
         .add_inputs("in")
         .add_layer("o1", OutputLayer(n_in=6, n_out=2, activation="softmax",
                                      loss_function="mcxent"), "in")
         .add_layer("o2", OutputLayer(n_in=6, n_out=2, activation="softmax",
                                      loss_function="mcxent"), "in")
         .set_outputs("o1", "o2"))
    net = ComputationGraph(b.build()).init()
    x = rng.standard_normal((8, 6)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    with pytest.raises(ValueError, match="single-input/single-output"):
        evaluate_sharded(net, DataSet(x, y))
