"""Transformer block + GPT zoo tests.

SURVEY §7.7 extension layers: gradcheck, causality, training, and the
single-config single-chip vs DP×SP sequence-parallel equivalence.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    RnnOutputLayer, SequenceEmbeddingLayer, TransformerBlock)
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import make_mesh, sequence_mesh


def _tiny_gpt(vocab=11, d=16, layers=2, max_len=16, dropout=0.0):
    return gpt(vocab_size=vocab, d_model=d, n_layers=layers, num_heads=2,
               max_len=max_len, dropout=dropout, compute_dtype="float32",
               learning_rate=0.01).init()


def _data(rng, vocab=11, b=4, t=8):
    ids = rng.integers(0, vocab, (b, t))
    x = ids.astype(np.float32)
    y = np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    return DataSet(x, y)


def test_gpt_trains(rng):
    net = _tiny_gpt()
    ds = _data(rng)
    s0 = net.score(ds)
    for _ in range(30):
        net.fit(ds)
    s1 = net.score(ds)
    assert np.isfinite(s1) and s1 < s0 * 0.7, (s0, s1)


def test_transformer_block_gradcheck(rng):
    """FD-vs-analytic on a block stack over continuous inputs (the
    framework's correctness oracle, GradientCheckUtil doctrine)."""
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .updater("sgd").activation("identity").weight_init("xavier")
            .list()
            .layer(TransformerBlock(n_in=8, n_out=8, num_heads=2, causal=True))
            .layer(RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((2, 4, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 4))]
    assert check_gradients(net, DataSet(x, y))


def test_causality(rng):
    """Changing a future token must not change earlier logits."""
    net = _tiny_gpt()
    ids = rng.integers(0, 11, (1, 8))
    out1 = net.output(ids.astype(np.float32))
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 1) % 11
    out2 = net.output(ids2.astype(np.float32))
    np.testing.assert_allclose(out1[0, :-1], out2[0, :-1], rtol=1e-5, atol=1e-6)
    assert np.abs(out1[0, -1] - out2[0, -1]).max() > 1e-6


def test_seq_mesh_equivalence(rng):
    """Same params: single-chip flash output == DP×SP ring output."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 CPU devices")
    net = _tiny_gpt(d=16, layers=2, max_len=16)
    x = rng.integers(0, 11, (4, 8)).astype(np.float32)
    full = net.output(x)
    mesh = make_mesh({"data": 2, "seq": 4}, devices=devs[:8])
    with sequence_mesh(mesh):
        ringed = net.output(x)
    np.testing.assert_allclose(ringed, full, rtol=2e-4, atol=1e-5)


def test_moe_transformer_trains_and_gradchecks(rng):
    """Mixtral wiring: TransformerBlock with routed expert MLPs."""
    net = gpt(vocab_size=11, d_model=16, n_layers=2, num_heads=2,
              max_len=16, compute_dtype="float32", learning_rate=0.01,
              num_experts=4).init()
    ds = _data(rng)
    s0 = net.score(ds)
    for _ in range(30):
        net.fit(ds)
    assert np.isfinite(net.score(ds)) and net.score(ds) < s0
    # gradcheck a single MoE block over continuous input
    conf = (NeuralNetConfiguration.builder().seed(9).learning_rate(0.1)
            .updater("sgd").activation("identity").weight_init("xavier")
            .list()
            .layer(TransformerBlock(n_in=8, n_out=8, num_heads=2,
                                    causal=True, num_experts=2,
                                    capacity_factor=8.0))
            .layer(RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    blk = MultiLayerNetwork(conf).init()
    x = (rng.standard_normal((2, 4, 8)) * 2.0).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 4))]
    assert check_gradients(blk, DataSet(x, y))


def test_bf16_policy_keeps_ids_exact(rng):
    """Regression: the mixed-precision input cast must not touch token
    ids — bf16(257) rounds to 256, silently swapping embeddings (and
    bf16(511) == 512 goes out of range). ids >= 256 must select their
    own rows under a bf16 compute policy."""
    net = gpt(vocab_size=512, d_model=16, n_layers=1, num_heads=2,
              max_len=8, compute_dtype="bfloat16", seed=3).init()
    a = net.output(np.full((1, 4), 257.0, np.float32))
    b = net.output(np.full((1, 4), 256.0, np.float32))
    c = net.output(np.full((1, 4), 511.0, np.float32))
    assert np.abs(a - b).max() > 1e-6, "id 257 collapsed onto 256"
    assert np.abs(c - b).max() > 1e-6, "id 511 corrupted"
    # and bf16 training through the scanned path stays finite
    ids = rng.integers(0, 512, (8, 8))
    ds = DataSet(ids.astype(np.float32),
                 np.eye(512, dtype=np.float32)[np.roll(ids, -1, 1)])
    scores = net.fit_scan(None, 4, epochs=1, staged=net.stage_scan(ds, 4))
    assert np.isfinite(scores).all()


def test_kv_cache_generate_matches_full_forward(rng):
    """Greedy generate() with KV caches must produce exactly the tokens
    the O(t²) full-window argmax loop produces."""
    from deeplearning4j_tpu.models.zoo.transformer import generate

    net = _tiny_gpt(vocab=11, d=16, layers=2, max_len=16)
    ds = _data(rng)
    for _ in range(10):
        net.fit(ds)
    prompt = rng.integers(0, 11, (2, 3))
    got = generate(net, prompt, max_new_tokens=8)

    # oracle: full forward per step
    want = np.array(prompt, np.int64)
    for _ in range(8):
        logits = net.output(want.astype(np.float32))
        nxt = np.argmax(logits[:, -1], axis=-1)
        want = np.concatenate([want, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, want)


def test_generate_moe_and_sampling(rng):
    from deeplearning4j_tpu.models.zoo.transformer import generate

    net = gpt(vocab_size=11, d_model=16, n_layers=1, num_heads=2,
              max_len=12, compute_dtype="float32", num_experts=2).init()
    prompt = rng.integers(0, 11, (4, 2))  # b=4 > per-expert train capacity
    out = generate(net, prompt, max_new_tokens=4, temperature=1.0, seed=3)
    assert out.shape == (4, 6)
    assert (out >= 0).all() and (out < 11).all()
    # greedy decode is deterministic and the cached jits reproduce it
    g1 = generate(net, prompt, max_new_tokens=4)
    g2 = generate(net, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(g1, g2)
    # the fused engine caches one prefill program (per cache length) and
    # one decode program (per max_new × sampler) on the net
    assert any(k[0] == "gen_prefill" for k in net._jits)
    assert ("gen_decode", 4, 0.0, 0, 0.0, None) in net._jits
    # top-k=1 sampling degenerates to greedy regardless of temperature
    g3 = generate(net, prompt, max_new_tokens=4, temperature=5.0, top_k=1)
    np.testing.assert_array_equal(g3, g1)
    # nucleus filter produces valid tokens
    g4 = generate(net, prompt, max_new_tokens=4, temperature=1.0, top_p=0.8)
    assert (g4 >= 0).all() and (g4 < 11).all()
    with pytest.raises(ValueError, match="max_len"):
        generate(net, prompt, max_new_tokens=100)


def test_embedding_rejects_overlong(rng):
    net = _tiny_gpt(max_len=8)
    with pytest.raises(ValueError, match="max_len"):
        net.output(rng.integers(0, 11, (1, 9)).astype(np.float32))


def test_block_validation():
    with pytest.raises(ValueError, match="divisible"):
        conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(0.1)
                .updater("sgd").activation("identity")
                .list()
                .layer(TransformerBlock(n_in=10, n_out=10, num_heads=3))
                .layer(RnnOutputLayer(n_in=10, n_out=2, activation="softmax",
                                      loss_function="mcxent"))
                .build())
        MultiLayerNetwork(conf).init()


def test_serialization_roundtrip(rng, tmp_path):
    from deeplearning4j_tpu.util.model_serializer import (
        restore_model, write_model)
    net = _tiny_gpt()
    ds = _data(rng)
    net.fit(ds)
    path = str(tmp_path / "gpt.zip")
    write_model(net, path)
    net2 = restore_model(path)
    np.testing.assert_allclose(net.output(ds.features),
                               net2.output(ds.features), rtol=1e-6)
