"""DataSetPreProcessor seam (setPreProcessor contract): normalizers and
combined preprocessors attach to every iterator family."""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    CombinedPreProcessor,
    DataSetPreProcessor,
    ExistingDataSetIterator,
    ListDataSetIterator,
    SamplingDataSetIterator,
)
from deeplearning4j_tpu.datasets.normalizers import NormalizerStandardize


def _ds(rng, n=20, f=4):
    x = (rng.standard_normal((n, f)) * 5 + 3).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return DataSet(x, y)


class _Shift(DataSetPreProcessor):
    def __init__(self, k):
        self.k = k

    def pre_process(self, ds):
        return DataSet(np.asarray(ds.features) + self.k, ds.labels,
                       ds.features_mask, ds.labels_mask)


def test_normalizer_as_pre_processor(rng):
    ds = _ds(rng, 64)
    norm = NormalizerStandardize().fit(ds)
    it = ListDataSetIterator(ds, 16)
    it.set_pre_processor(norm)
    batches = list(it)
    x = np.concatenate([np.asarray(b.features) for b in batches])
    assert abs(x.mean()) < 0.1 and abs(x.std() - 1.0) < 0.15
    assert it.pre_processor() is norm


def test_combined_pre_processor_order(rng):
    ds = _ds(rng, 8)
    it = ListDataSetIterator(ds, 4)
    it.set_pre_processor(CombinedPreProcessor(_Shift(1.0), _Shift(10.0)))
    out = next(iter(it))
    np.testing.assert_allclose(np.asarray(out.features),
                               np.asarray(ds.features[:4]) + 11.0, rtol=1e-6)


def test_async_delegates_to_wrapped(rng):
    ds = _ds(rng, 32)
    inner = ListDataSetIterator(ds, 8)
    it = AsyncDataSetIterator(inner, queue_size=2)
    it.set_pre_processor(_Shift(5.0))
    assert inner.pre_processor() is it.pre_processor()
    xs = np.concatenate([np.asarray(b.features) for b in it])
    np.testing.assert_allclose(np.sort(xs, 0),
                               np.sort(np.asarray(ds.features) + 5.0, 0),
                               rtol=1e-6)


def test_sampling_and_existing_iterators_apply_pp(rng):
    ds = _ds(rng, 16)
    s = SamplingDataSetIterator(ds, 4, total_batches=2, seed=0)
    s.set_pre_processor(_Shift(2.0))
    b = s.next()
    assert float(np.asarray(b.features).mean()) > float(
        np.asarray(ds.features).mean()) + 1.5

    e = ExistingDataSetIterator([_ds(rng, 4), _ds(rng, 4)])
    e.set_pre_processor(_Shift(3.0))
    got = list(e)
    assert len(got) == 2
    e.reset()
    assert e.has_next()


def test_exported_iterator_applies_pp(rng, tmp_path):
    from deeplearning4j_tpu.datasets.export import (
        ExportedDataSetIterator, export_dataset)
    d = str(tmp_path / "spill")
    export_dataset(_ds(rng, 16), d, batch_size=8)
    it = ExportedDataSetIterator(d)
    it.set_pre_processor(_Shift(4.0))
    x = np.concatenate([np.asarray(b.features) for b in it])
    assert x.shape[0] == 16 and float(x.mean()) > 3.0


def test_multiple_epochs_delegates(rng):
    from deeplearning4j_tpu.datasets.iterators import MultipleEpochsIterator
    ds = _ds(rng, 8)
    inner = ListDataSetIterator(ds, 4)
    it = MultipleEpochsIterator(2, inner)
    it.set_pre_processor(_Shift(7.0))
    assert inner.pre_processor() is it.pre_processor()
    batches = list(it)
    assert len(batches) == 4  # 2 epochs x 2 batches
    for b in batches:
        assert float(np.asarray(b.features).mean()) > 5.0


def test_existing_iterator_rejects_bare_generator_and_takes_factory(rng):
    import pytest
    with pytest.raises(TypeError, match="factory"):
        ExistingDataSetIterator(iter([_ds(rng, 4)]))
    e = ExistingDataSetIterator(lambda: (x for x in [_ds(rng, 4), _ds(rng, 4)]))
    assert len(list(e)) == 2
    e.reset()
    assert len(list(e)) == 2  # factory replays


def test_streaming_iterator_applies_pp(rng):
    from deeplearning4j_tpu.streaming.broker import InMemoryBroker
    from deeplearning4j_tpu.streaming.pipeline import (
        StreamingDataSetIterator, publish_dataset)
    broker = InMemoryBroker()
    ds = _ds(rng, 8)
    publish_dataset(broker, "t", ds)
    it = StreamingDataSetIterator(broker, "t", batch_size=8, idle_timeout=0.2)
    it.set_pre_processor(_Shift(9.0))
    b = it.next()
    np.testing.assert_allclose(np.sort(np.asarray(b.features), 0),
                               np.sort(np.asarray(ds.features) + 9.0, 0),
                               rtol=1e-5)


def test_existing_iterator_inplace_pp_does_not_compound(rng):
    """A mutate-in-place pre-processor must not compound across epoch
    replays nor corrupt the caller's stored DataSets (review r4)."""
    from deeplearning4j_tpu.datasets.iterators import MultipleEpochsIterator

    base = _ds(rng, 8)
    stored = [DataSet(base.features[:4], base.labels[:4]),
              DataSet(base.features[4:], base.labels[4:])]
    orig0 = np.array(stored[0].features)

    class InPlace(DataSetPreProcessor):
        def pre_process(self, ds):
            ds.features += 1.0  # mutates, returns None

    e = ExistingDataSetIterator(stored)
    e.set_pre_processor(InPlace())
    it = MultipleEpochsIterator(2, e)
    means = [float(np.asarray(b.features).mean()) for b in it]
    # both epochs see exactly +1, not +1 then +2
    assert abs(means[0] - means[2]) < 1e-5, means
    # and the caller's stored arrays are untouched
    np.testing.assert_allclose(np.asarray(stored[0].features), orig0)


def test_reconstruction_iterator(rng):
    from deeplearning4j_tpu.datasets.iterators import (
        ReconstructionDataSetIterator)
    ds = _ds(rng, 12)
    it = ReconstructionDataSetIterator(ListDataSetIterator(ds, 4))
    b = next(iter(it))
    np.testing.assert_array_equal(np.asarray(b.labels),
                                  np.asarray(b.features))
    assert sum(1 for _ in it) >= 2  # restarted by __iter__


def test_iterator_dataset_iterator_batches_singles(rng):
    from deeplearning4j_tpu.datasets.iterators import IteratorDataSetIterator
    singles = [DataSet(rng.standard_normal((1, 3)).astype(np.float32),
                       np.eye(2, dtype=np.float32)[[i % 2]])
               for i in range(7)]
    it = IteratorDataSetIterator(singles, 3)
    sizes = [np.asarray(b.features).shape[0] for b in it]
    assert sizes == [3, 3, 1]
    it.reset()
    it.set_pre_processor(_Shift(2.0))
    b = it.next()
    assert float(np.asarray(b.features).mean()) > 1.0


def test_iterator_dataset_iterator_edge_cases(rng):
    """Review r4: None elements raise (no silent truncation); mixed mask
    presence merges with all-valid fill; unlabeled streams keep None."""
    import pytest
    from deeplearning4j_tpu.datasets.iterators import IteratorDataSetIterator

    bad = [DataSet(np.ones((1, 2), np.float32), None), None]
    it = IteratorDataSetIterator(bad, 4)
    with pytest.raises(ValueError, match="None"):
        it.has_next()

    # unlabeled stream: labels stay None, not object-dtype garbage
    singles = [DataSet(np.full((1, 2), i, np.float32), None) for i in range(3)]
    b = IteratorDataSetIterator(singles, 4).next()
    assert b.labels is None and np.asarray(b.features).shape == (3, 2)

    # mixed mask presence: missing masks fill with ones
    m = np.zeros((1, 4), np.float32)
    seqs = [DataSet(rng.standard_normal((1, 4, 2)).astype(np.float32),
                    None, features_mask=m),
            DataSet(rng.standard_normal((1, 4, 2)).astype(np.float32), None)]
    b = IteratorDataSetIterator(seqs, 4).next()
    got = np.asarray(b.features_mask)
    assert got.shape == (2, 4)
    np.testing.assert_array_equal(got[0], 0)
    np.testing.assert_array_equal(got[1], 1)


def test_moving_window_iterator(rng):
    from deeplearning4j_tpu.datasets.iterators import MovingWindowDataSetIterator
    x = rng.standard_normal((3, 6, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)
    it = MovingWindowDataSetIterator(DataSet(x, y), 4, 4, batch_size=64)
    batches = list(it)
    feats = np.concatenate([np.asarray(b.features) for b in batches])
    labels = np.concatenate([np.asarray(b.labels) for b in batches])
    # 3 examples x 4 rotations x 3x3 window positions = 108 windows
    assert feats.shape == (108, 16)
    # every window carries its source example's label
    assert labels[:36].argmax(1).tolist() == [0] * 36
    # rotations really differ from the unrotated windows
    assert not np.allclose(feats[:9], feats[9:18])


def test_moving_window_is_lazy_and_complete(rng):
    """Lazy generation serves all windows across batches without ever
    holding the full expansion (review r4)."""
    from deeplearning4j_tpu.datasets.iterators import MovingWindowDataSetIterator
    x = rng.standard_normal((5, 10, 10)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)
    it = MovingWindowDataSetIterator(DataSet(x, y), 8, 8, batch_size=7)
    total = sum(np.asarray(b.features).shape[0] for b in it)
    assert total == 5 * 4 * 9  # examples x rotations x 3x3 positions
    assert it._buffered <= 7 + 4 * 9  # never more than batch + one example
    it.reset()
    b = it.next()
    assert np.asarray(b.features).shape == (7, 64)


def test_iterator_dsi_mixed_label_presence_is_diagnosed():
    """ADVICE r4: mixing labeled and unlabeled examples in one chunk
    raises a descriptive error instead of a concatenate shape crash."""
    import pytest
    from deeplearning4j_tpu.datasets.iterators import IteratorDataSetIterator
    mixed = [
        DataSet(np.ones((1, 3)), np.ones((1, 2)), None,
                np.ones((1,), np.float32)),
        DataSet(np.ones((1, 3)), None, None, None),
    ]
    with pytest.raises(ValueError, match="mixes labeled and unlabeled"):
        IteratorDataSetIterator(mixed, 4).next()
