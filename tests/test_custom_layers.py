"""User-defined custom layers through the registration seam.

Parity: ``nn/layers/custom/TestCustomLayers.java`` (SURVEY.md §4) — a
layer type defined OUTSIDE the framework must register, build, train,
and survive config JSON round-trips exactly like built-ins (the
Jackson ``registerSubtypes`` doctrine; here ``register_layer`` +
``register_impl``).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, OutputLayer, layer_from_dict, register_layer)
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import init_weights


@register_layer
@dataclasses.dataclass(frozen=True)
class ScaledDenseLayer(L.FeedForwardLayer):
    """A user layer: dense transform times a fixed scale (the role of
    the reference test's CustomLayer — any extra hyperparameter must
    serialize)."""

    scale: float = 2.0


@register_impl(ScaledDenseLayer)
class ScaledDenseImpl(LayerImpl):
    def init_params(self, key):
        c = self.conf
        W = init_weights(key, (c.n_in, c.n_out), self.weight_init,
                         c.n_in, c.n_out, c.dist_mean, c.dist_std)
        return {"W": W, "b": jnp.zeros((c.n_out,), jnp.float32)}

    def forward(self, params, x, state, train, rng=None, mask=None):
        z = (x @ params["W"] + params["b"]) * self.conf.scale
        return jnp.tanh(z), state


def _conf(scale=3.0):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("adam").activation("tanh")
            .list()
            .layer(ScaledDenseLayer(n_in=4, n_out=16, scale=scale))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())


def test_custom_layer_trains(rng):
    from deeplearning4j_tpu.datasets.iris import load_iris_dataset

    net = MultiLayerNetwork(_conf()).init()
    ds = load_iris_dataset(shuffle_seed=3)
    net.fit(ds)
    s0 = net.score()
    for _ in range(30):
        net.fit(ds)
    assert net.score() < s0 / 2
    acc = float(np.mean(net.predict(ds.features) ==
                        np.argmax(ds.labels, axis=1)))
    assert acc > 0.85, acc


def test_custom_layer_json_round_trip():
    conf = _conf(scale=5.5)
    js = conf.to_json()
    restored = type(conf).from_json(js)
    lc = restored.layers[0]
    assert isinstance(lc, ScaledDenseLayer)
    assert lc.scale == 5.5

    # restored config builds and produces identical outputs
    a = MultiLayerNetwork(conf).init()
    b = MultiLayerNetwork(restored).init()
    x = np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32)
    np.testing.assert_allclose(a.output(x), b.output(x), rtol=1e-6)


def test_custom_layer_dict_round_trip():
    d = ScaledDenseLayer(n_in=4, n_out=8, scale=1.5).to_dict()
    lc = layer_from_dict(d)
    assert isinstance(lc, ScaledDenseLayer) and lc.scale == 1.5


def test_unregistered_layer_fails_loudly():
    @dataclasses.dataclass(frozen=True)
    class NotRegistered(L.FeedForwardLayer):
        pass

    with pytest.raises(KeyError):
        layer_from_dict({"@type": "NotRegistered", "n_in": 2, "n_out": 2})


def test_custom_gradient_check(rng):
    """The custom layer passes the same finite-difference oracle as
    built-ins (GradientCheckUtil doctrine)."""
    from deeplearning4j_tpu.nn.gradientcheck import check_gradients

    net = MultiLayerNetwork(_conf()).init(dtype=jnp.float64)
    x = rng.standard_normal((6, 4))
    y = np.eye(3)[rng.integers(0, 3, 6)]
    assert check_gradients(net, DataSet(x, y))
