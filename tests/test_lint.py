"""dl4j-analyze: the unified static-analysis engine (ISSUE 15).

Per-rule fixture corpora (tests/lint_fixtures/: one CLEAN and one
SEEDED-VIOLATION file each), the suppression and baseline round-trips,
the legacy ``check_*`` shim contracts, the quick_check section-0
wiring, the EngineShutdown typed-wire fix the typed-wire-raise rule
forced, and — the acceptance bar — a repo-wide ``analyze()`` green
assertion plus the REAL serving-plane lock graph reconstructed and
proven acyclic.
"""

import importlib.util
import json
import os

import pytest

from deeplearning4j_tpu.analysis import (
    analyze,
    all_rules,
    render_json,
    write_baseline,
)
from deeplearning4j_tpu.analysis.engine import Project
from deeplearning4j_tpu.analysis.rules import rule_by_name
from deeplearning4j_tpu.analysis.rules.lock_order import build_lock_graph

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
_FIX = os.path.join(_HERE, "lint_fixtures")
_SCRIPTS = os.path.join(_ROOT, "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fixture_findings(rule_name, fixture):
    """Run ONE rule over ONE fixture file (explicit-path project —
    the file is treated as in-package)."""
    path = os.path.join(_FIX, fixture)
    project = Project(_ROOT, paths=[path], rels=[fixture])
    return rule_by_name(rule_name).check(project)


# ------------------------------------------------- per-rule corpora

#: rule -> (expected violation count in the bad fixture, a substring
#: every corpus finding's message must contain)
_CORPUS = {
    "donation-gate": (1, "CPU gate"),
    "mesh-api": (3, ""),
    "metric-name": (1, "dl4j_totally_unpinned_total"),
    "lock-order": (1, "cycle"),
    "hot-path-host-sync": (5, "sync"),
    "recompile-hazard": (4, ""),
    "typed-wire-raise": (2, "typed"),
    "prng-reuse": (3, "consumed more than once"),
}


@pytest.mark.parametrize("rule_name", sorted(_CORPUS))
def test_rule_clean_fixture_passes(rule_name):
    fixture = rule_name.replace("-", "_")
    fixture = {"hot-path-host-sync": "host_sync",
               "recompile-hazard": "recompile",
               "typed-wire-raise": "typed_raise",
               "metric-name": "metric_name",
               "prng-reuse": "prng_reuse",
               "donation-gate": "donation_gate",
               "mesh-api": "mesh_api",
               "lock-order": "lock_order"}[rule_name]
    assert _fixture_findings(rule_name, fixture + "_clean.py") == []


@pytest.mark.parametrize("rule_name", sorted(_CORPUS))
def test_rule_bad_fixture_caught(rule_name):
    stem = {"hot-path-host-sync": "host_sync",
            "recompile-hazard": "recompile",
            "typed-wire-raise": "typed_raise",
            "metric-name": "metric_name",
            "prng-reuse": "prng_reuse",
            "donation-gate": "donation_gate",
            "mesh-api": "mesh_api",
            "lock-order": "lock_order"}[rule_name]
    want_n, want_sub = _CORPUS[rule_name]
    found = _fixture_findings(rule_name, stem + "_bad.py")
    assert len(found) == want_n, [f.render() for f in found]
    for f in found:
        assert f.rule == rule_name
        assert want_sub in f.message


def test_mesh_bad_fixture_flags_all_three_shapes():
    msgs = [f.message
            for f in _fixture_findings("mesh-api", "mesh_api_bad.py")]
    assert any("jax.shard_map does not exist" in m for m in msgs)
    assert any("shard_map import" in m for m in msgs)
    assert any("raw Mesh(...)" in m for m in msgs)


def test_lock_order_bad_fixture_names_the_inversion():
    found = _fixture_findings("lock-order", "lock_order_bad.py")
    (f,) = found
    assert "PeerA._lock" in f.message and "PeerB._lock" in f.message
    assert "witness" in f.message


# ------------------------------------------- suppression round-trip

def test_suppression_same_line_and_line_above(tmp_path):
    bad = tmp_path / "sup.py"
    bad.write_text(
        "import jax\n"
        "f = jax.jit(lambda x: x, donate_argnums=(0,))"
        "  # dl4j-lint: disable=donation-gate\n"
        "# dl4j-lint: disable=donation-gate — documented why\n"
        "g = jax.jit(lambda x: x, donate_argnums=(0,))\n"
        "h = jax.jit(lambda x: x, donate_argnums=(0,))\n")
    report = analyze(_ROOT, rules=[rule_by_name("donation-gate")],
                     paths=[str(bad)], rels=["sup.py"])
    by_line = {f.line: f for f in report.findings}
    assert by_line[2].suppressed       # same-line pragma
    assert by_line[4].suppressed       # comment-line-above pragma
    assert not by_line[5].suppressed   # unsuppressed stays NEW
    assert not report.ok


def test_suppression_disable_all(tmp_path):
    bad = tmp_path / "supall.py"
    bad.write_text(
        "import jax\n"
        "f = jax.jit(lambda x: x, donate_argnums=(0,))"
        "  # dl4j-lint: disable=all\n")
    report = analyze(_ROOT, rules=[rule_by_name("donation-gate")],
                     paths=[str(bad)], rels=["supall.py"])
    assert report.ok and report.findings[0].suppressed


# --------------------------------------------- baseline round-trip

def test_baseline_roundtrip(tmp_path):
    tree = tmp_path / "repo"
    tree.mkdir()
    (tree / "bad.py").write_text(
        "import jax\nf = jax.jit(lambda x: x, donate_argnums=(0,))\n")
    baseline = tmp_path / "baseline.json"
    rules = [rule_by_name("donation-gate")]
    first = analyze(str(tree), rules=rules, baseline=str(baseline))
    assert not first.ok and len(first.new) == 1
    write_baseline(str(baseline), first.new)
    again = analyze(str(tree), rules=rules, baseline=str(baseline))
    assert again.ok
    assert [f.baselined for f in again.findings] == [True]
    # the baseline is line-free: editing ABOVE the finding keeps it
    # grandfathered
    (tree / "bad.py").write_text(
        "import jax\n# a new comment shifts the line\n"
        "f = jax.jit(lambda x: x, donate_argnums=(0,))\n")
    moved = analyze(str(tree), rules=rules, baseline=str(baseline))
    assert moved.ok and moved.findings[0].baselined
    # a NEW violation is still caught next to the baselined one
    (tree / "bad.py").write_text(
        "import jax\nf = jax.jit(lambda x: x, donate_argnums=(0,))\n"
        "g = jax.jit(lambda y: y, donate_argnums=(0, 1))\n")
    # note: same (rule, path, message) key — the baseline grandfathers
    # the finding CLASS at that path, which is the documented trade
    third = analyze(str(tree), rules=rules, baseline=str(baseline))
    assert all(f.baselined for f in third.findings)
    entries = json.loads(baseline.read_text())["findings"]
    assert entries and all("note" in e for e in entries)


# ------------------------------------------------ repo-wide greens

def test_repo_wide_analyze_green():
    """THE acceptance bar: zero unsuppressed, unbaselined findings
    across the whole tree, every rule."""
    report = analyze(_ROOT)
    assert report.ok, "\n".join(f.render() for f in report.new)
    # the run actually covered the tree and ran every rule
    assert report.files > 200
    assert len(report.rules) == len(all_rules()) == 8
    # the sweep left its documented marks: sanctioned syncs are
    # suppressed (not silently ignored), accepted hazards baselined
    c = report.counts()
    assert c["suppressed"] >= 10
    assert c["baselined"] == 2


def test_serving_plane_lock_graph_reconstructed_and_acyclic():
    """The lock-order rule sees the REAL serving plane: the known
    load-bearing locks are nodes, the router's request-lock →
    router-lock ordering and the scheduler → pool/cache edges are
    reconstructed, and the whole graph is acyclic."""
    g = build_lock_graph(Project(_ROOT))
    for lock in ("InferenceRouter._lock", "_Routed.lock",
                 "ContinuousDecodeScheduler._lock",
                 "PagedKVCachePool._lock", "PrefixCache._lock",
                 "ModelRegistry._lock", "MetricsRegistry._lock"):
        assert lock in g.nodes, sorted(g.nodes)
    edges = set(g.edges)
    assert ("_Routed.lock", "InferenceRouter._lock") in edges
    assert ("ContinuousDecodeScheduler._lock",
            "PagedKVCachePool._lock") in edges
    assert ("PrefixCache._lock", "PagedKVCachePool._lock") in edges
    assert g.cycles() == []
    # PR-18 event-loop collapse: broker client faults are DEFERRED out
    # of the transport lock, so TcpBroker no longer orders ahead of the
    # metrics locks, and the router's one clock never calls out while
    # holding its condition (no outgoing edges from the loop)
    assert "_RouterLoop._cond" in g.nodes
    assert not any(src == "_RouterLoop._cond" for src, _ in edges)
    assert ("TcpBroker._lock", "Counter._lock") not in edges
    assert ("TcpBroker._lock", "MetricsRegistry._lock") not in edges
    # the committed snapshot tracks the live reconstruction
    with open(os.path.join(_ROOT, "scripts", "lock_graph.json")) as f:
        snap = json.load(f)
    assert set(snap["nodes"]) == set(g.nodes)
    assert {(e["from"], e["to"]) for e in snap["edges"]} == edges
    assert snap["cycles"] == []


# ------------------------------------------------- shims + CLI + QC

def test_legacy_shims_keep_their_contracts(tmp_path):
    donation = _load_script("check_donation_gates")
    mesh = _load_script("check_mesh_api")
    metric = _load_script("check_metric_names")
    assert donation.check_repo(_ROOT) == []
    assert mesh.check_repo(_ROOT) == []
    assert metric.check_repo(_ROOT) == []
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "f = jax.jit(lambda x: x, donate_argnums=(0,))\n")
    assert len(donation.check_file(str(bad))) == 1
    assert donation.main([str(tmp_path)]) == 1
    assert mesh.main([_ROOT]) == 0


def test_analyze_cli_text_json_and_rules(capsys):
    az = _load_script("analyze")
    assert az.main([]) == 0
    out = capsys.readouterr().out
    assert "ok:" in out and "8 rules" in out
    assert az.main(["--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["ok"] is True
    assert data["counts"]["new"] == 0
    assert az.main(["--list-rules"]) == 0
    listing = capsys.readouterr().out
    for r in all_rules():
        assert r.name in listing
    assert az.main(["--lock-graph"]) == 0
    graph = json.loads(capsys.readouterr().out)
    assert graph["cycles"] == [] and len(graph["nodes"]) > 10
    assert az.main(["--rules", "lock-order,prng-reuse"]) == 0
    capsys.readouterr()


def test_quick_check_section0_fail_fast(monkeypatch):
    stress = _load_script("stress_faultinject")
    # clean tree: section 0 passes and contributes nothing
    assert stress.analysis_section() == []
    # a seeded finding aborts quick_check BEFORE any chaos phase
    ran = []
    monkeypatch.setattr(stress, "_scenario_log",
                        lambda seed: ran.append(seed) or "log")
    monkeypatch.setattr(
        stress, "analysis_section",
        lambda: ["analysis: x.py:1: [lock-order] seeded"])
    out = stress.quick_check(seeds=(0,))
    assert out == ["analysis: x.py:1: [lock-order] seeded"]
    assert ran == []  # fail fast: the battery never ran


def test_render_json_is_stable():
    report = analyze(_ROOT, rules=[rule_by_name("mesh-api")])
    data = json.loads(render_json(report))
    assert set(data) == {"ok", "files", "rules", "counts", "findings"}


# --------------------------------- the typed-wire fix the rule forced

def test_engine_shutdown_is_wire_typed():
    """Satellite: the bare RuntimeErrors the typed-wire-raise rule
    caught on the worker frame paths (engine/scheduler shutdown
    guards) are now EngineShutdown — registered in the wire typed-error
    family, so remote == local by type."""
    from deeplearning4j_tpu.parallel.inference import EngineShutdown
    from deeplearning4j_tpu.serving import wire
    assert issubclass(EngineShutdown, RuntimeError)
    reg = wire._typed_error_registry()
    assert reg["EngineShutdown"] is EngineShutdown
    err = wire.typed_error({"etype": "EngineShutdown",
                            "error": "engine is shut down"})
    assert isinstance(err, EngineShutdown)
    # and it round-trips through a packed error reply
    header, _ = wire.unpack_frame(
        wire.pack_reply("c1", error=EngineShutdown("down")))
    assert header["etype"] == "EngineShutdown"
    assert isinstance(wire.typed_error(header), EngineShutdown)
