"""Streaming plane tests: serde, brokers, train/serve pipelines.

Parity: ``dl4j-streaming`` — ``NDArrayKafkaClient.java`` (serde +
pub/sub), ``SparkStreamingPipeline.java`` (streaming fit),
``DL4jServeRouteBuilder.java`` (serve route).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.streaming import (
    InMemoryBroker, StreamingDataSetIterator, StreamingInference,
    StreamingTrainer, TcpBroker, TcpBrokerServer, dataset_from_bytes,
    dataset_to_bytes, ndarray_from_bytes, ndarray_to_bytes)
from deeplearning4j_tpu.streaming.pipeline import publish_dataset, publish_stop


def _net():
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(rng, n=8):
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return DataSet(x, y)


def test_serde_roundtrip(rng):
    arr = rng.standard_normal((3, 5)).astype(np.float32)
    back = ndarray_from_bytes(ndarray_to_bytes(arr))
    np.testing.assert_array_equal(arr, back)

    mask = np.ones((4, 7), np.float32)
    ds = DataSet(rng.standard_normal((4, 7, 3)).astype(np.float32),
                 rng.standard_normal((4, 7, 2)).astype(np.float32),
                 features_mask=mask, labels_mask=mask)
    ds2 = dataset_from_bytes(dataset_to_bytes(ds))
    np.testing.assert_array_equal(ds.features, ds2.features)
    np.testing.assert_array_equal(ds.labels, ds2.labels)
    np.testing.assert_array_equal(ds.features_mask, ds2.features_mask)
    ds3 = dataset_from_bytes(dataset_to_bytes(DataSet(ds.features, ds.labels)))
    assert ds3.features_mask is None and ds3.labels_mask is None


def test_inmemory_broker_fifo():
    broker = InMemoryBroker()
    broker.publish("t", b"a")
    broker.publish("t", b"b")
    assert broker.consume("t", timeout=1) == b"a"
    assert broker.consume("t", timeout=1) == b"b"
    assert broker.consume("t", timeout=0.05) is None
    assert broker.consume("other", timeout=0.05) is None


def test_tcp_broker_pubsub(rng):
    server = TcpBrokerServer(port=0).start()
    try:
        host, port = server.address
        pub = TcpBroker(host, port)
        sub = TcpBroker(host, port)
        arr = rng.standard_normal((2, 3)).astype(np.float32)
        pub.publish("nd", ndarray_to_bytes(arr))
        got = sub.consume("nd", timeout=5)
        np.testing.assert_array_equal(ndarray_from_bytes(got), arr)
        assert sub.consume("nd", timeout=0.3) is None  # empty → long-poll timeout
        pub.close()
        sub.close()
    finally:
        server.stop()


def test_streaming_iterator_microbatches(rng):
    broker = InMemoryBroker()
    for _ in range(4):
        publish_dataset(broker, "train", _ds(rng, n=8))
    publish_stop(broker, "train")
    it = StreamingDataSetIterator(broker, "train", batch_size=16)
    batches = []
    while it.has_next():
        batches.append(it.next())
    # 4×8 examples at micro-batch 16 → two 16-example batches
    assert [b.num_examples() for b in batches] == [16, 16]


def test_tcp_broker_empty_payload_survives():
    """Zero-length payloads are messages, not timeouts (regression:
    the reply framing conflated them)."""
    server = TcpBrokerServer(port=0).start()
    try:
        host, port = server.address
        c = TcpBroker(host, port)
        c.publish("t", b"")
        assert c.consume("t", timeout=5) == b""
        assert c.consume("t", timeout=0.3) is None
        c.close()
    finally:
        server.stop()


def test_microbatch_mixed_mask_presence(rng):
    """Mixed masked/unmasked parts synthesize all-ones masks instead of
    crashing or dropping padding info (regression)."""
    broker = InMemoryBroker()
    b, t = 4, 6
    mk = lambda: DataSet(rng.standard_normal((b, t, 3)).astype(np.float32),
                         rng.standard_normal((b, t, 2)).astype(np.float32))
    masked = mk()
    masked.features_mask = np.zeros((b, t), np.float32)
    masked.features_mask[:, :3] = 1.0
    masked.labels_mask = masked.features_mask.copy()
    for oi, order in enumerate([[masked, mk()], [mk(), masked]]):  # both orders
        topic = f"m{oi}"
        for part in order:
            publish_dataset(broker, topic, part)
        publish_stop(broker, topic)
        it = StreamingDataSetIterator(broker, topic, batch_size=2 * b)
        out = it.next()
        assert out.num_examples() == 2 * b
        assert out.features_mask is not None and out.labels_mask is not None
        assert out.features_mask.sum() == 3 * b + t * b  # masked part + ones


def test_streaming_trainer_fits(rng):
    broker = InMemoryBroker()
    net = _net()
    trainer = StreamingTrainer(net, broker, "train", batch_size=16).start()
    before = net.score(_ds(rng, n=32))
    for _ in range(12):
        publish_dataset(broker, "train", _ds(rng, n=8))
    publish_stop(broker, "train")
    n = trainer.join(timeout=120)
    assert n == 6  # 96 examples / 16
    assert np.isfinite(net.score(_ds(rng, n=32)))
    assert trainer.batches_fit == n
    del before


def test_streaming_inference_serves(rng):
    broker = InMemoryBroker()
    net = _net()
    serve = StreamingInference(net, broker, "in", "out").start()
    xs = [rng.standard_normal((3, 4)).astype(np.float32) for _ in range(3)]
    for x in xs:
        broker.publish("in", ndarray_to_bytes(x))
    publish_stop(broker, "in")
    served = serve.join(timeout=120)
    assert served == 3
    for x in xs:
        pred = ndarray_from_bytes(broker.consume("out", timeout=5))
        np.testing.assert_allclose(pred, np.asarray(net.output(x)),
                                   rtol=1e-5, atol=1e-6)


def test_streaming_trainer_tcp_end_to_end(rng):
    """Producer process-boundary analog: publish over TCP, train from it."""
    server = TcpBrokerServer(port=0).start()
    try:
        host, port = server.address
        producer, consumer = TcpBroker(host, port), TcpBroker(host, port)
        net = _net()
        trainer = StreamingTrainer(net, consumer, "train", batch_size=8).start()
        for _ in range(4):
            publish_dataset(producer, "train", _ds(rng, n=8))
        publish_stop(producer, "train")
        assert trainer.join(timeout=120) == 4
    finally:
        server.stop()


def test_trainer_dead_letters_poison_message(rng):
    """An undecodable message must NOT kill the consume thread (the old
    behavior): it routes to the dead-letter topic and the stream keeps
    training — tests/test_fault_tolerance.py covers the full DLQ
    contract."""
    broker = InMemoryBroker()
    net = _net()
    trainer = StreamingTrainer(net, broker, "train", batch_size=8).start()
    broker.publish("train", b"garbage, not an npz")
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    publish_dataset(broker, "train", DataSet(x, y))
    publish_stop(broker, "train")
    assert trainer.join(timeout=60) == 1  # the good batch trained
    dead = broker.consume("train.deadletter", timeout=5)
    assert dead == b"garbage, not an npz"
