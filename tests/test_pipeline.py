"""Pipeline-parallelism tests: equivalence with the sequential stack,
gradients through the pipeline, DP-composability.

No reference counterpart (SURVEY §2.6 note 5); the oracle is the plain
sequential fori over stages.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, h):
    return jnp.tanh(h @ params["W"] + params["b"])


def _stacked_params(rng, p, d):
    return {"W": jnp.asarray(rng.standard_normal((p, d, d)) * 0.5, jnp.float32),
            "b": jnp.asarray(rng.standard_normal((p, d)) * 0.1, jnp.float32)}


def _sequential(params, x, p):
    h = x
    for s in range(p):
        h = _stage_fn(jax.tree.map(lambda v: v[s], params), h)
    return h


def _need(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return devs


def test_pipeline_equals_sequential(rng):
    devs = _need(4)
    p, d, b = 4, 8, 16
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    got = pipeline_apply(params, _stage_fn, x, mesh)
    want = _sequential(params, x, p)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_more_microbatches(rng):
    devs = _need(4)
    p, d, b = 4, 6, 24
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    got = pipeline_apply(params, _stage_fn, x, mesh, microbatches=8)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x, p)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match(rng):
    """jax.grad through ppermute = the backward pipeline."""
    devs = _need(4)
    p, d, b = 4, 6, 8
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    def loss_pp(params):
        return jnp.mean((pipeline_apply(params, _stage_fn, x, mesh) - y) ** 2)

    def loss_seq(params):
        return jnp.mean((_sequential(params, x, p) - y) ** 2)

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in ("W", "b"):
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_seq[k]),
                                   rtol=2e-4, atol=1e-6)


def test_pipeline_train_step_under_jit(rng):
    """One SGD step through the pipeline, jitted end-to-end."""
    devs = _need(2)
    p, d, b = 2, 4, 8
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    @jax.jit
    def step(params):
        loss, g = jax.value_and_grad(
            lambda pr: jnp.mean((pipeline_apply(pr, _stage_fn, x, mesh) - y) ** 2)
        )(params)
        return jax.tree.map(lambda v, gv: v - 0.1 * gv, params, g), loss

    losses = []
    for _ in range(10):
        params, loss = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_batch_divisibility_validated(rng):
    devs = _need(2)
    mesh = make_mesh({"pp": 2}, devices=devs[:2])
    params = _stacked_params(rng, 2, 4)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(params, _stage_fn,
                       jnp.zeros((7, 4), jnp.float32), mesh, microbatches=2)


class TestPipelinedTransformer:
    """VERDICT r2 #5: the pipeline must drive REAL TransformerBlock
    stages, not a toy lambda — loss and gradients must equal the
    sequential MultiLayerNetwork container."""

    def _net(self):
        from deeplearning4j_tpu.models.zoo.transformer import gpt
        return gpt(vocab_size=64, d_model=16, n_layers=4, num_heads=2,
                   max_len=16, compute_dtype="float32", seed=5).init()

    def test_pipelined_gpt_loss_and_grads_equal_sequential(self, rng):
        devs = _need(4)
        from deeplearning4j_tpu.models.zoo.transformer import (
            gpt_pipeline_loss_fn, gpt_stack_blocks)

        net = self._net()
        mesh = make_mesh({"pp": 4}, devices=devs[:4])
        ids = rng.integers(0, 64, (8, 8)).astype(np.float32)
        labels = np.roll(ids, -1, axis=1).astype(np.float32)

        emb, head = net.impls[0], net.impls[-1]
        blocks = net.impls[1:-1]
        p_emb = net.params[emb.name]
        p_head = net.params[head.name]
        p_blocks = gpt_stack_blocks(net)

        loss_pp = gpt_pipeline_loss_fn(net, mesh)

        def loss_seq(p_emb, p_blocks, p_head, ids, labels):
            z, _ = emb.forward(p_emb, jnp.asarray(ids), {}, False)
            for i, b in enumerate(blocks):
                z, _ = b.forward(jax.tree.map(lambda v, i=i: v[i], p_blocks),
                                 z, {}, False)
            return head.score(p_head, z.astype(jnp.float32),
                              jnp.asarray(labels), {}, False)

        args = (p_emb, p_blocks, p_head, jnp.asarray(ids), jnp.asarray(labels))
        l_pp, g_pp = jax.value_and_grad(loss_pp, argnums=(0, 1, 2))(*args)
        l_sq, g_sq = jax.value_and_grad(loss_seq, argnums=(0, 1, 2))(*args)
        assert float(l_pp) == pytest.approx(float(l_sq), rel=1e-5)
        flat_pp = jax.tree.leaves(g_pp)
        flat_sq = jax.tree.leaves(g_sq)
        for a, b in zip(flat_pp, flat_sq):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=1e-6)

    def test_pipelined_gpt_trains(self, rng):
        devs = _need(4)
        from deeplearning4j_tpu.models.zoo.transformer import (
            gpt_pipelined_train_step, gpt_stack_blocks, gpt_unstack_blocks)

        net = self._net()
        mesh = make_mesh({"pp": 4}, devices=devs[:4])
        ids = rng.integers(0, 64, (8, 8)).astype(np.float32)
        labels = np.roll(ids, -1, axis=1).astype(np.float32)
        p_emb = net.params[net.impls[0].name]
        p_head = net.params[net.impls[-1].name]
        p_blocks = gpt_stack_blocks(net)
        step = gpt_pipelined_train_step(net, mesh, learning_rate=0.05)
        losses = []
        for _ in range(8):
            p_emb, p_blocks, p_head, loss = step(
                p_emb, p_blocks, p_head, jnp.asarray(ids), jnp.asarray(labels))
            losses.append(float(loss))
        assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
        # round-trip the trained stages back onto the container
        gpt_unstack_blocks(net, p_blocks)
        net.params = {**net.params, net.impls[0].name: p_emb,
                      net.impls[-1].name: p_head}
        out = net.output(ids)
        assert np.isfinite(out).all()

    def test_moe_blocks_rejected(self, rng):
        """MoE blocks carry router aux loss in state the pipeline does
        not thread — they must be rejected loudly, not silently train a
        different objective than the container."""
        devs = _need(2)
        from deeplearning4j_tpu.models.zoo.transformer import (
            gpt, gpt_pipeline_loss_fn)
        net = gpt(vocab_size=32, d_model=16, n_layers=2, num_heads=2,
                  max_len=8, num_experts=2, compute_dtype="float32").init()
        mesh = make_mesh({"pp": 2}, devices=devs[:2])
        with pytest.raises(NotImplementedError, match="dense"):
            gpt_pipeline_loss_fn(net, mesh)
