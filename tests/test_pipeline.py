"""Pipeline-parallelism tests: equivalence with the sequential stack,
gradients through the pipeline, DP-composability.

No reference counterpart (SURVEY §2.6 note 5); the oracle is the plain
sequential fori over stages.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, h):
    return jnp.tanh(h @ params["W"] + params["b"])


def _stacked_params(rng, p, d):
    return {"W": jnp.asarray(rng.standard_normal((p, d, d)) * 0.5, jnp.float32),
            "b": jnp.asarray(rng.standard_normal((p, d)) * 0.1, jnp.float32)}


def _sequential(params, x, p):
    h = x
    for s in range(p):
        h = _stage_fn(jax.tree.map(lambda v: v[s], params), h)
    return h


def _need(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return devs


def test_pipeline_equals_sequential(rng):
    devs = _need(4)
    p, d, b = 4, 8, 16
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    got = pipeline_apply(params, _stage_fn, x, mesh)
    want = _sequential(params, x, p)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_more_microbatches(rng):
    devs = _need(4)
    p, d, b = 4, 6, 24
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    got = pipeline_apply(params, _stage_fn, x, mesh, microbatches=8)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x, p)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match(rng):
    """jax.grad through ppermute = the backward pipeline."""
    devs = _need(4)
    p, d, b = 4, 6, 8
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    def loss_pp(params):
        return jnp.mean((pipeline_apply(params, _stage_fn, x, mesh) - y) ** 2)

    def loss_seq(params):
        return jnp.mean((_sequential(params, x, p) - y) ** 2)

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in ("W", "b"):
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_seq[k]),
                                   rtol=2e-4, atol=1e-6)


def test_pipeline_train_step_under_jit(rng):
    """One SGD step through the pipeline, jitted end-to-end."""
    devs = _need(2)
    p, d, b = 2, 4, 8
    mesh = make_mesh({"pp": p}, devices=devs[:p])
    params = _stacked_params(rng, p, d)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    @jax.jit
    def step(params):
        loss, g = jax.value_and_grad(
            lambda pr: jnp.mean((pipeline_apply(pr, _stage_fn, x, mesh) - y) ** 2)
        )(params)
        return jax.tree.map(lambda v, gv: v - 0.1 * gv, params, g), loss

    losses = []
    for _ in range(10):
        params, loss = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_batch_divisibility_validated(rng):
    devs = _need(2)
    mesh = make_mesh({"pp": 2}, devices=devs[:2])
    params = _stacked_params(rng, 2, 4)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(params, _stage_fn,
                       jnp.zeros((7, 4), jnp.float32), mesh, microbatches=2)
