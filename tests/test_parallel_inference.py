"""ParallelInference engine tests: coalescing, result identity,
latency flush, backpressure, error propagation, shutdown drain, AOT
warmup, and the StreamingInference end-to-end round trip.

Parity doctrine: batched rows must be bitwise-identical to an inline
``net.output`` run on the same rows. XLA CPU special-cases batch-1
programs (gemv path, 1-ulp drift vs the gemm path), so the bitwise
assertions compare request sizes >= 2 (and coalesced singletons against
the concatenated inline run) — the same program-identity framing as the
PR 2 bucketing parity tests.
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (ListDataSetIterator,
                                                   bucket_for, bucket_sizes)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.inference import (InferenceBackpressure,
                                                   ParallelInference)
from deeplearning4j_tpu.streaming import (InMemoryBroker, StreamingInference,
                                          ndarray_from_bytes, ndarray_to_bytes)
from deeplearning4j_tpu.streaming.pipeline import publish_stop

N_IN, N_OUT = 4, 3


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture
def net():
    return _net()


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


def test_bucket_helpers():
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_sizes(12) == (1, 2, 4, 8, 12)
    assert bucket_sizes(1) == (1,)
    assert bucket_for(3, (1, 2, 4, 8)) == 4
    assert bucket_for(8, (1, 2, 4, 8)) == 8
    assert bucket_for(9, (1, 2, 4, 8)) == 9  # oversize passes through
    with pytest.raises(ValueError):
        bucket_sizes(0)


def test_concurrent_submit_result_identity(net, rng):
    """Every caller gets exactly its own rows, bitwise-equal to the
    inline output() run on those rows."""
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=2.0,
                            replicas=2)
    try:
        xs = [rng.standard_normal((2 + i % 3, N_IN)).astype(np.float32)
              for i in range(24)]
        refs = [np.asarray(net.output(x)) for x in xs]
        results = [None] * len(xs)

        def submit_some(lo, hi):
            futs = [(j, eng.submit(xs[j])) for j in range(lo, hi)]
            for j, f in futs:
                results[j] = f.result(timeout=60)

        threads = [threading.Thread(target=submit_some, args=(k, k + 6))
                   for k in range(0, 24, 6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for x, r, ref in zip(xs, results, refs):
            assert r.shape == (x.shape[0], N_OUT)
            np.testing.assert_array_equal(r, ref)
        assert eng.stats()["requests"] == 24
    finally:
        eng.shutdown()


def test_singleton_coalescing_row_routing(net, rng):
    """Singleton requests coalesced into one batch each resolve to the
    same rows as the inline run of the concatenated batch (bitwise)."""
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=50.0,
                            replicas=1, eager_when_idle=False)
    try:
        xs = [rng.standard_normal((1, N_IN)).astype(np.float32)
              for _ in range(8)]
        futs = [eng.submit(x) for x in xs]
        rows = [f.result(timeout=60) for f in futs]
        ref = np.asarray(net.output(np.concatenate(xs)))
        np.testing.assert_array_equal(np.concatenate(rows), ref)
        # 8 singletons under one max_latency window == one full batch
        assert eng.stats()["batches"] == 1
        assert eng.stats()["rows_padded"] == 0
    finally:
        eng.shutdown()


def test_eager_dispatch_when_idle(net, rng):
    """Default discipline: an idle replica dispatches a lone request
    immediately instead of sitting out the coalescing window."""
    eng = ParallelInference(net, max_batch_size=64, max_latency_ms=500.0,
                            replicas=1)
    try:
        eng.warmup([(N_IN,)])
        t0 = time.perf_counter()
        eng.output(rng.standard_normal((2, N_IN)).astype(np.float32),
                   timeout=60)
        assert time.perf_counter() - t0 < 0.4  # never waited out 500ms
    finally:
        eng.shutdown()


def test_max_latency_flush(net, rng):
    """A lone sub-batch request must flush when max_latency_ms elapses,
    not wait for a full batch."""
    eng = ParallelInference(net, max_batch_size=64, max_latency_ms=30.0,
                            replicas=1, eager_when_idle=False)
    try:
        eng.warmup([(N_IN,)])  # exclude compile time from the bound
        t0 = time.perf_counter()
        fut = eng.submit(rng.standard_normal((2, N_IN)).astype(np.float32))
        fut.result(timeout=60)
        elapsed = time.perf_counter() - t0
        assert elapsed >= 0.02  # held for the coalescing window...
        assert elapsed < 5.0    # ...but flushed by the timer
        # padded onto the bucket ladder: 2 rows is already a bucket
        assert eng.stats()["rows_dispatched"] == 2
    finally:
        eng.shutdown()


def test_padding_to_bucket(net, rng):
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=1.0,
                            replicas=1)
    try:
        fut = eng.submit(rng.standard_normal((3, N_IN)).astype(np.float32))
        out = fut.result(timeout=60)
        assert out.shape == (3, N_OUT)  # de-padded
        s = eng.stats()
        assert s["rows_dispatched"] == 4  # 3 padded up to bucket 4
        assert s["rows_padded"] == 1
        assert 0.0 < s["padded_ratio"] <= 0.25
    finally:
        eng.shutdown()


def test_backpressure_reject_and_deferred_start(net, rng):
    """With reject_when_full the queue bounds admission; a deferred
    start drains the backlog once running."""
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            queue_capacity=2, reject_when_full=True,
                            replicas=1, start=False)
    x = rng.standard_normal((1, N_IN)).astype(np.float32)
    f1, f2 = eng.submit(x), eng.submit(x)
    with pytest.raises(InferenceBackpressure):
        eng.submit(x)
    eng.start()
    r1, r2 = f1.result(timeout=60), f2.result(timeout=60)
    np.testing.assert_array_equal(r1, r2)
    eng.shutdown()


def test_submit_rejects_bad_rank_and_closed(net, rng):
    eng = ParallelInference(net, replicas=1)
    with pytest.raises(ValueError):
        eng.submit(np.zeros((N_IN,), np.float32))  # no batch dim
    eng.shutdown()
    with pytest.raises(RuntimeError):
        eng.submit(np.zeros((1, N_IN), np.float32))


def test_worker_error_propagates_to_futures(net, rng):
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            replicas=1)
    bad = rng.standard_normal((2, N_IN + 3)).astype(np.float32)  # wrong width
    fut = eng.submit(bad)
    with pytest.raises(Exception):
        fut.result(timeout=60)
    # engine survives for well-formed traffic...
    good = rng.standard_normal((2, N_IN)).astype(np.float32)
    np.testing.assert_array_equal(eng.output(good, timeout=60),
                                  np.asarray(net.output(good)))
    # ...and shutdown re-raises the first worker error
    with pytest.raises(Exception):
        eng.shutdown()


def test_shutdown_drains_in_flight(net, rng):
    """shutdown(drain=True) racing a burst of submits must resolve every
    accepted future."""
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=2.0,
                            replicas=2)
    xs = [rng.standard_normal((1 + i % 4, N_IN)).astype(np.float32)
          for i in range(32)]
    futs = [eng.submit(x) for x in xs]
    eng.shutdown()  # immediately: queued work must still complete
    for x, f in zip(xs, futs):
        assert f.result(timeout=60).shape == (x.shape[0], N_OUT)
    assert eng.stats()["requests"] == 32


def test_shutdown_no_drain_cancels_queued(net, rng):
    eng = ParallelInference(net, queue_capacity=8, replicas=1, start=False)
    futs = [eng.submit(np.zeros((1, N_IN), np.float32)) for _ in range(3)]
    eng.shutdown(drain=False)
    for f in futs:
        with pytest.raises(RuntimeError):
            f.result(timeout=5)


def test_warmup_precompiles_bucket_set(net, rng, fresh_registry):
    """After warmup(shapes) the serve loop performs ZERO fresh
    trace+compiles across ragged request sizes within the bucket set —
    asserted via dl4j_jit_cache_miss_total."""
    reg = fresh_registry
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=1.0,
                            replicas=2)
    try:
        compiled = eng.warmup([(N_IN,)])
        assert compiled == len(bucket_sizes(8)) * 2  # buckets x replicas
        warm = reg.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        assert warm == compiled
        for n in (1, 2, 3, 4, 5, 7, 8, 6, 1, 5):  # ragged request mix
            eng.output(rng.standard_normal((n, N_IN)).astype(np.float32),
                       timeout=60)
        assert reg.family_total(monitor.JIT_CACHE_MISS_COUNTER) == warm
        assert reg.family_total(monitor.INFER_REQUESTS_COUNTER) == 10
        assert reg.family_total(monitor.INFER_BATCHES_COUNTER) >= 1
    finally:
        eng.shutdown()


def test_engine_metrics_in_prometheus_exposition(net, rng, fresh_registry):
    """dl4j_infer_* families render valid, schema-pinned exposition
    (the UiServer /metrics contract)."""
    import scripts.check_telemetry_schema as schema
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            replicas=1)
    try:
        eng.output(rng.standard_normal((3, N_IN)).astype(np.float32),
                   timeout=60)
    finally:
        eng.shutdown()
    text = fresh_registry.prometheus_text()
    assert "dl4j_infer_requests_total" in text
    assert "dl4j_infer_batch_size_bucket" in text
    assert schema.validate_prometheus_text(text) == []
    assert schema.validate_known_metrics(text) == []


def test_moe_style_models_disable_coalescing(rng):
    """A model with cross-batch statistics must not be padded/coalesced
    (INPLACE mode): each request dispatches alone, unpadded."""
    net = _net()
    net.impls[0].batch_statistics = True  # simulate MoE capacity routing
    eng = ParallelInference(net, max_batch_size=8, max_latency_ms=10.0,
                            replicas=1)
    try:
        assert not eng.coalesce
        futs = [eng.submit(rng.standard_normal((3, N_IN)).astype(np.float32))
                for _ in range(2)]
        for f in futs:
            assert f.result(timeout=60).shape == (3, N_OUT)
        s = eng.stats()
        assert s["batches"] == 2 and s["rows_padded"] == 0
    finally:
        eng.shutdown()


def test_computation_graph_engine(rng):
    from deeplearning4j_tpu.nn.graph import (ComputationGraph,
                                             ComputationGraphConfiguration)
    base = NeuralNetConfiguration(seed=3, activation="tanh",
                                  learning_rate=0.1, updater="sgd")
    conf = (ComputationGraphConfiguration.builder(base)
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_in=N_IN, n_out=8), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=N_OUT,
                                          activation="softmax",
                                          loss_function="mcxent"), "h")
            .set_outputs("out").build())
    cg = ComputationGraph(conf).init()
    eng = ParallelInference(cg, max_batch_size=8, max_latency_ms=2.0,
                            replicas=1)
    try:
        x = rng.standard_normal((4, N_IN)).astype(np.float32)
        np.testing.assert_array_equal(eng.output(x, timeout=60),
                                      np.asarray(cg.output(x)))
    finally:
        eng.shutdown()


# ------------------------------------------------- satellite: nn paths

def test_predict_on_device_argmax_matches_host(net, rng):
    x = rng.standard_normal((9, N_IN)).astype(np.float32)
    ids = net.predict(x)
    assert ids.dtype == np.int64 and ids.shape == (9,)
    np.testing.assert_array_equal(
        ids, np.argmax(np.asarray(net.output(x)), axis=-1))


def test_feed_forward_jit_cached(net, rng):
    x = rng.standard_normal((5, N_IN)).astype(np.float32)
    acts = net.feed_forward(x)
    assert [a.shape for a in acts] == [(5, 8), (5, N_OUT)]
    np.testing.assert_array_equal(acts[-1], np.asarray(net.output(x)))
    key_present = any(k[0] == "feed_forward" for k in net._jits
                      if isinstance(k, tuple))
    assert key_present
    # second call hits the cache (no new program objects)
    n_jits = len(net._jits)
    net.feed_forward(x)
    net.feed_forward(x, train=True)  # distinct cached entry
    assert len(net._jits) == n_jits + 1


def test_evaluate_bucketed_single_program(net, rng, fresh_registry):
    """net.evaluate over a ragged iterator reuses ONE compiled program
    (tail padded to the canonical batch) and matches the reference
    Evaluation built from full probabilities."""
    from deeplearning4j_tpu.eval.evaluation import Evaluation
    n = 21
    x = rng.standard_normal((n, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, n)]
    ev = net.evaluate(DataSet(x, y), batch_size=8)  # tail of 5
    ref = Evaluation()
    ref.eval(y, np.asarray(net.output(x)))
    np.testing.assert_array_equal(ev.confusion.counts, ref.confusion.counts)
    assert ev.accuracy() == ref.accuracy()
    # 8,8,5(->8): one predict program signature == one cache miss
    assert fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER) == 1


def test_evaluate_sharded_tail_no_recompile(net, rng):
    """The sharded evaluator pads ragged tails to the canonical shape:
    dispatch signatures collapse to one program (and results stay exact)."""
    from deeplearning4j_tpu.parallel import evaluate_sharded
    n = 21
    x = rng.standard_normal((n, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, n)]
    ev = evaluate_sharded(net, ListDataSetIterator(DataSet(x, y), 8))
    ev_host = net.evaluate(DataSet(x, y), batch_size=8)
    np.testing.assert_array_equal(ev.confusion.counts,
                                  ev_host.confusion.counts)
    assert int(ev.confusion.counts.sum()) == n


# --------------------------------------- satellite: streaming round trip

def test_streaming_inference_engine_end_to_end(net, rng):
    """Serve-route round trip through the engine: concurrent ragged
    messages come back on out_topic in order, equal to inline output."""
    broker = InMemoryBroker()
    engine = ParallelInference(net, max_batch_size=8, max_latency_ms=2.0,
                               replicas=2)
    engine.warmup([(N_IN,)])
    serve = StreamingInference(net, broker, "in", "out",
                               engine=engine).start()
    xs = [rng.standard_normal((2 + i % 3, N_IN)).astype(np.float32)
          for i in range(9)]
    for x in xs:
        broker.publish("in", ndarray_to_bytes(x))
    publish_stop(broker, "in")
    assert serve.join(timeout=120) == 9
    for x in xs:  # out_topic preserves in_topic order
        pred = ndarray_from_bytes(broker.consume("out", timeout=5))
        np.testing.assert_array_equal(pred, np.asarray(net.output(x)))
    engine.shutdown()


def test_streaming_inference_owns_engine_by_default(net, rng):
    broker = InMemoryBroker()
    serve = StreamingInference(net, broker, "in", "out").start()
    x = rng.standard_normal((3, N_IN)).astype(np.float32)
    broker.publish("in", ndarray_to_bytes(x))
    publish_stop(broker, "in")
    assert serve.join(timeout=120) == 1
    np.testing.assert_array_equal(
        ndarray_from_bytes(broker.consume("out", timeout=5)),
        np.asarray(net.output(x)))


def test_ui_healthz_exposes_engine_stats(net, rng):
    import json
    from urllib.request import urlopen

    from deeplearning4j_tpu.ui.server import UiServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    eng = ParallelInference(net, max_batch_size=4, max_latency_ms=1.0,
                            replicas=1)
    server = UiServer(InMemoryStatsStorage(), inference_engine=eng).start()
    try:
        eng.output(rng.standard_normal((2, N_IN)).astype(np.float32),
                   timeout=60)
        body = json.loads(urlopen(server.url + "/healthz", timeout=10).read())
        assert body["inference"]["requests"] == 1
        assert body["inference"]["replicas"] == 1
        metrics = urlopen(server.url + "/metrics", timeout=10).read().decode()
        assert "dl4j_infer_requests_total" in metrics
    finally:
        server.stop()
        eng.shutdown()
