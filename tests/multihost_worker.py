"""Worker script for the multi-host equivalence tests (the cluster
analog of the reference's ``TestCompareParameterAveragingSparkVsSingleMachine``):
run as N processes × M CPU devices, train over the global mesh, have
process 0 dump the final params.

Usage: python multihost_worker.py <pid> <nproc> <port> <out.npz> [mode]
(single-process reference mode: nproc=1, no distributed init)

Modes (VERDICT r4 #6 — the sharded axes must CROSS the process
boundary, not just DP):
  dp    params replicated, batch sharded over data (original test)
  fsdp  ZeRO-3: params+opt state sharded over the data axis, which
        spans both processes — every forward all-gathers shards over
        DCN (gloo here), every backward reduce-scatters across it
  tp    tensor parallelism with the MODEL axis as the OUTER (cross-
        process) mesh axis — per-layer psum/all-gather collectives
        cross the process boundary every step

Env (set by the spawner, BEFORE interpreter start): JAX_PLATFORMS=cpu,
GRAFT_LOCAL_DEVICES=<M> mirrored into
XLA_FLAGS=--xla_force_host_platform_device_count=<M> (the worker
asserts the resulting device count — the count must never silently
degrade to 1 again), PALLAS_AXON_POOL_IPS removed.
"""

import os
import sys

pid, nproc, port, out = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                         sys.argv[4])
mode = sys.argv[5] if len(sys.argv) > 5 else "dp"
assert mode in ("dp", "fsdp", "tp"), f"unknown mode {mode!r}"

import jax  # noqa: E402

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel import multihost  # noqa: E402

if nproc > 1:
    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)

# re-assert the device count EXPLICITLY: the spawner sets XLA_FLAGS to
# --xla_force_host_platform_device_count=<GRAFT_LOCAL_DEVICES> before
# interpreter start (this jax has no jax_num_cpu_devices config — the
# old spelling silently left the worker on ONE device). A mismatch here
# means the env plumbing regressed and every "multi-host" assertion
# below would be vacuous.
_want_local = int(os.environ.get("GRAFT_LOCAL_DEVICES", "4"))
assert len(jax.local_devices()) == _want_local, (
    f"worker {pid}: expected {_want_local} local devices from XLA_FLAGS, "
    f"got {len(jax.local_devices())} "
    f"(XLA_FLAGS={os.environ.get('XLA_FLAGS')!r})")
assert len(jax.devices()) == _want_local * nproc, (
    f"worker {pid}: global mesh has {len(jax.devices())} devices, "
    f"expected {_want_local * nproc}")

import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402

GLOBAL_BATCH = 32
STEPS = 5

conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.1)
        .updater("sgd").activation("tanh")
        .list()
        .layer(DenseLayer(n_in=6, n_out=16))
        .layer(DenseLayer(n_in=16, n_out=16))
        .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                           loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()

rng = np.random.default_rng(0)  # same data in every process
X = rng.standard_normal((GLOBAL_BATCH, 6)).astype(np.float32)
Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, GLOBAL_BATCH)]

if mode == "tp":
    # MODEL axis OUTER = across processes; per-layer collectives ride
    # the process boundary. data axis is the local devices.
    n_dev = len(jax.devices())
    mesh = multihost.make_multihost_mesh(
        dcn_axes={"model": 2}, ici_axes={"data": n_dev // 2})
else:
    mesh = multihost.make_multihost_mesh()  # pure DP over all devices
    assert dict(mesh.shape)["data"] == len(jax.devices()), dict(mesh.shape)

# batch sharded over data. In tp mode the data axis lives inside each
# process (the model axis spans them), so every process contributes the
# FULL batch; in dp/fsdp each contributes its slice.
if mode == "tp":
    x_local, y_local = X, Y
else:
    per = GLOBAL_BATCH // max(nproc, 1)
    lo = pid * per
    x_local, y_local = X[lo:lo + per], Y[lo:lo + per]
xg, yg = multihost.global_batch(mesh, [x_local, y_local])

# broadcast (replicate) params + optimizer state over the global mesh
net.params = multihost.replicate(mesh, jax.device_get(net.params))
net.opt_state = multihost.replicate(mesh, jax.device_get(net.opt_state))
net.states = multihost.replicate(mesh, jax.device_get(net.states))

if mode == "fsdp":
    from deeplearning4j_tpu.parallel.zero import apply_fsdp
    specs = apply_fsdp(net, mesh, axis="data")
    assert specs, "no parameter was FSDP-sharded"
    # placement proof: at least one param's shards live on devices of
    # BOTH processes (the data axis spans them)
    if nproc > 1:
        spanned = False
        for layer, ps in specs.items():
            for pname in ps:
                shards = net.params[layer][pname].sharding \
                    .device_set
                if len({d.process_index for d in shards}) > 1:
                    spanned = True
        assert spanned, "FSDP shards never crossed the process boundary"
elif mode == "tp":
    from deeplearning4j_tpu.parallel.tensor_parallel import (
        apply_shardings, dense_tp_specs)
    specs = dense_tp_specs(["layer0", "layer1", "layer2"])
    apply_shardings(net, mesh, specs)
    if nproc > 1:
        w0 = net.params["layer0"]["W"]
        assert len({d.process_index
                    for d in w0.sharding.device_set}) > 1, \
            "TP model axis did not cross the process boundary"

step = net._get_jit("train", fm=False, lm=False)

zero = jnp.zeros(())
key = jax.random.PRNGKey(1)
for _ in range(STEPS):
    net.params, net.opt_state, net.states, score = step(
        net.params, net.opt_state, net.states, xg, yg, zero, zero, key)

# gather sharded params back to replicated THROUGH the mesh (an
# all-gather program over DCN in fsdp/tp mode — itself part of the
# cross-process proof), then dump on rank 0
gather = jax.jit(lambda t: t, out_shardings=NamedSharding(mesh, P()))
params_full = jax.device_get(gather(net.params))

if pid == 0:
    flat = {}
    for ln, ps in params_full.items():
        for pn, v in ps.items():
            flat[f"{ln}/{pn}"] = np.asarray(v)
    np.savez(out, score=float(score), **flat)
    print("saved", out, "score", float(score), flush=True)
if nproc > 1:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("done")
