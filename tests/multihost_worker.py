"""Worker script for the multi-host equivalence test (the cluster
analog of the reference's ``TestCompareParameterAveragingSparkVsSingleMachine``):
run as N processes × M CPU devices, train DP over the global mesh, have
process 0 dump the final params.

Usage: python multihost_worker.py <pid> <nproc> <port> <out.npz>
(single-process reference mode: nproc=1, no distributed init)

Env (set by the spawner, BEFORE interpreter start): JAX_PLATFORMS=cpu,
GRAFT_LOCAL_DEVICES=<M>, PALLAS_AXON_POOL_IPS removed.
"""

import os
import sys

pid, nproc, port, out = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]

import jax  # noqa: E402

jax.config.update("jax_num_cpu_devices", int(os.environ.get("GRAFT_LOCAL_DEVICES", "2")))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel import multihost  # noqa: E402

if nproc > 1:
    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402

GLOBAL_BATCH = 32
STEPS = 5

conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.1)
        .updater("sgd").activation("tanh")
        .list()
        .layer(DenseLayer(n_in=6, n_out=10))
        .layer(OutputLayer(n_in=10, n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()

rng = np.random.default_rng(0)  # same data in every process
X = rng.standard_normal((GLOBAL_BATCH, 6)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, GLOBAL_BATCH)]

mesh = multihost.make_multihost_mesh()  # pure DP over all devices
assert dict(mesh.shape)["data"] == len(jax.devices()), dict(mesh.shape)

# each process contributes only ITS slice of the global batch
per = GLOBAL_BATCH // nproc
lo = pid * per
x_local, y_local = X[lo:lo + per], Y[lo:lo + per]
xg, yg = multihost.global_batch(mesh, [x_local, y_local])

# broadcast (replicate) params + optimizer state over the global mesh
net.params = multihost.replicate(mesh, jax.device_get(net.params))
net.opt_state = multihost.replicate(mesh, jax.device_get(net.opt_state))
net.states = multihost.replicate(mesh, jax.device_get(net.states))

step = net._get_jit("train", fm=False, lm=False)
import jax.numpy as jnp  # noqa: E402

zero = jnp.zeros(())
key = jax.random.PRNGKey(1)
for _ in range(STEPS):
    net.params, net.opt_state, net.states, score = step(
        net.params, net.opt_state, net.states, xg, yg, zero, zero, key)

if pid == 0:
    flat = {}
    for ln, ps in jax.device_get(net.params).items():
        for pn, v in ps.items():
            flat[f"{ln}/{pn}"] = np.asarray(v)
    np.savez(out, score=float(score), **flat)
    print("saved", out, "score", float(score), flush=True)
if nproc > 1:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("done")
