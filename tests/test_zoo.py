"""Model-zoo tests: ResNet bottleneck graphs.

Parity: the reference's ResNet-50-class capability is "ComputationGraph
+ conv helpers" (``ComputationGraph.java:677``,
``CudnnConvolutionHelper.java:51``). The full 50-layer graph is
exercised on the TPU by bench.py; here a 1/1/1/1-stage bottleneck
variant proves the block wiring (projection shortcuts, zero-init last
BN, strided 3x3) on the CPU mesh cheaply.
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.models.zoo.resnet import (
    resnet, resnet50, resnet50_train_flops_per_example)


def test_tiny_resnet_trains(rng):
    net = resnet(stages=(1, 1), widths=(8, 16), num_classes=4,
                 compute_dtype="float32", learning_rate=0.01).init()
    x = rng.standard_normal((4, 32, 32, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 4)]
    mds = MultiDataSet([x], [y])
    net.fit(mds)
    s0 = net._score
    for _ in range(6):
        net.fit(mds)
    assert np.isfinite(net._score)
    assert net._score < s0
    out = net.output(x)
    assert out.shape == (4, 4)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


def test_resnet50_graph_shape():
    net = resnet50(num_classes=1000)
    # 50 conv/fc layers: 1 stem + 3*16 bottleneck convs + fc
    convs = [v for v in net.conf.vertices
             if v.layer is not None and type(v.layer).__name__ == "ConvolutionLayer"]
    assert len(convs) == 1 + 3 * 16 + 4  # stem + block convs + 4 projections
    assert len(net.order) == len(net.conf.vertices)  # acyclic, fully ordered


def test_resnet50_flops_model():
    # torchvision-reported ~4.09 GMACs fwd => ~24.5 GFLOP per training example
    f = resnet50_train_flops_per_example()
    assert 22e9 < f < 27e9
