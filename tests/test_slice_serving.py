"""Mesh-sharded serving slices (ISSUE 12 tentpole).

The contracts under test:

1. **Sharded serving exactness** — a ``ParallelInference`` whose one
   replica is a tp≥2 mesh slice (params column-sharded per the
   serving SpecLayout, KV pool heads-sharded, programs
   jitted-with-shardings) produces output BITWISE equal to the
   single-device engine: classify logits byte-for-byte, greedy and
   seeded-sampled generate token-for-token — and steady state performs
   zero XLA compiles on warmed ladders.
2. **Slice as a failure domain** — a ``ChipFailure`` inside the slice
   poisons the WHOLE engine: typed ``SliceDegraded`` (in submits, in
   heartbeat-carried stats, in ``fleet_snapshot``), in-flight streams
   migrate through the PR-10 journal/resume path token-for-token, and
   ``ScalePolicy``/``LocalFleet`` rebuild the slice at a NARROWER
   width from the survivors (the 8→4→1 mesh-portable ladder) —
   deterministically across drill reruns.
3. **Disaggregated prefill/decode** — a prefill-role endpoint computes
   the prompt KV, ships it (wire v3 tensor chunks), and the decode
   endpoint admits the session from the shipped state with ZERO prompt
   tokens recomputed — tokens exactly equal the fused path.

Plus the satellite guards: the check_mesh_api lint now bans mesh
construction inside serving/ (and catches crafted violations), and the
dl4j_slice_* / dl4j_disagg_* metric family is schema-pinned.
"""

import importlib.util
import os
import tempfile

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import ChipFailure, SliceKill
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.generate import generate_eager
from deeplearning4j_tpu.parallel.inference import (ParallelInference,
                                                   SliceDegraded)
from deeplearning4j_tpu.parallel.mesh import (MeshPlane,
                                              apply_serving_slice,
                                              serving_slice_layout,
                                              slice_planes)
from deeplearning4j_tpu.serving import (InferenceRouter, LocalEndpoint,
                                        LocalFleet, ScalePolicy)
from deeplearning4j_tpu.util.model_serializer import (restore_model,
                                                      write_model)

VOCAB = 13

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _need(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _tiny_gpt(seed=3):
    return gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=seed).init()


@pytest.fixture(scope="module")
def artifact():
    """One saved model artifact + a single-device oracle net — every
    slice in the module restores the SAME weights from it (the
    mesh-portable deploy story) so cross-width comparisons are
    bitwise-meaningful."""
    lm = _tiny_gpt()
    td = tempfile.mkdtemp(prefix="dl4j-slice-test-")
    path = os.path.join(td, "lm.zip")
    write_model(lm, path)
    return lm, path


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _slice_engine(path, devices, width=2, **kw):
    plane = MeshPlane.build({"tp": width}, devices=devices[:width])
    kw.setdefault("continuous", True)
    kw.setdefault("decode_slots", 2)
    kw.setdefault("decode_burst", 4)
    kw.setdefault("kv_block_size", 4)
    kw.setdefault("max_latency_ms", 1.0)
    return ParallelInference(net=restore_model(path), slice_plane=plane,
                             **kw)


# ------------------------------------------------- sharded serving


def test_sliced_engine_bitwise_parity(artifact, rng, fresh_registry):
    """tp=2 slice vs single device: classify logits BITWISE, greedy and
    seeded-sampled generate token-for-token, zero leaked blocks after
    drain — the house bar holds across the mesh."""
    _need(2)
    lm, path = artifact
    ids = rng.integers(1, VOCAB, (2, 6))
    prompt = ids[:1]
    y_ref = np.asarray(lm.output(ids))
    g_ref = generate_eager(lm, prompt, 8, seed=5)
    s_ref = generate_eager(lm, prompt, 8, temperature=0.8, top_k=4, seed=5)
    eng = _slice_engine(path, jax.devices(), width=2)
    try:
        assert eng.stats()["slice"] == {
            "width": 2,
            "devices": sorted(d.id for d in jax.devices()[:2]),
            "degraded": False}
        y = np.asarray(eng.output(ids, timeout=60))
        assert y.tobytes() == y_ref.tobytes()  # bitwise, not allclose
        g = eng.generate(prompt, 8, seed=5, timeout=60)
        assert np.array_equal(g, g_ref)
        s = eng.generate(prompt, 8, temperature=0.8, top_k=4, seed=5,
                         timeout=60)
        assert np.array_equal(s, s_ref)
        assert eng.drain(timeout=30)
        pool = eng.stats()["scheduler"]["pool"]
        assert pool["blocks_free"] == pool["blocks_total"]
    finally:
        eng.shutdown()


def test_sliced_zero_steady_state_compiles(artifact, rng, fresh_registry):
    """Warmed ladders on the slice mesh serve any request mix with zero
    XLA compiles — the GSPMD jit-with-shardings programs ladder exactly
    like the single-device ones."""
    _need(2)
    lm, path = artifact
    eng = _slice_engine(path, jax.devices(), width=2)
    try:
        compiled = eng.warmup_generate([2, 4, 8], 8)
        assert compiled > 0
        assert eng.stats()["scheduler"]["warmed"]
        miss0 = fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        futs = [eng.submit_generate(rng.integers(1, VOCAB, (1, t)), mn,
                                    temperature=temp, seed=i)
                for i, (t, mn, temp) in enumerate(
                    [(3, 8, 0.0), (5, 4, 0.5), (8, 6, 0.0)])]
        for f in futs:
            f.result(60)
        assert fresh_registry.family_total(
            monitor.JIT_CACHE_MISS_COUNTER) == miss0
    finally:
        eng.shutdown()


def test_serving_slice_layout_and_planes():
    """The column-only layout shards every big matrix on a
    NON-contracting dim (the bitwise precondition), leaves the head
    replicated, and slice_planes carves the device budget in order."""
    _need(4)
    lm = _tiny_gpt()
    layout = serving_slice_layout(lm)
    blk = lm.impls[1].name
    from jax.sharding import PartitionSpec as P
    assert layout.get(blk, "Wqkv") == P(None, "tp")
    assert layout.get(blk, "W2") == P(None, "tp")
    assert layout.get(lm.impls[0].name, "W") == P(None, "tp")
    head = lm.impls[-1].name
    assert layout.get(head, "W") is None  # logits whole on every chip
    planes = slice_planes(2, jax.devices()[:4])
    assert len(planes) == 2
    assert [p.axis_size("tp") for p in planes] == [2, 2]
    ids = sorted(d.id for p in planes for d in p.mesh.devices.flat)
    assert ids == sorted(d.id for d in jax.devices()[:4])
    # a width that does not divide num_heads is refused loudly — the
    # bitwise seam shards WHOLE heads, never head_dim
    from deeplearning4j_tpu.parallel.mesh import apply_serving_slice
    with pytest.raises(ValueError, match="num_heads"):
        apply_serving_slice(
            _tiny_gpt(),  # 2 heads
            MeshPlane.build({"tp": 4}, devices=jax.devices()[:4]))


# ------------------------------------------- slice failure domain


def _slice_fleet(path, engines, n_endpoints=2, width=2,
                 wedge_timeout_s=1.0):
    def factory(plane):
        eng = ParallelInference(net=restore_model(path), slice_plane=plane,
                                continuous=True, decode_slots=2,
                                decode_burst=2, kv_block_size=4,
                                max_latency_ms=1.0)
        engines.append(eng)
        return eng

    router = InferenceRouter(per_try_timeout_s=4.0, eject_backoff_s=0.1,
                             max_attempts=6,
                             wedge_timeout_s=wedge_timeout_s)
    fleet = LocalFleet(factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=4.0, heartbeat_timeout_s=0.5,
                       slice_width=width,
                       slice_devices=jax.devices()[:width * n_endpoints])
    for _ in range(n_endpoints):
        fleet.add_endpoint()
    assert fleet.wait_ready(30)
    return router, fleet


@pytest.mark.faultinject
def test_kill_chip_slice_dead_stream_resumes(artifact, fresh_registry):
    """Kill a chip inside the pinned slice mid-stream: the engine
    poisons itself typed (SliceDegraded rides the heartbeats — the
    fleet snapshot shows the degraded topology, not a bare unhealthy
    bit), the stream migrates with its journaled prefix, and the
    delivered tokens equal an uninterrupted run — no dup, no gap."""
    import time
    _need(4)
    lm, path = artifact
    engines = []
    router, fleet = _slice_fleet(path, engines)
    try:
        prompt = np.array([[3, 5, 7, 2]])
        max_new = 12
        oracle = generate_eager(lm, prompt, max_new, seed=9)
        toks, dups, gaps = [], [0], [0]

        def on_tokens(off, ts):
            for i, t in enumerate(np.asarray(ts).reshape(-1).tolist()):
                idx = int(off) + i
                if idx < len(toks):
                    dups[0] += 1
                elif idx == len(toks):
                    toks.append(int(t))
                else:
                    gaps[0] += 1

        fut = router.submit_generate(prompt, max_new, seed=9,
                                     session="s1", on_tokens=on_tokens)
        deadline = time.monotonic() + 30
        while len(toks) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(toks) >= 3, "stream never started"
        pin = router.session_endpoint("s1")
        inj = fleet.kill_chip(pin, seed=1)
        assert inj.victim in inj.devices
        out = fut.result(timeout=60)
        assert np.array_equal(out, oracle)
        assert toks == [int(t) for t in oracle[0, -max_new:]]
        assert dups[0] == 0 and gaps[0] == 0
        # the dead slice POSITIVELY declared itself: degraded topology
        # in the snapshot, engine submits reject typed
        snap = router.fleet_snapshot()
        assert snap["endpoints"][pin]["slice"]["degraded"] is True
        assert snap["endpoints"][pin]["in_pool"] is False
        dead_eng = next(e for e in engines if e._slice_dead is not None)
        with pytest.raises(SliceDegraded):
            dead_eng.submit(np.zeros((1, 4), np.float32))
        # zero leaked blocks across every engine ever alive
        for eng in engines:
            sched = eng._scheduler
            if sched is None:
                continue
            pool = sched.stats()["pool"]
            assert pool["blocks_free"] == pool["blocks_total"]
    finally:
        fleet.shutdown(drain=False)
        router.close()


@pytest.mark.faultinject
def test_elastic_rebuild_policy_and_determinism(fresh_registry):
    """The 8→4 elastic rebuild drill: ScalePolicy sees the degraded
    slice in the snapshot and emits a REBUILD decision (before any
    add/remove sizing, under the cooldown discipline); LocalFleet
    restores the artifact onto a slice of HALF the width from the
    survivors (8 chips → a chip dies → 4); the drill replays
    deterministically — same seed ⇒ same victim chip, same rebuilt
    width, same tokens."""
    _need(8)
    # an 8-wide slice needs heads divisible by 8: dedicated artifact
    lm = gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=8,
             max_len=32, compute_dtype="float32", learning_rate=0.01,
             seed=3).init()
    td = tempfile.mkdtemp(prefix="dl4j-slice8-")
    path = os.path.join(td, "lm8.zip")
    write_model(lm, path)
    prompt = np.array([[4, 2, 9]])
    oracle = generate_eager(lm, prompt, 6, seed=11)

    def one_run():
        engines = []
        router, fleet = _slice_fleet(path, engines, n_endpoints=1,
                                     width=8)
        try:
            name = fleet.names()[0]
            inj = fleet.kill_chip(name, seed=2)
            eng = fleet._members[name].worker.engine
            with pytest.raises(BaseException):
                eng.output(np.zeros((1, 4), np.float32), timeout=30)
            assert eng._slice_dead is not None
            # wait for a heartbeat to carry the degraded topology out
            import time
            deadline = time.monotonic() + 10
            snap = router.fleet_snapshot()
            while time.monotonic() < deadline:
                snap = router.fleet_snapshot()
                sl = snap["endpoints"][name].get("slice")
                if sl and sl.get("degraded"):
                    break
                time.sleep(0.02)
            pol = ScalePolicy(min_endpoints=1, max_endpoints=4,
                              cooldown_s=5.0)
            dec = pol.decide(snap, now=100.0)
            assert [d.action for d in dec] == ["rebuild"]
            assert dec[0].endpoint == name
            # cooldown: an immediate second decision is suppressed
            assert pol.decide(router.fleet_snapshot(), now=101.0) == []
            log = fleet.apply(dec)
            assert log and log[0].startswith("rebuild")
            new_width = fleet._members[name].plane.axis_size("tp")
            assert new_width == 4  # 8 → 4: the narrower-slice ladder
            # the rebuilt worker re-enters the pool on its first
            # healthy heartbeat
            from deeplearning4j_tpu.serving import RetryAfter
            out = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    out = router.generate(prompt, 6, seed=11, timeout=60)
                    break
                except RetryAfter:
                    time.sleep(0.05)
            assert out is not None, "rebuilt slice never rejoined"
            return inj.victim, new_width, np.asarray(out)
        finally:
            fleet.shutdown(drain=False)
            router.close()

    v1, w1, out1 = one_run()
    v2, w2, out2 = one_run()
    assert (v1, w1) == (v2, w2)
    assert np.array_equal(out1, oracle) and np.array_equal(out2, oracle)


# ------------------------------------------- disaggregated serving


def test_disaggregated_handoff_exact_tokens(artifact, rng, fresh_registry):
    """A prefill-role endpoint computes the prompt KV; the decode
    endpoint admits the session from the shipped state — ZERO prompt
    tokens recomputed (the scheduler's prefill accounting pins it),
    streams emit from offset 0, tokens exactly equal the fused path,
    and the handoff counter ticks."""
    lm, path = artifact
    dec_eng = ParallelInference(net=restore_model(path), continuous=True,
                                decode_slots=2, decode_burst=4,
                                kv_block_size=4, max_latency_ms=1.0)
    pre_eng = ParallelInference(net=restore_model(path),
                                max_latency_ms=1.0)
    router = InferenceRouter()
    router.add_endpoint(LocalEndpoint(dec_eng, "dec"), role="decode")
    router.add_endpoint(LocalEndpoint(pre_eng, "pre"), role="prefill")
    try:
        prompt = rng.integers(1, VOCAB, (1, 5))
        g_ref = generate_eager(lm, prompt, 8, seed=4)
        s_ref = generate_eager(lm, prompt, 8, temperature=0.7, seed=4)
        toks = []
        fut = router.submit_generate(
            prompt, 8, seed=4,
            on_tokens=lambda off, ts: toks.extend(
                np.asarray(ts).reshape(-1).tolist()))
        assert np.array_equal(fut.result(timeout=60), g_ref)
        assert toks == [int(t) for t in g_ref[0, -8:]]
        assert np.array_equal(
            router.generate(prompt, 8, temperature=0.7, seed=4,
                            timeout=60), s_ref)
        sched = dec_eng.stats()["scheduler"]
        assert sched["kv_handoffs"] == 2
        assert sched["prefill_tokens_computed"] == 0  # the disagg win
        assert fresh_registry.family_total(
            monitor.DISAGG_KV_HANDOFFS_COUNTER) == 2
        # prefill endpoints never serve classify/decode traffic
        snap = router.fleet_snapshot()
        assert snap["endpoints"]["pre"]["role"] == "prefill"
    finally:
        dec_eng.shutdown()
        pre_eng.shutdown()


def test_disagg_remote_wire_v3(artifact, fresh_registry):
    """The handoff crosses the broker wire: prefill reply = one tagged
    kv tensor chunk + terminal logits frame (wire v3), the generate
    frame carries the shipped KV as its body — tokens stay exact."""
    import time

    from deeplearning4j_tpu.serving import EngineWorker, RemoteEndpoint
    from deeplearning4j_tpu.streaming.broker import InMemoryBroker
    lm, path = artifact
    broker = InMemoryBroker()
    dec_eng = ParallelInference(net=restore_model(path), continuous=True,
                                decode_slots=2, decode_burst=4,
                                kv_block_size=4, max_latency_ms=1.0)
    pre_eng = ParallelInference(net=restore_model(path),
                                max_latency_ms=1.0)
    w1 = EngineWorker(dec_eng, broker, "rdec", heartbeat_s=0.05)
    w2 = EngineWorker(pre_eng, broker, "rpre", heartbeat_s=0.05)
    router = InferenceRouter()
    router.add_endpoint(RemoteEndpoint(broker, "rdec",
                                       heartbeat_timeout_s=1.0),
                        role="decode")
    router.add_endpoint(RemoteEndpoint(broker, "rpre",
                                       heartbeat_timeout_s=1.0),
                        role="prefill")
    try:
        time.sleep(0.2)
        prompt = np.array([[3, 5, 7, 2, 9]])
        g_ref = generate_eager(lm, prompt, 8, seed=4)
        out = router.generate(prompt, 8, seed=4, timeout=60)
        assert np.array_equal(out, g_ref)
        assert dec_eng.stats()["scheduler"]["kv_handoffs"] == 1
    finally:
        w1.kill()
        w2.kill()
        dec_eng.shutdown()
        pre_eng.shutdown()
        router.close()


# ------------------------------------------------------- satellites


def test_slicekill_schedule_deterministic():
    """Same (devices, seed, fail_at) ⇒ same victim, same survivors,
    same failure tick — and a dead chip NEVER heals (every later
    dispatch still raises)."""
    a = SliceKill([0, 1, 2, 3], seed=5, fail_at=2)
    b = SliceKill([0, 1, 2, 3], seed=5, fail_at=2)
    assert (a.victim, a.survivors) == (b.victim, b.survivors)
    assert a.victim in (0, 1, 2, 3)
    assert len(a.survivors) == 3 and a.victim not in a.survivors
    hits = []
    for i in range(5):
        try:
            a(("lane", None), i)
            hits.append(0)
        except ChipFailure as e:
            hits.append(1)
            assert tuple(e.survivor_ids) == a.survivors
    assert hits == [0, 0, 1, 1, 1]  # fires at the tick, stays dead


def test_mesh_lint_covers_serving(tmp_path):
    """The check_mesh_api lint is clean on the repo and CATCHES mesh
    construction smuggled into serving/ — the sharded-serving code must
    go through MeshPlane."""
    lint = _load_script("check_mesh_api")
    root = os.path.dirname(_SCRIPTS)
    assert lint.check_repo(root) == []
    bad_dir = tmp_path / "deeplearning4j_tpu" / "serving"
    bad_dir.mkdir(parents=True)
    bad = bad_dir / "rogue.py"
    bad.write_text("from deeplearning4j_tpu.parallel.mesh import "
                   "make_mesh\nm = make_mesh({'tp': 2})\n")
    problems = lint.check_file(
        str(bad), rel="deeplearning4j_tpu/serving/rogue.py")
    assert len(problems) == 2  # the import AND the call
    assert all("serving" in p for p in problems)
    ok = bad_dir / "fine.py"
    ok.write_text("from deeplearning4j_tpu.parallel.mesh import "
                  "MeshPlane\np = MeshPlane.build({'tp': 2})\n")
    assert lint.check_file(
        str(ok), rel="deeplearning4j_tpu/serving/fine.py") == []


def test_slice_metrics_schema_pinned(artifact, fresh_registry):
    """dl4j_slice_* / dl4j_disagg_* are registered names the telemetry
    schema knows, and a sliced engine publishes them."""
    _need(2)
    schema = _load_script("check_telemetry_schema")
    for name in ("dl4j_slice_devices", "dl4j_slice_degraded",
                 "dl4j_slice_rebuilds_total",
                 "dl4j_disagg_kv_handoffs_total"):
        assert name in schema.KNOWN_DL4J_METRICS
    lm, path = artifact
    eng = _slice_engine(path, jax.devices(), width=2, continuous=False)
    try:
        text = fresh_registry.prometheus_text()
        assert "dl4j_slice_devices" in text
        assert "dl4j_slice_degraded" in text
        assert schema.validate_prometheus_text(text) == []
    finally:
        eng.shutdown()
