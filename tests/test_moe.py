"""Mixture-of-experts tests: routing math, gradcheck, aux loss seam,
expert-parallel sharding equivalence.

No reference counterpart (SURVEY §2.6 note 5); the correctness oracle
for the dense dispatch formulation is a per-token Python reroute.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, MoELayer, OutputLayer)
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.moe import moe_ffn, top1_dispatch


def test_top1_dispatch_routes_and_caps(rng):
    logits = jnp.asarray(rng.standard_normal((12, 3)), jnp.float32)
    dispatch, combine, aux = top1_dispatch(logits, capacity=2)
    expert = np.argmax(np.asarray(logits), axis=-1)
    d = np.asarray(dispatch)
    # each kept token occupies exactly one (expert, slot); capped at 2
    per_expert = d.sum(axis=(0, 2))
    for e in range(3):
        want = min(2, int((expert == e).sum()))
        assert per_expert[e] == want
    # tokens are routed to their argmax expert only
    for n in range(12):
        nz = np.nonzero(d[n])[0]
        assert set(nz) <= {expert[n]}
    # no slot double-booked
    assert np.asarray(dispatch).sum(axis=0).max() <= 1.0
    assert float(aux) > 0.0


def test_moe_ffn_matches_per_token_reroute(rng):
    n, d, f, e = 16, 8, 16, 4
    x = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    Wg = jnp.asarray(rng.standard_normal((d, e)), jnp.float32)
    W1 = jnp.asarray(rng.standard_normal((e, d, f)) * 0.1, jnp.float32)
    b1 = jnp.asarray(rng.standard_normal((e, f)) * 0.1, jnp.float32)
    W2 = jnp.asarray(rng.standard_normal((e, f, d)) * 0.1, jnp.float32)
    b2 = jnp.asarray(rng.standard_normal((e, d)) * 0.1, jnp.float32)
    y, aux = moe_ffn(x, Wg, W1, b1, W2, b2, capacity_factor=8.0)  # no drops

    probs = np.asarray(jax.nn.softmax(x @ Wg, axis=-1))
    want = np.zeros((n, d), np.float32)
    for i in range(n):
        ei = int(np.argmax(probs[i]))
        h = np.asarray(jax.nn.gelu(x[i] @ W1[ei] + b1[ei]))
        want[i] = probs[i, ei] * (h @ np.asarray(W2[ei]) + np.asarray(b2[ei]))
    np.testing.assert_allclose(np.asarray(y), want, rtol=2e-5, atol=2e-5)


def test_overflow_tokens_drop_to_zero(rng):
    """With capacity 1 and all tokens preferring one expert, only the
    first token gets expert output."""
    n, d = 4, 6
    x = jnp.ones((n, d), jnp.float32)
    Wg = jnp.zeros((d, 2), jnp.float32).at[:, 0].set(1.0)  # all -> expert 0
    W1 = jnp.ones((2, d, 8), jnp.float32) * 0.1
    b1 = jnp.zeros((2, 8), jnp.float32)
    W2 = jnp.ones((2, 8, d), jnp.float32) * 0.1
    b2 = jnp.zeros((2, d), jnp.float32)
    y, _ = moe_ffn(x, Wg, W1, b1, W2, b2, capacity_factor=0.5)  # cap = 1
    y = np.asarray(y)
    assert np.abs(y[0]).max() > 0.01
    np.testing.assert_allclose(y[1:], 0.0, atol=1e-7)


def test_masked_tokens_consume_no_capacity(rng):
    """Padded timesteps must not occupy expert slots or skew the aux
    loss (regression: routing ignored the mask)."""
    n, d = 8, 6
    x = jnp.ones((n, d), jnp.float32)
    Wg = jnp.zeros((d, 2), jnp.float32).at[:, 0].set(1.0)  # all -> expert 0
    W1 = jnp.ones((2, d, 8), jnp.float32) * 0.1
    b1 = jnp.zeros((2, 8), jnp.float32)
    W2 = jnp.ones((2, 8, d), jnp.float32) * 0.1
    b2 = jnp.zeros((2, d), jnp.float32)
    # capacity 2; first 6 tokens are padding — without masking they
    # would fill expert 0 and starve the 2 real tokens
    valid = jnp.asarray([0, 0, 0, 0, 0, 0, 1, 1], jnp.float32)
    y, aux = moe_ffn(x, Wg, W1, b1, W2, b2, capacity_factor=1.0, valid=valid)
    y = np.asarray(y)
    np.testing.assert_allclose(y[:6], 0.0, atol=1e-7)  # masked: no output
    assert np.abs(y[6:]).max() > 0.01                  # real tokens served
    # aux computed over valid tokens only: frac=1, prob~= softmax -> E*f*p
    probs = float(jax.nn.softmax(jnp.asarray([1.0 * d, 0.0]))[0])
    assert float(aux) == pytest.approx(2 * probs, rel=1e-5)


def _moe_net(aux_weight=0.01, residual=False):
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
            .updater("adam").activation("tanh").weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=6, n_out=8))
            .layer(MoELayer(n_in=8, n_out=8, num_experts=4,
                            capacity_factor=4.0, aux_loss_weight=aux_weight,
                            residual=residual))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def test_moe_net_trains_and_aux_flows(rng):
    net = _moe_net(aux_weight=0.01, residual=True)
    x = rng.standard_normal((32, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    ds = DataSet(x, y)
    s0 = net.score(ds)
    for _ in range(25):
        net.fit(ds)
    assert net.score(ds) < s0
    # aux loss seam: score with aux weight > score with 0 weight
    net0 = _moe_net(aux_weight=0.0)
    net1 = _moe_net(aux_weight=0.5)
    assert net1.score(ds) > net0.score(ds)


def test_moe_gradcheck(rng):
    """FD-vs-analytic through routing: top-1 routing is piecewise
    constant, so with well-separated gates the dispatch is locally
    constant and gradients must check."""
    net = _moe_net()
    x = (rng.standard_normal((8, 6)) * 2.0).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    assert check_gradients(net, DataSet(x, y))


def test_expert_parallel_sharding_matches(rng):
    """EP is a sharding: expert-dim PartitionSpecs over an 'expert'
    axis must not change the math."""
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.tensor_parallel import (
        apply_shardings, moe_ep_specs)

    net = _moe_net()
    x = rng.standard_normal((16, 6)).astype(np.float32)
    full = net.output(x)
    mesh = make_mesh({"expert": 4}, devices=devs[:4])
    apply_shardings(net, mesh, moe_ep_specs(["layer1"]))
    sharded = net.output(x)
    np.testing.assert_allclose(sharded, full, rtol=2e-5, atol=1e-6)
    # and a training step under the sharding stays finite
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    net.fit(DataSet(x, y))
    assert np.isfinite(net.score(DataSet(x, y)))
