"""Small parity items: profiler hooks, ParamAndGradient listener,
TrainingHook seam, Curves fetcher.

Parity: SURVEY §5 tracing ("XLA/TPU profiler traces"),
``ParamAndGradientIterationListener.java``, ``spark/api/TrainingHook``,
``CurvesDataFetcher.java``.
"""

import numpy as np

from deeplearning4j_tpu.datasets.curves import load_curves
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import ParamAndGradientIterationListener
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingHook
from deeplearning4j_tpu.util import profiler


def _net_and_data(rng):
    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    return net, DataSet(x, y)


def test_param_and_gradient_listener_writes_tsv(rng, tmp_path):
    net, ds = _net_and_data(rng)
    path = str(tmp_path / "pg.tsv")
    net.set_listeners(ParamAndGradientIterationListener(path=path))
    for _ in range(3):
        net.fit(ds)
    lines = open(path).read().strip().split("\n")
    assert len(lines) == 4  # header + 3 iterations
    header = lines[0].split("\t")
    assert header[:2] == ["iteration", "score"]
    assert "layer0/W:norm" in header and "layer0/W:upd" in header
    row = lines[2].split("\t")
    assert len(row) == len(header)
    assert float(row[header.index("layer0/W:norm")]) > 0
    assert np.isfinite(float(row[header.index("layer0/W:upd")]))


def test_training_hooks_called(rng):
    net, ds = _net_and_data(rng)
    calls = []

    class Recorder(TrainingHook):
        def pre_update(self, model, iteration):
            calls.append(("pre", iteration))

        def post_update(self, model, iteration):
            calls.append(("post", iteration))

    pw = ParallelWrapper(net, hooks=[Recorder()])
    pw.fit(ds)
    assert calls[0][0] == "pre" and calls[1][0] == "post"
    assert calls[1][1] > calls[0][1]


def test_training_hooks_see_fresh_params_in_averaging_mode(rng):
    """post_update must observe updated params in BOTH modes
    (regression: averaging mode handed hooks the stale pre-fit copy)."""
    import jax

    net, ds = _net_and_data(rng)
    before = np.asarray(jax.device_get(net.params["layer0"]["W"])).copy()
    seen = []

    class Snap(TrainingHook):
        def post_update(self, model, iteration):
            seen.append(np.asarray(jax.device_get(model.params["layer0"]["W"])))

    pw = ParallelWrapper(net, mode="averaging", hooks=[Snap()])
    pw.fit(ds)
    assert seen and np.abs(seen[-1] - before).max() > 1e-7


def test_listeners_see_fresh_params_in_averaging_mode(rng, tmp_path):
    """Listeners too — without any hook registered (regression: the
    refresh was gated on hooks)."""
    net, ds = _net_and_data(rng)
    path = str(tmp_path / "avg_pg.tsv")
    net.set_listeners(ParamAndGradientIterationListener(path=path))
    pw = ParallelWrapper(net, mode="averaging")
    for _ in range(3):
        pw.fit(ds)
    lines = open(path).read().strip().split("\n")
    header = lines[0].split("\t")
    col = header.index("layer0/W:upd")
    upds = [float(line.split("\t")[col]) for line in lines[2:]]
    assert any(u > 1e-9 for u in upds), f"stale params: updates {upds}"


def test_profiler_trace_tolerates_backend(tmp_path, rng):
    """trace() must run the body exactly once whether or not the
    backend supports tracing."""
    ran = []
    with profiler.trace(str(tmp_path / "trace")):
        ran.append(1)
    assert ran == [1]
    with profiler.annotate("custom-phase"):
        ran.append(2)
    assert ran == [1, 2]


def test_curves_fetcher(rng):
    ds = load_curves(num_examples=32, seed=9)
    assert ds.features.shape == (32, 784)
    assert ds.labels.shape == (32, 6)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
    # every image has an actual stroke, none is saturated
    on = (ds.features > 0.5).sum(axis=1)
    assert (on > 10).all() and (on < 400).all()
    # deterministic by seed
    ds2 = load_curves(num_examples=32, seed=9)
    np.testing.assert_array_equal(ds.features, ds2.features)
    nhwc = load_curves(num_examples=4, flat=False)
    assert nhwc.features.shape == (4, 28, 28, 1)
