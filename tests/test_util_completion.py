"""Berkeley collections, tree parsing/sentiment, provisioning plans.

Parity (VERDICT r2 missing #5-#7): ``deeplearning4j-nn/.../berkeley/``
utility API, ``deeplearning4j-nlp-uima/.../treeparser/TreeParser.java``
+ SentiWordNet role, and a TESTED ``Ec2BoxCreator``/``ClusterSetup``
analog replacing the previously untested shell script.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.util.berkeley import (
    Counter, CounterMap, Pair, PriorityQueue, Triple)


class TestBerkeleyCollections:
    def test_counter(self):
        c = Counter()
        c.increment_all(["a", "b", "a", "a"])
        c.increment_count("b", 0.5)
        assert c.get_count("a") == 3.0
        assert c.arg_max() == "a"
        assert c.total_count() == pytest.approx(4.5)
        assert c.sorted_keys() == ["a", "b"]
        c.normalize()
        assert c.total_count() == pytest.approx(1.0)
        assert c.get_count("a") == pytest.approx(3 / 4.5)

    def test_counter_map(self):
        cm = CounterMap()
        cm.increment_count("x", "a", 2.0)
        cm.increment_count("x", "b", 2.0)
        cm.increment_count("y", "a", 1.0)
        assert cm.get_count("x", "a") == 2.0
        assert cm.get_count("z", "a") == 0.0
        cm.normalize()  # row-conditional
        assert cm.get_count("x", "a") == pytest.approx(0.5)
        assert cm.get_count("y", "a") == pytest.approx(1.0)

    def test_priority_queue_descending(self):
        q = PriorityQueue()
        for item, pri in [("low", 1.0), ("high", 9.0), ("mid", 5.0)]:
            q.add(item, pri)
        assert q.peek() == "high" and q.get_priority() == 9.0
        assert list(q) == ["high", "mid", "low"]
        assert not q.has_next()

    def test_pair_triple(self):
        p = Pair(1, "a")
        assert (p.get_first(), p.get_second()) == (1, "a")
        assert p == Pair(1, "a") and hash(p) == hash(Pair(1, "a"))
        a, b, c = Triple(1, 2, 3)
        assert (a, b, c) == (1, 2, 3)


class TestShallowTreeParser:
    def test_parses_np_vp_structure(self):
        from deeplearning4j_tpu.text.trees import ShallowTreeParser

        trees = ShallowTreeParser().parse("The quick dog chased a cat.")
        assert len(trees) == 1
        t = trees[0]
        assert t.label == "S"
        labels = [c.label for c in t.children]
        assert "NP" in labels and "VP" in labels
        assert t.yield_tokens() == ["The", "quick", "dog", "chased",
                                    "a", "cat"]
        assert t.depth() >= 3

    def test_multiple_sentences_and_sexpr(self):
        from deeplearning4j_tpu.text.trees import ShallowTreeParser

        trees = ShallowTreeParser().parse("Dogs bark. Cats sleep.")
        assert len(trees) == 2
        s = trees[0].to_sexpr()
        assert s.startswith("(S") and "Dogs" in s

    def test_pp_absorbs_following_np(self):
        from deeplearning4j_tpu.text.trees import ShallowTreeParser

        t = ShallowTreeParser().parse("The dog sat on the mat.")[0]
        pp = [c for c in t.children if c.label == "PP"]
        assert pp and pp[0].yield_tokens() == ["on", "the", "mat"]


class TestSentiment:
    def test_polarity_signs(self):
        from deeplearning4j_tpu.text.trees import SentiWordNetLexicon

        lex = SentiWordNetLexicon()
        assert lex.polarity("good") > 0 > lex.polarity("terrible")
        assert lex.polarity("table") == 0.0

    def test_sentence_scores_order(self):
        from deeplearning4j_tpu.text.trees import (
            SentiWordNetLexicon, ShallowTreeParser)

        lex = SentiWordNetLexicon()
        pos = lex.score_tokens("what a great wonderful day".split())
        neg = lex.score_tokens("a terrible awful experience".split())
        assert pos > 0 > neg

        tree = ShallowTreeParser().parse("The movie was great.")[0]
        assert lex.score_tree(tree) > 0

    def test_negation_flip(self):
        from deeplearning4j_tpu.text.trees import SentiWordNetLexicon

        lex = SentiWordNetLexicon()
        assert lex.score_tokens("not good".split()) < 0
        assert lex.score_tokens("never bad".split()) > 0

    def test_load_tsv(self, tmp_path):
        from deeplearning4j_tpu.text.trees import SentiWordNetLexicon

        p = tmp_path / "swn.tsv"
        p.write_text("stellar\t0.9\t0.0\n# comment\n", encoding="utf-8")
        lex = SentiWordNetLexicon().load_tsv(str(p))
        assert lex.polarity("stellar") == pytest.approx(0.9)


class TestProvisioning:
    def _prov(self, **kw):
        from deeplearning4j_tpu.parallel.provisioning import (
            TpuPodProvisioner, TpuPodSpec)
        return TpuPodProvisioner(TpuPodSpec(
            "dl4j-pod", "us-west4-a", "v5litepod-64", **kw))

    def test_create_command(self):
        cmd = self._prov().create_command()
        assert cmd[:5] == ["gcloud", "compute", "tpus", "queued-resources",
                           "create"]
        assert "--accelerator-type" in cmd
        assert cmd[cmd.index("--accelerator-type") + 1] == "v5litepod-64"
        assert "--spot" not in cmd
        assert "--spot" in self._prov(spot=True).create_command()

    def test_ship_targets_all_workers(self):
        ship = self._prov().ship_commands()
        assert all("--worker=all" in c for c in ship)
        assert any("scp" in c for c in ship)

    def test_run_is_argv_not_shell(self):
        cmd = self._prov().run_command("python bench.py --x 'a b'")
        # the user command is ONE argv element after --command
        assert cmd[cmd.index("--command") + 1] == "python bench.py --x 'a b'"

    def test_plan_order_and_dry_run_executes_nothing(self):
        prov = self._prov()
        steps = prov.plan("python bench.py")
        assert steps[0][4] == "create" and steps[1][0] == "tar"
        calls = []
        out = prov.execute(steps, dry_run=True,
                           runner=lambda *a, **k: calls.append(a))
        assert calls == [] and out == steps

    def test_execute_runs_each_step(self):
        prov = self._prov()
        calls = []
        prov.execute([["echo", "hi"]], dry_run=False,
                     runner=lambda cmd, check: calls.append((tuple(cmd), check)))
        assert calls == [(("echo", "hi"), True)]

    def test_spec_rejects_injection(self):
        from deeplearning4j_tpu.parallel.provisioning import TpuPodSpec

        with pytest.raises(ValueError):
            TpuPodSpec("bad name", "z", "v5litepod-8")
        with pytest.raises(ValueError):
            TpuPodSpec("n", "", "v5litepod-8")

    def test_cli_plan_dry_run(self, capsys):
        from deeplearning4j_tpu.parallel.provisioning import main

        rc = main(["plan", "pod1", "us-west4-a", "v5litepod-8",
                   "--command", "python bench.py", "--dry-run"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "queued-resources create pod1" in out
        assert "python bench.py" in out


def test_pp_does_not_absorb_verbs():
    """Review regression: a verb after a PP's NP must open a VP chunk,
    not be swallowed into the PP."""
    from deeplearning4j_tpu.text.trees import ShallowTreeParser

    t = ShallowTreeParser().parse("The dog on the mat jumped.")[0]
    labels = [c.label for c in t.children]
    assert "PP" in labels and "VP" in labels
    pp = next(c for c in t.children if c.label == "PP")
    assert "jumped" not in pp.yield_tokens()


def test_cli_plan_never_executes(capsys):
    """Review regression: `plan` without --dry-run must still be
    print-only (asking for a plan must never provision a pod)."""
    from deeplearning4j_tpu.parallel import provisioning

    calls = []
    orig = provisioning.subprocess.run
    provisioning.subprocess.run = lambda *a, **k: calls.append(a)
    try:
        rc = provisioning.main(["plan", "pod1", "us-west4-a", "v5litepod-8"])
    finally:
        provisioning.subprocess.run = orig
    assert rc == 0 and calls == []
    assert "queued-resources create pod1" in capsys.readouterr().out


def test_cli_run_requires_command():
    from deeplearning4j_tpu.parallel.provisioning import main

    with pytest.raises(SystemExit):
        main(["run", "pod1", "us-west4-a"])


def test_spec_rejects_leading_dash():
    from deeplearning4j_tpu.parallel.provisioning import TpuPodSpec

    with pytest.raises(ValueError, match="leading"):
        TpuPodSpec("--force", "z1", "v5litepod-8")
