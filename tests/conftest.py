"""Test harness: force an 8-device CPU-backed virtual mesh.

This is the TPU analog of the reference's ``local[N]`` fake Spark cluster
(``BaseSparkTest.java:90``, SURVEY.md §4): multi-device semantics are
exercised without real chips by splitting the host CPU into 8 XLA
devices. Must run before the first ``import jax``.
"""

import os

# NOTE: this box's sitecustomize pre-imports jax before conftest runs, so
# plain env-var assignment is too late for JAX_PLATFORMS; use the config
# API as well (backends initialize lazily, so this still lands in time).
# The sitecustomize registers a TPU PJRT plugin whenever
# PALLAS_AXON_POOL_IPS is set and the tunnel hangs CPU-only runs — scrub
# the trigger so the suite is self-contained regardless of caller env
# (same doctrine as __graft_entry__._dryrun_in_subprocess).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
# jaxlib's ProfilerSession segfaults (C++-level, uncatchable) when created
# in this harness after donated-buffer programs have run; util/profiler
# degrades to its documented warn-and-no-op path under this switch. The
# monitor/ host-side spans are unaffected and fully tested.
os.environ["DL4J_TPU_DISABLE_DEVICE_TRACE"] = "1"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the test box has one CPU core, so XLA
# compile time dominates the suite; cache executables across runs.
jax.config.update("jax_compilation_cache_dir", "/root/.cache/jax_comp_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

# Gradient checks are finite-difference vs analytic (the reference runs
# them in double precision, GradientCheckUtil.java); enable x64 so the
# same tolerances hold.
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
# numpy.testing's import-time SVE probe spawns a subprocess; forking from
# this process becomes unreliable (C-level segfault in the parent) once
# enough XLA state has accumulated, so force the probe NOW while fork is
# still safe — later lazy `np.testing` imports then hit the module cache.
import numpy.testing  # noqa: E402,F401
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
