"""Unified telemetry tests: registry → spans → exports → endpoints.

Tier-1 guard for the monitor/ subsystem: a real CPU training run must
produce (a) a JSONL event stream ``scripts/check_telemetry_schema.py``
accepts, (b) a Chrome ``trace_event`` JSON with distinct
data_load/device_step/all_reduce/checkpoint spans (Perfetto-loadable),
and (c) a Prometheus ``/metrics`` exposition with the step-duration
histogram, score gauge, and NaN-watchdog counter — with span overhead
small enough to live inside the host-side step loop (<5%).
"""

import importlib.util
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener, UiServer

_SCRIPT = os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                       "check_telemetry_schema.py")
_spec = importlib.util.spec_from_file_location("check_telemetry_schema",
                                               _SCRIPT)
schema = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(schema)


@pytest.fixture
def registry():
    """Fresh process registry per test; the previous one is restored so
    parallel-running suites keep their own counters."""
    reg = monitor.MetricsRegistry()
    old = monitor.set_registry(reg)
    try:
        yield reg
    finally:
        monitor.set_registry(old)
        monitor.disable_tracing()


def _tiny_net():
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .updater("sgd").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _tiny_data(rng, n=32):
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return DataSet(x, y)


# ------------------------------------------------------------- registry

def test_registry_counters_gauges_histograms(registry):
    c = registry.counter("req_total", "requests", route="/a")
    c.inc()
    c.inc(2)
    assert registry.counter("req_total", route="/a") is c
    assert c.value == 3
    registry.gauge("temp", "t").set(1.5)
    h = registry.histogram("lat_ms", "latency", buckets=(1, 10, 100))
    for v in (0.5, 5, 50, 500):
        h.observe(v)
    assert h.count == 4 and h.sum == 555.5
    assert h.cumulative_counts() == [1, 2, 3, 4]
    assert 0 <= h.percentile(0.5) <= 50
    with pytest.raises(ValueError):
        registry.gauge("req_total")  # kind conflict must be loud
    errs = schema.validate_prometheus_text(registry.prometheus_text())
    assert errs == []


def test_registry_prometheus_label_escaping(registry):
    registry.counter("odd_total", "odd", detail='he said "hi"\\n').inc()
    text = registry.prometheus_text()
    assert schema.validate_prometheus_text(text) == []
    assert '\\"hi\\"' in text


def test_phase_breakdown_from_spans(registry):
    with monitor.span("data_load"):
        pass
    with monitor.span("device_step"):
        pass
    with monitor.span("device_step"):
        pass
    b = monitor.phase_breakdown(registry)
    assert b["device_step"]["count"] == 2
    assert b["data_load"]["count"] == 1
    assert all(v["total_ms"] >= 0 for v in b.values())


def test_span_records_without_tracer(registry):
    monitor.disable_tracing()
    with monitor.span("device_step"):
        pass
    hist = registry.get(monitor.PHASE_HISTOGRAM, phase="device_step")
    assert hist is not None and hist.count == 1


def test_span_propagates_exceptions_and_tags_error(registry, tmp_path):
    tracer = monitor.enable_tracing(str(tmp_path / "e.jsonl"))
    with pytest.raises(RuntimeError):
        with monitor.span("checkpoint"):
            raise RuntimeError("boom")
    monitor.disable_tracing()
    [event] = tracer.events()
    assert event["attrs"]["error"] == "RuntimeError"


# ----------------------------------------------------------- step health

def test_watchdog_counts_nan_and_slow_steps(registry):
    w = monitor.StepHealthWatchdog(registry=registry, min_samples=10,
                                   slow_factor=3.0)
    w.record(float("nan"), None, iteration=7)
    assert registry.family_total(monitor.NAN_COUNTER) == 1
    assert w.nan_iterations == [7] and not w.healthy()
    for i in range(30):
        w.record(0.5, 1.0, iteration=i)
    w.record(0.5, 50.0, iteration=99)  # >3x rolling p50 and > rolling p99
    assert registry.family_total(monitor.SLOW_COUNTER) == 1
    assert w.slow_iterations == [99]
    p50, p99 = w.percentiles()
    assert p50 <= p99
    assert registry.get(monitor.SCORE_GAUGE).value == 0.5
    assert registry.get(monitor.STEP_HISTOGRAM).count == 31


def test_watchdog_rides_listener_chain(registry, rng):
    net = _tiny_net()
    w = monitor.StepHealthWatchdog(registry=registry)
    net.set_listeners(w)
    net.fit(_tiny_data(rng))
    assert w.healthy()
    assert registry.get(monitor.SCORE_GAUGE).value == pytest.approx(
        net.score())


# ----------------------------------------------------------- end to end

def test_end_to_end_trace_metrics_and_endpoints(registry, rng, tmp_path):
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.util.model_serializer import write_model

    jsonl = str(tmp_path / "events.jsonl")
    monitor.enable_tracing(jsonl)
    net = _tiny_net()
    storage = InMemoryStatsStorage()
    watchdog = monitor.StepHealthWatchdog(registry=registry)
    net.set_listeners(StatsListener(storage, session_id="e2e",
                                    registry=registry), watchdog)
    ds = _tiny_data(rng)
    for _ in range(3):
        net.fit(ds)                                  # data_load/device_step
    pw = ParallelWrapper(net, mode="averaging", averaging_frequency=1)
    pw.fit(ds)                                       # all_reduce
    write_model(net, str(tmp_path / "model.zip"))    # checkpoint
    net.score(ds)                                    # eval
    watchdog.record(float("nan"), None, iteration=-1)  # tick the watchdog
    tracer = monitor.disable_tracing()

    # (a) the JSONL stream validates
    assert schema.validate_events_file(jsonl) == []
    assert tracer.dropped == 0

    # (b) the Chrome trace validates and has the distinct phase spans
    trace_path = str(tmp_path / "trace.json")
    tracer.export_chrome_trace(trace_path)
    assert schema.validate_chrome_trace_file(trace_path) == []
    with open(trace_path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"data_load", "device_step", "all_reduce",
            "checkpoint", "eval"} <= names

    # (c) /metrics serves Prometheus text with the required families,
    #     /healthz reports the watchdog state
    srv = UiServer(storage, registry=registry).start()
    try:
        text = urllib.request.urlopen(srv.url + "/metrics").read().decode()
        assert schema.validate_prometheus_text(text) == []
        assert "dl4j_step_duration_ms_bucket" in text
        assert "dl4j_score" in text
        assert "dl4j_nan_scores_total" in text
        assert "dl4j_phase_duration_ms_bucket" in text
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/healthz")
        assert e.value.code == 503  # the injected NaN degrades health
        health = json.loads(e.value.read())
        assert health["status"] == "degraded" and health["nan_scores"] >= 1
    finally:
        srv.stop()

    # the storage consumer saw the same run the registry did
    reports = storage.get_reports("e2e")
    assert reports and np.isfinite(reports[-1].score)


def test_command_line_interface(registry, tmp_path, capsys):
    monitor.enable_tracing(str(tmp_path / "ev.jsonl"))
    with monitor.span("device_step"):
        pass
    tracer = monitor.disable_tracing()
    tracer.export_chrome_trace(str(tmp_path / "trace.json"))
    metrics = tmp_path / "metrics.txt"
    metrics.write_text(registry.prometheus_text())
    rc = schema.main([str(tmp_path / "ev.jsonl"), str(tmp_path / "trace.json"),
                      "--metrics", str(metrics)])
    assert rc == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"type": "span", "name": "x"}\n')
    assert schema.main([str(bad)]) == 1


# -------------------------------------------------------------- overhead

def test_monitoring_overhead_under_5_percent(registry):
    """The acceptance bar: spans around a step-loop-scale workload (~2ms
    per step, the test_host_baseline per-batch scale) must cost <5%."""
    def work():
        time.sleep(0.002)

    n = 60
    t0 = time.perf_counter()
    for _ in range(n):
        work()
    bare = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(n):
        with monitor.span("device_step", iteration=i):
            work()
    instrumented = time.perf_counter() - t0
    # generous sleep jitter guard: the *absolute* span cost is what we
    # actually bound — a few µs per span against a 2ms step
    per_span_ms = max(0.0, instrumented - bare) / n * 1e3
    assert per_span_ms < 0.1, f"span overhead {per_span_ms:.4f}ms"
    assert instrumented < bare * 1.05 + 0.05


def test_training_stats_shares_monitor_clock(tmp_path):
    from deeplearning4j_tpu.optimize.training_stats import TrainingStats

    stats = TrainingStats()
    with stats.time("step"):
        pass
    trace = stats.chrome_trace()
    assert schema.validate_chrome_trace(trace) == []
    [ev] = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    # same origin as monitor.now_us(): the event sits in the past of "now"
    assert 0 <= ev["ts"] <= monitor.now_us()
    out = stats.export_chrome_trace(str(tmp_path / "ts.json"))
    assert schema.validate_chrome_trace_file(out) == []
